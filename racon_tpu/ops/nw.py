"""Batched banded Needleman-Wunsch on TPU (cudaaligner-equivalent).

Design (TPU-first, not a CUDA port):

- pairs are bucketed by padded length and packed into fixed-shape uint8
  batches (struct-of-arrays), so XLA compiles one kernel per bucket shape;
- the O(n*m) DP runs on device as a banded anti-diagonal wavefront:
  ``vmap`` over the batch, ``lax.scan`` over wavefronts ``a = i + j``;
  every data dependency is a static +-1 lane shift and character loads are
  contiguous slices, so each step is pure VPU elementwise work (see
  ``_nw_wavefront_kernel`` for the coordinate frame);
- the kernel emits 2-bit direction codes packed 4-per-byte into HBM;
- the O(n+m) traceback also runs on device (``_traceback_kernel``, a
  vmapped pointer chase) so the direction matrix never crosses the slow
  host link; only per-step op codes (~2 bytes/base) are fetched;
- pairs that exceed the largest bucket or whose optimum cannot be proven
  inside the band get per-pair status flags and are re-routed to the host
  aligner — the same reject contract as the reference's
  ``StatusType::exceeded_max_length`` / ``exceeded_max_alignment_difference``
  (``src/cuda/cudaaligner.cpp:64-72``).

Reference call-site parity: replaces edlib/cudaaligner behind
``Polisher.find_overlap_breaking_points`` (``src/cuda/cudapolisher.cpp:86-200``).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# (max query length, band width). Band covers error rates up to ~W/(2L).
BUCKETS: Tuple[Tuple[int, int], ...] = (
    (256, 128),
    (1024, 384),
    (4096, 1024),
    (8192, 2048),
    (16384, 4096),
    (16384, 8192),
)
# Expected divergence used to pick the initial band (escalation corrects
# underestimates; ONT reads of the reference's era run 15-30%).
TYPICAL_DIVERGENCE = 0.25
# Adaptive band-ladder rungs (round 17): a pair's starting band is seeded
# from its overlap's estimated divergence, quantized to this 1.5x-step
# geometric ladder and capped at the pair's bucket band (the terminal
# rung, so the accept/reject SET is identical to the fixed-band path's —
# part of the byte-identity contract). DP work is linear in band, so a
# pair accepted two rungs down sheds most of its wavefront lanes;
# escapees re-dispatch batched at the rung >= 2x their failed band (the
# reference host's band doubling, but batched — cudaaligner sizes
# per-alignment work from each pair's own length/band the same way,
# src/cuda/cudaaligner.cpp:39-44). Every rung keeps the kernels' static
# constraints (band % 8 == 0, band/2 even); each distinct rung is one
# extra compile per bucket, remembered by the persistent XLA cache.
BAND_RUNGS = (64, 96, 128, 192, 256, 384, 512, 768, 1024, 1536, 2048,
              3072, 4096)
# The adaptive half of the ladder: seeds trust the run's OBSERVED
# clean-walk score divergence once this many pairs have resolved — the
# overlap filter's span-asymmetry error only sees net indels, so a
# substitution-heavy run would otherwise seed low and escape every pair.
ADAPT_MIN_PAIRS = 256
# Cold-start probe batch: the ragged stream seeds/dispatches/fetches
# this many leading pairs FIRST (one pipeline bubble), so every later
# seed uses observed divergence rather than the blind span proxy.
ALIGN_PROBE_PAIRS = 1024
# Bound on pairs per device chunk: the ragged packer's memory-budget cap
# can reach 6 figures for short-pair classes, but each pair also pins a
# transient host span copy until its chunk is fetched — the same
# O(slice) contract the polisher's 64k overlap slices enforce.
MAX_CHUNK_PAIRS = 65536
# Companion bound on the stream's in-flight PAIRS: short-pair chunks are
# tiny in direction-matrix bytes (the budget that normally forces
# fetches), so without this a 10M-overlap short-read run would pin
# millions of unresolved span copies before the byte budget ever bit.
MAX_INFLIGHT_PAIRS = 4 * MAX_CHUNK_PAIRS
# Upper bound on the packed direction-matrix bytes held across in-flight
# device batches (v5e has 16 GiB HBM; the matrix never leaves the
# device). Small caps fragment long-bucket batches into many chunks and
# each chunk pays a dispatch round-trip over the jittery tunnel (up to
# ~1 s at bad times — it, not the DP, bounds real runs); huge chunks
# coarsen the pack/transfer/compute pipeline overlap. 8 GiB across the
# pipeline depth keeps per-chunk matrices at ~2 GiB even 4-deep, i.e.
# ~500 ONT read pairs per launch.
MAX_DIRS_BYTES = 8 * 1024 * 1024 * 1024

@functools.partial(jax.jit, static_argnames=("max_len", "band", "steps",
                                             "swar"))
def _nw_wavefront_kernel(qrp, tp, n, m, *, max_len: int, band: int,
                         steps: int = 0, swar: bool = False):
    """Banded anti-diagonal wavefront DP for one bucket batch.

    Coordinate frame: wavefront ``a = i + j`` (scan axis), diagonal
    ``k = j - i + band/2``; lanes hold every-other diagonal (parity of k is
    fixed per wavefront), so a wavefront is ``W/2`` lanes indexed by ``u``
    with ``k = 2u + p(a)``, ``p(a) = (a + band/2) & 1``. All data
    dependencies are static +-1 lane shifts of the previous two wavefronts,
    and the per-step character loads are two contiguous ``dynamic_slice``
    reads — no gathers and no inner scans, which is what makes this fast
    on the TPU VPU (the earlier row-scan formulation was ~100x slower).

    Inputs (host-prepacked, see ``TpuAligner._run_chunk``):
      qrp: uint8 [B, band/2 + max_len + band] — reversed query at offset
           ``band/2 + max_len - n`` (so lane reads share one slice start);
      tp:  uint8 [B, band/2 + max_len + band] — target at offset ``band/2``;
      n, m: int32 [B] true lengths.

    Returns (dirs_packed uint8 [B, steps, band/8], score int32 [B]):
    per-wavefront 2-bit direction codes (0=M diag, 1=I consume-query,
    2=D consume-target), 4 lanes per byte (planar).

    ``steps`` bounds the anti-diagonal sweep (default ``2*max_len``):
    callers that know the longest real pair pass ``ceil(max(n+m))``
    rounded to 256, cutting the dead wavefronts past the last finish
    (pairs with ``n + m > steps`` never reach their final cell, keep
    score BIG, and are rejected like band escapes).

    ``swar`` runs the SWAR-packed variant: wavefront scores travel as
    **int16 lanes** — two per 32-bit VPU lane (2x arithmetic density;
    the vectorizer does the in-register packing) — saturating at
    ``swar.BIG16`` instead of ``1 << 28``. Every cell value is bounded
    by ``max_len`` (:func:`swar.swar_fits` is the callers' overflow
    guard), so the {real, BIG, BIG+1} value classes and hence every
    direction-code comparison are identical: the direction matrix is
    **byte-identical** to the int32 path's, and scores are remapped
    (``BIG16 -> 1 << 28``) so the outputs match bit-for-bit.
    """
    W = band
    c = W // 2
    L = max_len
    U = W // 2  # lanes per wavefront
    S = steps if steps else 2 * L
    if swar:
        from .swar import BIG16, BIG32
        assert max_len + 2 < BIG16, (max_len, BIG16)
        vdt = jnp.int16
        BIG = jnp.int16(BIG16)
    else:
        vdt = jnp.int32
        BIG = jnp.int32(1 << 28)

    us = jnp.arange(U, dtype=jnp.int32)

    def per_pair(qv, tv, nn, mm):
        def step(carry, a):
            v1, v2, score = carry  # wavefronts a-1 and a-2
            p = (a + c) & 1
            # lane -> (i, j):  i = I0 - u, j = J0 + u
            I0 = (a + c - p) // 2
            J0 = (a - c + p) // 2
            i_vec = I0 - us
            j_vec = J0 + us

            # shifted views of wavefront a-1 (parity alternates):
            #   p == 0: D-source = v1[u-1], I-source = v1[u]
            #   p == 1: D-source = v1[u],   I-source = v1[u+1]
            v1_left = jnp.concatenate([jnp.full((1,), BIG, vdt), v1[:-1]])
            v1_right = jnp.concatenate([v1[1:], jnp.full((1,), BIG, vdt)])
            d_src = jnp.where(p == 0, v1_left, v1)
            i_src = jnp.where(p == 0, v1, v1_right)

            # characters: q[i-1] and t[j-1] as contiguous slices
            qchars = lax.dynamic_slice_in_dim(qv, c + L - I0, U)
            tchars = lax.dynamic_slice_in_dim(tv, c + J0 - 1, U)
            sub = jnp.where(qchars == tchars, 0, 1).astype(vdt)

            cd = v2 + sub          # diagonal (i-1, j-1)
            ci = i_src + vdt(1)    # consume query (i-1, j)
            cdel = d_src + vdt(1)  # consume target (i, j-1)
            best = jnp.minimum(cd, jnp.minimum(ci, cdel))
            d = jnp.where(cd == best, jnp.uint8(0),
                          jnp.where(ci == best, jnp.uint8(1), jnp.uint8(2)))

            interior = (i_vec >= 1) & (i_vec <= nn) & (j_vec >= 1) & (j_vec <= mm)
            v = jnp.where(interior, jnp.minimum(best, BIG), BIG)
            # boundary rows/cols of the DP table (values <= max_len, so
            # the int16 cast in the packed path is lossless)
            v = jnp.where((i_vec == 0) & (j_vec >= 0) & (j_vec <= mm),
                          j_vec.astype(vdt), v)
            v = jnp.where((j_vec == 0) & (i_vec >= 1) & (i_vec <= nn),
                          i_vec.astype(vdt), v)

            # final score lives at a == n + m, u_final = (m - n + c - p) / 2
            u_fin = (mm - nn + c - p) // 2
            fin = jnp.take(v, jnp.clip(u_fin, 0, U - 1))
            score = jnp.where(a == nn + mm, fin, score)

            # planar 2-bit pack: byte k holds lanes k, k+RB, k+2RB, k+3RB
            # (static contiguous slices — no cross-lane reshuffle, so the
            # same format is cheap in both this kernel and the Pallas one)
            RB = U // 4
            packed = (d[:RB] | (d[RB:2 * RB] << 2) | (d[2 * RB:3 * RB] << 4)
                      | (d[3 * RB:] << 6))
            return (v, v1, score), packed

        # wavefront 0: only (0,0) at u0 = (c - p0)/2
        p0 = c & 1
        u0 = (c - p0) // 2
        v0 = jnp.where(us == u0, 0, BIG).astype(vdt)
        vm1 = jnp.full((U,), BIG, vdt)  # "wavefront -1"
        score0 = jnp.where(nn + mm == 0, 0, BIG).astype(vdt)
        (v, v1, score), packed = lax.scan(
            step, (v0, vm1, score0),
            jnp.arange(1, S + 1, dtype=jnp.int32))
        if swar:
            # restore the int32 saturation constant so consumers (and
            # the parity harness) see the exact int32-path scores
            score = jnp.where(score == BIG, jnp.int32(BIG32),
                              score.astype(jnp.int32))
        return packed, score

    return jax.vmap(per_pair)(qrp, tp, n, m)


def _walk_op(pk, i, j, *, c, RB, S, U):
    """Shared one-step decode of the packed direction matrix during a
    backward walk from (i, j). Returns (op, di, dj): op 0=M, 1=I, 2=D,
    3=done-or-stalled (band escape stalls so final (i,j) != 0 flags it).
    Planar layout: lane u lives in byte ``u % RB`` at shift ``2*(u//RB)``."""
    a = i + j
    p = (a + c) & 1
    u = (j - i + c - p) // 2
    pos = (a - 1) * RB + u % RB
    byte = jnp.take(pk, jnp.clip(pos, 0, S * RB - 1))
    # clip the plane index: escaped u (< 0 or >= U) decodes garbage, but
    # the `escaped` flag below overrides the op — just keep the shift legal
    plane = jnp.clip(u // RB, 0, 3).astype(jnp.uint8)
    d = ((byte >> (2 * plane)) & 3).astype(jnp.uint8)
    d = jnp.where(i == 0, jnp.uint8(2), d)              # only D left
    d = jnp.where((j == 0) & (i > 0), jnp.uint8(1), d)  # only I left
    escaped = (i > 0) & (j > 0) & ((u < 0) | (u >= U))
    done = ((i == 0) & (j == 0)) | escaped
    op = jnp.where(done, jnp.uint8(3), d)
    di = jnp.where((op == 0) | (op == 1), 1, 0)
    dj = jnp.where((op == 0) | (op == 2), 1, 0)
    return op, di, dj


@functools.partial(jax.jit, static_argnames=("band", "swar"))
def _walk_ops_kernel(packed, n, m, *, band: int, swar: bool = False):
    """On-device traceback: vmapped pointer chase over the packed direction
    matrix (which never leaves HBM — downloading it dominated wall-clock
    otherwise). Emits one op code per step, consumed backwards from (n, m):
    0=M, 1=I, 2=D, 3=done-or-band-escape. Exactly n+m real steps per pair
    (a band escape stalls the walk, leaving the final ``(fi, fj) != 0``).
    Walk length follows ``packed``'s wavefront-row count (the producer's
    ``steps`` bound, default ``2*max_len``). Returns unpacked
    ``(ops [B, steps] u8, fi, fj)`` — stays on device for the consensus
    vote path; the aligner packs via :func:`_traceback_kernel`.

    ``swar`` runs the SWAR-packed variant (the round-6 layout extended
    to the walk, the ROADMAP open item): the ``(i, j)`` walk state
    travels as ONE int32 halfword pair — positions are bounded by the
    bucket cap (16384 < 2^15, the same ``swar.swar_fits`` ceiling the
    forward kernel's guard enforces), so the scan carry and its
    per-step update halve. Decode math is shared with the unpacked path
    (:func:`_walk_op`), so the op stream is **byte-identical**; the
    sanitizer's int32 shadow execution covers it (the shadow leg runs
    ``swar=False`` end to end)."""
    W = band
    c = W // 2
    U = W // 2
    RB = W // 8
    B, S = packed.shape[0], packed.shape[1]
    flat = packed.reshape(B, S * RB)

    def per_pair(pk, nn, mm):
        if swar:
            def step(carry, _):
                ij = carry  # (i << 16) | j, both < 2^15 (swar_fits)
                op, di, dj = _walk_op(pk, ij >> 16, ij & 0xFFFF,
                                      c=c, RB=RB, S=S, U=U)
                return ij - ((di << 16) | dj), op

            ijf, ops = lax.scan(step, (nn << 16) | mm, None, length=S)
            return ops, ijf >> 16, ijf & 0xFFFF

        def step(carry, _):
            i, j = carry
            op, di, dj = _walk_op(pk, i, j, c=c, RB=RB, S=S, U=U)
            return (i - di, j - dj), op

        (fi, fj), ops = lax.scan(step, (nn, mm), None, length=S)
        return ops, fi, fj

    return jax.vmap(per_pair)(flat, n, m)


@functools.partial(jax.jit, static_argnames=("max_len", "band", "swar"))
def _traceback_kernel(packed, score, n, m, *, max_len: int, band: int,
                      swar: bool = False):
    """Aligner-facing traceback: walks on device, then packs the op codes
    2-bit x 4-per-byte so one host round-trip fetches everything (the
    tunnel to the device has ~0.2s per-transfer latency). ``swar``
    forwards to the packed-carry walk (byte-identical op stream)."""
    ops, fi, fj = _walk_ops_kernel(packed, n, m, band=band, swar=swar)
    return _pack_ops(ops), score, fi, fj


def _pack_ops(ops):
    """2-bit x 4-per-byte op packing for the host fetch (one consumer:
    ``TpuAligner._finish_chunk``'s unpacker)."""
    B, S = ops.shape
    o4 = ops.reshape(B, S // 4, 4)
    return (o4[:, :, 0] | (o4[:, :, 1] << 2) | (o4[:, :, 2] << 4)
            | (o4[:, :, 3] << 6))


def align_chain(qrp, tp, n, m, *, max_len: int, band: int, steps: int = 0,
                use_pallas: bool = False, use_swar: bool = False):
    """Wavefront NW + on-device traceback — the single source of truth for
    the aligner's kernel wiring, wrapped unchanged by both the plain path
    (``TpuAligner._run_chunk``) and the ``shard_map`` path
    (``racon_tpu.parallel.sharded_align``). With ``use_pallas`` the
    VMEM-resident Mosaic kernels produce the identical direction matrix
    and (gap-interleaved) op codes; with ``use_swar`` the forward DP runs
    on packed int16x2 score lanes (bit-identical outputs — the walks
    consume the same direction matrix either way)."""
    if use_pallas:
        from .pallas_nw import pallas_nw_fwd, pallas_walk_ops
        packed, score = pallas_nw_fwd(qrp, tp, n, m, max_len=max_len,
                                      band=band, steps=steps,
                                      out_quant=512, use_swar=use_swar)
        # the Pallas walk emits the packed op stream directly
        ops_packed, fi, fj = pallas_walk_ops(packed, n, m, band=band)
        return ops_packed, score, fi, fj
    packed, score = _nw_wavefront_kernel(qrp, tp, n, m,
                                         max_len=max_len, band=band,
                                         steps=steps, swar=use_swar)
    return _traceback_kernel(packed, score, n, m, max_len=max_len,
                             band=band, swar=use_swar)


def _row_layout(n, m, *, max_len: int, band: int):
    """Shared offset/validity math for the banded NW row layout: qrp holds
    the reversed query ending at column ``c + max_len``, tp the forward
    target at offset ``c`` — exactly the layout the host used to pack."""
    B = n.shape[0]
    c = band // 2
    width = c + max_len + band
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]
    row0 = (jnp.arange(B, dtype=jnp.int32) * max_len)[:, None]
    qoff = c + max_len - 1 - pos  # reversed: column c+j holds q[...-j]
    toff = pos - c
    return (row0, (qoff, (qoff >= 0) & (qoff < n[:, None])),
            (toff, (toff >= 0) & (toff < m[:, None])))


@functools.partial(jax.jit, static_argnames=("max_len", "band"))
def _build_rows(qcat, tcat, n, m, *, max_len: int, band: int):
    """Build the banded NW row layout on device from dense byte blocks
    (pair k's query/target at ``k * max_len``)."""
    B = n.shape[0]
    row0, qlay, tlay = _row_layout(n, m, max_len=max_len, band=band)

    def fill(cat, lay):
        off, valid = lay
        src = row0 + jnp.clip(off, 0, max_len - 1)
        w = src.shape[1]
        return jnp.where(valid, jnp.take(cat, src.reshape(-1)
                                         ).reshape(B, w), jnp.uint8(0))

    return fill(qcat, qlay), fill(tcat, tlay)


@functools.partial(jax.jit, static_argnames=("max_len", "band"))
def _build_rows_packed(q4, t4, n, m, *, max_len: int, band: int):
    """``_build_rows`` over nibble-packed inputs (two 4-bit codes per
    byte; code 0 is padding). Unpacking is a shift/mask on the gathered
    byte, so the wide row arrays never cross the host link."""
    B = n.shape[0]
    row0, qlay, tlay = _row_layout(n, m, max_len=max_len, band=band)

    def unpack(cat4, lay):
        off, valid = lay
        src = row0 + jnp.clip(off, 0, max_len - 1)
        w = src.shape[1]
        byte = jnp.take(cat4, (src // 2).reshape(-1)).reshape(B, w)
        code = (byte >> ((src % 2) * 4).astype(jnp.uint8)) & 0xF
        return jnp.where(valid, code.astype(jnp.uint8), jnp.uint8(0))

    return unpack(q4, qlay), unpack(t4, tlay)


@functools.partial(jax.jit, static_argnames=("max_len", "band"))
def _build_rows_packed2(q2, t2, n, m, *, max_len: int, band: int):
    """``_build_rows`` over 2-bit-packed inputs (four codes per byte, 16
    per int32 word — the SWAR transfer format for chunks whose alphabet
    fits 4 symbols). The gathered byte count drops 4x vs raw and 2x vs
    the nibble pack; code 0 doubles as padding, which is sound because
    the wavefront kernel only consumes characters at interior cells
    (pad lanes' direction codes are never read by any walk)."""
    B = n.shape[0]
    row0, qlay, tlay = _row_layout(n, m, max_len=max_len, band=band)

    def unpack(cat2, lay):
        off, valid = lay
        src = row0 + jnp.clip(off, 0, max_len - 1)
        w = src.shape[1]
        byte = jnp.take(cat2, (src // 4).reshape(-1)).reshape(B, w)
        code = (byte >> ((src % 4) * 2).astype(jnp.uint8)) & 3
        return jnp.where(valid, code.astype(jnp.uint8), jnp.uint8(0))

    return unpack(q2, qlay), unpack(t2, tlay)


def _sweep_bound(max_nm: int, max_len: int) -> int:
    """Anti-diagonal sweep bound for a bucket/chunk, multiple of 512
    (the Pallas kernels' granularity: every band's flush period
    F = FL/RB divides 128 and the packed walk flushes 128-byte output
    groups of 512 steps). Long buckets quantize to 2048: every distinct
    ``steps`` value is a separate XLA/Mosaic compile (~30 s) and a
    longest-first chunk stream over a real read set walks through a
    handful of them, while the static bound only sizes the direction
    matrix — the kernels' per-block dynamic bounds already skip the
    quantization's dead wavefronts, so the coarse quantum costs memory
    (<= 1 MB/pair), not compute. Shared by the chunk launcher and the
    memory-budget sizing so they account identically."""
    quant = 512 if max_len <= 1024 else 2048
    steps = min(-(-max_nm // quant) * quant, 2 * max_len)
    return -(-steps // 512) * 512


@functools.partial(jax.jit, static_argnames=("w", "NW"))
def _breaking_points_kernel(ops_packed, n, m, first_rel, nb, *, w: int,
                            NW: int):
    """Per-window breaking points straight from the packed walk op codes —
    the device analog of :func:`core.overlap.breaking_points_from_cigar`,
    so only ~8 bytes per window boundary ever cross the host link instead
    of the whole op stream (~2 bits/base; the tunnel's bandwidth, not the
    DP, bounded the aligner).

    Coordinates are span-relative and packed ``tpos << 14 | qpos`` (both
    < 16384, the bucket cap). For boundary interval k (boundaries at
    ``first_rel + j*w`` for j < nb-1, plus ``m-1``):

    - ``bp_first[b, k]`` = packed coords of the first match in interval k
      (BIG when the interval has no match — nothing is emitted, exactly
      the walker's found_first rule);
    - ``bp_last[b, k]`` = packed coords of the last match at or before
      boundary k (a running prefix max; the walker's ``last``/M-crossing
      cases unify to this).

    Identical for both walk backends: gap-code placement differs but the
    M steps' (tpos, qpos) sets are equal and min/max are order-free.

    Per-interval aggregation is ``NW`` (static, ~10-34) masked reduces
    over the [B, S] step stream rather than a scatter-min/max: XLA's
    scatter engine crawls the ~4M updates of a full chunk at ~90M/s
    (~45 ms per table — it used to cost more than the DP itself), while
    the masked reduces are streaming VPU passes (~5 ms total).
    """
    B, S4 = ops_packed.shape
    S = S4 * 4
    shifts = jnp.arange(4, dtype=jnp.uint8) * 2
    ops = ((ops_packed[:, :, None] >> shifts) & 3).reshape(B, S)
    is_real = ops < 3
    is_M = ops == 0
    di = (is_M | (ops == 1)).astype(jnp.int32)
    dj = (is_M | (ops == 2)).astype(jnp.int32)
    i_t = n[:, None] - jnp.cumsum(di, axis=1) + di
    j_t = m[:, None] - jnp.cumsum(dj, axis=1) + dj
    tpos = j_t - 1          # 0-based span-relative target pos of an M base
    qpos = i_t - 1
    BIG = jnp.int32(1 << 30)

    # boundary-interval index: number of boundaries < tpos (the final
    # boundary m-1 is never < tpos since tpos <= m-1)
    widx = jnp.clip(
        -(-(tpos - first_rel[:, None]) // w), 0, nb[:, None] - 1)
    valid = is_M & is_real & (tpos >= 0)
    packed = jnp.where(valid, (tpos << 14) | jnp.maximum(qpos, 0), BIG)

    bp_first = jnp.stack(
        [jnp.min(jnp.where(widx == k, packed, BIG), axis=1)
         for k in range(NW)], axis=1)
    bp_last = jnp.stack(
        [jnp.max(jnp.where(valid & (widx == k), packed, -1), axis=1)
         for k in range(NW)], axis=1)
    bp_last = lax.cummax(bp_last, axis=1)
    return bp_first, bp_last


def _pow2_pool(n: int) -> int:
    """THE packed-pool padding rule (round 19): the resident dataflow's
    uploaded ``weight << 3 | code`` pool is zero-padded to this pow2
    length so the derive-kernel jit signature stays stable across runs
    of similar size. Shared by :func:`upload_qpw_pool` and the
    aligner's warm-up so the warm-cache claim cannot drift."""
    c = 1024
    while c < n:
        c *= 2
    return c


@functools.partial(jax.jit, static_argnames=("w", "NW", "Lq"))
def _derive_layer_rows(bp_first, bp_last, qpw_pool, live, tb, qo_read,
                       qo_pool, n_reg, win_base, ov_idx, has_q, qlen,
                       s_min, q_need, *, w: int, NW: int, Lq: int):
    """Device-resident layer-row derivation (round 19): the vectorized
    filter core of ``Polisher._assemble_layers`` re-expressed over ONE
    align chunk's device-resident breaking-point tables, so the tables
    are never fetched and no per-row host work remains.

    Inputs are the chunk's packed ``tpos << 14 | qpos`` tables
    ([B, NW], :func:`_breaking_points_kernel`), the run's uploaded
    packed pool, and per-lane scalars: ``live`` marks accepted lanes,
    ``tb``/``qo_read`` the overlap's global target begin / oriented
    query offset, ``qo_pool`` the lane's pool offset (``ov_off + qo``),
    ``win_base`` the target's first window id, ``qlen`` the query-span
    length (<= Lq, the bucket cap — which is what keeps the weight
    gather [B, Lq] instead of [B, read_len]).

    The three keeps mirror the host oracle EXACTLY (the parity suite
    locks this): min-span as ``span >= s_min`` with ``s_min =
    ceil(0.02 * w)`` (an integer >= a real iff >= its ceiling);
    mean-PHRED as the integer cross-multiplication ``sum(q - 33) >=
    q_need * span`` — equivalent to the host's f64 quotient compare
    whenever the threshold is an integer and every quality byte >= 33
    (the resident gate), because a non-equal quotient differs from the
    threshold by >= 1/span >= 2^-14, far above f64 rounding error;
    and the empty-layer drop ``begin != end``.

    Returns a flat [B * NW, 6] int32 table of (win_id, overlap index,
    q_first, q_end_excl, layer_begin, layer_end) rows; dropped rows
    carry the ``_ROW_SENTINEL`` win_id and sort to the tail of the
    finalize output."""
    BIG = jnp.int32(1 << 30)
    col = jnp.arange(NW, dtype=jnp.int32)[None, :]
    fp = bp_first
    lp = bp_last
    valid = (col <= n_reg[:, None]) & (fp < BIG) & live[:, None]
    t_first = tb[:, None] + (fp >> 14)
    qf = fp & 0x3FFF
    qe = (lp & 0x3FFF) + 1
    t_endx = tb[:, None] + (lp >> 14) + 1
    span = qe - qf
    keep = valid & (span >= s_min)
    # per-lane quality prefix sums over the lane's own query span: the
    # host oracle's budgeted csum slices collapse to one [B, Lq] gather
    B = bp_first.shape[0]
    pos = jnp.arange(Lq, dtype=jnp.int32)[None, :]
    src = qo_pool[:, None] + jnp.minimum(pos,
                                         jnp.maximum(qlen[:, None] - 1, 0))
    wrow = jnp.where(pos < qlen[:, None],
                     (qpw_pool[src] >> 3).astype(jnp.int32), 0)
    csum = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), jnp.cumsum(wrow, axis=1)], axis=1)
    qf_c = jnp.clip(qf, 0, Lq)
    qe_c = jnp.clip(qe, 0, Lq)
    sum_w = (jnp.take_along_axis(csum, qe_c, axis=1)
             - jnp.take_along_axis(csum, qf_c, axis=1))
    keep = keep & jnp.where(has_q[:, None], sum_w >= q_need * span, True)
    rank = t_first // w
    win = win_base[:, None] + rank
    lb = t_first - rank * w
    le = t_endx - rank * w - 1
    keep = keep & (lb != le)
    out = jnp.stack(
        [jnp.where(keep, win, jnp.int32(_ROW_SENTINEL)),
         jnp.broadcast_to(ov_idx[:, None], (B, NW)),
         qo_read[:, None] + qf, qo_read[:, None] + qe, lb, le], axis=-1)
    return out.reshape(B * NW, 6)


# win_id sentinel for dropped derive rows: sorts after every real window
_ROW_SENTINEL = (1 << 31) - 1


@jax.jit
def _finalize_layer_table(flat_all, src):
    """One-shot finalize of the resident layer table: gather the
    per-chunk derive blocks (+ host-fallback rows) into overlap-stream
    order and stable-sort by window id — exactly the host oracle's
    ``np.argsort(win_id, kind="stable")`` over rows in overlap order,
    with dropped rows (``_ROW_SENTINEL``) sorting to the tail. Traced
    shapes on purpose: this runs ONCE per run, and the shape-hazard
    lint exempts traced-shape jits."""
    g = jnp.take(flat_all, src, axis=0)
    order = jnp.argsort(g[:, 0], stable=True)
    return jnp.take(g, order, axis=0)


def upload_qpw_pool(qpw_pool: np.ndarray):
    """Upload the run's packed ``weight << 3 | code`` pool ONCE (padded
    to the shared pow2 rule), synchronously — the caller times this to
    estimate link bandwidth for the lane-upload-saved accounting."""
    cap = _pow2_pool(len(qpw_pool))
    if cap != len(qpw_pool):
        qpw_pool = np.pad(qpw_pool, (0, cap - len(qpw_pool)))
    arr = jnp.asarray(qpw_pool)
    arr.block_until_ready()
    return arr


def finalize_layer_table(parts, host_flat: np.ndarray,
                         src: np.ndarray) -> np.ndarray:
    """Concatenate the per-chunk derive blocks with the host-fallback
    rows, run :func:`_finalize_layer_table`, and fetch the ONE sorted
    [T, 6] table — the resident dataflow's single bulk device->host
    transfer."""
    from ..parallel import fetch_global
    segs = list(parts)
    segs.append(jnp.asarray(
        np.ascontiguousarray(host_flat, dtype=np.int32).reshape(-1, 6)))
    flat_all = jnp.concatenate(segs, axis=0) if len(segs) > 1 else segs[0]
    table = _finalize_layer_table(flat_all,
                                  jnp.asarray(src.astype(np.int32)))
    return np.asarray(fetch_global([table])[0])


class _DevChunkBp:
    """Device-resident breaking-point tables of ONE align chunk (round
    19): the resident ``_finish_chunk_bp`` keeps ``bp_first``/``bp_last``
    on device and fetches only the 12 bytes/lane accept-gate scalars.
    Accepted lanes hold :class:`_DevBp` handles into this object; the
    polisher's resident assemble calls :meth:`derive` per chunk, and
    :meth:`fetch` is the host-decode escape hatch (one whole-chunk
    fetch, shared by every handle)."""

    __slots__ = ("bp_first", "bp_last", "w", "NW", "B", "max_len",
                 "_host")

    def __init__(self, bp_first, bp_last, w: int, max_len: int):
        self.bp_first = bp_first
        self.bp_last = bp_last
        self.w = w
        self.B = int(bp_first.shape[0])
        self.NW = int(bp_first.shape[1])
        self.max_len = max_len
        self._host = None

    def fetch(self):
        """Host copies of the tables (cached; one fetch per chunk)."""
        if self._host is None:
            from ..parallel import fetch_global
            fp, lp = fetch_global([self.bp_first, self.bp_last])
            # graftlint: disable=lock-discipline (idempotent lazy cache — both contexts would store the same fetched tables; worst case is one duplicate fetch)
            self._host = (np.asarray(fp, dtype=np.int64),
                          np.asarray(lp, dtype=np.int64))
        return self._host

    def derive(self, dev_pool, live, tb, qo_read, qo_pool, n_reg,
               win_base, ov_idx, has_q, qlen, s_min: int, q_need: int):
        """Dispatch :func:`_derive_layer_rows` for this chunk's lanes
        (per-lane arrays are host np, full-B, dead lanes zeroed)."""
        return _derive_layer_rows(
            self.bp_first, self.bp_last, dev_pool,
            jnp.asarray(live), jnp.asarray(tb), jnp.asarray(qo_read),
            jnp.asarray(qo_pool), jnp.asarray(n_reg),
            jnp.asarray(win_base), jnp.asarray(ov_idx),
            jnp.asarray(has_q), jnp.asarray(qlen),
            np.int32(s_min), np.int32(q_need),
            w=self.w, NW=self.NW, Lq=self.max_len)


class _DevBp:
    """One accepted pair's device-resident breaking points: a (chunk,
    lane) reference plus the host-side meta the row construction needs.
    Replaces the (k, 4) ndarray in ``overlap.breaking_points`` when the
    resident dataflow is on; :meth:`decode_host` reproduces the host
    path's rows byte-exactly (the universal fallback when a resident
    precondition fails)."""

    __slots__ = ("chunk", "lane", "t_begin", "q_off", "n_reg", "qlen")

    is_device_resident = True

    def __init__(self, chunk: _DevChunkBp, lane: int, t_begin: int,
                 q_off: int, n_reg: int, qlen: int):
        self.chunk = chunk
        self.lane = lane
        self.t_begin = t_begin
        self.q_off = q_off
        self.n_reg = n_reg
        self.qlen = qlen

    def __len__(self) -> int:
        # row-count upper bound (n_reg + 1 boundary intervals) — the
        # pipelined run()'s queue-depth heuristic only needs the scale
        return self.n_reg + 1

    def decode_host(self) -> np.ndarray:
        """The non-resident ``_finish_chunk_bp`` row construction for
        this lane, from the chunk's (cached) host fetch."""
        fp_all, lp_all = self.chunk.fetch()
        fp = fp_all[self.lane]
        lp = lp_all[self.lane]
        col = np.arange(fp.shape[0], dtype=np.int64)
        valid = (col <= self.n_reg) & (fp < (1 << 30))
        rows = np.stack(
            [self.t_begin + (fp >> 14), self.q_off + (fp & 0x3FFF),
             self.t_begin + (lp >> 14) + 1,
             self.q_off + (lp & 0x3FFF) + 1], axis=-1)
        return rows[valid].astype(np.int32)


def _ops_to_cigar(path: np.ndarray) -> str:
    """Run-length encode a backward-order op path into a CIGAR string
    (callers pre-filter ``ops < 3`` — the Pallas walk interleaves
    inactive-gap codes after M steps, the XLA walk only trails them)."""
    if len(path) == 0:
        return ""
    arr = path[::-1]
    change = np.flatnonzero(np.diff(arr)) + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [len(arr)]))
    sym = {0: "M", 1: "I", 2: "D"}
    return "".join(f"{e - s}{sym[int(arr[s])]}" for s, e in zip(starts, ends))


from .pallas_nw import PallasDispatchMixin
from .. import faults, obs
from ..obs import metrics


class TpuAligner(PallasDispatchMixin):
    """Batched device aligner with on-device traceback and host fallback.

    ``mesh``: optional 1-D :class:`jax.sharding.Mesh`; when given, every
    device batch is split along its batch dimension over the mesh with
    ``shard_map`` (multi-chip analog of the reference's per-GPU batch
    binning, ``src/cuda/cudapolisher.cpp:163-171``).
    """

    def __init__(self, fallback=None, buckets=BUCKETS,
                 max_dirs_bytes=MAX_DIRS_BYTES, mesh=None,
                 num_batches: int = 1, use_swar: bool = True,
                 device=None, use_ragged=None, use_ladder=None):
        from .. import flags
        self.fallback = fallback
        self.buckets = buckets
        self.max_dirs_bytes = max_dirs_bytes
        self.mesh = mesh
        # per-engine chip pin (mutually exclusive with a mesh): the
        # in-process chip scheduler builds one aligner per local device
        # and every launch/fetch runs under jax.default_device(device)
        self.device = device
        # Batch count (reference --cudaaligner-batches N,
        # cudapolisher.cpp:91): the device pipeline depth. N chunks are
        # kept in flight (JAX async dispatch), each capped at 1/N of the
        # direction-matrix memory budget, so host packing of chunk k+1
        # overlaps device compute of chunk k.
        self.num_batches = max(1, num_batches)
        # SWAR-packed forward DP (int16x2 score lanes + 2-bit bases when
        # the chunk alphabet fits 4 symbols). Guarded per bucket by the
        # overflow guard (swar.swar_fits) and globally by the bit-exact
        # availability probe (swar.swar_ok) — both identical-output, so
        # this knob only exists for A/B measurement and escape hatches.
        self.use_swar = use_swar
        # ragged pair packing (round 17, on by default off-mesh; ctor
        # arg or RACON_TPU_ALIGN_RAGGED=0 restores the bucketed wave
        # driver): pairs greedy-fill a fixed direction-matrix arena by
        # their own sweep cost through the streaming _AlignStream
        # session — the aligner analog of poa._ConsensusStream
        self.use_ragged = (flags.get_bool("RACON_TPU_ALIGN_RAGGED")
                           if use_ragged is None else use_ragged)
        # adaptive band ladder (round 17; RACON_TPU_BAND_LADDER=0 for
        # A/B): seed each pair's band from its overlap's estimated
        # divergence, escalate escapees batched — see BAND_RUNGS
        self.use_ladder = (flags.get_bool("RACON_TPU_BAND_LADDER")
                           if use_ladder is None else use_ladder)
        # memory backpressure (round 12 ladder parity, round 17): a
        # device RESOURCE_EXHAUSTED halves the effective direction-
        # matrix budget (reduce_capacity) and the chunk re-dispatches —
        # grouping never changes output bytes, only launch size
        self.capacity_scale = 1
        # shapes already submitted for warm-up compilation (the
        # resident service warms per admitted job; repeats are free)
        self._warmed_shapes: set = set()
        # adaptive ladder state: [count, sum, sum_sq] of the realized
        # divergence (score / longer span) of every accepted pair
        self._div_obs = [0, 0.0, 0.0]
        # sanitizer: per-aligner shadow sampler (first chunk always)
        from .. import sanitize
        self._shadow = sanitize.ShadowSampler()
        # occupancy telemetry (round 17): chunks/lanes_occupied/
        # lanes_total count every dispatched wavefront arena (occupied
        # = sum of real pairs' n+m anti-diagonals, total = B x steps
        # per launch); steps_wasted is their gap and wavefront_work
        # (total x band, summed over rungs) is the banded-DP cost the
        # bench A/B grid records — replacing the blind device/
        # band_escalated counts as the aligner's efficiency signal
        self.stats = {"device": 0, "fallback_length": 0, "fallback_band": 0,
                      "band_escalated": 0, "swar_chunks": 0,
                      "swar_guard_int32": 0, "chunks": 0,
                      "lanes_occupied": 0, "lanes_total": 0,
                      "steps_wasted": 0, "wavefront_work": 0,
                      "ladder_narrow": 0}

    # the floor keeps OOM backpressure from shrinking chunks below the
    # point where per-chunk fixed costs dominate (mirrors the consensus
    # engine's _MAX_CAPACITY_SCALE contract)
    _MAX_CAPACITY_SCALE = 16

    @property
    def dirs_budget_cap(self) -> int:
        """Total in-flight direction-matrix byte budget under the
        current OOM-backpressure scale (``max_dirs_bytes`` at 1). The
        floor derives from the CONFIGURED budget at the maximum scale —
        an absolute floor would both override small caller-sized
        budgets and let reduce_capacity() report shrinkage it no
        longer delivers (the exec ladder would re-dispatch at
        unchanged memory and OOM again)."""
        return max(1, self.max_dirs_bytes // self.capacity_scale)

    def chunk_dirs_budget(self) -> int:
        """Per-chunk direction-matrix budget: the in-flight budget split
        over the pipeline depth — shared by the bucketed wave driver,
        the ragged stream's greedy fill and the warm-up shape estimate
        so all three account identically."""
        return max(1, self.dirs_budget_cap // self.num_batches)

    def reduce_capacity(self) -> bool:
        """Halve the direction-matrix arena (device-OOM backpressure,
        the exec ladder's ``reduce-capacity`` rung). Returns False once
        at the floor — the ladder then falls through to the CPU
        engines. Chunk grouping never changes output bytes (pairs are
        independent), so a reduced re-dispatch is byte-identical."""
        if self.capacity_scale >= self._MAX_CAPACITY_SCALE:
            return False
        self.capacity_scale *= 2
        metrics.set_gauge("aligner.capacity_scale", self.capacity_scale)
        metrics.inc("faults.backpressure_halvings")
        return True

    def pack_metrics(self) -> dict:
        """Derived occupancy view of :attr:`stats` (the aligner twin of
        ``TpuPoaConsensus.pack_metrics``): ``align_pad_fraction`` =
        wavefront-arena lanes spent on padding (batch pow2 pad + dead
        anti-diagonals past each pair's own n+m), ``align_chunks`` =
        dispatched device chunks."""
        tot = self.stats.get("lanes_total", 0)
        eff = self.stats.get("lanes_occupied", 0) / tot if tot else 0.0
        return {"align_pack_efficiency": round(eff, 4),
                "align_pad_fraction": round(1.0 - eff, 4) if tot else 0.0,
                "align_chunks": self.stats.get("chunks", 0),
                "align_steps_wasted": self.stats.get("steps_wasted", 0)}

    def _swar_choice(self, max_len: int) -> bool:
        """Packed-lane eligibility for a bucket: the global availability
        probe plus the per-bucket overflow guard — a band/length
        combination whose scores could exceed the int16 saturation
        ceiling re-dispatches to the int32 path (counted in stats)."""
        from .swar import swar_fits, swar_ok
        if not self.use_swar:
            return False
        if not swar_fits(max_len):
            self.stats["swar_guard_int32"] += 1
            metrics.inc("aligner.swar_guard_int32")
            return False
        return swar_ok()

    def _pad_batch(self, count: int) -> int:
        """Batch sizes are ``mesh_size * 2^k`` — always divisible by the
        mesh (shard_map splits evenly) and geometric (compile-cache hits);
        plain power of two without a mesh."""
        from ..parallel import mesh_size
        B = mesh_size(self.mesh)
        while B < count:
            B *= 2
        return B

    def _bucket_index(self, qlen: int, tlen: int, start: int = 0):
        need = abs(qlen - tlen) + 16
        want = need + int(TYPICAL_DIVERGENCE * max(qlen, tlen))
        fallback_bi = None
        for bi in range(start, len(self.buckets)):
            max_len, band = self.buckets[bi]
            if qlen <= max_len and tlen <= max_len and need <= band // 2:
                if want <= band // 2:
                    return bi
                if fallback_bi is None:
                    fallback_bi = bi
        return fallback_bi

    def _observe_divergence(self, scores, maxlens) -> None:
        """Feed accepted pairs' realized edit divergence (score over the
        longer span) into the run's running estimate — the adaptive half
        of the band ladder."""
        cnt, s, s2 = self._div_obs
        d = np.asarray(scores, dtype=np.float64) / np.maximum(
            np.asarray(maxlens, dtype=np.float64), 1.0)
        self._div_obs = [cnt + d.size, s + float(d.sum()),
                         s2 + float((d * d).sum())]

    def _adaptive_divergence(self):
        """Observed-divergence upper estimate (mean + 2 sigma) once
        enough pairs have resolved; None while cold."""
        cnt, s, s2 = self._div_obs
        if cnt < ADAPT_MIN_PAIRS:
            return None
        mean = s / cnt
        var = max(0.0, s2 / cnt - mean * mean)
        return mean + 2.0 * var ** 0.5

    def _est_divergence(self, err) -> float:
        """Divergence estimate for the band ladder. COLD (no resolved
        pairs yet): ``TYPICAL_DIVERGENCE`` — deliberately conservative,
        so a run never gambles narrow bands on the span-asymmetry proxy
        alone (the overlap filter's ``o.error`` only sees NET indels; a
        substitution-heavy run would seed low and escape every pair).
        WARM: the observed divergence (:meth:`_adaptive_divergence`),
        raised per pair by the span proxy (2x + 5% margin) when that
        reads higher. An underestimate costs one batched re-dispatch
        (band escape), never a wrong alignment — the accept gate is the
        same optimality certificate at every rung."""
        ad = self._adaptive_divergence()
        if ad is None:
            return TYPICAL_DIVERGENCE
        proxy = 0.0 if err is None else 2.0 * float(err) + 0.05
        return min(TYPICAL_DIVERGENCE, max(proxy, ad))

    def _seed_geometry(self, qlen: int, tlen: int, err=None,
                       record: bool = True):
        """Starting ``(bucket_index, band)`` for one pair: the fixed
        path's bucket, at the narrowest ladder rung the divergence
        estimate admits (the bucket's full band with the ladder off, or
        when no rung is plausibly wide enough). None -> host fallback,
        exactly the fixed path's length-reject set. ``record=False``
        skips the ladder telemetry — the warm-up's shape ESTIMATE must
        not count phantom pairs (nor write the stats dict from the
        service's admission thread)."""
        bi = self._bucket_index(qlen, tlen)
        if bi is None:
            return None
        bucket_band = self.buckets[bi][1]
        if not self.use_ladder:
            return (bi, bucket_band)
        need = abs(qlen - tlen) + 16
        want = need + int(self._est_divergence(err) * max(qlen, tlen))
        for rung in BAND_RUNGS:
            if rung >= bucket_band:
                break
            if want <= rung // 2:
                if record:
                    self.stats["ladder_narrow"] += 1
                    metrics.inc("aligner.ladder_narrow")
                return (bi, rung)
        return (bi, bucket_band)

    def _chunk_cap(self, steps: int, band: int, base: int = 1) -> int:
        """Pairs per device chunk for one sweep geometry: the largest
        ``base * 2^k`` batch whose direction matrix fits the per-chunk
        budget, bounded by ``MAX_CHUNK_PAIRS`` (transient host span
        copies). THE one cap rule — shared by the bucketed wave driver,
        the ragged stream's greedy fill and the warm-up shape estimate,
        so the warm-cache claim cannot drift from the live caps."""
        raw = self.chunk_dirs_budget() // (steps * (band // 8))
        cap = base
        while cap * 2 <= raw and cap * 2 <= MAX_CHUNK_PAIRS:
            cap *= 2
        return cap

    def _next_geometry(self, qlen: int, tlen: int, bi: int, band: int):
        """Escalation after a band escape: the next ladder rung inside
        the same bucket (skipping rungs the pair's length difference
        already rules out), then the fixed path's bucket escalation —
        so the ladder's terminal geometry sequence IS the fixed path's,
        and the two reject sets coincide. None -> host fallback."""
        bucket_band = self.buckets[bi][1]
        if band < bucket_band:
            # an escape means the seed was wrong, so jump, don't creep:
            # the rung the CURRENT divergence estimate (adaptive once
            # warm, TYPICAL when cold — conservative) says should hold,
            # at least 2x the failed band — a 1.5x walk would waste a
            # re-dispatch per step
            need = abs(qlen - tlen) + 16
            want = need + int(self._est_divergence(None)
                              * max(qlen, tlen))
            nb = bucket_band
            for rung in BAND_RUNGS:
                if rung >= 2 * band and rung < bucket_band \
                        and want <= rung // 2:
                    nb = rung
                    break
            return (bi, nb)
        nbi = self._bucket_index(qlen, tlen, bi + 1)
        if nbi is None:
            return None
        return (nbi, self.buckets[nbi][1])

    # the polisher hands this backend the whole overlap stream (it buckets
    # and chunks internally) instead of pre-chunked 1024-pair slices
    wants_full_stream = True

    def align_batch(self, pairs: Sequence[Tuple[bytes, bytes]],
                    progress=None, errors=None) -> List[str]:
        """CIGAR strings for every pair (test/bench surface; the pipeline
        uses :meth:`breaking_points_batch`, which never fetches the op
        stream). ``errors`` optionally carries per-pair divergence
        estimates for the band ladder (overlap ``error`` values)."""
        return self._drive(pairs, progress, None, errors)

    def breaking_points_batch(self, pairs, metas, window_length: int,
                              progress=None, errors=None):
        """Per-window breaking points for every (query-span, target-span)
        pair — the production surface behind
        ``Polisher.find_overlap_breaking_points``. ``metas[i]`` is the
        overlap's ``(t_begin, q_off)`` (global target start; strand-aware
        global query offset); ``errors[i]`` (optional) its filter-time
        ``error`` estimate, seeding the band ladder. The walk stays on
        device and only ~8 bytes per window boundary are fetched
        (:func:`_breaking_points_kernel`); rejects fall back to the host
        aligner + the shared CIGAR walker. Returns one **columnar** int32
        ndarray of shape (k, 4) per pair — rows of (t_first, q_first,
        t_end_excl, q_end_excl), row-identical to the walker's pairs on
        every path."""
        return self._drive(pairs, progress, (window_length, metas), errors)

    def bp_stream(self, window_length: int, progress=None, total: int = 0,
                  resident: bool = False):
        """Open a ragged streaming breaking-points session (round 17):
        ``feed()`` buckets pairs by their own sweep cost and band rung
        and **asynchronously dispatches** greedy-filled chunks as
        overlap slices arrive — packing/dispatch/fetch pipeline across
        slice boundaries instead of draining per slice — and
        ``finish()`` drains the pipeline, runs the batched band-ladder
        escalations and the host fallback, and returns breaking points
        for every fed pair in feed order. ``Polisher._align_need`` feeds
        this directly. Returns None when the ragged packer is
        unavailable (mesh runs, ``RACON_TPU_ALIGN_RAGGED=0``) — callers
        then fall back to per-slice :meth:`breaking_points_batch`."""
        if not self.use_ragged or self.mesh is not None:
            return None
        return _AlignStream(self, window_length=window_length,
                            progress=progress, total_hint=total,
                            resident=resident)

    def _drive(self, pairs, progress, bp_meta, errors=None):
        if self.use_ragged and self.mesh is None:
            # one-feed session: the same ragged packer the polisher's
            # streaming feed uses, so batch surfaces and the pipeline
            # share one dispatch path (and one A/B axis)
            sess = _AlignStream(
                self, window_length=bp_meta[0] if bp_meta else None,
                progress=progress, total_hint=len(pairs))
            sess.feed(pairs, metas=bp_meta[1] if bp_meta else None,
                      errors=errors)
            return sess.finish()
        return self._drive_bucketed(pairs, progress, bp_meta, errors)

    def _drive_bucketed(self, pairs, progress, bp_meta, errors=None):
        # progress counts pairs whose final result is settled — escaped
        # pairs re-enter a wider geometry and are only counted once, on
        # their last visit; fallback/empty pairs are counted when resolved
        done_pairs = 0
        empty_bp = np.zeros((0, 4), dtype=np.int32)
        cigars: List = [("" if bp_meta is None else empty_bp)
                        for _ in range(len(pairs))]
        by_class = {}  # (bucket_index, band) -> indices
        reject: List[int] = []
        for idx, (q, t) in enumerate(pairs):
            if len(q) == 0 or len(t) == 0:
                if bp_meta is None:
                    cigars[idx] = (f"{len(t)}D" if len(t) else
                                   (f"{len(q)}I" if len(q) else ""))
                else:
                    cigars[idx] = empty_bp  # no matches -> no breaking pts
                done_pairs += 1
                continue
            g = self._seed_geometry(len(q), len(t),
                                    None if errors is None
                                    else errors[idx])
            if g is None:
                reject.append(idx)
            else:
                by_class.setdefault(g, []).append(idx)
        self.stats["fallback_length"] += len(reject)

        # Band escapes retry on device at the next rung (ladder) or the
        # next wider-band bucket — the analog of the reference host's
        # band-doubling, but batched. All classes of a wave share one
        # in-flight window (num_batches deep): with num_batches > 1,
        # chunk k+1 of any class is packed and dispatched while chunk k
        # computes, hiding the tunnel's ~0.3s per-fetch round-trip;
        # escape handling is batched per wave either way. Only escapes
        # from the widest geometry go to the host fallback.
        from ..parallel import mesh_size
        # cold-estimator eager fetch (see _AlignStream._launch): fetch
        # the wave's first chunk immediately so the adaptive ladder
        # seeds the rest of the wave from real scores
        eager = (self.use_ladder
                 and self._adaptive_divergence() is None)
        while by_class:
            inflight = []
            escaped = {}  # class -> indices that escaped its band
            for cls in sorted(by_class):
                bi, band = cls
                # longest first: chunks (and the Pallas kernels' 64-pair
                # blocks within them) hold similar-length pairs, so the
                # per-block dynamic sweep bound cuts the short blocks'
                # dead wavefronts instead of averaging against the max
                indices = sorted(
                    by_class[cls],
                    key=lambda i: -(len(pairs[i][0]) + len(pairs[i][1])))
                max_len = self.buckets[bi][0]
                # budget by the real sweep bound, not the worst case: the
                # direction matrix is (B, steps, band/8) and steps tracks
                # the longest pair in the class — budgeting 2*max_len
                # halved the chunk size (and doubled the dispatch syncs)
                # for typical pairs well under the bucket cap (indices
                # are sorted longest-first, so the head is the max)
                max_nm = (len(pairs[indices[0]][0])
                          + len(pairs[indices[0]][1]))
                steps_est = _sweep_bound(max_nm, max_len)
                raw_cap = self.chunk_dirs_budget() // (steps_est
                                                       * (band // 8))
                # chunks pad to mesh_size * 2^k (see _pad_batch), so cap
                # at the largest such size to keep the memory bound honest
                batch_cap = mesh_size(self.mesh)
                if batch_cap > max(1, raw_cap):
                    import warnings
                    warnings.warn(
                        f"mesh size {batch_cap} exceeds the direction-"
                        f"matrix memory budget ({raw_cap} pairs of bucket "
                        f"({max_len},{band}) fit in "
                        f"{self.chunk_dirs_budget()} "
                        f"bytes); lower num_batches or use a smaller mesh",
                        RuntimeWarning)
                batch_cap = self._chunk_cap(steps_est, band,
                                            base=batch_cap)
                esc = escaped.setdefault(cls, [])
                # keep num_batches chunks in flight so the host packs
                # chunk k+1 while the device computes chunk k (reference
                # analog: per-batch fill/process loops on pool threads,
                # cudapolisher.cpp:98-160)
                for start in range(0, len(indices), batch_cap):
                    chunk = indices[start:start + batch_cap]
                    inflight.append(
                        (band, esc, self._launch_chunk(pairs, chunk,
                                                       max_len, band,
                                                       bp_meta)))
                    if len(inflight) >= (1 if eager
                                         else self.num_batches):
                        eager = False
                        band0, esc0, launched = inflight.pop(0)
                        n_chunk = len(launched[0])
                        n_esc = len(esc0)
                        self._finish_chunk(launched, band0, cigars, esc0,
                                           bp_meta)
                        done_pairs += n_chunk - (len(esc0) - n_esc)
                        if progress is not None:
                            progress(done_pairs, len(pairs))
            while inflight:
                band0, esc0, launched = inflight.pop(0)
                n_chunk = len(launched[0])
                n_esc = len(esc0)
                self._finish_chunk(launched, band0, cigars, esc0, bp_meta)
                done_pairs += n_chunk - (len(esc0) - n_esc)
                if progress is not None:
                    progress(done_pairs, len(pairs))
            by_class = {}
            for cls, idxs in escaped.items():
                bi, band = cls
                for idx in idxs:
                    q, t = pairs[idx]
                    # graftlint: disable=warmup-coverage (escalation rungs are data-dependent and rare by design; the terminal rung — the bucket band — IS warmed as the escape shape)
                    ng = self._next_geometry(len(q), len(t), bi, band)
                    if ng is None:
                        self.stats["fallback_band"] += 1
                        metrics.inc("aligner.fallback_band")
                        reject.append(idx)
                    else:
                        self.stats["band_escalated"] += 1
                        metrics.inc("aligner.band_escalated")
                        by_class.setdefault(ng, []).append(idx)

        self._resolve_rejects(pairs, reject, cigars, bp_meta)
        if progress is not None and done_pairs < len(pairs):
            progress(len(pairs), len(pairs))
        return cigars

    def _resolve_rejects(self, pairs, reject, results, bp_meta) -> None:
        """Host-fallback resolution for length/band rejects, shared by
        the bucketed wave driver and the ragged stream (``pairs`` only
        needs ``pairs[i]`` indexing — a list or a slot dict)."""
        if not reject:
            return
        if self.fallback is None:
            raise RuntimeError(
                f"{len(reject)} pairs rejected and no fallback aligner")
        fb = self.fallback.align_batch([pairs[i] for i in reject])
        if bp_meta is None:
            for i, cig in zip(reject, fb):
                results[i] = cig
        else:
            from ..core.overlap import decode_breaking_points_batch
            w, metas = bp_meta
            arrs = decode_breaking_points_batch(
                fb, [metas[i][1] for i in reject],
                [metas[i][0] for i in reject],
                [metas[i][0] + len(pairs[i][1]) for i in reject], w)
            for i, arr in zip(reject, arrs):
                results[i] = arr

    def _launch_chunk(self, pairs, chunk, max_len, band, bp_meta=None):
        """Span-wrapped :meth:`_launch_chunk_impl` — the dispatch half
        of the aligner's dispatch-vs-fetch split (host pack + async
        kernel dispatch; the device computes after this returns)."""
        faults.check("align.dispatch")
        with self._pinned(), obs.span("align.dispatch", pairs=len(chunk),
                                      max_len=max_len, band=band):
            return self._launch_chunk_impl(pairs, chunk, max_len, band,
                                           bp_meta)

    def _launch_chunk_impl(self, pairs, chunk, max_len, band,
                           bp_meta=None):
        """Pack a chunk and dispatch its kernels; returns the in-flight
        handle consumed by ``_finish_chunk``. Device work proceeds
        asynchronously after dispatch.

        Sequences cross the host link as dense ``B * max_len`` byte
        blocks; the banded row layout (reversal, band offsets, padding) is
        built on device (:func:`_build_rows`) — the padded row arrays are
        ~3x the raw bases, and the tunnel is bandwidth-starved."""
        # Pad the batch to a power of two: B is part of the compiled shape,
        # so arbitrary batch sizes would recompile the kernels every call.
        B = self._pad_batch(len(chunk))
        qcat = np.zeros(B * max_len, dtype=np.uint8)
        tcat = np.zeros(B * max_len, dtype=np.uint8)
        n = np.ones(B, dtype=np.int32)
        m = np.ones(B, dtype=np.int32)
        for k, idx in enumerate(chunk):
            qb, tb = pairs[idx]
            qcat[k * max_len: k * max_len + len(qb)] = \
                np.frombuffer(qb, dtype=np.uint8)
            tcat[k * max_len: k * max_len + len(tb)] = \
                np.frombuffer(tb, dtype=np.uint8)
            n[k], m[k] = len(qb), len(tb)

        steps = _sweep_bound(int((n + m).max()), max_len)

        # occupancy telemetry (round 17): the launch's wavefront arena
        # is B x steps band-wide DP rows; each real pair only produces
        # work on its own n+m anti-diagonals — the rest (batch pow2
        # padding + dead wavefronts past each pair's finish) is the
        # waste the ragged packer and band ladder exist to cut
        occ = int(n[:len(chunk)].sum()) + int(m[:len(chunk)].sum())
        total = B * steps
        self.stats["chunks"] += 1
        self.stats["lanes_occupied"] += occ
        self.stats["lanes_total"] += total
        self.stats["steps_wasted"] += total - occ
        self.stats["wavefront_work"] += total * band
        metrics.inc("align.chunks")
        metrics.inc("align.lanes_occupied", occ)
        metrics.inc("align.lanes_total", total)
        metrics.inc("align.steps_wasted", total - occ)
        metrics.inc("align.wavefront_work", total * band)

        # host->device bytes are the bottleneck on thin links: when the
        # chunk's alphabet fits 4 symbols (ACGT does) and the SWAR path
        # is live, remap to 2-bit codes packed 16 per int32 word (4x
        # fewer bytes than raw); up to 15 symbols (ACGTN does) remap to
        # nibble codes (2x). Equality-preserving bijections either way —
        # the kernels only ever compare characters for equality.
        hist = np.bincount(qcat, minlength=256)
        hist += np.bincount(tcat, minlength=256)
        alphabet = np.flatnonzero(hist[1:]) + 1  # O(N), no sort; 0 is pad
        sw = self._swar_choice(max_len)
        # multi-host: every process packs the (deterministic) chunk and
        # materializes only its addressable shards of the global arrays
        # (the flat char blocks shard evenly too: B is a mesh multiple,
        # so [B * max_len] splits on row boundaries — max_len is a
        # multiple of 4, so the 2-bit blocks split evenly as well)
        from ..parallel import to_global
        put = ((lambda a: to_global(self.mesh, a)) if self.mesh is not None
               else jnp.asarray)
        nd, md = put(n), put(m)
        if sw and len(alphabet) <= 4:
            from .swar import pack_bases_2bit
            lut = np.zeros(256, np.uint8)
            lut[alphabet] = np.arange(len(alphabet), dtype=np.uint8)
            qrp, tp = _build_rows_packed2(
                put(pack_bases_2bit(lut[qcat])),
                put(pack_bases_2bit(lut[tcat])),
                nd, md, max_len=max_len, band=band)
        elif len(alphabet) <= 15:
            lut = np.zeros(256, np.uint8)
            lut[alphabet] = np.arange(1, len(alphabet) + 1, dtype=np.uint8)
            q4 = lut[qcat]
            t4 = lut[tcat]
            q4 = q4[0::2] | (q4[1::2] << 4)
            t4 = t4[0::2] | (t4[1::2] << 4)
            qrp, tp = _build_rows_packed(put(q4), put(t4),
                                         nd, md, max_len=max_len,
                                         band=band)
        else:
            qrp, tp = _build_rows(put(qcat), put(tcat),
                                  nd, md, max_len=max_len, band=band)
        args = (qrp, tp, nd, md)
        base_key = (max_len, band, steps, B)
        swar_key = base_key + ("swar",)
        if self._use_pallas(base_key):
            from .pallas_nw import pallas_swar_ok
            # the packed Mosaic kernel's XOR+mask equality reads 4-bit
            # codes, so raw-byte chunks (alphabet > 15, rows not
            # remapped) must never take it — bytes differing only in
            # bits 4-7 would compare equal there
            sw_p = (sw and len(alphabet) <= 15 and pallas_swar_ok()
                    and self._use_pallas(swar_key))
            key = swar_key if sw_p else base_key
            try:
                out = self._dispatch(args, max_len, band, steps, True,
                                     sw_p)
                out = self._attach_bp(out, chunk, pairs, n, m, max_len,
                                      bp_meta, put)
                # counted on the path actually taken: the Pallas-level
                # decision can differ from the XLA-level one
                self.stats["swar_chunks"] += int(sw_p)
                metrics.inc("aligner.swar_chunks", int(sw_p))
                return chunk, pairs, n, m, out, (max_len, key)
            except Exception as e:
                from .. import sanitize
                sanitize.reraise_if_sanitizer(e)
                self._note_pallas_failure(key, e)
                # a packed-kernel-only fault must not cost the whole
                # Pallas path: retry the int32 Mosaic kernel before
                # downgrading the shape to XLA
                if sw_p and self._use_pallas(base_key):
                    try:
                        out = self._dispatch(args, max_len, band, steps,
                                             True, False)
                        out = self._attach_bp(out, chunk, pairs, n, m,
                                              max_len, bp_meta, put)
                        return chunk, pairs, n, m, out, (max_len,
                                                         base_key)
                    except Exception as e2:
                        from .. import sanitize
                        sanitize.reraise_if_sanitizer(e2)
                        self._note_pallas_failure(base_key, e2)
        out = self._dispatch(args, max_len, band, steps, False, sw)
        out = self._attach_bp(out, chunk, pairs, n, m, max_len, bp_meta,
                              put)
        self.stats["swar_chunks"] += int(sw)
        metrics.inc("aligner.swar_chunks", int(sw))
        return chunk, pairs, n, m, out, (max_len, None)

    def _attach_bp(self, out, chunk, pairs, n, m, max_len, bp_meta, put):
        """In breaking-points mode, derive the per-boundary tables on
        device from the (device-resident) packed op stream; the stream
        itself is never fetched."""
        if bp_meta is None:
            return out
        w, metas = bp_meta
        ops_packed, score, fi, fj = out
        B = ops_packed.shape[0]
        NW = max_len // max(w, 1) + 2
        first_rel = np.zeros(B, np.int32)
        nb = np.ones(B, np.int32)
        for k, idx in enumerate(chunk):
            t_begin, _ = metas[idx]
            t_end = t_begin + len(pairs[idx][1])
            n_reg = (t_end - 1) // w - t_begin // w
            nb[k] = n_reg + 1
            first_rel[k] = ((t_begin // w + 1) * w - 1 - t_begin
                            if n_reg else m[k] - 1)
        bp_first, bp_last = _breaking_points_kernel(
            ops_packed, put(n), put(m), put(first_rel), put(nb),
            w=w, NW=NW)
        return bp_first, bp_last, score, fi, fj

    def _dispatch(self, args, max_len, band, steps, use_pallas,
                  use_swar=False):
        if self.mesh is not None:
            from ..parallel import sharded_align
            out = sharded_align(self.mesh, *args, max_len=max_len,
                                band=band, steps=steps,
                                use_pallas=use_pallas, use_swar=use_swar)
        else:
            out = align_chain(*args, max_len=max_len, band=band,
                              steps=steps, use_pallas=use_pallas,
                              use_swar=use_swar)
        if use_swar:
            from .. import sanitize
            if self._shadow.should_shadow():
                # int32 shadow execution on the SAME walk backend (the
                # two walks place inactive-gap codes differently, so a
                # cross-backend compare would flag legitimate deltas):
                # isolates exactly the packed-lane arithmetic. Both
                # tuples come down through fetch_global — mesh runs hand
                # back global sharded arrays np.asarray cannot read.
                from ..parallel import fetch_global
                shadow = self._dispatch(args, max_len, band, steps,
                                        use_pallas, False)
                sanitize.shadow_compare(
                    fetch_global(list(out)), fetch_global(list(shadow)),
                    ("ops_packed", "score", "fi", "fj"),
                    f"aligner SWAR chunk (max_len={max_len}, "
                    f"band={band}, steps={steps})")
        return out

    def _finish_chunk(self, launched, band, cigars, reject, bp_meta=None,
                      resident=False):
        """Span-wrapped :meth:`_finish_chunk_impl` — the fetch half of
        the dispatch-vs-fetch split (blocks on the device result)."""
        faults.check("align.fetch")
        with self._pinned(), obs.span("align.fetch",
                                      pairs=len(launched[0]), band=band):
            self._finish_chunk_impl(launched, band, cigars, reject,
                                    bp_meta, resident)

    def _finish_chunk_impl(self, launched, band, cigars, reject,
                           bp_meta=None, resident=False):
        chunk, pairs, n, m, out, (max_len, shape_key) = launched
        from ..parallel import fetch_global
        if bp_meta is not None:
            try:
                self._finish_chunk_bp(launched, band, cigars, reject,
                                      bp_meta, resident)
            except Exception as e:
                from .. import sanitize
                sanitize.reraise_if_sanitizer(e)
                launched = self._refetch_xla(launched, band, bp_meta, e)
                self._finish_chunk_bp(launched, band, cigars, reject,
                                      bp_meta, resident)
            return
        try:
            ops_packed, score, fi, fj = fetch_global(list(out))
        except Exception as e:
            from .. import sanitize
            sanitize.reraise_if_sanitizer(e)
            launched = self._refetch_xla(launched, band, bp_meta, e)
            chunk, pairs, n, m, out, _ = launched
            ops_packed, score, fi, fj = fetch_global(list(out))
        from .. import sanitize
        if sanitize.enabled():
            sanitize.check_aligner_canaries(
                score, fi, fj, big=1 << 28,
                context=f"aligner chunk (band={band})")
        # unpack 4 codes/byte -> [B, 2L] uint8
        shifts = np.array([0, 2, 4, 6], dtype=np.uint8)
        ops = ((ops_packed[:, :, None] >> shifts) & 3).reshape(
            ops_packed.shape[0], -1)

        obs_scores: List[int] = []
        obs_maxlens: List[int] = []
        for k, idx in enumerate(chunk):
            diff = abs(int(n[k]) - int(m[k]))
            # real path codes are < 3 (a band escape stalls the walk,
            # leaving (fi, fj) != 0); inactive-gap codes interleave on the
            # Pallas walk and only trail on the XLA walk — filtering
            # handles both
            path = ops[k][ops[k] < 3]
            clean = (len(path) > 0 and int(fi[k]) == 0 and int(fj[k]) == 0)
            # adaptive-ladder signal: any CLEAN walk's finite score —
            # accepted (the true distance) or gate-failed (the banded
            # distance, an upper bound, i.e. a conservative estimate) —
            # a run whose first chunks all escape still teaches the
            # estimator to stop seeding low
            if clean and int(score[k]) < (1 << 28):
                obs_scores.append(int(score[k]))
                obs_maxlens.append(max(int(n[k]), int(m[k])))
            # optimality certificate: an optimal path's diagonal wander is
            # bounded by its edit count; require it inside the half band.
            if int(score[k]) <= band // 2 - diff - 2 and clean:
                cigars[idx] = _ops_to_cigar(path)
                self.stats["device"] += 1
            else:
                reject.append(idx)
        if obs_scores:
            self._observe_divergence(obs_scores, obs_maxlens)

    def _refetch_xla(self, launched, band, bp_meta, exc):
        """A Pallas *runtime* fault surfaced at the async fetch (the
        compile-time probe cannot see DMA/VMEM faults on the real chip):
        note the shape and re-run the chunk on the XLA kernels
        (ADVICE r3). Raises if the failed chunk was already XLA."""
        chunk, pairs, n, m, out, (max_len, shape_key) = launched
        if shape_key is None:
            raise exc
        self._note_pallas_failure(shape_key, exc)
        return self._launch_chunk(pairs, chunk, max_len, band, bp_meta)

    def _finish_chunk_bp(self, launched, band, results, reject, bp_meta,
                         resident=False):
        """Breaking-points decode: convert the fetched per-boundary tables
        to columnar (k, 4) int32 row arrays for the WHOLE chunk in one
        vectorized pass (same accept/reject gate as the CIGAR path — the
        walk is complete and provably optimal inside the band, else
        escalate). The per-pair arrays are views into one flat buffer.

        With ``resident`` (round 19) the tables STAY on device: only the
        12 bytes/lane of accept-gate scalars (score, fi, fj) are
        fetched, and accepted lanes resolve to :class:`_DevBp` handles
        into one shared :class:`_DevChunkBp` — the polisher's resident
        assemble derives layer rows from them without a host decode."""
        chunk, pairs, n, m, out, _geom = launched
        from ..parallel import fetch_global
        w, metas = bp_meta
        if resident:
            score, fi, fj = fetch_global(list(out[2:]))
            bp_first = bp_last = None
        else:
            bp_first, bp_last, score, fi, fj = fetch_global(list(out))
        from .. import sanitize
        if sanitize.enabled():
            sanitize.check_aligner_canaries(
                score, fi, fj, big=1 << 28,
                context=f"aligner bp chunk (band={band})")
        BIG = 1 << 30
        C = len(chunk)
        n_h = np.asarray(n[:C], dtype=np.int64)
        m_h = np.asarray(m[:C], dtype=np.int64)
        diff = np.abs(n_h - m_h)
        clean = (np.asarray(fi[:C]) == 0) & (np.asarray(fj[:C]) == 0)
        score_h = np.asarray(score[:C], dtype=np.int64)
        accept = (score_h <= band // 2 - diff - 2) & clean
        # adaptive-ladder signal: every clean walk's finite score (see
        # the CIGAR path) — gate-failed ones are banded upper bounds,
        # so the estimate errs wide, never low
        obs = clean & (score_h < (1 << 28))
        if obs.any():
            self._observe_divergence(score_h[obs],
                                     np.maximum(n_h, m_h)[obs])
        tb = np.fromiter((metas[idx][0] for idx in chunk), np.int64, C)
        qo = np.fromiter((metas[idx][1] for idx in chunk), np.int64, C)
        te = tb + np.fromiter((len(pairs[idx][1]) for idx in chunk),
                              np.int64, C)
        n_reg = (te - 1) // w - tb // w
        if resident:
            devc = _DevChunkBp(out[0], out[1], w, _geom[0])
            # dataflow accounting: the gate scalars crossed the link,
            # the two [B, NW] int32 tables did not
            metrics.inc("dataflow.bytes_fetched", 12 * C)
            metrics.inc("dataflow.bytes_avoided", 8 * devc.B * devc.NW)
            for k, idx in enumerate(chunk):
                if accept[k]:
                    results[idx] = _DevBp(devc, k, int(tb[k]), int(qo[k]),
                                          int(n_reg[k]),
                                          len(pairs[idx][0]))
                    self.stats["device"] += 1
                else:
                    reject.append(idx)
            return
        fp = np.asarray(bp_first[:C], dtype=np.int64)
        lp = np.asarray(bp_last[:C], dtype=np.int64)
        col = np.arange(fp.shape[1], dtype=np.int64)
        valid = (col[None, :] <= n_reg[:, None]) & (fp < BIG) \
            & accept[:, None]
        rows = np.stack(
            [tb[:, None] + (fp >> 14), qo[:, None] + (fp & 0x3FFF),
             tb[:, None] + (lp >> 14) + 1, qo[:, None] + (lp & 0x3FFF) + 1],
            axis=-1)
        flat = rows[valid].astype(np.int32)
        parts = np.split(flat, np.cumsum(valid.sum(axis=1))[:-1])
        for k, idx in enumerate(chunk):
            if accept[k]:
                results[idx] = parts[k]
                self.stats["device"] += 1
            else:
                reject.append(idx)

    # ------------------------------------------------------------- warm-up

    def _warmup_shapes(self, est_len: int, est_pairs: int,
                       window_length: int):
        """The ``(max_len, band, steps, B, window_length)`` chunk shapes
        the align stream is expected to dispatch for pairs of roughly
        ``est_len`` bases — the ladder seed rung for a typical
        low-divergence overlap plus the bucket-band escape rung — ONE
        source of truth consumed by :meth:`warmup_async`, derived with
        the same geometry/cap rules the stream uses."""
        g = self._seed_geometry(est_len, est_len, 0.05, record=False)
        if g is None:
            return []
        bi, band = g
        max_len, bucket_band = self.buckets[bi]
        bands = [band]
        if bucket_band not in bands:
            bands.append(bucket_band)
        shapes = []
        for bd in bands:
            steps = _sweep_bound(2 * est_len, max_len)
            cap = self._chunk_cap(steps, bd)
            # the launcher's own batch-padding rule (plain pow2 here:
            # warm-up never runs under a mesh) — warmup-coverage keeps
            # this shared with _launch_chunk_impl
            B = self._pad_batch(min(cap, est_pairs))
            shapes.append((max_len, bd, steps, B, window_length))
        return shapes

    def warmup_async(self, est_len: int, est_pairs: int,
                     window_length: int = 500):
        """Background warm-up compilation of the expected align-chunk
        shapes (the aligner analog of ``TpuPoaConsensus.warmup_async``):
        the resident polishing service calls this at startup and per
        admitted job so job #1's alignment phase dispatches into a hot
        jit cache. Derives the ragged stream's chunk geometry
        (:meth:`_warmup_shapes`) and executes the full kernel chain —
        row build, wavefront DP, packed walk, breaking-points tables —
        once per shape on near-empty inputs (real lengths of 1, so the
        Pallas dynamic sweep bound makes the execution itself cheap;
        the compile is the product). Shape-deduped like the consensus
        warm-up, so repeat geometries are free; a wrong estimate wastes
        a background compile and nothing else. Returns the thread (for
        tests) or None when skipped (mesh runs, zero estimates, every
        shape already warmed)."""
        if self.mesh is not None or est_pairs <= 0 or est_len <= 0:
            return None
        shapes = [s for s in self._warmup_shapes(est_len, est_pairs,
                                                 window_length)
                  if s not in self._warmed_shapes]
        if not shapes:
            return None
        self._warmed_shapes.update(shapes)

        def _compile_one(max_len, band, steps, B, w):
            # the availability probes compile and run kernels, so they
            # belong on this thread too (same choice order as
            # _launch_chunk_impl: ACGT chunks take the 2-bit path);
            # probed directly rather than via _swar_choice so the warm
            # thread never writes the stats dict the main thread owns
            from .swar import swar_fits, swar_ok
            sw = self.use_swar and swar_fits(max_len) and swar_ok()
            n = jnp.ones((B,), jnp.int32)
            m = jnp.ones((B,), jnp.int32)
            if sw:
                from .swar import pack_bases_2bit
                blk = jnp.asarray(pack_bases_2bit(
                    np.zeros(B * max_len, np.uint8)))
                qrp, tp = _build_rows_packed2(blk, blk, n, m,
                                              max_len=max_len, band=band)
            else:
                z = jnp.zeros((B * max_len,), jnp.uint8)
                qrp, tp = _build_rows(z, z, n, m, max_len=max_len,
                                      band=band)
            base_key = (max_len, band, steps, B)
            use_pallas = self._use_pallas(base_key)
            if use_pallas and sw:
                from .pallas_nw import pallas_swar_ok
                sw = (sw and pallas_swar_ok()
                      and self._use_pallas(base_key + ("swar",)))
            out = align_chain(qrp, tp, n, m, max_len=max_len, band=band,
                              steps=steps, use_pallas=use_pallas,
                              use_swar=sw)
            if w:
                NW = max_len // max(w, 1) + 2
                bp = _breaking_points_kernel(
                    out[0], n, m, jnp.zeros((B,), jnp.int32),
                    jnp.ones((B,), jnp.int32), w=w, NW=NW)
                # resident derive root (round 19): warmed with the SAME
                # chunk geometry and the shared pow2 pool rule, so a
                # resident run's per-chunk layer-row derivation
                # dispatches into a hot cache (the one-shot finalize
                # sort is traced-shape and compiles on use); skipped
                # when the flag is off — a host-path run never
                # dispatches this root
                from .. import flags
                if flags.get_bool("RACON_TPU_RESIDENT"):
                    zi = jnp.zeros((B,), jnp.int32)
                    zb = jnp.zeros((B,), bool)
                    _derive_layer_rows(
                        bp[0], bp[1],
                        jnp.zeros((_pow2_pool(est_len * est_pairs),),
                                  jnp.uint16),
                        zb, zi, zi, zi, zi, zi, zi, zb,
                        jnp.ones((B,), jnp.int32), np.int32(1),
                        np.int32(10), w=w, NW=NW, Lq=max_len)
            jax.block_until_ready(out[1])

        def _run():
            with self._pinned():
                for shape in shapes:
                    try:
                        _compile_one(*shape)
                    except Exception as e:
                        from ..utils.logger import log_swallowed
                        log_swallowed(
                            f"aligner warm-up shape {shape} failed "
                            f"(run()'s own shapes still compile on "
                            f"first use)", e)

        import threading

        # fire-and-forget by design: a daemon thread killed at exit
        # loses nothing but a speculative compile (same contract as the
        # consensus warm-up thread)
        # graftlint: disable=thread-lifecycle (droppable best-effort warm-up; daemon dies harmlessly at exit)
        th = threading.Thread(target=_run, daemon=True,
                              name="racon-align-warmup")
        th.start()
        return th


class _AlignStream:
    """Ragged streaming align session (round 17) — the aligner analog of
    ``poa._ConsensusStream``.

    Pairs arrive through :meth:`feed` in any number of slices; each is
    seeded a ``(bucket, band)`` geometry class (the band ladder's rung
    when an overlap-error estimate admits one) and classes greedy-fill
    device chunks against the engine's fixed direction-matrix arena
    budget **by each pair's actual sweep cost**: within a class, pairs
    sort longest-first and every chunk's pair cap is re-derived from its
    OWN head's sweep bound — short tail chunks both shrink their
    compiled step count and grow their batch, instead of every chunk
    paying one cap sized for the bucket's longest pair (the cudabatch
    batch-fill shape, ``cudabatch.cpp:54-62``; ``reduce_capacity``
    halves the arena under OOM backpressure).

    Full chunks dispatch ASYNCHRONOUSLY the moment they close: host
    packing of the next slice overlaps device compute of the previous
    chunks, and fetches happen only when the in-flight byte budget
    forces one or at :meth:`finish` — the double-buffered dispatch that
    keeps the per-chunk tunnel round-trip (which bounds real runs, see
    the module constants) off the critical path. Band escapes re-enter
    the pending classes at their escalated rung and re-dispatch
    *batched*; geometry strictly escalates, so the drain loop
    terminates. Accepted alignments are byte-identical at every rung
    (the ``score <= band/2 - diff - 2`` accept gate is an optimality
    certificate: any cell whose value can influence a traceback
    decision is provably uninflated by the banding), and the terminal
    geometry sequence is the fixed path's, so the host-fallback reject
    set matches too — the {bucketed, ragged} x {fixed-band, ladder}
    byte-identity contract ``tests/test_align_stream.py`` locks.

    Resolved pairs release their span bytes immediately; the resident
    set is bounded by the in-flight pipeline plus one partial chunk per
    geometry class (``MAX_CHUNK_PAIRS`` bounds each), preserving the
    polisher's O(slice) transient-copy contract."""

    def __init__(self, eng: "TpuAligner", window_length=None,
                 progress=None, total_hint: int = 0,
                 resident: bool = False):
        self.eng = eng
        self.w = window_length             # None -> CIGAR mode
        # resident mode (round 19): accepted chunks keep their bp
        # tables on device and resolve to _DevBp handles; host-fallback
        # rejects are the dataflow's fallback-pair count
        self.resident = bool(resident) and window_length is not None
        self.progress = progress
        self.total_hint = total_hint
        self.results: List = []            # per fed pair, feed order
        self.pairs: dict = {}              # slot -> (q, t), until resolved
        self.metas: dict = {}              # slot -> (t_begin, q_off)
        self.buffer: List = []             # (slot, err) awaiting a seed
        self.pending: dict = {}            # (bucket, band) -> [slot]
        self.reject: List[int] = []        # host-fallback slots
        self.inflight: List[dict] = []
        self.inflight_bytes = 0
        self.inflight_pairs = 0
        self.done_pairs = 0
        self._done = False
        self._est_warmed = False  # first-chunk eager fetch fired
        self._empty_bp = np.zeros((0, 4), dtype=np.int32)

    def _bp_meta(self):
        return None if self.w is None else (self.w, self.metas)

    def _tick(self) -> None:
        if self.progress is not None:
            self.progress(self.done_pairs,
                          max(self.total_hint, len(self.results)))

    # ------------------------------------------------------------- intake

    def feed(self, pairs, metas=None, errors=None) -> None:
        """Add a pair slice; packs and dispatches every chunk that
        fills. Returns without blocking unless the in-flight byte
        budget forces a (pipelined) fetch."""
        assert not self._done, "align stream already finished"
        for k, (q, t) in enumerate(pairs):
            slot = len(self.results)
            if len(q) == 0 or len(t) == 0:
                # resolved inline: no span, no meta retained
                if self.w is None:
                    self.results.append(f"{len(t)}D" if len(t) else
                                        (f"{len(q)}I" if len(q) else ""))
                else:
                    self.results.append(self._empty_bp)
                self.done_pairs += 1
                continue
            if self.w is not None:
                self.metas[slot] = metas[k]
            self.results.append("" if self.w is None else self._empty_bp)
            self.pairs[slot] = (q, t)
            # seeds are assigned at FLUSH time, not here: with the
            # ladder on, pairs buffered behind the cold-start probe are
            # seeded from OBSERVED divergence instead of the blind
            # span-asymmetry proxy
            self.buffer.append((slot,
                                None if errors is None else errors[k]))
        self._flush(final=False)
        self._tick()

    # ----------------------------------------------------------- dispatch

    def _classify(self, buffered) -> None:
        """Seed buffered pairs into (bucket, band) geometry classes
        with the estimator's CURRENT knowledge."""
        eng = self.eng
        for slot, err in buffered:
            q, t = self.pairs[slot]
            g = eng._seed_geometry(len(q), len(t), err)
            if g is None:
                eng.stats["fallback_length"] += 1
                self.reject.append(slot)
            else:
                self.pending.setdefault(g, []).append(slot)

    def _flush(self, final: bool) -> None:
        eng = self.eng
        # cold-start ladder probe: seed + force-dispatch + fetch a
        # small leading batch first (the eager fetch in _launch), so
        # every LATER seed uses observed divergence — without it, a
        # substitution-heavy run whose span-asymmetry estimates read
        # near zero would seed every chunk low and escape them all
        if (eng.use_ladder and self.buffer and not self._est_warmed
                and eng._adaptive_divergence() is None):
            if not final and len(self.buffer) < ALIGN_PROBE_PAIRS:
                return                     # wait for a probe's worth
            probe = self.buffer[:ALIGN_PROBE_PAIRS]
            self.buffer = self.buffer[ALIGN_PROBE_PAIRS:]
            self._classify(probe)
            self._drain(final=True)        # partial probe chunks too
        if self.buffer:
            self._classify(self.buffer)
            self.buffer = []
        self._drain(final)

    def _drain(self, final: bool) -> None:
        eng = self.eng
        for cls in sorted(self.pending):
            # drain a DETACHED list: _launch below may force a fetch
            # (_finish_oldest) whose escapees escalate into this very
            # class — they must land in a fresh pending entry, not be
            # appended behind the one-time longest-first sort (the head
            # invariant sizes the chunk cap and the in-flight bytes)
            slots = self.pending.pop(cls)
            bi, band = cls
            max_len = eng.buckets[bi][0]
            # longest first: a chunk's compiled sweep bound tracks its
            # OWN head, so similar-length pairs share chunks and short
            # tail chunks shrink their steps AND grow their batch
            slots.sort(key=lambda s: -(len(self.pairs[s][0])
                                       + len(self.pairs[s][1])))
            while slots:
                q0, t0 = self.pairs[slots[0]]
                steps = _sweep_bound(len(q0) + len(t0), max_len)
                cap = eng._chunk_cap(steps, band)
                if not final and len(slots) < cap:
                    break                  # wait for more pairs
                chunk = slots[:cap]
                del slots[:cap]
                self._launch(cls, chunk, max_len, band)
            if slots:
                # re-merge the unfilled remainder with any escapees
                # that arrived mid-drain (order is irrelevant — the
                # next drain re-sorts)
                self.pending.setdefault(cls, []).extend(slots)

    def _launch(self, cls, chunk, max_len: int, band: int) -> None:
        eng = self.eng
        launched = eng._launch_chunk(self.pairs, chunk, max_len, band,
                                     self._bp_meta())
        q0, t0 = self.pairs[chunk[0]]     # head = chunk's longest pair
        steps = _sweep_bound(len(q0) + len(t0), max_len)
        entry = {"cls": cls, "chunk": chunk, "launched": launched,
                 "bytes": eng._pad_batch(len(chunk)) * steps * (band // 8)}
        self.inflight.append(entry)
        self.inflight_bytes += entry["bytes"]
        self.inflight_pairs += len(chunk)
        # cold-estimator eager fetch: with the ladder on, the FIRST
        # chunk fetches immediately so the adaptive divergence
        # estimator learns real scores before the pipeline fills —
        # otherwise a substitution-heavy run (whose span-asymmetry
        # estimates read near zero) seeds EVERY chunk low and escapes
        # them all; one pipeline bubble at run start buys the whole
        # run's seeds
        if (eng.use_ladder and not self._est_warmed
                and eng._adaptive_divergence() is None):
            self._finish_oldest()
        self._est_warmed = True
        # the pair bound keeps unresolved host span copies O(slice)
        # even when the chunks are byte-cheap (short pairs at narrow
        # rungs) — each unresolved pair pins its q/t byte copies
        while (len(self.inflight) > max(eng.num_batches, 1)
               and (self.inflight_bytes > eng.dirs_budget_cap
                    or self.inflight_pairs > MAX_INFLIGHT_PAIRS)):
            self._finish_oldest()

    def _finish_oldest(self) -> None:
        eng = self.eng
        la = self.inflight.pop(0)
        self.inflight_bytes -= la["bytes"]
        self.inflight_pairs -= len(la["chunk"])
        esc: List[int] = []
        eng._finish_chunk(la["launched"], la["cls"][1], self.results,
                          esc, self._bp_meta(), self.resident)
        esc_set = set(esc)
        for slot in la["chunk"]:
            if slot not in esc_set:
                # resolved: release the span bytes AND the meta tuple —
                # a whole-run session must not retain O(total) of either
                self.pairs.pop(slot, None)
                self.metas.pop(slot, None)
                self.done_pairs += 1
        bi, band = la["cls"]
        for slot in esc:
            q, t = self.pairs[slot]
            # graftlint: disable=warmup-coverage (escalation rungs are data-dependent and rare by design; the terminal rung — the bucket band — IS warmed as the escape shape)
            ng = eng._next_geometry(len(q), len(t), bi, band)
            if ng is None:
                eng.stats["fallback_band"] += 1
                metrics.inc("aligner.fallback_band")
                self.reject.append(slot)
            else:
                eng.stats["band_escalated"] += 1
                metrics.inc("aligner.band_escalated")
                self.pending.setdefault(ng, []).append(slot)
        self._tick()

    # -------------------------------------------------------------- drain

    def finish(self) -> List:
        """Dispatch the partial chunks, drain the pipeline (escapees
        re-dispatch batched at their wider rungs until none remain),
        run the host fallback; results for every fed pair in feed
        order."""
        assert not self._done, "align stream already finished"
        self._done = True
        eng = self.eng
        self._flush(final=True)
        while self.inflight or self.pending:
            while self.inflight:
                self._finish_oldest()
            self._flush(final=True)
        self.done_pairs += len(self.reject)
        if self.resident and self.reject:
            # band/length escapees decode on host — the resident
            # dataflow's (small) fallback set
            metrics.inc("dataflow.fallback_pairs", len(self.reject))
        eng._resolve_rejects(self.pairs, self.reject, self.results,
                             self._bp_meta())
        for slot in self.reject:
            self.pairs.pop(slot, None)
            self.metas.pop(slot, None)
        if self.progress is not None:
            total = max(self.total_hint, len(self.results))
            self.progress(total, total)
        return self.results
