"""Jit'd minimizer seeding — stage one of the first-party overlapper.

The reference pipeline demands precomputed overlaps from an external
mapper (minimap2), so PAF/MHAP/SAM parsing is its entire ingest story.
``--overlaps auto`` replaces that with an in-process minimizer-seed →
chain overlapper (ROADMAP item 5); this module is the seeding half:

- sequences pack host-side into 2-bit code arrays (A/C/G/T → 0..3,
  anything else → 4, which invalidates every k-mer covering it) and
  bucket by pow2 length into fixed-shape ``[B, L]`` batches, one compile
  per bucket geometry — the same arena discipline as ``nw._AlignStream``;
- one jit'd pass per batch builds forward and reverse-complement k-mer
  codes (k static shifted slices), takes the strand-canonical minimum
  (``fwd == rc`` palindrome ties are skipped, like minimap2), scrambles
  it through an invertible 32-bit finalizer so rank ties don't follow
  base composition, and selects each w-window's leftmost minimum with a
  strict-< iterative sweep (deterministic: no argmin tie ambiguity);
- selected positions scatter into a per-position mask; the host (or,
  under ``RACON_TPU_RESIDENT=1``, a device compaction kernel that ships
  only the selected entries over the link) flattens the batch into one
  flat ``(hash, seq_id, pos, strand)`` table for the matcher
  (:mod:`racon_tpu.ops.chain`).

Long sequences (contig targets) are sliced into bounded window-start
spans so the arena never scales with contig length; slices overlap by
``k + w - 2`` bases and each window is owned by exactly one slice, so
the union equals the whole-sequence scan (the numpy oracle
:func:`minimizers_np` asserts this in tests/test_overlapper.py).
"""

from __future__ import annotations

import functools
import hashlib
import threading
from collections import OrderedDict
from typing import Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..obs import metrics
from ..parallel import fetch_global

# defaults mirrored by the RACON_TPU_OVERLAP_K/W flags (k=15/w=5: ONT
# read-vs-draft seeding; ~1/3 of positions carry a minimizer)
DEFAULT_K = 15
DEFAULT_W = 5
# minimizer-arena budget in cells: every per-position working array
# (codes, fwd/rc kmers, hashes, mask) is B*L, so the batch cap derives
# from this one constant
SEED_ARENA_CELLS = 1 << 22
# window starts per kernel launch for one long sequence: contigs slice
# into spans this size (plus k+w-2 overlap bases) so the arena never
# scales with contig length
SEED_SLICE = 1 << 17
# flat-table sentinel: invalid k-mer slots (ambiguous base in window,
# fwd==rc palindrome tie, past the sequence end) never win a window
_HASH_MAX = 0xFFFFFFFF

_BASE_LUT = np.full(256, 4, np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _BASE_LUT[_b] = _i
for _i, _b in enumerate(b"acgt"):
    _BASE_LUT[_b] = _i


# -------------------------------------------------------------- geometry

def _len_bucket(n: int) -> int:
    """pow2 length bucket for one code chunk (floor 64 so every bucket
    admits a full k+w window) — the ONE quantizer both the dispatch
    path and :func:`_warmup_shapes` derive chunk length from."""
    b = 64
    while b < n:
        b *= 2
    return b


def _seed_batch(L: int, n: int) -> int:
    """pow2 batch cap for one minimizer launch against the fixed
    :data:`SEED_ARENA_CELLS` arena (companion quantizer of
    :func:`_len_bucket`; shared with warm-up)."""
    want = min(max(1, n), max(1, SEED_ARENA_CELLS // max(1, L)))
    b = 1
    while b < want:
        b *= 2
    return b


# --------------------------------------------------------------- kernels

def _mix32(h):
    """Invertible 32-bit integer finalizer (murmur3 fmix32): minimizer
    rank stops following base composition, and distinct canonical codes
    can never collide (bijective on the uint32 domain)."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


@functools.partial(jax.jit, static_argnames=("k", "w", "L"))
def _minimizer_kernel(codes, lens, nwin, *, k: int, w: int, L: int):
    """One minimizer pass over a ``[B, L]`` code batch.

    ``lens`` bounds each row's real bases, ``nwin`` its owned window
    starts (slice discipline: overlap-region windows belong to the next
    slice). Returns ``(hash [B, P] uint32, strand [B, P] bool,
    selected [B, P] bool)`` with ``P = L - k + 1``."""
    P = L - k + 1
    B = codes.shape[0]
    base = codes.astype(jnp.uint32)
    f = jnp.zeros((B, P), jnp.uint32)
    r = jnp.zeros((B, P), jnp.uint32)
    bad = jnp.zeros((B, P), jnp.bool_)
    for j in range(k):
        c = base[:, j:j + P]
        bad = bad | (c > jnp.uint32(3))
        cc = c & jnp.uint32(3)
        f = (f << jnp.uint32(2)) | cc
        r = (r >> jnp.uint32(2)) | ((jnp.uint32(3) - cc)
                                    << jnp.uint32(2 * (k - 1)))
    pos = jnp.arange(P, dtype=jnp.int32)
    in_seq = pos[None, :] + k <= lens[:, None]
    strand = r < f  # canonical k-mer is the reverse complement
    h = _mix32(jnp.minimum(f, r))
    h = jnp.where(bad | (f == r) | ~in_seq, jnp.uint32(_HASH_MAX), h)

    # leftmost strict-< windowed minimum over w consecutive k-mer slots
    W = P - w + 1
    minv = h[:, 0:W]
    minp = jnp.zeros((B, W), jnp.int32)
    for j in range(1, w):
        cand = h[:, j:j + W]
        take = cand < minv
        minv = jnp.where(take, cand, minv)
        minp = jnp.where(take, jnp.int32(j), minp)
    minp = minp + pos[None, :W]
    wvalid = (pos[None, :W] < nwin[:, None]) \
        & (pos[None, :W] + (w + k - 1) <= lens[:, None]) \
        & (minv != jnp.uint32(_HASH_MAX))
    # scatter each window's pick; invalid windows park on the P slot
    tgt = jnp.where(wvalid, minp, jnp.int32(P))
    sel = jnp.zeros((B, P + 1), jnp.bool_)
    sel = sel.at[jnp.arange(B, dtype=jnp.int32)[:, None], tgt].set(True)
    return h, strand, sel[:, :P]


@jax.jit
def _compact_kernel(h, strand, sel):
    """Device-side table compaction (the resident path): selected
    entries pack to the front in row-major order — identical to the
    host ``np.nonzero`` walk — so only ``n_selected`` elements ever
    cross the host link instead of the full ``[B, P]`` arenas."""
    B, P = h.shape
    flat = sel.reshape(-1)
    rank = jnp.cumsum(flat.astype(jnp.int32))
    total = rank[-1]
    idx = jnp.where(flat, rank - 1, jnp.int32(B * P))
    lin = jnp.arange(B * P, dtype=jnp.int32)
    out_h = jnp.zeros((B * P + 1,), jnp.uint32).at[idx].set(h.reshape(-1))
    out_row = jnp.zeros((B * P + 1,), jnp.int32).at[idx].set(lin // P)
    out_pos = jnp.zeros((B * P + 1,), jnp.int32).at[idx].set(lin % P)
    out_s = jnp.zeros((B * P + 1,), jnp.bool_).at[idx].set(
        strand.reshape(-1))
    return out_h, out_row, out_pos, out_s, total


# ------------------------------------------------------------ host driver

def _iter_chunks(seqs: List[bytes], k: int, w: int
                 ) -> Iterator[Tuple[int, int, bytes, int]]:
    """``(seq_id, window_start_offset, byte_slice, n_windows)`` chunks:
    whole short sequences, bounded overlapping slices of long ones."""
    for sid, s in enumerate(seqs):
        L = len(s)
        if L < k + w - 1:
            continue  # no complete window fits
        n_total = L - (k + w - 1) + 1
        for s0 in range(0, n_total, SEED_SLICE):
            n_here = min(SEED_SLICE, n_total - s0)
            end = min(L, s0 + n_here + (k + w - 2))
            yield sid, s0, s[s0:end], n_here


# target seed-table cache (RACON_TPU_OVERLAP_CACHE): the target set is
# identical across every shard of one run and across serve jobs naming
# the same draft, so the table is keyed by a content fingerprint +
# (k, w) and rebuilt only when the inputs actually change. Entries are
# treated as immutable by every consumer (the matcher copies via fancy
# indexing / padding), so sharing the arrays is safe.
_TABLE_CACHE: "OrderedDict[Tuple[bytes, int, int], tuple]" = OrderedDict()
_TABLE_CACHE_CAP = 4
_TABLE_CACHE_LOCK = threading.Lock()


def _fingerprint(seqs: List[bytes], k: int, w: int
                 ) -> Tuple[bytes, int, int]:
    """Content fingerprint of a sequence set: blake2b over the count,
    each length, and each byte string — any byte change changes the
    key, and (k, w) ride alongside so parameter sweeps never alias."""
    hsh = hashlib.blake2b(digest_size=16)
    hsh.update(len(seqs).to_bytes(8, "little"))
    for s in seqs:
        hsh.update(len(s).to_bytes(8, "little"))
        hsh.update(s)
    return hsh.digest(), k, w


def clear_table_cache() -> None:
    """Drop every cached target table (tests / memory pressure)."""
    with _TABLE_CACHE_LOCK:
        _TABLE_CACHE.clear()


def build_seed_table(seqs: List[bytes], *, k: int = DEFAULT_K,
                     w: int = DEFAULT_W, resident: bool = False,
                     cache: bool = False
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
    """The flat minimizer table of a sequence set: parallel numpy arrays
    ``(hash uint32, seq_id int32, pos int32, strand bool)`` in
    deterministic (bucket-grouped, sequence-order) row order.

    ``resident=True`` compacts on device and fetches only the selected
    entries (counted into the ``dataflow.*`` bytes ledger); the host
    path fetches the full masks and compacts with numpy. Both produce
    identical tables (tests assert the parity).

    ``cache=True`` (the target side of the overlapper under
    ``RACON_TPU_OVERLAP_CACHE``) consults the fingerprint-keyed table
    cache first: a hit skips packing, kernels, and fetches entirely —
    counted in ``overlap.cache_hits`` and credited to
    ``dataflow.bytes_avoided`` at the table's own wire size."""
    ckey = None
    if cache:
        ckey = _fingerprint(seqs, k, w)
        with _TABLE_CACHE_LOCK:
            hit = _TABLE_CACHE.get(ckey)
            if hit is not None:
                _TABLE_CACHE.move_to_end(ckey)
        if hit is not None:
            metrics.inc("overlap.cache_hits")
            metrics.inc("overlap.minimizers", int(hit[0].size))
            # the fetch (resident wire size) + kernels this hit skipped
            metrics.inc("dataflow.bytes_avoided", int(hit[0].size) * 10)
            return hit
        metrics.inc("overlap.cache_misses")
    by_bucket: dict = {}
    for chunk in _iter_chunks(seqs, k, w):
        by_bucket.setdefault(_len_bucket(len(chunk[2])), []).append(chunk)

    hs: List[np.ndarray] = []
    ids: List[np.ndarray] = []
    ps: List[np.ndarray] = []
    ss: List[np.ndarray] = []
    for L in sorted(by_bucket):
        chunks = by_bucket[L]
        B_cap = _seed_batch(L, len(chunks))
        for begin in range(0, len(chunks), B_cap):
            part = chunks[begin:begin + B_cap]
            B = _seed_batch(L, len(part))
            codes = np.full((B, L), 4, np.uint8)
            lens = np.zeros(B, np.int32)
            nwin = np.zeros(B, np.int32)
            for i, (_, _, blob, n_here) in enumerate(part):
                arr = _BASE_LUT[np.frombuffer(blob, np.uint8)]
                codes[i, :arr.size] = arr
                lens[i] = arr.size
                nwin[i] = n_here
            with obs.span("overlap.seed.dispatch", rows=len(part)):
                # graftlint: disable=jit-shape-hazard (k/w are run-constant flag values — one compile per run; L is the pow2 bucket)
                h, strand, sel = _minimizer_kernel(codes, lens, nwin,
                                                   k=k, w=w, L=L)
                if resident:
                    h, row, pcol, strand, total = _compact_kernel(
                        h, strand, sel)
            if resident:
                with obs.span("overlap.seed.fetch", rows=len(part)):
                    n_host = fetch_global([total])[0]
                    n = int(n_host)
                    h_np, rows, cols, s_np = fetch_global(
                        [h[:n], row[:n], pcol[:n], strand[:n]])
                fetched = n * 10  # 4 + 4 + 1 + 1 bytes per entry
                metrics.inc("dataflow.bytes_fetched", fetched)
                metrics.inc("dataflow.bytes_avoided",
                            max(0, B * (L - k + 1) * 6 - fetched))
            else:
                with obs.span("overlap.seed.fetch", rows=len(part)):
                    h_full, sel_np, s_full = fetch_global(
                        [h, sel, strand])
                rows, cols = np.nonzero(sel_np)
                h_np = h_full[rows, cols]
                s_np = s_full[rows, cols]
            keep = h_np != np.uint32(_HASH_MAX)
            rows, cols = rows[keep], cols[keep]
            chunk_ids = np.fromiter((c[0] for c in part), np.int32,
                                    len(part))
            chunk_off = np.fromiter((c[1] for c in part), np.int32,
                                    len(part))
            hs.append(h_np[keep])
            ids.append(chunk_ids[rows])
            ps.append(chunk_off[rows] + cols.astype(np.int32))
            ss.append(np.asarray(s_np)[keep])
            metrics.inc("overlap.seed_lanes_total", B * L)
            metrics.inc("overlap.seed_lanes_occupied", int(lens.sum()))
    if not hs:
        z = np.zeros(0, np.int32)
        table = (np.zeros(0, np.uint32), z, z, np.zeros(0, bool))
        if ckey is not None:
            _table_cache_put(ckey, table)
        return table
    h_all = np.concatenate(hs)
    id_all = np.concatenate(ids)
    p_all = np.concatenate(ps)
    s_all = np.concatenate(ss)
    # canonical (seq_id, pos) order, deduping the one legitimate repeat
    # source: a position selected by windows on both sides of a slice
    # boundary emits once per slice
    order = np.lexsort((p_all, id_all))
    h_all, id_all, p_all, s_all = (h_all[order], id_all[order],
                                   p_all[order], s_all[order])
    uniq = np.ones(h_all.size, bool)
    uniq[1:] = (id_all[1:] != id_all[:-1]) | (p_all[1:] != p_all[:-1])
    table = (h_all[uniq], id_all[uniq], p_all[uniq], s_all[uniq])
    metrics.inc("overlap.minimizers", int(table[0].size))
    if ckey is not None:
        _table_cache_put(ckey, table)
    return table


def _table_cache_put(ckey, table) -> None:
    with _TABLE_CACHE_LOCK:
        _TABLE_CACHE[ckey] = table
        _TABLE_CACHE.move_to_end(ckey)
        while len(_TABLE_CACHE) > _TABLE_CACHE_CAP:
            _TABLE_CACHE.popitem(last=False)


# -------------------------------------------------------------- warm-up

_warmed_shapes: set = set()


def _warmup_shapes(est_len: int, est_seqs: int) -> List[Tuple[int, int]]:
    """The ``(L, B)`` batch geometries a run over ``est_seqs`` sequences
    of roughly ``est_len`` bases dispatches — derived with the same
    :func:`_len_bucket` / :func:`_seed_batch` quantizers the driver
    uses (ONE source of truth, consumed by :func:`warmup_async`)."""
    if est_len <= 0 or est_seqs <= 0:
        return []
    chunk_len = min(est_len, SEED_SLICE + DEFAULT_K + DEFAULT_W - 2)
    L = _len_bucket(chunk_len)
    return [(L, _seed_batch(L, est_seqs))]


def warmup_async(est_len: int, est_seqs: int,
                 k: int = DEFAULT_K, w: int = DEFAULT_W):
    """Background warm-up compilation of the expected minimizer batch
    shapes (the overlapper analog of ``TpuAligner.warmup_async``):
    executes the kernel once per shape on near-empty inputs while the
    host packs real code arrays. Shape-deduped; returns the thread
    (for tests) or None when skipped (zero estimates, every shape
    already warmed)."""
    shapes = [(L, B, k, w) for L, B in _warmup_shapes(est_len, est_seqs)
              if (L, B, k, w) not in _warmed_shapes]
    if not shapes:
        return None
    _warmed_shapes.update(shapes)

    def _one(L, B, kk, ww):
        codes = np.full((B, L), 4, np.uint8)
        ones = np.ones(B, np.int32)
        # graftlint: disable=jit-shape-hazard (k/w are run-constant flag values — one compile per run; L is the pow2 bucket)
        out = _minimizer_kernel(codes, ones, ones, k=kk, w=ww, L=L)
        jax.block_until_ready(out[0])

    def _run():
        for L, B, kk, ww in shapes:
            try:
                _one(L, B, kk, ww)
            except Exception as e:
                from ..utils.logger import log_swallowed
                log_swallowed(
                    f"minimizer warm-up shape {(L, B)} failed (the "
                    f"run's own shapes still compile on first use)", e)

    import threading

    # graftlint: disable=thread-lifecycle (droppable best-effort warm-up; daemon dies harmlessly at exit)
    th = threading.Thread(target=_run, daemon=True,
                          name="racon-seed-warmup")
    th.start()
    return th


# --------------------------------------------------------- numpy oracle

def minimizers_np(seq: bytes, k: int = DEFAULT_K, w: int = DEFAULT_W
                  ) -> List[Tuple[int, int, int]]:
    """Pure-numpy single-sequence oracle: sorted-by-position
    ``(hash, pos, strand)`` triples with exactly the kernel's
    semantics (canonical min, fmix32, palindrome/ambiguity skips,
    leftmost strict-< window minimum)."""
    codes = _BASE_LUT[np.frombuffer(seq, np.uint8)]
    L = codes.size
    if L < k + w - 1:
        return []
    P = L - k + 1
    f = np.zeros(P, np.uint32)
    r = np.zeros(P, np.uint32)
    bad = np.zeros(P, bool)
    for j in range(k):
        c = codes[j:j + P].astype(np.uint32)
        bad |= c > 3
        cc = c & np.uint32(3)
        f = (f << np.uint32(2)) | cc
        r = (r >> np.uint32(2)) | ((np.uint32(3) - cc)
                                   << np.uint32(2 * (k - 1)))
    strand = r < f
    h = np.minimum(f, r)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    h = np.where(bad | (f == r), np.uint32(_HASH_MAX), h)
    sel = np.zeros(P, bool)
    for s in range(P - w + 1):
        win = h[s:s + w]
        m = int(win.min())
        if m != _HASH_MAX:
            sel[s + int(np.argmax(win == m))] = True
    return [(int(h[p]), int(p), int(strand[p]))
            for p in np.flatnonzero(sel)]
