"""Pallas TPU kernels for the banded wavefront NW forward pass + walk.

Why Pallas here (SURVEY §7's "centerpiece" kernel): the XLA ``lax.scan``
formulations round-trip their carries through HBM every wavefront step —
at ``band/2`` lanes per pair that is ~8 MB of carry traffic per step for a
2048-pair batch, making the kernel HBM-bound at ~45 µs/step. These kernels
keep the two live wavefronts **in VMEM/registers for the whole sweep** and
stream only the 2-bit direction planes to HBM (the data actually needed
later), which is the TPU analog of cudaaligner's shared-memory DP tiles
(``src/cuda/cudaaligner.cpp:39-44`` batch contract; one fused kernel per
batch like ``src/cuda/cudabatch.cpp:188-199``).

Layout contract (shared bit-for-bit with the XLA kernels in ``ops.nw`` so
either backend's output feeds either consumer):

- direction matrix: per wavefront ``a`` a row of ``RB = band/8`` bytes,
  planar 2-bit packing — lane ``u`` lives in byte ``u % RB`` at bit shift
  ``2 * (u // RB)`` (static contiguous slices in both producers);
- walk op codes: uint8, 0=M, 1=I, 2=D, >=3 inactive. The Pallas walk is
  *wavefront-synchronized*: one step per global anti-diagonal ``a`` from
  ``S`` down to 1, each pair acting only when its position sits on ``a``
  (an M step skips one diagonal, leaving an inactive-gap code 3). Codes
  stay in backward-walk order, so consumers that mask on ``op < 3``
  (``_vote_from_ops``, CIGAR RLE after filtering) accept both backends'
  outputs unchanged.

Mosaic's vector unit only addresses 128-lane-aligned windows, so every
dynamic access goes through one of two shapes:

- *aligned-load + dynamic roll* for the per-step character windows (load
  ``U + 128`` lanes at the enclosing 128-multiple, then ``pltpu.roll`` by
  the traced remainder — dynamic shifts are supported);
- *rolling 128-lane buffers* for sub-128 stores (direction rows and walk
  ops accumulate in a register buffer shifted ``RB``/1 lanes per step and
  flush to the output ref every 128 lanes at a ``pl.multiple_of`` offset).

The walk streams direction rows through a double-buffered VMEM window in
*descending-a* chunks (the only order the walk needs), so the matrix never
materializes in VMEM and arbitrarily long buckets fit.

Availability is probed once (``pallas_ok()``) by running both kernels on
a random small batch and comparing bit-for-bit against the XLA reference
kernels; on hosts whose backend cannot lower Mosaic (the CPU test mesh)
or where the comparison fails, callers fall back to the XLA kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
import jax.experimental.pallas.tpu as pltpu

_BIG = 1 << 28
# extra tail lanes so aligned-window loads never run off the char arrays
_LOAD_PAD = 256
# Per-block dynamic sweep bounds (traced loop trip counts): blocks stop
# at their longest pair's sweep. Off-switch for A/B measurement — traced
# trip counts can inhibit Mosaic's static loop optimizations.
from .. import flags as _flags
DYNAMIC_BOUND = _flags.get_bool("RACON_TPU_DYNBOUND")
# pair-block (sublane) caps: the TPU grid is sequential, so bigger blocks
# amortize per-step loop/DMA overhead across more pairs; 64 measured best
# on v5e for both kernels (32 leaves ~30% on the table, 128 regresses the
# walk); module constants so the profiling harness can sweep them
FWD_P_CAP = 64
WALK_P_CAP = 64
# VMEM budget for the walk kernels' double-buffered chunk window — long
# aligner buckets shrink the pair-block (P) instead of overflowing VMEM
# (the fwd kernel streams its direction rows to HBM by DMA, so it has no
# comparable per-block buffer)
_WALK_BUF_BYTES = 4 * 1024 * 1024


def _cap_block(B: int, per_pair_bytes: int, budget: int) -> int:
    # Mosaic block sublane counts below 8 fail to lower ("Sublane
    # broadcast" errors at B < 4, tiling pessimization below 8), so P
    # never drops below 8 — wrappers pad tiny batches up to 8 rows first.
    # B is always a power of two >= 8 here (wrappers pad), so the halving
    # loop keeps P a power-of-two divisor of B; assert rather than
    # silently truncating grid rows if a future caller breaks that.
    assert B >= 8 and (B & (B - 1)) == 0, f"batch {B} not a power of two"
    P = min(WALK_P_CAP, B)
    while P > 8 and P * per_pair_bytes > budget:
        P //= 2
    return P


def _pad_rows(arrs, B: int, fills):
    """Pad each (B, ...) array to 8 rows (the minimum Mosaic-legal pair
    block); padded rows get ``fill`` and callers slice outputs back."""
    pad = 8 - B
    return [jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                    constant_values=f) for a, f in zip(arrs, fills)]


def _rup(x: int, k: int) -> int:
    return -(-x // k) * k


def _load_window(ref, off, width: int, U: int):
    """Load ``U`` lanes at traced offset ``off`` (clamped like XLA's
    ``dynamic_slice_in_dim``) via an aligned wide load + dynamic roll
    (Mosaic's vector unit only addresses 128-lane-aligned windows, and
    ``tpu.dynamic_rotate`` wants int32 at 128-multiple widths)."""
    offc = jnp.clip(off, 0, width - U)
    base = pl.multiple_of((offc // 128) * 128, 128)
    W2 = _rup(U, 128) + 128
    win = ref[:, pl.ds(base, W2)].astype(jnp.int32)
    r = offc - base
    return pltpu.roll(win, shift=(W2 - r) % W2, axis=1)[:, :U]


def _fwd_kernel(qrp_ref, tp_ref, n_ref, m_ref, dirs_ref, score_ref,
                stage, dsems, *, max_len: int, band: int, P: int,
                width: int, steps: int, PER: int, out_quant: int):
    W = band
    c = W // 2
    L = max_len
    U = W // 2
    RB = U // 4
    S = steps
    # flush F wavefront rows per 128-aligned stage write (F*RB =
    # lcm(RB, 128)); every PER stage writes, DMA the staged rows to HBM —
    # the direction matrix streams out instead of occupying a VMEM output
    # block, so arbitrarily long buckets fit
    FL = RB
    while FL % 128:
        FL += RB
    F = FL // RB
    FPL = FL * PER
    blk = pl.program_id(0)
    nn = n_ref[:, :]  # (P, 1) i32
    mm = m_ref[:, :]
    us = lax.broadcasted_iota(jnp.int32, (P, U), 1)

    def stage_dma(slot, fidx):
        # DMA stage slot -> dirs rows ending at flush index fidx
        base = (fidx + 1) * FL - FPL
        return pltpu.make_async_copy(
            stage.at[slot],
            dirs_ref.at[pl.ds(blk * P, P),
                        pl.ds(pl.multiple_of(base, 128), FPL)],
            dsems.at[slot])

    p0 = c & 1
    u0 = (c - p0) // 2
    # `zrow` is zero for every real length but opaque to constant folding:
    # adding it forces a row-varying (non-sublane-replicated) Mosaic layout
    # on the loop carries — the body's outputs are row-varying and Mosaic
    # cannot relayout varying data into a replicated carry
    zrow = jnp.minimum(nn, 0)
    v0 = jnp.where(us == u0, 0, _BIG) + zrow
    vm1 = jnp.full((P, U), _BIG, jnp.int32) + zrow
    # final scores accumulate elementwise into a (P, U) vector (one lane
    # per pair is ever written); the cross-lane reduce happens ONCE after
    # the sweep instead of once per wavefront
    svec0 = jnp.full((P, U), _BIG, jnp.int32) + zrow
    dbuf0 = jnp.zeros((P, FL), jnp.int32) + zrow

    def substep(a, p, v1, v2, svec, dbuf, qchars, tchars, trim):
        """One wavefront with *statically known* parity ``p`` (the
        two-step loop body alternates p=1 then p=0, so every branch on
        parity folds at trace time). ``trim`` (static) drops the DP
        boundary-row/column handling: for a > c the band sits strictly
        inside the table (i >= 1 and j >= 1 on every lane), so only the
        upper length bounds remain — the bulk of the sweep runs ~6 fewer
        VPU ops per lane."""
        I0 = (a + c - p) // 2
        J0 = (a - c + p) // 2
        i_vec = I0 - us
        j_vec = J0 + us

        # shifted views of wavefront a-1 (parity alternates):
        #   p == 0: D-source = v1[u-1], I-source = v1[u]
        #   p == 1: D-source = v1[u],   I-source = v1[u+1]
        if p == 0:
            d_src = jnp.where(us == 0, _BIG,
                              pltpu.roll(v1, shift=1, axis=1))
            i_src = v1
        else:
            d_src = v1
            i_src = jnp.where(us == U - 1, _BIG,
                              pltpu.roll(v1, shift=U - 1, axis=1))

        sub = jnp.where(qchars == tchars, 0, 1)
        cd = v2 + sub          # diagonal (i-1, j-1)
        ci = i_src + 1         # consume query (i-1, j)
        cdel = d_src + 1       # consume target (i, j-1)
        best = jnp.minimum(cd, jnp.minimum(ci, cdel))
        d = jnp.where(cd == best, 0, jnp.where(ci == best, 1, 2))

        if trim:
            interior = (i_vec <= nn) & (j_vec <= mm)
            v = jnp.where(interior, jnp.minimum(best, _BIG), _BIG)
        else:
            interior = ((i_vec >= 1) & (i_vec <= nn)
                        & (j_vec >= 1) & (j_vec <= mm))
            v = jnp.where(interior, jnp.minimum(best, _BIG), _BIG)
            v = jnp.where((i_vec == 0) & (j_vec >= 0) & (j_vec <= mm),
                          j_vec, v)
            v = jnp.where((j_vec == 0) & (i_vec >= 1) & (i_vec <= nn),
                          i_vec, v)

        # final score lives at a == n + m, u_fin = (m - n + c - p) / 2
        u_fin = jnp.clip((mm - nn + c - p) // 2, 0, U - 1)
        svec = jnp.where((a == nn + mm) & (us == u_fin), v, svec)

        packed = (d[:, :RB] | (d[:, RB:2 * RB] << 2)
                  | (d[:, 2 * RB:3 * RB] << 4) | (d[:, 3 * RB:] << 6))
        if FL == RB:
            # rows are already 128-aligned (F == 1): no accumulation
            dbuf = packed
        else:
            # rolling flush buffer: row a lands in the last RB lanes; every
            # F wavefronts it holds rows a-F+1..a and moves to the stage
            dbuf = pltpu.roll(dbuf, shift=FL - RB, axis=1)
            dbuf = jnp.concatenate([dbuf[:, :FL - RB], packed], axis=1)

        @pl.when(a % F == 0)
        def _():
            fidx = a // F - 1            # 0-based flush index
            slot = (fidx // PER) % 2

            # reusing a slot: its previous DMA must have drained
            @pl.when((fidx % PER == 0) & (fidx >= 2 * PER))
            def _():
                stage_dma(slot, fidx - PER).wait()

            stage[slot, :, pl.ds(pl.multiple_of((fidx % PER) * FL, 128),
                                 FL)] = dbuf.astype(jnp.uint8)

            @pl.when(fidx % PER == PER - 1)
            def _():
                stage_dma(slot, fidx).start()

        return v, v1, svec, dbuf

    # two wavefronts per iteration: with even c, parity is a & 1, so the
    # body sees p statically — and the character windows only advance on
    # one parity each (q on even a, t on odd a), halving the expensive
    # aligned-load + dynamic-roll work to one q- and one t-load per pair
    # of steps (odd a reuses the previous even step's query window; even
    # a reuses the odd step's target window)
    assert c % 2 == 0, "band/2 must be even for the two-step parity fold"
    qch0 = _load_window(qrp_ref, c + L - c // 2, width, U)

    def two_steps(k, carry, trim):
        v1, v2, svec, dbuf, qch = carry
        a1 = 2 * k + 1                   # p = 1
        tch = _load_window(tp_ref, c + (a1 - c + 1) // 2 - 1, width, U)
        v1, v2, svec, dbuf = substep(a1, 1, v1, v2, svec, dbuf,
                                     qch, tch, trim)
        a2 = 2 * k + 2                   # p = 0
        qch = _load_window(qrp_ref, c + L - (a2 + c) // 2, width, U)
        v1, v2, svec, dbuf = substep(a2, 0, v1, v2, svec, dbuf,
                                     qch, tch, trim)
        return v1, v2, svec, dbuf, qch

    # per-block dynamic sweep bound: no wavefront beyond the block's
    # longest pair ever matters (scores land at a == n+m; the walks only
    # read rows a <= n+m), so the trip count is traced — blocks of short
    # (or zero-length) pairs stop early. Unwritten dirs rows past the
    # bound are never read.
    # round to whole flush-DMA groups (F*PER steps) AND whole consumer
    # read groups (``out_quant``: 512 rows = 4 chunks for the packed
    # aligner walk, which rounds its start DOWN to a 512-row group; 128
    # for the consensus vote walk), so the staging protocol stays intact
    # and the walks' chunk DMAs never read unwritten rows; F and PER are
    # powers of two <= 256, so one quantum divides the other
    QB = max(out_quant, F * PER)
    assert QB % 128 == 0 and QB % (F * PER) == 0, (F, PER)
    if DYNAMIC_BOUND:
        maxnm = jnp.max(nn + mm)
        bound = jnp.minimum(jnp.int32(S), ((maxnm + QB - 1) // QB) * QB)
    else:
        bound = jnp.int32(S)

    # split the sweep at a == c: boundary rows/columns can only appear on
    # wavefronts a <= c (i == 0 needs I0 < U, j == 0 needs J0 <= 0), so
    # every later wavefront runs the trimmed substep
    ksplit = jnp.minimum(jnp.int32(c // 2), bound // 2)
    carry = lax.fori_loop(
        0, ksplit, functools.partial(two_steps, trim=False),
        (v0, vm1, svec0, dbuf0, qch0))
    _, _, svec, _, _ = lax.fori_loop(
        ksplit, bound // 2, functools.partial(two_steps, trim=True), carry)
    score = jnp.min(svec, axis=1, keepdims=True)
    score_ref[:, :] = jnp.where(nn + mm == 0, 0, score)

    # drain outstanding DMAs (one or two slots in flight at the end).
    # Slot indices stay static: each slot's last flush group is derived
    # from the traced bound and guarded by whether it ever fired.
    NFb = bound // F
    last = NFb // PER - 1  # last flush-group index (groups are PER flushes)
    for s in (0, 1):
        g = last - ((last - s) % 2)

        @pl.when((NFb > 0) & (g >= 0))
        def _(s=s, g=g):
            stage_dma(s, (g + 1) * PER - 1).wait()


def _fwd_kernel_swar(qrp_ref, tp_ref, n_ref, m_ref, dirs_ref, score_ref,
                     stage, dsems, *, max_len: int, band: int, P: int,
                     width: int, steps: int, PER: int, out_quant: int):
    """SWAR-packed forward kernel: two int16 wavefront scores per int32
    lane, biased-unsigned halfword arithmetic (``ops.swar``), so the
    carry state, the rolls and every min/add run on half the vector
    lanes. **Planar** halfword layout — packed word ``k`` holds lanes
    ``u = k`` (low) and ``u = k + U/4`` (high) — so the DP's +-1 lane
    shifts stay single-word rolls (one seam word fixed per shift) and
    the 2-bit direction planes fall out of the halfword halves with no
    cross-lane shuffle. Bit-identical direction matrix and scores vs
    ``_fwd_kernel`` (see the ``ops.swar`` module docstring for why the
    saturation classes line up); probed by ``pallas_swar_ok()``."""
    from .swar import (BIG16, LO16, ONES16, TWOS16, swar16_eq, swar16_ge,
                       swar16_ne_small, swar16_sel)
    W = band
    c = W // 2
    L = max_len
    U = W // 2
    U2 = U // 2           # packed words per wavefront
    RB = U // 4
    S = steps
    FL = RB
    while FL % 128:
        FL += RB
    F = FL // RB
    FPL = FL * PER
    blk = pl.program_id(0)
    nn = n_ref[:, :]
    mm = m_ref[:, :]
    lane = lax.broadcasted_iota(jnp.int32, (P, U2), 1)
    # packed u iota: low field u = k, high field u = k + U2 (planar)
    usp = lane | ((lane + U2) << 16)
    usp1 = usp + ONES16   # u + 1 (inclusive upper bounds compare via +1)
    BIGS = jnp.int32(BIG16 * 0x00010001)

    def stage_dma(slot, fidx):
        base = (fidx + 1) * FL - FPL
        return pltpu.make_async_copy(
            stage.at[slot],
            dirs_ref.at[pl.ds(blk * P, P),
                        pl.ds(pl.multiple_of(base, 128), FPL)],
            dsems.at[slot])

    assert c % 2 == 0, "band/2 must be even for the two-step parity fold"
    p0 = c & 1
    u0 = (c - p0) // 2
    zrow = jnp.minimum(nn, 0)  # row-varying layout forcer (_fwd_kernel)
    lo0 = jnp.where(lane == u0, 0, BIG16)
    hi0 = jnp.where(lane == u0 - U2, 0, BIG16)
    v0 = (lo0 | (hi0 << 16)) + zrow
    vm1 = jnp.full((P, U2), BIGS, jnp.int32) + zrow
    svec0 = jnp.full((P, U2), BIGS, jnp.int32) + zrow
    dbuf0 = jnp.zeros((P, FL), jnp.int32) + zrow

    def substep(a, p, v1, v2, svec, dbuf, qpl, tpl, trim):
        I0 = (a + c - p) // 2
        J0 = (a - c + p) // 2

        # +-1 lane shifts: both planar halves shift together, so one
        # word roll + one seam-word fixup replaces the halfword shuffle
        # an interleaved layout would need on every lane
        if p == 0:
            r = pltpu.roll(v1, shift=1, axis=1)   # word k <- v1[k-1]
            # seam word 0: low = BIG (u = -1), high = v1[U2-1].low
            d_src = jnp.where(lane == 0, (r << 16) | BIG16, r)
            i_src = v1
        else:
            d_src = v1
            r = pltpu.roll(v1, shift=U2 - 1, axis=1)  # word k <- v1[k+1]
            # seam word U2-1: low = v1[0].high (u = U2), high = BIG
            i_src = jnp.where(lane == U2 - 1,
                              ((r >> 16) & LO16) | (BIG16 << 16), r)

        # XOR + mask SWAR equality on the packed 4-bit codes
        sub = swar16_ne_small(qpl ^ tpl, 4)
        cd = v2 + sub          # diagonal (i-1, j-1)
        ci = i_src + ONES16    # consume query (i-1, j)
        cdel = d_src + ONES16  # consume target (i, j-1)
        mB = swar16_ge(cdel, ci)    # I beats D on ties (walker order)
        m2 = swar16_sel(ci, cdel, mB)
        mA = swar16_ge(m2, cd)      # diagonal wins ties
        best = swar16_sel(cd, m2, mA)
        d = swar16_sel(ONES16, TWOS16, mB) & ~mA  # 0 where diag won

        # interior as a contiguous lane range [lo, hi] (the four i/j
        # bounds are monotone in u), checked per halfword against the
        # packed u iota; saturation folds into the same select
        if trim:
            lo = jnp.maximum(I0 - nn, 0)
            hi1 = jnp.clip(mm - J0 + 1, 0, U)
        else:
            lo = jnp.maximum(jnp.maximum(I0 - nn, 1 - J0), 0)
            hi1 = jnp.clip(jnp.minimum(mm - J0, I0 - 1) + 1, 0, U)
        rng_m = (swar16_ge(usp, lo * ONES16)
                 & swar16_ge(hi1 * ONES16, usp1))
        v = swar16_sel(best, BIGS, swar16_ge(BIGS, best) & rng_m)
        if not trim:
            # DP boundary rows/cols (only reachable at a <= c): at
            # i == 0 the value is j = a, at j == 0 it is i = a — one
            # shared select with per-pair validity predicates
            pj = jnp.where(a <= mm, -1, 0)
            pi = jnp.where(a <= nn, -1, 0)
            bm = ((swar16_eq(usp, I0 * ONES16) & pj)
                  | (swar16_eq(usp, (-J0) * ONES16) & pi))
            v = swar16_sel(a * ONES16, v, bm)

        # final score lives at a == n + m, u_fin = (m - n + c - p) / 2
        u_fin = jnp.clip((mm - nn + c - p) // 2, 0, U - 1)
        fm = (swar16_eq(usp, u_fin * ONES16)
              & jnp.where(a == nn + mm, -1, 0))
        svec = swar16_sel(v, svec, fm)

        # planar 2-bit pack straight off the halfword halves: byte k =
        # lanes (k, k+RB, k+2RB, k+3RB) = (t1.lo, t2.lo, t1.hi, t2.hi)
        t1 = d[:, :RB]
        t2 = d[:, RB:]
        packed = ((t1 & 3) | ((t2 & 3) << 2) | (((t1 >> 16) & 3) << 4)
                  | (((t2 >> 16) & 3) << 6))
        if FL == RB:
            dbuf = packed
        else:
            dbuf = pltpu.roll(dbuf, shift=FL - RB, axis=1)
            dbuf = jnp.concatenate([dbuf[:, :FL - RB], packed], axis=1)

        @pl.when(a % F == 0)
        def _():
            fidx = a // F - 1            # 0-based flush index
            slot = (fidx // PER) % 2

            @pl.when((fidx % PER == 0) & (fidx >= 2 * PER))
            def _():
                stage_dma(slot, fidx - PER).wait()

            stage[slot, :, pl.ds(pl.multiple_of((fidx % PER) * FL, 128),
                                 FL)] = dbuf.astype(jnp.uint8)

            @pl.when(fidx % PER == PER - 1)
            def _():
                stage_dma(slot, fidx).start()

        return v, v1, svec, dbuf

    def planar(win):
        return win[:, :U2] | (win[:, U2:] << 16)

    qpl0 = planar(_load_window(qrp_ref, c + L - c // 2, width, U))

    def two_steps(k, carry, trim):
        v1, v2, svec, dbuf, qpl = carry
        a1 = 2 * k + 1                   # p = 1
        tpl = planar(_load_window(tp_ref, c + (a1 - c + 1) // 2 - 1,
                                  width, U))
        v1, v2, svec, dbuf = substep(a1, 1, v1, v2, svec, dbuf,
                                     qpl, tpl, trim)
        a2 = 2 * k + 2                   # p = 0
        qpl = planar(_load_window(qrp_ref, c + L - (a2 + c) // 2,
                                  width, U))
        v1, v2, svec, dbuf = substep(a2, 0, v1, v2, svec, dbuf,
                                     qpl, tpl, trim)
        return v1, v2, svec, dbuf, qpl

    QB = max(out_quant, F * PER)
    assert QB % 128 == 0 and QB % (F * PER) == 0, (F, PER)
    if DYNAMIC_BOUND:
        maxnm = jnp.max(nn + mm)
        bound = jnp.minimum(jnp.int32(S), ((maxnm + QB - 1) // QB) * QB)
    else:
        bound = jnp.int32(S)

    ksplit = jnp.minimum(jnp.int32(c // 2), bound // 2)
    carry = lax.fori_loop(
        0, ksplit, functools.partial(two_steps, trim=False),
        (v0, vm1, svec0, dbuf0, qpl0))
    _, _, svec, _, _ = lax.fori_loop(
        ksplit, bound // 2, functools.partial(two_steps, trim=True), carry)
    s16 = jnp.minimum(
        jnp.min(svec & LO16, axis=1, keepdims=True),
        jnp.min((svec >> 16) & LO16, axis=1, keepdims=True))
    s32 = jnp.where(s16 == BIG16, jnp.int32(_BIG), s16)
    score_ref[:, :] = jnp.where(nn + mm == 0, 0, s32)

    NFb = bound // F
    last = NFb // PER - 1
    for s in (0, 1):
        g = last - ((last - s) % 2)

        @pl.when((NFb > 0) & (g >= 0))
        def _(s=s, g=g):
            stage_dma(s, (g + 1) * PER - 1).wait()


@functools.partial(jax.jit, static_argnames=("max_len", "band", "steps",
                                             "out_quant", "use_swar"))
def pallas_nw_fwd(qrp, tp, n, m, *, max_len: int, band: int,
                  steps: int = 0, out_quant: int = 128,
                  use_swar: bool = False):
    """Drop-in Pallas replacement for ``_nw_wavefront_kernel``: same
    inputs, same packed direction matrix [B, steps, RB] and scores [B]
    (``steps`` defaults to the full ``2*max_len`` sweep). ``out_quant``
    is the downstream walk's read granularity in rows: 512 when the
    packed-output aligner walk consumes the matrix, 128 (default) for
    the consensus vote walk — the dynamic sweep bound rounds up to it so
    the consumer never reads unwritten rows. ``use_swar`` runs the
    int16x2-packed variant (``_fwd_kernel_swar``, bit-identical
    outputs); callers gate it on ``pallas_swar_ok()`` plus the
    ``swar.swar_fits`` overflow guard."""
    B0, width = qrp.shape
    if B0 < 8:
        qrp, tp, n, m = _pad_rows([qrp, tp, n, m], B0, [0, 0, 1, 1])
    B = qrp.shape[0]
    U = band // 2
    RB = U // 4
    S = steps if steps else 2 * max_len
    P = min(FWD_P_CAP, B)
    FL = RB
    while FL % 128:
        FL += RB
    F = FL // RB
    if S % F or S % 2:
        raise ValueError(
            f"steps={S} must be even and divisible by the dirs flush "
            f"period {F} (band={band}); round steps up to a multiple "
            f"of 128")
    # stage ~2-4 KB per DMA, PER a power-of-two divisor of the flush count
    PER = 1
    while (PER * 2 * FL <= 4096 and (S // F) % (PER * 2) == 0):
        PER *= 2
    qrp = jnp.pad(qrp, ((0, 0), (0, _LOAD_PAD)))
    tp = jnp.pad(tp, ((0, 0), (0, _LOAD_PAD)))
    fwd = _fwd_kernel_swar if use_swar else _fwd_kernel
    kernel = functools.partial(fwd, max_len=max_len, band=band,
                               P=P, width=width, steps=S, PER=PER,
                               out_quant=out_quant)
    dirs, score = pl.pallas_call(
        kernel,
        grid=(B // P,),
        in_specs=[
            pl.BlockSpec((P, width + _LOAD_PAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, width + _LOAD_PAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S * RB), jnp.uint8),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, P, FL * PER), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(qrp, tp, n.reshape(B, 1).astype(jnp.int32),
      m.reshape(B, 1).astype(jnp.int32))
    return dirs.reshape(B, S, RB)[:B0], score.reshape(B)[:B0]


def _chunk_dma_factory(dirs_ref, buf, sems, blk, *, P, C, RB, S):
    """Double-buffered descending-a chunk DMA: chunk k holds direction
    rows [S - (k+1)*C, S - k*C) — the walk consumes rows backwards."""
    def chunk_dma(slot, k):
        lo = S - (k + 1) * C
        return pltpu.make_async_copy(
            dirs_ref.at[pl.ds(blk * P, P),
                        pl.ds(pl.multiple_of(lo * RB, 128), C * RB)],
            buf.at[slot, :, pl.ds(0, C * RB)],
            sems.at[slot])
    return chunk_dma


def _walk_step_decode(buf, slot, lo, a, i, j, lane_ww, *, c, U, RB, WW):
    """One wavefront-synchronized walk step, shared by the plain walk and
    the fused walk+vote kernel (the trickiest logic in this file — keep
    one copy): decode the pair's direction byte from an aligned window of
    the chunk buffer, apply boundary overrides, and gate on activity.
    Returns (op, di, dj, active) as (P, 1) vectors."""
    p = (a + c) & 1
    u = (j - i + c - p) // 2
    done = (i == 0) & (j == 0)
    escaped = (i > 0) & (j > 0) & ((u < 0) | (u >= U))
    active = ((i + j) == a) & ~done & ~escaped

    # the row may straddle a 128-lane boundary (offsets are RB-granular);
    # WW covers it, masked tail reads are never selected
    uc = jnp.clip(u, 0, U - 1)
    roff = (a - 1 - lo) * RB
    rbase = pl.multiple_of((roff // 128) * 128, 128)
    win = buf[slot, :, pl.ds(rbase, WW)]
    bidx = (roff - rbase) + uc % RB
    sel = jnp.sum(jnp.where(lane_ww == bidx, win.astype(jnp.int32), 0),
                  axis=1, keepdims=True)
    d = (sel >> (2 * (uc // RB))) & 3
    d = jnp.where(i == 0, 2, d)               # only D left
    d = jnp.where((j == 0) & (i > 0), 1, d)   # only I left
    op = jnp.where(active, d, 3)
    di = jnp.where(active & (op != 2), 1, 0)  # M/I consume query
    dj = jnp.where(active & (op != 1), 1, 0)  # M/D consume target
    return op, di, dj, active


def _walk_start(nn, mm, chunk_dma, blank_group, *, S: int, C: int,
                CHUNKS: int, group_chunks: int = 1):
    """Shared dynamic-start preamble of both walk kernels: compute the
    first live chunk (the walk begins at a = n + m, so leading
    descending-a chunks with no active pair are skipped), blank the
    skipped output range via ``blank_group(g)`` (group ``g`` covers
    chunks ``[g*group_chunks, (g+1)*group_chunks)`` — the packed-output
    walk needs 4 chunks per 128-byte-aligned store) so consumers see
    exactly what the XLA walk emits there, and prefetch the first live
    chunk's DMA (skipped entirely when the block has nothing to walk)."""
    if DYNAMIC_BOUND:
        maxnm = jnp.max(nn + mm)
        k0 = (S - jnp.minimum(jnp.int32(S),
                              ((maxnm + C - 1) // C) * C)) // C
        k0 = (k0 // group_chunks) * group_chunks
    else:
        k0 = jnp.int32(0)

    def blank(g, _):
        blank_group(g)
        return 0

    lax.fori_loop(0, k0 // group_chunks, blank, 0)

    @pl.when(k0 < CHUNKS)
    def _():
        chunk_dma(k0 % 2, k0).start()

    return k0


def _walk_kernel(dirs_ref, n_ref, m_ref, ops_ref, fi_ref, fj_ref,
                 buf, sems, *, band: int, P: int, C: int, steps: int):
    """Walk emitting the aligner's 2-bit x 4-per-byte PACKED op stream
    directly (``ops_ref`` is [B, S//4] uint8): the downstream `_pack_ops`
    pass disappears, the output writes shrink 4x, and the rolling output
    buffer shifts once per 4 steps instead of every step. The inner loop
    is unrolled 4 steps per iteration so the 2-bit shifts stay static."""
    W = band
    c = W // 2
    U = W // 2
    RB = U // 4
    S = steps
    CHUNKS = S // C
    GC = 512 // C              # chunks per 128-byte output flush group
    WW = _rup(128 + RB, 128)   # byte-select window (row may straddle 128s)
    blk = pl.program_id(0)
    nn = n_ref[:, :]
    mm = m_ref[:, :]
    lane_ww = lax.broadcasted_iota(jnp.int32, (P, WW), 1)
    chunk_dma = _chunk_dma_factory(dirs_ref, buf, sems, blk,
                                   P=P, C=C, RB=RB, S=S)

    def blank_group(g):
        # 4 steps of the inactive code 3 pack to 0xFF
        ops_ref[:, pl.ds(pl.multiple_of(g * 128, 128), 128)] = \
            jnp.full((P, 128), 255, jnp.uint8)

    k0 = _walk_start(nn, mm, chunk_dma, blank_group, S=S, C=C,
                     CHUNKS=CHUNKS, group_chunks=GC)
    # min(nn, 0) == 0 forces a row-varying carry layout (_fwd_kernel note)
    obuf0 = jnp.full((P, 128), 255, jnp.int32) + jnp.minimum(nn, 0)

    def chunk_body(k, carry):
        i, j, obuf = carry
        slot = k % 2

        @pl.when(k + 1 < CHUNKS)
        def _():
            chunk_dma((k + 1) % 2, k + 1).start()

        chunk_dma(slot, k).wait()
        lo = S - (k + 1) * C

        def quad_body(s4, carry):
            i, j, obuf = carry            # (P, 1) positions before step
            cur = jnp.zeros((P, 1), jnp.int32)
            for r in range(4):
                t = k * C + s4 * 4 + r    # emitted step index, asc.
                a = S - t                 # global anti-diagonal, desc.
                op, di, dj, _ = _walk_step_decode(buf, slot, lo, a, i, j,
                                                  lane_ww, c=c, U=U, RB=RB,
                                                  WW=WW)
                cur = cur | (op << (2 * r))
                i = i - di
                j = j - dj

            # rolling packed-byte buffer, flushed 128-aligned every
            # 128 bytes (= 512 steps)
            obuf = pltpu.roll(obuf, shift=127, axis=1)
            obuf = jnp.concatenate([obuf[:, :127], cur], axis=1)
            q = (k * C) // 4 + s4         # global packed-byte index

            @pl.when((q + 1) % 128 == 0)
            def _():
                off = pl.multiple_of(q + 1 - 128, 128)
                ops_ref[:, pl.ds(off, 128)] = obuf.astype(jnp.uint8)

            return i, j, obuf

        return lax.fori_loop(0, C // 4, quad_body, (i, j, obuf))

    fi, fj, _ = lax.fori_loop(k0, CHUNKS, chunk_body, (nn, mm, obuf0))
    fi_ref[:, :] = fi
    fj_ref[:, :] = fj


@functools.partial(jax.jit, static_argnames=("band",))
def pallas_walk_ops(dirs, n, m, *, band: int):
    """Wavefront-synchronized walk over the packed direction matrix.

    Returns ``(ops_packed [B, S//4] u8, fi, fj)`` — the same 2-bit x
    4-per-byte packing `_pack_ops` produces from the XLA walk, and the
    same op semantics up to inactive-gap placement (codes >= 3 interleave
    with the path after M steps); all consumers mask on ``op < 3`` after
    unpacking.
    """
    B0 = dirs.shape[0]
    if B0 < 8:
        dirs, n, m = _pad_rows([dirs, n, m], B0, [0, 1, 1])
    B, S, RB = dirs.shape
    C = min(128, S)
    P = _cap_block(B, 2 * (C * RB + _rup(128 + RB, 128)), _WALK_BUF_BYTES)
    if S % 512:
        raise ValueError(
            f"steps={S} must be a multiple of 512 (the packed walk "
            f"flushes 128-byte output groups of 4 chunks); round steps "
            f"up to a multiple of 512")
    kernel = functools.partial(_walk_kernel, band=band, P=P, C=C, steps=S)
    ops, fi, fj = pl.pallas_call(
        kernel,
        grid=(B // P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((P, S // 4), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S // 4), jnp.uint8),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        scratch_shapes=[
            # +WW tail lanes: the aligned byte-select window may read past
            # the chunk's last row (reads are masked, never selected)
            pltpu.VMEM((2, P, C * RB + _rup(128 + RB, 128)), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(dirs.reshape(B, S * RB), n.reshape(B, 1).astype(jnp.int32),
      m.reshape(B, 1).astype(jnp.int32))
    return ops[:B0], fi.reshape(B)[:B0], fj.reshape(B)[:B0]


class PallasDispatchMixin:
    """Shared try-Pallas-then-XLA dispatch with a per-shape disable memo:
    one exotic-shape Mosaic failure must not downgrade the whole run to
    the XLA kernels (the big well-tested shapes dominate wall-clock).

    Also hosts the per-engine device pin (``device`` ctor kwarg of both
    engines): the in-process chip scheduler gives every local chip its
    own engine pair, and :meth:`_pinned` is the thread-local
    ``jax.default_device`` context the engines wrap their launch/fetch
    halves in so host->device puts (and the computations that follow
    them) land on that chip."""

    device = None  # optional per-engine jax.Device pin

    def _pinned(self):
        if self.device is None:
            import contextlib
            return contextlib.nullcontext()
        import jax
        return jax.default_device(self.device)

    _pallas_failed_shapes = None
    # after this many distinct shape failures the breakage is systemic
    # (e.g. a libtpu upgrade): disable globally instead of paying one
    # failed Mosaic compile + warning per remaining shape
    _PALLAS_MAX_SHAPE_FAILURES = 3

    def _use_pallas(self, shape_key) -> bool:
        failed = self._pallas_failed_shapes
        if failed and (shape_key in failed
                       or len(failed) >= self._PALLAS_MAX_SHAPE_FAILURES):
            return False
        return pallas_ok()

    def _note_pallas_failure(self, shape_key, exc) -> None:
        import warnings
        warnings.warn(
            f"Pallas kernels failed at shape {shape_key}; using the XLA "
            f"kernels for this shape: {exc!r}", RuntimeWarning)
        if self._pallas_failed_shapes is None:
            self._pallas_failed_shapes = set()
        self._pallas_failed_shapes.add(shape_key)
        self.stats["pallas_fallback"] = \
            self.stats.get("pallas_fallback", 0) + 1


_PALLAS_OK = None


def pallas_ok() -> bool:
    """Probe once whether Mosaic kernels compile+run on this backend AND
    reproduce the XLA reference kernels bit-for-bit on a random small
    batch (True on real TPU; False on the CPU test mesh, which then uses
    the XLA kernels). The value-level comparison matters: a Mosaic
    regression that only corrupts values would otherwise ship silently —
    tests pin JAX to CPU and never execute this path."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            import numpy as np
            from .nw import _nw_wavefront_kernel, _walk_ops_kernel

            max_len, band = 256, 128
            B, c = 8, band // 2
            width = c + max_len + band
            rng = np.random.default_rng(7)
            bases = np.frombuffer(b"ACGT", np.uint8)
            qrp = np.full((B, width), 6, np.uint8)
            tp = np.full((B, width), 7, np.uint8)
            n = np.zeros(B, np.int32)
            m = np.zeros(B, np.int32)
            for k in range(B):
                ln = int(rng.integers(60, 200))
                t = bases[rng.integers(0, 4, ln)]
                q = np.delete(t.copy(), rng.integers(0, ln, 4))
                flips = rng.random(len(q)) < 0.2
                q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
                qrp[k, c + max_len - len(q): c + max_len] = q[::-1]
                tp[k, c: c + ln] = t
                n[k], m[k] = len(q), ln
            args = (jnp.asarray(qrp), jnp.asarray(tp),
                    jnp.asarray(n), jnp.asarray(m))
            # out_quant=512: this matrix feeds the packed aligner walk
            dp, sp = pallas_nw_fwd(*args, max_len=max_len, band=band,
                                   out_quant=512)
            dx, sx = _nw_wavefront_kernel(*args, max_len=max_len, band=band)
            opk, fip, fjp = pallas_walk_ops(dp, args[2], args[3],
                                            band=band)
            ox, fix, fjx = _walk_ops_kernel(dx, args[2], args[3],
                                            band=band)
            dp, sp, dx, sx, opk, fip, fjp, ox, fix, fjx = map(
                np.asarray, (dp, sp, dx, sx, opk, fip, fjp, ox, fix, fjx))
            # the Pallas walk's output is 2-bit packed — unpack to compare
            shifts4 = np.arange(4, dtype=np.uint8) * 2
            op_ = ((opk[:, :, None] >> shifts4) & 3).reshape(opk.shape[0],
                                                             -1)
            # rows past the block's dynamic sweep bound are never written
            # by the Pallas kernel (and never read by any consumer) —
            # compare only the guaranteed-computed rows
            mx = int((n + m).max())
            ok = (
                np.array_equal(dp[:, :mx], dx[:, :mx])
                and np.array_equal(sp, sx)
                and np.array_equal(fip, fix) and np.array_equal(fjp, fjx)
                and all(np.array_equal(op_[k][op_[k] < 3], ox[k][ox[k] < 3])
                        for k in range(B)))

            # fused walk+vote path must land on identical vote matrices
            if ok:
                from .poa import (CH, DEL, _accumulate_votes,
                                  _vote_from_ops)
                L, K, nW = max_len, 4, 4
                qcodes = rng.integers(0, 5, (B, max_len)).astype(np.uint8)
                qweights = rng.integers(0, 60,
                                        (B, max_len)).astype(np.uint8)
                qpw = jnp.asarray(
                    (qweights.astype(np.uint16) << 3) | qcodes)
                bg = jnp.asarray(rng.integers(0, 8, B).astype(np.int32))
                win_of = jnp.asarray(
                    (np.arange(B) % (nW - 1)).astype(np.int32))
                idxx, wx8, okx = _vote_from_ops(
                    jnp.asarray(ox), jnp.asarray(fix), jnp.asarray(fjx),
                    jnp.asarray(sx), args[2], args[3], qpw,
                    bg, max_len=max_len, band=band, L=L, K=K)
                wx, ux, _ovx, _owx = _accumulate_votes(
                    idxx, wx8, okx, win_of, args[3], bg, args[2],
                    jnp.asarray(sx), n_windows=nW, L=L, K=K, band=band)
                idx, w8, fiv, fjv = pallas_walk_vote(
                    jnp.asarray(dp), args[2], args[3], bg, qpw,
                    band=band, L=L, K=K, CH=CH, DEL=DEL)
                okv = ((fiv == 0) & (fjv == 0)
                       & (jnp.asarray(sp) < (band // 2)))
                wp, up, _ovp, _owp = _accumulate_votes(
                    idx, w8.astype(jnp.int32), okv, win_of, args[3], bg,
                    args[2], jnp.asarray(sp), n_windows=nW, L=L, K=K,
                    band=band)
                ok = (np.array_equal(np.asarray(wx), np.asarray(wp))
                      and np.array_equal(np.asarray(ux), np.asarray(up)))
            _PALLAS_OK = ok
        except Exception as e:
            from ..utils.logger import log_swallowed
            log_swallowed("pallas: availability probe failed; Mosaic "
                          "kernels disabled for this process", e)
            _PALLAS_OK = False
    return _PALLAS_OK


_PALLAS_SWAR_OK = None


def pallas_swar_ok() -> bool:
    """Probe once whether the SWAR-packed Mosaic forward kernel
    (``_fwd_kernel_swar``) reproduces the XLA reference bit-for-bit on a
    random small batch. Separate memo from ``pallas_ok()`` so a packed-
    kernel regression downgrades only the packed path — the int32 Pallas
    kernels keep running."""
    global _PALLAS_SWAR_OK
    if _PALLAS_SWAR_OK is None:
        if not pallas_ok():
            _PALLAS_SWAR_OK = False
            return False
        try:
            import numpy as np
            from .nw import _nw_wavefront_kernel

            max_len, band = 256, 128
            B, c = 8, band // 2
            width = c + max_len + band
            rng = np.random.default_rng(17)
            bases = np.frombuffer(b"ACGT", np.uint8)
            qrp = np.zeros((B, width), np.uint8)
            tp = np.zeros((B, width), np.uint8)
            n = np.zeros(B, np.int32)
            m = np.zeros(B, np.int32)
            for k in range(B):
                ln = int(rng.integers(60, 200))
                t = bases[rng.integers(0, 4, ln)]
                q = np.delete(t.copy(), rng.integers(0, ln, 4))
                flips = rng.random(len(q)) < 0.2
                q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
                qrp[k, c + max_len - len(q): c + max_len] = q[::-1]
                tp[k, c: c + ln] = t
                n[k], m[k] = len(q), ln
            args = (jnp.asarray(qrp), jnp.asarray(tp),
                    jnp.asarray(n), jnp.asarray(m))
            # graftlint: disable=swar-guard (probe bucket: 256 + 2 < BIG16 by construction)
            dp, sp = pallas_nw_fwd(*args, max_len=max_len, band=band,
                                   out_quant=512, use_swar=True)
            dx, sx = _nw_wavefront_kernel(*args, max_len=max_len,
                                          band=band)
            dp, sp, dx, sx = map(np.asarray, (dp, sp, dx, sx))
            mx = int((n + m).max())
            _PALLAS_SWAR_OK = (np.array_equal(dp[:, :mx], dx[:, :mx])
                               and np.array_equal(sp, sx))
        except Exception as e:
            from ..utils.logger import log_swallowed
            log_swallowed("pallas: SWAR probe failed; packed Mosaic "
                          "kernel disabled for this process", e)
            _PALLAS_SWAR_OK = False
    return _PALLAS_SWAR_OK


def _walk_vote_kernel(dirs_ref, n_ref, m_ref, bg_ref, qpw_ref,
                      idx_ref, w_ref, fi_ref, fj_ref, buf, sems, *,
                      band: int, P: int, C: int, steps: int, Lq: int,
                      L: int, K: int, CH: int, DEL: int):
    """Fused walk + vote emission for the consensus engine.

    Same traversal as ``_walk_kernel`` (shared ``_walk_step_decode``), but
    instead of op codes it emits each step's vote address (``idx``,
    column/insertion-slot layout of ``ops.poa._vote_from_ops``; the sink
    ``VOT`` when invalid) and its quality weight — the walk already holds
    (i, j, op) and the insertion-run counter in registers, so the
    XLA-side [B, S] prefix-sum reconstruction (two cumsums, a cummax, two
    batched gathers) disappears entirely; the XLA side only folds in
    ``win_of``, applies the per-pair ``ok`` gate, and scatter-adds.

    The layer base/weight lookup is ONE per-pair masked max-reduce over
    the (P, Lq) query rows held in VMEM (only one lane matches ``i - 1``,
    so max == select): codes and weights **travel packed** from the host
    as ``weight << 3 | code`` uint16 lanes (codes are 0..4, weights
    integral 0..93 — ``poa._pack_shard``), so one VMEM block and one
    per-step O(Lq) scan serve both lookups.
    """
    W = band
    c = W // 2
    U = W // 2
    RB = U // 4
    S = steps
    VOT = L * (1 + K) * CH
    CHUNKS = S // C
    WW = _rup(128 + RB, 128)
    blk = pl.program_id(0)
    nn = n_ref[:, :]
    mm = m_ref[:, :]
    bg = bg_ref[:, :]
    # packed i32 view for the per-step select (Mosaic only reduces
    # i32/f32): weight<<3 | code per lane, one reduce recovers both
    qpw = qpw_ref[:, :].astype(jnp.int32)      # (P, Lq)
    lane_ww = lax.broadcasted_iota(jnp.int32, (P, WW), 1)
    lane_q = lax.broadcasted_iota(jnp.int32, (P, Lq), 1)
    chunk_dma = _chunk_dma_factory(dirs_ref, buf, sems, blk,
                                   P=P, C=C, RB=RB, S=S)

    def blank_group(g):
        off = pl.multiple_of(g * C, 128)
        idx_ref[:, pl.ds(off, C)] = jnp.full((P, C), VOT, jnp.int32)
        w_ref[:, pl.ds(off, C)] = jnp.zeros((P, C), jnp.uint8)

    k0 = _walk_start(nn, mm, chunk_dma, blank_group, S=S, C=C,
                     CHUNKS=CHUNKS)
    zrow = jnp.minimum(nn, 0)
    ibuf0 = jnp.full((P, 128), VOT, jnp.int32) + zrow
    wbuf0 = jnp.zeros((P, 128), jnp.int32) + zrow

    def chunk_body(k, carry):
        i, j, run, ibuf, wbuf = carry
        slot = k % 2

        @pl.when(k + 1 < CHUNKS)
        def _():
            chunk_dma((k + 1) % 2, k + 1).start()

        chunk_dma(slot, k).wait()
        lo = S - (k + 1) * C

        def step_body(s, carry):
            i, j, run, ibuf, wbuf = carry
            a = S - (k * C + s)
            t = k * C + s
            op, di, dj, active = _walk_step_decode(buf, slot, lo, a, i, j,
                                                   lane_ww, c=c, U=U,
                                                   RB=RB, WW=WW)

            # layer base code + weight at query position i-1 (clipped like
            # the XLA path; a single lane matches, so max == select)
            qmask = lane_q == jnp.clip(i - 1, 0, Lq - 1)
            sel_pw = jnp.max(jnp.where(qmask, qpw, 0), axis=1,
                             keepdims=True)
            base = sel_pw & 7
            wq = sel_pw >> 3

            slot_i = jnp.minimum(run, K - 1)
            col = bg + j - 1
            addr = jnp.where(
                op == 0, col * CH + base,
                jnp.where(op == 2, col * CH + DEL,
                          (L + col * K + slot_i) * CH + base))
            # drop-collapse: an insertion run votes only its last K bases
            # (keeps every vote address's count bounded by layer depth,
            # which the packed-u32 accumulation relies on)
            valid = (active & (j >= 1) & (col >= 0) & (col < L)
                     & ~((op == 1) & (run >= K)))
            addr = jnp.where(valid, addr, VOT)
            wv = jnp.where(valid, wq, 0)
            run = jnp.where(active, jnp.where(op == 1, run + 1, 0), run)

            ibuf = pltpu.roll(ibuf, shift=127, axis=1)
            ibuf = jnp.concatenate([ibuf[:, :127], addr], axis=1)
            wbuf = pltpu.roll(wbuf, shift=127, axis=1)
            wbuf = jnp.concatenate([wbuf[:, :127], wv], axis=1)

            @pl.when((t + 1) % 128 == 0)
            def _():
                off = pl.multiple_of(t + 1 - 128, 128)
                idx_ref[:, pl.ds(off, 128)] = ibuf
                w_ref[:, pl.ds(off, 128)] = wbuf.astype(jnp.uint8)

            return i - di, j - dj, run, ibuf, wbuf

        return lax.fori_loop(0, C, step_body, (i, j, run, ibuf, wbuf))

    fi, fj, _, _, _ = lax.fori_loop(
        k0, CHUNKS, chunk_body, (nn, mm, zrow, ibuf0, wbuf0))
    fi_ref[:, :] = fi
    fj_ref[:, :] = fj


@functools.partial(jax.jit, static_argnames=("band", "L", "K", "CH", "DEL"))
def pallas_walk_vote(dirs, n, m, bg, qpw, *, band: int,
                     L: int, K: int, CH: int, DEL: int):
    """Fused walk + vote emission over the packed ``weight << 3 | code``
    uint16 query block. Returns (idx [B,S] i32 — vote address or the
    sink VOT, w [B,S] u8, fi, fj). Replaces ``pallas_walk_ops`` + the
    XLA prefix-sum vote prep on the consensus path."""
    B0 = dirs.shape[0]
    if B0 < 8:
        dirs, n, m, bg, qpw = _pad_rows(
            [dirs, n, m, bg, qpw], B0, [0, 1, 1, 0, 0])
    B, S, RB = dirs.shape
    Lq = qpw.shape[1]
    C = min(128, S)
    P = _cap_block(B, 2 * (C * RB + _rup(128 + RB, 128)), _WALK_BUF_BYTES)
    if S % C:
        raise ValueError(
            f"steps={S} must be a multiple of the walk chunk ({C}); "
            f"round steps up to a multiple of 128")
    kernel = functools.partial(_walk_vote_kernel, band=band, P=P, C=C,
                               steps=S, Lq=Lq, L=L, K=K, CH=CH, DEL=DEL)
    idx, w, fi, fj = pl.pallas_call(
        kernel,
        grid=(B // P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, Lq), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((P, S), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, S), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S), jnp.int32),
            jax.ShapeDtypeStruct((B, S), jnp.uint8),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, P, C * RB + _rup(128 + RB, 128)), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(dirs.reshape(B, S * RB), n.reshape(B, 1).astype(jnp.int32),
      m.reshape(B, 1).astype(jnp.int32),
      bg.reshape(B, 1).astype(jnp.int32), qpw.astype(jnp.uint16))
    return idx[:B0], w[:B0], fi.reshape(B)[:B0], fj.reshape(B)[:B0]
