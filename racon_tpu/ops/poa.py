"""Batched window consensus on TPU (cudapoa-equivalent).

Role: the accelerated consensus engine behind ``Polisher.polish`` — one
device batch processes (windows x layers) at once, the analog of a cudapoa
``Batch`` of POA groups (``src/cuda/cudabatch.cpp:54-62``).

Design (TPU-first): instead of porting cudapoa's irregular
one-block-per-group graph POA, consensus is computed as a
**quality-weighted pileup** refined over several device-resident rounds:

1. every layer is globally aligned to its backbone span with the banded
   wavefront NW forward kernel (Pallas with VMEM-resident wavefronts on
   TPU, the XLA scan from ``ops.nw`` elsewhere — all windows' layers in
   one fixed-shape batch, thousands of concurrent alignments);
2. the walk emits weighted votes (A/C/G/T/N/deletion per backbone column,
   plus K insertion slots per junction): on TPU the fused Pallas
   walk+vote kernel (``pallas_walk_vote``) emits each step's vote address
   and weight directly from registers; the XLA path reconstructs them
   from op codes with vectorized prefix sums (``_vote_from_ops``); both
   streams land on bit-identical matrices via the shared TPU-native
   accumulation ``_accumulate_votes`` (stable binary-routed compaction +
   per-row alignment + one-hot MXU matmul for the column votes, a folded
   packed scatter for the rare insertion votes — a flat scatter-add here
   costs more than the alignment kernels themselves);
3. consensus = per-column argmax over weighted base votes, a column
   dropped when deletion weight exceeds ``del_beta`` x the summed base
   weights, and insertion slot ``s`` emitted when its summed weight
   exceeds ``ins_theta`` x the column total (see ``_consensus_kernel``),
   with per-base unweighted coverage for the reference's TGS end-trimming
   contract (``src/window.cpp:118-139``);
4. the emitted consensus becomes the next round's backbone **on device**:
   ``refine_round`` rebuilds the backbone rows (the emitted entries
   compact to their prefix-sum positions) and remaps every layer span
   through the emitted-column map; ``refine_loop`` runs a stage's rounds
   in ONE dispatch — the host packs once, dispatches once and fetches
   once per stage (the tunnel costs ~0.1-0.3 s per round-trip, which
   used to dominate wall-clock). Windows whose backbone reproduces
   itself byte-for-byte are **converged**: their layers stop realigning
   (n = m = 0 pairs, which the Pallas kernels' per-block dynamic bounds
   skip nearly for free), the loop exits early once every window is
   converged or frozen, and after ``STAGE_A_ROUNDS`` a mostly-converged
   group re-packs its few stragglers ~25x smaller for the remaining
   rounds (clean high-coverage windows reach their fixed point in ~2
   rounds; noisy real windows often never reproduce byte-exactly, so a
   mostly-live group instead continues in place on its device-resident
   state). Recorded goldens are unchanged by all three mechanisms:
   converged/frozen windows reject updates, so skipped rounds are
   provably no-ops.

Like the reference's GPU path, this engine is allowed to differ slightly
from the CPU spoa-semantics engine (upstream records separate CUDA goldens:
1385 vs CPU 1312, ``test/racon_test.cpp:312``); windows the device cannot
handle (oversize backbone/layers, depth, band escapes) fall back to the CPU
engine, mirroring ``StatusType`` rejects (``src/cuda/cudabatch.cpp:135-156``).

Emission thresholds (``ins_theta``/``del_beta``) and the refinement round
count were calibrated against the CPU engine on λ-phage: the recorded
device golden is 1346 vs CPU 1324 (+1.7%, PAF input — bit-identical on
real TPU v5e and the XLA CPU mesh), well inside the reference's own
accelerated-path divergence (cudapoa 1385 vs spoa 1312, +5.6%,
``test/racon_test.cpp:312``).

Engine caps (documented, per ADVICE round 1): insertion runs longer than
``K_INS`` vote only their last ``K_INS`` bases, and insertions before
the first backbone column of a window (junction "-1") only have a vote
slot when the layer starts past column 0; refinement rounds recover most
of both effects. A backbone that grows past its fixed device buffer
(``L + GROW`` columns) freezes at its last refined state — backbones are
consensus estimates of ~window length, so growth beyond GROW columns does
not occur on real data.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .nw import _nw_wavefront_kernel, _walk_ops_kernel
from .pallas_nw import PallasDispatchMixin
from .. import faults, flags, obs, sanitize
from ..core.window import WindowType
from ..obs import metrics

# Alignment band for layer-vs-backbone-span alignment (layers are ~window
# sized; c=256 covers ~50% divergence at 500 bp).
BAND = 512
# Insertion slots tracked per backbone junction.
K_INS = 4
# Columns of backbone-growth headroom per refinement round loop.
GROW = 256
# Pairs per device group: larger window sets split into several groups
# dispatched in flight (keeps per-launch arrays and the vote scatter at a
# steady size instead of one monolithic batch; the analog of cudapoa's
# fixed per-batch memory, cudapolisher.cpp:219-228). 16k pairs/group:
# every group costs a host fetch round trip over the (jittery, up to
# ~1 s) tunnel, which at 8k/group rivaled the group's own device time;
# the vote accumulation's MXU matmul grows with B x n_windows but stays
# well under the round-trip cost it buys back.
MAX_GROUP_PAIRS = 32768
# Ragged-packing lane arena (round 10, the cudabatch greedy batch-fill
# analog, SURVEY §L3): a group greedy-fills windows until its pair rows
# x lane width reach this budget, so short-window buckets carry
# proportionally MORE pairs per dispatch instead of padding every pair
# row to the global maxima. Sized to keep the w=500 default bucket at
# exactly the proven MAX_GROUP_PAIRS geometry (Lq = 1024 there).
ARENA_LANES = MAX_GROUP_PAIRS * 1024
# Windows per group ceiling: the vote reduction's [B, n_windows] one-hot
# matmul and the [n_windows, Lb*(1+K)*CH] vote matrices grow with the
# window count, so very short windows close a group on this before the
# lane arena fills.
MAX_GROUP_WINDOWS = 4096
# In-flight ceiling for dispatched-but-unfetched groups: each holds its
# packed inputs (~(2*Lq + ~20) bytes/pair) plus a small output state on
# device (the big per-round intermediates live only inside the one
# execution running at a time). The tunnel charges ~0.5-1.3 s per
# EXECUTION and per fetch — at assembly scale those round trips, not
# the DP, bound wall-clock — so groups are as large as the vote stream
# affords and as many as this budget affords are dispatched before the
# first fetch blocks; the user's -c pipeline depth acts as a floor.
MAX_INFLIGHT_BYTES = 4 * 1024 * 1024 * 1024
# Refinement rounds run at full group size before the decision point: a
# group whose windows mostly converged (clean high-coverage data reaches
# its byte-exact fixed point in ~2 rounds) re-packs the few stragglers
# into a small stage-B group for the remaining rounds; a group that is
# mostly still refining (noisy real data rarely hits an exact fixed
# point) just continues the remaining rounds IN PLACE on its
# device-resident state — no repack, no re-upload, one extra fetch.
STAGE_A_ROUNDS = 2
# Stage-B repack pays a host pack + upload; it wins only when it shrinks
# the batch a lot. Above this survivor fraction, continue in place.
STAGE_B_MAX_SURVIVOR_FRAC = 0.5
# Vote channels: A C G T N DEL (stride 8 for cheap addressing).
CH = 8
A, C, G, T, N_CODE, DEL = 0, 1, 2, 3, 4, 5
# Packing codes distinct from every base code, so query padding never
# "matches" target padding in the NW kernel's character compare.
Q_PAD, T_PAD = 6, 7
# Reference default POA scores (src/main.cpp; shared with the CLI so the
# device-engine divergence warning tracks the real defaults).
DEFAULT_MATCH, DEFAULT_MISMATCH, DEFAULT_GAP = 3, -5, -4

_CODE_LUT = np.full(256, N_CODE, dtype=np.uint8)
for i, b in enumerate(b"ACGT"):
    _CODE_LUT[b] = i
_BYTE_LUT = np.frombuffer(b"ACGTN-", dtype=np.uint8)


@functools.partial(jax.jit,
                   static_argnames=("max_len", "band", "L", "K"))
def _vote_from_ops(ops, fi, fj, score, n, m, qpw, begin,
                   *, max_len: int, band: int, L: int, K: int):
    """Turn walked op codes into the (idx, w, ok) vote stream — vectorized.

    ops: uint8 [B, S] backward-walk op codes from ``_walk_ops_kernel``
    (0=M, 1=I, 2=D, >=3 done/stalled); qpw: [B, max_len] uint16 layer
    base codes and phred weights packed ``weight << 3 | code`` (the same
    lane format the fused Pallas emitter consumes — codes 3 bits,
    weights <= 93 in 7); begin: [B] backbone-span start column.

    The walk position *before* step t is recovered with prefix sums of the
    consumed-query/-target indicators (no sequential re-walk), the
    insertion-run length with a prefix max over the last non-insertion
    step, and the layer base+weight lookup is ONE batched gather on the
    packed lanes (it used to be two) — everything is [B, S] elementwise
    work. The XLA twin of the fused Pallas emitter
    (``pallas_walk_vote``): both produce the identical stream consumed
    by :func:`_accumulate_votes`.

    Vote layout: column votes at col*CH+ch, insertion slot s of junction
    col at (L + col*K + s)*CH + ch, sink VOT for non-votes. Insertion
    runs longer than K vote only their last K bases (the rest are
    dropped), which bounds every vote address's count at the layer depth.
    """
    B, S = ops.shape
    Lq = max_len
    VOT = L * (1 + K) * CH

    is_M = ops == 0
    is_I = ops == 1
    is_D = ops == 2
    di = (is_M | is_I).astype(jnp.int32)   # consumed a query base
    dj = (is_M | is_D).astype(jnp.int32)   # consumed a target base
    # position before step t: (n, m) minus everything consumed earlier
    i_t = n[:, None] - jnp.cumsum(di, axis=1) + di
    j_t = m[:, None] - jnp.cumsum(dj, axis=1) + dj

    # ins_run at t = number of consecutive I steps immediately before t
    t_idx = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    last_ni = lax.cummax(jnp.where(~is_I, t_idx, -1), axis=1)
    last_ni_excl = jnp.concatenate(
        [jnp.full((B, 1), -1, jnp.int32), last_ni[:, :-1]], axis=1)
    ins_run = t_idx - 1 - last_ni_excl
    slot = jnp.minimum(ins_run, K - 1)

    qpos = jnp.clip(i_t - 1, 0, Lq - 1)
    pw = jnp.take_along_axis(qpw, qpos, axis=1).astype(jnp.int32)
    base = pw & 7
    # weights travel packed (integral 0..93 phred, or 1 for no-quality
    # layers) — identical values to the Pallas emitter's
    wgt = pw >> 3
    col = begin[:, None] + j_t - 1
    # vote target: M -> (col, base); D -> (col, DEL); I -> ins slot
    idx = jnp.where(
        is_M, col * CH + base,
        jnp.where(is_D, col * CH + DEL,
                  (L + col * K + slot) * CH + base))
    valid = ((ops < 3) & (j_t >= 1) & (col >= 0) & (col < L)
             & ~(is_I & (ins_run >= K)))
    idx = jnp.where(valid, idx, VOT)  # sink
    w = jnp.where(valid, wgt, 0)

    ok = (fi == 0) & (fj == 0) & (score < (band // 2))
    return idx, w, ok


def _shift_left(x, sh: int):
    """Shift lanes toward index 0 by static ``sh``, zero-filling the tail
    (unlike ``jnp.roll`` nothing wraps)."""
    return jnp.pad(x[:, sh:], ((0, 0), (0, sh)))


def _compact_rows(flag, payload, S: int):
    """Stable per-row compaction: move flagged lanes to [0, rank) keeping
    order; unflagged output lanes are zero. ``payload`` is one int32 array
    (or a tuple of them, routed together) of nonnegative values — callers
    bit-pack what they need.

    Routing is LSB-first binary shifting: pass k moves items whose
    remaining distance has bit k by 2**k lanes. Destinations are the
    strictly-increasing ranks and distances d = t - rank are
    non-decreasing over flagged items, which makes every pass
    collision-free: a mover landing on a stayer would need
    d_j - d_i = c*2^k (c >= 1) with both ranks r_j > r_i and
    r_j - r_i = (1 - c)*2^k <= 0 — a contradiction. ~log2(S) elementwise
    passes; no scatter, no gather."""
    B = flag.shape[0]
    single = not isinstance(payload, tuple)
    pays = (payload,) if single else payload
    f = flag.astype(jnp.int32)
    rank = jnp.cumsum(f, axis=1) - f
    t_idx = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    d = jnp.where(flag, t_idx - rank, 0)
    alive = flag
    pays = tuple(jnp.where(flag, p, 0) for p in pays)
    for k in range((S - 1).bit_length()):
        sh = 1 << k
        if sh >= S:
            break
        mov = alive & (((d >> k) & 1) == 1)
        stay = alive & ~mov
        mov_s = _shift_left(mov, sh)
        d_s = _shift_left(d, sh)
        pays_s = tuple(_shift_left(p, sh) for p in pays)
        alive = mov_s | stay
        d = jnp.where(mov_s, d_s, jnp.where(stay, d, 0))
        pays = tuple(jnp.where(mov_s, ps, jnp.where(stay, p, 0))
                     for ps, p in zip(pays_s, pays))
    out = pays[0] if single else pays
    return out, alive


def _shift_rows_left(x, amount, max_amount: int):
    """Per-row left shift by a traced per-row ``amount`` (binary
    decomposition of the shift into static-shift selects; zero fill)."""
    for k in range(max(max_amount, 1).bit_length()):
        sh = 1 << k
        if sh > max_amount:
            break
        x = jnp.where((((amount >> k) & 1) == 1)[:, None],
                      _shift_left(x, sh), x)
    return x


def _shift_right(x, sh: int):
    """Shift lanes away from index 0 by static ``sh``, zero-filling the
    head (mirror of :func:`_shift_left`; nothing wraps)."""
    return jnp.pad(x[:, :-sh], ((0, 0), (sh, 0)))


def _expand_rows(alive, payload, dist, S: int):
    """Stable per-row expansion — the mirror of :func:`_compact_rows`:
    move the alive lane at rank position ``r`` RIGHT by ``dist[r]`` lanes
    (``dist`` must be >= 0 and non-decreasing over alive lanes, with
    ``r + dist[r] < S``); vacated and untouched lanes read zero.

    Binary routing like :func:`_compact_rows` but **MSB-first**: pass k
    moves items whose remaining distance has bit k by 2**k lanes toward
    the tail. MSB-first is what makes expansion collision-free (LSB-first
    only works for the dense-rank destinations of compaction): at pass k
    every item sits at ``dest - (d mod 2^(k+1))``, so a mover i landing
    on a stayer j would need ``dest_j - dest_i = (d_j - d_i) mod-parts``
    forcing ``q_i > q_j`` in the bit-k+1 quotients while ``d_i <= d_j``
    — a contradiction. Used to land per-(column, slot) insertion votes
    on their absolute column lanes without a scatter."""
    pays = jnp.where(alive, payload, 0)
    d = jnp.where(alive, dist, 0)
    for k in reversed(range((S - 1).bit_length())):
        sh = 1 << k
        if sh >= S:
            continue
        mov = alive & (((d >> k) & 1) == 1)
        stay = alive & ~mov
        mov_s = _shift_right(mov, sh)
        d_s = _shift_right(d, sh)
        pays_s = _shift_right(pays, sh)
        alive = mov_s | stay
        d = jnp.where(mov_s, d_s, jnp.where(stay, d, 0))
        pays = jnp.where(mov_s, pays_s, jnp.where(stay, pays, 0))
    return pays, alive


def _int_vote_matmul(ohT8, a_ch, a_w, CH: int):
    """Exact integer window-reduction of per-lane (channel, weight) votes
    on the MXU: an int8 x int8 -> int32 matmul pair instead of the f32
    HIGHEST one-hot matmul. Weights (< 2^13 after alpha scaling) split
    into two 7-bit limbs so the operands fit int8; int32 accumulation
    (``preferred_element_type``) is exact at any voting depth up to
    2^31 / 8184 ≈ 262k — where the f32 path lost integer exactness at
    2^24 partial sums, the old depth-2047 cap. Returns (weight sums,
    vote counts), both int32 [nW, L*CH]."""
    ch_iota = jnp.arange(CH, dtype=jnp.int32)
    wop = jnp.where(a_ch[:, :, None] == ch_iota, a_w[:, :, None], 0)
    B = wop.shape[0]
    flat = wop.reshape(B, -1)
    lo = (flat & 127).astype(jnp.int8)
    hi = (flat >> 7).astype(jnp.int8)       # a_w < 2^13 -> hi < 64
    cnt = (flat > 0).astype(jnp.int8)
    w = (jnp.matmul(ohT8, lo, preferred_element_type=jnp.int32)
         + (jnp.matmul(ohT8, hi, preferred_element_type=jnp.int32) << 7))
    c = jnp.matmul(ohT8, cnt, preferred_element_type=jnp.int32)
    return w, c


def _accumulate_votes(idx, w, ok, win_of, span_m, bg, n, score, *,
                      n_windows: int, L: int, K: int, band: int,
                      scores=(DEFAULT_MATCH, DEFAULT_MISMATCH,
                              DEFAULT_GAP), matmul_votes: bool = False):
    """Accumulate the per-step vote stream into per-window matrices —
    shared by both walk backends (identical results by construction).

    TPU-native replacement for a flat scatter-add (XLA's scatter engine
    processes the ~10M updates of a full-size group at ~90M/s, an order
    of magnitude over everything else in the round):

    - **column votes** (M/D steps, one per consumed backbone column, the
      ~98% majority): the r-th column-consuming step of a pair hits
      column ``bg + m - 1 - r``, so a stable per-row compaction
      (:func:`_compact_rows`) followed by a lane reverse and a per-row
      shift lands every vote at its absolute column; a one-hot
      [B, n_windows] matmul (exact: integer values < 2^24 in f32 with
      HIGHEST precision) then reduces pairs into windows on the MXU;
    - **insertion votes** (~2%): compacted to the first ``band//2`` lanes
      (an ok pair has score < band//2, so it cannot carry more insertion
      steps than that) and scatter-added into a **u32 pair** per address
      (weight table + count table). The old single-u32 packing (weight
      bits 0-22, count bits 23-31) silently carried the count into the
      weight field past 511 votes per address — it was what capped the
      voting depth at 511; the widened pair is exact to depth 2^32 and
      the depth ceiling now comes from the f32-exactness of the column
      matmul (see ``TpuPoaConsensus.__init__``).

    **Score-weighted voting** (the -m/-x/-g contract, the analog of
    cudapoa consuming the CLI scores directly,
    ``src/cuda/cudabatch.cpp:54-62``): every layer's votes are scaled by
    alpha = 64 * (its alignment score under the CLI m/x/g) / (its score
    under the reference defaults 3/-5/-4), so relatively poor layers
    under the chosen scoring lose voting power. The match/mismatch/gap
    counts come from the edit score plus a gap count derived from the
    vote stream itself (gaps = insertion votes + DEL column votes), so
    both walk backends compute identical alphas. The stream-derived gap
    count is an *approximation*: insertion runs longer than K_INS and
    insertions outside [0, L) emit no votes, so their gaps are
    undercounted and mat/mis correspondingly overestimated — alpha is an
    approximate CLI-score weight (consistently for both backends;
    defaults are exact since alpha is the constant 64 there). At the default scores
    alpha == 64 exactly for every layer — a uniform scale that cancels
    in every consensus ratio — so default results are bit-identical to
    unweighted voting (backbone votes are pre-scaled by 64 at pack
    time to keep the competition fair).

    Returns (weighted [n_windows, L*(1+K)*CH] f32, unweighted i32,
    ins_overflow telemetry, per-window overflow counts [n_windows] i32).
    """
    B, S = idx.shape
    VOT = L * (1 + K) * CH
    nW = n_windows

    col_flag = idx < L * CH
    ins_flag = (idx >= L * CH) & (idx < VOT)

    # ---- per-layer score weight alpha (q6 fixed point, 64 == 1.0)
    ms, xs, gs = scores
    ch_all = idx & (CH - 1)
    gaps = jnp.sum((ins_flag | (col_flag & (ch_all == DEL))
                    ).astype(jnp.int32), axis=1)
    mis = jnp.maximum(score - gaps, 0)
    mat = jnp.maximum((n + span_m - gaps) // 2 - mis, 0)
    if (ms, xs, gs) == (DEFAULT_MATCH, DEFAULT_MISMATCH, DEFAULT_GAP):
        alpha = jnp.full((B,), 64, jnp.int32)
    else:
        s_cli = (ms * mat + xs * mis + gs * gaps).astype(jnp.float32)
        s_def = (DEFAULT_MATCH * mat + DEFAULT_MISMATCH * mis
                 + DEFAULT_GAP * gaps).astype(jnp.float32)
        # floor 1 (not 0): a layer must never lose its unweighted
        # coverage counts to down-weighting — counts stay
        # alpha-independent; ceiling 88 keeps 93*88 in the 13-bit field
        alpha = jnp.clip(jnp.round(
            64.0 * jnp.maximum(s_cli, 0.0) / jnp.maximum(s_def, 1.0)
        ).astype(jnp.int32), 1, 88)
    w = w * alpha[:, None]

    # ---- column votes: compact to rank space, reverse, align, matmul
    ch = idx & (CH - 1)  # CH is a power of two
    pay = (ch << 13) | jnp.minimum(w, (1 << 13) - 1)
    comp, _ = _compact_rows(col_flag, pay, S)
    W2 = max(S, L)
    if W2 > S:
        comp = jnp.pad(comp, ((0, 0), (0, W2 - S)))
    rev = jnp.flip(comp, axis=1)
    aligned = _shift_rows_left(rev, W2 - bg - span_m, W2)[:, :L]
    a_ch = (aligned >> 13) & (CH - 1)
    onemask = ((win_of[:, None] == jnp.arange(nW, dtype=win_of.dtype))
               & ok[:, None])
    if matmul_votes:
        # exact int8/int32 MXU reduction — no f32 partial sums, but the
        # totals below are still cast to f32 for the consensus kernel,
        # so the ctor's depth cap must keep them f32-representable
        # (64-aligned at default scores -> 65535; 2047 otherwise)
        ohT8 = onemask.astype(jnp.int8).T
        w_icols, c_icols = _int_vote_matmul(
            ohT8, a_ch, aligned & ((1 << 13) - 1), CH)
        w_cols = w_icols.astype(jnp.float32)
        c_cols = c_icols.astype(jnp.float32)
    else:
        a_w = (aligned & ((1 << 13) - 1)).astype(jnp.float32)
        ch_iota = jnp.arange(CH, dtype=jnp.int32)
        wop = jnp.where(a_ch[:, :, None] == ch_iota, a_w[:, :, None], 0.0)
        cop = (wop > 0).astype(jnp.float32)
        onehot = onemask.astype(jnp.float32)
        hi = jax.lax.Precision.HIGHEST
        w_cols = jnp.matmul(onehot.T, wop.reshape(B, L * CH), precision=hi)
        c_cols = jnp.matmul(onehot.T, cop.reshape(B, L * CH), precision=hi)

    if matmul_votes:
        # ---- insertion votes as K aligned slot planes through the same
        # exact matmul (no scatter): per (pair, junction, slot) there is
        # at most ONE vote — slots of one insertion run are distinct and
        # distinct runs sit at distinct junction columns — so each slot
        # plane compacts in walk order (strictly decreasing junction
        # column) and RIGHT-expands onto absolute column lanes
        # (:func:`_expand_rows`; destinations ``L-1-col`` are strictly
        # increasing over ranks). Replaces the fold + packed scatter:
        # the scatter engine was the slowest op in the round, and the
        # fold cap's overflow events (``ins_overflow``, 265 in the r05
        # 96-window bench) are structurally impossible here.
        iaddr = idx - L * CH
        icol = iaddr // (K * CH)
        isub = iaddr - icol * (K * CH)    # slot*CH + ch
        lane = jnp.arange(W2, dtype=jnp.int32)[None, :]
        plane_w, plane_c = [], []
        for s in range(K):
            sflag = ins_flag & (isub >= s * CH) & (isub < (s + 1) * CH)
            ipay = ((icol << 16) | ((isub - s * CH) << 13)
                    | jnp.minimum(w, (1 << 13) - 1))
            comp_s, alive_s = _compact_rows(sflag, ipay, S)
            if W2 > S:
                comp_s = jnp.pad(comp_s, ((0, 0), (0, W2 - S)))
                alive_s = jnp.pad(alive_s, ((0, 0), (0, W2 - S)))
            dist = jnp.where(alive_s, (L - 1) - (comp_s >> 16) - lane, 0)
            exp_s, _ = _expand_rows(alive_s, comp_s, dist, W2)
            al_s = jnp.flip(exp_s[:, :L], axis=1)
            ws, cs = _int_vote_matmul(ohT8, (al_s >> 13) & (CH - 1),
                                      al_s & ((1 << 13) - 1), CH)
            plane_w.append(ws.reshape(nW, L, CH))
            plane_c.append(cs.reshape(nW, L, CH))
        INS = L * K * CH
        ins_w = jnp.stack(plane_w, axis=2).reshape(nW, INS) \
            .astype(jnp.float32)
        ins_c = jnp.stack(plane_c, axis=2).reshape(nW, INS)
        weighted = jnp.concatenate([w_cols, ins_w], axis=1)
        unweighted = jnp.concatenate(
            [c_cols.astype(jnp.int32), ins_c], axis=1)
        return (weighted, unweighted, jnp.int32(0),
                jnp.zeros((nW,), jnp.int32))

    # ---- insertion votes: two-level compaction, then one packed scatter
    # level 1 (per pair): an ok pair has < band//2 edits, hence < band//2
    # insertion steps — lanes beyond IC can only hold votes of pairs that
    # are dropped anyway
    IC = min(S, band // 2)
    ipay = ((idx - L * CH) << 13) | jnp.minimum(w, (1 << 13) - 1)
    icomp, ialive = _compact_rows(ins_flag, ipay, S)
    icomp = icomp[:, :IC]
    ialive = ialive[:, :IC]
    iaddr = icomp >> 13
    iw = ((icomp & ((1 << 13) - 1))
          * (ialive & ok[:, None]).astype(jnp.int32))
    # live = lanes that actually carry weight: rejected pairs' and
    # zero-weight lanes must not occupy fold-cap slots (they'd trip the
    # overflow fallback without representing any real vote density)
    ialive = ialive & ok[:, None] & (iw > 0)
    INS = L * K * CH
    iflat = jnp.where(ialive, win_of[:, None] * INS + iaddr, nW * INS)
    # level 2: fold G pairs per row and compact again — real insertions
    # are a few percent of steps, so folded rows compact ~CAP_DIV-fold
    # and the scatter engine (the slowest op on TPU at ~90M updates/s)
    # scans CAP_DIV x fewer lanes. A fold row can overflow its cap when
    # its G pairs average > IC/CAP_DIV insertions each (e.g. one very
    # divergent window's layers packed together): votes are never lost —
    # overflow switches that round to scattering the uncapped level-1
    # stream (lax.cond compiles both, the fast path runs when clean);
    # the returned tally counts the overflowing items for telemetry.
    def pack_scatter(flat, w):
        # widened accumulator: weight and count land in separate u32
        # tables (a u64 pair per address) — the old 23-bit weight /
        # 9-bit count split of one u32 saturated silently at depth 511,
        # carrying counts into the weight bits
        fl = flat.reshape(-1)
        wt = jnp.zeros(nW * INS + 1, jnp.uint32).at[fl].add(
            w.reshape(-1).astype(jnp.uint32))
        ct = jnp.zeros(nW * INS + 1, jnp.uint32).at[fl].add(
            (w.reshape(-1) > 0).astype(jnp.uint32))
        return wt, ct

    G, CAP_DIV = 32, 4
    if B % G == 0 and (G * IC) % CAP_DIV == 0:
        rows = B // G
        cap = G * IC // CAP_DIV
        f2 = iflat.reshape(rows, G * IC)
        w2 = iw.reshape(rows, G * IC)
        (f2, w2), alive2 = _compact_rows(
            ialive.reshape(rows, G * IC), (f2, w2), G * IC)
        # per-window overflow attribution (the r05 bare counter hid WHICH
        # window's vote density tripped the uncapped-scatter fallback):
        # an overflowing lane's flat address f2 still encodes its window
        # as f2 // INS, so one tiny scatter tallies them per window
        ovf_live = alive2[:, cap:] & (w2[:, cap:] > 0)
        ins_ovf_w = jnp.zeros(nW + 1, jnp.int32).at[
            jnp.where(ovf_live, f2[:, cap:] // INS, nW)].add(
            ovf_live.astype(jnp.int32))[:nW]
        ins_overflow = jnp.sum(ins_ovf_w)
        itab_w, itab_c = lax.cond(
            ins_overflow == 0,
            lambda: pack_scatter(
                jnp.where(alive2[:, :cap], f2[:, :cap], nW * INS),
                w2[:, :cap]),
            lambda: pack_scatter(iflat, iw))
    else:  # tiny batches: skip the fold
        itab_w, itab_c = pack_scatter(iflat, iw)
        ins_overflow = jnp.int32(0)
        ins_ovf_w = jnp.zeros((nW,), jnp.int32)
    ins_w = itab_w[:nW * INS].astype(jnp.float32).reshape(nW, INS)
    ins_c = itab_c[:nW * INS].astype(jnp.int32).reshape(nW, INS)

    weighted = jnp.concatenate([w_cols, ins_w], axis=1)
    unweighted = jnp.concatenate([c_cols.astype(jnp.int32), ins_c], axis=1)
    return weighted, unweighted, ins_overflow, ins_ovf_w


@functools.partial(jax.jit, static_argnames=("L", "K"))
def _consensus_kernel(weighted, unweighted, bcodes, bweights, blen,
                      ins_theta, del_beta, *, L: int, K: int):
    """Add backbone votes, then pick per-column and insertion winners.

    Emission rules (POA heaviest-bundle analogs, calibrated against the
    CPU engine on λ-phage):
    - a column emits its winning base unless the deletion weight exceeds
      ``del_beta`` x the summed base weights (reads voting *any* base
      jointly defend the column, as substitution variants occupy one
      aligned-ring position in the POA graph);
    - insertion slot ``s`` emits its winning base when the slot's summed
      weight (all bases — the slot is one graph node position, bases are
      its aligned ring) exceeds ``ins_theta`` x the column total.
    """
    n_windows = weighted.shape[0]
    cols = jnp.arange(L)

    w = weighted.reshape(n_windows, L * (1 + K), CH)
    uw = unweighted.reshape(n_windows, L * (1 + K), CH)
    col_votes = w[:, :L, :]      # [n, L, CH]
    ins_votes = w[:, L:, :].reshape(n_windows, L, K, CH)
    col_unw = uw[:, :L, :]
    ins_unw = uw[:, L:, :].reshape(n_windows, L, K, CH)

    # backbone's own votes (weight may be 0 for dummy quality -> still
    # contributes 1 to unweighted coverage, like a spoa sequence label)
    in_range = cols[None, :] < blen[:, None]
    bb_onehot = jax.nn.one_hot(bcodes, CH, dtype=jnp.float32)
    eps_w = jnp.maximum(bweights, 0.01)  # dummy-quality backbones still win
                                         # columns with no layer votes
    col_votes = col_votes + bb_onehot * (eps_w * in_range)[..., None]
    col_unw = col_unw + (bb_onehot * in_range[..., None]).astype(jnp.int32)

    base_winner = jnp.argmax(col_votes[:, :, :N_CODE + 1], axis=-1)
    base_total = col_votes[:, :, :N_CODE + 1].sum(-1)
    del_w = col_votes[:, :, DEL]
    winner = jnp.where(del_w > del_beta * base_total, DEL, base_winner)
    # winner-channel lookups as one-hot selects (take_along_axis lowers to
    # a generic gather, which is slow on TPU)
    ch_iota = jnp.arange(CH, dtype=winner.dtype)
    coverage = jnp.sum(
        jnp.where(winner[..., None] == ch_iota, col_unw, 0), axis=-1)
    col_total = col_votes.sum(-1)

    ins_winner = jnp.argmax(ins_votes[:, :, :, :N_CODE + 1], axis=-1)
    ins_total = ins_votes[:, :, :, :N_CODE + 1].sum(-1)
    ins_cov = jnp.sum(
        jnp.where(ins_winner[..., None] == ch_iota, ins_unw, 0), axis=-1)
    ins_emit = ins_total > ins_theta * col_total[:, :, None]

    return winner, coverage, ins_winner, ins_emit, ins_cov


@functools.partial(jax.jit, static_argnames=("n_windows", "max_len", "band",
                                             "Lb", "K", "steps",
                                             "use_pallas", "use_swar",
                                             "Lq2", "scores",
                                             "matmul_votes"))
def refine_round(n, qpw, win_of, real, bg, ed,
                 bcodes, bweights, blen, covs, ever, frozen, conv,
                 dropped, ins_theta, del_beta, *, n_windows: int,
                 max_len: int, band: int, Lb: int, K: int, steps: int = 0,
                 use_pallas: bool = False, use_swar: bool = False,
                 Lq2: int = 0,
                 scores=(DEFAULT_MATCH, DEFAULT_MISMATCH, DEFAULT_GAP),
                 matmul_votes: bool = False):
    """One fully-device-resident refinement round.

    Align every layer against its current backbone span, vote, pick
    winners, then *rebuild the backbone rows on device* (emitted-entry
    prefix sums give each emitted base its output column; one scatter
    writes the new backbone and its coverage) and remap every layer span
    through the emitted-column map. The host never sees intermediate
    backbones — it packs once before round 1 and fetches once after the
    last round. Replaces the per-round pack/fetch/Python-rebuild loop
    (_apply_shard) whose tunnel round-trips dominated wall-clock.

    Per-window state: ``bcodes/bweights/blen`` backbone rows (codes, Lb
    columns), ``covs`` coverage of the current backbone, ``ever`` whether
    any round succeeded (false -> CPU fallback), ``frozen`` stop-refining
    flag (backbone outgrew Lb), ``conv`` converged flag (backbone
    reproduced itself; layers stop realigning). ``dropped`` accumulates telemetry
    counters ([nd, 4 + n_windows] i32: rejected layer alignments,
    sweep-truncated spans, fold-overflow insertion votes — which never
    lose votes, they switch the round to the uncapped scatter — executed
    post-gating wavefront steps, then the fold overflows attributed to
    their windows). The single source of truth for the round wiring,
    wrapped by :func:`refine_loop` (all rounds in one dispatch) and the
    ``shard_map`` path (``racon_tpu.parallel.sharded_refine_loop``).

    Layer codes and phred weights travel packed (``qpw`` uint16 lanes,
    ``weight << 3 | code`` — one transfer array instead of two, one
    gather in the vote prep, one VMEM block in the fused Pallas
    emitter); ``use_swar`` runs the forward DP on int16x2-packed score
    lanes (bit-identical outputs, see ``ops.swar``).
    """
    Lq = max_len
    # the vote emitters only read query lanes < the longest real layer —
    # slicing their blocks to Lq2 cuts the fused kernel's per-step
    # base/weight selects by Lq/Lq2 (the fwd row layout still needs Lq)
    Lq2 = Lq2 or Lq
    c = band // 2
    width = c + Lq + band
    B = qpw.shape[0]
    qcodes = (qpw & 7).astype(jnp.uint8)  # unpacked codes for the rows
    # convergence gating: pairs of a window whose backbone reproduced
    # itself last round are zeroed out (n = m = 0) — their walk ends
    # immediately, they emit no votes, and the Pallas kernels' per-block
    # dynamic bounds skip whole blocks of them; the window's state is
    # frozen below via ok_upd, so its final consensus is the fixed point
    conv_p = jnp.take(conv | frozen, win_of)  # frozen windows' results
                                              # are discarded anyway
    n = jnp.where(conv_p, 0, n)
    m = jnp.where(conv_p, 0, ed - bg + 1)

    # ---- reversed query rows derived on device (the host sends only the
    # forward codes once; the reversed NW layout is a flip + mask)
    core = jnp.where((Lq - 1 - jnp.arange(Lq, dtype=jnp.int32))[None, :]
                     < n[:, None],
                     jnp.flip(qcodes, axis=1), jnp.uint8(Q_PAD))
    qrp = jnp.concatenate(
        [jnp.full((B, c), Q_PAD, jnp.uint8), core,
         jnp.full((B, band), Q_PAD, jnp.uint8)], axis=1)

    # ---- target rows from the backbone state: one row gather, then a
    # per-pair lane shift by ``bg`` via binary-decomposed rolls (wrapped
    # lanes always fall outside [0, m) and are masked) — the elementwise
    # rolls are ~8x cheaper than the generic 2-D gather they replace
    cols = jnp.arange(width, dtype=jnp.int32)[None, :] - c
    bbrow = jnp.take(bcodes, win_of, axis=0)            # (B, Lb)
    y = jnp.pad(bbrow, ((0, 0), (c, width - c - Lb)))
    for k in range((Lb - 1).bit_length()):
        y = jnp.where(((bg[:, None] >> k) & 1).astype(bool),
                      jnp.roll(y, -(1 << k), axis=1), y)
    tp = jnp.where((cols >= 0) & (cols < m[:, None]), y, jnp.uint8(T_PAD))

    if use_pallas:
        from .pallas_nw import pallas_nw_fwd, pallas_walk_vote
        packed, score = pallas_nw_fwd(qrp, tp, n, m,
                                      max_len=Lq, band=band, steps=steps,
                                      use_swar=use_swar)
        idx, w8, fi, fj = pallas_walk_vote(packed, n, m, bg,
                                           qpw[:, :Lq2], band=band,
                                           L=Lb, K=K, CH=CH, DEL=DEL)
        okp = (fi == 0) & (fj == 0) & (score < (band // 2))
        wv = w8.astype(jnp.int32)
    else:
        packed, score = _nw_wavefront_kernel(qrp, tp, n, m,
                                             max_len=Lq, band=band,
                                             steps=steps, swar=use_swar)
        ops, fi, fj = _walk_ops_kernel(packed, n, m, band=band)
        idx, wv, okp = _vote_from_ops(
            ops, fi, fj, score, n, m, qpw[:, :Lq2],
            bg, max_len=Lq2, band=band, L=Lb, K=K)
    weighted, unweighted, ins_ovf, ins_ovf_w = _accumulate_votes(
        idx, wv, okp, win_of, m, bg, n, score, n_windows=n_windows,
        L=Lb, K=K, band=band, scores=scores, matmul_votes=matmul_votes)
    winner, coverage, ins_winner, ins_emit, ins_cov = _consensus_kernel(
        weighted, unweighted, bcodes, bweights, blen, ins_theta, del_beta,
        L=Lb, K=K)
    # telemetry: [0] total dropped layer alignments, [1] the subset whose
    # span outgrew the sweep bound (n + m > steps keeps the walk from
    # finishing — a quality cliff distinct from band escapes, ADVICE r3),
    # [2] insertion votes past the fold-compaction cap (not lost — the
    # round fell back to the uncapped level-1 scatter), [3] executed
    # wavefront steps (sum of n+m AFTER convergence gating — the honest
    # numerator for device-utilization estimates: gated pairs do no DP);
    # columns [4:] attribute the fold overflows of [2] to their windows
    dropped = dropped + jnp.concatenate(
        [jnp.stack([jnp.sum((~okp) & real),
                    jnp.sum(real & (n + m > steps)),
                    ins_ovf,
                    jnp.sum(jnp.where(real, jnp.minimum(n + m, steps),
                                      0))]),
         ins_ovf_w])[None, :]

    # ---- rebuild backbone rows from emitted columns/slots.
    # Entry order within a column: its base first, then insertion slots
    # high-to-low (slot s holds the s-th base from the END of an insertion
    # run — the walk is backwards — so high slots come first in sequence).
    colr = jnp.arange(Lb, dtype=jnp.int32)[None, :]
    in_range = colr < blen[:, None]
    base_emit = (winner <= N_CODE) & in_range
    ins_e = ins_emit & in_range[:, :, None]
    ent_emit = jnp.concatenate([base_emit[:, :, None], ins_e[:, :, ::-1]], 2)
    ent_code = jnp.concatenate(
        [jnp.clip(winner, 0, N_CODE).astype(jnp.uint8)[:, :, None],
         ins_winner.astype(jnp.uint8)[:, :, ::-1]], 2)
    ent_cov = jnp.concatenate([coverage[:, :, None],
                               ins_cov[:, :, ::-1]], 2)
    E = Lb * (1 + K)
    fe = ent_emit.reshape(n_windows, E).astype(jnp.int32)
    pos = jnp.cumsum(fe, axis=1) - fe           # exclusive prefix sum
    new_len = jnp.sum(fe, axis=1)
    c2n = pos[:, ::(1 + K)]                     # old col -> new position

    # emitted entries compact to their output columns (ranks == the
    # prefix-sum positions, entries past Lb fall off the slice) — same
    # routing primitive as the vote accumulation, no scatter. Packing:
    # codes fit 3 bits; covs are winner-channel counts <= depth+1.
    epay = ((ent_cov.reshape(n_windows, E).astype(jnp.int32) << 3)
            | ent_code.reshape(n_windows, E).astype(jnp.int32))
    ecomp, _ = _compact_rows(fe > 0, epay, E)
    nb_mat = (ecomp[:, :Lb] & 7).astype(jnp.uint8)
    nc_mat = ecomp[:, :Lb] >> 3

    # empty consensus keeps the previous state (host analog: `continue`);
    # overflow freezes the window at its last refined backbone; converged
    # windows keep everything (their votes this round were backbone-only)
    ok_upd = (~frozen) & (~conv) & (new_len > 0) & (new_len <= Lb)
    frozen = frozen | (new_len > Lb)
    # a window converges when the refined backbone reproduces itself
    # byte-for-byte: later rounds would keep emitting the same fixed
    # point, so stop realigning its layers (the output is unchanged
    # except where an un-gated engine would oscillate between states)
    conv = conv | (ok_upd & (new_len == blen)
                   & jnp.all(jnp.where(in_range, nb_mat == bcodes, True),
                             axis=1))
    bcodes = jnp.where(ok_upd[:, None], nb_mat, bcodes)
    covs = jnp.where(ok_upd[:, None], nc_mat, covs)
    bweights = jnp.where(ok_upd[:, None], 0.0, bweights)  # refined backbone
                                                          # carries no phred
    ever = ever | ok_upd

    # ---- remap layer spans through the emitted-column map
    blen_g = jnp.take(blen, win_of)
    nl_g = jnp.take(new_len, win_of)

    def lookup(col):
        cl = jnp.minimum(col, blen_g)
        v = jnp.take(c2n.reshape(-1),
                     win_of * Lb + jnp.clip(cl, 0, Lb - 1))
        return jnp.where(cl >= blen_g, nl_g, v)  # col_to_new[blen] = len

    nb = lookup(bg)
    ne = jnp.maximum(nb + 1, lookup(ed + 1) - 1)
    nb = jnp.minimum(nb, nl_g - 1)
    ne = jnp.minimum(ne, nl_g - 1)
    upd_p = jnp.take(ok_upd, win_of)
    bg = jnp.where(upd_p, nb, bg)
    ed = jnp.where(upd_p, ne, ed)
    blen = jnp.where(ok_upd, new_len, blen)

    return (bg, ed, bcodes, bweights, blen, covs, ever, frozen, conv,
            dropped)


@functools.partial(jax.jit, static_argnames=("rounds", "n_windows",
                                             "max_len", "band", "Lb", "K",
                                             "steps", "use_pallas",
                                             "use_swar", "Lq2", "scores",
                                             "matmul_votes"))
def refine_loop(n, qpw, win_of, real, bg, ed,
                bcodes, bweights, blen, covs, ever, frozen, conv,
                dropped, ins_theta, del_beta, *, rounds: int,
                n_windows: int,
                max_len: int, band: int, Lb: int, K: int, steps: int = 0,
                use_pallas: bool = False, use_swar: bool = False,
                Lq2: int = 0,
                scores=(DEFAULT_MATCH, DEFAULT_MISMATCH, DEFAULT_GAP),
                matmul_votes: bool = False):
    """All refinement rounds of a group in ONE device dispatch.

    ``lax.while_loop`` over :func:`refine_round` — per-round host
    dispatches over the tunnel (~0.1 s each) otherwise rival the device
    time of a round; with the loop on device a group costs one dispatch
    and one fetch regardless of ``rounds``. The loop **exits early** once
    every window with real pairs is converged or frozen: further rounds
    are provably no-ops (converged/frozen windows reject updates via
    ``ok_upd`` and their gated pairs emit no votes and no telemetry), so
    the early exit is bit-invisible — it only skips work."""
    nW_rows = bcodes.shape[0]
    win_real = (jnp.zeros((nW_rows,), jnp.int32)
                .at[win_of].max(real.astype(jnp.int32)) > 0)

    def cond(carry):
        return (carry[0] < rounds) & ~jnp.all(carry[9] | carry[8]
                                              | ~win_real)

    def body(carry):
        out = refine_round(
            n, qpw, win_of, real, *carry[1:], ins_theta,
            del_beta, n_windows=n_windows, max_len=max_len, band=band,
            Lb=Lb, K=K, steps=steps, use_pallas=use_pallas,
            use_swar=use_swar, Lq2=Lq2, scores=scores,
            matmul_votes=matmul_votes)
        return (carry[0] + 1,) + tuple(out)

    state = (bg, ed, bcodes, bweights, blen, covs, ever, frozen, conv,
             dropped)
    return lax.while_loop(cond, body, (jnp.int32(0),) + state)[1:]


@functools.partial(jax.jit, static_argnames=("Lq",))
def _gather_qpw_rows(pool, src0, lens, *, Lq: int):
    """Device-side twin of :meth:`LayerStore.gather_qpw` (round 19):
    gather a group's packed ``weight << 3 | code`` lane block [B, Lq]
    straight from the resident pool the align->consensus dataflow
    uploaded once — the 2*B*Lq-byte per-group lane upload this replaces
    is the ``lane_upload_saved_bytes`` accounting. Same clipped-index /
    zero-pad construction, so the lanes are byte-identical to the host
    gather."""
    pos = jnp.arange(Lq, dtype=jnp.int32)[None, :]
    idx = src0[:, None] + jnp.minimum(pos,
                                      jnp.maximum(lens[:, None] - 1, 0))
    return jnp.where(pos < lens[:, None], pool[idx], jnp.uint16(0))


@jax.jit
def _fetch_pack(bcodes, blen, covs, ever, frozen, conv, dropped, bg, ed):
    """Coalesce a group's fetch into TWO device arrays: the tunnel pays
    ~0.1 s latency per transfer, so nine per-array fetches per group cost
    more than the round compute they retrieve. ``mat`` packs coverage and
    backbone code per column (cov << 3 | code — the same packing the
    rebuild uses, both values already bounded); ``meta`` concatenates
    every per-window/per-pair vector."""
    mat = (covs << 3) | bcodes.astype(jnp.int32)
    meta = jnp.concatenate([
        blen, ever.astype(jnp.int32), frozen.astype(jnp.int32),
        conv.astype(jnp.int32), dropped.reshape(-1), bg, ed])
    return mat, meta


@functools.partial(jax.jit, static_argnames=("rounds", "n_windows",
                                             "max_len", "band", "Lb", "K",
                                             "steps", "use_pallas",
                                             "use_swar", "Lq2", "scores",
                                             "matmul_votes"))
def _refine_loop_packed(*args, **kw):
    """refine_loop + the coalesced-fetch packing in ONE jitted program:
    the tunnel charges ~0.5-1.3 s per dispatched execution, so running
    the packing as a second program doubled the per-group overhead."""
    out = refine_loop(*args, **kw)
    (bg, ed, bcodes, _, blen, covs, ever, frozen, conv, dropped) = out
    mat, meta = _fetch_pack(bcodes, blen, covs, ever, frozen, conv,
                            dropped, bg, ed)
    return out + (mat, meta)


class _Work:
    """Per-window packing view (layers capped at ``max_depth``).

    Two storage modes share one packing surface: columnar windows
    (``win.layer_view`` attached by the polisher) keep ``rows`` indices
    into the shared :class:`~racon_tpu.core.layers.LayerStore` plus the
    store's flat ``lens``/``begin``/``end`` slices — the packer then
    builds the whole group's lane block with one vectorized pool gather;
    hand-built windows (``add_layer``) keep the legacy bytes tuples and
    pack through the join-and-LUT path."""

    __slots__ = ("win", "backbone", "bqual", "layers", "n_seqs", "store",
                 "rows", "lens", "begins", "ends", "n_layers",
                 "max_layer_len")

    def __init__(self, win, max_depth, stats):
        self.win = win
        self.backbone = win.backbone
        self.bqual = win.backbone_quality
        total = win.layer_count
        over = total - max_depth
        if over > 0:
            stats["dropped_layers"] += over
            metrics.inc("consensus.dropped_layers", over)
        depth = min(total, max_depth)
        self.n_seqs = total + 1
        self.n_layers = depth
        store, r0, _ = win.layer_view
        self.store = store
        if store is not None:
            self.rows = np.arange(r0, r0 + depth, dtype=np.int64)
            self.lens = store.length[r0:r0 + depth]
            self.begins = store.begin[r0:r0 + depth]
            self.ends = store.end[r0:r0 + depth]
            self.layers = None
            self.max_layer_len = int(self.lens.max()) if depth else 0
        else:
            self.layers = []  # (seq, qual, begin, end)
            for li in range(1, depth + 1):
                b, e = win.positions[li]
                self.layers.append((win.sequences[li], win.qualities[li],
                                    b, e))
            self.lens = np.array([len(s) for s, _, _, _ in self.layers],
                                 np.int64)
            self.begins = np.array([b for _, _, b, _ in self.layers],
                                   np.int64)
            self.ends = np.array([e for _, _, _, e in self.layers],
                                 np.int64)
            self.rows = None
            self.max_layer_len = int(self.lens.max()) if depth else 0


class _ConsensusStream:
    """Ragged streaming consensus session (round 10).

    Windows arrive through :meth:`feed` in any number of batches; live
    windows bucket by the power-of-two lane width their OWN backbone and
    layers need (``_bucket_L``) instead of padding to a global maximum,
    and every bucket greedy-fills groups against the fixed
    ``ARENA_LANES`` pair arena — short windows pack proportionally more
    pairs per dispatch (the cudabatch batch-fill design,
    ``cudabatch.cpp:54-62``). Full groups dispatch ASYNCHRONOUSLY the
    moment they close: host packing of the next range overlaps device
    compute of the previous ones through the bounded in-flight pipeline,
    and fetches happen only when the in-flight byte budget forces one or
    at :meth:`finish` — the double-buffered dispatch that stops host
    fetch/emit from gating the device.

    The alignment **band is frozen at the first dispatch** from the
    windows seen so far (plus the caller's ``band_hint``), because the
    band alters alignment outcomes (the ``score < band//2`` accept gate)
    and per-window consensus must not depend on which batch a window
    arrived in. ``run()``-style usage (one feed of everything, then
    finish) therefore reproduces the padded path's band exactly; per-
    window output is bit-identical to the padded path by construction —
    windows are independent and the vote accumulation is exact integer
    arithmetic at any grouping.

    Two-stage refinement carries over per bucket: groups dispatched
    while more work is expected run ``STAGE_A_ROUNDS`` and collect their
    unconverged windows; :meth:`finish` coalesces each bucket's
    stragglers into small stage-B groups (a bucket whose only group is
    its last runs the full budget directly, like the padded path's
    single-group rule)."""

    def __init__(self, eng: "TpuPoaConsensus", trim: bool,
                 band_hint: int = 0, progress=None):
        self.eng = eng
        self.trim = trim
        self.band_hint = band_hint
        self.windows: List = []            # every fed window, feed order
        self.results: List[Optional[bool]] = []
        self.buffer: List = []             # live works awaiting band/bucket
        self.buffered_pairs = 0
        self.max_bb_live = 0
        self.band: Optional[int] = None    # frozen at first dispatch
        self._Lq_pad = 0                   # padded-path reject caps,
        self._Lb_pad = 0                   # set when the band freezes
        self.pending: dict = {}            # bucket L -> [(slot, work)]
        self.bucket_state: dict = {}       # bucket L -> {groups,steps,Lq2}
        self.survivors: dict = {}          # bucket L -> stage-B collect
        self.inflight: List[dict] = []
        self.inflight_bytes = 0
        self.fetched = 0
        self.progress = progress
        self._done = False
        self._stats_before = dict(eng.stats)

    # ------------------------------------------------------------- intake

    def feed(self, windows) -> None:
        """Add a window range; packs and dispatches every group that
        fills. Returns immediately — dispatch is async, only the
        in-flight byte budget can force a (pipelined) fetch here."""
        assert not self._done, "stream already finished"
        eng = self.eng
        for win in windows:
            self.windows.append(win)
            if win.layer_count + 1 < 3:
                win.consensus = win.backbone
                self.results.append(False)
                eng.stats["passthrough"] += 1
                continue
            self.results.append(None)      # None -> CPU fallback unless
            slot = len(self.results) - 1   # a device group resolves it
            w = _Work(win, eng.max_depth, eng.stats)
            if w.n_layers < 2:
                continue
            self.buffer.append((slot, w))
            self.buffered_pairs += w.n_layers
            self.max_bb_live = max(self.max_bb_live, len(w.backbone))
        self._flush(final=False)

    # ----------------------------------------------------------- geometry

    def _bucket_L(self, w: "_Work", band: int) -> Optional[int]:
        """Power-of-two lane-width bucket for one window (None -> the
        window exceeds every device bucket and takes the CPU fallback,
        the same reject contract as the padded path's global caps).
        The pow2 rule itself is the engine's shared
        :meth:`TpuPoaConsensus.bucket_L_for`."""
        max_dev_L = (1 << 18) // (K_INS * CH) - GROW
        bb = len(w.backbone)
        if bb > max_dev_L:
            # the padded geometry admits backbones into the GROW margin
            # at the device ceiling (its accept test is bb <= Lb =
            # min(L + GROW, L + band) with L capped at max_dev_L);
            # mirror that accept set exactly — the reject set is part
            # of the ragged/padded byte-identity contract
            if bb > max_dev_L + min(GROW, band):
                return None
            bb = max_dev_L
        return self.eng.bucket_L_for(max(256, bb,
                                         w.max_layer_len - band))

    # ----------------------------------------------------------- dispatch

    def _flush(self, final: bool) -> None:
        eng = self.eng
        if self.band is None:
            # freeze the band only once there is enough buffered work to
            # justify a dispatch (or at finish): a full feed batch has
            # already been absorbed into max_bb_live at this point, so
            # run()-style usage sees the batch-global maximum exactly
            if not self.buffer:
                return
            if not final and self.buffered_pairs < eng.group_pairs_cap:
                return
            max_bb = max(self.max_bb_live, self.band_hint)
            # the padded path's geometry from the same live maximum:
            # its band AND its reject caps. Windows the padded path
            # would send to the CPU fallback (layers past Lq, backbones
            # past Lb) must take the CPU fallback here too — the reject
            # set is part of the byte-identity contract, and per-window
            # consensus is invariant to bucket size only for windows
            # both paths actually polish on device
            self.band, _, self._Lq_pad, self._Lb_pad = \
                eng._bucket_geometry(max_bb)
            eng.stats["band"] = self.band
        band = self.band
        for slot, w in self.buffer:
            if (w.max_layer_len > self._Lq_pad
                    or len(w.backbone) > self._Lb_pad):
                continue                   # CPU fallback via results None
            L = self._bucket_L(w, band)
            if L is None:
                continue                   # CPU fallback via results None
            self.pending.setdefault(L, []).append((slot, w))
        self.buffer = []
        self.buffered_pairs = 0

        for L in list(self.pending):
            items = self.pending[L]
            # straight to the engine's shared formula (the ragged path
            # and the warm-up estimate must read one cap rule)
            cap = eng.cap_pairs_for(L, band)
            while items:
                total = sum(w.n_layers for _, w in items)
                if (total < cap and len(items) <= MAX_GROUP_WINDOWS
                        and not final):
                    break                  # wait for more windows
                group: List = []
                pairs = 0
                while items and len(group) < MAX_GROUP_WINDOWS:
                    _, w = items[0]
                    if group and pairs + w.n_layers > cap:
                        break
                    pairs += w.n_layers
                    group.append(items.pop(0))
                more = bool(items) or not final
                self._dispatch(L, group, more_expected=more)
            if not items:
                del self.pending[L]

    def _dispatch(self, L: int, group: List, more_expected: bool) -> None:
        eng = self.eng
        band = self.band
        Lq = L + band
        Lb = min(L + GROW, Lq)
        max_nm = max(
            int(np.max(w.lens + np.minimum(w.ends - w.begins + 65, Lb)))
            for _, w in group)
        max_n = max(w.max_layer_len for _, w in group)
        steps, Lq2 = eng._sweep_geometry(Lq, max_nm, max_n)
        bk = self.bucket_state.setdefault(
            L, {"groups": 0, "steps": 0, "Lq2": 0})
        bk["steps"] = max(bk["steps"], steps)
        bk["Lq2"] = max(bk["Lq2"], Lq2)
        two_stage = (eng.rounds > STAGE_A_ROUNDS
                     and (more_expected or bk["groups"] > 0))
        la = eng._launch_group(group, Lq, Lb)
        la["geom"] = (Lq, Lb, steps, Lq2)
        la["band"] = band
        la["rounds"] = (min(eng.rounds, STAGE_A_ROUNDS) if two_stage
                        else eng.rounds)
        la["bucket"] = L
        la["collect"] = two_stage
        # resident bytes of this launch (packed pair inputs + per-window
        # state + coalesced fetch arrays) — the in-flight budget's unit
        la["bytes"] = (2 * Lq + 24) * la["B"] + 16 * Lb * la["nWp"]
        eng._rounds(la, Lq, Lb, steps, Lq2)
        bk["groups"] += 1
        self.inflight.append(la)
        self.inflight_bytes += la["bytes"]
        while (len(self.inflight) > max(eng.num_batches, 1)
               and self.inflight_bytes > MAX_INFLIGHT_BYTES):
            self._finish_oldest()

    def _finish_oldest(self) -> None:
        la = self.inflight.pop(0)
        self.inflight_bytes -= la["bytes"]
        collect = (self.survivors.setdefault(la["bucket"], [])
                   if la["collect"] else None)
        self.eng._finish_group(la, self.trim, self.results,
                               collect=collect)
        self.fetched += 1
        if self.progress is not None:
            est = self.fetched + len(self.inflight) + 1
            self.progress(self.fetched, est)

    # -------------------------------------------------------------- drain

    def finish(self, progress=None) -> List[bool]:
        """Dispatch the partial groups, drain the pipeline, run stage B
        per bucket and the CPU fallback; flags for every fed window."""
        assert not self._done, "stream already finished"
        self._done = True
        eng = self.eng
        if progress is not None:   # keep a callback set at stream() time
            self.progress = progress
        progress = self.progress
        self._flush(final=True)
        while self.inflight:
            self._finish_oldest()
        for L, surv in self.survivors.items():
            if not surv:
                continue
            band = self.band
            Lq = L + band
            Lb = min(L + GROW, Lq)
            bk = self.bucket_state[L]
            eng._run_stage_b(surv, self.trim, self.results,
                             Lq, Lb, bk["steps"], bk["Lq2"], band)
        cpu_idx = [i for i, r in enumerate(self.results) if r is None]
        if cpu_idx:
            eng.stats["fallback_windows"] += len(cpu_idx)
            metrics.inc("consensus.fallback_windows", len(cpu_idx))
            if eng.fallback is None:
                raise RuntimeError(
                    f"{len(cpu_idx)} windows rejected, no CPU fallback")
            flags_cpu = eng.fallback.run(
                [self.windows[i] for i in cpu_idx], self.trim)
            for i, f in zip(cpu_idx, flags_cpu):
                self.results[i] = f
        if progress is not None:
            progress(1, 1)
        eng._warn_dropped(self._stats_before)
        return [bool(r) for r in self.results]


class TpuPoaConsensus(PallasDispatchMixin):
    """Batched device consensus with CPU fallback for rejects.

    ``rounds`` controls iterative refinement: round r re-aligns every layer
    against the round r-1 consensus (with layer spans remapped through the
    emitted-column map), which recovers most of the gap between one-shot
    pileup voting and graph POA. All rounds run device-resident
    (:func:`refine_round`); the host packs once and fetches once.

    ``mesh``: optional 1-D :class:`jax.sharding.Mesh`; window groups are
    LPT-split across shards and the whole refinement loop runs under
    ``shard_map`` (multi-chip analog of cudapoa's per-GPU batch binning,
    ``src/cuda/cudapolisher.cpp:72-83``).
    """

    # pipelined-polish chunk sizing hint (Polisher.run): window ranges
    # streamed into run() should carry about one device group's worth of
    # layer pairs, so the pipelining never shrinks the fused executions
    group_pairs_hint = MAX_GROUP_PAIRS

    def __init__(self, match: int, mismatch: int, gap: int, fallback=None,
                 max_depth: int = 200, band: int = BAND, rounds: int = 6,
                 mesh=None, ins_theta: float = 0.25, del_beta: float = 0.65,
                 num_batches: int = 1, use_swar: bool = True,
                 use_matmul_votes: Optional[bool] = None,
                 use_ragged: Optional[bool] = None, device=None):
        self.fallback = fallback
        # per-engine chip pin (mutually exclusive with a mesh): the
        # in-process chip scheduler builds one consensus engine per
        # local device; pack/dispatch/fetch run under
        # jax.default_device(device) so this engine's whole working set
        # lives on its chip (PallasDispatchMixin._pinned)
        self.device = device
        # int8/i32 MXU vote reduction (on by default; ctor arg or
        # RACON_TPU_MATMUL_VOTES=0 restores the f32-matmul + packed
        # scatter for A/B): exact integer accumulation, no fold cap —
        # ins_overflow is structurally 0 on this path
        self.use_matmul_votes = (flags.get_bool("RACON_TPU_MATMUL_VOTES")
                                 if use_matmul_votes is None
                                 else use_matmul_votes)
        # ragged window packing (on by default off-mesh; ctor arg or
        # RACON_TPU_RAGGED=0 restores the single-geometry padded path):
        # windows bucket by their own size, groups greedy-fill a fixed
        # lane arena — the cudabatch batch-fill design (SURVEY §L3)
        self.use_ragged = (flags.get_bool("RACON_TPU_RAGGED")
                           if use_ragged is None else use_ragged)
        # device ceiling (companion to the K_INS/CH caps in the module
        # docstring): the insertion accumulator is exact on both paths
        # (u32-pair scatter / int32 matmul), so the binding limit is the
        # COLUMN vote reduction. On the f32 one-hot matmul per-column
        # weighted sums must stay < 2^24 — a vote carries at most
        # 93 * 88 (phred x alpha) plus the backbone's 64 * 60, making
        # 2047 the largest exact depth (2047 * 8184 + 3840 < 2^24). The
        # int8-limb matmul accumulates in int32, but the sums are still
        # handed to the f32 consensus kernel; at the DEFAULT scores
        # alpha is the constant 64, every weight (and the pre-scaled
        # backbone votes) is a multiple of 64, and multiples of 64 are
        # f32-exact up to 2^30 — 65535 * 5952 stays under that, so the
        # cap lifts to a conservative 65535. Custom -m/-x/-g scores make
        # alpha vary in [1, 88], sums are no longer 64-aligned, and the
        # f32 handoff re-binds the cap at 2047. Deeper requests clamp
        # rather than silently losing integer exactness.
        default_scores = (match, mismatch, gap) == (
            DEFAULT_MATCH, DEFAULT_MISMATCH, DEFAULT_GAP)
        self.max_depth = min(max_depth,
                             65535 if (self.use_matmul_votes
                                       and default_scores) else 2047)
        self.band = band
        self.rounds = rounds
        self.mesh = mesh
        # The pileup engine votes by base quality rather than alignment
        # score, so the reference's POA scores map onto the emission
        # thresholds instead of the DP (cudapoa consumes them directly,
        # ``src/cuda/cudabatch.cpp:54-62``): a stronger gap penalty makes
        # indels proportionally harder to emit — identity at the default
        # ``-g -4``, so the recorded goldens are untouched. ``-m/-x`` have
        # no quality-weighted analog; flag the divergence rather than
        # silently ignoring them.
        # indel-emission scale: gap cost *relative to the match reward*
        # (g=-8 with m=8 makes gaps relatively cheaper than the default
        # g=-4/m=3, not costlier), identity at the reference defaults
        scale = ((max(abs(gap), 1) * DEFAULT_MATCH)
                 / (abs(DEFAULT_GAP) * max(match, 1)))
        self.ins_theta = min(ins_theta * scale, 0.95)
        # cap mirrors the ins_theta cap: past it a stronger -g would make
        # column deletion effectively impossible while insertions saturate
        # at 0.95, an asymmetry users tuning -g don't expect (ADVICE r3)
        self.del_beta = min(del_beta * scale, 2.5)
        # -m/-x/-g reach the device engine as score-weighted voting
        # (alpha per layer, _accumulate_votes) on top of the -g emission
        # scaling; identity at the reference defaults
        self.scores = (match, mismatch, gap)
        # Batch count (reference -c N, cudapolisher.cpp:215-228): windows
        # are LPT-split into N groups, every group's whole refinement loop
        # is dispatched before the first result is fetched (JAX async
        # dispatch), so host packing overlaps device compute.
        self.num_batches = max(1, num_batches)
        # SWAR-packed forward DP (int16x2 score lanes); bit-identical
        # outputs, guarded per geometry by swar.swar_fits and globally
        # by the swar_ok probe — the knob exists for A/B measurement
        self.use_swar = use_swar
        # memory backpressure (round 12): the shard runner's
        # degradation ladder halves the effective pair-arena/group
        # capacity on a device RESOURCE_EXHAUSTED and re-dispatches —
        # output bytes are invariant to grouping, only the per-launch
        # working set shrinks. 1 = full capacity; doubled per
        # reduce_capacity() call up to _MAX_CAPACITY_SCALE.
        self.capacity_scale = 1
        # sanitizer: per-engine shadow sampler for the refine loop (the
        # first SWAR group of every run is always checked) — the
        # consensus-side analog of TpuAligner._shadow
        self._shadow = sanitize.ShadowSampler()
        self._warmup = None
        # shapes already submitted for warm-up compilation: the
        # resident polishing service calls warmup_async per admitted
        # job (so a NEW geometry starts compiling while the job waits
        # in queue), and repeat geometries — the service's whole point
        # — must cost nothing, not a redundant background compile
        self._warmed_shapes: set = set()
        # wavefront_steps: executed (post-gating) DP anti-diagonal steps,
        # the honest numerator for utilization estimates (bench.py);
        # lanes_occupied/lanes_total/groups/group_windows: real packing
        # efficiency of every dispatched pair arena (occupied lanes =
        # sum of real layer lengths, total = B x Lq per launch) — the
        # round-10 occupancy telemetry that replaces the coarse
        # consensus_vpu_util_est
        self.stats = {"device_windows": 0, "fallback_windows": 0,
                      "dropped_layers": 0, "sweep_truncated": 0,
                      "ins_overflow": 0, "passthrough": 0,
                      "stage_b_windows": 0, "wavefront_steps": 0,
                      "lanes_occupied": 0, "lanes_total": 0,
                      "groups": 0, "group_windows": 0,
                      "lane_upload_saved_bytes": 0}
        # per-window attribution of the ins_overflow counter (round 19,
        # keyed by result index): the r05 bench showed a bare 265 with
        # no way to tell WHICH window's insertion density tripped the
        # uncapped-scatter fallback — kept out of ``stats`` so numeric
        # consumers (bench JSON, stat-reset loops) stay untouched
        self.ins_overflow_by_window: dict = {}

    # the floor keeps groups large enough that per-group fixed costs
    # (fetch round trips) stay amortized: 16x reduction is already a
    # 94% working-set cut — past that the device is simply too small
    _MAX_CAPACITY_SCALE = 16

    @property
    def group_pairs_cap(self) -> int:
        """Pairs per device group under the current backpressure scale
        (``MAX_GROUP_PAIRS`` at scale 1)."""
        return max(2048, MAX_GROUP_PAIRS // self.capacity_scale)

    @property
    def arena_lanes_cap(self) -> int:
        """Ragged lane-arena budget under the current backpressure
        scale (``ARENA_LANES`` at scale 1)."""
        return max(2048 * 1024, ARENA_LANES // self.capacity_scale)

    def cap_pairs_for(self, L: int, band: int) -> int:
        """Greedy-fill pair budget for one ragged bucket: the lane
        arena (fixed, until OOM backpressure halves it) divided by the
        bucket's lane width — short windows pack more pairs per group,
        the whole point of ragged packing."""
        return max(2048, min(self.arena_lanes_cap // (L + band),
                             4 * self.group_pairs_cap))

    @staticmethod
    def bucket_L_for(L_req: int) -> Optional[int]:
        """THE power-of-two lane-width rule: the smallest pow2 bucket
        >= ``L_req`` (floor 256), capped at the device insertion-payload
        ceiling; None when it cannot fit.  Shared by the ragged
        stream's per-window bucketing (``_ConsensusStream._bucket_L``)
        and :meth:`_warmup_shapes`, so the dispatch and warm-up
        geometries derive from one formula (the ``warmup-coverage``
        lint checks exactly this)."""
        max_dev_L = (1 << 18) // (K_INS * CH) - GROW
        L = 256
        while L < L_req:
            if L >= max_dev_L:
                return None
            L = min(L * 2, max_dev_L)
        return L

    def reduce_capacity(self) -> bool:
        """Halve the pair-arena/group capacity (device-OOM
        backpressure). Returns False once at the floor — the caller's
        ladder then falls through to the CPU engines. Grouping never
        changes output bytes (windows are independent; the vote
        accumulation is exact at any batch size), so a reduced
        re-dispatch is byte-identical, just smaller."""
        if self.capacity_scale >= self._MAX_CAPACITY_SCALE:
            return False
        self.capacity_scale *= 2
        metrics.set_gauge("consensus.capacity_scale", self.capacity_scale)
        metrics.inc("faults.backpressure_halvings")
        return True

    def pack_metrics(self) -> dict:
        """Derived occupancy view of :attr:`stats` (zeros before any
        launch): ``pack_efficiency`` = occupied / total pair-arena
        lanes, ``pad_fraction`` = 1 - efficiency, ``windows_per_group``
        = mean windows per dispatched group."""
        tot = self.stats.get("lanes_total", 0)
        eff = self.stats.get("lanes_occupied", 0) / tot if tot else 0.0
        grp = self.stats.get("groups", 0)
        wpg = self.stats.get("group_windows", 0) / grp if grp else 0.0
        return {"pack_efficiency": round(eff, 4),
                "pad_fraction": round(1.0 - eff, 4) if tot else 0.0,
                "windows_per_group": round(wpg, 2),
                "groups": grp}

    # -------------------------------------------------------------- public

    def run(self, windows, trim: bool, progress=None) -> List[bool]:
        """Consensus over a window batch. Default routing is the ragged
        packer (:meth:`stream` — per-size-bucket geometry with greedy
        arena fill); ``use_ragged=False`` / ``RACON_TPU_RAGGED=0`` or a
        device mesh take the padded single-geometry path. Outputs are
        bit-identical across the two (windows are independent and the
        vote accumulation is exact at any grouping)."""
        if self.use_ragged and self.mesh is None:
            sess = self.stream(trim)
            sess.feed(windows)
            return sess.finish(progress=progress)
        before = dict(self.stats)
        out = self._run_padded(windows, trim, progress)
        self._warn_dropped(before)
        return out

    def stream(self, trim: bool, band_hint: int = 0):
        """Open a ragged streaming session (round 10): ``feed()`` packs
        and **asynchronously dispatches** full groups as window ranges
        arrive — host packing/fetch/emit overlaps device compute through
        the in-flight launch pipeline — and ``finish()`` drains, runs
        stage B and the CPU fallback, and returns the flags for every
        fed window in feed order. The ``Polisher.run()`` bounded queue
        feeds this directly, so the device never idles on the host
        between window ranges (double-buffered dispatch). Returns None
        when the ragged packer is unavailable (mesh runs, flag off) —
        callers then fall back to per-batch :meth:`run` calls.

        ``band_hint``: optional backbone-length upper bound used to
        freeze the alignment band before the full window set has been
        fed (the padded path derives band from the global live maximum;
        a streaming caller that knows its window length passes it here
        so both surfaces pick the same band)."""
        if not self.use_ragged or self.mesh is not None:
            return None
        return _ConsensusStream(self, trim, band_hint)

    def _warn_dropped(self, before: dict) -> None:
        """One-line per-run visibility for silently dropped layers
        (scale_stats.dropped_layers was 4943 at BENCH_r05 with no
        warning): depth-cap drops and rejected layer alignments both
        land in the counter."""
        d = self.stats["dropped_layers"] - before.get("dropped_layers", 0)
        if d > 0:
            from ..utils.logger import warn
            warn(f"consensus: {d} layer alignments dropped this run "
                 f"(voting depth cap {self.max_depth} and/or rejected "
                 f"alignments) — see consensus_stats.dropped_layers")

    def _run_padded(self, windows, trim: bool, progress=None) -> List[bool]:
        results: List[Optional[bool]] = [None] * len(windows)
        works: List[_Work] = []
        for i, win in enumerate(windows):
            if win.layer_count + 1 < 3:
                win.consensus = win.backbone
                results[i] = False
                self.stats["passthrough"] += 1
            else:
                works.append((i, _Work(win, self.max_depth, self.stats)))

        live = [(i, w) for i, w in works if w.n_layers >= 2]
        for i, w in works:
            if w.n_layers < 2:
                results[i] = None  # CPU fallback

        if live:
            max_bb = max(len(w.backbone) for _, w in live)
            band, L, Lq, Lb = self._bucket_geometry(max_bb)
            self.stats["band"] = band
            # windows whose layers exceed the pair buffer (or backbones the
            # backbone buffer) go to the CPU fallback via results[i] None
            live = [(i, w) for i, w in live
                    if w.max_layer_len <= Lq and len(w.backbone) <= Lb]

        if live:
            # anti-diagonal sweep bound: longest real pair plus span-growth
            # slack (dead wavefronts past the last finish are pure waste;
            # a span that outgrows the slack drops that pair's votes for
            # the round, like a band escape)
            max_nm = max(
                int(np.max(w.lens + np.minimum(w.ends - w.begins + 65,
                                               Lb)))
                for _, w in live)
            max_n = max(w.max_layer_len for _, w in live)
            steps, Lq2 = self._sweep_geometry(Lq, max_nm, max_n)
            from ..parallel import partition_balanced
            total_pairs = sum(w.n_layers for _, w in live)
            n_groups = max(self.num_batches,
                           -(-total_pairs // self.group_pairs_cap))
            if n_groups == 1:
                groups = [list(live)]
            else:
                bins = partition_balanced([w.n_layers for _, w in live],
                                          n_groups)
                groups = [[live[i] for i in b] for b in bins if b]
            # bounded pipeline: at most inflight_cap+1 groups'
            # inputs/state live on device at once (launch group k+1,
            # then fetch the oldest once the cap is exceeded); the big
            # per-round intermediates exist only inside the single
            # executing program — the MAX_INFLIGHT_BYTES budget is the
            # analog of cudapoa's fixed per-batch memory
            # (cudapolisher.cpp:219-228), sized for the tunnel's
            # per-round-trip latency instead of GPU RAM
            total_units = len(groups) + 1
            self._last_total_units = total_units
            done_units = 0
            inflight = []
            # two-stage refinement: stage A runs the first STAGE_A_ROUNDS
            # at full group size; windows still unconverged after it are
            # re-packed (with their refined backbones and remapped spans)
            # into far smaller stage-B groups for the remaining rounds.
            # Single-group runs skip the split: a lone group's stage-B
            # launch cannot coalesce anything, so the split only adds a
            # tunnel round trip there — the monolithic dispatch with the
            # in-loop early exit is strictly better.
            two_stage = self.rounds > STAGE_A_ROUNDS and len(groups) > 1
            survivors = [] if two_stage else None
            ra = min(self.rounds, STAGE_A_ROUNDS) if two_stage \
                else self.rounds
            # per-launch resident bytes: packed pair inputs (the qpw
            # uint16 lanes are 2*Lq bytes/pair — codes and weights
            # travel in ONE array; +24 covers n/bg/ed/win_of/real) PLUS
            # the per-window state and coalesced-fetch arrays each
            # un-fetched launch pins (bcodes u8 + covs/mat i32 +
            # bweights f32 ~ 13 bytes per backbone column, padded to
            # the worst group's power-of-two window count)
            max_wins = max(len(g) for g in groups)
            nWp_max = self._pow2_at_least(max_wins + 1)
            group_bytes = ((2 * Lq + 24) * self.group_pairs_cap
                           + 16 * Lb * nWp_max)
            inflight_cap = max(self.num_batches,
                               MAX_INFLIGHT_BYTES // max(group_bytes, 1))
            for g in groups:
                la = self._launch_group(g, Lq, Lb)
                la["geom"] = (Lq, Lb, steps, Lq2)
                la["band"] = band
                la["rounds"] = ra
                self._rounds(la, Lq, Lb, steps, Lq2)
                done_units += 1
                if progress is not None:
                    # ticks show groups entering the device pipeline
                    # (dispatch is async; only fetches block — syncing
                    # mid-group would reintroduce the tunnel round-trips
                    # this engine exists to avoid)
                    progress(done_units, total_units)
                inflight.append(la)
                if len(inflight) > inflight_cap:
                    self._finish_group(inflight.pop(0), trim, results,
                                       collect=survivors)
            for la in inflight:
                self._finish_group(la, trim, results, collect=survivors)
            if survivors:
                self._run_stage_b(survivors, trim, results,
                                  Lq, Lb, steps, Lq2, band)

        cpu_idx = [i for i, r in enumerate(results) if r is None]
        if cpu_idx:
            self.stats["fallback_windows"] += len(cpu_idx)
            metrics.inc("consensus.fallback_windows", len(cpu_idx))
            if self.fallback is None:
                raise RuntimeError(
                    f"{len(cpu_idx)} windows rejected, no CPU fallback")
            flags = self.fallback.run([windows[i] for i in cpu_idx], trim)
            for i, f in zip(cpu_idx, flags):
                results[i] = f
        if progress is not None:
            # close the bar with the same denominator the in-loop ticks
            # used (falls back to a single unit when nothing was live)
            total_units = getattr(self, "_last_total_units", 1)
            progress(total_units, total_units)
        return [bool(r) for r in results]

    # ----------------------------------------------------------- geometry

    def _bucket_geometry(self, max_bb: int):
        """Static kernel geometry from the longest backbone — THE single
        source of truth shared by :meth:`run` and :meth:`warmup_async`
        (drift between them would silently waste the warm-up compile).

        The alignment band scales with the window length (cudapoa's
        banded width is proportional to its matrix size too): a fixed
        512-lane band caps acceptable per-layer edits at 256, which
        w>=1000 windows at ONT divergence routinely exceed — those
        layers' alignments were dropped wholesale, the r4 w=1000 quality
        cliff (device 2591 vs CPU 1289 with ~1.2k dropped alignments).
        Identity for <=512 bp windows, so every recorded w=500 golden is
        untouched. Device ceiling: the packed insertion payload holds
        addr << 13 in an int32, so Lb*K_INS*CH must fit 18 bits
        (Lb <= 8192); longer backbones take the CPU fallback like any
        other reject."""
        band = min(self.band * -(-max_bb // 512), 4096)
        max_dev_L = (1 << 18) // (K_INS * CH) - GROW
        L = max(256, min(-(-max_bb // 256) * 256, max_dev_L))
        Lq = L + band
        Lb = min(L + GROW, Lq)  # backbone buffer (span fit: Lb <= Lq)
        return band, L, Lq, Lb

    @staticmethod
    def _sweep_geometry(Lq: int, max_nm: int, max_n: int):
        """Sweep bound and vote-kernel query width, both multiples of
        128 (the Pallas kernels chunk/flush at 128-lane granularity and
        statically require it). Shared by :meth:`run` and
        :meth:`warmup_async` like :meth:`_bucket_geometry`."""
        steps = -(-min(-(-max_nm // 128) * 128, 2 * Lq) // 128) * 128
        Lq2 = min(Lq, -(-max_n // 128) * 128)
        return steps, Lq2

    # ------------------------------------------------------------- warm-up

    @staticmethod
    def _pow2_at_least(x: int) -> int:
        p = 1
        while p < max(1, x):
            p *= 2
        return p

    def _warmup_shapes(self, window_length: int, est_pairs: int,
                       est_windows: int, est_layer_len: int,
                       est_contigs: int):
        """The refinement-loop shapes a run is expected to dispatch, as
        ``(Lq, Lb, band, steps, Lq2, B, nWp, rounds)`` tuples — ONE
        source of truth consumed by :meth:`warmup_async`, derived with
        the same geometry rules :meth:`run` / :class:`_ConsensusStream`
        use."""
        band, L, Lq, Lb = self._bucket_geometry(window_length)
        depth = max(1.0, est_pairs / max(1, est_windows))
        shapes = []

        def add(L_b, pairs, wins, rounds):
            lq = L_b + band
            lb = min(L_b + GROW, lq)
            ell = min(est_layer_len or window_length + 64, lq)
            max_nm = ell + min(ell + 64, lb)
            steps, Lq2 = self._sweep_geometry(lq, max_nm, ell)
            shapes.append((lq, lb, band, steps, Lq2,
                           self._pow2_at_least(pairs),
                           self._pow2_at_least(wins + 1), rounds))

        if not self.use_ragged:
            cap = self.group_pairs_cap
            n_groups = max(self.num_batches, -(-est_pairs // cap))
            rounds = (min(self.rounds, STAGE_A_ROUNDS)
                      if self.rounds > STAGE_A_ROUNDS and n_groups > 1
                      else self.rounds)
            add(L, -(-est_pairs // n_groups),
                -(-est_windows // n_groups), rounds)
            return shapes

        # ragged stream geometry: windows bucket by their own
        # power-of-two lane width and groups greedy-fill the arena, so
        # the dominant bucket's FULL groups close just under
        # cap_pairs_for(L) and pad to pow2(cap) — est_pairs/n_groups
        # undershoots that shape whenever the estimate is not an exact
        # multiple of the cap, wasting the warm compile precisely on
        # big runs. A run smaller than one arena dispatches a single
        # group of everything at the full round budget.
        max_dev_L = (1 << 18) // (K_INS * CH) - GROW
        # the dominant bucket width through THE shared pow2 rule (the
        # L_req is capped at the device ceiling, so this never rejects)
        Ld = self.bucket_L_for(min(window_length, max_dev_L))
        cap = self.cap_pairs_for(Ld, band)
        if est_pairs > cap:
            wins = min(est_windows, max(1, int(cap / depth)),
                       MAX_GROUP_WINDOWS)
            # full groups dispatch with more work expected -> stage A
            rounds = (min(self.rounds, STAGE_A_ROUNDS)
                      if self.rounds > STAGE_A_ROUNDS else self.rounds)
            add(Ld, cap, wins, rounds)
        else:
            add(Ld, est_pairs, min(est_windows, MAX_GROUP_WINDOWS),
                self.rounds)
        # contig-tail windows (<= one per contig, shorter than the
        # window length) coalesce in the half-width bucket and flush as
        # one lone full-budget group at finish
        if est_contigs > 0 and Ld > 256 and est_pairs > cap:
            # capped like any greedy-filled group: a fragmented assembly
            # (10^5 contigs) must not warm a multi-GB batch the stream
            # would never dispatch
            t_pairs = min(max(1, int(est_contigs * depth)),
                          self.cap_pairs_for(Ld // 2, band))
            add(Ld // 2, t_pairs, min(est_contigs, MAX_GROUP_WINDOWS),
                self.rounds)
        return shapes

    def warmup_async(self, window_length: int, est_pairs: int,
                     est_windows: int, est_layer_len: int = 0,
                     est_contigs: int = 0):
        """Background warm-up compilation of the expected refinement-loop
        shapes. The first consensus compile (~16 s) used to land inside
        ``polish()``; ``Polisher.initialize`` calls this on a thread
        while it aligns overlaps, so ``polish()`` starts hot.

        Derives the same static geometry :meth:`run` /
        :class:`_ConsensusStream` compute — for a ragged engine that is
        the power-of-two *bucket* shapes the stream will actually
        dispatch (the dominant bucket's greedy-filled full-group shape,
        plus the half-width contig-tail bucket when ``est_contigs`` is
        given), not the padded single geometry — and executes the jitted
        loop once per shape on zero state: ``win_real`` is all-false, so
        the device loop exits before round 1 and each shape costs
        exactly one compile (which the persistent XLA cache then also
        remembers across runs). Runs under the engine's pinned device
        (:meth:`_pinned`), so per-chip engines warm their own chip. A
        wrong estimate wastes a background compile and nothing else:
        run()'s own shapes still compile on first use. Returns the
        thread (for tests), or None when skipped (mesh runs, zero
        estimates, every derived shape already warmed — repeat calls
        with the same geometry are deliberately free, so the resident
        service can warm per admitted job)."""
        if self.mesh is not None or est_pairs <= 0:
            return None
        shapes = [s for s in self._warmup_shapes(
            window_length, est_pairs, est_windows, est_layer_len,
            est_contigs) if s not in self._warmed_shapes]
        if not shapes:
            return None
        self._warmed_shapes.update(shapes)

        def _compile_one(Lq, Lb, band, steps, Lq2, B, nWp, rounds):
            # the availability probes themselves compile and run
            # kernels, so they belong on this thread too — the whole
            # point is keeping the caller's critical path clear
            from .swar import swar_fits, swar_ok
            sw = self.use_swar and swar_fits(Lq) and swar_ok()
            use_pallas = self._use_pallas((Lq, band, steps, Lb, Lq2))
            if use_pallas:
                from .pallas_nw import pallas_swar_ok
                sw = sw and pallas_swar_ok()
            static = (jnp.zeros((B,), jnp.int32),
                      jnp.zeros((B, Lq), jnp.uint16),
                      jnp.full((B,), nWp - 1, jnp.int32),
                      jnp.zeros((B,), bool))
            state = (jnp.zeros((B,), jnp.int32),
                     jnp.zeros((B,), jnp.int32),
                     jnp.zeros((nWp, Lb), jnp.uint8),
                     jnp.zeros((nWp, Lb), jnp.float32),
                     jnp.zeros((nWp,), jnp.int32),
                     jnp.zeros((nWp, Lb), jnp.int32),
                     jnp.zeros((nWp,), bool),
                     jnp.zeros((nWp,), bool),
                     jnp.zeros((nWp,), bool),
                     jnp.zeros((1, 4 + nWp), jnp.int32))
            out = _refine_loop_packed(
                *static, *state, jnp.float32(self.ins_theta),
                jnp.float32(self.del_beta), rounds=rounds,
                n_windows=nWp, max_len=Lq, band=band, Lb=Lb,
                K=K_INS, steps=steps, use_pallas=use_pallas,
                use_swar=sw, Lq2=Lq2, scores=self.scores,
                matmul_votes=self.use_matmul_votes)
            # resident lane-ingest root, warmed with the SAME pow2 pool
            # rule the uploader pads to (nw._pow2_pool) — a size
            # mismatch costs one background compile of a tiny gather
            from .nw import _pow2_pool
            gat = _gather_qpw_rows(
                jnp.zeros((_pow2_pool(Lq * B),), jnp.uint16),
                jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                Lq=Lq)
            jax.block_until_ready(out[10])
            jax.block_until_ready(gat)

        def _compile():
            try:
                with self._pinned():
                    for shape in shapes:
                        _compile_one(*shape)
            except Exception as e:  # warm-up is an optimization, never fatal
                from ..utils.logger import log_swallowed
                log_swallowed("poa: background warm-up compile failed "
                              "(polish will compile on first use)", e)

        import threading
        # fire-and-forget by design: the warm-up is a droppable
        # optimization (its own except arm says so) — a daemon thread
        # killed at exit loses nothing but a speculative compile, and
        # the engine it warms outlives it
        # graftlint: disable=thread-lifecycle (droppable best-effort warm-up; daemon dies harmlessly at exit)
        self._warmup = threading.Thread(target=_compile, daemon=True,
                                        name="racon-tpu-warmup")
        self._warmup.start()
        return self._warmup

    # -------------------------------------------------------------- device

    def _launch_group(self, live, Lq, Lb, overrides=None):
        """Span-wrapped :meth:`_launch_group_impl` — the host-pack half
        of the consensus dispatch pipeline."""
        with self._pinned(), obs.span("poa.pack", windows=len(live)):
            return self._launch_group_impl(live, Lq, Lb, overrides)

    def _rounds(self, launch, Lq, Lb, steps, Lq2=0) -> None:
        """Span-wrapped :meth:`_rounds_impl` — the async kernel dispatch
        of a group's whole refinement loop (and the ``consensus.dispatch``
        fault-injection site: a real device OOM surfaces here as a
        RESOURCE_EXHAUSTED, which is exactly what the injected one
        mimics)."""
        faults.check("consensus.dispatch")
        with self._pinned(), obs.span("poa.dispatch", pairs=launch["B"]):
            self._rounds_impl(launch, Lq, Lb, steps, Lq2)

    def _finish_group(self, launch, trim: bool, results,
                      retried: bool = False, collect=None) -> None:
        """Span-wrapped :meth:`_finish_group_impl` — the blocking fetch
        + decode half (a retry re-dispatch nests under this span)."""
        with self._pinned(), obs.span("poa.fetch", windows=launch["nWp"]):
            self._finish_group_impl(launch, trim, results,
                                    retried=retried, collect=collect)

    def _run_stage_b(self, survivors, trim, results, Lq, Lb, steps,
                     Lq2, band) -> None:
        """Span-wrapped :meth:`_run_stage_b_impl`."""
        with self._pinned(), obs.span("poa.stage_b",
                                      windows=len(survivors)):
            self._run_stage_b_impl(survivors, trim, results, Lq, Lb,
                                   steps, Lq2, band)

    def _pack_shard(self, items, Lq, B, nWp, Lb, overrides=None,
                    allow_dev=False):
        """Pack one shard's windows into fixed-shape pair/window arrays.

        ``items`` is a list of ``(result_index, _Work)``; pair rows beyond
        the shard's real pairs vote into the sink window ``nWp - 1``.
        ``overrides`` (stage-B repack) maps a result index to that
        window's fetched stage-A state ``(bcodes_row, blen, covs_row,
        ever, bg_per_layer, ed_per_layer)`` so the window resumes from
        its refined backbone and remapped spans instead of restarting.

        With ``allow_dev`` (single-shard, unpinned, meshless launches)
        and every layer coming from ONE columnar store that carries a
        device-resident pool (``store.dev_qpw``, uploaded by the
        resident dataflow), the lane block is NOT host-gathered: the
        third return value is ``(dev_pool, src0, lens)`` full-B gather
        metadata for :func:`_gather_qpw_rows` and the host ``qpw`` stays
        zeros. Otherwise the third return is None.
        """
        n = np.ones(B, np.int32)
        # packed layer lanes: weight << 3 | code per base (codes 3 bits,
        # phred weights <= 93 in 7) — codes and weights travel as ONE
        # uint16 array, the format both vote emitters consume directly
        qpw = np.zeros((B, Lq), np.uint16)
        bg = np.zeros(B, np.int32)
        ed = np.zeros(B, np.int32)
        win_of = np.full(B, nWp - 1, np.int32)  # padding -> sink window
        real = np.zeros(B, bool)
        dev_spec = None

        counts = np.array([w.n_layers for _, w in items], np.int64)
        k = int(counts.sum())
        if k:
            # per-pair metadata straight from the works' flat arrays —
            # no per-layer Python loop in either storage mode
            offs = np.zeros(len(items) + 1, np.int64)
            np.cumsum(counts, out=offs[1:])
            lens = np.concatenate([w.lens for _, w in items])
            bb_len = np.repeat([len(w.backbone) for _, w in items], counts)
            n[:k] = lens
            bg[:k] = np.minimum(np.concatenate(
                [w.begins for _, w in items]), bb_len - 1)
            ed[:k] = np.minimum(np.concatenate(
                [w.ends for _, w in items]), bb_len - 1)
            win_of[:k] = np.repeat(np.arange(len(items)), counts)
            real[:k] = True

            # columnar windows: ONE vectorized pool gather per store
            # lands every layer's finished uint16 lanes (codes + phred
            # weights were packed once at store build)
            by_store = {}
            legacy = []
            for wi, (_, w) in enumerate(items):
                if not w.n_layers:
                    continue
                if w.store is not None:
                    by_store.setdefault(id(w.store), []).append(wi)
                else:
                    legacy.append(wi)
            for wis in by_store.values():
                store = items[wis[0]][1].store
                rows = np.concatenate([items[wi][1].rows for wi in wis])
                dest = np.concatenate(
                    [np.arange(offs[wi], offs[wi + 1]) for wi in wis])
                if (allow_dev and len(by_store) == 1 and not legacy
                        and store.dev_qpw is not None):
                    # resident dataflow: ship 8-byte gather rows, not
                    # 2*Lq-byte lanes — the device reads the pool it
                    # already holds
                    src0_full = np.zeros(B, np.int32)
                    lens_full = np.zeros(B, np.int32)
                    src0_full[dest] = store.src[rows]
                    lens_full[dest] = store.length[rows]
                    dev_spec = (store.dev_qpw, src0_full, lens_full)
                else:
                    qpw[dest] = store.gather_qpw(rows, Lq)

            # hand-built windows (tests, benches): the round-7 join-and-
            # LUT path over just their layers
            if legacy:
                lay = [(s, q) for wi in legacy
                       for s, q, _, _ in items[wi][1].layers]
                cat = np.frombuffer(b"".join(s for s, _ in lay), np.uint8)
                codes_cat = _CODE_LUT[cat]
                llens = np.array([len(s) for s, _ in lay], np.int64)
                starts = np.concatenate(([0], np.cumsum(llens)[:-1]))
                pos = np.arange(Lq)[None, :]
                valid = pos < llens[:, None]
                src = starts[:, None] + np.minimum(pos, llens[:, None] - 1)
                qual_cat = np.frombuffer(
                    b"".join((q if q is not None else b"\x22" * len(s))
                             for s, q in lay), np.uint8)
                # integral weights: phred-33 (clipped at 0 — a quality
                # byte below '!' would otherwise wrap) or 1 for
                # no-quality
                weights = np.maximum(qual_cat[src].astype(np.int16) - 33, 0)
                has_q = np.array([q is not None for _, q in lay])
                weights = np.where(has_q[:, None], weights, 1)
                dest = np.concatenate(
                    [np.arange(offs[wi], offs[wi + 1]) for wi in legacy])
                qpw[dest] = np.where(
                    valid,
                    (weights.astype(np.uint16) << 3) | codes_cat[src],
                    0).astype(np.uint16)

        bcodes = np.zeros((nWp, Lb), np.uint8)
        bweights = np.zeros((nWp, Lb), np.float32)
        blen = np.zeros(nWp, np.int32)
        covs = np.zeros((nWp, Lb), np.int32)
        ever = np.zeros(nWp, bool)
        for wi, (_, w) in enumerate(items):
            bb = w.backbone
            bcodes[wi, :len(bb)] = _CODE_LUT[np.frombuffer(bb, np.uint8)]
            if w.bqual is not None:
                # x64: layer votes carry the q6 alpha scale (64 == 1.0),
                # so backbone votes are pre-scaled to compete at par
                bweights[wi, :len(bb)] = 64.0 * (
                    np.frombuffer(w.bqual, np.uint8).astype(np.float32)
                    - 33.0)
            blen[wi] = len(bb)

        if overrides:
            off = 0
            for wi, (ri, w) in enumerate(items):
                kw = w.n_layers
                st = overrides.get(ri)
                if st is not None:
                    st_bc, st_bl, st_cov, st_ever, st_bg, st_ed = st
                    bcodes[wi] = st_bc
                    blen[wi] = st_bl
                    covs[wi] = st_cov
                    ever[wi] = st_ever
                    if st_ever:
                        # a refined backbone carries no phred
                        bweights[wi] = 0.0
                    bg[off:off + kw] = st_bg
                    ed[off:off + kw] = st_ed
                off += kw

        return (n, qpw, win_of, real, bg, ed), \
               (bcodes, bweights, blen, covs, ever), dev_spec

    def _launch_group_impl(self, live, Lq, Lb, overrides=None):
        """Pack one window group (per-mesh-shard when a mesh is set — pairs
        of a window never cross shards, so votes stay shard-local) into the
        device-resident refinement state. ``overrides`` carries fetched
        stage-A state for a stage-B repack (see :meth:`_pack_shard`)."""
        from ..parallel import mesh_size, partition_balanced
        # graftlint: disable=warmup-coverage (mesh size is fixed at engine construction; warm-up runs on the same engine so its shapes see the same nd)
        nd = mesh_size(self.mesh)
        if nd == 1:
            shards = [list(live)]
        else:
            bins = partition_balanced([w.n_layers for _, w in live], nd)
            shards = [[live[i] for i in b] for b in bins]

        max_pairs = max(sum(w.n_layers for _, w in sh) for sh in shards)
        max_wins = max(len(sh) for sh in shards)
        # pow2 batch/window-count padding through the same helper the
        # warm-up derivation uses (warmup-coverage keeps them shared)
        B = self._pow2_at_least(max_pairs)
        nWp = self._pow2_at_least(max_wins + 1)

        # device-lane ingest gate: one shard, no mesh, no per-chip pin
        # (a pinned engine would gather across devices from the
        # polisher-uploaded pool) — the parity grids cover both sides
        allow_dev = nd == 1 and self.mesh is None and self.device is None
        packs = [self._pack_shard(sh, Lq, B, nWp, Lb, overrides,
                                  allow_dev=allow_dev)
                 for sh in shards]
        pair_np = [np.concatenate([p[0][a] for p in packs])
                   for a in range(6)]
        # occupancy telemetry (round 10): real lane occupancy of this
        # launch's pair arena — occupied = sum of real layer lengths,
        # total = padded rows x the bucket's lane width
        occupied = int(pair_np[0][pair_np[3]].sum())
        lanes = int(pair_np[0].shape[0]) * Lq
        self.stats["lanes_occupied"] += occupied
        self.stats["lanes_total"] += lanes
        self.stats["groups"] += 1
        self.stats["group_windows"] += len(live)
        # registry mirror: the heartbeat / run report read occupancy
        # from the one process-wide registry, not this engine's dict
        metrics.inc("consensus.lanes_occupied", occupied)
        metrics.inc("consensus.lanes_total", lanes)
        metrics.inc("consensus.groups")
        metrics.inc("consensus.group_windows", len(live))
        win_np = [np.concatenate([p[1][a] for p in packs])
                  for a in range(5)]
        # single-host: plain device puts; multi-host: every process packs
        # the (deterministic) full arrays and materializes only its
        # addressable shards of the global array
        from ..parallel import to_global
        put = ((lambda a: to_global(self.mesh, a)) if self.mesh is not None
               else jnp.asarray)
        dev_spec = packs[0][2] if allow_dev else None
        if dev_spec is not None:
            # resident lane ingest: the pool is already on device, so the
            # group's [B, Lq] uint16 lane block never crosses the link —
            # only the 8-byte-per-pair gather rows do
            pool_d, src0_full, lens_full = dev_spec
            qpw_dev = _gather_qpw_rows(pool_d, jnp.asarray(src0_full),
                                       jnp.asarray(lens_full), Lq=Lq)
            saved = 2 * B * Lq
            self.stats["lane_upload_saved_bytes"] += saved
            metrics.inc("dataflow.bytes_avoided", saved)
            metrics.inc("dataflow.lanes_device_groups")
            static = (put(pair_np[0]), qpw_dev, put(pair_np[2]),
                      put(pair_np[3]))
        else:
            static = tuple(put(a) for a in pair_np[:4])  # n qpw win_of real
        bg, ed = (put(pair_np[4]), put(pair_np[5]))
        bcodes, bweights, blen, covs, ever = (put(a) for a in win_np)
        zput = (lambda a: put(np.asarray(a)))
        frozen = zput(np.zeros(nd * nWp, bool))
        conv = zput(np.zeros(nd * nWp, bool))
        # telemetry row per shard: [dropped, sweep-truncated, ins-overflow,
        # executed wavefront steps, then nWp per-window overflow tallies]
        dropped = zput(np.zeros((nd, 4 + nWp), np.int32))
        state = [bg, ed, bcodes, bweights, blen, covs, ever, frozen, conv,
                 dropped]
        return {"shards": shards, "static": static, "state": state,
                "nWp": nWp, "nd": nd, "B": B, "overrides": overrides}

    def _rounds_impl(self, launch, Lq, Lb, steps, Lq2=0) -> None:
        """Dispatch a group's full refinement loop (no host sync).

        The Pallas availability probe runs at one small shape, so a Mosaic
        compile failure at the production shape (e.g. an exotic band or a
        VMEM overflow) is still possible — it surfaces synchronously at
        dispatch, and we fall back to the XLA kernels for that shape
        instead of aborting the polish (jit compilation is eager, so
        only compile errors are catchable here; numerics are covered by
        the probe's bit-exact comparison)."""
        from .swar import swar_fits, swar_ok
        sw = self.use_swar and swar_fits(Lq) and swar_ok()
        if self.use_swar and not swar_fits(Lq):
            # SWAR -> int32 re-dispatch (geometry outgrew the packed
            # lanes' overflow headroom) — counted like the aligner's
            metrics.inc("consensus.swar_guard_int32")
        base_key = (Lq, launch.get("band", self.band), steps, Lb, Lq2)
        swar_key = base_key + ("swar",)
        if self._use_pallas(base_key):
            from .pallas_nw import pallas_swar_ok
            sw_p = (sw and pallas_swar_ok()
                    and self._use_pallas(swar_key))
            key = swar_key if sw_p else base_key
            try:
                self._dispatch_rounds(launch, Lq, Lb, steps, Lq2, True,
                                      sw_p)
                launch["pallas_key"] = key  # blamed on a fetch fault
                return
            except Exception as e:
                from .. import sanitize
                sanitize.reraise_if_sanitizer(e)
                self._note_pallas_failure(key, e)
                # a packed-kernel-only fault must not cost the whole
                # Pallas path: retry the int32 Mosaic kernels first
                if sw_p and self._use_pallas(base_key):
                    try:
                        self._dispatch_rounds(launch, Lq, Lb, steps,
                                              Lq2, True, False)
                        launch["pallas_key"] = base_key
                        return
                    except Exception as e2:
                        from .. import sanitize
                        sanitize.reraise_if_sanitizer(e2)
                        self._note_pallas_failure(base_key, e2)
        launch["pallas_key"] = None
        self._dispatch_rounds(launch, Lq, Lb, steps, Lq2, False, sw)

    _STATE_NAMES = ("bg", "ed", "bcodes", "bweights", "blen", "covs",
                    "ever", "frozen", "conv", "dropped")

    def _dispatch_rounds(self, launch, Lq, Lb, steps, Lq2,
                         use_pallas, use_swar=False) -> None:
        pre_state = launch["state"]
        out = self._dispatch_loop(launch, pre_state, Lq, Lb, steps, Lq2,
                                  use_pallas, use_swar)
        launch["state"] = list(out[:10])
        if launch["nd"] == 1:
            launch["fetch2"] = out[10:12]
        if use_swar and self._shadow.should_shadow():
            # int32 shadow execution of the WHOLE refine loop from the
            # same pre-round state (the packed forward DP is the only
            # difference — its bit-exactness contract makes every output
            # comparable, telemetry included). Sampled per group, so the
            # sanitizer's cost stays bounded on long runs.
            shadow = self._dispatch_loop(launch, pre_state, Lq, Lb, steps,
                                         Lq2, use_pallas, False)
            from ..parallel import fetch_global
            sanitize.shadow_compare(
                fetch_global(list(out[:10])),
                fetch_global(list(shadow[:10])),
                self._STATE_NAMES,
                f"consensus SWAR group (Lq={Lq}, "
                f"band={launch.get('band', self.band)}, steps={steps})")

    def _dispatch_loop(self, launch, state, Lq, Lb, steps, Lq2,
                       use_pallas, use_swar):
        """One full refinement-loop dispatch from an explicit state (the
        shadow path re-runs the identical launch with ``use_swar`` off)."""
        static = launch["static"]
        rounds = launch.get("rounds", self.rounds)
        band = launch.get("band", self.band)
        theta = jnp.float32(self.ins_theta)
        beta = jnp.float32(self.del_beta)
        if launch["nd"] == 1:
            # single execution: rounds + the coalesced-fetch packing
            # (single-device only: the packed concat would force
            # cross-shard gathers under a mesh)
            return _refine_loop_packed(
                *static, *state, theta, beta, rounds=rounds,
                n_windows=launch["nWp"], max_len=Lq, band=band,
                Lb=Lb, K=K_INS, steps=steps, use_pallas=use_pallas,
                use_swar=use_swar, Lq2=Lq2, scores=self.scores,
                matmul_votes=self.use_matmul_votes)
        from ..parallel import sharded_refine_loop
        return sharded_refine_loop(
            self.mesh, static, state, theta, beta, rounds=rounds,
            n_windows_local=launch["nWp"], max_len=Lq, band=band,
            Lb=Lb, K=K_INS, steps=steps, use_pallas=use_pallas,
            use_swar=use_swar, Lq2=Lq2, scores=self.scores,
            matmul_votes=self.use_matmul_votes)

    def _run_stage_b_impl(self, survivors, trim, results, Lq, Lb, steps,
                          Lq2, band) -> None:
        """Remaining rounds for the stage-A stragglers, re-packed small.

        ``survivors`` is ``[(result_index, work, fetched_state), ...]``
        collected by :meth:`_finish_group` across ALL stage-A groups, so
        the handful of unconverged windows of a big run coalesce into one
        (or few) groups — B and n_windows shrink by the convergence
        factor (~30x on real data) while rounds 4+ compute the identical
        per-window fixed points (windows are independent; the vote
        accumulation is exact integer arithmetic at any batch size)."""
        rb = self.rounds - STAGE_A_ROUNDS
        live = [(i, w) for i, w, _ in survivors]
        overrides = {i: st for i, _, st in survivors}
        self.stats["stage_b_windows"] += len(live)
        total_pairs = sum(w.n_layers for _, w in live)
        n_groups = max(1, -(-total_pairs // self.group_pairs_cap))
        if n_groups == 1:
            groups = [live]
        else:
            from ..parallel import partition_balanced
            bins = partition_balanced([w.n_layers for _, w in live],
                                      n_groups)
            groups = [[live[i] for i in b] for b in bins if b]
        inflight = []
        for g in groups:
            la = self._launch_group(g, Lq, Lb, overrides=overrides)
            la["geom"] = (Lq, Lb, steps, Lq2)
            la["band"] = band
            la["rounds"] = rb
            self._rounds(la, Lq, Lb, steps, Lq2)
            inflight.append(la)
            if len(inflight) > self.num_batches:
                self._finish_group(inflight.pop(0), trim, results)
        for la in inflight:
            self._finish_group(la, trim, results)

    def _finish_group_impl(self, launch, trim: bool, results,
                           retried: bool = False, collect=None) -> None:
        """One host fetch per group; decode consensus bytes + trim.

        With ``collect`` (a list — stage A of a two-stage run), windows
        that are neither converged nor frozen are NOT decoded: their
        fetched state is appended to ``collect`` for the stage-B repack
        and their result stays pending.

        JAX dispatch is async, so a Pallas *runtime* fault (a DMA/VMEM
        fault on the real chip that the compile-time probe could not see)
        surfaces here at the fetch — note the shape and re-run the whole
        group on the XLA kernels instead of aborting the polish
        (ADVICE r3)."""
        shards, nWp = launch["shards"], launch["nWp"]
        # single-device groups fetch TWO coalesced arrays (_fetch_pack —
        # per-transfer tunnel latency dominates the bytes); mesh groups
        # fetch per array (bweights always stays on device)
        from ..parallel import fetch_global
        try:
            if "fetch2" in launch:
                mat, meta = fetch_global(list(launch["fetch2"]))
            else:
                (bg_d, ed_d, bcodes, _, blen, covs, ever, frozen, conv,
                 dropped) = launch["state"]
                fetch = [bcodes, blen, covs, ever, dropped]
                if collect is not None:  # straggler-resume state
                    fetch += [frozen, conv, bg_d, ed_d]
                fetched = fetch_global(fetch)
        except Exception as e:
            from .. import sanitize
            sanitize.reraise_if_sanitizer(e)
            Lq, Lb, steps, Lq2 = launch["geom"]
            if retried:
                raise
            self._note_pallas_failure(
                launch.get("pallas_key")
                or (Lq, launch.get("band", self.band), steps, Lb, Lq2), e)
            live = [item for sh in shards for item in sh]
            relaunch = self._launch_group(live, Lq, Lb,
                                          overrides=launch["overrides"])
            relaunch["geom"] = launch["geom"]
            relaunch["band"] = launch.get("band", self.band)
            # a stage-B repack resumes from its override state with the
            # remaining rounds; a stage-A (or continued-in-place) group
            # relaunches from the ORIGINAL backbones, so it must re-run
            # the FULL round budget and decode directly — handing it to
            # a second stage would double-refine, truncating would
            # under-refine
            if launch["overrides"] is not None:
                relaunch["rounds"] = launch.get("rounds", self.rounds)
            else:
                relaunch["rounds"] = self.rounds
                collect = None
            self._rounds(relaunch, Lq, Lb, steps, Lq2)
            self._finish_group(relaunch, trim, results, retried=True,
                               collect=collect)
            return
        if "fetch2" in launch:
            nWr = launch["nd"] * nWp
            ndt = launch["nd"] * (4 + nWp)
            B_all = launch["nd"] * launch["B"]
            bcodes = (mat & 7).astype(np.uint8)
            covs = mat >> 3
            offs = np.cumsum([nWr, nWr, nWr, nWr, ndt, B_all])
            blen, ever, frozen_h, conv_h, dropped, bg_h, ed_h = \
                np.split(meta, offs)
            ever = ever.astype(bool)
            dropped = dropped.reshape(launch["nd"], 4 + nWp)
        else:
            bcodes, blen, covs, ever, dropped = fetched[:5]
            if collect is not None:
                frozen_h, conv_h, bg_h, ed_h = fetched[5:]
        from .. import sanitize
        if sanitize.enabled():
            sanitize.check_consensus_canaries(
                bcodes, blen, covs, Lb=launch["geom"][1],
                context=f"consensus group (nWp={nWp})")
        if collect is not None:
            # decision point: repack the stragglers only when few survive;
            # a mostly-unconverged group (noisy data rarely reaches an
            # exact fixed point) continues its remaining rounds on the
            # state already resident on device — no repack, no re-upload
            n_real = sum(len(sh) for sh in shards)
            n_surv = 0
            for s, sh in enumerate(shards):
                for wi in range(len(sh)):
                    row = s * nWp + wi
                    if not conv_h[row] and not frozen_h[row]:
                        n_surv += 1
            if n_surv > STAGE_B_MAX_SURVIVOR_FRAC * n_real:
                Lq, Lb, steps, Lq2 = launch["geom"]
                launch["rounds"] = self.rounds - STAGE_A_ROUNDS
                self._rounds(launch, Lq, Lb, steps, Lq2)
                self._finish_group(launch, trim, results, retried=retried,
                                   collect=None)
                return
        self.stats["dropped_layers"] += int(dropped[:, 0].sum())
        self.stats["sweep_truncated"] += int(dropped[:, 1].sum())
        self.stats["ins_overflow"] += int(dropped[:, 2].sum())
        self.stats["wavefront_steps"] += int(dropped[:, 3].sum())
        metrics.inc("consensus.dropped_layers", int(dropped[:, 0].sum()))
        metrics.inc("consensus.sweep_truncated", int(dropped[:, 1].sum()))
        metrics.inc("consensus.ins_overflow", int(dropped[:, 2].sum()))
        metrics.inc("consensus.wavefront_steps", int(dropped[:, 3].sum()))
        # columns [4:] attribute the overflow counter to shard-local
        # window rows (accumulated across this launch's rounds)
        ovf_tail = dropped[:, 4:]
        B = launch["B"]
        for s, sh in enumerate(shards):
            off = 0  # pair-row offset within this shard's pack
            for wi, (i, w) in enumerate(sh):
                row = s * nWp + wi
                ovf = int(ovf_tail[s, wi])
                if ovf:
                    self.ins_overflow_by_window[i] = \
                        self.ins_overflow_by_window.get(i, 0) + ovf
                    metrics.inc("consensus.ins_overflow_windows")
                kw = w.n_layers
                p0 = s * B + off
                off += kw
                if (collect is not None and not conv_h[row]
                        and not frozen_h[row]):
                    collect.append((i, w, (
                        bcodes[row].copy(), int(blen[row]),
                        covs[row].copy(), bool(ever[row]),
                        bg_h[p0:p0 + kw].copy(), ed_h[p0:p0 + kw].copy())))
                    continue
                if not ever[row]:
                    results[i] = None  # no successful round -> CPU fallback
                    continue
                bl = int(blen[row])
                consensus = _BYTE_LUT[bcodes[row, :bl]].tobytes()
                if w.win.type == WindowType.TGS and trim:
                    # threshold uses the *voted* depth: layers beyond
                    # max_depth never vote, so counting them would make
                    # trimming a no-op on windows deeper than ~2x max_depth
                    avg_cov = min(w.n_seqs - 1, self.max_depth) // 2
                    good = np.flatnonzero(covs[row, :bl] >= avg_cov)
                    if len(good) and good[0] < good[-1]:
                        consensus = consensus[good[0]:good[-1] + 1]
                w.win.consensus = consensus
                results[i] = True
                self.stats["device_windows"] += 1
