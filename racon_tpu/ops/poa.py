"""Batched window consensus on TPU (cudapoa-equivalent).

Role: the accelerated consensus engine behind ``Polisher.polish`` — one
device batch processes (windows x layers) at once, the analog of a cudapoa
``Batch`` of POA groups (``src/cuda/cudabatch.cpp:54-62``).

Design (TPU-first): instead of porting cudapoa's irregular
one-block-per-group graph POA, consensus is computed as a
**quality-weighted pileup**:

1. every layer is globally aligned to its backbone span with the wavefront
   NW kernel from ``ops.nw`` (all windows' layers in one fixed-shape batch —
   thousands of concurrent alignments, the shape TPUs like);
2. a traceback variant walks each alignment on device and scatter-adds
   weighted votes (A/C/G/T/N/deletion per backbone column, plus K insertion
   slots per junction) into per-window count matrices;
3. consensus = per-column argmax over weighted base votes, a column
   dropped when deletion weight exceeds ``del_beta`` x the summed base
   weights, and insertion slot ``s`` emitted when its summed weight
   exceeds ``ins_theta`` x the column total (see ``_consensus_kernel``),
   with per-base unweighted coverage for the reference's TGS end-trimming
   contract (``src/window.cpp:118-139``).

Like the reference's GPU path, this engine is allowed to differ slightly
from the CPU spoa-semantics engine (upstream records separate CUDA goldens:
1385 vs CPU 1312, ``test/racon_test.cpp:312``); windows the device cannot
handle (oversize backbone/layers, depth, band escapes) fall back to the CPU
engine, mirroring ``StatusType`` rejects (``src/cuda/cudabatch.cpp:135-156``).

Emission thresholds (``ins_theta``/``del_beta``) and the refinement round
count were calibrated against the CPU engine on λ-phage: the recorded
device golden is 1384 vs CPU 1324 (+4.5%, PAF input, real TPU v5e),
matching the reference's own accelerated-path divergence (cudapoa 1385 vs
spoa 1312, +5.6%, ``test/racon_test.cpp:312``).

Engine caps (documented, per ADVICE round 1): insertion runs longer than
``K_INS`` collapse extra bases into the last slot, and insertions before
the first backbone column of a window (junction "-1") only have a vote
slot when the layer starts past column 0; refinement rounds recover most
of both effects.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .nw import _nw_wavefront_kernel, _walk_ops_kernel
from ..core.window import WindowType

# Alignment band for layer-vs-backbone-span alignment (layers are ~window
# sized; c=256 covers ~50% divergence at 500 bp).
BAND = 512
# Insertion slots tracked per backbone junction.
K_INS = 4
# Vote channels: A C G T N DEL (stride 8 for cheap addressing).
CH = 8
A, C, G, T, N_CODE, DEL = 0, 1, 2, 3, 4, 5

_CODE_LUT = np.full(256, N_CODE, dtype=np.uint8)
for i, b in enumerate(b"ACGT"):
    _CODE_LUT[b] = i
_BYTE_LUT = np.frombuffer(b"ACGTN-", dtype=np.uint8)

MAX_PAIR_DIRS_BYTES = 1024 * 1024 * 1024


@functools.partial(jax.jit,
                   static_argnames=("max_len", "band", "L", "K", "n_windows"))
def _vote_from_ops(ops, fi, fj, score, n, m, qcodes, qweights, begin, win_of,
                   *, n_windows: int, max_len: int, band: int, L: int, K: int):
    """Turn walked op codes into scatter-added weighted votes — vectorized.

    ops: uint8 [B, S] backward-walk op codes from ``_walk_ops_kernel``
    (0=M, 1=I, 2=D, >=3 done/stalled); qcodes/qweights: [B, max_len] layer
    base codes and weights; begin: [B] backbone-span start column; win_of:
    [B] owning window index.

    The walk position *before* step t is recovered with prefix sums of the
    consumed-query/-target indicators (no sequential re-walk), the
    insertion-run length with a prefix max over the last non-insertion
    step, and the layer base/weight lookups are one batched gather each —
    everything is [B, S] elementwise work, which XLA fuses into a handful
    of passes instead of S tiny scan steps.

    Returns (weighted [n_windows, L*(1+K)*CH] f32, unweighted same-shape
    i32, ok [B] bool). Vote layout: column votes at col*CH+ch, insertion
    slot s of junction col at (L + col*K + s)*CH + ch.
    """
    B, S = ops.shape
    Lq = max_len
    VOT = L * (1 + K) * CH

    is_M = ops == 0
    is_I = ops == 1
    is_D = ops == 2
    di = (is_M | is_I).astype(jnp.int32)   # consumed a query base
    dj = (is_M | is_D).astype(jnp.int32)   # consumed a target base
    # position before step t: (n, m) minus everything consumed earlier
    i_t = n[:, None] - jnp.cumsum(di, axis=1) + di
    j_t = m[:, None] - jnp.cumsum(dj, axis=1) + dj

    # ins_run at t = number of consecutive I steps immediately before t
    t_idx = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    last_ni = lax.cummax(jnp.where(~is_I, t_idx, -1), axis=1)
    last_ni_excl = jnp.concatenate(
        [jnp.full((B, 1), -1, jnp.int32), last_ni[:, :-1]], axis=1)
    ins_run = t_idx - 1 - last_ni_excl
    slot = jnp.minimum(ins_run, K - 1)

    qpos = jnp.clip(i_t - 1, 0, Lq - 1)
    base = jnp.take_along_axis(qcodes, qpos, axis=1).astype(jnp.int32)
    wgt = jnp.take_along_axis(qweights, qpos, axis=1).astype(jnp.float32)
    col = begin[:, None] + j_t - 1
    # vote target: M -> (col, base); D -> (col, DEL); I -> ins slot
    idx = jnp.where(
        is_M, col * CH + base,
        jnp.where(is_D, col * CH + DEL,
                  (L + col * K + slot) * CH + base))
    valid = (ops < 3) & (j_t >= 1) & (col >= 0) & (col < L)
    idx = jnp.where(valid, idx, VOT)  # sink
    w = jnp.where(valid, wgt, 0.0)

    ok = (fi == 0) & (fj == 0) & (score < (band // 2))
    wsv = w * ok[:, None].astype(jnp.float32)

    flat_idx = (win_of[:, None] * (VOT + 1) + idx).reshape(-1)
    weighted = jnp.zeros(n_windows * (VOT + 1), jnp.float32)
    weighted = weighted.at[flat_idx].add(wsv.reshape(-1))
    unweighted = jnp.zeros(n_windows * (VOT + 1), jnp.int32)
    unweighted = unweighted.at[flat_idx].add(
        (wsv.reshape(-1) > 0).astype(jnp.int32))
    weighted = weighted.reshape(n_windows, VOT + 1)[:, :VOT]
    unweighted = unweighted.reshape(n_windows, VOT + 1)[:, :VOT]
    return weighted, unweighted, ok


@functools.partial(jax.jit, static_argnames=("L", "K"))
def _consensus_kernel(weighted, unweighted, bcodes, bweights, blen,
                      ins_theta, del_beta, *, L: int, K: int):
    """Add backbone votes, then pick per-column and insertion winners.

    Emission rules (POA heaviest-bundle analogs, calibrated against the
    CPU engine on λ-phage):
    - a column emits its winning base unless the deletion weight exceeds
      ``del_beta`` x the summed base weights (reads voting *any* base
      jointly defend the column, as substitution variants occupy one
      aligned-ring position in the POA graph);
    - insertion slot ``s`` emits its winning base when the slot's summed
      weight (all bases — the slot is one graph node position, bases are
      its aligned ring) exceeds ``ins_theta`` x the column total.
    """
    n_windows = weighted.shape[0]
    cols = jnp.arange(L)

    w = weighted.reshape(n_windows, L * (1 + K), CH)
    uw = unweighted.reshape(n_windows, L * (1 + K), CH)
    col_votes = w[:, :L, :]      # [n, L, CH]
    ins_votes = w[:, L:, :].reshape(n_windows, L, K, CH)
    col_unw = uw[:, :L, :]
    ins_unw = uw[:, L:, :].reshape(n_windows, L, K, CH)

    # backbone's own votes (weight may be 0 for dummy quality -> still
    # contributes 1 to unweighted coverage, like a spoa sequence label)
    in_range = cols[None, :] < blen[:, None]
    bb_onehot = jax.nn.one_hot(bcodes, CH, dtype=jnp.float32)
    eps_w = jnp.maximum(bweights, 0.01)  # dummy-quality backbones still win
                                         # columns with no layer votes
    col_votes = col_votes + bb_onehot * (eps_w * in_range)[..., None]
    col_unw = col_unw + (bb_onehot * in_range[..., None]).astype(jnp.int32)

    base_winner = jnp.argmax(col_votes[:, :, :N_CODE + 1], axis=-1)
    base_total = col_votes[:, :, :N_CODE + 1].sum(-1)
    del_w = col_votes[:, :, DEL]
    winner = jnp.where(del_w > del_beta * base_total, DEL, base_winner)
    coverage = jnp.take_along_axis(col_unw, winner[..., None], -1)[..., 0]
    col_total = col_votes.sum(-1)

    ins_winner = jnp.argmax(ins_votes[:, :, :, :N_CODE + 1], axis=-1)
    ins_total = ins_votes[:, :, :, :N_CODE + 1].sum(-1)
    ins_cov = jnp.take_along_axis(ins_unw, ins_winner[..., None], -1)[..., 0]
    ins_emit = ins_total > ins_theta * col_total[:, :, None]

    return winner, coverage, ins_winner, ins_emit, ins_cov


def consensus_chain(qrp, tp, n, m, qcodes, qweights, begin, win_of,
                    bcodes, bweights, blen, ins_theta, del_beta, *,
                    n_windows: int, max_len: int, band: int, L: int, K: int):
    """Align + vote + pick-winners — the single source of truth for the
    consensus engine's kernel wiring, wrapped unchanged by the plain path
    (``TpuPoaConsensus._device_round``) and the ``shard_map`` path
    (``racon_tpu.parallel.sharded_consensus_round``). Returns
    ``(winner, coverage, ins_winner, ins_emit, ins_cov, ok)``."""
    packed, score = _nw_wavefront_kernel(qrp, tp, n, m,
                                         max_len=max_len, band=band)
    ops, fi, fj = _walk_ops_kernel(packed, n, m, max_len=max_len, band=band)
    weighted, unweighted, ok = _vote_from_ops(
        ops, fi, fj, score, n, m, qcodes, qweights, begin, win_of,
        n_windows=n_windows, max_len=max_len, band=band, L=L, K=K)
    out = _consensus_kernel(weighted, unweighted, bcodes, bweights, blen,
                            ins_theta, del_beta, L=L, K=K)
    return out + (ok,)


class _Work:
    """Mutable per-window state across refinement rounds."""

    __slots__ = ("win", "backbone", "bqual", "layers", "n_seqs", "covs")

    def __init__(self, win, max_depth, stats):
        self.win = win
        self.backbone = win.sequences[0]
        self.bqual = win.qualities[0]
        self.layers = []  # (seq, qual, begin, end)
        depth = min(len(win.sequences) - 1, max_depth)
        stats["dropped_layers"] += max(0, len(win.sequences) - 1 - max_depth)
        for li in range(1, depth + 1):
            b, e = win.positions[li]
            self.layers.append((win.sequences[li], win.qualities[li], b, e))
        self.n_seqs = len(win.sequences)
        self.covs = None


class TpuPoaConsensus:
    """Batched device consensus with CPU fallback for rejects.

    ``rounds`` controls iterative refinement: round r re-aligns every layer
    against the round r-1 consensus (with layer spans remapped through the
    emitted-column map), which recovers most of the gap between one-shot
    pileup voting and graph POA.
    """

    def __init__(self, match: int, mismatch: int, gap: int, fallback=None,
                 max_depth: int = 200, band: int = BAND, rounds: int = 5,
                 mesh=None, ins_theta: float = 0.25, del_beta: float = 0.6,
                 num_batches: int = 1):
        # match/mismatch/gap kept for interface parity; the pileup engine
        # votes by base weight rather than alignment score.
        self.fallback = fallback
        self.max_depth = max_depth
        self.band = band
        self.rounds = rounds
        self.mesh = mesh
        self.ins_theta = ins_theta
        self.del_beta = del_beta
        # Batch count (reference -c N, cudapolisher.cpp:215-228): windows
        # are LPT-split into N groups per refinement round, all dispatched
        # before the first result is fetched, so host packing overlaps
        # device compute.
        self.num_batches = max(1, num_batches)
        self.stats = {"device_windows": 0, "fallback_windows": 0,
                      "dropped_layers": 0, "passthrough": 0}

    # -------------------------------------------------------------- public

    def run(self, windows, trim: bool, progress=None) -> List[bool]:
        results: List[Optional[bool]] = [None] * len(windows)
        works: List[_Work] = []
        for i, win in enumerate(windows):
            if len(win.sequences) < 3:
                win.consensus = win.sequences[0]
                results[i] = False
                self.stats["passthrough"] += 1
            else:
                works.append((i, _Work(win, self.max_depth, self.stats)))

        live = [(i, w) for i, w in works if len(w.layers) >= 2]
        for i, w in works:
            if len(w.layers) < 2:
                results[i] = None  # CPU fallback

        for rnd in range(self.rounds):
            if not live:
                break
            max_bb = max(len(w.backbone) for _, w in live)
            L = max(256, -(-max_bb // 256) * 256)
            Lq = L + self.band
            fit, rejected = [], []
            for i, w in live:
                if all(len(s) <= Lq for s, _, _, _ in w.layers):
                    fit.append((i, w))
                else:
                    rejected.append(i)
            live = fit
            if not live:
                break
            self._device_round(live, L, Lq)
            if progress is not None:
                # bar units = refinement rounds (+1 for stitch/fallback)
                progress(rnd + 1, self.rounds + 1)

        for i, w in live:
            covs = w.covs
            consensus = w.backbone
            if covs is None:  # no successful device round
                results[i] = None
                continue
            if w.win.type == WindowType.TGS and trim:
                # threshold uses the *voted* depth: layers beyond max_depth
                # never vote, so counting them would make trimming a no-op
                # on windows deeper than ~2x max_depth
                avg_cov = min(w.n_seqs - 1, self.max_depth) // 2
                b_, e_ = 0, len(consensus) - 1
                while b_ < len(consensus) and covs[b_] < avg_cov:
                    b_ += 1
                while e_ >= 0 and covs[e_] < avg_cov:
                    e_ -= 1
                if b_ < e_:
                    consensus = consensus[b_:e_ + 1]
            w.win.consensus = consensus
            results[i] = True
            self.stats["device_windows"] += 1

        cpu_idx = [i for i, r in enumerate(results) if r is None]
        if cpu_idx:
            self.stats["fallback_windows"] += len(cpu_idx)
            if self.fallback is None:
                raise RuntimeError(
                    f"{len(cpu_idx)} windows rejected, no CPU fallback")
            flags = self.fallback.run([windows[i] for i in cpu_idx], trim)
            for i, f in zip(cpu_idx, flags):
                results[i] = f
        if progress is not None:
            progress(self.rounds + 1, self.rounds + 1)
        return [bool(r) for r in results]

    # -------------------------------------------------------------- device

    def _pack_shard(self, items, L, Lq, B, nWp):
        """Pack one shard's windows into fixed-shape pair/window arrays.

        ``items`` is a list of ``(result_index, _Work)``; pair rows beyond
        the shard's real pairs vote into the sink window ``nWp - 1``.
        """
        band = self.band
        c = band // 2
        width = c + Lq + band

        qrp = np.zeros((B, width), np.uint8)
        tp = np.zeros((B, width), np.uint8)
        n = np.ones(B, np.int32)
        m = np.ones(B, np.int32)
        qcodes = np.zeros((B, Lq), np.uint8)
        qweights = np.zeros((B, Lq), np.float32)
        begin = np.zeros(B, np.int32)
        win_of = np.full(B, nWp - 1, np.int32)  # padding -> sink window

        k = 0
        for wi, (_, w) in enumerate(items):
            for seq, qual, bg, ed in w.layers:
                bb = w.backbone
                bg = min(bg, len(bb) - 1)
                ed = min(ed, len(bb) - 1)
                span = bb[bg:ed + 1]
                qrp[k, c + Lq - len(seq): c + Lq] = \
                    np.frombuffer(seq, np.uint8)[::-1]
                tp[k, c: c + len(span)] = np.frombuffer(span, np.uint8)
                n[k], m[k] = len(seq), len(span)
                qcodes[k, :len(seq)] = _CODE_LUT[np.frombuffer(seq, np.uint8)]
                if qual is not None:
                    qweights[k, :len(seq)] = \
                        np.frombuffer(qual, np.uint8).astype(np.float32) - 33.0
                else:
                    qweights[k, :len(seq)] = 1.0
                begin[k] = bg
                win_of[k] = wi
                k += 1

        bcodes = np.zeros((nWp, L), np.uint8)
        bweights = np.zeros((nWp, L), np.float32)
        blen = np.zeros(nWp, np.int32)
        for wi, (_, w) in enumerate(items):
            bb = w.backbone
            bcodes[wi, :len(bb)] = _CODE_LUT[np.frombuffer(bb, np.uint8)]
            if w.bqual is not None:
                bweights[wi, :len(bb)] = \
                    np.frombuffer(w.bqual, np.uint8).astype(np.float32) - 33.0
            blen[wi] = len(bb)

        return (qrp, tp, n, m, qcodes, qweights, begin, win_of), \
               (bcodes, bweights, blen), k

    def _device_round(self, live, L, Lq) -> None:
        """One align+vote+consensus pass; updates each _Work in place.

        Windows are LPT-split into ``num_batches`` groups, every group's
        kernels are dispatched before the first group's results are
        fetched (JAX async dispatch), and results apply in order."""
        from ..parallel import partition_balanced
        if self.num_batches == 1:
            groups = [list(live)]
        else:
            bins = partition_balanced([len(w.layers) for _, w in live],
                                      self.num_batches)
            groups = [[live[i] for i in b] for b in bins if b]
        launches = [self._launch_group(g, L, Lq) for g in groups]
        for launch in launches:
            self._finish_group(launch)

    def _launch_group(self, live, L, Lq):
        """Pack one window group (per-mesh-shard when a mesh is set — pairs
        of a window never cross shards, so votes stay shard-local) and
        dispatch its align+vote+consensus kernels without blocking."""
        from ..parallel import (mesh_size, partition_balanced,
                                sharded_consensus_round)
        band = self.band
        nd = mesh_size(self.mesh)
        if nd == 1:
            shards = [list(live)]
        else:
            bins = partition_balanced([len(w.layers) for _, w in live], nd)
            shards = [[live[i] for i in b] for b in bins]

        max_pairs = max(sum(len(w.layers) for _, w in sh) for sh in shards)
        max_wins = max(len(sh) for sh in shards)
        B = 1
        while B < max(max_pairs, 1):
            B *= 2
        nWp = 1
        while nWp < max_wins + 1:
            nWp *= 2

        packs = [self._pack_shard(sh, L, Lq, B, nWp) for sh in shards]

        if nd == 1:
            pair_arrays, window_arrays, nP = packs[0]
            out = consensus_chain(
                *(jnp.asarray(a) for a in pair_arrays),
                *(jnp.asarray(a) for a in window_arrays),
                jnp.float32(self.ins_theta), jnp.float32(self.del_beta),
                n_windows=nWp, max_len=Lq, band=band, L=L, K=K_INS)
        else:
            pair_stk = [np.concatenate([p[0][a] for p in packs])
                        for a in range(8)]
            win_stk = [np.concatenate([p[1][a] for p in packs])
                       for a in range(3)]
            out = sharded_consensus_round(
                self.mesh,
                tuple(jnp.asarray(a) for a in pair_stk),
                tuple(jnp.asarray(a) for a in win_stk),
                n_windows_local=nWp, max_len=Lq, band=band, L=L, K=K_INS,
                ins_theta=self.ins_theta, del_beta=self.del_beta)
        n_pairs = [p[2] for p in packs]
        return shards, out, n_pairs, B, nWp, nd

    def _finish_group(self, launch) -> None:
        """Fetch one launched group's results and apply them in place."""
        shards, out, n_pairs, B, nWp, nd = launch
        res = [np.asarray(x) for x in jax.device_get(out)]
        # fixed output order: five window-major arrays, then pair-major ok
        strides = (nWp, nWp, nWp, nWp, nWp, B)
        shard_results = []
        for s in range(nd):
            shard_results.append(tuple(
                r[s * st:(s + 1) * st] for r, st in zip(res, strides)))

        for sh, (winner, coverage, ins_winner, ins_emit, ins_cov, ok), nP \
                in zip(shards, shard_results, n_pairs):
            self.stats["dropped_layers"] += int((~ok[:nP]).sum())
            self._apply_shard(sh, winner, coverage, ins_winner, ins_emit,
                              ins_cov)

    def _apply_shard(self, items, winner, coverage, ins_winner, ins_emit,
                     ins_cov) -> None:
        for wi, (_, w) in enumerate(items):
            blen_i = len(w.backbone)
            out_bytes = bytearray()
            covs: List[int] = []
            # emitted-column map for layer-span remapping in later rounds
            col_to_new = np.zeros(blen_i + 1, np.int32)
            for col in range(blen_i):
                col_to_new[col] = len(out_bytes)
                ch = int(winner[wi, col])
                if ch <= N_CODE:
                    out_bytes.append(_BYTE_LUT[ch])
                    covs.append(int(coverage[wi, col]))
                # slot s holds the s-th base from the END of an insertion
                # run (the walk is backwards), so emit high slots first
                for s_ in range(K_INS - 1, -1, -1):
                    if ins_emit[wi, col, s_]:
                        out_bytes.append(
                            _BYTE_LUT[int(ins_winner[wi, col, s_])])
                        covs.append(int(ins_cov[wi, col, s_]))
            col_to_new[blen_i] = len(out_bytes)

            new_bb = bytes(out_bytes)
            if len(new_bb) == 0:
                continue  # degenerate; keep previous backbone/covs
            new_layers = []
            for seq, qual, bg, ed in w.layers:
                nb = int(col_to_new[min(bg, blen_i)])
                ne = max(nb + 1, int(col_to_new[min(ed + 1, blen_i)]) - 1)
                nb = min(nb, len(new_bb) - 1)
                ne = min(ne, len(new_bb) - 1)
                new_layers.append((seq, qual, nb, ne))
            w.backbone = new_bb
            w.bqual = None  # refined consensus carries no phred quality
            w.layers = new_layers
            w.covs = covs
