"""SWAR (SIMD-within-a-register) primitives for the packed DP kernels.

The round-5 telemetry showed the DP kernels using <2% of the VPU: int32
vector lanes carry 2-bit bases and scores that are provably bounded by
the alignment band. Two packed formats recover the wasted lane width:

- **int16x2 score lanes**: wavefront scores are bounded by
  ``max(n, m) <= max_len`` (every banded-NW cell is an edit distance of a
  prefix pair), so two scores share one 32-bit lane. The XLA kernels use
  the ``int16`` dtype directly (the VPU/AVX vectorizer packs two values
  per 32-bit lane); the Pallas kernel packs explicitly into int32 words
  (planar halves, see ``pallas_nw._fwd_kernel_swar``) and runs min/select
  with the **biased-unsigned** halfword trick below, so per-lane min/add
  never borrows across the halfword boundary.
- **2-bit bases**: when a chunk's alphabet fits 4 symbols (ACGT does),
  bases travel host->device 4 per byte (16 per int32 word) and equality
  runs as XOR + mask instead of per-byte compares.

Saturation ceiling: packed scores saturate at ``BIG16`` (the int16 analog
of the int32 kernels' ``1 << 28``). Any band/length combination whose
real scores could reach ``BIG16`` must re-dispatch to the int32 path —
:func:`swar_fits` is that overflow guard (all current buckets fit:
``max_len <= 16384 < BIG16``).

Bit-exactness contract (relied on by the goldens): for the same input
rows, the packed kernels emit **byte-identical direction matrices and
scores** — real scores are < ``BIG16`` in both paths, the saturated
cells form the same {BIG, BIG+1} classes, and every comparison the
direction code depends on sees the same ordering. :func:`swar_ok` probes
this once per process on a random batch (the same philosophy as
``pallas_nw.pallas_ok``) and the dispatch layers fall back to int32 when
it fails.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

# Saturation value for packed int16 score lanes. Must exceed every real
# cell value (<= max_len, see module docstring) and keep BIG16 + 1 inside
# int16 (boundary cells add +1 per step off a saturated source). 0x4800
# leaves 2x headroom over the largest bucket (16384).
BIG16 = 0x4800
# int32 analog restored on the way out so consumers (and the parity
# harness) see the exact int32-path scores.
BIG32 = 1 << 28

# Halfword SWAR constants (int32 words carrying two unsigned 16-bit
# fields whose values stay < 2^15, so bit 15 of each field is a free
# guard bit for borrow-free compares).
ONES16 = int(np.int32(0x00010001))
TWOS16 = int(np.int32(0x00020002))
# guard-bit mask 0x80008000 as a (negative) int32
H16 = int(np.uint32(0x80008000).view(np.int32))
LO16 = int(np.int32(0x0000FFFF))


def swar16_ge(a, b):
    """Per-halfword full-field mask (0xFFFF) where ``a >= b``.

    Both operands' fields must be unsigned values < 2^15 (guard bit 15
    clear). Biased-unsigned compare: ``(a | H) - b`` adds 2^15 to each
    field before subtracting, so the per-field result stays in 16 bits
    and no borrow crosses the halfword boundary; field bit 15 then reads
    ``a >= b``. The shift is arithmetic (int32) — the ``& ONES16`` mask
    discards the sign smear before the mask-expansion multiply."""
    m = ((a | H16) - b) & H16
    return ((m >> 15) & ONES16) * LO16


def swar16_sel(a, b, m):
    """Per-halfword select: ``a`` where the full-field mask ``m`` is set,
    else ``b`` (masks come from :func:`swar16_ge` / :func:`swar16_eq`)."""
    return (a & m) | (b & ~m)


def swar16_min(a, b):
    """Per-halfword minimum (fields < 2^15): keep ``b`` where a >= b."""
    return swar16_sel(b, a, swar16_ge(a, b))


def swar16_eq(a, b):
    """Per-halfword full-field mask where ``a == b`` (fields < 2^15):
    XOR + or-tree nonzero detect, inverted, expanded to field masks."""
    x = a ^ b
    t = x | (x >> 8)
    t = t | (t >> 4)
    t = t | (t >> 2)
    t = t | (t >> 1)
    return ((t & ONES16) ^ ONES16) * LO16


def swar16_ne_small(x, bits: int = 4):
    """Per-halfword 0/1 nonzero detect for XOR results of codes < 2^bits
    (the SWAR base-equality substitute for a per-byte compare): cross-
    field shift contamination lands above bit ``bits`` and is masked."""
    t = x
    sh = 1
    while sh < bits:
        t = t | (t >> sh)
        sh *= 2
    return t & ONES16


def swar_fits(max_len: int) -> bool:
    """Overflow guard: True when every cell value a ``max_len`` bucket can
    produce (boundary values <= max_len, interior edit distances
    <= max(i, j) <= max_len, +1 per step of saturated-source slack) stays
    strictly below the packed saturation ceiling. Combinations that fail
    re-dispatch to the int32 path."""
    return max_len + 2 < BIG16


_SWAR_OK = None


def swar_ok() -> bool:
    """Probe once whether the packed (int16-lane) XLA wavefront kernel
    reproduces the int32 kernel bit-for-bit on a random small batch —
    dirs, scores, and walked tracebacks. Mirrors ``pallas_ok()``: a
    backend whose 16-bit lowering misbehaves downgrades to the int32
    kernels instead of shipping corrupt alignments."""
    global _SWAR_OK
    from .. import flags
    if not flags.get_bool("RACON_TPU_SWAR"):
        return False  # global escape hatch / A-B switch, like DYNBOUND
    if _SWAR_OK is None:
        try:
            from .nw import _nw_wavefront_kernel, _walk_ops_kernel

            max_len, band = 256, 128
            B, c = 8, band // 2
            width = c + max_len + band
            rng = np.random.default_rng(13)
            bases = np.frombuffer(b"ACGT", np.uint8)
            qrp = np.zeros((B, width), np.uint8)
            tp = np.zeros((B, width), np.uint8)
            n = np.zeros(B, np.int32)
            m = np.zeros(B, np.int32)
            for k in range(B):
                ln = int(rng.integers(50, 220))
                t = bases[rng.integers(0, 4, ln)]
                q = np.delete(t.copy(), rng.integers(0, ln, 3))
                flips = rng.random(len(q)) < 0.2
                q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
                qrp[k, c + max_len - len(q): c + max_len] = q[::-1]
                tp[k, c: c + ln] = t
                n[k], m[k] = len(q), ln
            args = (jnp.asarray(qrp), jnp.asarray(tp),
                    jnp.asarray(n), jnp.asarray(m))
            # graftlint: disable=swar-guard (probe bucket: 256 + 2 < BIG16 by construction)
            dp, sp = _nw_wavefront_kernel(*args, max_len=max_len,
                                          band=band, swar=True)
            dx, sx = _nw_wavefront_kernel(*args, max_len=max_len,
                                          band=band)
            # packed walk (round 17): the SWAR path's traceback carries
            # (i, j) as one halfword pair — probe it against the
            # unpacked walk on the same matrices, so a backend whose
            # shift/mask lowering misbehaves downgrades the whole
            # packed path (fwd + walk) together
            # graftlint: disable=swar-guard (probe bucket: 256 + 2 < BIG16 by construction)
            op_, fip, fjp = _walk_ops_kernel(dp, args[2], args[3],
                                             band=band, swar=True)
            ox, fix, fjx = _walk_ops_kernel(dx, args[2], args[3],
                                            band=band)
            _SWAR_OK = (
                np.array_equal(np.asarray(dp), np.asarray(dx))
                and np.array_equal(np.asarray(sp), np.asarray(sx))
                and np.array_equal(np.asarray(op_), np.asarray(ox))
                and np.array_equal(np.asarray(fip), np.asarray(fix))
                and np.array_equal(np.asarray(fjp), np.asarray(fjx)))
        except Exception as e:
            from ..utils.logger import log_swallowed
            log_swallowed("swar: availability probe failed; packed "
                          "int16 kernels disabled for this process", e)
            _SWAR_OK = False
    return _SWAR_OK


def pack_bases_2bit(codes: np.ndarray) -> np.ndarray:
    """Host-side 2-bit base packing: 4 codes per byte (16 per int32
    word), LSB-first. ``codes`` values must be < 4; length is padded to a
    multiple of 4. The device unpacker is ``nw._build_rows_packed2``."""
    pad = (-len(codes)) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    c4 = codes.reshape(-1, 4)
    return (c4[:, 0] | (c4[:, 1] << 2) | (c4[:, 2] << 4)
            | (c4[:, 3] << 6)).astype(np.uint8)
