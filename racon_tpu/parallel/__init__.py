"""racon_tpu.parallel — multi-chip dispatch over a ``jax.sharding.Mesh``.

Reference analog: the CUDA driver round-robins its batches across every
visible GPU (``src/cuda/cudapolisher.cpp:72-83,163-171,217-228``).  The
TPU-native equivalent is single-program data sharding: windows and overlap
pairs are embarrassingly parallel (SURVEY §2.3), so the fixed-shape device
batches built by :mod:`racon_tpu.ops` are split along their batch dimension
over a 1-D device mesh with :func:`jax.shard_map`.  Each chip runs the same
compiled kernels on its slice; there are **no collectives in the hot path**
(the scatter-add vote accumulators are window-major and windows never span
shards), so scaling rides ICI bandwidth-free and multi-host meshes over DCN
work unchanged.

Public surface:

- :func:`get_mesh` — build a 1-D mesh over (a prefix of) the local devices;
- :func:`sharded_align` — batched wavefront-NW + on-device traceback,
  batch dim sharded (used by :class:`racon_tpu.ops.nw.TpuAligner`);
- :func:`sharded_refine_loop` — a group's device-resident consensus
  refinement loop with pair arrays and window state co-sharded (used by
  :class:`racon_tpu.ops.poa.TpuPoaConsensus`);
- :func:`partition_balanced` — greedy LPT binning of variable-cost items
  into per-shard groups (host-side analog of the reference's dynamic work
  queue, ``src/cuda/cudapolisher.cpp:98-118``).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "d"


def _shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` moved between releases (``jax.shard_map`` with
    ``check_vma`` on current JAX; ``jax.experimental.shard_map.shard_map``
    with ``check_rep`` on 0.4.x) — one compat shim so every engine wires
    through identical code."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def distributed_init(coordinator_address: str, num_processes: int,
                     process_id: int) -> None:
    """Join a multi-host JAX job (idempotent): after this,
    ``jax.devices()`` spans every host and :func:`get_mesh` builds a
    global mesh, so the same ``shard_map`` engines scale over DCN. The
    reference analog is single-node multi-GPU binning
    (``src/cuda/cudapolisher.cpp:72-83``); the TPU-native story is SPMD
    over a global mesh with per-host input packing (SURVEY §2.3)."""
    # NOTE: must run before anything initializes the XLA backend (even
    # jax.process_count() would), hence the flag-only idempotence guard
    if getattr(distributed_init, "_done", False):
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    distributed_init._done = True


def is_multihost() -> bool:
    return jax.process_count() > 1


def to_global(mesh: Mesh, arr_np):
    """Build a device array sharded along AXIS over ``mesh`` from the
    full host-side content. Every process calls this with identically
    computed ``arr_np`` (packing is deterministic); each materializes
    only its addressable shards, so multi-host placement needs no
    host-to-host transfer. Single-process: a plain device put."""
    if not is_multihost():
        return jax.numpy.asarray(arr_np)
    sharding = NamedSharding(mesh, P(AXIS))
    return jax.make_array_from_callback(arr_np.shape, sharding,
                                        lambda idx: arr_np[idx])


def fetch_global(tree):
    """Fetch device results to host numpy. Multi-host: an allgather
    over DCN replicates every shard to every process, so downstream
    decode (stitching windows into contigs) is identical on all hosts
    and each can emit the full output."""
    if not is_multihost():
        return jax.device_get(tree)
    from jax.experimental import multihost_utils
    return [np.asarray(multihost_utils.process_allgather(x, tiled=True))
            for x in tree]


def get_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh named ``d`` over ``n_devices`` (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def mesh_size(mesh: Optional[Mesh]) -> int:
    return 1 if mesh is None else mesh.shape[AXIS]


def partition_balanced(costs: Sequence[int], n_bins: int) -> List[List[int]]:
    """Greedy longest-processing-time binning: returns per-bin item indices.

    Host-side replacement for the reference's mutex'd shared-index work
    queue — with fixed-shape device batches the binning happens up front.
    """
    order = sorted(range(len(costs)), key=lambda i: -costs[i])
    bins: List[List[int]] = [[] for _ in range(n_bins)]
    loads = [0] * n_bins
    for i in order:
        b = loads.index(min(loads))
        bins[b].append(i)
        loads[b] += costs[i]
    return bins


@functools.lru_cache(maxsize=None)
def _sharded_align_fn(mesh: Mesh, max_len: int, band: int, steps: int,
                      use_pallas: bool, use_swar: bool):
    from ..ops.nw import align_chain

    def local(qrp, tp, n, m):
        return align_chain(qrp, tp, n, m, max_len=max_len, band=band,
                           steps=steps, use_pallas=use_pallas,
                           use_swar=use_swar)

    spec = P(AXIS)
    return jax.jit(_shard_map(local, mesh,
                              in_specs=(spec, spec, spec, spec),
                              out_specs=(spec, spec, spec, spec)))


def sharded_align(mesh: Mesh, qrp, tp, n, m, *, max_len: int, band: int,
                  steps: int = 0, use_pallas: bool = False,
                  use_swar: bool = False):
    """NW + traceback with the batch dimension split over ``mesh``.

    Batch size must be a multiple of the mesh size (callers pad).
    Returns ``(ops_packed, score, fi, fj)`` exactly like the single-device
    ``_traceback_kernel``.
    """
    return _sharded_align_fn(mesh, max_len, band, steps,
                             use_pallas, use_swar)(qrp, tp, n, m)


@functools.lru_cache(maxsize=None)
def _sharded_refine_fn(mesh: Mesh, rounds: int, n_windows_local: int,
                       max_len: int, band: int, Lb: int, K: int,
                       steps: int, use_pallas: bool, use_swar: bool,
                       Lq2: int, scores, matmul_votes: bool = False):
    from ..ops.poa import refine_loop

    def local(n, qpw, win_of, real, bg, ed,
              bcodes, bweights, blen, covs, ever, frozen, conv, dropped,
              ins_theta, del_beta):
        return refine_loop(n, qpw, win_of, real, bg, ed,
                           bcodes, bweights, blen, covs, ever, frozen,
                           conv, dropped, ins_theta, del_beta,
                           rounds=rounds,
                           n_windows=n_windows_local, max_len=max_len,
                           band=band, Lb=Lb, K=K, steps=steps,
                           use_pallas=use_pallas, use_swar=use_swar,
                           Lq2=Lq2, scores=scores,
                           matmul_votes=matmul_votes)

    spec = P(AXIS)
    return jax.jit(_shard_map(
        local, mesh, in_specs=(spec,) * 14 + (P(), P()),
        out_specs=(spec,) * 10))


def sharded_refine_loop(mesh: Mesh, static, state, ins_theta, del_beta, *,
                        rounds: int, n_windows_local: int, max_len: int,
                        band: int, Lb: int, K: int, steps: int = 0,
                        use_pallas: bool = False, use_swar: bool = False,
                        Lq2: int = 0, scores=(3, -5, -4),
                        matmul_votes: bool = False):
    """A group's whole refinement loop over a co-sharded batch, one
    dispatch (the shard-local body is ``refine_loop``'s fori over
    ``refine_round``).

    ``static`` = (n, qpw, win_of, real) with leading dim
    ``n_shards * B_local`` (``qpw`` is the packed ``weight << 3 | code``
    uint16 layer block); ``win_of`` holds **shard-local** window
    ordinals.  ``state`` = (bg, ed, bcodes, bweights, blen, covs, ever,
    frozen, conv, dropped) — pair-major arrays share the pair stacking, window
    rows have leading dim ``n_shards * n_windows_local``, ``dropped`` is
    a [n_shards, 4 + n_windows_local] telemetry row per shard (rejected
    alignments, sweep-truncated spans, insertion-fold overflows,
    executed wavefront steps, then the fold overflows attributed per
    shard-local window row — the shard specs only constrain the leading
    dim, so the widened trailing dim shards transparently).  Pairs belonging to one
    window must live in that window's shard — :func:`partition_balanced`
    plus per-shard packing guarantees it, so no cross-shard reduction is
    needed and the whole refinement loop scales collective-free.  Returns
    the updated ``state`` stacked the same way.
    """
    fn = _sharded_refine_fn(mesh, rounds, n_windows_local, max_len, band,
                            Lb, K, steps, use_pallas, use_swar, Lq2,
                            scores, matmul_votes)
    return fn(*static, *state, ins_theta, del_beta)
