"""Local device topology: enumerate chips, hand out executor slots.

The reference driver round-robins batches over every visible GPU from
one process (``src/cuda/cudapolisher.cpp:72-83``).  The TPU analog has
two shapes, and this module is where a run picks between them:

- **shard-per-chip** (the common case): each local device gets its own
  pinned engine pair and an in-process chip worker drains manifest
  shards onto it (``racon_tpu.exec.runner``), coordinated by the same
  lease files multi-process workers use — no collectives, no mesh, each
  chip runs the full single-device fast path (ragged packing, streaming
  sessions, SWAR) that a mesh run must disable;
- **mesh-sharded** (one contig dominates the plan): the existing
  ``sharded_align`` / ``sharded_refine_loop`` ``shard_map`` path splits
  that one shard's batches over all chips (``racon_tpu.parallel``).

Pinning rides plain JAX placement: a :class:`ChipSlot`'s :meth:`~
ChipSlot.pin` context makes ``jax.default_device`` the slot's device,
so the engines' host->device puts (and every computation that follows
them) land on that chip.  ``jax.default_device`` is thread-local, which
is exactly what lets N chip workers share one process.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import List, Optional

from .. import flags


def local_devices() -> list:
    """Every device addressable by this process (``jax.local_devices()``
    — on multi-host jobs this is the host-local slice, which is the set
    one process can drive)."""
    import jax

    return list(jax.local_devices())


def n_local_chips() -> int:
    return len(local_devices())


def resolve_chips(requested: int = 0) -> int:
    """Number of in-process chip workers a run should spawn: an explicit
    request (CLI ``--chips``) wins, then ``RACON_TPU_CHIPS``, then every
    local device.  Always clamped to the local device count and floored
    at 1."""
    if requested <= 0:
        requested = flags.get_int("RACON_TPU_CHIPS")
    n = n_local_chips()
    if requested <= 0:
        return max(1, n)
    return max(1, min(requested, n))


@dataclass
class ChipSlot:
    """One local chip's executor slot: the device plus its ordinal (the
    key per-device metrics, worker ids and plan assignments use)."""

    ordinal: int
    device: Optional[object] = None

    @property
    def key(self) -> str:
        return f"chip{self.ordinal}"

    def pin(self):
        """Context manager placing default JAX computation on this
        slot's device (thread-local; a no-op for the unpinned default
        slot, which keeps the single-chip path byte-for-byte the code
        it was before the scheduler existed)."""
        if self.device is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)


class Topology:
    """The local chip set as executor slots.

    ``n_chips <= 1`` yields one *unpinned* slot — the legacy
    single-device path.  ``n_chips > 1`` yields one pinned slot per
    device prefix, slot 0 doubling as the mesh-capable slot (it may run
    plan shards marked mesh-sharded over ALL local chips)."""

    def __init__(self, n_chips: int = 0):
        n = resolve_chips(n_chips)
        if n <= 1:
            self.slots: List[ChipSlot] = [ChipSlot(0, None)]
        else:
            devs = local_devices()
            self.slots = [ChipSlot(k, devs[k]) for k in range(n)]

    @property
    def n_chips(self) -> int:
        return len(self.slots)

    def describe(self) -> dict:
        """Advisory topology record for plans/reports (platform +
        device kind + chip count)."""
        devs = local_devices()
        first = devs[0] if devs else None
        return {
            "n_chips": self.n_chips,
            "n_local_devices": len(devs),
            "platform": getattr(first, "platform", "unknown"),
            "device_kind": getattr(first, "device_kind", "unknown"),
        }
