"""rampler: standalone sequence subsampler / splitter (L6 companion tool).

Re-creates the observable CLI contract of the reference's vendored
``rampler`` as used by ``racon_wrapper`` (``scripts/racon_wrapper.py:58-59,
83-84`` of the reference tree):

- ``rampler -o DIR subsample <sequences> <reference_length> <coverage>``
  writes ``DIR/<basename>_<coverage>x.<ext>`` with a random subset of
  sequences totalling ~reference_length x coverage bases;
- ``rampler -o DIR split <sequences> <chunk_size>`` writes
  ``DIR/<basename>_<i>.<ext>`` chunks whose sequence bytes stay under
  ``chunk_size`` each (input order preserved).

Outputs are uncompressed FASTA, or FASTQ when the input records carry
qualities. Subsampling is deterministic by default (``--seed``, default 0)
so wrapper runs are reproducible; pass a different seed for new samples.
"""

from __future__ import annotations

import argparse
import os
import sys
import random
from typing import List

from .io import parsers


def _base_and_ext(path: str, has_quality: bool):
    base = os.path.basename(path).split(".")[0]
    return base, (".fastq" if has_quality else ".fasta")


def _write(records, path: str) -> None:
    with open(path, "wb") as f:
        for rec in records:
            if rec.quality is not None:
                f.write(b"@" + rec.name + b"\n" + rec.data + b"\n+\n"
                        + rec.quality + b"\n")
            else:
                f.write(b">" + rec.name + b"\n" + rec.data + b"\n")


def _load(path: str):
    parse = parsers.sequence_parser_for(path)
    if parse is None:
        print(f"[rampler::] error: file {path} has unsupported format",
              file=sys.stderr)
        sys.exit(1)
    return list(parse(path))


def subsample(sequences_path: str, reference_length: int, coverage: int,
              out_dir: str, seed: int = 0) -> str:
    records = _load(sequences_path)
    target = reference_length * coverage
    order = list(range(len(records)))
    random.Random(seed).shuffle(order)
    picked: List[int] = []
    total = 0
    for i in order:
        if total >= target:
            break
        picked.append(i)
        total += len(records[i].data)
    picked.sort()  # keep input order inside the sample
    has_quality = any(records[i].quality is not None for i in picked)
    base, ext = _base_and_ext(sequences_path, has_quality)
    out_path = os.path.join(out_dir, f"{base}_{coverage}x{ext}")
    _write((records[i] for i in picked), out_path)
    return out_path


def split(sequences_path: str, chunk_size: int, out_dir: str) -> List[str]:
    records = _load(sequences_path)
    has_quality = any(r.quality is not None for r in records)
    base, ext = _base_and_ext(sequences_path, has_quality)
    out_paths: List[str] = []
    chunk: List = []
    chunk_bytes = 0
    for rec in records:
        if chunk and chunk_bytes + len(rec.data) > chunk_size:
            path = os.path.join(out_dir, f"{base}_{len(out_paths)}{ext}")
            _write(chunk, path)
            out_paths.append(path)
            chunk, chunk_bytes = [], 0
        chunk.append(rec)
        chunk_bytes += len(rec.data)
    if chunk:
        path = os.path.join(out_dir, f"{base}_{len(out_paths)}{ext}")
        _write(chunk, path)
        out_paths.append(path)
    return out_paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="rampler",
        description="sampling module for raw de novo DNA assembly of long "
                    "uncorrected reads")
    p.add_argument("-o", "--out-directory", default=".",
                   help="path in which sampled files will be created")
    p.add_argument("--seed", type=int, default=0,
                   help="subsampling RNG seed (deterministic by default)")
    sub = p.add_subparsers(dest="mode", required=True)

    ps = sub.add_parser("subsample", help="subsample sequences to coverage")
    ps.add_argument("sequences")
    ps.add_argument("reference_length", type=int)
    ps.add_argument("coverage", type=int)

    pp = sub.add_parser("split", help="split sequences into byte chunks")
    pp.add_argument("sequences")
    pp.add_argument("chunk_size", type=int)

    args = p.parse_args(argv)
    os.makedirs(args.out_directory, exist_ok=True)

    if args.mode == "subsample":
        subsample(args.sequences, args.reference_length, args.coverage,
                  args.out_directory, args.seed)
    else:
        split(args.sequences, args.chunk_size, args.out_directory)
    return 0


if __name__ == "__main__":
    sys.exit(main())
