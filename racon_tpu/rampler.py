"""rampler: standalone sequence subsampler / splitter (L6 companion tool).

Re-creates the observable CLI contract of the reference's vendored
``rampler`` as used by ``racon_wrapper`` (``scripts/racon_wrapper.py:58-59,
83-84`` of the reference tree):

- ``rampler -o DIR subsample <sequences> <reference_length> <coverage>``
  writes ``DIR/<basename>_<coverage>x.<ext>`` with a random subset of
  sequences totalling ~reference_length x coverage bases;
- ``rampler -o DIR split <sequences> <chunk_size>`` writes
  ``DIR/<basename>_<i>.<ext>`` chunks whose sequence bytes stay under
  ``chunk_size`` each (input order preserved).

Plus one racon-tpu extension: ``rampler plan <sequences> <overlaps>
<targets> [--shards N | --max-ram SIZE | --split BYTES]`` prints the
streaming shard runner's plan (contig assignment + per-shard cost
estimates, JSON) without running anything — the dry-run surface for
sizing a large polish before committing hours to it.

Outputs are uncompressed FASTA, or FASTQ when the input records carry
qualities. Subsampling is deterministic by default (``--seed``, default 0)
so wrapper runs are reproducible; pass a different seed for new samples.
"""

from __future__ import annotations

import argparse
import os
import sys
import random
from typing import List

from .io import parsers


def _base_and_ext(path: str, has_quality: bool):
    base = os.path.basename(path).split(".")[0]
    return base, (".fastq" if has_quality else ".fasta")


def _write(records, path: str) -> None:
    with open(path, "wb") as f:
        for rec in records:
            if rec.quality is not None:
                f.write(b"@" + rec.name + b"\n" + rec.data + b"\n+\n"
                        + rec.quality + b"\n")
            else:
                f.write(b">" + rec.name + b"\n" + rec.data + b"\n")


def _load(path: str):
    parse = parsers.sequence_parser_for(path)
    if parse is None:
        print(f"[rampler::] error: file {path} has unsupported format",
              file=sys.stderr)
        sys.exit(1)
    return list(parse(path))


def subsample(sequences_path: str, reference_length: int, coverage: int,
              out_dir: str, seed: int = 0) -> str:
    records = _load(sequences_path)
    target = reference_length * coverage
    order = list(range(len(records)))
    random.Random(seed).shuffle(order)
    picked: List[int] = []
    total = 0
    for i in order:
        if total >= target:
            break
        picked.append(i)
        total += len(records[i].data)
    picked.sort()  # keep input order inside the sample
    has_quality = any(records[i].quality is not None for i in picked)
    base, ext = _base_and_ext(sequences_path, has_quality)
    out_path = os.path.join(out_dir, f"{base}_{coverage}x{ext}")
    _write((records[i] for i in picked), out_path)
    return out_path


def split(sequences_path: str, chunk_size: int, out_dir: str) -> List[str]:
    records = _load(sequences_path)
    has_quality = any(r.quality is not None for r in records)
    base, ext = _base_and_ext(sequences_path, has_quality)
    out_paths: List[str] = []
    chunk: List = []
    chunk_bytes = 0
    for rec in records:
        if chunk and chunk_bytes + len(rec.data) > chunk_size:
            path = os.path.join(out_dir, f"{base}_{len(out_paths)}{ext}")
            _write(chunk, path)
            out_paths.append(path)
            chunk, chunk_bytes = [], 0
        chunk.append(rec)
        chunk_bytes += len(rec.data)
    if chunk:
        path = os.path.join(out_dir, f"{base}_{len(out_paths)}{ext}")
        _write(chunk, path)
        out_paths.append(path)
    return out_paths


def plan(sequences_path: str, overlaps_path: str, target_path: str,
         n_shards: int = 0, max_ram: str = "", split_bytes: int = 0,
         fragment_correction: bool = False,
         error_threshold: float = 0.3) -> dict:
    """Dry-run shard plan (see module docstring): index the inputs, run
    the planner, return the JSON-ready plan summary. ``-f``/``-e`` must
    match the eventual racon invocation — they change the global overlap
    filter and therefore the per-shard cost estimates."""
    from .core.polisher import PolisherType
    from .exec import build_index, parse_ram, plan_shards
    from .exec.heartbeat import peak_rss_bytes
    from .exec.index import build_index_readsonly
    from .io import parsers

    if parsers.is_auto_overlaps(overlaps_path):
        # --overlaps auto: no overlaps file exists at planning time —
        # cost from reads + target sizes only (reads apportioned to
        # contigs by contig size)
        index = build_index_readsonly(sequences_path, target_path)
    else:
        index = build_index(sequences_path, overlaps_path, target_path,
                            PolisherType.F if fragment_correction
                            else PolisherType.C, error_threshold)
    sp = plan_shards(index, n_shards,
                     parse_ram(max_ram) if max_ram else 0, split_bytes,
                     base_rss=peak_rss_bytes())
    return {
        "mode": sp.mode,
        "n_contigs": len(index.targets),
        "n_overlaps": int(len(index.ov_start)),
        "total_mbp": round(sum(t.bases for t in index.targets) / 1e6, 4),
        "budget_bytes": sp.budget_bytes,
        "avail_bytes": sp.avail_bytes,
        "shards": [{
            "id": si,
            "contigs": [index.targets[ci].name.decode("utf-8", "replace")
                        for ci in shard],
            "est_resident_mb": sp.costs[si] >> 20,
        } for si, shard in enumerate(sp.shards)],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="rampler",
        description="sampling module for raw de novo DNA assembly of long "
                    "uncorrected reads")
    p.add_argument("-o", "--out-directory", default=".",
                   help="path in which sampled files will be created")
    p.add_argument("--seed", type=int, default=0,
                   help="subsampling RNG seed (deterministic by default)")
    sub = p.add_subparsers(dest="mode", required=True)

    ps = sub.add_parser("subsample", help="subsample sequences to coverage")
    ps.add_argument("sequences")
    ps.add_argument("reference_length", type=int)
    ps.add_argument("coverage", type=int)

    pp = sub.add_parser("split", help="split sequences into byte chunks")
    pp.add_argument("sequences")
    pp.add_argument("chunk_size", type=int)

    pl = sub.add_parser("plan", help="print the streaming shard runner's "
                                     "plan without running anything")
    pl.add_argument("sequences")
    pl.add_argument("overlaps")
    pl.add_argument("target_sequences")
    pl.add_argument("--shards", type=int, default=0)
    pl.add_argument("--max-ram", default="")
    pl.add_argument("--split", type=int, default=0)
    pl.add_argument("-f", "--fragment-correction", action="store_true",
                    help="plan for fragment correction (keep-all overlap "
                         "filter) — must match the racon invocation")
    pl.add_argument("-e", "--error-threshold", type=float, default=0.3,
                    help="overlap error threshold — must match the racon "
                         "invocation")

    args = p.parse_args(argv)

    if args.mode == "plan":
        import json

        print(json.dumps(plan(args.sequences, args.overlaps,
                              args.target_sequences, args.shards,
                              args.max_ram, args.split,
                              args.fragment_correction,
                              args.error_threshold), indent=1))
        return 0

    os.makedirs(args.out_directory, exist_ok=True)
    if args.mode == "subsample":
        subsample(args.sequences, args.reference_length, args.coverage,
                  args.out_directory, args.seed)
    else:
        split(args.sequences, args.chunk_size, args.out_directory)
    return 0


if __name__ == "__main__":
    sys.exit(main())
