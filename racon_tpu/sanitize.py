"""Runtime sanitizer (``RACON_TPU_SANITIZE=1``) — the dynamic half of
graftlint (``tools/analysis``).

Five independent detectors, all off unless the flag is set:

- **SWAR shadow execution** — sampled packed-lane aligner chunks re-run
  on the int32 kernels and every output is compared bit-for-bit
  (:func:`should_shadow` / :func:`shadow_compare`).  The static guards
  (``swar.swar_fits`` + the kernels' trace-time assert) make a real
  int16 overflow unreachable *when they are in place*; the shadow path
  is the net that catches the day someone loosens them.  The consensus
  shadow re-dispatches WHOLE launches from their pre-round state
  (``TpuPoaConsensus._dispatch_rounds``), so it follows whatever layout
  the launch used — ragged per-bucket geometry and int8-matmul vote
  groups shadow exactly like padded single-geometry ones (the ragged
  parity suite re-runs under the sanitizer in CI to prove it).
- **Kernel-output canaries** — cheap host-side invariant checks on every
  fetched chunk/group (:func:`check_aligner_canaries`,
  :func:`check_consensus_canaries`): a wrapped int16 lane surfaces as a
  negative or out-of-range score, a poisoned f32 vote surfaces as an
  out-of-alphabet consensus code or an impossible backbone length.
- **jit-retrace budget** — :class:`PhaseRetraceBudget` snapshots the
  total jit cache size across the kernel modules around a pipeline
  phase and flags silent-recompile regressions (a shape leaking into
  the batch geometry recompiles per chunk — historically a 30 s/chunk
  stealth tax).
- **Queue watchdog** — :class:`QueueWatchdog` arms a monitor over the
  pipelined ``Polisher.run()`` bounded queue and dumps every thread's
  stack to stderr when producer/consumer progress stalls past the
  timeout (deadlock triage without attaching a debugger).
- **Lock-order witness** (round 15, the runtime companion of the
  ``lock-discipline``/``blocking-under-lock`` lint rules) — the
  project's named locks (:func:`named_lock`: the exec runner's
  manifest/notes/states locks, the serve scheduler's state lock, the
  heartbeat and index locks) are wrapped in :class:`WitnessedLock`,
  the cross-thread acquisition-order graph is recorded (one stack per
  first-seen edge), and any cycle — a potential deadlock, even one the
  current interleaving never hit — is reported at process exit with
  the stack of every edge on the cycle.  ``obs``-internal locks stay
  plain (the witness publishes through the metrics registry, so the
  registry lock cannot be witnessed without recursing).

Import cost is nil when disabled: numpy only, jax is touched lazily and
only for the retrace scan.
"""

from __future__ import annotations

import atexit
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Sequence, Tuple

from . import contracts, flags
from .obs import metrics
from .utils.logger import warn


class SanitizerError(AssertionError):
    """Base of every sanitizer-raised fault (an AssertionError so plain
    test harnesses treat it as a hard failure)."""


class SwarShadowMismatch(SanitizerError):
    """The packed (SWAR) kernel output diverged from the int32 shadow."""


class CanaryError(SanitizerError):
    """A fetched kernel output violated a value-range invariant."""


class RetraceBudgetExceeded(SanitizerError):
    """A pipeline phase compiled more new jit entries than its budget."""


class CompileAfterWarmError(SanitizerError):
    """An XLA compile was observed after the warm path was sealed —
    the resident server's warm-path claim (jobs dispatch into a hot
    jit cache) is violated; the message names the offending
    (function, shape signature) next to the nearest warmed one."""


def enabled() -> bool:
    """Master switch, read from the environment on every call so tests
    can toggle ``RACON_TPU_SANITIZE`` without re-importing."""
    return flags.sanitize_enabled()


def reraise_if_sanitizer(exc: BaseException) -> None:
    """Guard for broad fallback handlers: a sanitizer fault must fail
    the run, never be retried/downgraded like an ordinary kernel fault
    (the Pallas fallback chains catch ``Exception``, and
    :class:`SanitizerError` would otherwise vanish into them)."""
    if isinstance(exc, SanitizerError):
        raise exc


# ------------------------------------------------------ shadow execution

class ShadowSampler:
    """Sampling gate for SWAR shadow execution: chunk 0 always, then
    every ``RACON_TPU_SANITIZE_SAMPLE``-th chunk. One instance per
    engine/run (TpuAligner owns one), so the first chunk of EVERY run
    is checked — a process-global counter would leave short follow-up
    runs unsampled. Thread-safe: chunks launch from pipelined producer
    threads too."""

    def __init__(self):
        self._seen = 0
        self._lock = threading.Lock()

    def should_shadow(self) -> bool:
        if not enabled():
            return False
        n = max(1, flags.get_int("RACON_TPU_SANITIZE_SAMPLE"))
        with self._lock:
            k = self._seen
            self._seen += 1
        return k % n == 0


def shadow_compare(packed_out: Sequence, shadow_out: Sequence,
                   names: Sequence[str], context: str) -> None:
    """Bit-exact comparison of a packed-path output tuple against its
    int32 shadow. Raises :class:`SwarShadowMismatch` naming the first
    diverging output and the lane count that differs."""
    import numpy as np

    for name, a, b in zip(names, packed_out, shadow_out):
        ah, bh = np.asarray(a), np.asarray(b)
        if ah.shape != bh.shape:
            raise SwarShadowMismatch(
                f"{context}: {name} shape {ah.shape} != shadow {bh.shape}")
        if not np.array_equal(ah, bh):
            bad = int(np.count_nonzero(ah != bh))
            raise SwarShadowMismatch(
                f"{context}: {name} diverged from the int32 shadow on "
                f"{bad}/{ah.size} lanes (packed-lane overflow or a "
                f"kernel regression — the bit-exactness contract in "
                f"ops/swar.py is broken)")


# -------------------------------------------------------------- canaries

def check_aligner_canaries(score, fi, fj, *, big: int,
                           context: str) -> None:
    """Host-side invariants on a fetched aligner chunk: scores are
    edit counts in ``[0, big]`` (a wrapped int16 lane goes negative or
    lands between the saturation classes' ceiling and ``big``), walk
    endpoints are non-negative."""
    import numpy as np

    s = np.asarray(score)
    if s.size and (int(s.min()) < 0 or int(s.max()) > big):
        raise CanaryError(
            f"{context}: score outside [0, {big}] "
            f"(min {int(s.min())}, max {int(s.max())}) — packed-lane "
            f"wraparound or kernel corruption")
    for name, v in (("fi", fi), ("fj", fj)):
        vh = np.asarray(v)
        if vh.size and int(vh.min()) < 0:
            raise CanaryError(f"{context}: negative walk endpoint {name}")


def check_consensus_canaries(bcodes, blen, covs, *, Lb: int,
                             context: str) -> None:
    """Host-side invariants on a fetched consensus group: backbone codes
    stay inside the 6-symbol alphabet (a NaN-poisoned f32 vote argmax or
    a corrupted packed fetch shows up as code 6/7), lengths stay inside
    the device buffer, coverage counts are non-negative."""
    import numpy as np

    bc = np.asarray(bcodes)
    if bc.size and int(bc.max()) > 5:
        raise CanaryError(
            f"{context}: backbone code {int(bc.max())} outside the "
            f"ACGTN- alphabet — vote matrix corruption")
    bl = np.asarray(blen)
    if bl.size and (int(bl.min()) < 0 or int(bl.max()) > Lb):
        raise CanaryError(
            f"{context}: backbone length outside [0, {Lb}]")
    cv = np.asarray(covs)
    if cv.size and int(cv.min()) < 0:
        raise CanaryError(f"{context}: negative coverage count")


# -------------------------------------------------------- retrace budget

def retrace_count(prefixes: Sequence[str] = ("racon_tpu",)) -> int:
    """Total live jit-cache entries across modules matching
    ``prefixes`` — the monotone counter :class:`PhaseRetraceBudget`
    differences.  Walks the already-imported modules for jitted
    callables (objects exposing ``_cache_size``), so nothing has to
    register itself. Phase budgets pass their own module scope so the
    background consensus warm-up thread's compiles (``ops.poa``) are
    not attributed to the concurrently-open align phase."""
    total = 0
    prefixes = tuple(prefixes)
    for mod_name, mod in list(sys.modules.items()):
        if not mod_name.startswith(prefixes):
            continue
        for attr in list(vars(mod).values()):
            size = getattr(attr, "_cache_size", None)
            if callable(size):
                try:
                    total += int(size())
                except Exception:  # graftlint: disable=swallowed-exception (foreign jit internals)
                    pass
    return total


class PhaseRetraceBudget:
    """Context manager asserting a pipeline phase compiles at most
    ``budget`` new jit entries (default from
    ``RACON_TPU_SANITIZE_RETRACE_BUDGET``). The delta is **always**
    measured and published to the metrics registry as the gauge
    ``retrace.<phase>`` on a clean exit (the scan walks already-imported
    modules — microseconds per phase — so bench.py reports and the
    shard runner's heartbeat line read compile churn from the one
    registry without paying for shadow execution); the budget itself is
    only *enforced* when the sanitizer is armed.

    ``prefixes`` scopes the counted modules: the polisher's align phase
    counts the aligner kernel modules only, so consensus compiles from
    the concurrent warm-up thread (``warmup_async``) cannot push a
    healthy align phase over budget. (The one-time availability probes
    may still add a few shared-module entries — the default budget has
    ample headroom for those; what the budget hunts is per-chunk
    recompile *growth*.)"""

    def __init__(self, phase: str, budget: Optional[int] = None,
                 prefixes: Sequence[str] = ("racon_tpu",)):
        self.phase = phase
        self.budget = budget
        self.prefixes = tuple(prefixes)
        self._start = 0
        self._armed = False

    def __enter__(self):
        self._armed = enabled()
        self._start = retrace_count(self.prefixes)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        delta = retrace_count(self.prefixes) - self._start
        # gauge: the MOST RECENT delta per phase (heartbeat/per-shard
        # attribution; the exec runner clears the prefix between
        # shards); counter: the run-lifetime total (run reports — it
        # survives the per-shard clear)
        metrics.set_gauge(f"retrace.{self.phase}", delta)
        metrics.inc(f"retrace_total.{self.phase}", delta)
        if not self._armed:
            return False
        budget = (self.budget if self.budget is not None
                  else flags.get_int("RACON_TPU_SANITIZE_RETRACE_BUDGET"))
        if delta > budget:
            raise RetraceBudgetExceeded(
                f"phase {self.phase!r} compiled {delta} new jit entries "
                f"(budget {budget}) — a shape is leaking into the batch "
                f"geometry and forcing silent recompiles")
        return False


def check_post_warm_compiles(scope=None) -> list:
    """The warm-path assert (round 18): raise
    :class:`CompileAfterWarmError` when the process-wide compile watch
    (:mod:`racon_tpu.obs.compilewatch`) recorded a compile after
    :func:`~racon_tpu.obs.compilewatch.seal` — for the resident server
    that means a job dispatched a geometry neither the warm-up profile
    nor any earlier job compiled.  Armed only under
    ``RACON_TPU_SANITIZE=1`` (the violations are warned and counted
    either way); returns the violation records when not raising, so
    unsanitized callers can surface them."""
    from .obs import compilewatch
    violations = compilewatch.post_warm(scope)
    if violations and enabled():
        raise CompileAfterWarmError(compilewatch.describe(violations))
    return violations


# -------------------------------------------------------- queue watchdog

def dump_all_stacks(reason: str, stream=None) -> None:
    """Write every live thread's stack to ``stream`` (stderr default) —
    the deadlock-triage dump the queue watchdog fires."""
    stream = stream if stream is not None else sys.stderr
    lines = [f"[racon_tpu::sanitize] watchdog: {reason} — "
             f"dumping {threading.active_count()} thread stacks"]
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
    print("\n".join(lines), file=stream)
    stream.flush()


class QueueWatchdog:
    """Stall monitor for a bounded producer/consumer queue: call
    :meth:`beat` on every put/get; if no beat lands for ``timeout``
    seconds the watchdog dumps all thread stacks (once per stall) and
    counts the firing.

    **Escalation** (round 12): with an ``escalate_cb``, a stall that
    persists past ``timeout * escalate_after`` fires the callback once
    per stall — the pipelined polisher uses it to fail the attempt with
    a ``stall``-class fault (:class:`racon_tpu.faults.StallError`) so
    the shard runner's degradation ladder can retry/quarantine the
    shard instead of the process hanging forever. Without a callback
    the watchdog stays purely passive — it reports, it never kills the
    run."""

    def __init__(self, timeout: float, name: str = "queue",
                 stream=None, escalate_cb=None,
                 escalate_after: float = 2.0):
        self.timeout = float(timeout)
        self.name = name
        self.fired = 0
        self._stream = stream
        self._escalate_cb = escalate_cb
        self._escalate_after = max(1.0, float(escalate_after))
        self._last = time.monotonic()
        self._dumped_for_beat = -1.0
        self._escalated_for_beat = -1.0
        self._stop = threading.Event()
        self.stalled = threading.Event()  # test hook: set on each dump
        self.escalated = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last = time.monotonic()
        self.stalled.clear()

    def start(self) -> "QueueWatchdog":
        self._thread = threading.Thread(
            target=self._watch, name=f"racon-watchdog-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()

    def _watch(self) -> None:
        poll = max(0.01, self.timeout / 4.0)
        while not self._stop.wait(poll):
            last = self._last
            idle = time.monotonic() - last
            if idle > self.timeout and self._dumped_for_beat != last:
                self._dumped_for_beat = last
                self.fired += 1
                warn(f"{self.name} stalled for > {self.timeout:.1f}s")
                dump_all_stacks(
                    f"{self.name} made no progress for "
                    f"{self.timeout:.1f}s", self._stream)
                self.stalled.set()
            if (self._escalate_cb is not None
                    and idle > self.timeout * self._escalate_after
                    and self._escalated_for_beat != last):
                self._escalated_for_beat = last
                metrics.inc("faults.stall_escalations")
                warn(f"{self.name} still stalled after "
                     f"{self.timeout * self._escalate_after:.1f}s — "
                     f"escalating to a stall-class fault")
                try:
                    self._escalate_cb()
                except Exception as e:
                    warn(f"{self.name} stall-escalation callback "
                         f"failed: {type(e).__name__}: {e}")
                self.escalated.set()


def queue_watchdog(name: str,
                   escalate_cb=None) -> Optional[QueueWatchdog]:
    """A started watchdog with the flag-configured timeout when the
    sanitizer is on, else None (callers guard beats with ``if wd:``)."""
    if not enabled():
        return None
    return QueueWatchdog(
        flags.get_float("RACON_TPU_SANITIZE_WATCHDOG_S"), name,
        escalate_cb=escalate_cb).start()


# ----------------------------------------------------- lock-order witness

class LockOrderWitness:
    """Acquisition-order recorder over the project's named locks.

    Every successful acquire of a :class:`WitnessedLock` while the
    thread already holds others adds directed edges ``held -> acquired``
    to a process-wide graph, stamped (on first sight only — steady-state
    cost is a TLS list append) with the acquiring stack.  A cycle in
    that graph is a potential deadlock: two threads can reach the two
    edges' program points concurrently and wait on each other forever,
    whether or not *this* run's interleaving did.  :meth:`report`
    prints every cycle with the first-seen stack of each edge on it —
    wired to process exit via :func:`lock_witness`, and exercised by
    the exec/serve chaos soaks under ``RACON_TPU_SANITIZE=1``.

    Same-name edges are skipped: instances of one lock *class* (per-
    shard keepers, say) share a witness name, and nesting two distinct
    instances is ordered by a different key than the name records."""

    def __init__(self):
        self._mu = threading.Lock()
        # (held name, acquired name) -> first-seen acquiring stack
        self._edges: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            fresh = [(p, name) for p in held
                     if p != name and (p, name) not in self._edges]
            if fresh:
                stack = "".join(traceback.format_stack()[:-1])
                with self._mu:
                    for edge in fresh:
                        self._edges.setdefault(edge, stack)
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Every distinct simple cycle in the recorded order graph,
        as name lists (``[a, b]`` means ``a -> b -> a``)."""
        edges = self.edges()
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        out: List[List[str]] = []
        seen: set = set()

        def dfs(node: str, path: List[str]) -> None:
            if len(path) > 32:   # defensive: graphs here are tiny
                return
            for nxt in adj.get(node, ()):
                if nxt in path:
                    cyc = path[path.index(nxt):]
                    # canonical rotation (not a set): A->B->C->A and its
                    # reverse are DIFFERENT potential deadlocks over the
                    # same locks and must both report
                    k = cyc.index(min(cyc))
                    key = tuple(cyc[k:] + cyc[:k])
                    if key not in seen:
                        seen.add(key)
                        out.append(cyc)
                else:
                    dfs(nxt, path + [nxt])

        for start in sorted(adj):
            dfs(start, [start])
        return out

    def report(self, stream=None) -> int:
        """Print every cycle (with each edge's first-seen acquiring
        stack) to ``stream`` (stderr default); returns the cycle
        count.  Registered at process exit by :func:`lock_witness`."""
        cycles = self.cycles()
        if not cycles:
            return 0
        stream = stream if stream is not None else sys.stderr
        edges = self.edges()
        lines: List[str] = []
        for cyc in cycles:
            ring = " -> ".join(cyc + [cyc[0]])
            lines.append(f"[racon_tpu::sanitize] lock-order witness: "
                         f"cycle {ring} (potential deadlock)")
            for a, b in zip(cyc, cyc[1:] + [cyc[0]]):
                lines.append(f"  edge {a} -> {b} first acquired at:")
                lines.append(edges.get((a, b), "  <stack unavailable>")
                             .rstrip("\n"))
        print("\n".join(lines), file=stream)
        stream.flush()
        metrics.set_gauge("sanitize.lock_order_cycles", len(cycles))
        return len(cycles)


_witness: Optional[LockOrderWitness] = None
_witness_mu = threading.Lock()


def lock_witness() -> LockOrderWitness:
    """The process-wide witness (created on first use; the exit-time
    cycle report is registered exactly once)."""
    global _witness
    with _witness_mu:
        if _witness is None:
            _witness = LockOrderWitness()
            atexit.register(_witness.report)
    return _witness


class WitnessedLock:
    """A ``threading.Lock`` that reports its acquisition order to a
    :class:`LockOrderWitness` under the lock's witness *name* (one name
    per coordination point, shared by instances of the same class).

    Duck-type compatible with ``threading.Condition(lock)``: the
    Condition's default ``_release_save``/``_acquire_restore``/
    ``_is_owned`` fallbacks drive ``acquire``/``release``, so a
    ``cond.wait()`` correctly pops and re-pushes the witness's held
    record around the sleep."""

    def __init__(self, name: str,
                 witness: Optional[LockOrderWitness] = None):
        self.name = name
        self._lock = threading.Lock()
        self._witness = witness if witness is not None else lock_witness()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._witness.note_acquire(self.name)
        return ok

    def release(self) -> None:
        self._witness.note_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<WitnessedLock {self.name!r} at {id(self):#x}>"


def named_lock(name: str):
    """A lock for a named cross-thread coordination point: witnessed
    (:class:`WitnessedLock`) when the sanitizer is armed at creation
    time, a plain ``threading.Lock`` otherwise — the zero-overhead
    default mirrors every other sanitizer half."""
    if enabled():
        return WitnessedLock(name)
    return threading.Lock()


# ------------------------------------------------------------------------
# process-exit contract audit (the runtime half of the round-22 contract
# layer): the static rules prove every EMISSION SITE is registered; this
# audit reports the other direction at the end of a real run — names the
# registry promises that the process never actually produced.


def contract_audit(stream=None) -> Dict[str, List[str]]:
    """Diff the contract registry against what the process really
    emitted: registered metrics no site ever wrote
    (``never_emitted``), and report keys whose backing metric/span
    timer (:data:`racon_tpu.contracts.REPORT_BACKING`) never fired —
    i.e. keys the report carries only because the emitters defaulted
    them (``defaulted_keys``).  Informational, never fatal: a CLI run
    legitimately never touches the serve metrics.  Counts land in the
    ``sanitize.contract_*`` gauges so chaos-soak reports carry them."""
    seen = metrics.seen_names()
    audit: Dict[str, List[str]] = {"never_emitted": [], "defaulted_keys": []}
    if not seen:
        return audit     # nothing ran — everything would be "missing"

    def emitted(name: str) -> bool:
        if name in seen:
            return True
        return any(s.startswith(name + ".") for s in seen)

    audit["never_emitted"] = sorted(
        m for m in contracts.METRICS if m not in seen)
    audit["defaulted_keys"] = sorted(
        key for key, backing in contracts.REPORT_BACKING.items()
        if not emitted(backing))
    stream = stream if stream is not None else sys.stderr
    ne, dk = audit["never_emitted"], audit["defaulted_keys"]
    metrics.set_gauge("sanitize.contract_never_emitted", len(ne))
    metrics.set_gauge("sanitize.contract_defaulted_keys", len(dk))
    if ne:
        print(f"[racon_tpu::sanitize] contract audit: "
              f"{len(ne)} registered metric(s) never emitted this "
              f"process: {', '.join(ne[:12])}"
              + (" ..." if len(ne) > 12 else ""), file=stream)
    if dk:
        print(f"[racon_tpu::sanitize] contract audit: "
              f"{len(dk)} report key(s) backed by silent metrics "
              f"(validator defaults): {', '.join(dk[:12])}"
              + (" ..." if len(dk) > 12 else ""), file=stream)
    stream.flush()
    return audit


def _exit_contract_audit() -> None:
    # armed lazily at exit so a test toggling RACON_TPU_SANITIZE
    # mid-process still gets/loses the audit correctly
    if enabled():
        try:
            contract_audit()
        except Exception:  # graftlint: disable=swallowed-exception (exit path: a dead stderr must not mask the real exit status)
            pass


atexit.register(_exit_contract_audit)
