"""racon_tpu.serve — the resident polishing service (ROADMAP item 3).

A long-lived server process (``racon --serve SOCK`` /
``python -m racon_tpu.serve SOCK``) keeps one warm engine pool per
local chip and executes submitted polish jobs through the existing
:meth:`Polisher.run` pipeline with those engines injected, so a job's
latency is compute, not the 16–80 s cold XLA compile every one-shot
invocation pays.  Jobs arrive over a newline-JSON unix-socket protocol
(:mod:`.protocol`), pass admission control driven by the exec planner's
cost model, walk the round-12 degradation ladder on faults, and return
their polished FASTA byte-identical to a one-shot CLI run, alongside a
per-job schema-validated run report (:mod:`.service`).  The thin
client (``racon --submit SOCK ...``, :mod:`.client`) streams the FASTA
to stdout exactly like the one-shot CLI would.
"""

from .client import ServiceClient, submit_and_stream  # noqa: F401
from .service import PolishServer  # noqa: F401
