"""``python -m racon_tpu.serve SOCK [racon options]`` — the module
entry for the resident polishing service; equivalent to
``racon --serve SOCK [options]`` (the options set the server's engine
profile: -m/-x/-g/-b, -t, -c, --tpualigner-batches, --chips,
--serve-budget, --compile-cache; ``--serve-dir D`` makes the service
crash-safe — durable job journal, result spool, restart recovery)."""

import sys

from ..cli import main

sys.exit(main(["--serve"] + sys.argv[1:]))
