"""Client side of the resident polishing service: a thin connection
wrapper plus the ``racon --submit`` entry that streams a job's polished
FASTA back **byte-identical** to a one-shot CLI run's stdout.

The client never re-encodes the payload: the server announces
``"bytes": N`` and the client copies exactly N raw bytes to the output
stream — the byte-identity contract is structural, not best-effort.
"""

from __future__ import annotations

import os
import socket
import sys
from typing import Optional, Tuple

from . import protocol


class ServiceClient:
    """One connection to a :class:`PolishServer` socket.  Usable as a
    context manager; every helper returns the decoded response header
    (and :meth:`result` the payload too)."""

    def __init__(self, socket_path: str, timeout_s: float = 600.0):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout_s)
        self.sock.connect(socket_path)
        self.rfile = self.sock.makefile("rb")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def close(self) -> None:
        self.rfile.close()
        self.sock.close()

    def _roundtrip(self, msg: dict) -> dict:
        protocol.send_msg(self.sock, msg)
        resp = protocol.read_msg(self.rfile)
        if resp is None:
            raise ConnectionError(
                "server closed the connection mid-request")
        return resp

    def ping(self) -> dict:
        return self._roundtrip({"op": "ping"})

    def stats(self) -> dict:
        return self._roundtrip({"op": "stats"})

    def submit(self, spec: dict) -> dict:
        return self._roundtrip({"op": "submit", "spec": spec})

    def status(self, job_id: str) -> dict:
        return self._roundtrip({"op": "status", "job": job_id})

    def cancel(self, job_id: str) -> dict:
        return self._roundtrip({"op": "cancel", "job": job_id})

    def shutdown(self) -> dict:
        return self._roundtrip({"op": "shutdown"})

    def result(self, job_id: str, timeout_s: Optional[float] = None,
               keep: bool = False) -> Tuple[dict, Optional[bytes]]:
        """Block until the job is terminal; returns ``(header,
        payload)`` — payload is the polished FASTA bytes on success,
        None on failure/timeout (the header carries the reason and the
        per-job run report either way).  The default server-side wait
        is derived from THIS connection's socket timeout (minus a
        margin), so the server answers before the client's own read
        would give up — an explicit ``timeout_s`` longer than the
        socket timeout cannot be honored and is clamped the same
        way."""
        sock_timeout = self.sock.gettimeout()
        if sock_timeout is not None:
            bound = max(1.0, sock_timeout - 5.0)
            timeout_s = bound if timeout_s is None \
                else min(timeout_s, bound)
        elif timeout_s is None:
            timeout_s = 3600.0
        header = self._roundtrip({"op": "result", "job": job_id,
                                  "timeout_s": timeout_s,
                                  "keep": keep})
        if not header.get("ok") or "bytes" not in header:
            return header, None
        payload = protocol.read_exact(self.rfile, int(header["bytes"]))
        return header, payload


def spec_from_args(args) -> dict:
    """A submit spec from the parsed ``racon`` CLI namespace — the
    one-shot option surface forwarded verbatim, so ``--submit`` output
    matches the equivalent one-shot invocation byte for byte."""
    return {
        "sequences": os.path.abspath(args.sequences),
        "overlaps": os.path.abspath(args.overlaps),
        "target_sequences": os.path.abspath(args.target_sequences),
        "fragment_correction": bool(args.fragment_correction),
        "window_length": args.window_length,
        "quality_threshold": args.quality_threshold,
        "error_threshold": args.error_threshold,
        "no_trimming": bool(args.no_trimming),
        "match": args.match, "mismatch": args.mismatch,
        "gap": args.gap,
        "banded": bool(args.tpu_banded_alignment),
        "threads": args.threads,
        "include_unpolished": bool(args.include_unpolished),
    }


def submit_and_stream(socket_path: str, spec: dict, out,
                      report_path: Optional[str] = None,
                      timeout_s: float = 3600.0) -> int:
    """The ``racon --submit`` flow: submit, wait, stream the FASTA to
    ``out``, optionally persist the per-job run report.  Returns the
    process exit code (0 = polished bytes were streamed)."""
    with ServiceClient(socket_path, timeout_s=timeout_s) as client:
        resp = client.submit(spec)
        if not resp.get("ok"):
            print(f"[racon_tpu::serve] submission rejected: "
                  f"{resp.get('error')}", file=sys.stderr)
            return 1
        job_id = resp["job"]
        print(f"[racon_tpu::serve] job {job_id} submitted "
              f"({resp.get('cost_bytes', 0) >> 20} MB estimated)",
              file=sys.stderr)
        header, payload = client.result(job_id, timeout_s=timeout_s)
    if report_path and header.get("report"):
        from ..obs import report as obs_report
        obs_report.write_report(report_path, header["report"])
    if payload is None:
        print(f"[racon_tpu::serve] job {job_id} "
              f"{header.get('state')}: {header.get('error')}",
              file=sys.stderr)
        return 1
    out.write(payload)
    out.flush()
    print(f"[racon_tpu::serve] job {job_id} done in "
          f"{header.get('wall_s', 0.0):.2f}s "
          f"(compile {header.get('compile_s', 0.0):.2f}s, "
          f"engine={header.get('engine', '-')})", file=sys.stderr)
    return 0
