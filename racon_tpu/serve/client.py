"""Client side of the resident polishing service: a connection wrapper
with bounded retry, plus the ``racon --submit`` entry that streams a
job's polished FASTA back **byte-identical** to a one-shot CLI run's
stdout.

The client never re-encodes the payload: the server announces
``"bytes": N`` and the client copies exactly N raw bytes to the output
stream — the byte-identity contract is structural, not best-effort.

Robustness (round 16): connects retry with exponential backoff and
deterministic jitter (the shared :func:`racon_tpu.faults.backoff_s`
formula — not a second implementation), bounded by
``RACON_TPU_CLIENT_RETRIES`` × ``RACON_TPU_CLIENT_BACKOFF_S``; and
:func:`submit_and_stream` survives a server death mid-job by
reconnecting and resubmitting under the SAME idempotency key — a
``--serve-dir`` server (restarted by its operator/orchestrator)
recognizes the key, returns the existing journaled job, and the fetch
resumes where it left off with zero duplicated compute.
"""

from __future__ import annotations

import os
import socket
import sys
import time
from typing import Optional, Tuple

from .. import faults, flags
from . import protocol


def parse_tcp_address(address: str) -> Optional[Tuple[str, int]]:
    """``host:port`` -> ``(host, port)`` when ``address`` names a TCP
    endpoint (the fleet gateway's listener), else None — a unix-socket
    path.  Disambiguation: a path contains ``/`` or exists on disk; a
    TCP address is ``host:port`` with a numeric port (IPv6 literals
    use the last colon)."""
    if "/" in address or os.path.exists(address):
        return None
    host, sep, port = address.rpartition(":")
    if not sep or not host or not port.isdigit():
        return None
    return host, int(port)


class ServiceClient:
    """One connection to a :class:`PolishServer` socket — or, given a
    ``host:port`` address, to the fleet gateway's TCP listener (same
    protocol, same helpers) — established with bounded retry + backoff
    (a server that is restarting — socket missing or refusing — is
    retried, not failed).  Usable as a context manager; every helper
    returns the decoded response header (and :meth:`result` the
    payload too)."""

    def __init__(self, socket_path: str, timeout_s: float = 600.0,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        self.retries = max(0, flags.get_int("RACON_TPU_CLIENT_RETRIES")
                           if retries is None else retries)
        self.backoff_base = max(0.0, flags.get_float(
            "RACON_TPU_CLIENT_BACKOFF_S")
            if backoff_s is None else backoff_s)
        self.sock: Optional[socket.socket] = None
        self.rfile = None
        self._connect()

    def _connect(self) -> None:
        last: Optional[BaseException] = None
        for k in range(self.retries + 1):
            try:
                faults.check("serve.socket")
                tcp = parse_tcp_address(self.socket_path)
                if tcp is not None:
                    sock = socket.create_connection(
                        tcp, timeout=self.timeout_s)
                    sock.settimeout(self.timeout_s)
                else:
                    sock = socket.socket(socket.AF_UNIX,
                                         socket.SOCK_STREAM)
                    sock.settimeout(self.timeout_s)
                    sock.connect(self.socket_path)
            except (OSError, ConnectionError) as e:
                last = e
                if k >= self.retries:
                    break
                delay = faults.backoff_s(
                    self.backoff_base, k,
                    f"{self.socket_path}:{os.getpid()}:{k}")
                time.sleep(delay)
                continue
            self.sock = sock
            self.rfile = sock.makefile("rb")
            return
        raise ConnectionError(
            f"could not connect to {self.socket_path} after "
            f"{self.retries + 1} attempt(s): {last}")

    def reconnect(self) -> None:
        """Drop the (possibly dead) connection and re-establish it with
        the same retry budget."""
        self.close()
        self._connect()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def close(self) -> None:
        if self.rfile is not None:
            try:
                self.rfile.close()
            except OSError:
                pass
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.rfile = None
        self.sock = None

    def _roundtrip(self, msg: dict) -> dict:
        protocol.send_msg(self.sock, msg)
        resp = protocol.read_msg(self.rfile)
        if resp is None:
            raise ConnectionError(
                "server closed the connection mid-request")
        return resp

    def ping(self) -> dict:
        return self._roundtrip({"op": "ping"})

    def stats(self) -> dict:
        return self._roundtrip({"op": "stats"})

    def submit(self, spec: dict, key: Optional[str] = None) -> dict:
        """Submit a job; ``key`` is an idempotency key — resubmitting
        under the same key returns the existing job (``existing`` in
        the response) instead of duplicating compute."""
        msg = {"op": "submit", "spec": spec}
        if key is not None:
            msg["key"] = key
        return self._roundtrip(msg)

    def status(self, job_id: str) -> dict:
        return self._roundtrip({"op": "status", "job": job_id})

    def cancel(self, job_id: str) -> dict:
        return self._roundtrip({"op": "cancel", "job": job_id})

    def preempt(self, job_id: str) -> dict:
        """Ask the server to drain a job (fleet preemption): a queued
        job is released immediately (``drained: true``); a running
        one drains at its next ladder boundary or completes first."""
        return self._roundtrip({"op": "preempt", "job": job_id})

    def shutdown(self, mode: str = "now") -> dict:
        """Stop the server; ``mode="drain"`` finishes queued +
        in-flight jobs and flushes the journal first."""
        return self._roundtrip({"op": "shutdown", "mode": mode})

    def result(self, job_id: str, timeout_s: Optional[float] = None,
               keep: bool = False) -> Tuple[dict, Optional[bytes]]:
        """Block until the job is terminal; returns ``(header,
        payload)`` — payload is the polished FASTA bytes on success,
        None on failure/timeout (the header carries the reason and the
        per-job run report either way).  The default server-side wait
        is derived from THIS connection's socket timeout (minus a
        margin), so the server answers before the client's own read
        would give up — an explicit ``timeout_s`` longer than the
        socket timeout cannot be honored and is clamped the same
        way."""
        sock_timeout = self.sock.gettimeout()
        if sock_timeout is not None:
            bound = max(1.0, sock_timeout - 5.0)
            timeout_s = bound if timeout_s is None \
                else min(timeout_s, bound)
        elif timeout_s is None:
            timeout_s = 3600.0
        header = self._roundtrip({"op": "result", "job": job_id,
                                  "timeout_s": timeout_s,
                                  "keep": keep})
        if not header.get("ok") or "bytes" not in header:
            return header, None
        payload = protocol.read_exact(self.rfile, int(header["bytes"]))
        return header, payload


def spec_from_args(args) -> dict:
    """A submit spec from the parsed ``racon`` CLI namespace — the
    one-shot option surface forwarded verbatim, so ``--submit`` output
    matches the equivalent one-shot invocation byte for byte."""
    from ..io import parsers
    spec = {
        "sequences": os.path.abspath(args.sequences),
        # the --overlaps auto sentinel travels verbatim (no file)
        "overlaps": (args.overlaps
                     if parsers.is_auto_overlaps(args.overlaps)
                     else os.path.abspath(args.overlaps)),
        "target_sequences": os.path.abspath(args.target_sequences),
        "fragment_correction": bool(args.fragment_correction),
        "window_length": args.window_length,
        "quality_threshold": args.quality_threshold,
        "error_threshold": args.error_threshold,
        "no_trimming": bool(args.no_trimming),
        "match": args.match, "mismatch": args.mismatch,
        "gap": args.gap,
        "banded": bool(args.tpu_banded_alignment),
        "threads": args.threads,
        "include_unpolished": bool(args.include_unpolished),
    }
    # fleet routing hints ride only when given (--tenant/--priority):
    # normalize_spec fills the defaults, and plain serve submits stay
    # byte-for-byte what they were before the fleet round
    if getattr(args, "tenant", None):
        spec["tenant"] = args.tenant
    if getattr(args, "priority", None):
        spec["priority"] = int(args.priority)
    return spec


def _eprint(msg: str) -> None:
    print(f"[racon_tpu::serve] {msg}", file=sys.stderr, flush=True)


def submit_and_stream(socket_path: str, spec: dict, out,
                      report_path: Optional[str] = None,
                      timeout_s: float = 3600.0,
                      idempotency_key: Optional[str] = None) -> int:
    """The ``racon --submit`` flow: submit, wait, stream the FASTA to
    ``out``, optionally persist the per-job run report.  Returns the
    process exit code (0 = polished bytes were streamed).

    Crash-safe against the SERVER dying mid-job: every submission
    carries an idempotency key (auto-generated unless supplied), and a
    connection lost at any point reconnects with backoff and
    resubmits under the same key — a restarted ``--serve-dir`` server
    returns the existing journaled job (recovered result included),
    so the retry never duplicates compute and the streamed bytes stay
    identical.  Admission rejections are NOT retried (they are
    deterministic answers, not faults)."""
    key = idempotency_key or (
        f"{socket.gethostname()}:{os.getpid()}:{time.monotonic_ns()}")
    retries = max(0, flags.get_int("RACON_TPU_CLIENT_RETRIES"))
    base = max(0.0, flags.get_float("RACON_TPU_CLIENT_BACKOFF_S"))
    attempt = 0
    while True:
        try:
            with ServiceClient(socket_path,
                               timeout_s=timeout_s) as client:
                resp = client.submit(spec, key=key)
                if not resp.get("ok"):
                    _eprint(f"submission rejected: {resp.get('error')}")
                    return 1
                job_id = resp["job"]
                if resp.get("existing"):
                    _eprint(f"job {job_id} already journaled under "
                            f"this key — resuming it")
                else:
                    _eprint(f"job {job_id} submitted "
                            f"({resp.get('cost_bytes', 0) >> 20} MB "
                            f"estimated)")
                header, payload = client.result(job_id,
                                                timeout_s=timeout_s)
        except (OSError, ConnectionError) as e:
            attempt += 1
            if attempt > retries:
                _eprint(f"giving up after {retries} reconnect "
                        f"attempt(s): {e}")
                return 1
            delay = faults.backoff_s(base, attempt - 1,
                                     f"{key}:{attempt}")
            _eprint(f"connection lost ({e}) — reconnecting in "
                    f"{delay:.2f}s (attempt {attempt}/{retries}; the "
                    f"idempotency key resumes the same job)")
            time.sleep(delay)
            continue
        break
    if report_path and header.get("report"):
        from ..obs import report as obs_report
        obs_report.write_report(report_path, header["report"])
    if payload is None:
        _eprint(f"job {job_id} {header.get('state')}: "
                f"{header.get('error')}")
        return 1
    out.write(payload)
    out.flush()
    _eprint(f"job {job_id} done in {header.get('wall_s', 0.0):.2f}s "
            f"(compile {header.get('compile_s', 0.0):.2f}s, "
            f"engine={header.get('engine', '-')})")
    return 0
