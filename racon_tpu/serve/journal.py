"""Durable job journal + result spool for the resident polishing
service (``racon --serve SOCK --serve-dir D``).

Round 14 made polishing resident; this module (round 16) makes it
*crash-safe*: every job lifecycle transition is journaled to an
append-only, per-record-fsync'd file, and result payloads are spooled
to CRC32-verified files instead of held in server RAM — so a server
OOM, preemption or SIGKILL loses nothing, and a restart from the same
``--serve-dir`` replays the journal and picks every job back up
(:meth:`racon_tpu.serve.service.PolishServer._recover`).

Serve-dir layout::

    D/
      journal.jsonl      # append-only, one JSON record per line
      spool/             # result payloads: result_<job>.fasta

Record grammar (``rec`` selects; every record carries ``job``):

- ``submitted`` — ``{job, key, cost, unix, spec}``: admitted into the
  queue (``key`` is the client's idempotency key, if any);
- ``running`` — ``{job, worker, run}``: an execution incarnation
  began.  The COUNT of these per job is the crash ladder's input: a
  job whose journal shows N running records and no terminal record
  died N times with the server, and recovery walks it down the same
  degradation ladder the round-12 exec layer uses (retry → CPU
  engines → fail) instead of an infinite redo loop;
- ``done`` — ``{job, bytes, crc32, spool, wall_s, engine}``: the
  payload is in the spool (size + CRC recorded here, verified on
  every post-restart fetch);
- ``failed`` / ``cancelled`` — terminal without a payload;
- ``collected`` — the one-fetch payload was streamed to a client; the
  job is fully retired and the next compaction drops its records (and
  its spool file).

Durability protocol: appends go through the shared
:func:`racon_tpu.exec.manifest.append_durable` (write + flush + fsync
per record), spool files and compaction rewrites go through the shared
:func:`racon_tpu.obs.report.atomic_write_bytes` tmp → fsync → rename
protocol plus a directory fsync — the exact crash-ordering contract
the exec manifest established.  A torn tail line (the crash happened
mid-append) is dropped on replay; anything before it is complete by
the fsync ordering.

**Compaction** keeps a long-lived server's serve-dir bounded: on every
startup (after replay) and every :attr:`JobJournal.compact_every`
appended records, the journal is atomically rewritten to live-jobs-only
records — live means queued, running, or done-but-uncollected; fully
retired jobs (collected, or terminal without a payload owed) drop out,
along with orphaned spool files and ``*.tmp.*`` litter from crashed
writes (the ``_clean_work_dir`` sweep, re-homed).
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import List, Optional, Tuple

from .. import contracts, faults, sanitize
from ..exec import manifest as mf
from ..obs import metrics
from ..obs.report import atomic_write_bytes
from ..utils.logger import log_swallowed, warn

JOURNAL_NAME = "journal.jsonl"
SPOOL_DIR = "spool"

# record types (the "rec" field) — declared in racon_tpu/contracts.py
# as the JOB_MACHINE vocabulary; the state-transition lint rule rejects
# appends minting any other record type
SUBMITTED = contracts.JOB_SUBMITTED
RUNNING = contracts.JOB_RUNNING
DONE = contracts.JOB_DONE
FAILED = contracts.JOB_FAILED
CANCELLED = contracts.JOB_CANCELLED
COLLECTED = contracts.JOB_COLLECTED


class JobJournal:
    """The serve-dir's journal + spool, behind one named lock
    (``serve.journal`` — under ``RACON_TPU_SANITIZE=1`` it feeds the
    round-15 lock-order witness together with the scheduler locks)."""

    # appended records between automatic compactions (class attribute:
    # the size-bound test shrinks it)
    compact_every = 256

    def __init__(self, serve_dir: str):
        self.serve_dir = os.path.abspath(serve_dir)
        self.spool_dir = os.path.join(self.serve_dir, SPOOL_DIR)
        os.makedirs(self.spool_dir, exist_ok=True)
        self.path = os.path.join(self.serve_dir, JOURNAL_NAME)
        self.lock = sanitize.named_lock("serve.journal")
        self._f = None
        self._closed = False
        self.appends_since_rewrite = 0
        self.sweep_tmp()

    # ------------------------------------------------------------ hygiene

    def sweep_tmp(self) -> int:
        """Drop ``*.tmp.*`` litter left by atomic writes that crashed
        between create and rename (their monotonic-ns names are never
        reused, so a crash-restarted serve-dir would otherwise collect
        them forever — the ``_clean_work_dir`` rule, re-homed)."""
        swept = 0
        for d in (self.serve_dir, self.spool_dir):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if ".tmp" not in name:
                    continue
                try:
                    os.unlink(os.path.join(d, name))
                    swept += 1
                except OSError as e:
                    log_swallowed("serve: tmp-litter sweep failed", e)
        return swept

    # ------------------------------------------------------------- append

    def _handle(self):
        if self._f is None:
            # fsync'd-append protocol: the handle stays open for the
            # journal's life; every append() flushes + fsyncs through
            # mf.append_durable before returning.  Every _f write site
            # (here, rewrite_locked, close) runs with self.lock held
            # by its caller — the guard is interprocedural.
            # graftlint: disable=lock-discipline (every caller holds self.lock; the guard is interprocedural)
            self._f = open(self.path, "ab")
        return self._f

    def _truncate_to_locked(self, pos: int) -> None:
        """Roll a failed append back to the pre-write offset (caller
        holds the lock): a write/flush that raised may have landed
        SOME bytes, and retrying on top of them would weld a torn
        prefix onto the retried record — one corrupt line that halts
        replay for every later job.  The handle is discarded (its
        buffer may hold the partial record) and the file truncated."""
        if self._f is not None:
            try:
                self._f.close()
            except OSError as e:
                log_swallowed("serve: failed-append handle close", e)
            self._f = None
        try:
            with open(self.path, "ab") as f:
                f.truncate(pos)
        except OSError as e:
            log_swallowed("serve: journal rollback truncate failed "
                          "(replay drops the torn line)", e)

    def append(self, rec: dict, retries: int = 3) -> None:
        """Durably append one lifecycle record (fsync'd before return),
        with the same transient-I/O retry ``manifest.durable_write``
        gives checkpoint writes — a blip on a *journal* write must not
        kill a server whose actual work succeeded.  A failed attempt
        rolls the file back to its pre-append size first, so a retry
        can never produce a torn-then-duplicate record."""
        blob = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        delay = 0.05
        for k in range(retries + 1):
            try:
                with self.lock:
                    if self._closed:
                        return
                    faults.check("serve.journal")
                    f = self._handle()
                    # prior appends always flushed+fsync'd, so st_size
                    # IS the logical end — the rollback point
                    pos = os.fstat(f.fileno()).st_size
                    try:
                        # fsync-under-lock is the POINT of this lock: a
                        # record must hit disk before another thread's
                        # record (or a compaction rewrite) interleaves
                        # graftlint: disable=blocking-under-lock (the lock exists to serialize fsync'd appends against compaction)
                        mf.append_durable(f, blob)
                    except OSError:
                        self._truncate_to_locked(pos)
                        raise
                    self.appends_since_rewrite += 1
                metrics.inc("serve.journal_records")
                return
            except OSError as e:
                if k >= retries or \
                        faults.classify(e) != faults.CLASS_TRANSIENT:
                    raise
                warn(f"transient fault appending to the job journal "
                     f"({e}) — retrying in {delay:.2f}s")
                time.sleep(delay)
                delay *= 2

    # ------------------------------------------------------------- replay

    def replay(self) -> List[dict]:
        """Every complete record, in append order.  A torn/corrupt line
        ends the replay there: per-record fsync guarantees everything
        BEFORE a torn tail is complete, and a mid-file corruption means
        the disk lied — later records' ordering cannot be trusted, and
        correct-over-salvaged wins (the affected jobs simply re-run)."""
        out: List[dict] = []
        try:
            with open(self.path, "rb") as f:
                lines = f.read().split(b"\n")
        except FileNotFoundError:
            return out
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                warn(f"job journal line {i + 1} is torn/corrupt — "
                     f"replay stops there (jobs past it re-run)")
                break
            if isinstance(rec, dict):
                out.append(rec)
        return out

    # --------------------------------------------------------- compaction

    def rewrite_locked(self, records: List[dict]) -> None:
        """Compaction core — caller holds :attr:`lock` (the server
        snapshots its live jobs and rewrites under ONE hold, so no
        append can slip between snapshot and rewrite and be lost):
        atomically replace the journal with the given live-jobs-only
        records (tmp → fsync → rename + directory fsync)."""
        blob = b"".join(
            json.dumps(r, separators=(",", ":")).encode() + b"\n"
            for r in records)
        if self._f is not None:
            self._f.close()
            self._f = None
        # the rename must land before appends resume — same
        # serialize-the-durable-write rationale as append()
        atomic_write_bytes(self.path, blob)
        mf.fsync_dir(self.serve_dir)
        # graftlint: disable=lock-discipline (every caller holds self.lock; the guard is interprocedural)
        self.appends_since_rewrite = 0
        metrics.inc("serve.journal_compactions")

    def rewrite(self, records: List[dict]) -> None:
        """:meth:`rewrite_locked` under the journal lock (the
        standalone-compaction entry tests use)."""
        with self.lock:
            # graftlint: disable=blocking-under-lock (compaction rewrite must not interleave with appends)
            self.rewrite_locked(records)

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # -------------------------------------------------------------- spool

    def spool_name(self, job_id: str) -> str:
        return f"result_{job_id}.fasta"

    def spool_path(self, job_id: str) -> str:
        return os.path.join(self.spool_dir, self.spool_name(job_id))

    def spool_write(self, job_id: str, blob: bytes) \
            -> Tuple[str, int, int]:
        """Durably spool one result payload (atomic write); returns
        ``(spool name, byte size, crc32)`` for the ``done`` record the
        fetch path verifies against."""
        crc = zlib.crc32(blob)
        atomic_write_bytes(self.spool_path(job_id), blob)
        mf.fsync_dir(self.spool_dir)
        return self.spool_name(job_id), len(blob), crc

    def spool_read(self, job_id: str, size: int,
                   crc32: int) -> Optional[bytes]:
        """The spooled payload, verified against its recorded size and
        CRC32 — None when missing/truncated/corrupt (the caller
        re-queues the job, mirroring the exec part-verification
        pass)."""
        try:
            with open(self.spool_path(job_id), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if len(blob) != size or zlib.crc32(blob) != crc32:
            warn(f"result spool for job {job_id} failed verification "
                 f"({len(blob)}B vs recorded {size}B) — treating the "
                 f"result as lost")
            return None
        return blob

    def spool_unlink(self, job_id: str) -> None:
        try:
            os.unlink(self.spool_path(job_id))
        except FileNotFoundError:
            pass
        except OSError as e:
            log_swallowed("serve: spool unlink failed", e)

    def sweep_spool(self, keep_jobs) -> int:
        """Unlink spool files whose job is not in ``keep_jobs`` —
        orphans of collected/compacted jobs (run with compaction)."""
        keep = {self.spool_name(j) for j in keep_jobs}
        swept = 0
        try:
            names = os.listdir(self.spool_dir)
        except OSError:
            return 0
        for name in names:
            if name in keep or not name.startswith("result_"):
                continue
            try:
                os.unlink(os.path.join(self.spool_dir, name))
                swept += 1
            except OSError as e:
                log_swallowed("serve: orphan spool sweep failed", e)
        return swept

    # -------------------------------------------------------------- close

    def close(self) -> None:
        with self.lock:
            self._closed = True
            if self._f is not None:
                try:
                    self._f.close()
                except OSError as e:
                    log_swallowed("serve: journal close failed", e)
                self._f = None
