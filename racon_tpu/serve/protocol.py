"""The resident polishing service's wire protocol: newline-delimited
JSON over a unix-domain stream socket, with one raw-bytes escape for the
polished FASTA payload.

Every request and every response is ONE ``\\n``-terminated JSON object.
A connection may carry any number of requests; responses come back in
request order.  The single exception to the line discipline: a
successful ``result`` response announces ``"bytes": N`` in its header
line and is followed by exactly N raw bytes of polished FASTA — the
client reads them verbatim (no re-encoding, no base64), which is what
keeps a ``racon --submit`` stream byte-identical to the one-shot CLI's
stdout.

Requests (``op`` selects):

- ``ping`` — liveness; response echoes server identity, uptime,
  healthy worker count, serve-dir and drain state.
- ``submit`` — a job spec (input paths + polishing options, see
  :data:`SPEC_KEYS`) plus an optional top-level ``key`` (client
  idempotency key): a resubmission under an already-journaled key
  returns the EXISTING job (``"existing": true``) instead of
  duplicating compute — the hook the retrying client uses to survive
  a server restart.  Response carries the job id, or ``ok: false``
  with the admission-rejection reason.  The spec's optional ``tenant``
  and ``priority`` fields are the fleet gateway's routing hints
  (round 23); a plain serve host records them but schedules FIFO.
- ``status`` — one job's state (queued/running/done/failed/cancelled),
  queue position, cost estimate, ladder attempts so far.
- ``result`` — blocks (bounded by ``timeout_s``) until the job is
  terminal, then returns the header + FASTA payload (and the per-job
  ``run_report`` alongside).  With ``--serve-dir`` the payload streams
  from the CRC-verified result spool, so it survives a server restart
  until one successful fetch.
- ``cancel`` — cancels a QUEUED job; a running job cannot be safely
  interrupted mid-dispatch and the response says so.
- ``stats`` — server-level counters (jobs done/failed, in-flight
  footprint, queue depth, per-tenant queue depths, a slot-health
  summary (healthy/quarantined counts), slot quarantine/restart and
  journal recovery counters).
- ``shutdown`` — ``{"mode": "now"}`` (default) stops accepting and
  lets running jobs finish; ``{"mode": "drain"}`` additionally waits
  for the QUEUE to empty (bounded by ``RACON_TPU_SERVE_DRAIN_S``) and
  flushes/compacts the job journal before exit — the same protocol a
  ``SIGTERM`` triggers.

Paths in a job spec are server-local: the socket is unix-domain, so
client and server share a filesystem by construction.  The fleet
gateway (``racon --gateway``) speaks this same protocol verbatim over
a TCP listener — there the spec paths must name files on the fleet's
shared filesystem (the gateway and every member host stat them).
"""

from __future__ import annotations

import json
import socket
from typing import Optional, Tuple

# every key a submit spec may carry; ("option", default) pairs mirror
# the CLI's polishing knobs (cli.build_parser) so --submit round-trips
# them verbatim
SPEC_DEFAULTS = {
    "fragment_correction": False,
    "window_length": 500,
    "quality_threshold": 10.0,
    "error_threshold": 0.3,
    "no_trimming": False,
    "match": 3, "mismatch": -5, "gap": -4,
    "banded": False,
    "threads": 1,
    "include_unpolished": False,
    # fleet routing hints (round 23): which tenant queue the gateway
    # files the job under, and its preemption priority (higher wins;
    # a plain serve host records them but schedules FIFO as before)
    "tenant": "default",
    "priority": 0,
}
SPEC_PATHS = ("sequences", "overlaps", "target_sequences")
SPEC_KEYS = SPEC_PATHS + tuple(SPEC_DEFAULTS)


def encode(obj: dict) -> bytes:
    """One protocol line (compact separators keep headers small)."""
    return json.dumps(obj, separators=(",", ":")).encode() + b"\n"


def send_msg(sock: socket.socket, obj: dict) -> None:
    sock.sendall(encode(obj))


def read_msg(rfile) -> Optional[dict]:
    """Read one JSON line from a socket makefile; None at EOF.  Raises
    ``ValueError`` on a non-JSON or non-object line (the server turns
    that into an error response rather than dying)."""
    line = rfile.readline()
    if not line:
        return None
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"protocol message is not an object: {obj!r}")
    return obj


def read_exact(rfile, n: int) -> bytes:
    """Read exactly ``n`` payload bytes (the FASTA body after a result
    header); raises ``ConnectionError`` on a short read."""
    parts = []
    remaining = n
    while remaining > 0:
        chunk = rfile.read(remaining)
        if not chunk:
            raise ConnectionError(
                f"connection closed {remaining} bytes short of the "
                f"announced {n}-byte payload")
        parts.append(chunk)
        remaining -= len(chunk)
    return b"".join(parts)


def normalize_spec(raw: dict) -> Tuple[Optional[dict], Optional[str]]:
    """Validate + default-fill a submitted job spec.  Returns
    ``(spec, None)`` or ``(None, reason)`` — a malformed spec is an
    admission rejection, never a server fault."""
    if not isinstance(raw, dict):
        return None, f"job spec is not an object: {type(raw).__name__}"
    unknown = set(raw) - set(SPEC_KEYS)
    if unknown:
        return None, f"unknown job spec keys: {sorted(unknown)}"
    spec = {}
    for key in SPEC_PATHS:
        val = raw.get(key)
        if not isinstance(val, str) or not val:
            return None, f"job spec is missing input path {key!r}"
        spec[key] = val
    for key, default in SPEC_DEFAULTS.items():
        val = raw.get(key, default)
        if isinstance(default, bool):
            if not isinstance(val, bool):
                return None, f"job spec {key!r} must be a boolean"
        elif isinstance(default, int):
            if not isinstance(val, int) or isinstance(val, bool):
                return None, f"job spec {key!r} must be an integer"
        elif isinstance(default, float):
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                return None, f"job spec {key!r} must be a number"
            val = float(val)
        else:
            if not isinstance(val, str):
                return None, f"job spec {key!r} must be a string"
        spec[key] = val
    if spec["window_length"] <= 0:
        return None, "job spec window_length must be positive"
    if spec["threads"] < 1:
        return None, "job spec threads must be >= 1"
    if not spec["tenant"]:
        return None, "job spec tenant must be a non-empty string"
    return spec, None
