"""PolishServer: the long-lived polishing daemon (``racon --serve``).

Every one-shot ``racon`` invocation pays the cold XLA compile
(16–80 s at BENCH r04/r05) for kernels whose warm dispatch is
sub-second — fatal for heavy traffic of small jobs (one user's plasmid
or amplicon panel).  The reference amortizes exactly this cost by
reusing its cudapoa/cudaaligner batch objects across fills (SURVEY
§L3); this server is the TPU analog at process granularity: ONE
resident process keeps a warm engine pool alive and executes submitted
polish jobs through the existing :meth:`Polisher.run` pipeline with
those engines injected, so a job's latency is compute, not compile.

Architecture (every piece is an existing subsystem, re-hosted):

- **Warm engine pool** — one :class:`racon_tpu.exec.runner._ChipWorker`
  per local chip (the round-13 slot type; the server passes itself as
  the duck-typed engine profile), each slot owning a device-pinned
  aligner/consensus pair plus a CPU-retry pair.  Engines are built
  eagerly at startup and *never* discarded: jit caches, SWAR probes and
  warm-up compiles survive across every job the server ever runs, and
  ``configure_compile_cache`` persists the executables across server
  restarts.
- **Shape canonicalization** — jobs land on already-compiled
  executables because the ragged consensus stream buckets windows by
  power-of-two lane width against a fixed arena (round 10): two jobs
  with the same polishing parameters share executables regardless of
  their input sizes.  At startup the pool warm-compiles the expected
  profile (``RACON_TPU_SERVE_WARM_SHAPES``) so job #1 is already warm,
  and every admitted job's own geometry is handed to ``warmup_async``
  (shape-deduped) so a genuinely new geometry starts compiling while
  the job waits in queue.
- **Admission control** — the exec planner's resident-footprint cost
  model (:func:`racon_tpu.exec.planner.estimate_job_cost`) gates
  submissions: a job estimated over the budget, a full queue, or a
  parameter set the resident engines cannot serve (the score/banding
  profile is baked into the compiled kernels) is *rejected with the
  reason* — never silently queued into an OOM.  Workers start a job
  only while the summed estimate of running jobs fits the budget.
- **Degradation ladder** — a failed job attempt walks the round-12
  per-class ladder (transient-io backoff → device-OOM backpressure via
  ``reduce_capacity`` → CPU engines → fail-with-reason); the server
  survives every rung — a job dying must never take the warm pool (and
  every queued job behind it) down with it.
- **Per-job observability** — each job runs under its own metric scope
  (``job.<id>.*``, :func:`racon_tpu.obs.metrics.set_scope`), gets its
  own schema-validated ``run_report`` (kind ``"job"``) returned
  alongside the result, and real XLA compile seconds are attributed
  per job via a ``jax.monitoring`` duration listener — the
  ``service_compile_fraction`` number the ROADMAP item is scored on.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import faults, flags, sanitize
from ..core.polisher import PolisherType, create_polisher
from ..exec import heartbeat as hb
from ..exec import lease as lease_mod
from ..exec.planner import estimate_job_cost, input_cost_bytes, parse_ram
from ..exec.runner import _ChipWorker
from ..io import parsers
from ..obs import metrics, report as obs_report
from ..parallel.topology import ChipSlot
from ..utils.logger import log_swallowed, warn
from . import protocol

# job states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

_TERMINAL = (DONE, FAILED, CANCELLED)

# default client-side wait bound for a blocking result request
DEFAULT_RESULT_TIMEOUT_S = 3600.0


def _eprint(msg: str) -> None:
    print(f"[racon_tpu::serve] {msg}", file=sys.stderr, flush=True)


# ------------------------------------------------------ compile attribution

_monitor_armed = False
_monitor_lock = threading.Lock()


def arm_compile_monitor() -> bool:
    """Attribute real XLA compile seconds to the thread that compiles:
    a ``jax.monitoring`` duration listener accumulates every
    ``/jax/core/compile/*`` event into the ``compile.jax_s`` timer —
    which, fired on a job's worker thread, lands in THAT job's metric
    scope.  This is the measured numerator of
    ``service_compile_fraction``; warm-up compiles run on unscoped
    background threads and are deliberately not charged to any job."""
    global _monitor_armed
    with _monitor_lock:
        if _monitor_armed:
            return True
        try:
            import jax.monitoring as jmon

            def _on_duration(event, duration, **kwargs):
                if event.startswith("/jax/core/compile/"):
                    metrics.add_time("compile.jax_s", duration)

            jmon.register_event_duration_secs_listener(_on_duration)
            _monitor_armed = True
        except Exception as e:
            log_swallowed(
                "serve: jax.monitoring compile listener unavailable "
                "(per-job compile_s will read 0)", e)
            return False
    return True


def parse_warm_shapes(raw: str) -> List[Tuple[int, int, int, int]]:
    """Parse ``RACON_TPU_SERVE_WARM_SHAPES``: comma-separated
    ``window_length:pairs:windows[:contigs]`` entries.  A malformed
    entry fails loudly (an operator typo must not silently serve
    cold)."""
    out: List[Tuple[int, int, int, int]] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"RACON_TPU_SERVE_WARM_SHAPES entry {entry!r} is not "
                f"window_length:pairs:windows[:contigs]")
        vals = [int(p) for p in parts]
        if any(v <= 0 for v in vals):
            raise ValueError(
                f"RACON_TPU_SERVE_WARM_SHAPES entry {entry!r} has a "
                f"non-positive field")
        out.append((vals[0], vals[1], vals[2],
                    vals[3] if len(vals) == 4 else 1))
    return out


class Job:
    """One submitted polish job: spec, admission cost, lifecycle state,
    ladder attempts, result payload and the per-job run report."""

    def __init__(self, job_id: str, spec: dict, cost: int):
        self.id = job_id
        self.spec = spec
        self.cost = cost
        self.state = QUEUED
        self.error: Optional[str] = None
        self.engine: Optional[str] = None
        self.attempts: List[dict] = []
        self.result: Optional[bytes] = None
        self.result_bytes = 0          # recorded before retention drop
        self.collected = False
        self.phases: Dict[str, float] = {}
        self.report: Optional[dict] = None
        self.worker: Optional[str] = None
        self.submitted_unix = time.time()
        self.started_at: Optional[float] = None
        self.wall_s = 0.0
        self.compile_s = 0.0
        self.done = threading.Event()

    def row(self) -> dict:
        """The protocol's status view of this job."""
        out = {"job": self.id, "state": self.state,
               "cost_bytes": self.cost,
               "submitted_unix": round(self.submitted_unix, 3)}
        if self.worker:
            out["worker"] = self.worker
        if self.engine:
            out["engine"] = self.engine
        if self.attempts:
            out["attempts"] = self.attempts
        if self.state in _TERMINAL:
            out["wall_s"] = round(self.wall_s, 3)
            out["compile_s"] = round(self.compile_s, 3)
            out["bytes"] = self.result_bytes
        elif self.started_at is not None:
            out["wall_s"] = round(time.perf_counter() - self.started_at,
                                  3)
        if self.error:
            out["error"] = self.error
        return out


class PolishServer:
    """The resident polishing service (see the module docstring).

    The server object doubles as the duck-typed **engine profile**
    :class:`racon_tpu.exec.runner._ChipWorker` consumes — the
    attributes below named like :class:`ShardRunner`'s are that
    contract, and they are also the *service profile* admission checks
    jobs against: scores and banding are baked into the resident
    compiled kernels, so a job requesting different ones cannot be
    served warm and is rejected with that reason."""

    def __init__(self, socket_path: str, *,
                 match: int = 3, mismatch: int = -5, gap: int = -4,
                 banded: bool = False, num_threads: int = 1,
                 aligner_backend: str = "auto",
                 consensus_backend: str = "auto",
                 aligner_batches: int = 1, consensus_batches: int = 1,
                 chips: int = 0, workers: int = 0,
                 budget_bytes: int = 0, max_queue: int = 0,
                 autostart: bool = True):
        self.socket_path = os.path.abspath(socket_path)
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.banded = banded
        self.num_threads = num_threads
        self.aligner_backend = aligner_backend
        self.consensus_backend = consensus_backend
        self.aligner_batches = aligner_batches
        self.consensus_batches = consensus_batches
        self.chips_requested = chips
        self.workers_requested = workers
        self.worker = lease_mod.worker_identity()
        self.budget_bytes = budget_bytes or parse_ram(
            flags.get_str("RACON_TPU_SERVE_BUDGET"))
        self.max_queue = max_queue or max(
            1, flags.get_int("RACON_TPU_SERVE_QUEUE"))
        self.autostart = autostart

        self._slots: Optional[List[_ChipWorker]] = None
        # first slot-pool resolution is raced by connection handlers
        # (admission warm-up) against startup (_warm_pool)
        self._slots_lock = sanitize.named_lock("serve.slots")
        # the scheduler state lock (queue, counts, footprint); under
        # RACON_TPU_SANITIZE=1 both feed the lock-order witness
        self._lock = sanitize.named_lock("serve.state")
        self._cond = threading.Condition(self._lock)
        self._queue: List[Job] = []            # admitted, not yet running
        self._jobs: Dict[str, Job] = {}
        # terminal jobs retained for status/result queries, oldest
        # first; bounded so a server that has run 100k jobs holds 100k
        # of nothing (payloads go after one fetch, scoped metrics at
        # job end, and whole records past this horizon)
        self._retired: List[str] = []
        self.max_retained_jobs = 1024
        self._next_id = 0
        self._running_cost = 0
        self._counts = {"submitted": 0, "rejected": 0, "done": 0,
                        "failed": 0, "cancelled": 0}
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conn_threads: List[threading.Thread] = []
        self._t0 = time.perf_counter()
        self.started = threading.Event()       # listener bound + warm kick

    # ------------------------------------------------------- engine pool

    def _chip_slots(self) -> List[_ChipWorker]:
        """The warm executor pool: one slot per local chip (mirrors the
        shard runner's auto-engagement — explicit ``--chips`` /
        ``RACON_TPU_CHIPS`` wins, else every local device when a device
        backend runs on real multi-chip hardware), topped up to
        ``workers`` unpinned slots when more concurrency than chips was
        asked for (each slot owns its OWN engine pair — engines hold
        per-run state and are never shared across concurrent jobs)."""
        if self._slots is not None:
            return self._slots
        # double-checked under its own lock: a connection handler's
        # admission warm-up and the startup warm pool can both trigger
        # the first resolution — two pools would split the warm jit
        # caches and double every engine's device footprint
        with self._slots_lock:
            if self._slots is not None:
                return self._slots
            n = 1
            explicit = self.chips_requested > 0 \
                or flags.get_int("RACON_TPU_CHIPS") > 0
            if explicit:
                from ..parallel import topology
                n = topology.resolve_chips(self.chips_requested)
            elif "tpu" in (self.aligner_backend, self.consensus_backend):
                from ..parallel import topology
                devs = topology.local_devices()
                if len(devs) > 1 and \
                        getattr(devs[0], "platform", "cpu") != "cpu":
                    n = len(devs)
            if n <= 1:
                slots = [_ChipWorker(self, ChipSlot(0, None),
                                     pinned=False)]
            else:
                from ..parallel import topology
                topo = topology.Topology(n)
                slots = [_ChipWorker(self, s, pinned=True)
                         for s in topo.slots]
            for k in range(len(slots), max(1, self.workers_requested)):
                extra = _ChipWorker(self, ChipSlot(k, None),
                                    pinned=False)
                extra.worker = f"{self.worker}#w{k}"
                slots.append(extra)
            self._slots = slots
        return slots

    def _warm_pool(self) -> None:
        """Build every slot's engines NOW (resident = the pool exists
        before the first job) and kick the expected-shape warm-up
        profile so job #1 dispatches into a hot jit cache."""
        raw = flags.get_str("RACON_TPU_SERVE_WARM_SHAPES")
        shapes = parse_warm_shapes(raw) if raw.strip() else []
        for w in self._chip_slots():
            aligner, consensus = w.get_engines(cpu=False)
            warm = getattr(consensus, "warmup_async", None)
            if warm is None:
                continue
            for (wl, pairs, wins, contigs) in shapes:
                warm(wl, pairs, wins, est_contigs=contigs)
        _eprint(f"engine pool: {len(self._chip_slots())} worker(s), "
                f"budget {self.budget_bytes >> 20} MB, "
                f"{len(shapes)} warm shape profile(s)")

    def _warm_job_geometry(self, spec: dict) -> None:
        """Hand an admitted job's own (estimated) geometry to every
        slot's warm-up — shape-deduped in the engine, so a repeat
        geometry (the service's common case) is free and a genuinely
        new one starts compiling while the job waits in queue."""
        wl = spec["window_length"]
        read_bases = max(1, input_cost_bytes(spec["sequences"]) // 2)
        target_bases = max(
            1, input_cost_bytes(spec["target_sequences"]) // 2)
        est_pairs = max(1, read_bases // wl)
        est_windows = max(1, target_bases // wl)
        for w in self._chip_slots():
            if w.engines is None:
                continue
            warm = getattr(w.engines[1], "warmup_async", None)
            if warm is not None:
                warm(wl, est_pairs, est_windows,
                     est_contigs=max(1, min(est_windows, 8)))

    # --------------------------------------------------------- admission

    def _admit(self, raw_spec: dict) -> Tuple[Optional[Job], Optional[str]]:
        """Admission control: validate the spec, check it against the
        resident engine profile, estimate its footprint with the exec
        planner's cost model, and bound queue depth + total footprint.
        Returns ``(job, None)`` or ``(None, rejection reason)`` — the
        reject-with-reason contract that replaces a silent OOM."""
        spec, err = protocol.normalize_spec(raw_spec)
        if err is not None:
            return None, err
        for key in protocol.SPEC_PATHS:
            spec[key] = os.path.abspath(spec[key])
            if not os.path.isfile(spec[key]):
                return None, f"input not found: {spec[key]}"
        for path, kind in ((spec["sequences"], "sequences"),
                           (spec["target_sequences"], "target")):
            if parsers.sequence_parser_for(path) is None:
                return None, (f"{kind} file {path} has an unsupported "
                              f"format extension")
        if parsers.overlap_parser_for(spec["overlaps"]) is None:
            return None, (f"overlaps file {spec['overlaps']} has an "
                          f"unsupported format extension")
        profile = (self.match, self.mismatch, self.gap, self.banded)
        requested = (spec["match"], spec["mismatch"], spec["gap"],
                     spec["banded"])
        if requested != profile:
            return None, (
                f"engine profile mismatch: the resident engines are "
                f"compiled for (match, mismatch, gap, banded) = "
                f"{profile}, the job asked for {requested} — submit to "
                f"a server started with those scores, or restart this "
                f"one with them")
        cost = estimate_job_cost(spec["sequences"], spec["overlaps"],
                                 spec["target_sequences"])
        if cost > self.budget_bytes:
            return None, (
                f"job footprint estimate {cost >> 20} MB exceeds the "
                f"service budget {self.budget_bytes >> 20} MB "
                f"(--serve-budget / RACON_TPU_SERVE_BUDGET) — run it "
                f"one-shot through the streaming shard runner "
                f"(--max-ram) instead")
        with self._cond:
            if len(self._queue) >= self.max_queue:
                return None, (
                    f"queue full ({self.max_queue} jobs waiting; "
                    f"RACON_TPU_SERVE_QUEUE raises the bound)")
            self._next_id += 1
            job = Job(f"j{self._next_id}", spec, cost)
            self._jobs[job.id] = job
            self._queue.append(job)
            self._counts["submitted"] += 1
            self._cond.notify_all()
        # outside the lock: warm-up geometry derivation stats files
        self._warm_job_geometry(spec)
        return job, None

    # ------------------------------------------------------ job execution

    def _next_job(self, worker: _ChipWorker) -> Optional[Job]:
        """Block until the HEAD of the queue fits the in-flight
        footprint budget (or the server stops).  Strict FIFO: a big
        job waiting for footprint is never overtaken by later small
        ones — overtaking would keep the footprint pinned high and
        starve it indefinitely.  Progress is guaranteed: admission
        rejected anything bigger than the whole budget, so the head
        always fits once enough running jobs drain (at the latest,
        when the pool is idle)."""
        with self._cond:
            while True:
                if self._stop.is_set():
                    return None
                if self._queue:
                    job = self._queue[0]
                    if job.cost + self._running_cost \
                            <= self.budget_bytes \
                            or self._running_cost == 0:
                        self._queue.pop(0)
                        job.state = RUNNING
                        job.worker = worker.worker
                        job.started_at = time.perf_counter()
                        self._running_cost += job.cost
                        return job
                self._cond.wait(0.2)

    def _worker_loop(self, worker: _ChipWorker) -> None:
        while True:
            job = self._next_job(worker)
            if job is None:
                return
            try:
                self._run_job(worker, job)
            except Exception as e:
                # a fault OUTSIDE the per-attempt ladder (a report-build
                # bug, say) must fail the job, never the worker — the
                # warm pool outliving every job is the whole service
                job.state = FAILED
                job.error = f"internal error: {type(e).__name__}: {e}"
                warn(f"job {job.id} worker fault past the ladder: {e}")
            finally:
                with self._cond:
                    self._running_cost -= job.cost
                    self._counts[job.state] = \
                        self._counts.get(job.state, 0) + 1
                    self._retired.append(job.id)
                    while len(self._retired) > self.max_retained_jobs:
                        old = self._jobs.pop(self._retired.pop(0),
                                             None)
                        if old is not None:
                            old.result = None  # drop a never-fetched blob
                    self._cond.notify_all()
                job.done.set()
            _eprint(f"job {job.id} {job.state} in {job.wall_s:.2f}s "
                    f"(engine={job.engine or '-'}, "
                    f"compile {job.compile_s:.2f}s, "
                    f"{job.result_bytes} B) on {worker.worker}")

    def _polish(self, job: Job, worker: _ChipWorker,
                cpu: bool) -> bytes:
        """One polish attempt with the worker's resident engines
        injected — the job's whole latency is :meth:`Polisher.run`."""
        spec = job.spec
        aligner, consensus = worker.get_engines(cpu)
        p = create_polisher(
            spec["sequences"], spec["overlaps"],
            spec["target_sequences"],
            PolisherType.F if spec["fragment_correction"]
            else PolisherType.C,
            window_length=spec["window_length"],
            quality_threshold=spec["quality_threshold"],
            error_threshold=spec["error_threshold"],
            trim=not spec["no_trimming"],
            match=spec["match"], mismatch=spec["mismatch"],
            gap=spec["gap"], num_threads=spec["threads"],
            aligner=aligner, consensus=consensus)
        polished = p.run(not spec["include_unpolished"])
        job.phases = dict(p.timings)
        return b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                        for s in polished)

    def _run_job(self, worker: _ChipWorker, job: Job) -> None:
        """Execute one job under its own metric scope, walking the
        round-12 degradation ladder on failure — the server survives
        every rung, and the ladder record rides in the job's status,
        result and report."""
        scope = metrics.job_scope(job.id)
        metrics.set_scope(scope)
        t_start = time.time()
        t0 = time.perf_counter()
        max_retries = max(0, flags.get_int("RACON_TPU_EXEC_RETRIES"))
        transient_used = 0
        tier_cpu = False
        blob: Optional[bytes] = None
        try:
            for attempt_no in range(64):  # ladder is finite
                try:
                    faults.check("serve.polish", attempt=attempt_no)
                    blob = self._polish(job, worker, cpu=tier_cpu)
                    break
                except Exception as e:
                    cls = faults.classify(e)
                    metrics.inc(f"faults.{cls}")
                    err = f"{type(e).__name__}: {e}"
                    att = {"n": attempt_no,
                           "engine": "cpu" if tier_cpu else "primary",
                           "class": cls, "error": err}
                    job.attempts.append(att)
                    if cls == faults.CLASS_TRANSIENT and \
                            transient_used < max_retries:
                        backoff = (max(0.0, flags.get_float(
                            "RACON_TPU_EXEC_BACKOFF_S"))
                            * (2.0 ** transient_used))
                        att["action"] = "retry-backoff"
                        att["backoff_s"] = round(backoff, 3)
                        transient_used += 1
                        warn(f"job {job.id} transient fault ({err}) — "
                             f"retry {transient_used}/{max_retries} in "
                             f"{backoff:.2f}s")
                        time.sleep(backoff)
                    elif cls == faults.CLASS_OOM and not tier_cpu and \
                            worker.reduce_capacity():
                        att["action"] = "reduce-capacity"
                        warn(f"job {job.id} device OOM ({err}) — "
                             f"halved worker {worker.worker}'s "
                             f"consensus arena/group capacity, "
                             f"re-dispatching on the device")
                    elif not tier_cpu:
                        tier_cpu = True
                        att["action"] = "cpu-retry"
                        warn(f"job {job.id} attempt failed ({err}) — "
                             f"retrying on the CPU engines")
                    else:
                        att["action"] = "fail"
                        job.error = "; ".join(
                            a["error"] for a in job.attempts)
                        break
            job.wall_s = time.perf_counter() - t0
            job.compile_s = metrics.timer_s(scope + "compile.jax_s")
            if blob is not None:
                job.result = blob
                job.result_bytes = len(blob)
                job.engine = "cpu-retry" if tier_cpu else "primary"
                job.state = DONE
            else:
                job.state = FAILED
            # the per-job run report: built from THIS job's metric
            # scope, so concurrent jobs' numbers stay disjoint — the
            # machine-readable artifact returned alongside the result
            job.report = obs_report.build_report(
                "job", argv=[job.id, spec_summary(job.spec)],
                started_unix=t_start, wall_s=job.wall_s,
                phases=job.phases, scope=scope)
        finally:
            metrics.set_scope(None)
            # the report snapshot above embeds everything the scope
            # held; retiring the registry entries NOW is what keeps a
            # server that runs 100k jobs from growing the metrics
            # dicts without bound (the heartbeat only reads RUNNING
            # jobs' scopes, so nothing still wants these)
            metrics.clear_job(job.id)

    # ----------------------------------------------------------- protocol

    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        try:
            while True:
                try:
                    msg = protocol.read_msg(rfile)
                except ValueError as e:
                    protocol.send_msg(conn, {"ok": False,
                                             "error": f"bad request: {e}"})
                    return
                if msg is None:
                    return
                try:
                    if not self._dispatch_op(conn, msg):
                        return
                except (ValueError, TypeError, KeyError) as e:
                    # a malformed FIELD (non-numeric timeout_s, an
                    # unhashable job id) is the client's fault: answer
                    # with the reason instead of letting the handler
                    # thread die and the socket close silently
                    protocol.send_msg(conn, {
                        "ok": False,
                        "error": f"bad request field: "
                                 f"{type(e).__name__}: {e}"})
        except OSError as e:
            # a client hanging up mid-response is its own business —
            # the server's job records stay intact either way
            log_swallowed("serve: client connection dropped", e)
        finally:
            rfile.close()
            conn.close()

    def _dispatch_op(self, conn, msg: dict) -> bool:
        """Handle one request; False ends the connection loop."""
        op = msg.get("op")
        if op == "ping":
            protocol.send_msg(conn, {
                "ok": True, "server": self.worker,
                "uptime_s": round(time.perf_counter() - self._t0, 3),
                "profile": {"match": self.match,
                            "mismatch": self.mismatch, "gap": self.gap,
                            "banded": self.banded},
                "workers": len(self._chip_slots())})
            return True
        if op == "submit":
            job, reason = self._admit(msg.get("spec", {}))
            if job is None:
                with self._lock:
                    self._counts["rejected"] += 1
                protocol.send_msg(conn, {"ok": False, "error": reason,
                                         "rejected": True})
                return True
            protocol.send_msg(conn, {"ok": True, "job": job.id,
                                     "state": job.state,
                                     "cost_bytes": job.cost})
            return True
        if op in ("status", "result", "cancel"):
            job = self._jobs.get(msg.get("job", ""))
            if job is None:
                protocol.send_msg(conn, {
                    "ok": False,
                    "error": f"unknown job {msg.get('job')!r}"})
                return True
            if op == "status":
                row = job.row()
                with self._lock:
                    if job in self._queue:
                        row["queue_position"] = self._queue.index(job)
                protocol.send_msg(conn, {"ok": True, **row})
                return True
            if op == "cancel":
                return self._op_cancel(conn, job)
            return self._op_result(conn, job, msg)
        if op == "stats":
            with self._lock:
                counts = dict(self._counts)
                depth = len(self._queue)
                running = self._running_cost
            protocol.send_msg(conn, {
                "ok": True, **counts, "queued": depth,
                "running_cost_bytes": running,
                "budget_bytes": self.budget_bytes,
                "peak_rss_bytes": metrics.peak_rss_bytes()})
            return True
        if op == "shutdown":
            protocol.send_msg(conn, {"ok": True, "state": "stopping"})
            self.shutdown()
            return False
        protocol.send_msg(conn, {"ok": False,
                                 "error": f"unknown op {op!r}"})
        return True

    def _op_cancel(self, conn, job: Job) -> bool:
        cancelled = False
        with self._cond:
            if job in self._queue:
                self._queue.remove(job)
                job.state = CANCELLED
                job.error = "cancelled by client"
                self._counts["cancelled"] += 1
                self._retired.append(job.id)  # bounded-history horizon
                job.done.set()
                cancelled = True
        # reply OUTSIDE the scheduler lock (blocking-under-lock): a
        # client slow to drain its socket must not stall every worker
        # contending for the state lock
        if cancelled:
            protocol.send_msg(conn, {"ok": True, "job": job.id,
                                     "state": job.state})
            return True
        protocol.send_msg(conn, {
            "ok": False, "job": job.id, "state": job.state,
            "error": f"job {job.id} is not queued ({job.state}) — a "
                     f"running job cannot be safely interrupted "
                     f"mid-dispatch"})
        return True

    def _op_result(self, conn, job: Job, msg: dict) -> bool:
        timeout = float(msg.get("timeout_s", DEFAULT_RESULT_TIMEOUT_S))
        if not job.done.wait(timeout):
            protocol.send_msg(conn, {
                "ok": False, "job": job.id, "state": job.state,
                "timeout": True,
                "error": f"job {job.id} not finished within "
                         f"{timeout:.0f}s (still {job.state})"})
            return True
        header = {"ok": job.state == DONE, **job.row(),
                  "report": job.report}
        if job.state != DONE:
            protocol.send_msg(conn, header)
            return True
        with self._lock:
            blob = job.result
        if blob is None:
            why = ("was already collected (payloads are retained for "
                   "one successful fetch)" if job.collected
                   else "was retired (the server keeps a bounded "
                        "terminal-job history)")
            header.update(ok=False,
                          error=f"job {job.id} result {why}")
            protocol.send_msg(conn, header)
            return True
        header["bytes"] = len(blob)
        protocol.send_msg(conn, header)
        conn.sendall(blob)
        if not msg.get("keep", False):
            # retention: the FASTA payload is the big allocation — one
            # SUCCESSFUL fetch per job keeps a long-lived server's
            # memory bounded by in-flight work, not by its history.
            # Dropped only AFTER sendall returned: a client that died
            # waiting must be able to reconnect and fetch (two racing
            # fetchers both succeed; the second drop is a no-op).
            with self._lock:
                job.result = None
                job.collected = True
        return True

    # ---------------------------------------------------------- lifecycle

    def _heartbeat_loop(self, interval: float) -> None:
        """Per-job progress heartbeat: one line per tick naming every
        running job with its scope's pack/queue/retrace summaries —
        the shard heartbeat's fields, re-keyed per job."""
        while not self._stop.wait(interval):
            with self._lock:
                running = [j for j in self._jobs.values()
                           if j.state == RUNNING]
                depth = len(self._queue)
                counts = dict(self._counts)
            fields = []
            for j in running:
                scope = metrics.job_scope(j.id)
                dt = (time.perf_counter() - j.started_at
                      if j.started_at else 0.0)
                fields.append(
                    f"{j.id}@{hb.Heartbeat._short(j.worker or '?')}"
                    f" {dt:.1f}s pack[{hb.pack_summary_str(scope)}]"
                    f" queue[{hb.queue_summary_str(scope)}]"
                    f" retrace[{hb.retrace_summary(scope)}]")
            _eprint(f"heartbeat: {counts.get('done', 0)} done, "
                    f"{counts.get('failed', 0)} failed, "
                    f"{len(running)} running"
                    + (" (" + "; ".join(fields) + ")" if fields else "")
                    + f", {depth} queued, "
                    f"peak_rss={metrics.peak_rss_bytes() >> 20}MB")

    def start_workers(self) -> None:
        """Spawn the pool's worker threads (idempotent; split out so
        tests can exercise the queue deterministically before any
        worker drains it)."""
        if self._threads:
            return
        for w in self._chip_slots():
            t = threading.Thread(target=self._worker_loop, args=(w,),
                                 name=f"racon-serve-{w.worker}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _bind(self) -> socket.socket:
        path = self.socket_path
        if os.path.exists(path):
            import stat as stat_mod
            if not stat_mod.S_ISSOCK(os.stat(path).st_mode):
                # refuse, don't unlink: a typo'd --serve path must not
                # delete the operator's regular file
                raise RuntimeError(
                    f"{path} exists and is not a socket — refusing to "
                    f"replace it")
            # a previous server may have died without unlinking; only a
            # CONNECTABLE socket proves a live one
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(1.0)
            try:
                probe.connect(path)
            except OSError as e:
                log_swallowed("serve: removing stale socket file", e)
                os.unlink(path)
            else:
                raise RuntimeError(
                    f"another server is already listening on {path}")
            finally:
                probe.close()
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(path)
        listener.listen(64)
        return listener

    def serve_forever(self) -> int:
        """Bind, warm the pool, accept until :meth:`shutdown`.  Returns
        an exit code (0 on a clean stop)."""
        arm_compile_monitor()
        # span TIMERS must record for the life of the server: the
        # per-job dispatch/fetch split reads them through each job's
        # metric scope (ring-buffer tracing stays off — a long-lived
        # daemon's trace is unbounded by definition)
        from ..obs import trace
        trace.activate()
        # serve_forever runs on exactly ONE thread per server (the
        # process main thread in production, the single spawner thread
        # in tests) — its attribute writes below never race themselves
        # graftlint: disable=lock-discipline (serve_forever runs on exactly one thread per server instance)
        self._listener = self._bind()
        self._warm_pool()
        if self.autostart:
            self.start_workers()
        interval = flags.get_float("RACON_TPU_HEARTBEAT_S")
        if interval > 0:
            t = threading.Thread(target=self._heartbeat_loop,
                                 args=(interval,),
                                 name="racon-serve-heartbeat",
                                 daemon=True)
            t.start()
        _eprint(f"listening on {self.socket_path} "
                f"(server {self.worker})")
        self.started.set()
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except OSError:
                    break  # listener closed by shutdown()
                t = threading.Thread(target=self._handle_conn,
                                     args=(conn,), daemon=True)
                t.start()
                self._conn_threads.append(t)
                # graftlint: disable=lock-discipline (serve_forever runs on exactly one thread per server instance)
                self._conn_threads = [c for c in self._conn_threads
                                      if c.is_alive()]
        finally:
            self.shutdown()
            for t in self._threads:
                t.join()
        _eprint(f"stopped ({self._counts['done']} done, "
                f"{self._counts['failed']} failed, "
                f"{self._counts['rejected']} rejected)")
        return 0

    def shutdown(self) -> None:
        """Stop accepting, let running jobs finish, fail what is still
        queued (idempotent)."""
        if self._stop.is_set():
            return
        self._stop.set()
        with self._cond:
            for job in self._queue:
                job.state = FAILED
                job.error = "server shutdown before the job ran"
                job.done.set()
            self._queue.clear()
            self._cond.notify_all()
        if self._listener is not None:
            try:
                # shutdown() BEFORE close(): a close alone does not
                # reliably wake a thread blocked in accept() on Linux —
                # the accept loop would outlive the server
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError as e:
                log_swallowed("serve: listener shutdown failed", e)
            try:
                self._listener.close()
            except OSError as e:
                log_swallowed("serve: listener close failed", e)
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        except OSError as e:
            log_swallowed("serve: socket unlink failed", e)


def spec_summary(spec: dict) -> str:
    """One-line human summary of a job spec (report argv, logs)."""
    return (f"{os.path.basename(spec['sequences'])} "
            f"{os.path.basename(spec['overlaps'])} "
            f"{os.path.basename(spec['target_sequences'])} "
            f"-w {spec['window_length']} -t {spec['threads']}"
            + (" -f" if spec["fragment_correction"] else "")
            + (" -u" if spec["include_unpolished"] else ""))
