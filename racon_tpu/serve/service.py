"""PolishServer: the long-lived polishing daemon (``racon --serve``).

Every one-shot ``racon`` invocation pays the cold XLA compile
(16–80 s at BENCH r04/r05) for kernels whose warm dispatch is
sub-second — fatal for heavy traffic of small jobs (one user's plasmid
or amplicon panel).  The reference amortizes exactly this cost by
reusing its cudapoa/cudaaligner batch objects across fills (SURVEY
§L3); this server is the TPU analog at process granularity: ONE
resident process keeps a warm engine pool alive and executes submitted
polish jobs through the existing :meth:`Polisher.run` pipeline with
those engines injected, so a job's latency is compute, not compile.

Architecture (every piece is an existing subsystem, re-hosted):

- **Warm engine pool** — one :class:`racon_tpu.exec.runner._ChipWorker`
  per local chip (the round-13 slot type; the server passes itself as
  the duck-typed engine profile), each slot owning a device-pinned
  aligner/consensus pair plus a CPU-retry pair.  Engines are built
  eagerly at startup and *never* discarded: jit caches, SWAR probes and
  warm-up compiles survive across every job the server ever runs, and
  ``configure_compile_cache`` persists the executables across server
  restarts.
- **Shape canonicalization** — jobs land on already-compiled
  executables because the ragged consensus stream buckets windows by
  power-of-two lane width against a fixed arena (round 10): two jobs
  with the same polishing parameters share executables regardless of
  their input sizes.  At startup the pool warm-compiles the expected
  profile (``RACON_TPU_SERVE_WARM_SHAPES``) so job #1 is already warm,
  and every admitted job's own geometry is handed to ``warmup_async``
  (shape-deduped) so a genuinely new geometry starts compiling while
  the job waits in queue.
- **Admission control** — the exec planner's resident-footprint cost
  model (:func:`racon_tpu.exec.planner.estimate_job_cost`) gates
  submissions: a job estimated over the budget, a full queue, or a
  parameter set the resident engines cannot serve (the score/banding
  profile is baked into the compiled kernels) is *rejected with the
  reason* — never silently queued into an OOM.  Workers start a job
  only while the summed estimate of running jobs fits the budget.
- **Degradation ladder** — a failed job attempt walks the round-12
  per-class ladder (transient-io backoff → device-OOM backpressure via
  ``reduce_capacity`` → CPU engines → fail-with-reason); the server
  survives every rung — a job dying must never take the warm pool (and
  every queued job behind it) down with it.
- **Per-job observability** — each job runs under its own metric scope
  (``job.<id>.*``, :func:`racon_tpu.obs.metrics.set_scope`), gets its
  own schema-validated ``run_report`` (kind ``"job"``) returned
  alongside the result, and real XLA compile seconds are attributed
  per job via a ``jax.monitoring`` duration listener — the
  ``service_compile_fraction`` number the ROADMAP item is scored on.
- **Crash safety** (round 16, ``--serve-dir``) — every lifecycle
  transition is journaled durably (:mod:`racon_tpu.serve.journal`),
  results spool to CRC-verified files instead of RAM, a restart from
  the same serve-dir replays the journal (completed jobs serve from
  the spool, queued/running jobs re-admit down the round-12 crash
  ladder, client idempotency keys dedupe resubmissions), worker slots
  are *supervised* (a dead/wedged slot thread fails its job down the
  per-job ladder and is restarted with fresh engines; repeated deaths
  quarantine the slot and shrink advertised capacity), and
  ``SIGTERM``/``shutdown {"mode": "drain"}`` stops admission, finishes
  in-flight jobs and flushes the journal before exit.  The run-report
  schema grew a ``recovery`` section (v5) carrying the journal
  replay/compaction and slot-supervision counters.
"""

from __future__ import annotations

import os
import signal as signal_mod
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import contracts, faults, flags, sanitize
from ..core.polisher import PolisherType, create_polisher
from ..exec import heartbeat as hb
from ..exec import lease as lease_mod
from ..exec.planner import cached_job_cost, input_cost_bytes, parse_ram
from ..exec.runner import _ChipWorker
from ..io import parsers
from ..obs import compilewatch, metrics, report as obs_report
from ..parallel.topology import ChipSlot
from ..utils.logger import log_swallowed, warn
from . import protocol
from .journal import JobJournal

# job states — the JOB_MACHINE of racon_tpu/contracts.py; the
# state-transition lint rule checks every `job.state = ...` write (and
# its lexical equality guard, when present) against the declared edges
QUEUED = contracts.JOB_QUEUED
RUNNING = contracts.JOB_RUNNING
DONE = contracts.JOB_DONE
FAILED = contracts.JOB_FAILED
CANCELLED = contracts.JOB_CANCELLED

_TERMINAL = (DONE, FAILED, CANCELLED)

# default client-side wait bound for a blocking result request
DEFAULT_RESULT_TIMEOUT_S = 3600.0

# the per-job crash ladder (server death / slot death both count):
# crash 1 -> re-run on the primary engines (could have been unlucky),
# crash 2 -> re-run on the CPU engines, crash 3 -> fail-with-reason —
# the round-12 degradation shape, never an infinite redo loop
_MAX_JOB_CRASHES = 3
# slot supervision: consecutive deaths before a slot is quarantined
# instead of restarted (advertised capacity shrinks with it)
_SLOT_QUARANTINE_DEATHS = 3
_SUPERVISE_POLL_S = 0.5


def _eprint(msg: str) -> None:
    print(f"[racon_tpu::serve] {msg}", file=sys.stderr, flush=True)


# Compile attribution (round 18): the serve-only jax.monitoring
# listener of round 14 is absorbed into the process-wide
# racon_tpu.obs.compilewatch — same ``compile.jax_s`` scoped-timer
# semantics (fired on a job's worker thread, the time lands in THAT
# job's metric scope: the measured numerator of
# ``service_compile_fraction``), plus per-compile attribution to
# (function, shape signature, phase, scope) and the warm-path seal the
# sanitized serve assert reads (``sanitize.check_post_warm_compiles``).


def parse_warm_shapes(raw: str) -> List[Tuple[int, int, int, int]]:
    """Parse ``RACON_TPU_SERVE_WARM_SHAPES``: comma-separated
    ``window_length:pairs:windows[:contigs]`` entries.  A malformed
    entry fails loudly (an operator typo must not silently serve
    cold)."""
    out: List[Tuple[int, int, int, int]] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"RACON_TPU_SERVE_WARM_SHAPES entry {entry!r} is not "
                f"window_length:pairs:windows[:contigs]")
        vals = [int(p) for p in parts]
        if any(v <= 0 for v in vals):
            raise ValueError(
                f"RACON_TPU_SERVE_WARM_SHAPES entry {entry!r} has a "
                f"non-positive field")
        out.append((vals[0], vals[1], vals[2],
                    vals[3] if len(vals) == 4 else 1))
    return out


class Job:
    """One submitted polish job: spec, admission cost, lifecycle state,
    ladder attempts, result payload and the per-job run report."""

    def __init__(self, job_id: str, spec: dict, cost: int):
        self.id = job_id
        self.spec = spec
        self.cost = cost
        self.state = QUEUED
        self.error: Optional[str] = None
        self.engine: Optional[str] = None
        self.attempts: List[dict] = []
        self.result: Optional[bytes] = None
        self.result_bytes = 0          # recorded before retention drop
        self.collected = False
        self.phases: Dict[str, float] = {}
        self.report: Optional[dict] = None
        self.worker: Optional[str] = None
        # fleet routing hints (round 23): recorded for stats/status —
        # a plain serve host still schedules FIFO; the gateway is the
        # layer that turns these into weighted-fair + preemption
        self.tenant = str(spec.get("tenant", "default"))
        self.priority = int(spec.get("priority", 0))
        # cooperative preemption: set by the `preempt` op on a RUNNING
        # job; honored at the next ladder-attempt boundary (a polish
        # dispatch is never interrupted mid-flight)
        self.preempt = threading.Event()
        self.submitted_unix = time.time()
        self.started_at: Optional[float] = None
        self.wall_s = 0.0
        self.compile_s = 0.0
        # compiles attributed to this job AFTER the server sealed its
        # warm path (round 18) — 0 on the warm-path claim, reported in
        # the result header and asserted by bench_service
        self.compiles_after_warm = 0
        # the warm-path assert only judges jobs that STARTED after the
        # seal: a job already compiling when the first job completed
        # must not be failed retroactively (concurrent submissions)
        self.post_warm_eligible = False
        # True when admission warm-up queued NEW shapes for this job's
        # estimated geometry — a declared geometry expansion, exempt
        # from the warm-path assert (see _warm_job_geometry)
        self.warmup_declared = False
        self.done = threading.Event()
        # crash-safe serving (round 16): the client's idempotency key,
        # the spooled-result coordinates (name + CRC the fetch path
        # verifies), how many `running` journal records exist for this
        # job, and how many times it died with its executor (server
        # crash or slot death) — the ladder input
        self.key: Optional[str] = None
        self.spool: Optional[str] = None
        self.crc32 = 0
        self.journal_runs = 0
        self.crash_count = 0
        self.recovered = False
        # answered FAILED in RAM by a hard stop, but still journaled
        # `submitted` on disk: the final compaction must keep it live
        # so the restarted server runs it
        self.shutdown_orphan = False

    def row(self) -> dict:
        """The protocol's status view of this job."""
        out = {"job": self.id, "state": self.state,
               "cost_bytes": self.cost,
               "tenant": self.tenant, "priority": self.priority,
               "submitted_unix": round(self.submitted_unix, 3)}
        if self.worker:
            out["worker"] = self.worker
        if self.engine:
            out["engine"] = self.engine
        if self.attempts:
            out["attempts"] = self.attempts
        if self.state in _TERMINAL:
            out["wall_s"] = round(self.wall_s, 3)
            out["compile_s"] = round(self.compile_s, 3)
            out["compiles_after_warm"] = self.compiles_after_warm
            out["bytes"] = self.result_bytes
        elif self.started_at is not None:
            out["wall_s"] = round(time.perf_counter() - self.started_at,
                                  3)
        if self.error:
            out["error"] = self.error
        return out


class PolishServer:
    """The resident polishing service (see the module docstring).

    The server object doubles as the duck-typed **engine profile**
    :class:`racon_tpu.exec.runner._ChipWorker` consumes — the
    attributes below named like :class:`ShardRunner`'s are that
    contract, and they are also the *service profile* admission checks
    jobs against: scores and banding are baked into the resident
    compiled kernels, so a job requesting different ones cannot be
    served warm and is rejected with that reason."""

    def __init__(self, socket_path: str, *,
                 match: int = 3, mismatch: int = -5, gap: int = -4,
                 banded: bool = False, num_threads: int = 1,
                 aligner_backend: str = "auto",
                 consensus_backend: str = "auto",
                 aligner_batches: int = 1, consensus_batches: int = 1,
                 chips: int = 0, workers: int = 0,
                 budget_bytes: int = 0, max_queue: int = 0,
                 autostart: bool = True,
                 serve_dir: Optional[str] = None,
                 fleet_dir: Optional[str] = None):
        self.socket_path = os.path.abspath(socket_path)
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.banded = banded
        self.num_threads = num_threads
        self.aligner_backend = aligner_backend
        self.consensus_backend = consensus_backend
        self.aligner_batches = aligner_batches
        self.consensus_batches = consensus_batches
        self.chips_requested = chips
        self.workers_requested = workers
        self.worker = lease_mod.worker_identity()
        self.budget_bytes = budget_bytes or parse_ram(
            flags.get_str("RACON_TPU_SERVE_BUDGET"))
        self.max_queue = max_queue or max(
            1, flags.get_int("RACON_TPU_SERVE_QUEUE"))
        self.autostart = autostart

        self._slots: Optional[List[_ChipWorker]] = None
        # first slot-pool resolution is raced by connection handlers
        # (admission warm-up) against startup (_warm_pool)
        self._slots_lock = sanitize.named_lock("serve.slots")
        # the scheduler state lock (queue, counts, footprint); under
        # RACON_TPU_SANITIZE=1 both feed the lock-order witness
        self._lock = sanitize.named_lock("serve.state")
        self._cond = threading.Condition(self._lock)
        self._queue: List[Job] = []            # admitted, not yet running
        self._jobs: Dict[str, Job] = {}
        # terminal jobs retained for status/result queries, oldest
        # first; bounded so a server that has run 100k jobs holds 100k
        # of nothing (payloads go after one fetch, scoped metrics at
        # job end, and whole records past this horizon)
        self._retired: List[str] = []
        self.max_retained_jobs = 1024
        self._next_id = 0
        self._running_cost = 0
        self._counts = {"submitted": 0, "rejected": 0, "done": 0,
                        "failed": 0, "cancelled": 0}
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conn_threads: List[threading.Thread] = []
        self._t0 = time.perf_counter()
        self.started = threading.Event()       # listener bound + warm kick
        # crash-safe serving (round 16): the durable job journal +
        # result spool (None = the pre-round-16 in-memory service),
        # the idempotency-key index, the drain flag, and the slot-
        # supervision state (per-ordinal thread/death bookkeeping)
        serve_dir = serve_dir or \
            flags.get_str("RACON_TPU_SERVE_DIR").strip() or None
        self.serve_dir = os.path.abspath(serve_dir) if serve_dir else None
        self._journal: Optional[JobJournal] = \
            JobJournal(self.serve_dir) if self.serve_dir else None
        self._by_key: Dict[str, str] = {}
        self._draining = False
        self._slot_threads: Dict[int, threading.Thread] = {}
        self._slot_deaths: Dict[int, int] = {}
        self._quarantined: set = set()
        self._supervisor: Optional[threading.Thread] = None
        # fleet membership (round 23): a --fleet-dir host advertises
        # itself to the gateway with a heartbeat beacon file; a beacon
        # gone stale past RACON_TPU_FLEET_HOST_TTL_S is how the
        # gateway declares this host dead and migrates its jobs
        self.fleet_dir = os.path.abspath(fleet_dir) if fleet_dir \
            else None
        self._beacon = None

    # ------------------------------------------------------- engine pool

    def _chip_slots(self) -> List[_ChipWorker]:
        """The warm executor pool: one slot per local chip (mirrors the
        shard runner's auto-engagement — explicit ``--chips`` /
        ``RACON_TPU_CHIPS`` wins, else every local device when a device
        backend runs on real multi-chip hardware), topped up to
        ``workers`` unpinned slots when more concurrency than chips was
        asked for (each slot owns its OWN engine pair — engines hold
        per-run state and are never shared across concurrent jobs)."""
        if self._slots is not None:
            return self._slots
        # double-checked under its own lock: a connection handler's
        # admission warm-up and the startup warm pool can both trigger
        # the first resolution — two pools would split the warm jit
        # caches and double every engine's device footprint
        with self._slots_lock:
            if self._slots is not None:
                return self._slots
            n = 1
            explicit = self.chips_requested > 0 \
                or flags.get_int("RACON_TPU_CHIPS") > 0
            if explicit:
                from ..parallel import topology
                n = topology.resolve_chips(self.chips_requested)
            elif "tpu" in (self.aligner_backend, self.consensus_backend):
                from ..parallel import topology
                devs = topology.local_devices()
                if len(devs) > 1 and \
                        getattr(devs[0], "platform", "cpu") != "cpu":
                    n = len(devs)
            if n <= 1:
                slots = [_ChipWorker(self, ChipSlot(0, None),
                                     pinned=False)]
            else:
                from ..parallel import topology
                topo = topology.Topology(n)
                slots = [_ChipWorker(self, s, pinned=True)
                         for s in topo.slots]
            for k in range(len(slots), max(1, self.workers_requested)):
                extra = _ChipWorker(self, ChipSlot(k, None),
                                    pinned=False)
                extra.worker = f"{self.worker}#w{k}"
                slots.append(extra)
            self._slots = slots
        return slots

    def _warm_pool(self) -> None:
        """Build every slot's engines NOW (resident = the pool exists
        before the first job) and kick the expected-shape warm-up
        profile so job #1 dispatches into a hot jit cache."""
        raw = flags.get_str("RACON_TPU_SERVE_WARM_SHAPES")
        shapes = parse_warm_shapes(raw) if raw.strip() else []
        for w in self._chip_slots():
            aligner, consensus = w.get_engines(cpu=False)
            warm = getattr(consensus, "warmup_async", None)
            awarm = getattr(aligner, "warmup_async", None)
            for (wl, pairs, wins, contigs) in shapes:
                if warm is not None:
                    warm(wl, pairs, wins, est_contigs=contigs)
                if awarm is not None:
                    # align-chunk geometry (round 17): overlap spans run
                    # read-length scale, not window scale — ~8 windows
                    # per ONT-era read is the profile's implied ratio;
                    # a wrong estimate only wastes a background compile
                    awarm(8 * wl, max(1, pairs // 8), window_length=wl)
        _eprint(f"engine pool: {len(self._chip_slots())} worker(s), "
                f"budget {self.budget_bytes >> 20} MB, "
                f"{len(shapes)} warm shape profile(s)")

    def _warm_job_geometry(self, spec: dict) -> bool:
        """Hand an admitted job's own (estimated) geometry to every
        slot's warm-up — shape-deduped in the engine, so a repeat
        geometry (the service's common case) is free and a genuinely
        new one starts compiling while the job waits in queue.
        Returns True when any engine queued NEW warm-up shapes: the
        job declared a geometry expansion, and the warm-path assert
        must not judge it (its dispatch legitimately races its own
        warm-up thread for the compile)."""
        wl = spec["window_length"]
        read_bases = max(1, input_cost_bytes(spec["sequences"]) // 2)
        target_bases = max(
            1, input_cost_bytes(spec["target_sequences"]) // 2)
        est_pairs = max(1, read_bases // wl)
        est_windows = max(1, target_bases // wl)
        queued_new = False
        for w in self._chip_slots():
            if w.engines is None:
                continue
            warm = getattr(w.engines[1], "warmup_async", None)
            if warm is not None:
                queued_new |= warm(
                    wl, est_pairs, est_windows,
                    est_contigs=max(1, min(est_windows, 8))) is not None
            awarm = getattr(w.engines[0], "warmup_async", None)
            if awarm is not None:
                # align-stream geometry (round 17): see _warm_pool —
                # shape-deduped in the engine, so repeats are free
                queued_new |= awarm(8 * wl, max(1, est_pairs // 8),
                                    window_length=wl) is not None
        if parsers.is_auto_overlaps(spec["overlaps"]):
            # --overlaps auto job: the overlapper's seed + chain-arena
            # kernels are process-global (module jit caches, not
            # per-slot engines) — warm them once with the job's implied
            # read geometry (the ~8-windows-per-read profile above),
            # shape-deduped inside each module so repeats are free
            from ..ops import chain as chain_ops
            from ..ops import overlap_seed
            est_len = 8 * wl
            est_reads = max(1, read_bases // est_len)
            k = max(4, min(16, flags.get_int("RACON_TPU_OVERLAP_K")))
            queued_new |= overlap_seed.warmup_async(
                est_len, est_reads) is not None
            queued_new |= chain_ops.warmup_async(
                max(1, est_len // 8), est_reads, k=k) is not None
        return queued_new

    # --------------------------------------------------------- admission

    def _admit(self, raw_spec: dict, key: Optional[str] = None) \
            -> Tuple[Optional[Job], Optional[str], bool]:
        """Admission control: validate the spec, check it against the
        resident engine profile, estimate its footprint with the exec
        planner's cost model, and bound queue depth + total footprint.
        Returns ``(job, None, existing)`` or ``(None, rejection
        reason, False)`` — the reject-with-reason contract that
        replaces a silent OOM.  ``key`` is the client's idempotency
        key: a resubmission of an already-journaled spec returns the
        EXISTING job (``existing=True``) instead of duplicating
        compute — the contract that makes client reconnect-and-refetch
        across a server restart safe."""
        if key:
            with self._lock:
                jid = self._by_key.get(key)
                prior = self._jobs.get(jid) if jid else None
            # a FAILED prior is retryable — a fresh submission under
            # the same key admits a new attempt; queued/running/done
            # work is never duplicated
            if prior is not None and prior.state != FAILED:
                return prior, None, True
        if self._draining:
            return None, (
                "server is draining (SIGTERM / shutdown mode=drain): "
                "admission is stopped — resubmit to the restarted "
                "server (your idempotency key keeps it safe)"), False
        if self._quarantined and self.healthy_workers() == 0:
            return None, (
                "every worker slot is quarantined after repeated "
                "deaths — the server has no healthy capacity left; "
                "restart it (a --serve-dir server recovers its queue "
                "on restart)"), False
        spec, err = protocol.normalize_spec(raw_spec)
        if err is not None:
            return None, err, False
        for pkey in protocol.SPEC_PATHS:
            if pkey == "overlaps" \
                    and parsers.is_auto_overlaps(spec[pkey]):
                # first-party overlapper: the job is self-contained
                # (reads + target, no overlaps upload)
                continue
            spec[pkey] = os.path.abspath(spec[pkey])
            if not os.path.isfile(spec[pkey]):
                return None, f"input not found: {spec[pkey]}", False
        for path, kind in ((spec["sequences"], "sequences"),
                           (spec["target_sequences"], "target")):
            if parsers.sequence_parser_for(path) is None:
                return None, (f"{kind} file {path} has an unsupported "
                              f"format extension"), False
        if not parsers.is_auto_overlaps(spec["overlaps"]) \
                and parsers.overlap_parser_for(spec["overlaps"]) is None:
            return None, (f"overlaps file {spec['overlaps']} has an "
                          f"unsupported format extension"), False
        profile = (self.match, self.mismatch, self.gap, self.banded)
        requested = (spec["match"], spec["mismatch"], spec["gap"],
                     spec["banded"])
        if requested != profile:
            return None, (
                f"engine profile mismatch: the resident engines are "
                f"compiled for (match, mismatch, gap, banded) = "
                f"{profile}, the job asked for {requested} — submit to "
                f"a server started with those scores, or restart this "
                f"one with them"), False
        # content-fingerprint cached (round 23): a fleet gateway and a
        # member host pricing the same inputs stat them once, not twice
        cost = cached_job_cost(spec["sequences"], spec["overlaps"],
                               spec["target_sequences"])
        if cost > self.budget_bytes:
            return None, (
                f"job footprint estimate {cost >> 20} MB exceeds the "
                f"service budget {self.budget_bytes >> 20} MB "
                f"(--serve-budget / RACON_TPU_SERVE_BUDGET) — run it "
                f"one-shot through the streaming shard runner "
                f"(--max-ram) instead"), False
        with self._cond:
            if len(self._queue) >= self.max_queue:
                return None, (
                    f"queue full ({self.max_queue} jobs waiting; "
                    f"RACON_TPU_SERVE_QUEUE raises the bound)"), False
            if key and key in self._by_key:
                # a racing duplicate landed between the fast-path check
                # and here: the first submission wins, same contract
                prior = self._jobs.get(self._by_key[key])
                if prior is not None and prior.state != FAILED:
                    return prior, None, True
            self._next_id += 1
            job = Job(f"j{self._next_id}", spec, cost)
            job.key = key or None
            # registered (and key-indexed) BEFORE it is runnable, so a
            # duplicate submit dedupes while we journal below
            self._jobs[job.id] = job
            if job.key:
                self._by_key[job.key] = job.id
        if self._journal is not None:
            # the write-ahead half of admission: the `submitted` record
            # must be durable BEFORE the job can run (a `running`
            # record must never precede its `submitted`); a journal
            # that cannot record the job means the job is not admitted
            try:
                self._journal.append({
                    "rec": "submitted", "job": job.id, "key": job.key,
                    "cost": cost, "unix": round(job.submitted_unix, 3),
                    "spec": spec})
            # graftlint: disable=swallowed-exception (the failure IS the reply: it becomes the client's rejection reason)
            except Exception as e:
                # the job stays registered but FAILED (not popped): a
                # racing duplicate submission under the same key may
                # already have been answered with this id, and an id
                # the server acknowledged must keep resolving.  A
                # FAILED prior is retryable, so the key is reusable.
                with self._cond:
                    job.state = FAILED
                    job.error = (f"job journal write failed "
                                 f"({type(e).__name__}: {e})")
                    self._counts["failed"] = \
                        self._counts.get("failed", 0) + 1
                    self._retired.append(job.id)
                    job.done.set()
                return None, (f"job journal write failed "
                              f"({type(e).__name__}: {e}) — the "
                              f"serve-dir is not accepting durable "
                              f"admissions"), False
        with self._cond:
            self._queue.append(job)
            self._counts["submitted"] += 1
            self._cond.notify_all()
        # outside the lock: warm-up geometry derivation stats files.
        # A job whose estimate queued NEW warm-up shapes declared a
        # geometry expansion — the warm-path assert must not judge it
        # (it races its own admission warm-up thread for the compile)
        job.warmup_declared = self._warm_job_geometry(spec)
        return job, None, False

    # ------------------------------------------------------ job execution

    def _next_job(self, worker: _ChipWorker) -> Optional[Job]:
        """Block until the HEAD of the queue fits the in-flight
        footprint budget (or the server stops).  Strict FIFO: a big
        job waiting for footprint is never overtaken by later small
        ones — overtaking would keep the footprint pinned high and
        starve it indefinitely.  Progress is guaranteed: admission
        rejected anything bigger than the whole budget, so the head
        always fits once enough running jobs drain (at the latest,
        when the pool is idle)."""
        with self._cond:
            while True:
                if self._stop.is_set():
                    return None
                if self._queue:
                    job = self._queue[0]
                    if job.cost + self._running_cost \
                            <= self.budget_bytes \
                            or self._running_cost == 0:
                        self._queue.pop(0)
                        job.state = RUNNING
                        job.worker = worker.worker
                        job.post_warm_eligible = (
                            compilewatch.sealed() is not None
                            and not job.warmup_declared)
                        job.started_at = time.perf_counter()
                        self._running_cost += job.cost
                        # supervision handle: if this slot's thread
                        # dies, the supervisor finds the orphaned job
                        # here and walks it down the crash ladder
                        worker.current_job = job
                        return job
                self._cond.wait(0.2)

    def _worker_loop(self, worker: _ChipWorker) -> None:
        while True:
            job = self._next_job(worker)
            if job is None:
                return
            # slot-supervision chaos site: an injected fault HERE is
            # OUTSIDE the per-job ladder and kills the slot thread
            # itself — exactly the death the supervisor must detect,
            # requeue the job from, and restart the slot after
            faults.check("serve.slot")
            try:
                self._run_job(worker, job)
            except Exception as e:
                # a fault OUTSIDE the per-attempt ladder (a report-build
                # bug, say) must fail the job, never the worker — the
                # warm pool outliving every job is the whole service
                job.state = FAILED
                job.error = f"internal error: {type(e).__name__}: {e}"
                warn(f"job {job.id} worker fault past the ladder: {e}")
            finally:
                with self._cond:
                    self._running_cost -= job.cost
                    worker.current_job = None
                    self._counts[job.state] = \
                        self._counts.get(job.state, 0) + 1
                    self._retired.append(job.id)
                    while len(self._retired) > self.max_retained_jobs:
                        old = self._jobs.pop(self._retired.pop(0),
                                             None)
                        if old is not None:
                            old.result = None  # drop a never-fetched blob
                    self._cond.notify_all()
                self._journal_terminal(job)
                job.done.set()
            self._maybe_compact()
            _eprint(f"job {job.id} {job.state} in {job.wall_s:.2f}s "
                    f"(engine={job.engine or '-'}, "
                    f"compile {job.compile_s:.2f}s, "
                    f"{job.result_bytes} B) on {worker.worker}")

    def _polish(self, job: Job, worker: _ChipWorker,
                cpu: bool) -> bytes:
        """One polish attempt with the worker's resident engines
        injected — the job's whole latency is :meth:`Polisher.run`."""
        spec = job.spec
        aligner, consensus = worker.get_engines(cpu)
        p = create_polisher(
            spec["sequences"], spec["overlaps"],
            spec["target_sequences"],
            PolisherType.F if spec["fragment_correction"]
            else PolisherType.C,
            window_length=spec["window_length"],
            quality_threshold=spec["quality_threshold"],
            error_threshold=spec["error_threshold"],
            trim=not spec["no_trimming"],
            match=spec["match"], mismatch=spec["mismatch"],
            gap=spec["gap"], num_threads=spec["threads"],
            aligner=aligner, consensus=consensus)
        polished = p.run(not spec["include_unpolished"])
        job.phases = dict(p.timings)
        return b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                        for s in polished)

    def _run_job(self, worker: _ChipWorker, job: Job) -> None:
        """Execute one job under its own metric scope, walking the
        round-12 degradation ladder on failure — the server survives
        every rung, and the ladder record rides in the job's status,
        result and report."""
        if self._journal is not None:
            # write-ahead: the incarnation is journaled BEFORE any
            # compute, so a crash from here on leaves a countable
            # `running` record — the crash ladder's input on replay
            job.journal_runs += 1
            self._journal.append({"rec": "running", "job": job.id,
                                  "worker": worker.worker,
                                  "run": job.journal_runs})
        # kill-restart chaos site: a SIGKILL here leaves this job
        # journaled `running` with no terminal record — exactly the
        # state restart recovery must re-admit
        faults.check("server.kill")
        scope = metrics.job_scope(job.id)
        metrics.set_scope(scope)
        t_start = time.time()
        t0 = time.perf_counter()
        max_retries = max(0, flags.get_int("RACON_TPU_EXEC_RETRIES"))
        transient_used = 0
        # a job that already died with its executor re-enters the
        # ladder where it left off: the second crash lands it on the
        # CPU engines (a device/engine fault may be what killed it)
        tier_cpu = job.crash_count >= 2
        blob: Optional[bytes] = None
        try:
            for attempt_no in range(64):  # ladder is finite
                if job.preempt.is_set():
                    # cooperative preemption (round 23): honored only
                    # at ladder-attempt boundaries — a polish dispatch
                    # is never interrupted, so a first attempt that
                    # succeeds outruns its own preemption (completion
                    # wins; drain, never kill)
                    job.attempts.append({
                        "n": attempt_no, "engine": "-",
                        "class": "preempt", "action": "drain"})
                    break
                try:
                    faults.check("serve.polish", attempt=attempt_no)
                    blob = self._polish(job, worker, cpu=tier_cpu)
                    break
                except Exception as e:
                    cls = faults.classify(e)
                    metrics.inc(f"faults.{cls}")
                    err = f"{type(e).__name__}: {e}"
                    att = {"n": attempt_no,
                           "engine": "cpu" if tier_cpu else "primary",
                           "class": cls, "error": err}
                    job.attempts.append(att)
                    if cls == faults.CLASS_TRANSIENT and \
                            transient_used < max_retries:
                        backoff = faults.backoff_s(
                            max(0.0, flags.get_float(
                                "RACON_TPU_EXEC_BACKOFF_S")),
                            transient_used,
                            f"{job.id}:{transient_used}")
                        att["action"] = "retry-backoff"
                        att["backoff_s"] = round(backoff, 3)
                        transient_used += 1
                        warn(f"job {job.id} transient fault ({err}) — "
                             f"retry {transient_used}/{max_retries} in "
                             f"{backoff:.2f}s")
                        time.sleep(backoff)
                    elif cls == faults.CLASS_OOM and not tier_cpu and \
                            worker.reduce_capacity():
                        att["action"] = "reduce-capacity"
                        # the halved arenas dispatch NEW geometries by
                        # design: this job leaves the warm-path claim
                        # (the ladder contract is that it survives),
                        # and the seal re-opens so the shrunk engine's
                        # re-warm compiles land in the warmed set
                        # instead of failing every subsequent sanitized
                        # job — the next completed job re-seals
                        job.post_warm_eligible = False
                        compilewatch.unseal()
                        warn(f"job {job.id} device OOM ({err}) — "
                             f"halved worker {worker.worker}'s "
                             f"consensus arena/group capacity, "
                             f"re-dispatching on the device "
                             f"(warm-path seal re-opened)")
                    elif not tier_cpu:
                        tier_cpu = True
                        att["action"] = "cpu-retry"
                        # off the warm path by definition: the failed
                        # device attempt may have compiled, but the
                        # ladder contract says this job completes on
                        # the CPU engines — it is not judged by the
                        # warm-path assert (its story is in `attempts`)
                        job.post_warm_eligible = False
                        warn(f"job {job.id} attempt failed ({err}) — "
                             f"retrying on the CPU engines")
                    else:
                        att["action"] = "fail"
                        job.error = "; ".join(
                            a["error"] for a in job.attempts)
                        break
            job.wall_s = time.perf_counter() - t0
            job.compile_s = metrics.timer_s(scope + "compile.jax_s")
            # warm-path claim (round 18): compiles attributed to this
            # job's scope after the server sealed warm-up are counted
            # into the result header; under RACON_TPU_SANITIZE=1 they
            # FAIL the job with the offending (function, signature)
            # named next to the nearest warmed one.  Only jobs that
            # STARTED after the seal are judged — a concurrent job
            # already compiling when job #1 completed is not failed
            # retroactively.
            if job.post_warm_eligible:
                try:
                    viol = sanitize.check_post_warm_compiles(scope)
                    job.compiles_after_warm = len(viol)
                except sanitize.CompileAfterWarmError as e:
                    job.compiles_after_warm = len(
                        compilewatch.post_warm(scope))
                    job.error = f"sanitized warm-path assert: {e}"
                    blob = None
            if blob is not None:
                if self._journal is not None:
                    # results spool to CRC-verified files, not RAM:
                    # the server's memory stays bounded by in-flight
                    # work and the payload survives a restart
                    job.spool, job.result_bytes, job.crc32 = \
                        self._journal.spool_write(job.id, blob)
                    job.result = None
                else:
                    job.result = blob
                    job.result_bytes = len(blob)
                job.engine = "cpu-retry" if tier_cpu else "primary"
                job.state = DONE
                # first completed job = warm-up complete: every shape
                # the startup profile, admission warm-ups and job #1
                # compiled is now the warmed set, and any later compile
                # of a never-seen (function, signature) is a warm-path
                # violation (warned + counted; a hard job failure under
                # RACON_TPU_SANITIZE=1)
                compilewatch.seal(f"serve warm path "
                                  f"(job {job.id} complete)")
            elif job.preempt.is_set():
                # drained at a ladder boundary: terminal here, but NOT
                # a failure — the fleet gateway requeues the job and
                # places it again under a fresh incarnation key
                job.state = CANCELLED
                job.error = job.error or (
                    "preempted: drained back to the queue at a "
                    "ladder boundary")
            else:
                job.state = FAILED
            # the per-job run report: built from THIS job's metric
            # scope, so concurrent jobs' numbers stay disjoint — the
            # machine-readable artifact returned alongside the result
            job.report = obs_report.build_report(
                "job", argv=[job.id, spec_summary(job.spec)],
                started_unix=t_start, wall_s=job.wall_s,
                phases=job.phases, scope=scope)
            # judged (or ladder-exempted) and reported: drop this
            # scope's violation records so the bounded global list
            # never fills up and quietly stops flagging later jobs
            compilewatch.clear_scope(scope)
        finally:
            metrics.set_scope(None)
            # the report snapshot above embeds everything the scope
            # held; retiring the registry entries NOW is what keeps a
            # server that runs 100k jobs from growing the metrics
            # dicts without bound (the heartbeat only reads RUNNING
            # jobs' scopes, so nothing still wants these)
            metrics.clear_job(job.id)

    # ----------------------------------------- journal lifecycle + recovery

    def _journal_terminal(self, job: Job) -> None:
        """Durably record a job's terminal transition.  A failed append
        here is logged, not raised: losing a ``done`` record only means
        the job re-runs (byte-identically) after a restart — safe,
        where a dead worker thread is not."""
        if self._journal is None or \
                job.state not in (DONE, FAILED, CANCELLED):
            return
        try:
            if job.state == DONE:
                self._journal.append({
                    "rec": "done", "job": job.id,
                    "bytes": job.result_bytes, "crc32": job.crc32,
                    "spool": job.spool,
                    "wall_s": round(job.wall_s, 3),
                    "engine": job.engine})
            elif job.state == CANCELLED:
                # a preempt-drained run: without this record a restart
                # would re-run a job the gateway already re-placed
                # elsewhere — a duplicate polish nobody collects
                self._journal.append({"rec": "cancelled",
                                      "job": job.id,
                                      "error": job.error or ""})
            else:
                self._journal.append({"rec": "failed", "job": job.id,
                                      "error": job.error or ""})
        except Exception as e:
            log_swallowed("serve: journal terminal record failed "
                          "(the job will re-run after a restart)", e)

    def _live_records_locked(self) -> List[dict]:
        """The live-jobs-only journal a compaction rewrites to: one
        ``submitted`` record, the job's ``running`` incarnations (the
        crash ladder's input must survive compaction), and the ``done``
        record for an uncollected payload.  Fully retired jobs —
        collected, failed, cancelled — drop out (their client already
        has the answer; a keyed resubmission simply runs fresh).
        Caller holds the scheduler lock; returns ``(records,
        live job ids)`` — the ids feed the orphan-spool sweep."""
        recs: List[dict] = []
        live: List[str] = []
        for job in self._jobs.values():
            if (job.state in (FAILED, CANCELLED)
                    and not job.shutdown_orphan) or \
                    (job.state == DONE and job.collected):
                continue
            live.append(job.id)
            recs.append({"rec": "submitted", "job": job.id,
                         "key": job.key, "cost": job.cost,
                         "unix": round(job.submitted_unix, 3),
                         "spec": job.spec})
            for k in range(job.journal_runs):
                recs.append({"rec": "running", "job": job.id,
                             "worker": job.worker, "run": k + 1})
            if job.state == DONE:
                recs.append({"rec": "done", "job": job.id,
                             "bytes": job.result_bytes,
                             "crc32": job.crc32, "spool": job.spool,
                             "wall_s": round(job.wall_s, 3),
                             "engine": job.engine})
        return recs, live

    def _compact(self) -> None:
        """Rewrite the journal to live jobs only (atomic tmp → fsync →
        rename) and sweep orphaned spool files — what keeps a
        long-lived server's serve-dir bounded."""
        j = self._journal
        if j is None:
            return
        # lock order journal -> state, matching every append site
        # (appends happen outside the scheduler lock); the round-15
        # lock-order witness checks this under RACON_TPU_SANITIZE=1.
        # Snapshot and rewrite happen under ONE journal-lock hold so a
        # concurrent append cannot slip between them and be dropped.
        with j.lock:
            with self._cond:
                recs, live = self._live_records_locked()
            # graftlint: disable=blocking-under-lock (snapshot+rewrite must be one atomic hold vs appends)
            j.rewrite_locked(recs)
        j.sweep_spool(live)

    def _maybe_compact(self) -> None:
        j = self._journal
        if j is not None and \
                j.appends_since_rewrite >= j.compact_every:
            self._compact()

    def _recover(self) -> None:
        """Restart recovery: replay the journal and pick every live job
        back up — completed jobs serve from the (CRC-verified) spool
        without re-polishing, queued/running jobs re-enter the queue in
        submission order walking the crash ladder, and terminal jobs
        answer status queries.  Runs before any worker starts."""
        if self._journal is None:
            return
        records = self._journal.replay()
        metrics.inc("serve.journal_replayed", len(records))
        by_job: Dict[str, List[dict]] = {}
        order: List[str] = []
        for rec in records:
            jid = rec.get("job")
            if not isinstance(jid, str):
                continue
            if jid not in by_job:
                order.append(jid)
            by_job.setdefault(jid, []).append(rec)
        max_id = 0
        n_live = n_spool = n_requeued = 0
        for jid in order:
            recs = by_job[jid]
            sub = next((r for r in recs
                        if r.get("rec") == "submitted"), None)
            if sub is None:
                continue  # unreadable head: nothing admissible remains
            if jid.startswith("j") and jid[1:].isdigit():
                max_id = max(max_id, int(jid[1:]))
            if any(r.get("rec") == "collected" for r in recs):
                continue  # fully retired; compaction fodder
            kinds = {r.get("rec"): r for r in recs}
            spec, err = protocol.normalize_spec(sub.get("spec") or {})
            if spec is None:
                warn(f"journal job {jid} has an unreadable spec "
                     f"({err}) — dropping it")
                continue
            job = Job(jid, spec, int(sub.get("cost", 0)))
            job.key = sub.get("key") or None
            job.recovered = True
            job.submitted_unix = float(sub.get("unix") or
                                       job.submitted_unix)
            job.journal_runs = sum(1 for r in recs
                                   if r.get("rec") == "running")
            n_live += 1
            if "cancelled" in kinds:
                job.state = CANCELLED
                job.error = "cancelled by client (before the restart)"
                self._register_recovered(job)
                continue
            if "failed" in kinds:
                job.state = FAILED
                job.error = str(kinds["failed"].get("error") or
                                "failed (before the restart)")
                self._register_recovered(job)
                continue
            done_rec = kinds.get("done")
            if done_rec is not None:
                blob = self._journal.spool_read(
                    jid, int(done_rec.get("bytes", -1)),
                    int(done_rec.get("crc32", 0)))
                if blob is not None:
                    # served from the spool: completed-at-crash work is
                    # NOT re-polished (the soak asserts zero duplicate
                    # running records for these)
                    job.state = DONE
                    job.spool = done_rec.get("spool") or \
                        self._journal.spool_name(jid)
                    job.result_bytes = int(done_rec.get("bytes", 0))
                    job.crc32 = int(done_rec.get("crc32", 0))
                    job.wall_s = float(done_rec.get("wall_s") or 0.0)
                    job.engine = done_rec.get("engine")
                    n_spool += 1
                    self._register_recovered(job)
                    continue
                # truncated/corrupt spool: the result is lost — requeue
                # the job instead of serving garbage (the round-12
                # part-verification rule)
                metrics.inc("serve.spool_corrupt")
                warn(f"job {jid}: result spool failed verification — "
                     f"re-queueing instead of serving a corrupt result")
            # queued or running at crash time: re-admit down the ladder
            job.crash_count = job.journal_runs
            for k in range(job.crash_count):
                job.attempts.append({
                    "n": k, "engine": "primary", "class": "crash",
                    "error": "server died while the job was running",
                    "action": ("fail" if k + 1 >= _MAX_JOB_CRASHES
                               else "requeue")})
            if job.crash_count >= _MAX_JOB_CRASHES:
                job.state = FAILED
                job.error = (f"the server crashed {job.crash_count} "
                             f"times while running this job — failing "
                             f"it down the ladder instead of an "
                             f"infinite redo loop")
                self._register_recovered(job)
                continue
            with self._cond:
                self._queue.append(job)
            n_requeued += 1
            self._register_recovered(job)
        with self._cond:
            self._next_id = max(self._next_id, max_id)
        metrics.inc("serve.recovered_jobs", n_live)
        metrics.inc("serve.requeued_jobs", n_requeued)
        metrics.inc("serve.spool_served", n_spool)
        if n_live:
            _eprint(f"recovery: {n_live} journaled job(s) restored "
                    f"({n_spool} served from the result spool, "
                    f"{n_requeued} re-queued) from {self.serve_dir}")
        # clean-startup compaction: the replayed history is rewritten
        # live-jobs-only, so crash-looped serve dirs stay bounded
        self._compact()

    def _register_recovered(self, job: Job) -> None:
        with self._cond:
            self._jobs[job.id] = job
            if job.key:
                self._by_key[job.key] = job.id
            self._counts["submitted"] += 1
            if job.state in _TERMINAL:
                self._counts[job.state] = \
                    self._counts.get(job.state, 0) + 1
                self._retired.append(job.id)
                job.done.set()
            self._cond.notify_all()

    # --------------------------------------------------- slot supervision

    def healthy_workers(self) -> int:
        """Advertised capacity: resolved slots minus quarantined ones
        (admission reads this — a server whose every slot died stops
        accepting instead of queueing into a black hole)."""
        with self._slots_lock:
            slots = self._slots or []
            return sum(1 for w in slots
                       if w.ordinal not in self._quarantined)

    def _supervise_loop(self) -> None:
        """Slot supervision: a worker thread that died outside the
        per-job ladder (device fault, unhandled exception, injected
        ``serve.slot`` chaos) is detected here; its job fails down the
        per-job crash ladder and the slot restarts with fresh engines.
        Repeated deaths quarantine the slot — capacity shrinks, the
        server survives."""
        while not self._stop.wait(_SUPERVISE_POLL_S):
            with self._slots_lock:
                slots = list(self._slots or [])
            for idx, slot in enumerate(slots):
                t = self._slot_threads.get(slot.ordinal)
                if t is None or t.is_alive() or self._stop.is_set():
                    continue
                if slot.ordinal in self._quarantined:
                    continue
                self._handle_slot_death(idx, slot)

    def _handle_slot_death(self, idx: int, slot: _ChipWorker) -> None:
        deaths = self._slot_deaths.get(slot.ordinal, 0) + 1
        self._slot_deaths[slot.ordinal] = deaths
        metrics.inc("slot.deaths")
        job = slot.current_job
        failed_job = None
        with self._cond:
            if job is not None and job.state == RUNNING:
                # the dying thread never reached its finally: the
                # footprint reservation and the job are both orphaned
                self._running_cost -= job.cost
                job.crash_count += 1
                att = {"n": len(job.attempts), "engine": "primary",
                       "class": "crash",
                       "error": f"worker slot {slot.worker} died while "
                                f"running this job"}
                job.attempts.append(att)
                if job.crash_count >= _MAX_JOB_CRASHES:
                    att["action"] = "fail"
                    job.state = FAILED
                    job.error = (f"executor died {job.crash_count} "
                                 f"times on this job — failing it "
                                 f"down the ladder")
                    self._counts["failed"] = \
                        self._counts.get("failed", 0) + 1
                    self._retired.append(job.id)
                    failed_job = job
                else:
                    att["action"] = "requeue"
                    job.state = QUEUED
                    job.worker = None
                    job.started_at = None
                    # head of the queue: it was already running
                    self._queue.insert(0, job)
                self._cond.notify_all()
            slot.current_job = None
        if failed_job is not None:
            self._journal_terminal(failed_job)
            failed_job.done.set()
        if deaths >= _SLOT_QUARANTINE_DEATHS:
            self._quarantined.add(slot.ordinal)
            metrics.inc("slot.quarantined")
            warn(f"worker slot {slot.worker} died {deaths} times — "
                 f"quarantining it (advertised capacity is now "
                 f"{self.healthy_workers()} worker(s))")
            if self.healthy_workers() == 0:
                warn("every worker slot is quarantined — failing "
                     "queued jobs and rejecting new submissions")
                with self._cond:
                    stranded = list(self._queue)
                    for queued in stranded:
                        queued.state = FAILED
                        queued.error = ("no healthy worker slots left "
                                        "(all quarantined)")
                        self._counts["failed"] = \
                            self._counts.get("failed", 0) + 1
                        self._retired.append(queued.id)
                        queued.done.set()
                    self._queue.clear()
                    self._cond.notify_all()
                # journal the failures (outside the lock): the clients
                # were TOLD failed — a restart must not resurrect and
                # re-run jobs nobody will ever fetch
                for queued in stranded:
                    self._journal_terminal(queued)
            return
        fresh = _ChipWorker(self, slot.slot, pinned=slot.device is not None)
        fresh.worker = slot.worker  # keep the identity stable
        with self._slots_lock:
            if self._slots is not None and idx < len(self._slots) \
                    and self._slots[idx] is slot:
                self._slots[idx] = fresh
            # drop the dead thread's registration NOW: until the
            # replacement registers, an absent mapping reads as
            # "not started yet" and the supervisor skips it (leaving
            # it would re-detect the same death next tick)
            self._slot_threads.pop(slot.ordinal, None)
        metrics.inc("slot.restarts")
        _eprint(f"slot {slot.worker} died (death {deaths}/"
                f"{_SLOT_QUARANTINE_DEATHS}) — restarting it with "
                f"fresh engines")
        self._spawn_worker(fresh)

    # ----------------------------------------------------------- protocol

    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        try:
            while True:
                try:
                    msg = protocol.read_msg(rfile)
                except ValueError as e:
                    protocol.send_msg(conn, {"ok": False,
                                             "error": f"bad request: {e}"})
                    return
                if msg is None:
                    return
                try:
                    if not self._dispatch_op(conn, msg):
                        return
                except (ValueError, TypeError, KeyError) as e:
                    # a malformed FIELD (non-numeric timeout_s, an
                    # unhashable job id) is the client's fault: answer
                    # with the reason instead of letting the handler
                    # thread die and the socket close silently
                    protocol.send_msg(conn, {
                        "ok": False,
                        "error": f"bad request field: "
                                 f"{type(e).__name__}: {e}"})
        except OSError as e:
            # a client hanging up mid-response is its own business —
            # the server's job records stay intact either way
            log_swallowed("serve: client connection dropped", e)
        finally:
            rfile.close()
            conn.close()

    def _dispatch_op(self, conn, msg: dict) -> bool:
        """Handle one request; False ends the connection loop."""
        op = msg.get("op")
        if op == "ping":
            self._chip_slots()  # resolve before counting capacity
            protocol.send_msg(conn, {
                "ok": True, "server": self.worker,
                "uptime_s": round(time.perf_counter() - self._t0, 3),
                "profile": {"match": self.match,
                            "mismatch": self.mismatch, "gap": self.gap,
                            "banded": self.banded},
                "workers": self.healthy_workers(),
                "serve_dir": self.serve_dir,
                "draining": self._draining})
            return True
        if op == "submit":
            key = msg.get("key")
            if key is not None and not isinstance(key, str):
                protocol.send_msg(conn, {
                    "ok": False,
                    "error": "idempotency key must be a string"})
                return True
            job, reason, existing = self._admit(msg.get("spec", {}),
                                                key=key)
            if job is None:
                with self._lock:
                    self._counts["rejected"] += 1
                protocol.send_msg(conn, {"ok": False, "error": reason,
                                         "rejected": True})
                return True
            protocol.send_msg(conn, {"ok": True, "job": job.id,
                                     "state": job.state,
                                     "cost_bytes": job.cost,
                                     "existing": existing})
            return True
        if op in ("status", "result", "cancel", "preempt"):
            job = self._jobs.get(msg.get("job", ""))
            if job is None:
                protocol.send_msg(conn, {
                    "ok": False,
                    "error": f"unknown job {msg.get('job')!r}"})
                return True
            if op == "status":
                row = job.row()
                with self._lock:
                    if job in self._queue:
                        row["queue_position"] = self._queue.index(job)
                protocol.send_msg(conn, {"ok": True, **row})
                return True
            if op == "cancel":
                return self._op_cancel(conn, job)
            if op == "preempt":
                return self._op_preempt(conn, job)
            return self._op_result(conn, job, msg)
        if op == "stats":
            with self._lock:
                counts = dict(self._counts)
                depth = len(self._queue)
                running = self._running_cost
                tenants: Dict[str, int] = {}
                for queued_job in self._queue:
                    tenants[queued_job.tenant] = \
                        tenants.get(queued_job.tenant, 0) + 1
            out = {
                "ok": True, **counts, "queued": depth,
                "tenants": tenants,
                "running_cost_bytes": running,
                "budget_bytes": self.budget_bytes,
                "peak_rss_bytes": metrics.peak_rss_bytes(),
                "quarantined_slots": len(self._quarantined),
                "slots": {"healthy": self.healthy_workers(),
                          "quarantined": len(self._quarantined)},
                "slot_restarts": int(metrics.counter("slot.restarts"))}
            if self._journal is not None:
                out["serve_dir"] = self.serve_dir
                out["recovery"] = metrics.recovery_summary()
            protocol.send_msg(conn, out)
            return True
        if op == "shutdown":
            mode = msg.get("mode", "now")
            if mode not in ("now", "drain"):
                protocol.send_msg(conn, {
                    "ok": False,
                    "error": f"unknown shutdown mode {mode!r} "
                             f"(now | drain)"})
                return True
            if mode == "drain":
                # admission must be stopped BEFORE the reply lands: a
                # client that sees "draining" and immediately submits
                # must deterministically be rejected
                with self._lock:
                    self._draining = True
            protocol.send_msg(conn, {
                "ok": True,
                "state": "draining" if mode == "drain" else "stopping"})
            self.shutdown(mode=mode)
            return False
        protocol.send_msg(conn, {"ok": False,
                                 "error": f"unknown op {op!r}"})
        return True

    def _op_cancel(self, conn, job: Job) -> bool:
        cancelled = False
        with self._cond:
            if job in self._queue:
                self._queue.remove(job)
                job.state = CANCELLED
                job.error = "cancelled by client"
                self._counts["cancelled"] += 1
                self._retired.append(job.id)  # bounded-history horizon
                job.done.set()
                cancelled = True
        # reply OUTSIDE the scheduler lock (blocking-under-lock): a
        # client slow to drain its socket must not stall every worker
        # contending for the state lock
        if cancelled:
            if self._journal is not None:
                try:
                    self._journal.append({"rec": "cancelled",
                                          "job": job.id})
                except Exception as e:
                    log_swallowed(
                        "serve: journal cancel record failed (the job "
                        "would re-run after a restart)", e)
            protocol.send_msg(conn, {"ok": True, "job": job.id,
                                     "state": job.state})
            return True
        protocol.send_msg(conn, {
            "ok": False, "job": job.id, "state": job.state,
            "error": f"job {job.id} is not queued ({job.state}) — a "
                     f"running job cannot be safely interrupted "
                     f"mid-dispatch"})
        return True

    def _op_preempt(self, conn, job: Job) -> bool:
        """The fleet gateway's drain request (round 23): a QUEUED job
        is released immediately (``drained: true`` — it never ran); a
        RUNNING job gets its cooperative preempt flag and drains at
        the next ladder-attempt boundary or completes first
        (``drained: false`` — the gateway watches its status either
        way).  Never kills a dispatch mid-flight."""
        drained = False
        running = False
        with self._cond:
            if job in self._queue:
                self._queue.remove(job)
                job.state = CANCELLED
                job.error = "preempted by the fleet scheduler"
                self._counts["cancelled"] += 1
                self._retired.append(job.id)
                job.done.set()
                drained = True
            elif job.state == RUNNING:
                job.preempt.set()
                running = True
        # reply OUTSIDE the scheduler lock, like _op_cancel
        if drained:
            if self._journal is not None:
                try:
                    self._journal.append({"rec": "cancelled",
                                          "job": job.id})
                except Exception as e:
                    log_swallowed(
                        "serve: journal preempt record failed (the "
                        "job would re-run after a restart)", e)
            protocol.send_msg(conn, {"ok": True, "job": job.id,
                                     "state": job.state,
                                     "drained": True})
            return True
        if running:
            protocol.send_msg(conn, {
                "ok": True, "job": job.id, "state": job.state,
                "drained": False,
                "note": "running — drains at the next ladder "
                        "boundary or completes first"})
            return True
        protocol.send_msg(conn, {
            "ok": False, "job": job.id, "state": job.state,
            "error": f"job {job.id} is already terminal "
                     f"({job.state})"})
        return True

    def _op_result(self, conn, job: Job, msg: dict) -> bool:
        timeout = float(msg.get("timeout_s", DEFAULT_RESULT_TIMEOUT_S))
        if not job.done.wait(timeout):
            protocol.send_msg(conn, {
                "ok": False, "job": job.id, "state": job.state,
                "timeout": True,
                "error": f"job {job.id} not finished within "
                         f"{timeout:.0f}s (still {job.state})"})
            return True
        header = {"ok": job.state == DONE, **job.row(),
                  "report": job.report}
        if job.state != DONE:
            protocol.send_msg(conn, header)
            return True
        with self._lock:
            blob = job.result
            spool = job.spool if self._journal is not None else None
            collected = job.collected
        if blob is None and spool and not collected:
            # spooled result (--serve-dir): re-read and CRC-verify on
            # EVERY fetch — a disk that lied about fsync or flipped a
            # bit must re-queue the job, never stream garbage (the
            # round-12 part-verification rule)
            blob = self._journal.spool_read(job.id, job.result_bytes,
                                            job.crc32)
            if blob is None:
                with self._lock:
                    racing_collected = job.collected
                if not racing_collected:
                    self._requeue_corrupt_spool(job)
                    header.update(
                        ok=False, state=job.state,
                        error=f"job {job.id} result spool failed "
                              f"verification — the job was re-queued; "
                              f"retry the fetch")
                    protocol.send_msg(conn, header)
                    return True
                # a racing fetcher streamed + unlinked the spool while
                # we were between the snapshot and the read: the result
                # was DELIVERED, not lost — answer "collected", never
                # re-queue already-delivered work
        if blob is None:
            why = ("was already collected (payloads are retained for "
                   "one successful fetch)" if job.collected
                   else "was retired (the server keeps a bounded "
                        "terminal-job history)")
            header.update(ok=False,
                          error=f"job {job.id} result {why}")
            protocol.send_msg(conn, header)
            return True
        header["bytes"] = len(blob)
        protocol.send_msg(conn, header)
        conn.sendall(blob)
        if not msg.get("keep", False):
            # retention: the FASTA payload is the big allocation — one
            # SUCCESSFUL fetch per job keeps a long-lived server's
            # memory bounded by in-flight work, not by its history.
            # Dropped only AFTER sendall returned: a client that died
            # waiting must be able to reconnect and fetch (two racing
            # fetchers both succeed; the second drop is a no-op).
            with self._lock:
                newly = not job.collected
                job.result = None
                job.collected = True
            if newly and self._journal is not None:
                try:
                    self._journal.append({"rec": "collected",
                                          "job": job.id})
                except Exception as e:
                    log_swallowed(
                        "serve: journal collected record failed (the "
                        "result would be re-servable after a restart "
                        "— safe)", e)
                self._journal.spool_unlink(job.id)
                self._maybe_compact()
        return True

    def _requeue_corrupt_spool(self, job: Job) -> None:
        """A spooled result that fails verification is LOST work, not
        servable work: put the job back at the head of the queue (it
        re-polishes byte-identically) — mirroring the exec runner's
        corrupt-part re-queue."""
        with self._cond:
            if job.state != DONE or job.collected:
                return  # racing fetcher re-queued it / already served
            metrics.inc("serve.spool_corrupt")
            warn(f"job {job.id}: result spool corrupt at fetch time — "
                 f"re-queueing the job")
            job.state = QUEUED
            job.done.clear()
            job.result = None
            job.spool = None
            job.attempts.append({
                "n": len(job.attempts), "engine": "primary",
                "class": "spool-corrupt", "action": "requeue",
                "error": "result spool failed size/CRC verification"})
            # it is live again: pull it back off the retention horizon,
            # or 1024 later terminals would evict it mid-queue (and its
            # re-completion would double-append the horizon entry)
            try:
                self._retired.remove(job.id)
            except ValueError:
                pass
            self._queue.insert(0, job)
            self._cond.notify_all()

    # ---------------------------------------------------------- lifecycle

    def _heartbeat_loop(self, interval: float) -> None:
        """Per-job progress heartbeat: one line per tick naming every
        running job with its scope's pack/queue/retrace summaries —
        the shard heartbeat's fields, re-keyed per job."""
        while not self._stop.wait(interval):
            with self._lock:
                running = [j for j in self._jobs.values()
                           if j.state == RUNNING]
                depth = len(self._queue)
                counts = dict(self._counts)
            fields = []
            for j in running:
                scope = metrics.job_scope(j.id)
                dt = (time.perf_counter() - j.started_at
                      if j.started_at else 0.0)
                fields.append(
                    f"{j.id}@{hb.Heartbeat._short(j.worker or '?')}"
                    f" {dt:.1f}s pack[{hb.pack_summary_str(scope)}]"
                    f" queue[{hb.queue_summary_str(scope)}]"
                    f" retrace[{hb.retrace_summary(scope)}]")
            _eprint(f"heartbeat: {counts.get('done', 0)} done, "
                    f"{counts.get('failed', 0)} failed, "
                    f"{len(running)} running"
                    + (" (" + "; ".join(fields) + ")" if fields else "")
                    + f", {depth} queued, "
                    f"peak_rss={metrics.peak_rss_bytes() >> 20}MB")

    def _spawn_worker(self, w: _ChipWorker) -> None:
        t = threading.Thread(target=self._worker_loop, args=(w,),
                             name=f"racon-serve-{w.worker}",
                             daemon=True)
        t.start()
        # registered under the slots lock (startup and the supervisor
        # both spawn), and only AFTER start() — a registered-but-not-
        # started thread reads as dead and would trip the supervisor
        with self._slots_lock:
            self._threads.append(t)
            self._slot_threads[w.ordinal] = t

    def start_workers(self) -> None:
        """Spawn the pool's worker threads plus their supervisor
        (idempotent; split out so tests can exercise the queue
        deterministically before any worker drains it)."""
        if self._threads:
            return
        for w in self._chip_slots():
            self._spawn_worker(w)
        # graftlint: disable=lock-discipline (start_workers runs once, guarded by the _threads check, on the single startup path)
        self._supervisor = threading.Thread(
            target=self._supervise_loop,
            name="racon-serve-supervisor", daemon=True)
        self._supervisor.start()

    def _bind(self) -> socket.socket:
        path = self.socket_path
        if os.path.exists(path):
            import stat as stat_mod
            if not stat_mod.S_ISSOCK(os.stat(path).st_mode):
                # refuse, don't unlink: a typo'd --serve path must not
                # delete the operator's regular file
                raise RuntimeError(
                    f"{path} exists and is not a socket — refusing to "
                    f"replace it")
            # a previous server may have died without unlinking; only a
            # CONNECTABLE socket proves a live one
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(1.0)
            try:
                probe.connect(path)
            except OSError as e:
                log_swallowed("serve: removing stale socket file", e)
                os.unlink(path)
            else:
                raise RuntimeError(
                    f"another server is already listening on {path}")
            finally:
                probe.close()
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(path)
        listener.listen(64)
        return listener

    def serve_forever(self) -> int:
        """Bind, warm the pool, accept until :meth:`shutdown`.  Returns
        an exit code (0 on a clean stop)."""
        compilewatch.arm()
        # a fresh server owns the process's warm-path state: re-open
        # the seal and drop stale attribution — events/counts AND the
        # registry's compile.* timers/counters, so a second in-process
        # server does not report a predecessor's total_s next to
        # count=0 (matters for in-process test servers sharing one
        # interpreter; production runs one server per process, where
        # this is a startup no-op)
        compilewatch.reset()
        metrics.clear("compile.")
        # span TIMERS must record for the life of the server: the
        # per-job dispatch/fetch split reads them through each job's
        # metric scope (ring-buffer tracing stays off — a long-lived
        # daemon's trace is unbounded by definition)
        from ..obs import trace
        trace.activate()
        # serve_forever runs on exactly ONE thread per server (the
        # process main thread in production, the single spawner thread
        # in tests) — its attribute writes below never race themselves
        # graftlint: disable=lock-discipline (serve_forever runs on exactly one thread per server instance)
        self._listener = self._bind()
        # restart recovery BEFORE any worker can drain the queue: the
        # journal's live jobs re-enter in submission order
        self._recover()
        self._warm_pool()
        if self.autostart:
            self.start_workers()
        # graceful drain on SIGTERM (the preemption signal): stop
        # admission, finish in-flight jobs, flush the journal, exit.
        # Only the process main thread may install handlers (in-process
        # test servers run serve_forever on a spawned thread).
        if threading.current_thread() is threading.main_thread():
            try:
                signal_mod.signal(
                    signal_mod.SIGTERM,
                    lambda *_: threading.Thread(
                        target=self.shutdown, kwargs={"mode": "drain"},
                        name="racon-serve-drain", daemon=True).start())
            except (ValueError, OSError) as e:
                log_swallowed("serve: SIGTERM drain handler "
                              "unavailable", e)
        interval = flags.get_float("RACON_TPU_HEARTBEAT_S")
        if interval > 0:
            t = threading.Thread(target=self._heartbeat_loop,
                                 args=(interval,),
                                 name="racon-serve-heartbeat",
                                 daemon=True)
            t.start()
        if self.fleet_dir:
            # registered AFTER the socket is bound: the beacon
            # advertises a listener the gateway can actually reach
            from ..fleet import registry as fleet_registry
            beacon = fleet_registry.HostBeacon(
                self.fleet_dir, socket_path=self.socket_path).start()
            # written once before the accept loop starts; shutdown
            # reads it only after _stop is set
            self._beacon = beacon  # graftlint: disable=lock-discipline (pre-accept-loop write)
            _eprint(f"fleet member {self._beacon.name} registered "
                    f"in {self.fleet_dir}")
        _eprint(f"listening on {self.socket_path} "
                f"(server {self.worker})")
        self.started.set()
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except OSError:
                    break  # listener closed by shutdown()
                t = threading.Thread(target=self._handle_conn,
                                     args=(conn,), daemon=True)
                t.start()
                self._conn_threads.append(t)
                # graftlint: disable=lock-discipline (serve_forever runs on exactly one thread per server instance)
                self._conn_threads = [c for c in self._conn_threads
                                      if c.is_alive()]
        finally:
            self.shutdown()
            for t in list(self._threads):
                t.join()
            if self._supervisor is not None:
                self._supervisor.join()
            self._finish_journal()
        _eprint(f"stopped ({self._counts['done']} done, "
                f"{self._counts['failed']} failed, "
                f"{self._counts['rejected']} rejected)")
        return 0

    def _finish_journal(self) -> None:
        """Final flush: one last live-jobs-only compaction (every
        worker has exited, so the snapshot is the run's terminal truth)
        and a clean close — the 'flushes the journal, then exits' leg
        of the drain contract."""
        if self._journal is None:
            return
        try:
            self._compact()
        except Exception as e:
            log_swallowed("serve: final journal compaction failed "
                          "(the un-compacted journal replays fine)", e)
        self._journal.close()

    def shutdown(self, mode: str = "now") -> None:
        """Stop the server (idempotent).  ``mode="now"``: stop
        admission and scheduling immediately — running jobs finish,
        queued jobs are answered FAILED in RAM but deliberately NOT
        journaled as failed, so a ``--serve-dir`` server recovers and
        runs them after restart.  ``mode="drain"``: stop admission,
        wait (bounded by ``RACON_TPU_SERVE_DRAIN_S``) for the queue
        AND the in-flight jobs to finish, then stop."""
        if mode == "drain" and not self._stop.is_set():
            with self._cond:
                self._draining = True
            _eprint("drain: admission stopped — finishing queued "
                    "and in-flight jobs")
            bound = flags.get_float("RACON_TPU_SERVE_DRAIN_S")
            deadline = (time.monotonic() + bound) if bound > 0 \
                else None
            drained = True
            with self._cond:
                while self._queue or any(
                        j.state == RUNNING
                        for j in self._jobs.values()):
                    if self._stop.is_set():
                        drained = False
                        break
                    if deadline is not None and \
                            time.monotonic() > deadline:
                        warn(f"drain: still busy after {bound:.0f}s "
                             f"(RACON_TPU_SERVE_DRAIN_S) — stopping "
                             f"anyway")
                        drained = False
                        break
                    self._cond.wait(0.2)
            if drained:
                _eprint("drain: all jobs finished")
        if self._stop.is_set():
            return
        self._stop.set()
        if self._beacon is not None:
            # deregister (clean goodbye): the gateway sees the beacon
            # withdrawn instead of waiting a TTL to declare us dead
            self._beacon.stop()
            self._beacon = None  # graftlint: disable=lock-discipline (_stop-gated shutdown)
        with self._cond:
            for job in self._queue:
                job.state = FAILED
                job.shutdown_orphan = self._journal is not None
                job.error = ("server shutdown before the job ran"
                             + (" — it is journaled and will recover "
                                "on restart from the same --serve-dir"
                                if self._journal is not None else ""))
                job.done.set()
            self._queue.clear()
            self._cond.notify_all()
        if self._listener is not None:
            try:
                # shutdown() BEFORE close(): a close alone does not
                # reliably wake a thread blocked in accept() on Linux —
                # the accept loop would outlive the server
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError as e:
                log_swallowed("serve: listener shutdown failed", e)
            try:
                self._listener.close()
            except OSError as e:
                log_swallowed("serve: listener close failed", e)
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        except OSError as e:
            log_swallowed("serve: socket unlink failed", e)


def spec_summary(spec: dict) -> str:
    """One-line human summary of a job spec (report argv, logs)."""
    return (f"{os.path.basename(spec['sequences'])} "
            f"{os.path.basename(spec['overlaps'])} "
            f"{os.path.basename(spec['target_sequences'])} "
            f"-w {spec['window_length']} -t {spec['threads']}"
            + (" -f" if spec["fragment_correction"] else "")
            + (" -u" if spec["include_unpolished"] else ""))
