from .logger import Logger
from .cigar import parse_cigar, cigar_to_string, alignment_path_to_cigar

__all__ = ["Logger", "parse_cigar", "cigar_to_string", "alignment_path_to_cigar"]
