"""CIGAR parsing/formatting helpers.

The reference manipulates CIGAR strings produced by edlib
(``src/overlap.cpp:205-224``), cudaaligner (``src/cuda/cudaaligner.cpp:101``)
or taken from SAM input (``src/overlap.cpp:44-108``). Ops handled by the
reference's walkers: M/=/X (match-ish), I, D/N, S/H (clips), P.
"""

from __future__ import annotations

from typing import List, Tuple

_OPS = frozenset(b"MIDNSHP=X")


def parse_cigar(cigar: str | bytes) -> List[Tuple[int, str]]:
    """Parse a CIGAR string into ``[(length, op), ...]``."""
    if isinstance(cigar, bytes):
        cigar = cigar.decode()
    runs: List[Tuple[int, str]] = []
    num = 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            runs.append((num, ch))
            num = 0
    return runs


def cigar_to_string(runs) -> str:
    return "".join(f"{n}{op}" for n, op in runs)


def alignment_path_to_cigar(path) -> str:
    """Collapse a per-column move sequence into a CIGAR string.

    ``path`` is an iterable of single-char ops ('M'/'=' /'X'/'I'/'D').
    Equivalent in role to ``edlibAlignmentToCigar`` (EDLIB_CIGAR_STANDARD:
    emits 'M' for both match and mismatch), used by the reference at
    ``src/overlap.cpp:213-215``.
    """
    out = []
    prev = None
    count = 0
    for op in path:
        if op in ("=", "X"):
            op = "M"
        if op == prev:
            count += 1
        else:
            if prev is not None:
                out.append(f"{count}{prev}")
            prev = op
            count = 1
    if prev is not None:
        out.append(f"{count}{prev}")
    return "".join(out)
