"""Stage-timing logger with a 20-bin progress bar.

Re-creates the observable behaviour of the reference's vendored ``logger``
library (stage wall-times via paired ``log()`` calls, 20-bin progress bar via
``bar()`` — bin contract documented at ``src/cuda/cudapolisher.cpp:21-24`` —
and a ``total()`` summary; call sites ``src/polisher.cpp:188,199,222,475-481``).
"""

from __future__ import annotations

import sys
import time

from ..obs import metrics

_seen_swallowed: set = set()


def warn(message: str) -> None:
    """Process-wide warning line on stderr (stdout carries the polished
    FASTA). The sanctioned sink for non-fatal fault reports — the
    graftlint ``swallowed-exception`` rule accepts handlers that route
    through here (or :func:`log_swallowed` / ``warnings.warn``)."""
    print(f"[racon_tpu] warning: {message}", file=sys.stderr)


def log_swallowed(context: str, exc: BaseException) -> None:
    """Report a swallowed exception: every ``except Exception`` site that
    deliberately continues (fallback paths, optimization failures) calls
    this so no fault disappears silently. De-duplicated per (context,
    exception type): fallback paths can swallow the same fault once per
    chunk, and one line per cause is signal while thousands are noise.
    EVERY occurrence still counts into the metrics registry
    (``swallowed.<context>|<type>``), so the run report shows how many
    faults each once-per-cause line actually hid."""
    key = (context, type(exc).__name__)
    metrics.inc(f"swallowed.{context}|{type(exc).__name__}")
    if key in _seen_swallowed:
        return
    _seen_swallowed.add(key)
    warn(f"{context}: swallowed {type(exc).__name__}: {exc}")


class Logger:
    """Wall-clock stage logger writing to stderr.

    ``log()`` with no message starts (or restarts) a stage timer;
    ``log(msg)`` prints ``msg`` and the elapsed stage time.
    ``bar(msg)`` advances a 20-bin progress bar on the same line.
    ``total(msg)`` prints time since construction.
    """

    def __init__(self, stream=None):
        self._stream = stream if stream is not None else sys.stderr
        self._origin = time.perf_counter()
        self._stage_start = self._origin
        self._bar_bins = 0
        self._bar_abs = 0

    def log(self, message: str | None = None) -> None:
        now = time.perf_counter()
        if message is None:
            self._stage_start = now
            return
        print(f"{message} {now - self._stage_start:.6f} s", file=self._stream)

    def bar(self, message: str) -> None:
        self._bar_bins = min(self._bar_bins + 1, 20)
        fill = "=" * self._bar_bins + ">" + " " * (20 - self._bar_bins)
        pct = self._bar_bins * 5
        end = "\n" if self._bar_bins == 20 else "\r"
        print(f"{message} [{fill}] {pct}%", file=self._stream, end=end)
        self._stream.flush()
        if self._bar_bins == 20:
            self._bar_bins = 0
            self._stage_start = time.perf_counter()

    def bar_to(self, message: str, done: int, total: int) -> None:
        """Advance the bar to ``20 * done / total`` bins (batched pipelines
        report chunk completions, not per-item ticks, so the bar may jump
        several bins per call). Tracks stage progress in an absolute
        counter: ``bar()`` itself wraps ``_bar_bins`` back to 0 at 100% for
        the next stage, so counting emitted bins directly would loop."""
        target = min(20, (20 * done) // max(1, total))
        while self._bar_abs < target:
            self._bar_abs += 1
            self.bar(message)
        if target >= 20:
            self._bar_abs = 0  # stage complete; next stage starts fresh

    def total(self, message: str) -> None:
        now = time.perf_counter()
        print(f"{message} {now - self._origin:.6f} s", file=self._stream)
