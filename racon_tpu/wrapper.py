"""racon_wrapper: out-of-core orchestration (L6).

Re-creates the reference's ``scripts/racon_wrapper.py``: optionally
subsample the correction reads to a target coverage and/or split the
target sequences into byte-sized chunks with :mod:`racon_tpu.rampler`,
then polish each chunk with a separate ``racon`` process run sequentially
(chunk-level restartability: a crash loses at most one chunk,
``racon_wrapper.py:125-135``). Polished FASTA is concatenated on stdout.

The split path now routes through the in-process streaming shard runner
(:mod:`racon_tpu.exec`) by default: same byte-bounded target chunks, but
with the contig->overlap index (each chunk reads only its own overlaps
and reads instead of re-parsing the whole files), engine reuse across
chunks (one warm-up compile instead of one per subprocess), a checkpoint
manifest (a crashed ``--split`` run resumes from completed chunks on
plain re-invocation — the runner's work dir is derived from the inputs,
not this wrapper's throwaway directory), and per-shard CPU
retry/quarantine. ``--legacy-split`` keeps the original rampler +
per-chunk-subprocess path as the fallback (each chunk's memory returned
to the OS wholesale).
"""

from __future__ import annotations

import argparse
import glob
import os
import shutil
import subprocess
import sys
import time


def eprint(*args, **kwargs):
    print(*args, file=sys.stderr, **kwargs)


class RaconWrapper:
    def __init__(self, sequences, overlaps, target_sequences, split,
                 subsample, include_unpolished, fragment_correction,
                 window_length, quality_threshold, error_threshold, match,
                 mismatch, gap, threads, tpupoa_batches=0,
                 tpu_banded_alignment=False, tpualigner_batches=0,
                 legacy_split=False):
        self.legacy_split = legacy_split
        self.sequences = os.path.abspath(sequences)
        self.overlaps = os.path.abspath(overlaps)
        self.target_sequences = os.path.abspath(target_sequences)
        self.chunk_size = split
        self.reference_length, self.coverage = (
            subsample if subsample is not None else (None, None))
        self.include_unpolished = include_unpolished
        self.fragment_correction = fragment_correction
        self.window_length = window_length
        self.quality_threshold = quality_threshold
        self.error_threshold = error_threshold
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.threads = threads
        self.tpupoa_batches = tpupoa_batches
        self.tpu_banded_alignment = tpu_banded_alignment
        self.tpualigner_batches = tpualigner_batches
        self.work_directory = os.path.join(
            os.getcwd(), f"racon_work_directory_{time.time()}")

    def __enter__(self):
        try:
            os.makedirs(self.work_directory, exist_ok=True)
        except OSError:
            eprint("[RaconWrapper::__enter__] error: unable to create work "
                   "directory!")
            sys.exit(1)

    def __exit__(self, exception_type, exception_value, traceback):
        try:
            shutil.rmtree(self.work_directory)
        except OSError:
            eprint("[RaconWrapper::__exit__] warning: unable to clean work "
                   "directory!")

    def _run_module(self, module, args):
        cmd = [sys.executable, "-m", module] + args
        try:
            p = subprocess.Popen(cmd)
        except OSError:
            eprint(f"[RaconWrapper::run] error: unable to run {module}!")
            sys.exit(1)
        p.communicate()
        if p.returncode != 0:
            sys.exit(1)

    def run(self) -> None:
        eprint("[RaconWrapper::run] preparing data with rampler")
        if self.reference_length is not None and self.coverage is not None:
            self._run_module("racon_tpu.rampler",
                             ["-o", self.work_directory, "subsample",
                              self.sequences, str(self.reference_length),
                              str(self.coverage)])
            base = os.path.basename(self.sequences).split(".")[0]
            # rampler names outputs by record content (.fasta/.fastq), so
            # glob rather than guessing from the input extension
            found = glob.glob(os.path.join(
                self.work_directory, f"{base}_{self.coverage}x.*"))
            if not found:
                eprint("[RaconWrapper::run] error: unable to find "
                       "subsampled sequences!")
                sys.exit(1)
            subsampled = found[0]
        else:
            subsampled = self.sequences

        if self.chunk_size is not None and not self.legacy_split:
            # default split path: the in-process streaming shard runner
            # (same byte-bounded chunks, plus indexed input extraction,
            # engine reuse and the checkpoint manifest)
            from .core.polisher import PolisherType
            from .exec import ShardRunner

            eprint("[RaconWrapper::run] processing data with the "
                   "streaming shard runner")
            runner = ShardRunner(
                subsampled, self.overlaps, self.target_sequences,
                type_=PolisherType.F if self.fragment_correction
                else PolisherType.C,
                window_length=self.window_length,
                quality_threshold=self.quality_threshold,
                error_threshold=self.error_threshold,
                match=self.match, mismatch=self.mismatch, gap=self.gap,
                num_threads=self.threads,
                aligner_backend="tpu" if self.tpualigner_batches > 0
                else "auto",
                consensus_backend="tpu" if self.tpupoa_batches > 0
                else "auto",
                aligner_batches=max(1, self.tpualigner_batches),
                consensus_batches=max(1, self.tpupoa_batches),
                banded=self.tpu_banded_alignment,
                include_unpolished=self.include_unpolished,
                max_target_bytes=self.chunk_size,
                # derived (input-hashed) work dir OUTSIDE the wrapper's
                # throwaway time-stamped directory, plus resume=True: a
                # crashed --split run picks up from its checkpoint on
                # plain re-invocation, and a fresh run starts clean
                # because a stale manifest cannot match this input set
                work_dir=None, resume=True, keep_work_dir=False)
            runner.run(sys.stdout.buffer)
            return

        split_targets = []
        if self.chunk_size is not None:
            self._run_module("racon_tpu.rampler",
                             ["-o", self.work_directory, "split",
                              self.target_sequences, str(self.chunk_size)])
            base = os.path.basename(self.target_sequences).split(".")[0]
            i = 0
            while True:
                found = glob.glob(os.path.join(
                    self.work_directory, f"{base}_{i}.*"))
                if not found:
                    break
                split_targets.append(found[0])
                i += 1
            if not split_targets:
                eprint("[RaconWrapper::run] error: unable to find split "
                       "target sequences!")
                sys.exit(1)
        else:
            split_targets.append(self.target_sequences)

        params = []
        if self.include_unpolished:
            params.append("-u")
        if self.fragment_correction:
            params.append("-f")
        if self.tpupoa_batches:
            params.extend(["-c", str(self.tpupoa_batches)])
        if self.tpu_banded_alignment:
            params.append("-b")
        if self.tpualigner_batches:
            params.extend(["--tpualigner-batches",
                           str(self.tpualigner_batches)])
        params.extend(["-w", str(self.window_length),
                       "-q", str(self.quality_threshold),
                       "-e", str(self.error_threshold),
                       "-m", str(self.match),
                       "-x", str(self.mismatch),
                       "-g", str(self.gap),
                       "-t", str(self.threads),
                       subsampled, self.overlaps, ""])

        for part in split_targets:
            eprint("[RaconWrapper::run] processing data with racon")
            params[-1] = part
            self._run_module("racon_tpu.cli", params)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="racon_wrapper",
        description="Racon_wrapper encapsulates racon and adds two "
                    "features: sequences can be subsampled to decrease "
                    "total execution time, and target sequences can be "
                    "split into smaller chunks run sequentially to "
                    "decrease memory consumption. The usage equals racon.",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("sequences", help="FASTA/FASTQ (may be gzipped) "
                                          "sequences used for correction")
    parser.add_argument("overlaps", help="MHAP/PAF/SAM (may be gzipped) "
                                         "overlaps")
    parser.add_argument("target_sequences", help="FASTA/FASTQ (may be "
                                                 "gzipped) targets")
    parser.add_argument("--split", type=int,
                        help="split target sequences into chunks of desired "
                             "size in bytes (runs through the streaming "
                             "shard runner; see --legacy-split)")
    parser.add_argument("--legacy-split", action="store_true",
                        help="use the original rampler-split + sequential "
                             "per-chunk subprocess path instead of the "
                             "in-process streaming shard runner")
    parser.add_argument("--subsample", nargs=2, type=int,
                        metavar=("REFERENCE_LENGTH", "COVERAGE"),
                        help="subsample sequences to desired coverage given "
                             "the reference length")
    parser.add_argument("-u", "--include-unpolished", action="store_true",
                        help="output unpolished target sequences")
    parser.add_argument("-f", "--fragment-correction", action="store_true",
                        help="perform fragment correction instead of contig "
                             "polishing")
    parser.add_argument("-w", "--window-length", type=int, default=500)
    parser.add_argument("-q", "--quality-threshold", type=float, default=10.0)
    parser.add_argument("-e", "--error-threshold", type=float, default=0.3)
    # NOTE: the reference wrapper defaults to 5/-4/-8 even though racon
    # itself defaults to 3/-5/-4 (scripts/racon_wrapper.py:175-180 vs
    # src/main.cpp:49-64); the upstream discrepancy is preserved for parity.
    parser.add_argument("-m", "--match", type=int, default=5)
    parser.add_argument("-x", "--mismatch", type=int, default=-4)
    parser.add_argument("-g", "--gap", type=int, default=-8)
    parser.add_argument("-t", "--threads", type=int, default=1)
    parser.add_argument("-c", "--tpupoa-batches", type=int, default=0,
                        help="number of batches for TPU accelerated "
                             "polishing")
    parser.add_argument("-b", "--tpu-banded-alignment", action="store_true",
                        help="use banding approximation on the TPU")
    parser.add_argument("--tpualigner-batches", type=int, default=0,
                        help="number of batches for TPU accelerated "
                             "alignment")

    args = parser.parse_args(argv)

    racon = RaconWrapper(
        args.sequences, args.overlaps, args.target_sequences, args.split,
        args.subsample, args.include_unpolished, args.fragment_correction,
        args.window_length, args.quality_threshold, args.error_threshold,
        args.match, args.mismatch, args.gap, args.threads,
        args.tpupoa_batches, args.tpu_banded_alignment,
        args.tpualigner_batches, args.legacy_split)
    with racon:
        racon.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
