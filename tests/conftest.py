"""Test configuration: force JAX onto a virtual 8-device CPU mesh so all
sharding/parallel tests run without TPU hardware (the driver dry-runs the
real multi-chip path separately via __graft_entry__.dryrun_multichip)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/test/data")


@pytest.fixture(scope="session")
def data_dir():
    if not REFERENCE_DATA.exists():
        pytest.skip("reference test data not available")
    return REFERENCE_DATA
