"""Test configuration: force JAX onto a virtual 8-device CPU mesh so all
sharding/parallel tests run without TPU hardware (the driver dry-runs the
real multi-chip path separately via __graft_entry__.dryrun_multichip)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

if os.environ.get("RACON_TPU_TEST_REAL", "") != "1":
    # The environment may pre-register an accelerator plugin (and pin
    # jax_platforms) from sitecustomize, so an env var alone is not enough:
    # override the config before any backend initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/test/data")


@pytest.fixture(scope="session")
def data_dir():
    if not REFERENCE_DATA.exists():
        pytest.skip("reference test data not available")
    return REFERENCE_DATA
