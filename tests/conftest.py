"""Test configuration: force JAX onto a virtual 8-device CPU mesh so all
sharding/parallel tests run without TPU hardware (the driver dry-runs the
real multi-chip path separately via __graft_entry__.dryrun_multichip)."""

import os

# dependency-free (and jax-free), so it is safe to consult before the
# XLA backend configuration below
from racon_tpu import flags as racon_flags

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

if not racon_flags.get_bool("RACON_TPU_TEST_REAL"):
    # The environment may pre-register an accelerator plugin (and pin
    # jax_platforms) from sitecustomize, so an env var alone is not enough:
    # override the config before any backend initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/test/data")


@pytest.fixture(scope="session")
def data_dir():
    if not REFERENCE_DATA.exists():
        pytest.skip("reference test data not available")
    return REFERENCE_DATA
