"""Worker process for the 2-process CPU-mesh test (test_multihost.py).

Each process owns 4 virtual CPU devices; after ``distributed_init`` the
global mesh spans 8 devices across both processes and the consensus
engine's ``shard_map`` path runs SPMD over it — the DCN analog of the
reference's multi-GPU batch binning (``src/cuda/cudapolisher.cpp:72-83``).
Asserts the multi-host consensus bytes equal a single-device run.
"""
import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    from racon_tpu.parallel import distributed_init, get_mesh, is_multihost

    distributed_init(f"localhost:{port}", nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.device_count()
    assert jax.local_device_count() == 4
    assert is_multihost()

    from __graft_entry__ import _tiny_windows
    from racon_tpu.ops.poa import TpuPoaConsensus

    mesh = get_mesh()  # global: 8 devices over 2 processes
    windows = _tiny_windows(8)
    eng = TpuPoaConsensus(3, -5, -4, mesh=mesh, band=64, rounds=2)
    flags = eng.run(windows, trim=False)
    assert all(flags), flags
    assert eng.stats["device_windows"] == len(windows), eng.stats
    multi = [w.consensus for w in windows]

    ref_windows = _tiny_windows(8)
    ref = TpuPoaConsensus(3, -5, -4, mesh=None, band=64, rounds=2)
    ref.run(ref_windows, trim=False)
    single = [w.consensus for w in ref_windows]
    assert multi == single, "multi-host consensus differs from single-device"

    # sharded aligner across both processes, vs the single-device CIGARs
    import numpy as np
    from racon_tpu.ops.nw import TpuAligner

    rng = np.random.default_rng(9)
    bases = b"ACGT"
    pairs = []
    for _ in range(16):
        t = bytes(bases[i] for i in rng.integers(0, 4, 120))
        q = bytearray(t)
        for p in rng.integers(1, 119, 8):
            q[p] = bases[int(rng.integers(0, 4))]
        pairs.append((bytes(q), t))
    multi_cig = TpuAligner(mesh=mesh, buckets=((256, 128),)).align_batch(
        pairs)
    single_cig = TpuAligner(mesh=None, buckets=((256, 128),)).align_batch(
        pairs)
    assert multi_cig == single_cig, "multi-host CIGARs differ"
    print(f"multihost worker {pid}: OK", flush=True)


if __name__ == "__main__":
    main()
