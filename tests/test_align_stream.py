"""Round-17 alignment occupancy: ragged pair packing (`_AlignStream`),
the adaptive band ladder, and the packed walk kernels — byte-identical
breaking points / CIGARs across the {bucketed, ragged} x {fixed-band,
ladder} grid.

The accept gate (``score <= band/2 - diff - 2``) is an optimality
certificate at every rung: any cell whose value can influence a
traceback decision is provably uninflated by the banding, so an
alignment accepted at a narrow rung IS the wide-band alignment, and the
ladder's terminal geometry sequence is the fixed path's — hence
identical accept/reject sets. This suite locks that contract on
randomized mixed-length/divergence pairs (escalation re-batching
included), the stream-feed-batching invariance the polisher relies on,
F-mode short reads, the empty-pair edges, OOM ``reduce_capacity``
re-dispatch parity, the align-stream warm-up cache claim, and the
``align.dispatch`` fault site's stall escalation through the exec
runner's degradation ladder. Wired as a fail-fast ci/cpu/test.sh shard
and re-run under RACON_TPU_SANITIZE=1 (the int32 shadow leg runs the
unpacked walk, covering the SWAR-packed walk kernel).
"""

import io
import pathlib

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.core.backends import NativeAligner, PythonAligner
from racon_tpu.obs import metrics
from racon_tpu.ops.nw import BAND_RUNGS, TpuAligner

BASES = np.frombuffer(b"ACGT", np.uint8)


def _fallback():
    return NativeAligner(2) if native.available() else PythonAligner()


def _engine(ragged=True, ladder=True, **kw):
    return TpuAligner(fallback=_fallback(), use_ragged=ragged,
                      use_ladder=ladder, **kw)


def _mixed_pairs(rng, n=48, lo=60, hi=1200, hot_every=9):
    """Randomized mixed workload spanning the (256, 128) and (1024, 384)
    buckets and several ladder rungs: low- and high-divergence pairs
    (the 50%-flip slice exceeds even the conservative TYPICAL-seeded rung,
    deterministically exercising the escalation re-batch path), indels
    for span asymmetry, one empty pair, plus overlap-filter-style error
    estimates."""
    pairs, errors = [], []
    for k in range(n):
        ln = int(rng.integers(lo, hi))
        t = BASES[rng.integers(0, 4, ln)]
        q = np.delete(t.copy(), rng.integers(0, ln, max(2, ln // 60)))
        div = 0.5 if k % hot_every == 0 else 0.03
        flips = rng.random(len(q)) < div
        q[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
        pairs.append((q.tobytes(), t.tobytes()))
        errors.append(1.0 - min(len(q), len(t)) / max(len(q), len(t)))
    pairs.append((b"", t.tobytes()))
    errors.append(0.0)
    pairs.append((b"ACGT", b""))
    errors.append(0.0)
    metas = [(k * 13 % 300, k * 7 % 200) for k in range(len(pairs))]
    return pairs, metas, errors


def _bp_equal(a, b):
    return len(a) == len(b) and all(
        np.array_equal(x, y) for x, y in zip(a, b))


@pytest.mark.parametrize("seed", range(2))
def test_grid_parity_randomized(seed):
    """{bucketed, ragged} x {fixed-band, ladder}: byte-identical CIGARs
    and breaking points; the ladder leg must actually seed narrow rungs
    and re-batch escapees, and its banded wavefront work must drop."""
    rng = np.random.default_rng(400 + seed)
    pairs, metas, errors = _mixed_pairs(rng)
    ref_cig = ref_bps = None
    work = {}
    for ragged in (False, True):
        for ladder in (False, True):
            eng = _engine(ragged, ladder)
            cig = eng.align_batch(pairs, errors=errors)
            bps = eng.breaking_points_batch(pairs, metas, 100,
                                            errors=errors)
            work[(ragged, ladder)] = eng.stats["wavefront_work"]
            if ref_cig is None:
                ref_cig, ref_bps = cig, bps
            else:
                assert cig == ref_cig, (ragged, ladder)
                assert _bp_equal(bps, ref_bps), (ragged, ladder)
            if ladder:
                assert eng.stats["ladder_narrow"] > 0
                assert eng.stats["band_escalated"] > 0  # 50%-flip slice
            assert eng.stats["lanes_occupied"] <= eng.stats["lanes_total"]
    assert any(len(b) for b in ref_bps)
    # the acceptance direction: ladder work strictly below fixed-band
    assert work[(True, True)] < work[(False, False)]


def test_stream_feed_batches_match_single_feed():
    """Polisher._align_need feeds the session in 64k slices; the slice
    boundaries must not change a single byte vs one monolithic feed
    (and vs the bucketed driver)."""
    rng = np.random.default_rng(77)
    pairs, metas, errors = _mixed_pairs(rng, n=30)
    ref = _engine(False, False).breaking_points_batch(
        pairs, metas, 100, errors=errors)

    eng = _engine()
    sess = eng.bp_stream(100, total=len(pairs))
    assert sess is not None
    for a in range(0, len(pairs), 7):
        sess.feed(pairs[a:a + 7], metas[a:a + 7], errors[a:a + 7])
    got = sess.finish()
    assert _bp_equal(got, ref)
    # every span copy and meta tuple released by the end of the session
    # (resolved slots release per chunk, rejects at finish)
    assert not sess.pairs and not sess.metas


def test_stream_empty_edges():
    """Empty feeds, empty pairs and a zero-pair finish must not wedge
    the drain loop."""
    eng = _engine()
    sess = eng.bp_stream(100)
    sess.feed([], [], [])
    assert sess.finish() == []

    sess2 = eng.bp_stream(100)
    sess2.feed([(b"", b"ACGT"), (b"AC", b"")], [(0, 0), (0, 0)],
               [0.0, 0.0])
    out = sess2.finish()
    assert len(out) == 2 and all(len(o) == 0 for o in out)

    # CIGAR-mode empties keep the wave driver's deletion/insertion codes
    cig = _engine().align_batch([(b"", b"ACGT"), (b"AC", b""), (b"", b"")])
    assert cig == ["4D", "2I", ""]


def test_f_mode_short_reads_parity():
    """F-mode shapes: very short pairs, all in the smallest bucket and
    the narrowest rungs — the regime that packs the most pairs per
    chunk."""
    rng = np.random.default_rng(31)
    pairs, metas, errors = _mixed_pairs(rng, n=40, lo=30, hi=90)
    ref = _engine(False, False).breaking_points_batch(
        pairs, metas, 50, errors=errors)
    eng = _engine()
    got = eng.breaking_points_batch(pairs, metas, 50, errors=errors)
    assert _bp_equal(got, ref)
    assert eng.stats["chunks"] >= 1


def test_reduce_capacity_redispatch_parity():
    """The exec ladder's OOM-backpressure rung on the align arena: a
    capacity-halved engine re-dispatches smaller chunks with
    byte-identical breaking points (grouping never changes bytes)."""
    rng = np.random.default_rng(55)
    pairs, metas, errors = _mixed_pairs(rng, n=36)
    ref_eng = _engine()
    ref = ref_eng.breaking_points_batch(pairs, metas, 100, errors=errors)

    eng = _engine()
    for _ in range(4):
        assert eng.reduce_capacity()
    assert eng.capacity_scale == 16
    assert not eng.reduce_capacity()  # floor reached -> ladder falls on
    got = eng.breaking_points_batch(pairs, metas, 100, errors=errors)
    assert _bp_equal(got, ref)


def test_occupancy_telemetry_registry():
    """The round-17 counters land in BOTH the engine stats and the ONE
    metrics registry, and the derived pack summary is coherent (the
    run-report schema v6 / heartbeat pack[...] source)."""
    metrics.clear_run()
    rng = np.random.default_rng(13)
    pairs, metas, errors = _mixed_pairs(rng, n=24)
    eng = _engine()
    eng.breaking_points_batch(pairs, metas, 100, errors=errors)
    st = eng.stats
    assert 0 < st["lanes_occupied"] <= st["lanes_total"]
    assert st["steps_wasted"] == st["lanes_total"] - st["lanes_occupied"]
    assert st["wavefront_work"] > 0
    pm = eng.pack_metrics()
    assert 0 < pm["align_pack_efficiency"] <= 1
    assert abs(pm["align_pack_efficiency"] + pm["align_pad_fraction"]
               - 1) < 1e-6
    assert metrics.counter("align.chunks") == st["chunks"]
    assert metrics.counter("align.lanes_total") == st["lanes_total"]
    pack = metrics.pack_summary()
    for key in ("align_pack_efficiency", "align_pad_fraction",
                "align_chunks", "align_steps_wasted"):
        assert key in pack
    assert pack["align_chunks"] == st["chunks"]
    from racon_tpu.exec.heartbeat import pack_summary_str
    assert f"{st['chunks']}c" in pack_summary_str()


def test_adaptive_ladder_learns_divergence():
    """A substitution-heavy run whose span-asymmetry estimates read
    near zero initially seeds low and escapes; once ADAPT_MIN_PAIRS
    accepted pairs are observed, seeds incorporate the realized
    divergence and later chunks stop escaping."""
    from racon_tpu.ops import nw as nw_mod

    eng = _engine()
    # feed the observer directly (unit-level: the estimator, not a
    # full 256-pair device run)
    eng._observe_divergence([20] * nw_mod.ADAPT_MIN_PAIRS,
                            [100] * nw_mod.ADAPT_MIN_PAIRS)
    ad = eng._adaptive_divergence()
    assert ad is not None and abs(ad - 0.2) < 1e-6
    # a misleading near-zero span estimate is floored by observation
    assert eng._est_divergence(0.0) >= 0.2
    # seeds quantize to a declared rung (or the bucket band)
    g = eng._seed_geometry(500, 500, 0.0)
    assert g is not None
    band = g[1]
    assert band in BAND_RUNGS or band == eng.buckets[g[0]][1]


def test_warmup_precompiles_align_stream_shapes():
    """The align warm-up derives the stream's chunk geometry: after
    warm-up, a matching live dispatch adds ZERO new compiles on the
    forward, traceback and breaking-points kernels (the round-13
    consensus warm-up test's claim, on the aligner)."""
    from racon_tpu import sanitize
    from racon_tpu.ops import nw as nw_mod

    if sanitize.enabled():
        pytest.skip("the sanitizer's int32 shadow leg compiles the "
                    "unpacked twin of every first chunk by design — "
                    "the cache-count claim holds for the production "
                    "path only")
    eng = _engine()
    th = eng.warmup_async(200, 8, window_length=100)
    assert th is not None
    th.join(timeout=300)
    assert not th.is_alive()
    # repeat calls with the same geometry are free (shape dedupe)
    assert eng.warmup_async(200, 8, window_length=100) is None
    cached = (nw_mod._nw_wavefront_kernel._cache_size(),
              nw_mod._traceback_kernel._cache_size(),
              nw_mod._breaking_points_kernel._cache_size())
    assert cached[0] >= 1

    # live pairs matching the warmed geometry: equal lengths (need ==
    # 16 like the estimate), the estimate's 0.05 error class, 8 pairs
    rng = np.random.default_rng(5)
    pairs = []
    for _ in range(8):
        t = BASES[rng.integers(0, 4, 200)]
        q = t.copy()
        flips = rng.random(200) < 0.02
        q[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
        pairs.append((q.tobytes(), t.tobytes()))
    bps = eng.breaking_points_batch(pairs, [(0, 0)] * 8, 100,
                                    errors=[0.05] * 8)
    assert sum(len(b) > 0 for b in bps) == 8
    assert (nw_mod._nw_wavefront_kernel._cache_size(),
            nw_mod._traceback_kernel._cache_size(),
            nw_mod._breaking_points_kernel._cache_size()) == cached, \
        "live dispatch missed the warmed shapes (recompiled)"


def test_polisher_stream_feed_byte_identity(tmp_path):
    """End-to-end through create_polisher with an injected off-mesh
    device aligner: the polisher's sliced session feed must produce the
    same polished FASTA as the bucketed fixed-band driver, and the
    dispatch-vs-fetch split must land in the init breakdown."""
    from test_columnar_init import write_synthetic_assembly

    from racon_tpu.core.polisher import create_polisher
    from racon_tpu.obs import trace as obs_trace

    rp, pp, lp = write_synthetic_assembly(pathlib.Path(tmp_path), seed=7,
                                          n_contigs=2, contig=2000)
    obs_trace.activate(tracing=False)  # arm span timers

    def run(**al_kw):
        p = create_polisher(str(rp), str(pp), str(lp), num_threads=4,
                            aligner=_engine(**al_kw))
        out = b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                       for s in p.run(True))
        return out, dict(p.timings)

    ref, timings = run()
    assert "align_dispatch_s" in timings and "align_fetch_s" in timings
    assert timings["align_dispatch_s"] > 0 or timings["align_fetch_s"] > 0
    got, _ = run(ragged=False, ladder=False)
    assert got == ref


def test_align_dispatch_stall_escalates_runner_ladder(tmp_path,
                                                     monkeypatch):
    """The new align.dispatch fault site: an injected stall during the
    align phase surfaces as a StallError, classifies 'stall' and walks
    the shard down the exec runner's degradation ladder (CPU retry)
    with the merged output still correct."""
    from test_columnar_init import write_synthetic_assembly

    from racon_tpu import faults
    from racon_tpu.core.polisher import create_polisher
    from racon_tpu.exec import ShardRunner

    rp, pp, lp = write_synthetic_assembly(pathlib.Path(tmp_path), seed=9,
                                          n_contigs=2, contig=2000)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=4)
    want = b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                    for s in p.run(True))

    monkeypatch.setenv("RACON_TPU_FAULTS", "align.dispatch:stall")
    faults.reset()
    try:
        runner = ShardRunner(str(rp), str(pp), str(lp),
                             work_dir=str(tmp_path / "work"),
                             num_threads=4, n_shards=2,
                             aligner_backend="tpu")
        buf = io.BytesIO()
        summary = runner.run(buf)
    finally:
        monkeypatch.delenv("RACON_TPU_FAULTS", raising=False)
        faults.reset()
    assert buf.getvalue() == want
    atts = [a for e in summary["shards"]
            for a in (e.get("attempts") or [])]
    assert any(a["class"] == "stall" for a in atts), atts
