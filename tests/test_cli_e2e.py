"""Byte-exact end-to-end CLI golden + observability contract.

The analog of the reference's golden-output CI run
(``ci/gpu/cuda_test.sh:29-42``, which byte-diffs polished stdout against a
recorded ``golden-output.txt``): run the ``racon`` CLI on the λ-phage set
and byte-compare stdout against ``tests/data/golden_lambda_fastq_paf.fasta``
(recorded with the CPU path at ``-t 8``; catches tag/format/stitch
regressions that scalar edit-distance goldens miss).

Also asserts the observability contract: 20-bin progress bars during
overlap alignment and consensus, and the total wall-time line
(``src/polisher.cpp:475-481,534-543``, ``src/cuda/cudapolisher.cpp:21-24``).
"""

import pathlib
import subprocess
import sys

import pytest

GOLDEN = pathlib.Path(__file__).parent / "data" / "golden_lambda_fastq_paf.fasta"


@pytest.fixture(scope="module")
def cli_run(data_dir):
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-t", "8",
         str(data_dir / "sample_reads.fastq.gz"),
         str(data_dir / "sample_overlaps.paf.gz"),
         str(data_dir / "sample_layout.fasta.gz")],
        capture_output=True, timeout=600,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc


def test_cli_stdout_byte_exact(cli_run):
    assert cli_run.stdout == GOLDEN.read_bytes()


def test_cli_progress_bars(cli_run):
    err = cli_run.stderr.decode()
    assert ("[racon_tpu::Polisher::initialize] aligning overlaps "
            "[====================>] 100%") in err
    assert ("[racon_tpu::Polisher::polish] generating consensus "
            "[====================>] 100%") in err
    # intermediate bins are emitted too (20-bin contract, not one jump)
    assert "] 50%" in err


def test_cli_total_line(cli_run):
    assert "[racon_tpu::Polisher::] total =" in cli_run.stderr.decode()


def test_cli_tpualigner_byte_exact(data_dir):
    """Real-data golden through the device aligner path: the PAF input
    carries no CIGARs, so ``--tpualigner-batches`` routes every breaking-
    point alignment through the batched device aligner (XLA kernels on the
    CPU test mesh; the Pallas kernels are bit-identical by probe) — stdout
    must match the recorded CPU-path golden byte for byte."""
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-t", "8",
         "--tpualigner-batches", "1",
         str(data_dir / "sample_reads.fastq.gz"),
         str(data_dir / "sample_overlaps.paf.gz"),
         str(data_dir / "sample_layout.fasta.gz")],
        capture_output=True, timeout=600,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert proc.stdout == GOLDEN.read_bytes()
