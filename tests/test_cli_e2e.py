"""Byte-exact end-to-end CLI golden + observability contract.

The analog of the reference's golden-output CI run
(``ci/gpu/cuda_test.sh:29-42``, which byte-diffs polished stdout against a
recorded ``golden-output.txt``): run the ``racon`` CLI on the λ-phage set
and byte-compare stdout against ``tests/data/golden_lambda_fastq_paf.fasta``
(recorded with the CPU path at ``-t 8``; catches tag/format/stitch
regressions that scalar edit-distance goldens miss).

Also asserts the observability contract: 20-bin progress bars during
overlap alignment and consensus, and the total wall-time line
(``src/polisher.cpp:475-481,534-543``, ``src/cuda/cudapolisher.cpp:21-24``).
"""

import pathlib
import subprocess
import sys

import pytest

GOLDEN = pathlib.Path(__file__).parent / "data" / "golden_lambda_fastq_paf.fasta"


def run_cli(data_dir, *extra_args):
    """Canonical λ-phage CLI invocation (+ optional extra flags) — the
    single definition every e2e test shares."""
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-t", "8", *extra_args,
         str(data_dir / "sample_reads.fastq.gz"),
         str(data_dir / "sample_overlaps.paf.gz"),
         str(data_dir / "sample_layout.fasta.gz")],
        capture_output=True, timeout=600,
        cwd=str(pathlib.Path(__file__).parent.parent))
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc


@pytest.fixture(scope="module")
def cli_run(data_dir):
    return run_cli(data_dir)


def test_cli_stdout_byte_exact(cli_run):
    assert cli_run.stdout == GOLDEN.read_bytes()


def test_cli_progress_bars(cli_run):
    err = cli_run.stderr.decode()
    assert ("[racon_tpu::Polisher::initialize] aligning overlaps "
            "[====================>] 100%") in err
    assert ("[racon_tpu::Polisher::polish] generating consensus "
            "[====================>] 100%") in err
    # intermediate bins are emitted too (20-bin contract, not one jump)
    assert "] 50%" in err


def test_cli_total_line(cli_run):
    assert "[racon_tpu::Polisher::] total =" in cli_run.stderr.decode()


def test_cli_tpualigner_byte_exact(data_dir):
    """Real-data golden through the device aligner path: the PAF input
    carries no CIGARs, so ``--tpualigner-batches`` routes every breaking-
    point alignment through the batched device aligner (XLA kernels on the
    CPU test mesh; the Pallas kernels are bit-identical by probe) — stdout
    must match the recorded CPU-path golden byte for byte."""
    proc = run_cli(data_dir, "--tpualigner-batches", "1")
    assert proc.stdout == GOLDEN.read_bytes()


def test_cli_profile_flag(data_dir, tmp_path):
    """--profile wraps the run in a jax.profiler trace (the nvprof-hooks
    analog): the run must still produce the golden bytes and leave a
    trace directory behind."""
    prof_dir = tmp_path / "trace"
    proc = run_cli(data_dir, "--profile", str(prof_dir))
    assert proc.stdout == GOLDEN.read_bytes()
    assert prof_dir.exists() and any(prof_dir.iterdir())
