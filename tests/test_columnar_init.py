"""Columnar host-init parity and the pipelined run() surface.

The vectorized window/layer build (``Polisher._assemble_layers``: one
concatenated breaking-point matrix, vectorized span/PHRED filters,
argsort-by-window grouping) must produce windows IDENTICAL to the legacy
per-overlap/per-pair loop (kept as ``_build_windows_legacy``) — same
layer bytes, same qualities, same positions, same per-window layer order —
across strands, dummy-quality (FASTA) reads and fragment-correction-style
multi-overlap-per-query inputs. The fused ``run()`` must emit the same
polished sequences as initialize() + polish(), pipelined or via the
``num_threads=1`` sequential fallback.
"""

import random

import numpy as np
import pytest

from racon_tpu.core.overlap import (Overlap, bp_pairs_to_array,
                                    breaking_points_from_cigar)
from racon_tpu.core.polisher import Polisher, PolisherType
from racon_tpu.core.sequence import Sequence
from racon_tpu.core.window import WindowType
from racon_tpu.utils.cigar import parse_cigar


def make_polisher(window_length=100, quality_threshold=10.0,
                  type_=PolisherType.C, num_threads=1):
    # paths are never touched: sequences/overlaps are injected directly
    return Polisher("x.fasta", "x.paf", "x.fasta", type_, window_length,
                    quality_threshold, 0.3, True, 3, -5, -4, num_threads)


def random_cigar(rng, approx_len):
    ops = []
    total_t = 0
    while total_t < approx_len:
        op = rng.choices(["M", "I", "D"], weights=[8, 1, 1])[0]
        n = rng.randint(1, 25)
        ops.append(f"{n}{op}")
        if op in ("M", "D"):
            total_t += n
    return "".join(ops), total_t


def random_state(seed, window_length, with_quality=True, multi=False):
    """Targets + reads + overlaps whose breaking points come from real
    CIGAR walks (so every row satisfies the walker's invariants).
    ``multi`` makes several overlaps share a query read (the
    fragment-correction/ava shape)."""
    rng = random.Random(seed)
    nrng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)

    targets = [Sequence(b"t%d" % i,
                        bases[nrng.integers(0, 4, rng.randint(
                            window_length * 2, window_length * 7))]
                        .tobytes())
               for i in range(3)]
    sequences = list(targets)
    overlaps = []
    n_reads = 12 if multi else 30
    for ri in range(n_reads):
        per_read = rng.randint(2, 3) if multi else 1
        read_len = rng.randint(window_length * 2, window_length * 5)
        data = bases[nrng.integers(0, 4, read_len)].tobytes()
        qual = (bytes(nrng.integers(33, 64, read_len).astype(np.uint8))
                if with_quality and ri % 3 else None)
        sequences.append(Sequence(b"r%d" % ri, data, qual))
        q_id = len(sequences) - 1
        for _ in range(per_read):
            t_id = rng.randrange(len(targets))
            t_len = len(targets[t_id].data)
            for _retry in range(20):
                cigar, t_span = random_cigar(rng, rng.randint(
                    window_length // 2,
                    min(t_len - 1, read_len - 30, window_length * 4)))
                q_span = sum(n for n, op in parse_cigar(cigar)
                             if op in ("M", "I"))
                if t_span < t_len and q_span <= read_len - 10:
                    break
            else:
                continue
            t_begin = rng.randint(0, t_len - t_span - 1)
            q_begin = rng.randint(0, read_len - q_span)
            o = Overlap()
            o.q_id = q_id
            o.t_id = t_id
            o.strand = rng.random() < 0.5
            o.q_begin, o.q_end = q_begin, q_begin + q_span
            o.q_length = read_len
            o.t_begin, o.t_end = t_begin, t_begin + t_span
            o.is_transmuted = True
            q_off = o.q_length - o.q_end if o.strand else o.q_begin
            o.breaking_points = bp_pairs_to_array(
                breaking_points_from_cigar(cigar, q_off, o.t_begin,
                                           o.t_end, window_length))
            overlaps.append(o)
    return sequences, len(targets), overlaps


def clone_overlaps(overlaps):
    out = []
    for o in overlaps:
        c = Overlap()
        c.q_id, c.t_id, c.strand = o.q_id, o.t_id, o.strand
        c.q_begin, c.q_end, c.q_length = o.q_begin, o.q_end, o.q_length
        c.t_begin, c.t_end = o.t_begin, o.t_end
        c.is_transmuted = True
        c.breaking_points = o.breaking_points.copy()
        out.append(c)
    return out


def build_with(p, sequences, n_targets, overlaps, legacy, **assemble_kw):
    p.sequences = list(sequences)
    p.targets_size = n_targets
    p._window_type = WindowType.TGS
    if legacy:
        p._build_backbone_windows()
        p._build_windows_legacy(overlaps)
    else:
        p._assemble_layers(overlaps, **assemble_kw)
    return p


def assert_windows_identical(pa, pb):
    assert len(pa.windows) == len(pb.windows)
    assert pa.targets_coverages == pb.targets_coverages
    for wa, wb in zip(pa.windows, pb.windows):
        assert (wa.id, wa.rank, wa.type) == (wb.id, wb.rank, wb.type)
        assert wa.sequences == wb.sequences
        assert wa.qualities == wb.qualities
        assert wa.positions == wb.positions


@pytest.mark.parametrize("seed", range(6))
def test_columnar_matches_legacy(seed):
    wl = [50, 100, 500][seed % 3]
    qthr = [10.0, 12.5][seed % 2]
    sequences, nt, overlaps = random_state(seed, wl)
    pa = build_with(make_polisher(wl, qthr), sequences, nt,
                    clone_overlaps(overlaps), legacy=False)
    pb = build_with(make_polisher(wl, qthr), sequences, nt,
                    clone_overlaps(overlaps), legacy=True)
    n_layers = sum(len(w.sequences) - 1 for w in pa.windows)
    n_rows = sum(len(o.breaking_points) for o in overlaps)
    assert 0 < n_layers <= n_rows
    assert_windows_identical(pa, pb)


def test_columnar_filters_fire_identically():
    """Both filters must actually drop rows (min-span and mean-PHRED),
    and drop the SAME rows in both paths."""
    sequences, nt, overlaps = random_state(11, 500, with_quality=True)
    pa = build_with(make_polisher(500, 43.0), sequences, nt,
                    clone_overlaps(overlaps), legacy=False)
    pb = build_with(make_polisher(500, 43.0), sequences, nt,
                    clone_overlaps(overlaps), legacy=True)
    n_layers = sum(len(w.sequences) - 1 for w in pa.windows)
    n_rows = sum(len(o.breaking_points) for o in overlaps)
    # qualities are uniform in [33, 64) (avg ~ 15): a 43.0 threshold
    # (avg >= 43 means raw mean >= 76) rejects every quality-bearing
    # read's rows, while the dummy-quality reads (ri % 3 == 0) pass
    assert 0 < n_layers < n_rows
    assert_windows_identical(pa, pb)


def test_columnar_matches_legacy_fragment_multi_overlap():
    """Fragment-correction shape: several overlaps per query read (mixed
    strands), like the PolisherType.F / ava-overlap inputs."""
    sequences, nt, overlaps = random_state(99, 100, multi=True)
    assert len({o.q_id for o in overlaps}) < len(overlaps)  # shared reads
    pa = build_with(make_polisher(100, type_=PolisherType.F), sequences,
                    nt, clone_overlaps(overlaps), legacy=False)
    pb = build_with(make_polisher(100, type_=PolisherType.F), sequences,
                    nt, clone_overlaps(overlaps), legacy=True)
    assert_windows_identical(pa, pb)


def test_columnar_matches_legacy_dummy_quality():
    """FASTA reads (quality None): the PHRED filter must not fire and the
    layers must carry None qualities, both paths."""
    sequences, nt, overlaps = random_state(7, 100, with_quality=False)
    assert all(s.quality is None for s in sequences)
    pa = build_with(make_polisher(100), sequences, nt,
                    clone_overlaps(overlaps), legacy=False)
    pb = build_with(make_polisher(100), sequences, nt,
                    clone_overlaps(overlaps), legacy=True)
    n_layers = sum(len(w.sequences) - 1 for w in pa.windows)
    assert n_layers > 0
    assert all(q is None for w in pa.windows for q in w.qualities[1:])
    assert_windows_identical(pa, pb)


def test_columnar_chunked_emit_matches_monolithic():
    """The run() producer's chunked emission (small chunk_windows, emit
    callback) must build the same windows as one monolithic pass, and the
    emitted ranges must tile [0, n_windows) in order."""
    sequences, nt, overlaps = random_state(3, 50)
    pa = build_with(make_polisher(50), sequences, nt,
                    clone_overlaps(overlaps), legacy=False)
    emitted = []
    pb = build_with(make_polisher(50), sequences, nt,
                    clone_overlaps(overlaps), legacy=False,
                    emit=lambda a, b: emitted.append((a, b)),
                    chunk_windows=3)
    assert_windows_identical(pa, pb)
    assert emitted[0][0] == 0 and emitted[-1][1] == len(pb.windows)
    assert all(e0[1] == e1[0] for e0, e1 in zip(emitted, emitted[1:]))
    assert len(emitted) > 1


def test_columnar_releases_breaking_points():
    sequences, nt, overlaps = random_state(5, 100)
    overlaps = clone_overlaps(overlaps)
    build_with(make_polisher(100), sequences, nt, overlaps, legacy=False)
    assert all(o.breaking_points is None for o in overlaps)


# ---------------------------------------------------------------- run()

def write_synthetic_assembly(tmp_path, seed=23, n_contigs=2, contig=3000):
    """Two-contig ~5x forward+reverse synthetic assembly on disk (the
    test_pipeline multi-target shape, plus reverse-strand reads)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    comp = bytes.maketrans(b"ACGT", b"TGCA")

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, contig)] for _ in range(n_contigs)]
    backbones = [mutate(t, 0.06) for t in truths]
    layout = tmp_path / "layout.fasta"
    with open(layout, "wb") as f:
        for ti, bb in enumerate(backbones):
            f.write(b">ctg%d\n" % ti + bb.tobytes() + b"\n")
    reads_path = tmp_path / "reads.fastq"
    paf_path = tmp_path / "ovl.paf"
    with open(reads_path, "wb") as rf, open(paf_path, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            for start in range(0, contig - 600, 150):
                end = min(start + 900, contig)
                read = mutate(truth[start:end], 0.08)
                name = b"read%d" % ri
                strand = b"-" if ri % 3 == 0 else b"+"
                if strand == b"-":
                    read_bytes = read.tobytes().translate(comp)[::-1]
                else:
                    read_bytes = read.tobytes()
                rf.write(b"@" + name + b"\n" + read_bytes +
                         b"\n+\n" + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    strand, b"ctg%d" % ti, b"%d" % contig, b"%d" % start,
                    b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1
    return reads_path, paf_path, layout


def polished_bytes(seqs):
    return [(s.name, s.data) for s in seqs]


def test_run_matches_initialize_polish(tmp_path):
    """Fused pipelined run() output == initialize() + polish() output
    (same bytes, names and order), with the pipelined path actually
    chunking (num_threads > 1)."""
    from racon_tpu.core.polisher import create_polisher

    rp, pp, lp = write_synthetic_assembly(tmp_path)
    ref = create_polisher(str(rp), str(pp), str(lp), num_threads=4)
    ref.initialize()
    want = polished_bytes(ref.polish(True))

    fused = create_polisher(str(rp), str(pp), str(lp), num_threads=4)
    got = polished_bytes(fused.run(True))
    assert got == want
    assert "build_windows_s" in fused.timings
    assert "align_s" in fused.timings
    assert "bp_decode_s" in fused.timings


def test_run_sequential_fallback_num_threads_1(tmp_path):
    """num_threads=1 takes the sequential initialize()/polish() path and
    must produce the same bytes as the pipelined run."""
    from racon_tpu.core.polisher import create_polisher

    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=31)
    seq = create_polisher(str(rp), str(pp), str(lp), num_threads=1)
    got1 = polished_bytes(seq.run(True))

    par = create_polisher(str(rp), str(pp), str(lp), num_threads=4)
    got4 = polished_bytes(par.run(True))
    assert got1 == got4
    assert len(got1) == 2


def test_failed_initialize_leaves_object_reinitializable(tmp_path):
    """An alignment fault mid-init must leave self.windows empty so the
    double-init guard stays accurate and a retry rebuilds everything."""
    from racon_tpu.core.polisher import create_polisher

    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=13, n_contigs=1,
                                          contig=1500)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2)
    real_align = p.aligner.align_batch
    calls = {"n": 0}

    def flaky(pairs, *a, **kw):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("injected aligner fault")
        return real_align(pairs, *a, **kw)

    p.aligner.align_batch = flaky
    with pytest.raises(RuntimeError, match="injected"):
        p.initialize()
    assert p.windows == []  # clean: retry is a real re-init, not a no-op
    p.initialize()
    assert len(p.windows) > 0
    assert len(p.polish(True)) == 1


def test_run_consensus_fault_retires_producer(tmp_path):
    """A consensus fault mid-stream must drain the bounded queue and join
    the producer before propagating (no stranded daemon thread)."""
    import threading

    from racon_tpu.core.polisher import create_polisher

    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=17)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=4)
    p.consensus.run = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("injected consensus fault"))
    before = {t.name for t in threading.enumerate()}
    with pytest.raises(RuntimeError, match="injected"):
        p.run(True)
    leaked = [t for t in threading.enumerate()
              if t.name == "racon-layers" and t.is_alive()]
    assert not leaked, (before, leaked)


def test_double_initialize_warns_on_stderr(tmp_path, capsys):
    """The double-init warning must go to stderr: stdout carries the
    polished FASTA byte stream."""
    from racon_tpu.core.polisher import create_polisher

    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=5, n_contigs=1,
                                          contig=1500)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2)
    p.initialize()
    p.initialize()  # second call: warning, no rebuild
    cap = capsys.readouterr()
    assert "already initialized" in cap.err
    assert cap.out == ""
