"""Compile-surface runtime attribution (round 18).

The static half lives in ``tools/analysis`` (jit-shape-hazard /
dtype-drift / jit-in-loop / warmup-coverage / host-transfer-in-jit,
self-tested via ``--selftest``); this file proves the RUNTIME half:
the process-wide ``jax.monitoring`` listener attributes every XLA
compile to (function, shape signature, phase, scope), the per-job
``compile_s`` semantics of the absorbed serve listener are preserved,
the run report's required schema-v7 ``compiles`` section validates,
and the sanitize gate judges only the offending scope.  (The full
sanitized-serve warm-path acceptance test rides at the end of
``tests/test_serve.py`` — see the note at the bottom of this file.)"""

import pytest

from racon_tpu import sanitize
from racon_tpu.obs import compilewatch, metrics, report, trace


@pytest.fixture(autouse=True)
def _fresh_watch():
    compilewatch.reset()
    metrics.clear("compile.")
    yield
    compilewatch.reset()
    metrics.clear("compile.")


def _fake_compile(max_len, band, duration=0.5):
    """Drive the listener directly: attribution walks the stack and —
    with no racon_tpu frame above — lands on THIS frame, whose integer
    locals (max_len/band) form the shape signature."""
    compilewatch._on_duration(
        "/jax/core/compile/backend_compile_duration", duration)


# ------------------------------------------------------------ attribution

def test_attribution_names_function_and_shape_on_forced_retrace(
        tmp_path):
    """A real forced retrace through a repo driver: the attributed
    event names the driving function and its dispatch geometry."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from racon_tpu import ops
    from racon_tpu.ops import nw

    # a fresh persistent-cache dir so this geometry genuinely compiles
    # — re-pointed BACK afterwards: the cache dir is process-wide, and
    # leaving it on a tmp_path would make every later test in the
    # session compile cold
    ops.configure_compile_cache(str(tmp_path / "xla_cache"))
    try:
        assert compilewatch.arm()

        # an oddball geometry nothing else in the suite dispatches (XLA
        # path: no Pallas/SWAR multiples required)
        max_len, band, steps, B = 320, 40, 512, 2
        width = band // 2 + max_len + band
        qrp = jnp.zeros((B, width), jnp.uint8)
        tp = jnp.zeros((B, width), jnp.uint8)
        n = jnp.ones((B,), jnp.int32)
        m = jnp.ones((B,), jnp.int32)
        out = nw.align_chain(qrp, tp, n, m, max_len=max_len, band=band,
                             steps=steps, use_pallas=False,
                             use_swar=False)
        jax.block_until_ready(out[1])
    finally:
        ops.configure_compile_cache()

    evs = [e for e in compilewatch.events() if "align_chain" in e["fn"]]
    assert evs, (f"no compile attributed to align_chain: "
                 f"{compilewatch.events()}")
    assert any("max_len=320" in e["signature"]
               and "band=40" in e["signature"] for e in evs), evs
    assert metrics.counter("compile.nw.align_chain") >= 1
    assert metrics.timer_s("compile.jax_s") > 0


def test_phase_attribution_reads_innermost_open_span():
    trace.activate()
    try:
        from racon_tpu import obs
        with obs.span("align.dispatch"):
            assert trace.current_span() == "align.dispatch"
            _fake_compile(128, 16)
        assert trace.current_span() is None
    finally:
        trace.deactivate()
    (ev,) = compilewatch.events()
    assert ev["phase"] == "align.dispatch"
    assert ev["fn"].endswith("._fake_compile")


# ---------------------------------------- serve listener absorbed (dedupe)

def test_scoped_compile_s_preserved_and_serve_listener_absorbed():
    """The round-14 serve contract, now served by the process-wide
    listener: compile seconds fired on a scoped thread land in that
    scope, and ``dispatch_fetch.compile_s`` of the per-job report
    keeps its value.  The serve-only listener is gone."""
    metrics.set_scope("job.t1.")
    try:
        _fake_compile(256, 64, duration=1.25)
        # a non-backend pipeline stage adds time but no event — the
        # exact accumulation semantics of the old serve listener
        compilewatch._on_duration(
            "/jax/core/compile/jaxpr_trace_duration", 0.25)
    finally:
        metrics.set_scope(None)
    assert metrics.timer_s("job.t1.compile.jax_s") == \
        pytest.approx(1.50)
    rep = report.build_report("job", scope="job.t1.")
    assert report.validate_report(rep) == []
    assert rep["dispatch_fetch"]["compile_s"] == pytest.approx(1.50)
    comp = rep["compiles"]
    assert comp["count"] == 1 and comp["post_warm"] == 0
    assert list(comp["by_function"]) == \
        ["test_compile_surface._fake_compile"]
    assert comp["events"][0]["signature"] == "max_len=256,band=64"

    from racon_tpu.serve import service
    assert not hasattr(service, "arm_compile_monitor")


def test_report_v7_requires_compiles_section():
    rep = report.build_report("cli")
    assert rep["schema_version"] == report.SCHEMA_VERSION
    assert report.validate_report(rep) == []
    broken = dict(rep)
    del broken["compiles"]
    assert any("compiles" in e for e in report.validate_report(broken))
    bad = dict(rep, compiles=dict(rep["compiles"], post_warm="x"))
    assert any("post_warm" in e for e in report.validate_report(bad))


# -------------------------------------------------------- warm-path seal

def test_seal_flags_only_unwarmed_shapes_with_nearest():
    _fake_compile(256, 64)
    compilewatch.seal("test warm-up complete")
    assert compilewatch.sealed() == "test warm-up complete"
    metrics.set_scope("job.seal.")      # job work is always scoped
    try:
        _fake_compile(256, 64)          # warmed shape: silent
        assert compilewatch.post_warm() == []
        _fake_compile(1024, 64)         # genuinely unwarmed
    finally:
        metrics.set_scope(None)
    viol = compilewatch.post_warm()
    assert len(viol) == 1
    assert "max_len=1024" in viol[0]["signature"]
    assert "max_len=256" in viol[0]["nearest_warmed"]
    msg = compilewatch.describe(viol)
    assert "max_len=1024" in msg and "nearest warmed" in msg
    assert compilewatch.summary()["post_warm"] == 1
    metrics.clear("job.seal.")


def test_unscoped_post_seal_compile_is_warmup_not_violation():
    """An UNSCOPED compile after the seal is warm-up/background work by
    construction (job work always runs under a metric scope): it joins
    the warmed set — so a job later dispatching that geometry is warm —
    and is never recorded as a violation."""
    _fake_compile(256, 64)
    compilewatch.seal("t")
    _fake_compile(4096, 64)             # admission warm-up, unscoped
    assert compilewatch.post_warm() == []
    metrics.set_scope("job.w.")
    try:
        _fake_compile(4096, 64)         # the job re-compiles it: warm
    finally:
        metrics.set_scope(None)
    assert compilewatch.post_warm() == []
    metrics.clear("job.w.")


def test_unseal_relearns_capacity_changed_geometry():
    """The degradation-ladder contract: a capacity change re-opens the
    seal (serve's OOM rung calls ``unseal()``), the shrunk geometry's
    compiles land in the warmed set, and after the re-seal the same
    geometry is silent instead of failing every subsequent job."""
    _fake_compile(1024, 64)
    compilewatch.seal("warm")
    compilewatch.unseal()             # reduce_capacity re-opens
    _fake_compile(512, 64)            # the shrunk-arena re-warm compile
    compilewatch.seal("re-warm after capacity change")
    _fake_compile(512, 64)            # next job, shrunk geometry: warm
    assert compilewatch.post_warm() == []


def test_run_boundary_resets_attribution():
    """A second run in one process must not report the first run's
    events: ``obs.begin()`` (the CLI/exec run boundary) resets the
    watch alongside ``metrics.clear_run()``."""
    from racon_tpu import obs

    _fake_compile(256, 64)
    assert compilewatch.summary()["count"] == 1
    obs.begin()
    assert compilewatch.summary() == {
        "total_s": 0.0, "count": 0, "post_warm": 0, "sealed": 0,
        "by_function": {}, "events": []}


def test_scoped_count_exact_past_event_ring_eviction(monkeypatch):
    """The event ring is bounded; a job whose early records were
    evicted still reports its exact compile count (the scoped counter,
    not the ring)."""
    monkeypatch.setattr(compilewatch, "MAX_EVENTS", 4)
    metrics.set_scope("job.ring.")
    try:
        for _ in range(10):
            _fake_compile(128, 8)
    finally:
        metrics.set_scope(None)
    s = compilewatch.summary("job.ring.")
    assert s["count"] == 10
    assert len(s["events"]) <= 4
    metrics.clear("job.ring.")


def test_violation_cap_cannot_disarm_later_jobs():
    """The bounded violation list evicts FIFO and judged scopes are
    pruned — a flood of earlier violations must not make a later job's
    genuine warm-path violation invisible to the sanitized assert."""
    compilewatch.seal("t")
    metrics.set_scope("job.flood.")
    try:
        for k in range(compilewatch.MAX_VIOLATIONS + 8):
            _fake_compile(8192 + k, 8)
    finally:
        metrics.set_scope(None)
    metrics.set_scope("job.later.")
    try:
        _fake_compile(31337, 8)
    finally:
        metrics.set_scope(None)
    assert len(compilewatch.post_warm("job.later.")) == 1
    compilewatch.clear_scope("job.later.")     # the judgment prune
    assert compilewatch.post_warm("job.later.") == []
    assert len(compilewatch.post_warm()) <= compilewatch.MAX_VIOLATIONS
    metrics.clear("job.flood.")
    metrics.clear("job.later.")


def test_sanitize_gate_raises_only_when_armed(monkeypatch):
    _fake_compile(128, 64)
    compilewatch.seal("t")
    metrics.set_scope("job.t9.")
    try:
        _fake_compile(4096, 64)
    finally:
        metrics.set_scope(None)
    monkeypatch.delenv("RACON_TPU_SANITIZE", raising=False)
    assert len(sanitize.check_post_warm_compiles("job.t9.")) == 1
    assert sanitize.check_post_warm_compiles("job.other.") == []
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    with pytest.raises(sanitize.CompileAfterWarmError) as ei:
        sanitize.check_post_warm_compiles("job.t9.")
    assert "nearest warmed" in str(ei.value)
    assert "max_len=4096" in str(ei.value)


# The sanitized serve warm-path acceptance test
# (test_serve_sanitized_warm_path_assert_fires_only_when_unwarmed)
# lives at the END of tests/test_serve.py: it traces the same engine
# geometries test_serve's own warm-path/retrace asserts rely on being
# cold, so in a single-process full run it must execute after them —
# in-file definition order guarantees that; alphabetical file order
# from here would not.
