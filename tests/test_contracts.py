"""The contract registry (racon_tpu.contracts) and its two enforcement
layers: the import-time selfcheck + state-machine declarations, the
runtime exit audit (sanitize.contract_audit), and the round-22 analyzer
surfaces (--rules-md/--check-readme generation, --changed-only helpers).

The headline test is the validator round-trip: a REAL synthetic polish
(first-party overlapper + device aligner path, span timers armed) built
into all three report kinds, each schema-valid, with ZERO
validator-defaulted keys among the sections that run exercises — every
exercised report key must trace back to a metric that actually fired,
not a section builder's ``.get()`` default."""

import pathlib
import sys

import pytest

from racon_tpu import contracts, sanitize
from racon_tpu.obs import metrics, report, trace

REPO = pathlib.Path(__file__).resolve().parent.parent


# ------------------------------------------------------------- registry

def test_registry_selfcheck_is_clean():
    """The registry's internal-consistency audit: grammar over every
    metric name, REPORT_BACKING targets registered, journal records are
    job states, section emitters declared."""
    assert contracts.selfcheck() == []


def test_state_machines_declare_the_lifecycles():
    job, shard, lease = (contracts.JOB_MACHINE, contracts.SHARD_MACHINE,
                         contracts.LEASE_MACHINE)
    # the crash-recovery edges the serve/exec layers rely on
    assert job.has_edge("running", "queued")        # slot-death requeue
    assert job.has_edge("done", "queued")           # corrupt-spool requeue
    assert job.has_edge("running", "running")       # crash incarnation
    assert not job.has_edge("collected", "running")
    assert set(job.terminal) == {"failed", "cancelled", "collected"}
    assert shard.has_edge("done", "pending")        # part-CRC requeue
    assert shard.has_edge("quarantined", "pending")
    assert shard.terminal == ()                     # every state requeues
    assert lease.has_edge("expired", "claimed")
    placement = contracts.PLACEMENT_MACHINE
    # first-sight-stale beacon / restart under the same name
    assert placement.has_edge("registered", "dead")
    assert placement.has_edge("dead", "alive")
    assert "zombie" not in job and "pending" in shard
    # the journal's record alphabet is a subset of the job states
    assert set(contracts.JOURNAL_RECORDS) <= set(job.states)


def test_consumers_import_the_one_registry():
    """The string constants the serve/exec/fault layers use ARE the
    registry's — a drifted copy would bring back the round-21 class of
    bug where a literal and the machine disagreed silently."""
    from racon_tpu import faults
    from racon_tpu.exec import manifest
    from racon_tpu.serve import journal, service

    assert journal.SUBMITTED is contracts.JOB_SUBMITTED
    assert journal.COLLECTED is contracts.JOB_COLLECTED
    assert service.QUEUED is contracts.JOB_QUEUED
    assert manifest.QUARANTINED is contracts.SHARD_QUARANTINED
    assert faults.KNOWN_SITES is contracts.FAULT_SITES
    assert faults.CLASSES is contracts.FAULT_CLASSES


def test_clear_run_covers_aligner_metrics():
    """Drift regression (round 22): the ``aligner.*`` family is a
    per-run prefix — before the registry migration it was missing from
    the clear-list, so back-to-back runs accumulated band-escalation
    counters across run boundaries."""
    assert "aligner." in contracts.RUN_PREFIXES
    metrics.inc("aligner.band_escalated", 3)
    metrics.clear_run()
    assert metrics.counter("aligner.band_escalated", None) is None


# ----------------------------------------------------- runtime exit audit

def test_contract_audit_silent_before_any_emission(monkeypatch):
    monkeypatch.setattr(metrics, "_seen", set())
    audit = sanitize.contract_audit()
    assert audit == {"never_emitted": [], "defaulted_keys": []}


def test_contract_audit_diffs_registry_against_seen(monkeypatch, capsys):
    monkeypatch.setattr(metrics, "_seen", set())
    metrics.inc("queue.depth", 0)
    metrics.add_time("align.dispatch", 0.01)
    audit = sanitize.contract_audit(stream=sys.stderr)
    # the two emitted names are NOT defaulted/never-emitted ...
    assert "queue.depth" not in audit["never_emitted"]
    assert "queue.depth" not in audit["defaulted_keys"]
    assert "dispatch_fetch.align_dispatch_s" not in audit["defaulted_keys"]
    # ... everything else still is
    assert "serve.recovered_jobs" in audit["never_emitted"]
    assert "recovery.recovered_jobs" in audit["defaulted_keys"]
    # counts published as sanitize gauges for the chaos-soak report
    assert metrics.gauge("sanitize.contract_never_emitted") == len(
        audit["never_emitted"])
    assert metrics.gauge("sanitize.contract_defaulted_keys") == len(
        audit["defaulted_keys"])
    assert "contract audit" in capsys.readouterr().err


# ------------------------------------- the validator round-trip (v11)

# report keys whose backing metric a small-but-real polish (first-party
# overlapper, device aligner + consensus, span timers armed) MUST drive.
# Deliberately excludes feature-gated families a CLI run never touches:
# recovery.* (serve-only), dataflow residency (RACON_TPU_RESIDENT),
# compile_s (jax.monitoring availability varies) and the event-
# conditional overlap counters (join_bailouts, freq caps, cache hits).
_EXERCISED_KEYS = frozenset((
    "queue.depth", "queue.producer_wait_s", "queue.consumer_wait_s",
    "queue.stall_s",
    "pack.pack_efficiency", "pack.pad_fraction", "pack.windows_per_group",
    "pack.groups", "pack.align_pack_efficiency", "pack.align_pad_fraction",
    "pack.align_chunks", "pack.align_steps_wasted",
    "dispatch_fetch.align_dispatch_s", "dispatch_fetch.align_fetch_s",
    "dispatch_fetch.consensus_pack_s",
    "dispatch_fetch.consensus_dispatch_s", "dispatch_fetch.consensus_fetch_s",
    "overlap.minimizers", "overlap.candidate_pairs",
    "overlap.chains_kept", "overlap.chains_dropped",
    "overlap.lanes_occupied", "overlap.lanes_total", "overlap.chunks",
    "overlap.seed_dispatch_s", "overlap.seed_fetch_s",
    "overlap.chain_dispatch_s", "overlap.chain_fetch_s",
))


def test_report_roundtrip_all_kinds_zero_defaulted_keys(tmp_path):
    """Satellite: round-trip the v11 validator over all three report
    kinds built from ONE real synthetic polish.  Every kind validates
    clean, and the exit audit finds no validator-defaulted key among
    the sections the run exercised — i.e. the REPORT_BACKING map is
    honest: those keys carry measured values, not builder defaults."""
    sys.path.insert(0, str(REPO / "tests"))
    from test_columnar_init import write_synthetic_assembly
    from racon_tpu.core.polisher import create_polisher

    assert set(_EXERCISED_KEYS) <= set(contracts.REPORT_BACKING)

    rp, _pp, lp = write_synthetic_assembly(tmp_path, seed=37, n_contigs=2,
                                           contig=2500)
    trace.deactivate()
    trace.activate()                  # arm span timers (no trace ring)
    try:
        p = create_polisher(str(rp), "auto", str(lp), num_threads=2,
                            aligner_backend="tpu", aligner_batches=1,
                            consensus_backend="tpu", consensus_batches=1)
        polished = p.run(True)
    finally:
        trace.deactivate()
    assert polished

    entry = {"id": 0, "status": "done", "engine": "primary", "mbp": 0.005,
             "wall_s": 1.0, "retrace": {"align": 0}, "timings": {},
             "peak_rss_mb": 64}
    reps = {
        "cli": report.build_report("cli", argv=["x"], started_unix=1.0,
                                   wall_s=2.0, phases={"align_s": 0.5}),
        "exec": report.build_report("exec", shards=[entry]),
        "job": report.build_report("job"),
    }
    assert set(reps) == set(contracts.REPORT_KINDS)
    for kind, rep in reps.items():
        errs = report.validate_report(rep)
        assert errs == [], (kind, errs)
        assert rep["kind"] == kind

    audit = sanitize.contract_audit()
    defaulted = set(audit["defaulted_keys"]) & _EXERCISED_KEYS
    assert not defaulted, (
        f"exercised report keys carried only builder defaults "
        f"(backing metric never fired): {sorted(defaulted)}")
    # and the audit only ever names keys the registry declares
    assert set(audit["defaulted_keys"]) <= set(contracts.REPORT_BACKING)


# ----------------------------------------- analyzer surfaces (round 22)

def test_rules_md_matches_readme():
    """The README rule table is generated — `--check-readme` gates it."""
    from tools import analysis

    md = analysis.rules_md()
    assert analysis._TABLE_NOTE in md
    for rule in analysis.rules.ALL_RULES:
        assert f"`{rule.name}`" in md
    assert analysis.check_readme(str(REPO / "README.md"))
    assert not analysis.check_readme(str(REPO / "ROADMAP.md"))


def test_changed_only_expansion_pulls_import_neighbors(tmp_path):
    from tools import analysis
    from tools.analysis.astutil import Project, load_module

    (tmp_path / "pkg").mkdir()
    files = {"__init__.py": "", "base.py": "X = 1\n",
             "mid.py": "from pkg.base import X\n",
             "leaf.py": "import pkg.mid\n", "far.py": "Y = 2\n"}
    for name, src in files.items():
        (tmp_path / "pkg" / name).write_text(src)
    project = Project([load_module(tmp_path / "pkg" / name, f"pkg/{name}")
                       for name in files])

    got = analysis.expand_changed(project, {"pkg/base.py"})
    assert "pkg/base.py" in got
    assert "pkg/mid.py" in got          # one-hop importer
    assert "pkg/far.py" not in got      # unrelated stays out

    # analyzer/registry edits force a full run (None = no narrowing)
    assert any(t in ("racon_tpu/contracts.py",)
               for t in analysis._FULL_RUN_TRIGGERS)
