"""Streaming shard-run subsystem (``racon_tpu.exec``).

The concluding contract under test: sharded runs are **byte-identical**
to the single-shot FASTA — across shard counts, gzipped inputs, MHAP id
rewriting, fragment-correction mode, a SIGKILL mid-run followed by
``--resume``, and a corrupt/truncated manifest. Plus the fault story (an
injected per-shard device fault is retried on the CPU engines; a
persistent one is quarantined with a logged reason instead of killing
the run), the planner's LPT/budget modes, read eviction, and the
heartbeat/manifest observability surface.
"""

import gzip
import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from test_columnar_init import write_synthetic_assembly

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.exec import (ShardRunner, build_index, load_manifest,
                            parse_ram, plan_shards)
from racon_tpu.exec.manifest import MANIFEST_NAME
from racon_tpu.io import parsers

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


def single_shot(rp, pp, lp, drop_unpolished=True, type_=PolisherType.C):
    """Reference output: the plain Polisher surface, CLI byte format."""
    p = create_polisher(str(rp), str(pp), str(lp), type_, num_threads=4)
    return b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                    for s in p.run(drop_unpolished))


def sharded(rp, pp, lp, work_dir, **kw):
    kw.setdefault("num_threads", 4)
    runner = ShardRunner(str(rp), str(pp), str(lp), work_dir=str(work_dir),
                         **kw)
    buf = io.BytesIO()
    summary = runner.run(buf)
    return buf.getvalue(), summary


@pytest.fixture()
def assembly(tmp_path):
    return write_synthetic_assembly(tmp_path, seed=5, n_contigs=4,
                                    contig=2500)


# ------------------------------------------------------------------- index

def test_index_replays_global_filter(tmp_path):
    """The index pass must keep exactly what _filter_overlaps keeps:
    error>threshold and self overlaps drop, contig polishing keeps the
    longest overlap per *consecutive-run* query group (later line wins
    ties) — including a query whose groups are split by another query's
    line (two kept overlaps, not one)."""
    lp = tmp_path / "t.fasta"
    lp.write_bytes(b">A\n" + b"ACGT" * 300 + b"\n>B\n" + b"TGCA" * 300
                   + b"\n")
    rp = tmp_path / "r.fasta"
    rp.write_bytes(b">r1\n" + b"ACGT" * 250 + b"\n>r2\n" + b"ACGT" * 250
                   + b"\n")

    def paf(q, ql, qb, qe, t, tl, tb, te):
        return b"\t".join([q, b"%d" % ql, b"%d" % qb, b"%d" % qe, b"+",
                           t, b"%d" % tl, b"%d" % tb, b"%d" % te,
                           b"50", b"100", b"255"]) + b"\n"

    pp = tmp_path / "o.paf"
    pp.write_bytes(
        # group 1 of r1: two lines, second is longer -> kept
        paf(b"r1", 1000, 0, 100, b"A", 1200, 0, 100)
        + paf(b"r1", 1000, 0, 400, b"A", 1200, 0, 400)
        # r2's line splits r1's groups
        + paf(b"r2", 1000, 0, 300, b"A", 1200, 100, 400)
        # group 2 of r1 (same query, NEW group) -> kept too
        + paf(b"r1", 1000, 0, 200, b"B", 1200, 0, 200)
        # error > 0.3 -> dropped inside its group
        + paf(b"r2", 1000, 0, 50, b"B", 1200, 0, 500))
    idx = build_index(str(rp), str(pp), str(lp))
    kept = list(zip(idx.ov_read.tolist(), idx.ov_target.tolist()))
    # r1->A (the 400-span line), r2->A, r1->B; the high-error r2->B gone
    assert kept == [(0, 0), (1, 0), (0, 1)]
    # the kept r1->A line is the longer SECOND line of its group
    assert idx.ov_start[0] > 0


def test_index_empty_sets_raise(tmp_path):
    lp = tmp_path / "t.fasta"
    lp.write_bytes(b">A\nACGT\n")
    rp = tmp_path / "r.fasta"
    rp.write_bytes(b">r1\nACGT\n")
    pp = tmp_path / "o.paf"
    pp.write_bytes(b"")
    with pytest.raises(ValueError, match="empty overlap set"):
        build_index(str(rp), str(pp), str(lp))
    # unsupported overlap extension: the same clean error a single-shot
    # create_polisher raises, not a parser crash deep in the scan
    bad = tmp_path / "o.txt"
    bad.write_bytes(b"whatever\n")
    with pytest.raises(ValueError, match="unsupported format extension"):
        build_index(str(rp), str(bad), str(lp))


def test_scan_spans_tile_the_file(assembly):
    rp, pp, lp = assembly
    for path, parse in ((rp, parsers.parse_fastq), (lp, parsers.parse_fasta)):
        spans = list(parsers.scan_sequence_spans(str(path)))
        recs = list(parse(str(path)))
        assert [s.name for s in spans] == [r.name for r in recs]
        assert [s.bases for s in spans] == [len(r.data) for r in recs]
        assert spans[0].start == 0
        assert spans[-1].end == os.path.getsize(path)
        for a, b in zip(spans, spans[1:]):
            assert a.end == b.start
        # a copied span re-parses to the identical record
        blob = next(parsers.iter_byte_ranges(str(path),
                                             [(spans[1].start,
                                               spans[1].end)]))
        part = path.parent / ("one" + path.suffix)
        part.write_bytes(blob)
        rec = list(parse(str(part)))[0]
        assert (rec.name, rec.data, rec.quality) == \
            (recs[1].name, recs[1].data, recs[1].quality)


# ----------------------------------------------------------------- planner

def test_parse_ram():
    assert parse_ram("4G") == 4 << 30
    assert parse_ram("500M") == 500 << 20
    assert parse_ram("64k") == 64 << 10
    assert parse_ram("100") == 100 << 20  # plain number = MB


def test_planner_modes(assembly):
    rp, pp, lp = assembly
    idx = build_index(str(rp), str(pp), str(lp))
    # explicit shard count: exact bins, clamped to the contig count
    assert plan_shards(idx, n_shards=3).n_shards == 3
    assert plan_shards(idx, n_shards=99).n_shards == 4
    # every contig appears exactly once
    plan = plan_shards(idx, n_shards=3)
    assert sorted(ci for s in plan.shards for ci in s) == [0, 1, 2, 3]
    # a huge budget collapses to one shard
    assert plan_shards(idx, max_ram_bytes=1 << 40,
                       base_rss=0).n_shards == 1
    # split mode bounds per-shard TARGET bytes (wrapper --split semantics)
    t_bases = [t.bases for t in idx.targets]
    sp = plan_shards(idx, max_target_bytes=max(t_bases) + 1)
    assert sp.mode == "split"
    for b in sp.shards:
        if len(b) > 1:
            assert sum(t_bases[ci] for ci in b) <= max(t_bases) + 1


def test_planner_max_ram_budget_packing():
    """Budget mode at realistic scale (synthetic index: eight 100 MB-ish
    contigs, 1 GB budget over a 200 MB base): the shard count grows until
    every multi-contig bin fits the available budget, and a single
    oversized contig gets its own shard instead of failing."""
    from types import SimpleNamespace

    class FakeIndex:
        def __init__(self, t_bases, read_b, ov_b):
            self.targets = [SimpleNamespace(bases=b, name=b"c%d" % i)
                            for i, b in enumerate(t_bases)]
            self._r = np.asarray(read_b, np.int64)
            self._o = np.asarray(ov_b, np.int64)

        def contig_read_bytes(self):
            return self._r

        def contig_overlap_bytes(self):
            return self._o

    mb = 1 << 20
    idx = FakeIndex([100 * mb] * 8, [90 * mb] * 8, [10 * mb] * 8)
    plan = plan_shards(idx, max_ram_bytes=1 << 30, base_rss=200 * mb)
    assert plan.mode == "max-ram" and plan.n_shards > 1
    assert sorted(ci for s in plan.shards for ci in s) == list(range(8))
    for b, cost in zip(plan.shards, plan.costs):
        if len(b) > 1:
            assert cost <= plan.avail_bytes
    # one contig bigger than the whole budget: own shard, run proceeds
    idx2 = FakeIndex([100 * mb, 4096 * mb], [0, 0], [0, 0])
    plan2 = plan_shards(idx2, max_ram_bytes=1 << 30, base_rss=0)
    assert [len(s) for s in plan2.shards] == [1, 1]


# -------------------------------------------------------------- invariance

def test_shard_invariance(assembly, tmp_path, capfd):
    """--shards N output == single-shot output, for N in {1, 3}; the
    heartbeat emits per-shard completion lines with retrace counters."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    got3, summary = sharded(rp, pp, lp, tmp_path / "w3", n_shards=3)
    assert got3 == want
    assert summary["n_shards"] == 3
    assert not summary["quarantined"]
    got1, _ = sharded(rp, pp, lp, tmp_path / "w1", n_shards=1)
    assert got1 == want
    err = capfd.readouterr().err
    assert "[racon_tpu::exec] shard 0 done engine=primary" in err
    assert "retrace[" in err and "peak_rss=" in err
    # per-shard stats carry the init breakdown incl. the slice-and-append
    # cost (the "move layer storage columnar" ROADMAP decision input)
    done = summary["shards"][0]
    assert "layer_append_s" in done["timings"]
    assert "align_s" in done["timings"]


def test_shard_invariance_gz_and_mhap(assembly, tmp_path):
    """Gzipped inputs (forward streamed-inflate range reads) and MHAP
    overlaps (file-ordinal ids rewritten per shard) stay byte-identical."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    gz = {}
    for src, name in ((rp, "reads.fastq.gz"), (pp, "ovl.paf.gz"),
                      (lp, "layout.fasta.gz")):
        dst = tmp_path / name
        with open(src, "rb") as f, gzip.open(dst, "wb") as g:
            g.write(f.read())
        gz[name] = dst
    got, _ = sharded(gz["reads.fastq.gz"], gz["ovl.paf.gz"],
                     gz["layout.fasta.gz"], tmp_path / "wgz", n_shards=3)
    assert got == want

    # PAF -> MHAP conversion (ids are 1-based file ordinals)
    rid = {r.name: i + 1 for i, r in
           enumerate(parsers.parse_fastq(str(rp)))}
    tid = {t.name: i + 1 for i, t in
           enumerate(parsers.parse_fasta(str(lp)))}
    lines = []
    for _s, _e, line in parsers.scan_line_spans(str(pp)):
        f = line.split(b"\t")
        lines.append(b" ".join([
            b"%d" % rid[f[0]], b"%d" % tid[f[5]], b"0.1", b"0",
            b"1" if f[4] == b"-" else b"0", f[2], f[3], f[1],
            b"0", f[7], f[8], f[6]]) + b"\n")
    mp = tmp_path / "ovl.mhap"
    mp.write_bytes(b"".join(lines))
    want_mhap = single_shot(rp, mp, lp)
    got_mhap, _ = sharded(rp, mp, lp, tmp_path / "wmh", n_shards=3)
    assert got_mhap == want_mhap


def test_fragment_mode_invariance(assembly, tmp_path):
    """-f self-correction (targets == reads, keep-all filter): the
    hardest resolution case — every query name is also a target name."""
    rp, _pp, _lp = assembly
    recs = list(parsers.parse_fastq(str(rp)))
    ava = []
    for a, b in zip(recs, recs[1:]):
        ln = min(len(a.data), len(b.data)) // 2
        for q, t in ((a, b), (b, a)):
            ava.append(b"\t".join([
                q.name, b"%d" % len(q.data), b"0", b"%d" % ln, b"+",
                t.name, b"%d" % len(t.data), b"0", b"%d" % ln,
                b"%d" % (ln // 2), b"%d" % ln, b"255"]) + b"\n")
    ap = tmp_path / "ava.paf"
    ap.write_bytes(b"".join(ava))
    want = single_shot(rp, ap, rp, drop_unpolished=False,
                       type_=PolisherType.F)
    got, _ = sharded(rp, ap, rp, tmp_path / "wf", n_shards=4,
                     type_=PolisherType.F, include_unpolished=True)
    assert got == want
    assert got.count(b">") == len(recs)


def test_unpolished_only_shard_matches_single_shot(assembly, tmp_path):
    """A contig with zero kept overlaps can land alone in a shard; with
    -u the single-shot run emits it raw with zero-coverage tags — the
    runner synthesizes the identical record (a Polisher would refuse the
    empty overlap set)."""
    rp, pp, lp = assembly
    targets = list(parsers.parse_fasta(str(lp)))
    victim = targets[1].name
    kept = [line + b"\n" for _s, _e, line in parsers.scan_line_spans(
        str(pp)) if line.split(b"\t")[5] != victim]
    pp2 = tmp_path / "cut.paf"
    pp2.write_bytes(b"".join(kept))
    want = single_shot(rp, pp2, lp, drop_unpolished=False)
    got, summary = sharded(rp, pp2, lp, tmp_path / "wu", n_shards=4,
                           include_unpolished=True)
    assert got == want
    assert b">" + victim + b" LN:i:%d RC:i:0" % len(targets[1].data) in got


def test_cli_shards_matches_plain_cli(assembly, tmp_path):
    """End-to-end through the actual CLI: --shards 3 stdout must equal
    the plain CLI's stdout byte for byte."""
    rp, pp, lp = assembly
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")

    def run(*extra):
        proc = subprocess.run(
            [sys.executable, "-m", "racon_tpu", "-t", "4", *extra,
             str(rp), str(pp), str(lp)],
            capture_output=True, timeout=600, cwd=REPO_ROOT, env=env)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return proc.stdout

    plain = run()
    shard = run("--shards", "3", "--shard-dir", str(tmp_path / "cli_w"))
    assert shard == plain


def test_wrapper_split_routes_through_runner(assembly, tmp_path):
    """racon_wrapper --split goes through the in-process shard runner by
    default and must reproduce the plain CLI's bytes; --legacy-split
    keeps the subprocess path and must too."""
    rp, pp, lp = assembly
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    t_bytes = sum(len(t.data) for t in parsers.parse_fasta(str(lp)))

    def run(module, *extra):
        proc = subprocess.run(
            [sys.executable, "-m", module, "-t", "4", *extra,
             str(rp), str(pp), str(lp)],
            capture_output=True, timeout=600, cwd=str(tmp_path), env=env)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return proc

    # the wrapper defaults to 5/-4/-8 scores (upstream discrepancy kept
    # for parity) — pin racon's own defaults for the comparison
    scores = ["-m", "3", "-x", "-5", "-g", "-4"]
    plain = run("racon_tpu.cli").stdout
    via_runner = run("racon_tpu.wrapper", "--split", str(t_bytes // 2),
                     *scores)
    assert via_runner.stdout == plain
    assert b"streaming shard runner" in via_runner.stderr
    legacy = run("racon_tpu.wrapper", "--split", str(t_bytes // 2),
                 "--legacy-split", *scores)
    assert legacy.stdout == plain
    assert b"streaming shard runner" not in legacy.stderr


# ---------------------------------------------------------- fault handling

def test_injected_fault_retries_on_cpu(assembly, tmp_path, monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_EXEC_FAULT_SHARD", "1")
    got, summary = sharded(rp, pp, lp, tmp_path / "w", n_shards=4)
    assert got == want  # CPU retry produced the identical bytes
    entry = summary["shards"][1]
    assert entry["status"] == "done"
    assert entry["engine"] == "cpu-retry"
    assert "injected device-engine fault" in entry["reason"]
    assert not summary["quarantined"]


def test_persistent_fault_quarantines_without_killing_run(
        assembly, tmp_path, monkeypatch):
    rp, pp, lp = assembly
    monkeypatch.setenv("RACON_TPU_EXEC_FAULT_SHARD", "2*")
    got, summary = sharded(rp, pp, lp, tmp_path / "w", n_shards=4,
                           keep_work_dir=True)
    assert summary["quarantined"] == [2]
    entry = summary["shards"][2]
    assert entry["status"] == "quarantined"
    assert "injected device-engine fault" in entry["reason"]
    assert "cpu retry" in entry["reason"]
    # the other three shards' contigs still came out
    assert got.count(b">") == 3
    # the manifest on disk records the quarantine reason
    m = load_manifest(str(tmp_path / "w"))
    assert m["shards"][2]["status"] == "quarantined"
    assert "injected" in m["shards"][2]["reason"]
    # resume after the fault clears re-runs ONLY the quarantined shard
    monkeypatch.delenv("RACON_TPU_EXEC_FAULT_SHARD")
    want = single_shot(rp, pp, lp)
    got2, summary2 = sharded(rp, pp, lp, tmp_path / "w", n_shards=4,
                             resume=True, keep_work_dir=True)
    assert got2 == want
    assert all(e["status"] == "done" for e in summary2["shards"])


# ------------------------------------------------------------------ resume

def test_resume_skips_completed_shards(assembly, tmp_path, capfd):
    rp, pp, lp = assembly
    want, summary = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                            keep_work_dir=True)
    parts = sorted((tmp_path / "w").glob("part_*.fasta"))
    assert len(parts) == 3
    mtimes = [p.stat().st_mtime_ns for p in parts]
    got, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3, resume=True,
                     keep_work_dir=True)
    assert got == want
    err = capfd.readouterr().err
    assert err.count("resume: skipping completed shard") == 3
    # untouched part files: nothing re-ran
    assert [p.stat().st_mtime_ns for p in parts] == mtimes


def test_resume_adopts_stored_plan_when_replan_drifts(assembly, tmp_path,
                                                      monkeypatch, capfd):
    """A --max-ram plan depends on the planning process's live RSS, so a
    resume can legitimately recompute a DIFFERENT plan. The resume must
    adopt the manifest's stored plan (the one the parts were cut by)
    and skip all completed shards, not discard hours of work."""
    import racon_tpu.exec.runner as runner_mod
    from racon_tpu.exec.planner import plan_shards as real_plan

    rp, pp, lp = assembly
    want, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                      keep_work_dir=True)

    def drifted(index, n_shards=0, max_ram_bytes=0, max_target_bytes=0,
                base_rss=0, **kw):
        return real_plan(index, n_shards=2)  # simulated RSS-shifted plan

    monkeypatch.setattr(runner_mod, "plan_shards", drifted)
    got, summary = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                           resume=True, keep_work_dir=True)
    assert got == want
    assert summary["n_shards"] == 3  # stored plan adopted, not the drift
    err = capfd.readouterr().err
    assert err.count("resume: skipping completed shard") == 3


def test_resume_ignores_sizing_knobs(assembly, tmp_path, capfd):
    """A bare `racon --resume` (no --shards/--max-ram repeated) must
    trust the checkpoint: shard boundaries never change the merged
    bytes, so the stored plan is adopted and completed shards skip."""
    rp, pp, lp = assembly
    want, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                      keep_work_dir=True)
    got, summary = sharded(rp, pp, lp, tmp_path / "w", resume=True,
                           keep_work_dir=True)  # no sizing knobs at all
    assert got == want
    assert summary["n_shards"] == 3  # the stored plan, not a fresh one
    err = capfd.readouterr().err
    assert err.count("resume: skipping completed shard") == 3


def test_resume_param_mismatch_reruns_everything(assembly, tmp_path,
                                                 capfd):
    """Output-shaping parameters ARE fingerprinted: resuming with a
    different quality threshold must not trust the old parts."""
    rp, pp, lp = assembly
    want3, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                       keep_work_dir=True)
    # '9'-quality reads pass both thresholds, so the bytes stay equal —
    # but the runner cannot know that and must re-run
    got, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3, resume=True,
                     keep_work_dir=True, quality_threshold=9.5)
    assert got == want3
    err = capfd.readouterr().err
    assert "fingerprint does not match" in err
    assert "resume: skipping" not in err


def test_corrupt_manifest_recovery(assembly, tmp_path, capfd):
    """A truncated manifest (torn write, disk full) must not wedge the
    run: resume warns, re-plans and reproduces the byte-identical
    output."""
    rp, pp, lp = assembly
    want, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                      keep_work_dir=True)
    mpath = tmp_path / "w" / MANIFEST_NAME
    blob = mpath.read_bytes()
    mpath.write_bytes(blob[:len(blob) // 2])  # torn mid-object
    got, summary = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                           resume=True, keep_work_dir=True)
    assert got == want
    assert all(e["status"] == "done" for e in summary["shards"])
    err = capfd.readouterr().err
    assert "corrupt" in err and "re-running every shard" in err


@pytest.mark.parametrize("kill_after_parts", [1])
def test_sigkill_then_resume_byte_identical(assembly, tmp_path,
                                            kill_after_parts):
    """The acceptance scenario: SIGKILL the CLI mid-shard (a test-hook
    sleep widens the window), then --resume; the final FASTA must be
    byte-identical to an uninterrupted run and completed shards must not
    re-run."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    wd = tmp_path / "w"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["RACON_TPU_EXEC_SLEEP_S"] = "8"
    args = [sys.executable, "-m", "racon_tpu", "-t", "2", "--shards", "4",
            "--shard-dir", str(wd), str(rp), str(pp), str(lp)]
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, cwd=REPO_ROOT, env=env)

    def done_count():
        # the manifest is written atomically, so polling it is safe; a
        # shard only counts once its part file is durable AND recorded
        m = load_manifest(str(wd))
        return (sum(e["status"] == "done" for e in m["shards"])
                if m else 0)

    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if done_count() >= kill_after_parts:
                break
            if proc.poll() is not None:
                pytest.fail("runner exited before the kill window: "
                            + proc.stderr.read().decode()[-2000:])
            time.sleep(0.1)
        else:
            pytest.fail("no completed shard appeared before the deadline")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode != 0  # killed, not completed
    m = load_manifest(str(wd))
    assert m is not None
    done = [e for e in m["shards"] if e["status"] == "done"]
    assert 0 < len(done) < 4  # interrupted mid-run, checkpoint intact

    env.pop("RACON_TPU_EXEC_SLEEP_S")
    proc = subprocess.run(args + ["--resume"], capture_output=True,
                          timeout=600, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert proc.stdout == want
    err = proc.stderr.decode()
    assert "resume: skipping completed shard" in err


# ---------------------------------------------------------------- eviction

def test_evict_reads_releases_payloads_and_preserves_output(assembly):
    rp, pp, lp = assembly
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=1,
                        evict_reads=True)
    p.initialize()
    # reads (everything past the targets) hold no payload bytes anymore
    assert all(len(s.data) == 0 and s._reverse_complement is None
               for s in p.sequences[p.targets_size:])
    evicted = b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                       for s in p.polish(True))
    assert evicted == single_shot(rp, pp, lp)


# ----------------------------------------------------------- rampler plan

def test_rampler_plan_cli(assembly, capsys):
    from racon_tpu import rampler

    rp, pp, lp = assembly
    assert rampler.main(["plan", str(rp), str(pp), str(lp),
                         "--shards", "3"]) == 0
    plan = json.loads(capsys.readouterr().out)
    assert plan["mode"] == "shards"
    assert plan["n_contigs"] == 4
    assert len(plan["shards"]) == 3
    assert sum(len(s["contigs"]) for s in plan["shards"]) == 4
