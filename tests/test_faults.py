"""Fault-tolerant multi-worker shard execution (round 12).

The concluding contracts under test:

- **leases** — O_EXCL claim exclusion, mtime-TTL expiry, race-safe
  break-and-reclaim, dead-pid fast reclaim;
- **degradation ladder** — per-fault-class transitions (transient-io
  backoff on the same engine, device-OOM arena backpressure with a
  byte-identical device re-dispatch, stall -> CPU, deterministic ->
  CPU -> quarantine), each attempt recorded in the manifest and the
  run report's ``faults`` section;
- **injection harness** — the ``RACON_TPU_FAULTS`` grammar, one-shot /
  persistent / seeded-probability triggers, the legacy
  ``RACON_TPU_EXEC_FAULT_SHARD`` alias routed through the registry;
- **part durability** — size+CRC32 verification before merge, with a
  corrupted part re-queued and re-polished instead of merged;
- **chaos soak** — workers racing one manifest under SIGKILLs and
  injected faults still merge output byte-identical to a single-shot
  run (the acceptance criterion).
"""

import io
import json
import os
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from test_columnar_init import write_synthetic_assembly

from racon_tpu import faults
from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.exec import ShardRunner, lease, load_manifest
from racon_tpu.exec import manifest as mf
from racon_tpu.obs import metrics, report as obs_report

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


def single_shot(rp, pp, lp, drop_unpolished=True, type_=PolisherType.C):
    p = create_polisher(str(rp), str(pp), str(lp), type_, num_threads=4)
    return b"".join(b">" + s.name + b"\n" + s.data + b"\n"
                    for s in p.run(drop_unpolished))


def sharded(rp, pp, lp, work_dir, **kw):
    kw.setdefault("num_threads", 4)
    runner = ShardRunner(str(rp), str(pp), str(lp), work_dir=str(work_dir),
                         **kw)
    buf = io.BytesIO()
    summary = runner.run(buf)
    return buf.getvalue(), summary, runner


@pytest.fixture()
def assembly(tmp_path):
    return write_synthetic_assembly(tmp_path, seed=7, n_contigs=4,
                                    contig=2500)


# ---------------------------------------------------------------- taxonomy

def test_classify():
    import errno
    assert faults.classify(OSError(errno.EIO, "x")) == \
        faults.CLASS_TRANSIENT
    assert faults.classify(OSError(errno.ENOSPC, "x")) == \
        faults.CLASS_TRANSIENT
    assert faults.classify(FileNotFoundError(2, "gone")) == \
        faults.CLASS_COMPUTE
    assert faults.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory while trying to allocate")) \
        == faults.CLASS_OOM
    assert faults.classify(faults.DeviceOOMError("boom")) == \
        faults.CLASS_OOM
    assert faults.classify(faults.StallError("wedged")) == \
        faults.CLASS_STALL
    assert faults.classify(ValueError("bad input")) == \
        faults.CLASS_COMPUTE


def test_parse_spec_grammar():
    spec = faults.parse_spec(
        "align.fetch:io@3,consensus.dispatch:oom*,part.write:enospc,"
        "worker.kill:kill@2,manifest.write:io%0.5")
    assert spec["align.fetch"][0].at == 3
    assert not spec["align.fetch"][0].every
    assert spec["consensus.dispatch"][0].every
    assert spec["part.write"][0].kind == "enospc"
    assert spec["worker.kill"][0].kind == "kill"
    assert spec["manifest.write"][0].prob == 0.5
    with pytest.raises(ValueError, match="unknown"):
        faults.parse_spec("nosuch.site:io")
    with pytest.raises(ValueError, match="unknown"):
        faults.parse_spec("align.fetch:frobnicate")
    with pytest.raises(ValueError, match="1-based"):
        faults.parse_spec("align.fetch:io@0")
    with pytest.raises(ValueError, match="probability"):
        faults.parse_spec("align.fetch:io%1.5")


def test_injection_one_shot_and_persistent(monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULTS", "align.fetch:io@2")
    faults.check("align.fetch")                      # hit 1: armed at 2
    with pytest.raises(faults.TransientIOError):
        faults.check("align.fetch")                  # hit 2 fires
    faults.check("align.fetch")                      # one-shot: consumed
    monkeypatch.setenv("RACON_TPU_FAULTS", "align.fetch:io@1*")
    for _ in range(3):                               # persistent
        with pytest.raises(faults.TransientIOError):
            faults.check("align.fetch")


def test_injection_seeded_probability(monkeypatch):
    def draws(seed):
        monkeypatch.setenv("RACON_TPU_FAULTS", "align.fetch:err%0.5")
        monkeypatch.setenv("RACON_TPU_FAULTS_SEED", seed)
        faults.reset()  # replay the seeded stream from its start
        out = []
        for _ in range(32):
            try:
                faults.check("align.fetch")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out
    a = draws("11")
    b = draws("11")
    c = draws("99")
    assert a == b          # same seed replays bit-for-bit
    assert a != c          # a different seed draws differently
    assert 0 < sum(a) < 32  # and it actually fires sometimes


# ------------------------------------------------------------------ leases

def test_lease_claim_exclusion_and_release(tmp_path):
    wd = str(tmp_path)
    a = lease.try_claim(wd, 0, "worker-a")
    assert a is not None
    assert lease.try_claim(wd, 0, "worker-b") is None  # double-claim
    assert lease.read_lease(wd, 0)["worker"] == "worker-a"
    b = lease.try_claim(wd, 1, "worker-b")    # another shard is free
    assert b is not None
    a.release()
    b.release()
    assert lease.try_claim(wd, 0, "worker-b") is not None


def test_lease_expiry_and_reclaim(tmp_path):
    wd = str(tmp_path)
    metrics.clear("lease.")
    a = lease.try_claim(wd, 0, "worker-a", ttl_s=0.2)
    assert a is not None
    a._keeper.stop()          # simulate a dead worker: no heartbeats
    a._keeper = None
    # make the lease look abandoned: owner pid "alive" (it is us), so
    # only the TTL can expire it
    time.sleep(0.35)
    b = lease.try_claim(wd, 0, "worker-b", ttl_s=0.2)
    assert b is not None      # broken + reclaimed
    assert lease.read_lease(wd, 0)["worker"] == "worker-b"
    assert metrics.counter("lease.expired") >= 1
    b.release()


def test_lease_heartbeat_blocks_expiry(tmp_path):
    wd = str(tmp_path)
    a = lease.try_claim(wd, 0, "worker-a", ttl_s=10.0)
    assert a is not None
    # keeper refreshes mtime; a 0.3s-TTL claimant must still lose
    # because the mtime is fresh
    time.sleep(0.2)
    assert lease.try_claim(wd, 0, "worker-b", ttl_s=10.0) is None
    a.release()


def test_lease_dead_pid_fast_reclaim(tmp_path):
    """A same-host lease whose owner pid is gone is broken immediately,
    without waiting out the TTL (kill-then-resume latency)."""
    wd = str(tmp_path)
    a = lease.try_claim(wd, 0, "worker-a", ttl_s=3600.0)
    assert a is not None
    a._keeper.stop()
    a._keeper = None
    # rewrite the payload with a certainly-dead pid
    blob = json.loads(open(a.path, "rb").read())
    blob["pid"] = 2 ** 22 + 1  # beyond default pid_max
    with open(a.path, "w") as f:
        json.dump(blob, f)
    b = lease.try_claim(wd, 0, "worker-b", ttl_s=3600.0)
    assert b is not None
    b.release()


def test_lease_race_single_winner(tmp_path):
    wd = str(tmp_path)
    wins = []
    barrier = threading.Barrier(8)

    def contend(k):
        barrier.wait()
        got = lease.try_claim(wd, 0, f"worker-{k}")
        if got is not None:
            wins.append(got)

    threads = [threading.Thread(target=contend, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    wins[0].release()


def test_lease_cross_process_expiry_single_winner(tmp_path):
    """The fleet placement contract at process scale: a lease claimed
    by a subprocess that is SIGKILLed (no release, no more
    heartbeats) is broken by a later claimant — and when TWO separate
    processes race to reclaim it, the tombstone rename admits exactly
    one winner."""
    import os
    import signal

    wd = str(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    claimer = (
        "import sys, time\n"
        "from racon_tpu.exec import lease\n"
        "l = lease.try_claim(sys.argv[1], 7, 'victim', ttl_s=1.0)\n"
        "assert l is not None\n"
        "print('CLAIMED', flush=True)\n"
        "time.sleep(600)\n")
    victim = subprocess.Popen(
        [sys.executable, "-c", claimer, wd], env=env,
        stdout=subprocess.PIPE, cwd=str(pathlib.Path(__file__).parents[1]))
    try:
        line = victim.stdout.readline()
        assert b"CLAIMED" in line, line
        assert lease.read_lease(wd, 7)["worker"] == "victim"
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=30)
    finally:
        if victim.poll() is None:
            victim.kill()
            victim.wait()
    # in-process claim still loses while the mtime is fresh: the TTL
    # (or the dead-pid fast path) is what admits the reclaim, not the
    # mere absence of the owner process
    reclaimer = (
        "import sys, time\n"
        "from racon_tpu.exec import lease\n"
        "deadline = time.monotonic() + 60\n"
        "while time.monotonic() < deadline:\n"
        "    l = lease.try_claim(sys.argv[1], 7, sys.argv[2],\n"
        "                        ttl_s=1.0)\n"
        "    if l is not None:\n"
        "        print('WON', flush=True)\n"
        "        time.sleep(600)\n"
        "    info = lease.read_lease(sys.argv[1], 7)\n"
        "    if info and str(info.get('worker', ''))."
        "startswith('reclaimer-'):\n"
        "        print('LOST', flush=True)\n"
        "        sys.exit(0)\n"
        "    time.sleep(0.05)\n"
        "print('TIMEOUT', flush=True)\n")
    racers = [subprocess.Popen(
        [sys.executable, "-c", reclaimer, wd, f"reclaimer-{k}"],
        env=env, stdout=subprocess.PIPE,
        cwd=str(pathlib.Path(__file__).parents[1])) for k in range(2)]
    try:
        verdicts = [p.stdout.readline() for p in racers]
        assert sum(b"WON" in v for v in verdicts) == 1, verdicts
        assert sum(b"LOST" in v for v in verdicts) == 1, verdicts
        winner = next(p for p, v in zip(racers, verdicts)
                      if b"WON" in v)
        info = lease.read_lease(wd, 7)
        assert info["worker"].startswith("reclaimer-")
        assert info["pid"] == winner.pid
    finally:
        for p in racers:
            if p.poll() is None:
                p.kill()
                p.wait()


# --------------------------------------------------------- ladder: classes

def test_transient_fault_backoff_retries_same_engine(assembly, tmp_path,
                                                     monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_FAULTS", "exec.polish:io@1")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0.02")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=2)
    assert got == want
    faulted = [e for e in summary["shards"] if e.get("attempts")]
    assert len(faulted) == 1
    (att,) = faulted[0]["attempts"]
    assert att["class"] == "transient-io"
    assert att["action"] == "retry-backoff"
    assert att["backoff_s"] > 0
    assert faulted[0]["engine"] == "primary"  # never left the engine
    assert summary["faults"]["transient-io"] == 1
    assert summary["faults"]["injected.exec.polish"] == 1


def test_enospc_part_write_retries(assembly, tmp_path, monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_FAULTS", "part.write:enospc@1")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0.02")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=2)
    assert got == want
    faulted = [e for e in summary["shards"] if e.get("attempts")]
    assert len(faulted) == 1
    assert faulted[0]["attempts"][0]["class"] == "transient-io"
    assert faulted[0]["status"] == "done"


def test_stall_fault_degrades_to_cpu(assembly, tmp_path, monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_FAULTS", "exec.polish:stall@1")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=2)
    assert got == want
    faulted = [e for e in summary["shards"] if e.get("attempts")]
    assert len(faulted) == 1
    (att,) = faulted[0]["attempts"]
    assert att["class"] == "stall"
    assert att["action"] == "cpu-retry"
    assert faulted[0]["engine"] == "cpu-retry"


def test_transient_budget_exhaustion_walks_the_whole_ladder(
        assembly, tmp_path, monkeypatch):
    """A persistent transient fault burns its backoff budget, falls to
    the CPU tier, keeps faulting (the site fires on every hit) and ends
    quarantined — with the full per-attempt record in the manifest."""
    rp, pp, lp = assembly
    monkeypatch.setenv("RACON_TPU_FAULTS", "exec.polish:io@1*")
    monkeypatch.setenv("RACON_TPU_EXEC_RETRIES", "2")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0.01")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=1,
                              keep_work_dir=True)
    assert summary["quarantined"] == [0]
    entry = summary["shards"][0]
    actions = [a["action"] for a in entry["attempts"]]
    assert actions == ["retry-backoff", "retry-backoff", "cpu-retry",
                       "quarantine"]
    assert "cpu retry" in entry["reason"]
    # the on-disk manifest carries the same ladder record
    m = load_manifest(str(tmp_path / "w"))
    assert [a["action"] for a in m["shards"][0]["attempts"]] == actions


def test_oom_backpressure_redispatch_parity(assembly, tmp_path,
                                            monkeypatch):
    """Device-OOM ladder rung: the consensus engine halves its
    arena/group capacity and the shard re-dispatches ON THE DEVICE,
    byte-identical (grouping never changes output bytes); the CPU tier
    is never reached."""
    rp, pp, lp = assembly
    # the parity oracle is the SAME device-engine config without any
    # injected fault (device consensus differs from the native-CPU
    # single-shot baseline by design; what backpressure must preserve
    # is the device path's own bytes)
    want, _, _ = sharded(rp, pp, lp, tmp_path / "clean", n_shards=2,
                         aligner_backend="tpu", consensus_backend="tpu")
    monkeypatch.setenv("RACON_TPU_FAULTS",
                       "align.fetch:io@1,consensus.dispatch:oom@1")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0.02")
    got, summary, runner = sharded(
        rp, pp, lp, tmp_path / "w", n_shards=2,
        aligner_backend="tpu", consensus_backend="tpu")
    assert got == want
    classes = {a["class"]: a["action"]
               for e in summary["shards"]
               for a in e.get("attempts", [])}
    assert classes["transient-io"] == "retry-backoff"
    assert classes["device-oom"] == "reduce-capacity"
    assert all(e["engine"] == "primary" for e in summary["shards"])
    consensus = runner._engines[1]
    assert consensus.capacity_scale == 2           # halved once
    assert consensus.group_pairs_cap * 2 <= 32768 * 2  # shrunk caps
    # one ladder rung, but BOTH of the worker's engines shrink (round
    # 17: the aligner's dirs-arena budget halves alongside the
    # consensus pair arena), so the halving counter records two
    assert summary["faults"]["backpressure_halvings"] == 2
    assert runner._engines[0].capacity_scale == 2  # aligner halved too


def test_oom_exhausted_backpressure_falls_to_cpu(assembly, tmp_path,
                                                 monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_FAULTS", "exec.polish:oom@1*")
    got, summary, runner = sharded(rp, pp, lp, tmp_path / "w",
                                   n_shards=1)
    # native primary engines expose no capacity knob: the oom rung is
    # skipped and the ladder falls straight to the CPU tier, where the
    # (every-attempt) injection keeps firing -> quarantine
    assert summary["quarantined"] == [0]
    actions = [a["action"] for a in summary["shards"][0]["attempts"]]
    assert actions == ["cpu-retry", "quarantine"]


def test_legacy_alias_routes_through_registry(assembly, tmp_path,
                                              monkeypatch):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_EXEC_FAULT_SHARD", "1")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=4)
    assert got == want
    entry = summary["shards"][1]
    assert entry["engine"] == "cpu-retry"
    assert "injected device-engine fault" in entry["reason"]
    # the alias is counted by the one fault registry now
    assert summary["faults"]["injected.exec.polish"] == 1
    assert summary["faults"]["deterministic-compute"] == 1


def test_manifest_write_transient_fault_survives(assembly, tmp_path,
                                                 monkeypatch, capfd):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    monkeypatch.setenv("RACON_TPU_FAULTS", "manifest.write:io@2")
    got, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=2)
    assert got == want
    assert not summary["quarantined"]
    assert "retrying" in capfd.readouterr().err


# ------------------------------------------------------ watchdog escalation

def test_watchdog_escalation_fails_attempt_with_stall(tmp_path,
                                                      monkeypatch):
    """Satellite: after the stack-dump timeout, a second timeout fails
    the attempt with a stall-class fault instead of hanging forever —
    but only where the runner's ladder can catch it
    (stall_escalation=True); standalone polishers keep the passive
    dump-only watchdog (test_sanitize covers that half)."""
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    monkeypatch.setenv("RACON_TPU_SANITIZE_WATCHDOG_S", "0.2")
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=31, n_contigs=1,
                                          contig=2000)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2,
                        stall_escalation=True)

    def wedged(overlaps, emit=None, chunk_windows=0):
        time.sleep(8)  # producer wedged well past both timeouts

    monkeypatch.setattr(p, "_assemble_layers", wedged)
    t0 = time.monotonic()
    with pytest.raises(faults.StallError):
        p.run(True)
    assert time.monotonic() - t0 < 5  # escalated, not 8s-wedged
    assert faults.classify(faults.StallError("x")) == faults.CLASS_STALL
    assert metrics.counter("faults.stall_escalations") >= 1


# ------------------------------------------------------- part verification

def test_corrupt_part_requeued_before_merge(assembly, tmp_path, capfd):
    rp, pp, lp = assembly
    want, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                               keep_work_dir=True)
    # flip bytes inside a completed part (size preserved: only the CRC
    # can catch it)
    part = tmp_path / "w" / summary["shards"][1]["part"]
    blob = bytearray(part.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    part.write_bytes(bytes(blob))
    got, summary2, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                               resume=True, keep_work_dir=True)
    assert got == want
    err = capfd.readouterr().err
    assert "failed verification" in err
    assert "re-queueing" in err


def test_truncated_part_requeued_before_merge(assembly, tmp_path, capfd):
    rp, pp, lp = assembly
    want, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                               keep_work_dir=True)
    part = tmp_path / "w" / summary["shards"][2]["part"]
    part.write_bytes(part.read_bytes()[:-40])
    got, _, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=3,
                        resume=True, keep_work_dir=True)
    assert got == want
    assert "failed verification" in capfd.readouterr().err


# -------------------------------------------------------------- run report

def test_run_report_faults_section(assembly, tmp_path, monkeypatch):
    rp, pp, lp = assembly
    monkeypatch.setenv("RACON_TPU_FAULTS", "exec.polish:io@1")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0.02")
    _, summary, _ = sharded(rp, pp, lp, tmp_path / "w", n_shards=2,
                            keep_work_dir=True)
    with open(tmp_path / "w" / mf.REPORT_NAME, "rb") as f:
        rep = json.loads(f.read())
    assert obs_report.validate_report(rep) == []
    assert rep["faults"]["transient-io"] == 1
    assert rep["faults"]["injected.exec.polish"] == 1
    assert rep["faults"]["lease.claimed"] >= 2
    rows = {r["id"]: r for r in rep["shards"]}
    faulted = [r for r in rows.values() if "attempts" in r]
    assert len(faulted) == 1
    assert faulted[0]["attempts"][0]["class"] == "transient-io"
    assert all("crc32" in r and "worker" in r for r in rows.values())


# ------------------------------------------------------------- multi-worker

def _cli_env(**extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


def _cli_args(rp, pp, lp, wd, *more):
    return [sys.executable, "-m", "racon_tpu", "-t", "2", "--shards", "4",
            "--shard-dir", str(wd), *more, str(rp), str(pp), str(lp)]


def test_workers_flag_spawns_cooperating_secondary(assembly, tmp_path):
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    proc = subprocess.run(
        _cli_args(rp, pp, lp, tmp_path / "w", "--workers", "2"),
        capture_output=True, timeout=600, cwd=REPO_ROOT, env=_cli_env())
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert proc.stdout == want


def test_chaos_soak_kill_then_reclaim_byte_identical(assembly, tmp_path):
    """The acceptance scenario: worker A is SIGKILLed mid-shard by the
    injection harness (lease left heartbeat-less, shard state
    ``running``); worker B — itself under an injected transient fault —
    joins the same manifest, breaks the dead lease, reclaims the shard,
    finishes the run and merges output byte-identical to a single-shot
    run. Every decision is visible in the manifest and run report."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    wd = tmp_path / "w"

    # worker A: dies on its second shard, after recording RUNNING
    env_a = _cli_env(RACON_TPU_FAULTS="worker.kill:kill@2",
                     RACON_TPU_WORKER="chaos-a",
                     RACON_TPU_EXEC_LEASE_TTL_S="60")
    proc_a = subprocess.run(_cli_args(rp, pp, lp, wd, "--resume"),
                            capture_output=True, timeout=600,
                            cwd=REPO_ROOT, env=env_a)
    assert proc_a.returncode == -9  # SIGKILLed itself mid-shard
    m = load_manifest(str(wd))
    running = [e for e in m["shards"] if e["status"] == "running"]
    assert len(running) == 1 and running[0]["worker"] == "chaos-a"
    done_by_a = [e for e in m["shards"] if e["status"] == "done"]
    assert len(done_by_a) == 1

    # worker B: joins the manifest, reclaims the abandoned shard (fast
    # path: the dead pid is detected without waiting out the TTL),
    # survives its own injected transient fault, merges
    env_b = _cli_env(RACON_TPU_FAULTS="exec.polish:io@1",
                     RACON_TPU_WORKER="chaos-b",
                     RACON_TPU_EXEC_LEASE_TTL_S="60",
                     RACON_TPU_EXEC_BACKOFF_S="0.05")
    proc_b = subprocess.run(_cli_args(rp, pp, lp, wd, "--resume"),
                            capture_output=True, timeout=600,
                            cwd=REPO_ROOT, env=env_b)
    assert proc_b.returncode == 0, proc_b.stderr.decode()[-2000:]
    assert proc_b.stdout == want                 # byte-identical merge
    assert b"reclaiming shard" in proc_b.stderr

    m = load_manifest(str(wd))
    assert all(e["status"] == "done" for e in m["shards"])
    workers = {e["worker"] for e in m["shards"]}
    assert workers == {"chaos-a", "chaos-b"}
    reclaimed = [e for e in m["shards"] if e.get("reclaimed")]
    assert len(reclaimed) == 1                   # the abandoned shard
    assert reclaimed[0]["worker"] == "chaos-b"
    # the run report records the lease lifecycle and the ladder
    with open(wd / mf.REPORT_NAME, "rb") as f:
        rep = json.loads(f.read())
    assert obs_report.validate_report(rep) == []
    assert rep["faults"]["lease.reclaimed"] >= 1
    assert rep["faults"]["injected.exec.polish"] == 1
    assert rep["faults"]["transient-io"] == 1
    assert any(r.get("attempts") for r in rep["shards"])


def test_two_workers_racing_one_manifest(assembly, tmp_path):
    """Two independently-launched workers start concurrently on an
    empty work dir: exactly one publishes the plan (atomic
    create-if-absent), both drain under lease exclusion, and both
    merged outputs are byte-identical to the single-shot run."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    wd = tmp_path / "w"
    env = {"RACON_TPU_EXEC_SLEEP_S": "0.5",
           "RACON_TPU_EXEC_LEASE_TTL_S": "60"}
    procs = [subprocess.Popen(
        _cli_args(rp, pp, lp, wd, "--resume"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO_ROOT,
        env=_cli_env(RACON_TPU_WORKER=f"race-{k}", **env))
        for k in range(2)]
    outs = []
    for proc in procs:
        out, err = proc.communicate(timeout=600)
        assert proc.returncode == 0, err.decode()[-2000:]
        outs.append(out)
    assert outs[0] == want
    assert outs[1] == want
    m = load_manifest(str(wd))
    assert all(e["status"] == "done" for e in m["shards"])


# ------------------------------------------------- review-fix regressions

def test_release_after_reclaim_preserves_new_lease(tmp_path):
    """A worker whose lease was broken must not, on release, unlink the
    reclaimer's lease at the same path (that would expose the shard to
    double-claims)."""
    wd = str(tmp_path)
    a = lease.try_claim(wd, 0, "worker-a", ttl_s=0.1)
    a._keeper.stop()
    a._keeper = None
    time.sleep(0.25)
    b = lease.try_claim(wd, 0, "worker-b", ttl_s=0.1)
    assert b is not None
    a.release()  # late release by the presumed-dead owner
    assert lease.read_lease(wd, 0)["worker"] == "worker-b"
    assert a.lost.is_set()
    b.release()
    assert lease.read_lease(wd, 0) is None


def test_corrupt_manifest_create_race_single_plan_wins(tmp_path):
    """With a corrupt manifest on disk, racing workers must converge on
    ONE published plan (each installing its own would cut parts by
    different shard maps against one merge)."""
    wd = str(tmp_path)
    with open(os.path.join(wd, mf.MANIFEST_NAME), "wb") as f:
        f.write(b'{"torn":')  # corrupt leftovers of a killed run
    results = []
    barrier = threading.Barrier(4)

    def publish(k):
        mine = {"fingerprint": {"k": "same"},
                "shards": [{"id": 0, "contigs": [0], "status": "pending",
                            "part": "part_0000.fasta",
                            "planner": f"worker-{k}"}]}
        barrier.wait()
        results.append(mf.create_manifest_if_absent(wd, mine))

    threads = [threading.Thread(target=publish, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    planners = {r["shards"][0]["planner"] for r in results}
    assert len(planners) == 1        # every worker adopted one plan
    on_disk = mf.load_manifest(wd)
    assert on_disk["shards"][0]["planner"] in planners


def test_stale_write_suppressed_after_lease_break(tmp_path, assembly,
                                                  monkeypatch, capfd):
    """A worker that finishes a shard AFTER its lease was broken must
    not overwrite the reclaimer's state (its late quarantine would
    silently drop a successfully polished shard from the merge)."""
    rp, pp, lp = assembly
    want = single_shot(rp, pp, lp)
    got, summary, runner = sharded(rp, pp, lp, tmp_path / "w",
                                   n_shards=2, keep_work_dir=True)
    assert got == want
    # simulate the split-brain tail: the old owner holds a broken lease
    # and tries to record a late quarantine over the reclaimer's DONE
    entry = dict(summary["shards"][0], status="quarantined",
                 reason="late loser")
    stale = lease.Lease(str(tmp_path / "w"), 0, "old-owner")
    stale.lost.set()
    manifest = load_manifest(str(tmp_path / "w"))
    runner._save_owned(entry, manifest, stale)
    m = load_manifest(str(tmp_path / "w"))
    assert m["shards"][0]["status"] == "done"   # reclaimer's truth stands
    assert entry["status"] == "done"            # loser adopted it
    assert "discarding its late" in capfd.readouterr().err


def test_fresh_run_refuses_to_clean_live_run_dir(tmp_path, assembly):
    """A plain (non --resume) launch into a shard dir where another
    worker holds a live lease must refuse instead of destroying the
    running worker's checkpoints."""
    rp, pp, lp = assembly
    wd = tmp_path / "w"
    os.makedirs(wd)
    live = lease.try_claim(str(wd), 0, "other-worker")
    assert live is not None
    with pytest.raises(RuntimeError, match="live shard lease"):
        sharded(rp, pp, lp, wd, n_shards=2)  # fresh run, same dir
    live.release()
    # with the lease gone the same fresh run proceeds normally
    got, _, _ = sharded(rp, pp, lp, wd, n_shards=2)
    assert got == single_shot(rp, pp, lp)
