"""Fleet serving (round 20): the multi-tenant gateway, weighted-fair
tenant scheduling, and lease-backed placement across serve hosts.

Acceptance contract at test scale: jobs submitted through the gateway
come back **byte-identical** to the equivalent one-shot CLI run;
tenants drain in weight proportion and per-tenant budgets reject with
a reason; a gateway restart recovers journaled jobs (done-but-
uncollected results serve from the fleet spool with ZERO hosts — no
re-polish by construction); a SIGKILLed member's leased jobs are
broken and re-placed on survivors with zero lost and zero duplicated
results; and a high-priority job preempts a running lower-priority
one by DRAINING it back to the queue at a ladder boundary, never
killing it mid-window.  The ``gateway.accept`` and ``fleet.place``
fault sites are exercised with the real injection grammar.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from racon_tpu.exec.planner import cached_job_cost
from racon_tpu.fleet.gateway import Gateway, parse_gateway_address
from racon_tpu.fleet.registry import HostBeacon, host_ttl_s, read_hosts
from racon_tpu.fleet.tenants import TenantScheduler, parse_tenants
from racon_tpu.obs import metrics
from racon_tpu.serve.client import ServiceClient, parse_tcp_address
from racon_tpu.serve.service import PolishServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -------------------------------------------------------------- workloads

def _assembly(td, sizes, seed=31, prefix="a"):
    """Synthetic per-contig assembly triple (the test_serve generator,
    re-homed so the fleet tests stand alone)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    comp = bytes.maketrans(b"ACGT", b"TGCA")

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, n)] for n in sizes]
    layout = os.path.join(td, f"{prefix}_layout.fasta")
    with open(layout, "wb") as f:
        for ti, t in enumerate(truths):
            f.write(b">ctg%d\n" % ti + mutate(t, 0.06).tobytes() + b"\n")
    reads = os.path.join(td, f"{prefix}_reads.fastq")
    paf = os.path.join(td, f"{prefix}_ovl.paf")
    with open(reads, "wb") as rf, open(paf, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            contig = len(truth)
            for start in range(0, max(1, contig - 600), 150):
                end = min(start + 900, contig)
                read = mutate(truth[start:end], 0.08)
                name = b"%s_read%d" % (prefix.encode(), ri)
                strand = b"-" if ri % 3 == 0 else b"+"
                rb = (read.tobytes().translate(comp)[::-1]
                      if strand == b"-" else read.tobytes())
                rf.write(b"@" + name + b"\n" + rb + b"\n+\n"
                         + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    strand, b"ctg%d" % ti, b"%d" % contig,
                    b"%d" % start, b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1
    return reads, paf, layout


def _spec(reads, paf, layout, **opts):
    spec = {"sequences": reads, "overlaps": paf,
            "target_sequences": layout, "window_length": 150,
            "threads": 2}
    spec.update(opts)
    return spec


def _oneshot_cli(reads, paf, layout, *extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-w", "150", "-t", "2",
         *extra, reads, paf, layout],
        capture_output=True, timeout=600, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc.stdout


@pytest.fixture()
def short_tmp():
    """AF_UNIX socket paths are length-bounded (~107 bytes); sockets
    live in a short /tmp dir."""
    with tempfile.TemporaryDirectory(dir="/tmp", prefix="rfl") as td:
        yield td


@pytest.fixture()
def fast_fleet(monkeypatch):
    """Test-scale fleet timing: tight heartbeat TTL and placement
    poll so membership transitions happen in test time, no warm-shape
    startup compiles."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setenv("RACON_TPU_FLEET_HOST_TTL_S", "1.0")
    monkeypatch.setenv("RACON_TPU_FLEET_POLL_S", "0.05")
    yield monkeypatch


class _Host:
    """In-process fleet member: a PolishServer with a --fleet-dir
    beacon, serve_forever on a thread."""

    def __init__(self, td, name, fleet_dir, **kw):
        self.server = PolishServer(os.path.join(td, f"{name}.sock"),
                                   fleet_dir=fleet_dir, **kw)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    def __enter__(self):
        self.thread.start()
        assert self.server.started.wait(60), "host did not start"
        return self.server

    def __exit__(self, exc_type, exc, tb):
        self.server.shutdown()
        self.thread.join(timeout=30)
        return False


class _Gate:
    """In-process gateway harness on an ephemeral TCP port."""

    def __init__(self, fleet_dir, **kw):
        self.gateway = Gateway("127.0.0.1:0", fleet_dir, **kw)
        self.thread = threading.Thread(
            target=self.gateway.serve_forever, daemon=True)

    def __enter__(self):
        self.thread.start()
        assert self.gateway.started.wait(60), "gateway did not start"
        self.address = f"127.0.0.1:{self.gateway.port}"
        return self

    def __exit__(self, exc_type, exc, tb):
        self.gateway.shutdown("now")
        self.thread.join(timeout=30)
        return False

    def client(self, timeout_s=300.0):
        return ServiceClient(self.address, timeout_s=timeout_s)

    def wait_hosts(self, n, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self.client(timeout_s=10.0) as c:
                if c.ping().get("hosts", {}).get("alive", 0) >= n:
                    return
            time.sleep(0.05)
        raise AssertionError(f"{n} hosts never registered")


def _journal_records(fleet_dir):
    path = os.path.join(fleet_dir, "journal.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "rb") as f:
        for line in f.read().splitlines():
            if line.strip():
                out.append(json.loads(line))
    return out


# ------------------------------------------------- tenant scheduler units

def test_parse_tenants_grammar():
    cfg = parse_tenants("alpha:3,beta:1:512M, gamma:2.5:1G")
    assert cfg["alpha"] == (3.0, 0)
    assert cfg["beta"] == (1.0, 512 << 20)
    assert cfg["gamma"] == (2.5, 1 << 30)
    assert parse_tenants("") == {}
    for bad in ("alpha", "alpha:x", "alpha:0", "alpha:-1", ":3",
                "alpha:1:2:3"):
        with pytest.raises(ValueError):
            parse_tenants(bad)


def test_stride_weighted_fairness():
    """alpha:3 vs beta:1 drains 3:1 over any window, and an idle
    tenant does not bank credit to later monopolize."""
    sched = TenantScheduler(parse_tenants("alpha:3,beta:1"))
    for i in range(12):
        sched.push("alpha", f"a{i}")
    for i in range(4):
        sched.push("beta", f"b{i}")
    first8 = [sched.pop()[0] for _ in range(8)]
    assert first8.count("alpha") == 6 and first8.count("beta") == 2
    # drain the rest, then let beta idle while alpha works: when beta
    # comes back it starts at the pass floor, not at zero
    while sched.pop() is not None:
        pass
    for i in range(20):
        sched.push("alpha", f"a2{i}")
    for _ in range(10):
        assert sched.pop()[0] == "alpha"
    sched.push("beta", "late")
    order = [sched.pop()[0] for _ in range(4)]
    # beta gets its fair turn promptly but cannot claim every slot
    assert "beta" in order and order.count("alpha") >= 2


def test_priority_and_requeue_ordering():
    sched = TenantScheduler()
    sched.push("t", "low1", priority=0)
    sched.push("t", "hi", priority=5)
    sched.push("t", "low2", priority=0)
    assert sched.peek_priority() == ("t", 5, "hi")
    assert sched.pop() == ("t", "hi")
    assert sched.pop() == ("t", "low1")
    # a drained/migrated job re-enters at the FRONT of its class
    sched.push("t", "low3", priority=0)
    sched.requeue("t", "drained", priority=0)
    assert sched.pop() == ("t", "drained")
    assert sched.remove("t", "low3")
    assert not sched.remove("t", "low3")
    assert sched.pop() == ("t", "low2")
    assert len(sched) == 0 and sched.depths() == {}


def test_budget_admit_check_rejects_with_reason():
    sched = TenantScheduler(parse_tenants("cap:1:10M"))
    assert sched.admit_check("cap", 6 << 20) is None
    sched.charge("cap", 6 << 20)
    reason = sched.admit_check("cap", 6 << 20)
    assert reason is not None and "budget exhausted" in reason
    assert "cap" in reason and "RACON_TPU_FLEET_TENANTS" in reason
    sched.uncharge("cap", 6 << 20)
    assert sched.admit_check("cap", 6 << 20) is None
    # unknown tenants are unbounded (weight 1, no budget)
    assert sched.admit_check("stranger", 1 << 40) is None


# --------------------------------------------------------- host registry

def test_host_beacon_lifecycle(short_tmp, fast_fleet):
    """announce -> alive; stale mtime -> not alive; stop -> withdrawn
    (the explicit goodbye the gateway sees before any TTL)."""
    beacon = HostBeacon(short_tmp, socket_path="/tmp/h0.sock",
                        name="h0").start()
    try:
        hosts = read_hosts(short_tmp)
        assert "h0" in hosts and hosts["h0"]["alive"]
        assert hosts["h0"]["socket"] == "/tmp/h0.sock"
        # a beacon stale past the TTL reads as not-alive
        stale = time.time() - 10 * host_ttl_s()
        os.utime(beacon.path, (stale, stale))
        assert not read_hosts(short_tmp)["h0"]["alive"]
        # ...and the keeper heals it within an interval
        deadline = time.monotonic() + 10
        while not read_hosts(short_tmp).get("h0", {}).get("alive"):
            assert time.monotonic() < deadline, \
                "beacon keeper never refreshed the heartbeat"
            time.sleep(0.05)
    finally:
        beacon.stop()
    assert "h0" not in read_hosts(short_tmp)


def test_gateway_address_parsing():
    assert parse_gateway_address("127.0.0.1:9000") == \
        ("127.0.0.1", 9000)
    assert parse_gateway_address(":0") == ("127.0.0.1", 0)
    for bad in ("nope", "host:port", "host:-1"):
        with pytest.raises(ValueError):
            parse_gateway_address(bad)
    # the client disambiguates TCP addresses from unix socket paths
    assert parse_tcp_address("127.0.0.1:9000") == ("127.0.0.1", 9000)
    assert parse_tcp_address("/tmp/racon.sock") is None
    assert parse_tcp_address("racon.sock:9000") == ("racon.sock", 9000)


# ------------------------------------------------- cost-estimate caching

def test_cost_cache_fingerprint(short_tmp):
    """Repeat estimates of one spec hit the content-fingerprint cache;
    rewriting an input invalidates it (satellite: fleet.cost_cache_*
    counters)."""
    reads, paf, layout = _assembly(short_tmp, [1200], seed=7,
                                   prefix="cc")
    h0 = metrics.counter("fleet.cost_cache_hits")
    m0 = metrics.counter("fleet.cost_cache_misses")
    cost = cached_job_cost(reads, paf, layout)
    assert cost > 0
    assert cached_job_cost(reads, paf, layout) == cost
    assert metrics.counter("fleet.cost_cache_hits") == h0 + 1
    assert metrics.counter("fleet.cost_cache_misses") == m0 + 1
    # an in-place rewrite changes (size, mtime_ns): natural miss
    with open(reads, "ab") as f:
        f.write(b"")
    os.utime(reads, (time.time() + 5, time.time() + 5))
    assert cached_job_cost(reads, paf, layout) == cost
    assert metrics.counter("fleet.cost_cache_misses") == m0 + 2


# --------------------------------------------------- gateway integration

def test_gateway_round_trip_byte_identity(short_tmp, fast_fleet):
    """Jobs through the gateway come back byte-identical to the
    one-shot CLI; idempotency keys dedupe fleet-wide; stats report
    per-tenant depths, budgets, host membership and fleet metrics."""
    fast_fleet.setenv("RACON_TPU_FLEET_TENANTS", "alpha:3,beta:1")
    reads, paf, layout = _assembly(short_tmp, [2000], prefix="rt")
    want = _oneshot_cli(reads, paf, layout)
    fleet_dir = os.path.join(short_tmp, "fleet")
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2), \
            _Host(short_tmp, "h1", fleet_dir, num_threads=2), \
            _Gate(fleet_dir) as gate:
        gate.wait_hosts(2)
        with gate.client() as c:
            sub = c.submit(_spec(reads, paf, layout, tenant="alpha",
                                 priority=1), key="rt-1")
            assert sub["ok"] and sub["tenant"] == "alpha", sub
            header, payload = c.result(sub["job"], timeout_s=240)
            assert header["ok"] and header["state"] == "done", header
            assert payload == want, \
                "gateway result diverged from the one-shot CLI"
            assert header["host"] in ("h0", "h1")
            # retention: the payload is handed out once, and the
            # second fetch says WHY (stage is COLLECTED by now)
            again, payload2 = c.result(sub["job"], timeout_s=10)
            assert payload2 is None and not again["ok"]
            assert "already collected" in again["error"], again
            # fleet-wide idempotency: same key -> the existing job
            dup = c.submit(_spec(reads, paf, layout, tenant="alpha"),
                           key="rt-1")
            assert dup["ok"] and dup["existing"]
            assert dup["job"] == sub["job"]
            st = c.stats()
            assert st["ok"] and st["done"] >= 1
            assert st["hosts"]["alive"] == 2
            assert isinstance(st["tenants"], dict)
            assert isinstance(st["fleet"], dict)
        # the gateway journal holds the full lifecycle: submitted ->
        # running -> done -> collected, exactly once each
        recs = _journal_records(fleet_dir)
        by_kind = {}
        for r in recs:
            if r.get("job") == sub["job"]:
                by_kind[r["rec"]] = by_kind.get(r["rec"], 0) + 1
        assert by_kind.get("submitted") == 1
        assert by_kind.get("running") == 1
        assert by_kind.get("done") == 1
        assert by_kind.get("collected") == 1


def test_serve_stats_tenants_and_slots(short_tmp, fast_fleet):
    """The serve ``stats`` op (satellite): per-tenant queue depths and
    the worker-slot health summary."""
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="st")
    server = PolishServer(os.path.join(short_tmp, "racon.sock"),
                          num_threads=2)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    assert server.started.wait(60)
    try:
        with ServiceClient(server.socket_path) as c:
            # one running + two queued under distinct tenants: the
            # 1-slot server reports both queued tenants' depths
            first = c.submit(_spec(reads, paf, layout))
            assert first["ok"]
            a = c.submit(_spec(reads, paf, layout, tenant="alpha"))
            b = c.submit(_spec(reads, paf, layout, tenant="beta"))
            assert a["ok"] and b["ok"]
            st = c.stats()
            assert st["ok"]
            assert st["slots"] == {"healthy": 1, "quarantined": 0}
            depth = st["tenants"]
            assert depth.get("alpha", 0) + depth.get("beta", 0) >= 1
            for jid in (first["job"], a["job"], b["job"]):
                header, _ = c.result(jid, timeout_s=240)
                assert header["ok"], header
            st = c.stats()
            assert st["tenants"] == {}
            assert st["slots"]["healthy"] == 1
    finally:
        server.shutdown()
        thread.join(timeout=30)


def test_gateway_budget_rejects_with_reason(short_tmp, fast_fleet):
    """A tenant over budget is rejected with the reason (round-14
    admission contract at the fleet tier) and nothing is journaled."""
    fast_fleet.setenv("RACON_TPU_FLEET_TENANTS", "cap:1:1K")
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="bg")
    fleet_dir = os.path.join(short_tmp, "fleet")
    with _Gate(fleet_dir) as gate:
        with gate.client() as c:
            resp = c.submit(_spec(reads, paf, layout, tenant="cap"))
            assert not resp["ok"]
            assert "budget exhausted" in resp["error"]
            assert c.stats()["rejected"] == 1
    assert not any(r.get("rec") == "submitted"
                   for r in _journal_records(fleet_dir))


def test_gateway_accept_fault_keyed_retry(short_tmp, fast_fleet):
    """The ``gateway.accept`` fault site: an accept-path fault fires
    BEFORE the journal write and ack, so the connection dies pre-ack
    and the client's keyed retry lands exactly one job."""
    fast_fleet.setenv("RACON_TPU_FAULTS", "gateway.accept:err@1")
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="ga")
    fleet_dir = os.path.join(short_tmp, "fleet")
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2), \
            _Gate(fleet_dir) as gate:
        gate.wait_hosts(1)
        c = gate.client()
        try:
            spec = _spec(reads, paf, layout)
            with pytest.raises((ConnectionError, OSError)):
                c.submit(spec, key="ga-1")
            c.reconnect()
            resub = c.submit(spec, key="ga-1")
            assert resub["ok"] and not resub["existing"], resub
            header, payload = c.result(resub["job"], timeout_s=240)
            assert header["ok"] and payload.startswith(b">ctg0")
            # the faulted first attempt died BEFORE the journal
            # write: exactly one submitted record exists (read before
            # shutdown compacts the collected job away)
            subs = [r for r in _journal_records(fleet_dir)
                    if r.get("rec") == "submitted"]
            assert len(subs) == 1 and subs[0]["key"] == "ga-1"
        finally:
            c.close()


def test_fleet_place_fault_requeues_and_retries(short_tmp, fast_fleet):
    """The ``fleet.place`` fault site: a placement attempt that dies
    mid-flight requeues the job and the next tick places it — the
    client never notices."""
    fast_fleet.setenv("RACON_TPU_FAULTS", "fleet.place:io@1")
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="fp")
    fleet_dir = os.path.join(short_tmp, "fleet")
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2), \
            _Gate(fleet_dir) as gate:
        gate.wait_hosts(1)
        with gate.client() as c:
            sub = c.submit(_spec(reads, paf, layout))
            assert sub["ok"]
            header, payload = c.result(sub["job"], timeout_s=240)
            assert header["ok"], header
            assert payload.startswith(b">ctg0")
            assert metrics.counter("faults.injected.fleet.place") >= 1


def test_gateway_restart_serves_done_from_spool(short_tmp, fast_fleet):
    """Gateway crash-restart (round-16 semantics at the fleet tier): a
    job done-but-uncollected at shutdown is served by the restarted
    gateway from the fleet spool — with ZERO hosts running, so the
    absence of re-polish is structural, not statistical."""
    reads, paf, layout = _assembly(short_tmp, [2000], prefix="rc")
    want = _oneshot_cli(reads, paf, layout)
    fleet_dir = os.path.join(short_tmp, "fleet")
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2):
        with _Gate(fleet_dir) as gate:
            gate.wait_hosts(1)
            with gate.client() as c:
                sub = c.submit(_spec(reads, paf, layout), key="rc-1")
                assert sub["ok"]
                jid = sub["job"]
                deadline = time.monotonic() + 240
                while True:
                    st = c.status(jid)
                    if st.get("state") == "done":
                        break
                    assert st.get("state") not in ("failed",
                                                   "cancelled"), st
                    assert time.monotonic() < deadline
                    time.sleep(0.1)
    # every host is down; a fresh gateway on the same fleet-dir must
    # still serve the spooled result byte-identically
    with _Gate(fleet_dir) as gate:
        with gate.client() as c:
            dup = c.submit(_spec(reads, paf, layout), key="rc-1")
            assert dup["ok"] and dup["existing"] and dup["job"] == jid
            header, payload = c.result(jid, timeout_s=60)
            assert header["ok"], header
            assert payload == want, \
                "recovered fleet result diverged from the one-shot CLI"


def test_gateway_shutdown_now_requeues_on_restart(short_tmp,
                                                  fast_fleet):
    """``shutdown now`` with jobs still queued: the RAM answer is
    FAILED, but the compacted journal keeps them LIVE (submitted, no
    failed record) — the restarted gateway re-queues and runs them,
    exactly what the shutdown docstring and the client error text
    promise."""
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="sn")
    fleet_dir = os.path.join(short_tmp, "fleet")
    # no hosts: the job is admitted and journaled but never places
    with _Gate(fleet_dir) as gate:
        with gate.client() as c:
            sub = c.submit(_spec(reads, paf, layout), key="sn-1")
            assert sub["ok"]
            jid = sub["job"]
    # hard stop compacted the journal: the queued job stays live
    recs = _journal_records(fleet_dir)
    kinds = [r["rec"] for r in recs if r.get("job") == jid]
    assert "submitted" in kinds
    assert "failed" not in kinds, \
        "shutdown(now) made a queued job durably FAILED"
    # the restarted gateway re-queues it and a host runs it
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2), \
            _Gate(fleet_dir) as gate:
        assert gate.gateway.recovery["jobs_recovered"] >= 1
        gate.wait_hosts(1)
        with gate.client() as c:
            dup = c.submit(_spec(reads, paf, layout), key="sn-1")
            assert dup["ok"] and dup["existing"] and dup["job"] == jid
            header, payload = c.result(jid, timeout_s=240)
            assert header["ok"], header
            assert payload.startswith(b">ctg0")


def test_host_local_rejection_routes_to_another_host(short_tmp,
                                                     fast_fleet):
    """A host submit rejection that is HOST-LOCAL (here: a member
    started with a tiny --serve-budget) requeues the job and the next
    tick tries a different host — it must not terminally fail a job
    another member would accept."""
    reads, paf, layout = _assembly(short_tmp, [1500], prefix="hr")
    fleet_dir = os.path.join(short_tmp, "fleet")
    before = metrics.counter("fleet.reject_requeued")
    # "a0" sorts first for placement (2 free slots vs 1) but rejects
    # everything: its budget is one KB
    with _Host(short_tmp, "a0", fleet_dir, num_threads=2,
               budget_bytes=1024), \
            _Host(short_tmp, "z1", fleet_dir, num_threads=1), \
            _Gate(fleet_dir) as gate:
        gate.wait_hosts(2)
        with gate.client() as c:
            sub = c.submit(_spec(reads, paf, layout))
            assert sub["ok"]
            header, payload = c.result(sub["job"], timeout_s=240)
            assert header["ok"], header
            assert header["host"] == "z1"
            assert payload.startswith(b">ctg0")
    assert metrics.counter("fleet.reject_requeued") > before
    assert not any(r.get("rec") == "failed"
                   for r in _journal_records(fleet_dir))


def test_host_worker_cache_invalidation(short_tmp, fast_fleet):
    """The cached advertised-worker count drops when a host dies or
    re-registers under the same name (a restart may come back with
    fewer workers), and a first-ever-seen beacon already stale past
    the TTL walks the declared registered->dead edge."""
    fleet_dir = os.path.join(short_tmp, "fleet")
    hx = HostBeacon(fleet_dir, os.path.join(short_tmp, "hx.sock"))
    hy = HostBeacon(fleet_dir, os.path.join(short_tmp, "hy.sock"))
    stale = time.time() - 60
    gw = Gateway("127.0.0.1:0", fleet_dir)
    try:
        hx.announce()
        hy.announce()
        os.utime(hy.path, (stale, stale))
        gw._refresh_hosts()
        # hy was stale on FIRST sight: registered -> dead, asserted
        # against the placement machine (no silent contract drift)
        assert gw._host_stage["hx"] == "alive"
        assert gw._host_stage["hy"] == "dead"
        # dead -> the cached worker count is dropped
        gw._host_workers["hx"] = (4, time.monotonic())
        os.utime(hx.path, (stale, stale))
        gw._refresh_hosts()
        assert gw._host_stage["hx"] == "dead"
        assert "hx" not in gw._host_workers
        # same name, new incarnation (registered_unix moves): the
        # dead -> alive edge re-learns the count too
        time.sleep(0.01)
        hx.announce()
        gw._refresh_hosts()
        assert gw._host_stage["hx"] == "alive"
        gw._host_workers["hx"] = (4, time.monotonic())
        time.sleep(0.01)
        hx.announce()  # restarted again while alive
        gw._refresh_hosts()
        assert "hx" not in gw._host_workers
    finally:
        gw._journal.close()


def test_fleet_preemption_chaos(short_tmp, fast_fleet):
    """Priority preemption drains, never kills: a low-priority job
    caught in a transient-retry backoff is drained back to the queue
    at the ladder boundary, the high-priority job takes the slot, and
    BOTH complete byte-identically (the victim on a fresh placement
    incarnation)."""
    # the victim's first polish attempt fails transient-io and sits in
    # a ~3-5s backoff — the deterministic drain window
    fast_fleet.setenv("RACON_TPU_FAULTS", "serve.polish:io@1")
    fast_fleet.setenv("RACON_TPU_EXEC_BACKOFF_S", "4.0")
    reads, paf, layout = _assembly(short_tmp, [2000], prefix="pr")
    want = _oneshot_cli(reads, paf, layout)
    fleet_dir = os.path.join(short_tmp, "fleet")
    done_at = {}
    with _Host(short_tmp, "h0", fleet_dir, num_threads=2), \
            _Gate(fleet_dir) as gate:
        gate.wait_hosts(1)
        with gate.client() as c:
            victim = c.submit(_spec(reads, paf, layout, priority=0),
                              key="pr-victim")
            assert victim["ok"]
            deadline = time.monotonic() + 60
            while c.status(victim["job"]).get("state") != "placed":
                assert time.monotonic() < deadline, \
                    "victim was never placed"
                time.sleep(0.02)
            time.sleep(0.5)  # let the host fail attempt 1 into backoff
            urgent = c.submit(_spec(reads, paf, layout, priority=5),
                              key="pr-urgent")
            assert urgent["ok"]

        def fetch(jid, label):
            with gate.client() as c2:
                header, payload = c2.result(jid, timeout_s=240)
            assert header.get("ok"), (label, header)
            assert payload == want, \
                f"{label} result diverged from the one-shot CLI"
            done_at[label] = time.monotonic()

        threads = [threading.Thread(target=fetch, args=args)
                   for args in ((urgent["job"], "urgent"),
                                (victim["job"], "victim"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
            assert not t.is_alive(), "a fetch never completed"
        with gate.client() as c:
            st = c.stats()
            assert st["preempted"] >= 1, st
            row = c.status(victim["job"])
            assert row.get("state") == "collected"
        # the victim's journal trail shows two placement incarnations
        # under DIFFERENT host keys (a cancelled answer must never be
        # inherited by the re-placement); read before shutdown
        # compacts the collected jobs away
        runs = [r for r in _journal_records(fleet_dir)
                if r.get("rec") == "running"
                and r["job"] == victim["job"]]
        assert len(runs) >= 2
        assert runs[0]["hkey"] != runs[-1]["hkey"]
    assert done_at["urgent"] < done_at["victim"], (
        "the high-priority job should finish before the drained "
        "victim's re-run")


def test_fleet_migration_chaos_kill_host(short_tmp, fast_fleet):
    """THE fleet crash contract: SIGKILL a member with a leased job in
    flight — the gateway breaks the dead host's lease and re-places
    the job on a survivor, every result byte-identical, zero lost,
    zero duplicated."""
    reads, paf, layout = _assembly(short_tmp, [2000], prefix="mg")
    want = _oneshot_cli(reads, paf, layout)
    fleet_dir = os.path.join(short_tmp, "fleet")
    sick_sock = os.path.join(short_tmp, "sick.sock")
    log_path = os.path.join(short_tmp, "sick.log")
    # the doomed member is a real subprocess (so SIGKILL is a real
    # SIGKILL) wedged by an every-attempt transient fault with a huge
    # backoff: any job placed on it stays leased-and-running until
    # the kill
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_SERVE_WARM_SHAPES="",
               RACON_TPU_FLEET_HOST_TTL_S="1.0",
               RACON_TPU_FAULTS="serve.polish:io@1*",
               RACON_TPU_EXEC_BACKOFF_S="120")
    with open(log_path, "wb") as log:
        sick = subprocess.Popen(
            [sys.executable, "-m", "racon_tpu", "--serve", sick_sock,
             "--fleet-dir", fleet_dir, "-w", "150", "-t", "2"],
            cwd=REPO_ROOT, env=env, stderr=log)
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(sick_sock):
            assert time.monotonic() < deadline, \
                "sick host did not start"
            assert sick.poll() is None, "sick host died at startup"
            time.sleep(0.1)
        with _Gate(fleet_dir) as gate:
            gate.wait_hosts(1)
            with gate.client() as c:
                sub = c.submit(_spec(reads, paf, layout), key="mg-1")
                assert sub["ok"]
                jid = sub["job"]
                # wait until the job is leased and placed on the
                # doomed host
                deadline = time.monotonic() + 60
                while True:
                    row = c.status(jid)
                    if row.get("state") == "placed" and \
                            row.get("host") == "sick":
                        break
                    assert time.monotonic() < deadline, row
                    time.sleep(0.05)
            os.kill(sick.pid, signal.SIGKILL)
            sick.wait(timeout=30)
            # a healthy survivor joins AFTER the kill: the migration
            # target
            with _Host(short_tmp, "h1", fleet_dir, num_threads=2):
                gate.wait_hosts(1)
                with gate.client(timeout_s=300) as c:
                    header, payload = c.result(jid, timeout_s=240)
                    assert header["ok"], header
                    assert payload == want, (
                        "migrated result diverged from the one-shot "
                        "CLI")
                    st = c.stats()
                    assert st["migrated"] >= 1, st
                    assert st["hosts"]["dead"] >= 1, st
                    row = c.status(jid)
                    assert row.get("host") == "h1"
                    assert row.get("migrations", 0) >= 1
                # journal truth — zero lost, zero duplicated: one
                # submitted record, a running record per incarnation
                # (>=2: sick then survivor), exactly one done and one
                # collected (read before shutdown compacts the
                # collected job away)
                kinds = {}
                for r in _journal_records(fleet_dir):
                    if r.get("job") == jid:
                        kinds[r["rec"]] = kinds.get(r["rec"], 0) + 1
                assert kinds.get("submitted") == 1
                assert kinds.get("running", 0) >= 2
                assert kinds.get("done") == 1
                assert kinds.get("collected") == 1
    finally:
        if sick.poll() is None:
            sick.kill()
            sick.wait()


def test_gateway_cli_entry(short_tmp, fast_fleet):
    """``racon --gateway HOST:PORT --fleet-dir DIR`` and ``racon
    --submit host:port --tenant --priority`` wire the fleet end to
    end through the real CLI surface."""
    reads, paf, layout = _assembly(short_tmp, [1800], prefix="cl")
    want = _oneshot_cli(reads, paf, layout)
    fleet_dir = os.path.join(short_tmp, "fleet")
    import socket as socket_mod
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_SERVE_WARM_SHAPES="",
               RACON_TPU_FLEET_HOST_TTL_S="1.0",
               RACON_TPU_FLEET_POLL_S="0.05")
    with open(os.path.join(short_tmp, "gw.log"), "wb") as log:
        gw = subprocess.Popen(
            [sys.executable, "-m", "racon_tpu",
             "--gateway", f"127.0.0.1:{port}",
             "--fleet-dir", fleet_dir],
            cwd=REPO_ROOT, env=env, stderr=log)
    try:
        deadline = time.monotonic() + 120
        while True:
            assert gw.poll() is None, "gateway process died"
            assert time.monotonic() < deadline, \
                "gateway never answered"
            try:
                with ServiceClient(f"127.0.0.1:{port}", timeout_s=5,
                                   retries=0) as c:
                    if c.ping().get("ok"):
                        break
            except (OSError, ConnectionError):
                time.sleep(0.1)
        with _Host(short_tmp, "h0", fleet_dir, num_threads=2):
            proc = subprocess.run(
                [sys.executable, "-m", "racon_tpu",
                 "--submit", f"127.0.0.1:{port}",
                 "--tenant", "alpha", "--priority", "2",
                 "-w", "150", "-t", "2", reads, paf, layout],
                capture_output=True, timeout=600, cwd=REPO_ROOT,
                env=env)
            assert proc.returncode == 0, proc.stderr.decode()[-2000:]
            assert proc.stdout == want, (
                "--submit through the gateway diverged from the "
                "one-shot CLI")
        with ServiceClient(f"127.0.0.1:{port}", timeout_s=30) as c:
            c.shutdown("now")
        gw.wait(timeout=60)
    finally:
        if gw.poll() is None:
            gw.kill()
            gw.wait()
