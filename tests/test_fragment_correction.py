"""Fragment-correction (-f) goldens on the λ-phage all-vs-all overlaps.

Mirrors the reference's four correction tests
(``test/racon_test.cpp:220-290``): reads corrected against themselves with
ava overlaps, scores 1/-1/-1, w=500 q=10 e=0.3. The reference's exact
totals are quoted per scenario; ours is an independent reimplementation,
so we record our own exact totals and additionally assert they are within
0.1% of the reference's (the reference's own GPU engine diverges by a
similar margin: 1,655,505 vs CPU 1,658,216, ``racon_test.cpp:458``).

The full scenarios take ~2 min each on one core, so they are gated behind
RACON_TPU_SLOW=1 like the other slow goldens; a subset smoke test keeps
the ``-f`` code path exercised in every run.
"""

import gzip

import pytest

from racon_tpu import flags as racon_flags
from racon_tpu.core.polisher import PolisherType, create_polisher

RUN_SLOW = racon_flags.get_bool("RACON_TPU_SLOW")
slow = pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")


def correct(data_dir, reads, overlaps, type_, drop):
    p = create_polisher(
        str(data_dir / reads), str(data_dir / overlaps),
        str(data_dir / reads), type_,
        window_length=500, quality_threshold=10.0, error_threshold=0.3,
        match=1, mismatch=-1, gap=-1, num_threads=8)
    p.initialize()
    out = p.polish(drop)
    return len(out), sum(len(s.data) for s in out)


@slow
def test_fragment_correction_kc_ava(data_dir):
    n, total = correct(data_dir, "sample_reads.fastq.gz",
                       "sample_ava_overlaps.paf.gz", PolisherType.C, True)
    assert n == 39               # reference: 39
    assert total == 389342       # our golden; reference: 389394
    assert abs(total - 389394) <= 0.001 * 389394


@slow
def test_fragment_correction_kf_paf_qualities(data_dir):
    n, total = correct(data_dir, "sample_reads.fastq.gz",
                       "sample_ava_overlaps.paf.gz", PolisherType.F, False)
    assert n == 236              # reference: 236
    assert total == 1658842      # our golden; reference: 1658216
    assert abs(total - 1658216) <= 0.001 * 1658216


@slow
def test_fragment_correction_kf_paf_no_qualities(data_dir):
    n, total = correct(data_dir, "sample_reads.fasta.gz",
                       "sample_ava_overlaps.paf.gz", PolisherType.F, False)
    assert n == 236              # reference: 236
    assert total == 1664206      # our golden; reference: 1663982
    assert abs(total - 1663982) <= 0.001 * 1663982


@slow
def test_fragment_correction_kf_mhap_qualities(data_dir):
    n, total = correct(data_dir, "sample_reads.fastq.gz",
                       "sample_ava_overlaps.mhap.gz", PolisherType.F, False)
    assert n == 236              # reference: 236
    # identical to the PAF+qualities scenario, as in the reference
    assert total == 1658842      # our golden; reference: 1658216
    assert abs(total - 1658216) <= 0.001 * 1658216


def _subset_inputs(data_dir, tmp_path, n_reads=25):
    """First ``n_reads`` reads + their ava overlaps, written to tmp files
    (shared by the -f smoke and device-backend tests)."""
    import racon_tpu.io.parsers as parsers

    reads = []
    for rec in parsers.parse_fastq(str(data_dir / "sample_reads.fastq.gz")):
        reads.append(rec)
        if len(reads) >= n_reads:
            break
    names = {r.name.split()[0] for r in reads}

    reads_path = tmp_path / "subset.fastq"
    with open(reads_path, "wb") as f:
        for r in reads:
            f.write(b"@" + r.name + b"\n" + r.data + b"\n+\n" + r.quality
                    + b"\n")

    ovl_path = tmp_path / "subset.paf"
    kept = 0
    with gzip.open(data_dir / "sample_ava_overlaps.paf.gz", "rb") as f, \
            open(ovl_path, "wb") as out:
        for line in f:
            cols = line.split(b"\t")
            if cols[0] in names and cols[5] in names:
                out.write(line)
                kept += 1
    assert kept > 10
    return reads_path, ovl_path, names


def test_fragment_correction_smoke(data_dir, tmp_path):
    """Fast -f smoke: correct the first 25 reads against themselves using
    only their ava overlaps; exercises the kF keep-all-overlaps filter,
    dual-strand layers, and the 'r' output tag in every test run."""
    reads_path, ovl_path, names = _subset_inputs(data_dir, tmp_path)

    p = create_polisher(str(reads_path), str(ovl_path), str(reads_path),
                        PolisherType.F, window_length=500,
                        quality_threshold=10.0, error_threshold=0.3,
                        match=1, mismatch=-1, gap=-1, num_threads=4)
    p.initialize()
    out = p.polish(False)
    assert len(out) == 25        # drop=False keeps every target
    assert all(b"r LN:i:" in s.name for s in out)  # kF tags
    corrected = [s for s in out if b"XC:f:0.000000" not in s.name]
    assert len(corrected) > 5


@slow
def test_fragment_correction_device_backend(data_dir, tmp_path):
    """-f through the device consensus engine (-c analog) on a 25-read
    subset: per-read windows run on the accelerated pileup engine with
    CPU fallback for thin pileups; read count must match the CPU engine
    exactly and total corrected bases stay close to the CPU engine
    (5% band: shallow 25-read pileups amplify the engines' intrinsic
    divergence — the full-set reference analog is cudapoa kF 1,655,505
    vs spoa 1,658,216 = 0.17%). Default scores on both engines so the
    device threshold mapping is at identity."""
    reads_path, ovl_path, _ = _subset_inputs(data_dir, tmp_path)

    def run(backend):
        p = create_polisher(str(reads_path), str(ovl_path),
                            str(reads_path), PolisherType.F,
                            num_threads=4, consensus_backend=backend)
        p.initialize()
        return p, p.polish(True)

    _, cpu_out = run("auto")
    p_dev, dev_out = run("tpu")
    assert p_dev.consensus.stats["device_windows"] > 0
    assert len(dev_out) == len(cpu_out)
    cpu_total = sum(len(s.data) for s in cpu_out)
    dev_total = sum(len(s.data) for s in dev_out)
    assert abs(dev_total - cpu_total) <= 0.05 * cpu_total


def correct_device(data_dir, reads, overlaps, type_, drop):
    """Full-set fragment correction through BOTH device engines (tpu
    aligner + tpu consensus), like the reference's GPU correction tests
    (racon_test.cpp:424-496)."""
    p = create_polisher(
        str(data_dir / reads), str(data_dir / overlaps),
        str(data_dir / reads), type_,
        window_length=500, quality_threshold=10.0, error_threshold=0.3,
        match=1, mismatch=-1, gap=-1, num_threads=8,
        consensus_backend="tpu", aligner_backend="tpu")
    p.initialize()
    out = p.polish(drop)
    assert p.consensus.stats["device_windows"] > 0
    return len(out), sum(len(s.data) for s in out)


@slow
def test_fragment_correction_device_kc_ava(data_dir):
    n, total = correct_device(data_dir, "sample_reads.fastq.gz",
                              "sample_ava_overlaps.paf.gz",
                              PolisherType.C, True)
    assert n == 39           # reference CUDA: 39 / 385,543
    assert total == 390039   # device golden (our CPU: 389,342)


@slow
def test_fragment_correction_device_kf_paf_q(data_dir):
    n, total = correct_device(data_dir, "sample_reads.fastq.gz",
                              "sample_ava_overlaps.paf.gz",
                              PolisherType.F, False)
    assert n == 236          # reference CUDA: 236 / 1,655,505
    assert total == 1656553  # device golden (our CPU: 1,658,842)


@slow
def test_fragment_correction_device_kf_paf_no_q(data_dir):
    n, total = correct_device(data_dir, "sample_reads.fasta.gz",
                              "sample_ava_overlaps.paf.gz",
                              PolisherType.F, False)
    assert n == 236          # reference CUDA: 236 / 1,663,732
    assert total == 1652942  # device golden (our CPU: 1,664,206)


@slow
def test_fragment_correction_device_kf_mhap(data_dir):
    n, total = correct_device(data_dir, "sample_reads.fastq.gz",
                              "sample_ava_overlaps.mhap.gz",
                              PolisherType.F, False)
    assert n == 236          # identical to PAF+qualities, as upstream
    assert total == 1656553  # device golden
