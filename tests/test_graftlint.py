"""graftlint (tools/analysis) — rule self-tests, pragma semantics, the
repo-wide zero-findings gate, and the flags-registry contract."""

import pathlib
import subprocess
import sys

import pytest

from racon_tpu import flags

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_selftest_fixtures():
    """Every rule fires on its seeded fixture and stays quiet on the
    clean twin (exact counts — see tools/analysis/selftest.py)."""
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis.selftest import run_selftest
        assert run_selftest(verbose=False) == 0
    finally:
        sys.path.remove(str(REPO))


def test_repo_is_clean():
    """The acceptance gate: zero unsuppressed findings over racon_tpu/
    (and the support trees CI lints)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--quiet",
         "racon_tpu", "tests", "tools", "bench.py"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_pragma_without_reason_does_not_suppress(tmp_path):
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis import run
        bad = tmp_path / "m.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # graftlint: disable=swallowed-exception\n"
            "        pass\n")
        reported, suppressed = run([str(bad)], scoped=False)
        assert len(reported) == 1 and not suppressed
        assert "missing its (reason)" in reported[0].message

        good = tmp_path / "ok.py"
        good.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:"
            "  # graftlint: disable=swallowed-exception (why)\n"
            "        pass\n")
        reported, suppressed = run([str(good)], scoped=False)
        assert not reported and len(suppressed) == 1
    finally:
        sys.path.remove(str(REPO))


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "m.py"
    bad.write_text("import os\n"
                   "x = os.environ.get('RACON_TPU_BOGUS', '')\n")
    rc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--quiet", str(bad)],
        cwd=REPO, capture_output=True, text=True)
    assert rc.returncode == 1
    assert "env-flag-registry" in rc.stdout


def test_json_output(tmp_path):
    """--json emits one machine-readable record per finding (rule,
    path, line, message, pragma state) for CI annotation."""
    import json

    src = tmp_path / "m.py"
    src.write_text(
        "import os\n"
        "x = os.environ.get('RACON_TPU_BOGUS', '')\n"
        "y = os.environ.get('RACON_TPU_ALSO', '')"
        "  # graftlint: disable=env-flag-registry (json fixture)\n")
    rc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--json", "--quiet",
         str(src)],
        cwd=REPO, capture_output=True, text=True)
    assert rc.returncode == 1
    data = json.loads(rc.stdout)
    assert len(data["findings"]) == 1
    f = data["findings"][0]
    assert f["rule"] == "env-flag-registry" and f["line"] == 2
    assert f["path"].endswith("m.py") and f["pragma"] is None
    assert "RACON_TPU_BOGUS" in f["message"]
    sup = data["suppressed"]
    assert len(sup) == 1 and sup[0]["pragma"] == "json fixture"


# ------------------------------------------------------- concurrency layer

def test_thread_entry_point_discovery():
    """Regression: the analyzer's thread discovery must see the repo's
    real concurrent surface — the chip-worker drain closure, the serve
    connection/worker/heartbeat threads, the lease keeper, and the
    pipelined polisher's producer."""
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis import load_project
        project = load_project([str(REPO / "racon_tpu")])
        roots = {fi.qualname for fi in project.thread_roots()}
    finally:
        sys.path.remove(str(REPO))
    expected = {
        "ShardRunner._drain.body",        # in-process chip workers
        "PolishServer._handle_conn",      # serve connection handlers
        "PolishServer._worker_loop",      # serve job workers
        "PolishServer._heartbeat_loop",
        "LeaseKeeper._run",               # lease mtime keeper
        "Heartbeat._tick",
        "QueueWatchdog._watch",
        "Polisher.run.produce",           # pipelined layer producer
    }
    assert expected <= roots, f"missing thread roots: {expected - roots}"


def test_exec_contexts_see_chip_worker_and_main():
    """The drain loop runs both on the main thread (single-slot) and on
    chip-worker threads — the context propagation must see both, which
    is exactly what arms lock-discipline over the shared manifest."""
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis import load_project
        from tools.analysis.astutil import MAIN_CONTEXT
        project = load_project([str(REPO / "racon_tpu")])
        ctx = project.exec_contexts()
        by_qual = {fi.qualname: ctx[id(fi)] for fi in project.functions}
    finally:
        sys.path.remove(str(REPO))
    drain_ctx = by_qual["ShardRunner._drain_loop_inner"]
    assert MAIN_CONTEXT in drain_ctx
    assert "thread:ShardRunner._drain.body" in drain_ctx


def test_every_pragma_carries_a_reason():
    """Repo-wide audit: a pragma without a (reason) does not suppress,
    so any reasonless pragma is dead weight that silently stops
    documenting its escape — fail it here, at the source."""
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis import EXCLUDE_PARTS, pragma_rules
    finally:
        sys.path.remove(str(REPO))
    bad = []
    for path in sorted(REPO.rglob("*.py")):
        # fixtures stay out: seeded-violation files deliberately carry
        # a reasonless pragma to prove it does NOT suppress
        if set(path.parts) & EXCLUDE_PARTS:
            continue
        for i, line in enumerate(
                path.read_text(encoding="utf-8").splitlines(), 1):
            if "graftlint" not in line or "disable=" not in line:
                continue
            parsed = pragma_rules(line)
            if parsed is not None and not parsed[1].strip():
                bad.append(f"{path.relative_to(REPO)}:{i}")
    assert not bad, f"pragmas without a reason: {bad}"


# ------------------------------------------------------------ flags registry

def test_undeclared_flag_raises():
    with pytest.raises(KeyError, match="not declared"):
        # graftlint: disable=env-flag-registry (negative test: must raise)
        flags.get_bool("RACON_TPU_NOT_A_FLAG")


def test_declared_flags_have_docs():
    for f in flags.REGISTRY.values():
        assert f.name.startswith("RACON_TPU_")
        assert f.help.strip()


def test_bool_semantics(monkeypatch):
    monkeypatch.setenv("RACON_TPU_SWAR", "0")
    assert not flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.setenv("RACON_TPU_SWAR", "off")
    assert not flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.setenv("RACON_TPU_SWAR", "1")
    assert flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.delenv("RACON_TPU_SWAR")
    assert flags.get_bool("RACON_TPU_SWAR")  # registry default


def test_readme_table_is_current():
    """The README 'Environment flags' section must match the generated
    table exactly (regenerate with `python -m racon_tpu.flags`)."""
    assert flags.check_readme(str(REPO / "README.md")), \
        "stale README flags table — run `python -m racon_tpu.flags`"
