"""graftlint (tools/analysis) — rule self-tests, pragma semantics, the
repo-wide zero-findings gate, and the flags-registry contract."""

import pathlib
import subprocess
import sys

import pytest

from racon_tpu import flags

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_selftest_fixtures():
    """Every rule fires on its seeded fixture and stays quiet on the
    clean twin (exact counts — see tools/analysis/selftest.py)."""
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis.selftest import run_selftest
        assert run_selftest(verbose=False) == 0
    finally:
        sys.path.remove(str(REPO))


def test_repo_is_clean():
    """The acceptance gate: zero unsuppressed findings over racon_tpu/
    (and the support trees CI lints)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--quiet",
         "racon_tpu", "tests", "tools", "bench.py"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_pragma_without_reason_does_not_suppress(tmp_path):
    sys.path.insert(0, str(REPO))
    try:
        from tools.analysis import run
        bad = tmp_path / "m.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # graftlint: disable=swallowed-exception\n"
            "        pass\n")
        reported, suppressed = run([str(bad)], scoped=False)
        assert len(reported) == 1 and not suppressed
        assert "missing its (reason)" in reported[0].message

        good = tmp_path / "ok.py"
        good.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:"
            "  # graftlint: disable=swallowed-exception (why)\n"
            "        pass\n")
        reported, suppressed = run([str(good)], scoped=False)
        assert not reported and len(suppressed) == 1
    finally:
        sys.path.remove(str(REPO))


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "m.py"
    bad.write_text("import os\n"
                   "x = os.environ.get('RACON_TPU_BOGUS', '')\n")
    rc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--quiet", str(bad)],
        cwd=REPO, capture_output=True, text=True)
    assert rc.returncode == 1
    assert "env-flag-registry" in rc.stdout


# ------------------------------------------------------------ flags registry

def test_undeclared_flag_raises():
    with pytest.raises(KeyError, match="not declared"):
        # graftlint: disable=env-flag-registry (negative test: must raise)
        flags.get_bool("RACON_TPU_NOT_A_FLAG")


def test_declared_flags_have_docs():
    for f in flags.REGISTRY.values():
        assert f.name.startswith("RACON_TPU_")
        assert f.help.strip()


def test_bool_semantics(monkeypatch):
    monkeypatch.setenv("RACON_TPU_SWAR", "0")
    assert not flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.setenv("RACON_TPU_SWAR", "off")
    assert not flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.setenv("RACON_TPU_SWAR", "1")
    assert flags.get_bool("RACON_TPU_SWAR")
    monkeypatch.delenv("RACON_TPU_SWAR")
    assert flags.get_bool("RACON_TPU_SWAR")  # registry default


def test_readme_table_is_current():
    """The README 'Environment flags' section must match the generated
    table exactly (regenerate with `python -m racon_tpu.flags`)."""
    assert flags.check_readme(str(REPO / "README.md")), \
        "stale README flags table — run `python -m racon_tpu.flags`"
