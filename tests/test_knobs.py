"""Accelerator knob tests: -b (banded), -c N / --tpualigner-batches N
(batch counts = device pipeline depth + per-batch memory split).
Reference: src/main.cpp:111-126, cudapolisher.cpp:91,215-228."""

import numpy as np

from racon_tpu.cli import build_parser, _preprocess_argv
from racon_tpu.core.backends import make_aligner, make_consensus
from racon_tpu.core.window import Window, WindowType
from racon_tpu.ops.nw import TpuAligner
from racon_tpu.ops.poa import BAND, TpuPoaConsensus

from test_parallel import _random_pairs, _random_windows


def test_cli_optional_c_argument():
    args = build_parser().parse_args(_preprocess_argv(
        ["-c", "2", "a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 2
    args = build_parser().parse_args(_preprocess_argv(
        ["-c", "a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 1
    args = build_parser().parse_args(_preprocess_argv(
        ["a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 0


def test_banded_flag_halves_consensus_band():
    eng = make_consensus("tpu", 3, -5, -4, banded=True)
    assert eng.band == BAND // 2
    eng = make_consensus("tpu", 3, -5, -4, banded=False)
    assert eng.band == BAND


def test_batch_counts_reach_engines():
    aligner = make_aligner("tpu", 1, num_batches=4)
    assert aligner.num_batches == 4
    consensus = make_consensus("tpu", 3, -5, -4, num_batches=3)
    assert consensus.num_batches == 3


def test_aligner_batches_do_not_change_results():
    pairs = _random_pairs(50, seed=13)
    one = TpuAligner(buckets=((256, 128),), num_batches=1,
                     max_dirs_bytes=256 * 128 * 64)  # force several chunks
    three = TpuAligner(buckets=((256, 128),), num_batches=3,
                       max_dirs_bytes=256 * 128 * 64)
    assert one.align_batch(pairs) == three.align_batch(pairs)
    assert three.stats["device"] == len(pairs)


def test_consensus_batches_do_not_change_results():
    wins_a = _random_windows(11, seed=31)
    wins_b = _random_windows(11, seed=31)
    TpuPoaConsensus(3, -5, -4, band=64, rounds=2, num_batches=1).run(
        wins_a, True)
    eng = TpuPoaConsensus(3, -5, -4, band=64, rounds=2, num_batches=3)
    eng.run(wins_b, True)
    assert [w.consensus for w in wins_a] == [w.consensus for w in wins_b]
    assert eng.stats["device_windows"] == len(wins_b)


def test_banded_consensus_still_polishes():
    wins = _random_windows(6, seed=41)
    eng = TpuPoaConsensus(3, -5, -4, band=64, rounds=2)
    flags = eng.run(wins, True)
    assert all(flags)
    assert all(len(w.consensus) > 0 for w in wins)


def test_device_scores_map_to_emission_thresholds():
    """-g scales the device indel-emission thresholds (identity at the
    default -4, so goldens are untouched); -m/-x warn that they only
    affect the CPU fallback (cudapoa consumes the scores directly,
    cudabatch.cpp:54-62 — the pileup engine's analog is this mapping)."""
    import warnings

    from racon_tpu.ops.poa import TpuPoaConsensus

    default = TpuPoaConsensus(3, -5, -4)
    assert default.ins_theta == 0.25 and default.del_beta == 0.65

    strong_gap = TpuPoaConsensus(3, -5, -8)
    assert strong_gap.ins_theta == 0.5 and strong_gap.del_beta == 1.3

    with warnings.catch_warnings(record=True) as wlist:
        warnings.simplefilter("always")
        TpuPoaConsensus(5, -4, -4)
    assert any("CPU fallback" in str(w.message) for w in wlist)
