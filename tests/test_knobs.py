"""Accelerator knob tests: -b (banded), -c N / --tpualigner-batches N
(batch counts = device pipeline depth + per-batch memory split).
Reference: src/main.cpp:111-126, cudapolisher.cpp:91,215-228."""

import numpy as np

from racon_tpu.cli import build_parser, _preprocess_argv
from racon_tpu.core.backends import make_aligner, make_consensus
from racon_tpu.core.window import Window, WindowType
from racon_tpu.ops.nw import TpuAligner
from racon_tpu.ops.poa import BAND, TpuPoaConsensus

from test_parallel import _random_pairs, _random_windows


def test_cli_optional_c_argument():
    args = build_parser().parse_args(_preprocess_argv(
        ["-c", "2", "a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 2
    args = build_parser().parse_args(_preprocess_argv(
        ["-c", "a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 1
    args = build_parser().parse_args(_preprocess_argv(
        ["a.fasta", "b.paf", "c.fasta"]))
    assert args.tpupoa_batches == 0


def test_banded_flag_halves_consensus_band():
    eng = make_consensus("tpu", 3, -5, -4, banded=True)
    assert eng.band == BAND // 2
    eng = make_consensus("tpu", 3, -5, -4, banded=False)
    assert eng.band == BAND


def test_batch_counts_reach_engines():
    aligner = make_aligner("tpu", 1, num_batches=4)
    assert aligner.num_batches == 4
    consensus = make_consensus("tpu", 3, -5, -4, num_batches=3)
    assert consensus.num_batches == 3


def test_aligner_batches_do_not_change_results():
    pairs = _random_pairs(50, seed=13)
    one = TpuAligner(buckets=((256, 128),), num_batches=1,
                     max_dirs_bytes=256 * 128 * 64)  # force several chunks
    three = TpuAligner(buckets=((256, 128),), num_batches=3,
                       max_dirs_bytes=256 * 128 * 64)
    assert one.align_batch(pairs) == three.align_batch(pairs)
    assert three.stats["device"] == len(pairs)


def test_consensus_batches_do_not_change_results():
    wins_a = _random_windows(11, seed=31)
    wins_b = _random_windows(11, seed=31)
    TpuPoaConsensus(3, -5, -4, band=64, rounds=2, num_batches=1).run(
        wins_a, True)
    eng = TpuPoaConsensus(3, -5, -4, band=64, rounds=2, num_batches=3)
    eng.run(wins_b, True)
    assert [w.consensus for w in wins_a] == [w.consensus for w in wins_b]
    assert eng.stats["device_windows"] == len(wins_b)


def test_banded_consensus_still_polishes():
    wins = _random_windows(6, seed=41)
    eng = TpuPoaConsensus(3, -5, -4, band=64, rounds=2)
    flags = eng.run(wins, True)
    assert all(flags)
    assert all(len(w.consensus) > 0 for w in wins)


def test_device_scores_map_to_emission_thresholds():
    """-g scales the device indel-emission thresholds (identity at the
    default -4, so goldens are untouched; the scale is capped so extreme
    -g degrades symmetrically, ADVICE r3); -m/-x/-g also reach the vote
    weights as the per-layer score multiplier (cudapoa consumes the
    scores directly, cudabatch.cpp:54-62 — score-weighted voting is the
    pileup engine's analog)."""
    from racon_tpu.ops.poa import TpuPoaConsensus

    default = TpuPoaConsensus(3, -5, -4)
    assert default.ins_theta == 0.25 and default.del_beta == 0.65
    assert default.scores == (3, -5, -4)

    strong_gap = TpuPoaConsensus(3, -5, -8)
    assert strong_gap.ins_theta == 0.5 and strong_gap.del_beta == 1.3

    extreme_gap = TpuPoaConsensus(3, -5, -20)
    assert extreme_gap.ins_theta == 0.95 and extreme_gap.del_beta == 2.5

    ref_e2e = TpuPoaConsensus(8, -6, -8)  # ci/gpu/cuda_test.sh:29 config
    assert ref_e2e.scores == (8, -6, -8)


def test_device_alpha_identity_at_defaults():
    """The score-weight alpha is exactly 64 (the q6 unit) for every layer
    at the reference default scores — weighted voting is bit-identical to
    unweighted there — and deviates for other score sets."""
    import jax.numpy as jnp
    import numpy as np

    from racon_tpu.ops.poa import CH, DEL, _accumulate_votes

    B, S, L, K, nW = 8, 128, 64, 4, 2
    rng = np.random.default_rng(5)
    # a tiny synthetic vote stream: 20 column votes + 2 ins votes per row
    idx = np.full((B, S), L * (1 + K) * CH, np.int32)
    for b in range(B):
        for t in range(20):
            ch = DEL if t % 7 == 0 else int(rng.integers(0, 4))
            idx[b, t] = (19 - t) * CH + ch
        idx[b, 20] = (L + 3 * K + 0) * CH + 1
        idx[b, 21] = (L + 3 * K + 1) * CH + 2
    w = np.where(idx < L * (1 + K) * CH, 9, 0).astype(np.int32)
    ok = np.ones(B, bool)
    win_of = np.zeros(B, np.int32)
    span_m = (np.sum(idx < L * CH, axis=1)).astype(np.int32)
    n = span_m + 2  # 2 ins steps consume query
    score = np.full(B, 5, np.int32)

    args = [jnp.asarray(a) for a in (idx, w, ok, win_of, span_m,
                                     np.zeros(B, np.int32), n, score)]
    w_def, u_def, _, _ = _accumulate_votes(
        *args, n_windows=nW, L=L, K=K, band=64, scores=(3, -5, -4))
    w_e2e, u_e2e, _, _ = _accumulate_votes(
        *args, n_windows=nW, L=L, K=K, band=64, scores=(8, -6, -8))
    # defaults: every weight is w * 64 exactly
    assert float(w_def.max()) > 0
    assert np.all(np.asarray(w_def) % 64 == 0)
    # counts are alpha-independent; weights shift under the e2e scores
    assert np.array_equal(np.asarray(u_def), np.asarray(u_e2e))
    assert not np.array_equal(np.asarray(w_def), np.asarray(w_e2e))
