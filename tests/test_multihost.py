"""Multi-host mesh path: 2 JAX processes x 4 virtual CPU devices form one
8-device global mesh over the coordination service; the consensus engine
runs its sharded refinement loop SPMD across both processes (per-host
packing via ``parallel.to_global``, result replication via
``parallel.fetch_global``) and must produce byte-identical consensus to a
single-device run. SURVEY §2.3's "multi-host via DCN with per-host input
sharding"; reference analog ``src/cuda/cudapolisher.cpp:72-83``.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

from racon_tpu import flags as racon_flags

RUN_SLOW = racon_flags.get_bool("RACON_TPU_SLOW")

WORKER = pathlib.Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_two_process_mesh():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"multihost worker {pid}: OK" in out
