"""Native C++ POA engine vs the pure-Python oracle: consensuses must be
byte-identical (same graph semantics, same tie-breaks everywhere).
Reference analog: racon's CPU path IS spoa, so there is exactly one CPU
consensus answer (src/window.cpp:65-142); our native engine replicates the
Python engine the goldens were recorded with."""

import random

import pytest

from racon_tpu import native
from racon_tpu.core.backends import NativePoaConsensus, PythonPoaConsensus
from racon_tpu.core.window import Window, WindowType

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

BASES = b"ACGT"


def mutate(rng, seq, err):
    out = bytearray()
    for ch in seq:
        r = rng.random()
        if r < err * 0.5:
            out.append(rng.choice(BASES))
        elif r < err * 0.75:
            pass
        elif r < err:
            out.append(ch)
            out.append(rng.choice(BASES))
        else:
            out.append(ch)
    return bytes(out)


def random_window(rng, rank, wtype, with_quality, depth, blen=120):
    truth = bytes(rng.choice(BASES) for _ in range(blen))
    backbone = mutate(rng, truth, 0.1)
    if not backbone:
        backbone = b"A"
    win = Window(0, rank, wtype, backbone, b"!" * len(backbone))
    for _ in range(depth):
        # partial or full span
        b = rng.randint(0, max(0, len(backbone) // 3))
        e = rng.randint(2 * len(backbone) // 3, len(backbone) - 1)
        if e <= b:
            e = min(b + 1, len(backbone) - 1)
        frag = mutate(rng, truth[b:e + 1], 0.12)
        if not frag:
            continue
        qual = (bytes(rng.randint(34, 74) for _ in range(len(frag)))
                if with_quality else None)
        win.add_layer(frag, qual, b, e)
    return win


def clone(win):
    c = Window(win.id, win.rank, win.type, win.sequences[0],
               win.qualities[0])
    c.sequences = list(win.sequences)
    c.qualities = list(win.qualities)
    c.positions = list(win.positions)
    return c


@pytest.mark.parametrize("wtype,with_quality,trim", [
    (WindowType.TGS, True, True),
    (WindowType.TGS, False, True),
    (WindowType.TGS, True, False),
    (WindowType.NGS, True, True),
])
def test_native_matches_python(wtype, with_quality, trim):
    rng = random.Random(hash((wtype.value, with_quality, trim)) & 0xffff)
    wins = [random_window(rng, k, wtype, with_quality,
                          depth=rng.randint(0, 12)) for k in range(12)]
    natives = [clone(w) for w in wins]

    pflags = PythonPoaConsensus(3, -5, -4).run(wins, trim)
    nflags = NativePoaConsensus(3, -5, -4, num_threads=4).run(natives, trim)

    assert pflags == nflags
    for a, b in zip(wins, natives):
        assert a.consensus == b.consensus


def test_native_matches_python_altered_scores():
    rng = random.Random(77)
    wins = [random_window(rng, k, WindowType.TGS, True, depth=8)
            for k in range(6)]
    natives = [clone(w) for w in wins]
    pflags = PythonPoaConsensus(8, -6, -8).run(wins, True)
    nflags = NativePoaConsensus(8, -6, -8, num_threads=2).run(natives, True)
    assert pflags == nflags
    for a, b in zip(wins, natives):
        assert a.consensus == b.consensus


def test_passthrough_below_three_sequences():
    win = Window(0, 0, WindowType.TGS, b"ACGTACGT", b"!" * 8)
    win.add_layer(b"ACGT", None, 0, 4)
    flags = NativePoaConsensus(3, -5, -4).run([win], True)
    assert flags == [False]
    assert win.consensus == b"ACGTACGT"
