"""NGS window-type path: the mean-read-length heuristic and the no-trim
consensus semantics for short accurate reads.

Reference contract: windows become ``kNGS`` when the mean sequence length
is <= 1000 (``src/polisher.cpp:275-276``) and NGS consensus skips the
TGS coverage end-trim entirely (``src/window.cpp:115-139`` trims only for
``WindowType::kTGS``).
"""

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.core.polisher import create_polisher
from racon_tpu.core.window import Window, WindowType
from racon_tpu.models.poa import PoaAlignmentEngine


def _write_set(tmp_path, read_len, n_reads=40, contig_len=3000, seed=3):
    """A synthetic contig + evenly tiled reads of ``read_len`` with their
    PAF overlaps; returns (reads, paf, layout) paths."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    truth = bases[rng.integers(0, 4, contig_len)]
    backbone = truth.copy()
    flips = rng.random(contig_len) < 0.04
    backbone[flips] = bases[rng.integers(0, 4, int(flips.sum()))]

    layout = tmp_path / "layout.fasta"
    layout.write_bytes(b">ctg\n" + backbone.tobytes() + b"\n")

    reads_path = tmp_path / "reads.fastq"
    paf_path = tmp_path / "ovl.paf"
    step = max(1, (contig_len - read_len) // n_reads)
    with open(reads_path, "wb") as rf, open(paf_path, "wb") as pf:
        for ri in range(n_reads):
            start = min(ri * step, contig_len - read_len)
            read = truth[start:start + read_len].copy()
            flips = rng.random(read_len) < 0.02
            read[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
            name = b"read%d" % ri
            rf.write(b"@" + name + b"\n" + read.tobytes() + b"\n+\n"
                     + b"I" * read_len + b"\n")
            pf.write(b"\t".join([
                name, b"%d" % read_len, b"0", b"%d" % read_len, b"+",
                b"ctg", b"%d" % contig_len, b"%d" % start,
                b"%d" % (start + read_len), b"%d" % (read_len // 2),
                b"%d" % read_len, b"255"]) + b"\n")
    return reads_path, paf_path, layout


def _polisher(tmp_path, read_len, **kw):
    reads, paf, layout = _write_set(tmp_path, read_len)
    p = create_polisher(str(reads), str(paf), str(layout), num_threads=2,
                        **kw)
    p.initialize()
    return p


def test_heuristic_flips_to_ngs(tmp_path):
    """Mean read length <= 1000 -> every window is NGS; > 1000 -> TGS
    (``polisher.cpp:275-276``). The mean includes the target contig."""
    p = _polisher(tmp_path / "short", 300)
    assert p.windows and all(w.type == WindowType.NGS for w in p.windows)

    p = _polisher(tmp_path / "long", 1400)
    assert p.windows and all(w.type == WindowType.TGS for w in p.windows)


def test_ngs_consensus_skips_trim():
    """An identical window polished as NGS vs TGS: low-coverage window
    ends must be trimmed only on the TGS path (``window.cpp:115-139``)."""
    rng = np.random.default_rng(11)
    bases = np.frombuffer(b"ACGT", np.uint8)
    backbone = bases[rng.integers(0, 4, 200)]

    def build(wtype):
        win = Window(0, 0, wtype, backbone.tobytes(), b"5" * len(backbone))
        # 6 layers covering only the middle [50, 150): ends have zero
        # layer coverage, far below the (n-1)/2 trim threshold
        for _ in range(6):
            layer = backbone[50:150].copy()
            flips = rng.random(100) < 0.02
            layer[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
            win.add_layer(layer.tobytes(), b"I" * 100, 50, 149)
        return win

    engine = PoaAlignmentEngine(3, -5, -4)
    ngs = build(WindowType.NGS)
    ngs.generate_consensus(engine, trim=True)
    tgs = build(WindowType.TGS)
    tgs.generate_consensus(engine, trim=True)

    # NGS keeps the full span; TGS trims the uncovered ends
    assert len(ngs.consensus) >= 190
    assert len(tgs.consensus) <= 110
    assert len(tgs.consensus) >= 90


def test_ngs_pipeline_end_to_end(tmp_path):
    """Short-read polishing end to end: NGS windows, consensus closer to
    the truth than the backbone (no trimming artifacts at window edges —
    output length stays ~contig-sized)."""
    reads, paf, layout = _write_set(tmp_path, 300)
    p = create_polisher(str(reads), str(paf), str(layout), num_threads=2)
    p.initialize()
    assert all(w.type == WindowType.NGS for w in p.windows)
    (polished,) = p.polish(True)

    rng = np.random.default_rng(3)
    bases = np.frombuffer(b"ACGT", np.uint8)
    truth = bases[rng.integers(0, 4, 3000)].tobytes()
    backbone_fa = (tmp_path / "layout.fasta").read_bytes().splitlines()[1]
    d_backbone = native.edit_distance(backbone_fa, truth)
    d_polished = native.edit_distance(polished.data, truth)
    assert d_polished < d_backbone / 2, (d_polished, d_backbone)
    assert len(polished.data) > 2800  # no TGS-style end trims
