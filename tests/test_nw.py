"""Pairwise NW reference-implementation tests."""

import random

import numpy as np
import pytest

from racon_tpu.models.nw import edit_distance, nw_align
from racon_tpu.utils.cigar import parse_cigar


def brute_edit_distance(a: bytes, b: bytes) -> int:
    n, m = len(a), len(b)
    dp = list(range(m + 1))
    for i in range(1, n + 1):
        prev = dp[0]
        dp[0] = i
        for j in range(1, m + 1):
            cur = min(prev + (a[i - 1] != b[j - 1]), dp[j] + 1, dp[j - 1] + 1)
            prev = dp[j]
            dp[j] = cur
    return dp[m]


def random_pair(rng, n, err):
    a = bytes(rng.choice(b"ACGT") for _ in range(n))
    b = bytearray(a)
    num_edits = int(n * err)
    for _ in range(num_edits):
        op = rng.randrange(3)
        pos = rng.randrange(max(1, len(b)))
        if op == 0:
            b[pos:pos + 1] = bytes([rng.choice(b"ACGT")])
        elif op == 1 and len(b) > 1:
            del b[pos]
        else:
            b.insert(pos, rng.choice(b"ACGT"))
    return a, bytes(b)


def cigar_consumes(cigar: str):
    q = t = 0
    for n, op in parse_cigar(cigar):
        if op == "M":
            q += n
            t += n
        elif op == "I":
            q += n
        elif op == "D":
            t += n
    return q, t


def cigar_cost(cigar: str, q: bytes, t: bytes) -> int:
    qi = ti = cost = 0
    for n, op in parse_cigar(cigar):
        if op == "M":
            for _ in range(n):
                cost += q[qi] != t[ti]
                qi += 1
                ti += 1
        elif op == "I":
            qi += n
            cost += n
        elif op == "D":
            ti += n
            cost += n
    return cost


@pytest.mark.parametrize("n,err", [(10, 0.3), (50, 0.2), (200, 0.15), (500, 0.1)])
def test_edit_distance_matches_bruteforce(n, err):
    rng = random.Random(n)
    for _ in range(5):
        a, b = random_pair(rng, n, err)
        assert edit_distance(a, b) == brute_edit_distance(a, b)


@pytest.mark.parametrize("n,err", [(10, 0.3), (80, 0.2), (300, 0.15)])
def test_nw_align_optimal_and_consistent(n, err):
    rng = random.Random(n * 7)
    for _ in range(5):
        a, b = random_pair(rng, n, err)
        cigar = nw_align(a, b)
        cq, ct = cigar_consumes(cigar)
        assert (cq, ct) == (len(a), len(b))
        assert cigar_cost(cigar, a, b) == brute_edit_distance(a, b)


def test_edge_cases():
    assert edit_distance(b"", b"ACGT") == 4
    assert edit_distance(b"ACGT", b"") == 4
    assert edit_distance(b"ACGT", b"ACGT") == 0
    assert nw_align(b"ACGT", b"ACGT") == "4M"
    assert cigar_consumes(nw_align(b"", b"AC")) == (0, 2)
