"""Observability subsystem (racon_tpu.obs): metrics registry, span
tracer, run reports — and the acceptance contracts: Chrome trace-event
schema on a CLI e2e run, byte-identity of polished output with
``RACON_TPU_TRACE`` on vs off, near-zero disabled-span cost in the
consensus hot loop, heartbeat/registry wiring, and run-report schema
validation for both CLI and exec runs."""

import json
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from racon_tpu.obs import metrics, report, trace

REPO = pathlib.Path(__file__).resolve().parent.parent

# span names the acceptance criteria require a CLI trace to cover:
# parse / align / decode / build / consensus / stitch + queue waits
REQUIRED_SPANS = {"parse.targets", "parse.reads", "parse.overlaps",
                  "align", "bp.decode", "build.backbone",
                  "build.windows", "consensus", "stitch",
                  "queue.put", "queue.get"}


@pytest.fixture
def clean_trace():
    """Reset the tracer around a test that activates it (the registry
    uses test-unique names instead, so cross-test state is harmless)."""
    trace.deactivate()
    yield
    trace.deactivate()


# ---------------------------------------------------------------- metrics

def test_metrics_counter_gauge_timer():
    metrics.clear("t_obs.")
    metrics.inc("t_obs.c")
    metrics.inc("t_obs.c", 4)
    metrics.set_gauge("t_obs.g", 7)
    metrics.set_gauge("t_obs.g", 3)
    metrics.add_time("t_obs.t", 0.25)
    metrics.add_time("t_obs.t", 0.25)
    assert metrics.counter("t_obs.c") == 5
    assert metrics.gauge("t_obs.g") == 3
    assert metrics.timer_s("t_obs.t") == pytest.approx(0.5)
    assert metrics.counter("t_obs.missing", -1) == -1


def test_metrics_group_and_clear():
    metrics.clear("t_grp.")
    metrics.inc("t_grp.a", 2)
    metrics.set_gauge("t_grp.b", 9)
    metrics.add_time("t_grp.c", 1.5)
    assert metrics.group("t_grp.") == {"a": 2, "b": 9, "c": 1.5}
    metrics.clear("t_grp.")
    assert metrics.group("t_grp.") == {}


def test_metrics_thread_safety():
    metrics.clear("t_mt.")

    def worker():
        for _ in range(1000):
            metrics.inc("t_mt.n")

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.counter("t_mt.n") == 8000


def test_pack_summary_derivation():
    metrics.clear("consensus.")
    metrics.clear("align.")
    assert metrics.pack_summary()["groups"] == 0
    metrics.inc("consensus.lanes_occupied", 600)
    metrics.inc("consensus.lanes_total", 1000)
    metrics.inc("consensus.groups", 2)
    metrics.inc("consensus.group_windows", 10)
    # the round-17 aligner half of the summary
    metrics.inc("align.lanes_occupied", 300)
    metrics.inc("align.lanes_total", 400)
    metrics.inc("align.chunks", 3)
    metrics.inc("align.steps_wasted", 100)
    pack = metrics.pack_summary()
    assert pack == {"pack_efficiency": 0.6, "pad_fraction": 0.4,
                    "windows_per_group": 5.0, "groups": 2,
                    "align_pack_efficiency": 0.75,
                    "align_pad_fraction": 0.25,
                    "align_chunks": 3, "align_steps_wasted": 100}


# ------------------------------------------------------------ span tracer

def test_disabled_span_is_free(clean_trace):
    """The overhead guard: with tracing disabled, obs.span returns ONE
    shared no-op singleton (no allocation beyond the kwargs dict), so
    the consensus hot loop pays a global load + branch per span. 200k
    disabled spans must be far under any measurable slice of a
    consensus run (real cost ~50 ms; the bound is 20x slack for CI)."""
    from racon_tpu import obs

    probe = obs.span("consensus")  # graftlint has tests out of scope,
    assert probe is trace.NULL_SPAN  # but keep the sanctioned pattern
    t0 = time.perf_counter()
    for _ in range(200_000):
        with obs.span("consensus"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"disabled spans cost {dt:.3f}s per 200k"


def test_span_records_timer_and_trace(clean_trace, tmp_path):
    from racon_tpu import obs

    metrics.clear("t_span.")
    trace.activate(tracing=True)
    with obs.span("t_span.outer", k=1):
        with obs.span("t_span.inner"):
            time.sleep(0.01)

    def worker():
        with obs.track("side"), obs.span("t_span.threaded"):
            pass

    t = threading.Thread(target=worker, name="obs-test-worker")
    t.start()
    t.join()
    assert metrics.timer_s("t_span.inner") >= 0.01
    assert metrics.timer_s("t_span.outer") >= metrics.timer_s(
        "t_span.inner")
    out = trace.export(str(tmp_path / "t.json"))
    assert out["events"] >= 3 and out["dropped"] == 0
    doc = json.loads((tmp_path / "t.json").read_bytes())
    events = doc["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"t_span.outer", "t_span.inner", "t_span.threaded"} <= names
    for e in spans:
        assert {"name", "cat", "ph", "pid", "tid", "ts", "dur"} <= set(e)
    outer = next(e for e in spans if e["name"] == "t_span.outer")
    inner = next(e for e in spans if e["name"] == "t_span.inner")
    # nesting: inner inside outer on the same track
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert outer["args"] == {"k": 1}
    # thread/track metadata rows name every tid
    meta = {e["tid"]: e["args"]["name"] for e in events
            if e["name"] == "thread_name"}
    assert set(meta) == {e["tid"] for e in spans}
    assert any(name.endswith("/side") for name in meta.values())


def test_thread_buffers_survive_deactivate_reactivate(clean_trace,
                                                      tmp_path):
    """A persistent worker thread whose buffer predates a deactivate()
    must re-register on its next span (epoch bump) — its later spans
    must appear in the new export, not vanish into an orphaned ring."""
    from racon_tpu import obs

    barrier_in = threading.Event()
    barrier_go = threading.Event()

    def worker():
        with obs.span("t_epoch.first"):
            pass
        barrier_in.set()
        barrier_go.wait(5)
        with obs.span("t_epoch.second"):
            pass

    trace.activate(tracing=True)
    t = threading.Thread(target=worker)
    t.start()
    barrier_in.wait(5)
    trace.deactivate()
    trace.activate(tracing=True)
    barrier_go.set()
    t.join()
    out_path = tmp_path / "epoch.json"
    trace.export(str(out_path))
    names = {e["name"]
             for e in json.loads(out_path.read_bytes())["traceEvents"]
             if e.get("ph") == "X"}
    assert "t_epoch.second" in names
    assert "t_epoch.first" not in names  # pre-reset events are gone


def test_trace_ring_is_bounded(clean_trace, tmp_path, monkeypatch):
    from racon_tpu import obs

    monkeypatch.setattr(trace, "RING_CAP", 16)
    trace.activate(tracing=True)
    for _ in range(40):
        with obs.span("t_ring.x"):
            pass
    out = trace.export(str(tmp_path / "r.json"))
    assert out["dropped"] == 24
    doc = json.loads((tmp_path / "r.json").read_bytes())
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 16


# ----------------------------------------------- registry feeds (producers)

def test_retrace_budget_publishes_registry_gauge():
    from racon_tpu import sanitize

    metrics.clear("retrace.")
    with sanitize.PhaseRetraceBudget("obsphase", prefixes=("no.such.",)):
        pass
    assert metrics.group("retrace.") == {"obsphase": 0}


def test_log_swallowed_counts_suppressed(capsys):
    from racon_tpu.utils import logger

    metrics.clear("swallowed.")
    logger._seen_swallowed.clear()
    for _ in range(3):
        logger.log_swallowed("obs test ctx", ValueError("boom"))
    err = capsys.readouterr().err
    assert err.count("obs test ctx: swallowed ValueError") == 1
    # the registry shows how many faults the once-per-cause line hid
    assert metrics.counter("swallowed.obs test ctx|ValueError") == 3


def test_queue_metrics_from_pipelined_run(tmp_path):
    """Polisher.run() publishes bounded-queue wait/depth to the registry
    unconditionally (the heartbeat's queue[...] field reads them)."""
    from racon_tpu.core.polisher import create_polisher
    from test_columnar_init import write_synthetic_assembly

    metrics.clear("queue.")
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=31, n_contigs=1,
                                          contig=2000)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2)
    polished = p.run(True)
    assert polished
    q = metrics.queue_summary()
    assert q["consumer_wait_s"] >= 0.0 and "stall_s" in q
    assert metrics.gauge("queue.depth", None) is not None


# ------------------------------------------------------------- run reports

def test_report_build_and_validate_roundtrip():
    rep = report.build_report("cli", argv=["a", "b"], started_unix=1.5,
                              wall_s=2.5, phases={"parse_s": 0.1})
    assert report.validate_report(rep) == []
    assert rep["schema_version"] == report.SCHEMA_VERSION
    assert rep["phases"] == {"parse_s": 0.1}


def test_report_validate_rejects_corruption():
    rep = report.build_report("cli")
    bad = dict(rep)
    del bad["queue"]
    assert any("queue" in e for e in report.validate_report(bad))
    bad = dict(rep, kind="daemon")
    assert any("kind" in e for e in report.validate_report(bad))
    bad = dict(rep, schema_version=99)
    assert any("schema_version" in e
               for e in report.validate_report(bad))
    bad = dict(rep, extra_key=1)
    assert any("unknown key" in e for e in report.validate_report(bad))
    bad = dict(rep, shards=[{"status": "done"}])  # missing id
    assert any("shards[0]" in e for e in report.validate_report(bad))
    bad = dict(rep, phases={"parse_s": "fast"})
    assert any("phases" in e for e in report.validate_report(bad))


def test_report_v8_requires_dataflow_section():
    """Schema v8: the resident-dataflow accounting section is required,
    fully populated (all keys numeric, zeros with the flag off), and
    validated key-by-key."""
    metrics.clear("dataflow.")
    rep = report.build_report("cli")
    assert report.validate_report(rep) == []
    df = rep["dataflow"]
    for key in ("resident", "bytes_fetched", "bytes_avoided",
                "fallback_pairs", "resident_bailouts",
                "lanes_device_groups", "ins_overflow_windows"):
        assert df[key] == 0, (key, df)
    broken = dict(rep)
    del broken["dataflow"]
    assert any("dataflow" in e for e in report.validate_report(broken))
    bad = dict(rep, dataflow=dict(df, bytes_fetched="lots"))
    assert any("bytes_fetched" in e for e in report.validate_report(bad))
    bad = dict(rep, dataflow={k: v for k, v in df.items()
                              if k != "resident"})
    assert any("resident" in e for e in report.validate_report(bad))

    # a resident run's numbers flow through (scoped, like a job report)
    metrics.set_scope("job.df1.")
    try:
        metrics.set_gauge("dataflow.resident", 1)
        metrics.inc("dataflow.bytes_fetched", 4096)
        metrics.inc("dataflow.bytes_avoided", 1 << 20)
        metrics.inc("dataflow.fallback_pairs", 3)
        metrics.inc("consensus.ins_overflow_windows", 2)
    finally:
        metrics.set_scope(None)
    scoped = report.build_report("job", scope="job.df1.")
    assert report.validate_report(scoped) == []
    assert scoped["dataflow"]["resident"] == 1
    assert scoped["dataflow"]["bytes_fetched"] == 4096
    assert scoped["dataflow"]["bytes_avoided"] == 1 << 20
    assert scoped["dataflow"]["fallback_pairs"] == 3
    assert scoped["dataflow"]["ins_overflow_windows"] == 2
    metrics.clear("job.df1.")


def test_report_v10_requires_overlap_section():
    """Schema v10: the first-party overlapper accounting section is
    required — mode 'paf' with zeros for precomputed-overlap runs,
    mode 'auto' with the seed/join/chain numbers when the in-process
    overlapper generated the rows — and validated key-by-key,
    including the round-21 occupancy/join/cache keys."""
    metrics.clear("overlap.")
    rep = report.build_report("cli")
    assert report.validate_report(rep) == []
    ov = rep["overlap"]
    assert ov["mode"] == "paf"
    for key in ("minimizers", "candidate_pairs", "freq_capped_buckets",
                "chains_kept", "chains_dropped", "lanes_occupied",
                "lanes_total", "chunks", "join_bailouts", "cache_hits",
                "cache_misses", "seed_dispatch_s", "seed_fetch_s",
                "join_dispatch_s", "join_fetch_s", "chain_dispatch_s",
                "chain_fetch_s"):
        assert ov[key] == 0, (key, ov)
    broken = dict(rep)
    del broken["overlap"]
    assert any("overlap" in e for e in report.validate_report(broken))
    bad = dict(rep, overlap=dict(ov, chains_kept="many"))
    assert any("chains_kept" in e for e in report.validate_report(bad))
    bad = dict(rep, overlap=dict(ov, mode="minimap2"))
    assert any("mode" in e for e in report.validate_report(bad))
    bad = dict(rep, overlap={k: v for k, v in ov.items()
                             if k != "minimizers"})
    assert any("minimizers" in e for e in report.validate_report(bad))
    # the v10 keys are required, not merely emitted
    for v10_key in ("lanes_total", "join_bailouts", "cache_hits",
                    "join_dispatch_s"):
        bad = dict(rep, overlap={k: v for k, v in ov.items()
                                 if k != v10_key})
        assert any(v10_key in e for e in report.validate_report(bad)), \
            v10_key

    # an auto run's numbers flow through (scoped, like a job report)
    metrics.set_scope("job.ov1.")
    try:
        metrics.set_gauge("overlap.mode_auto", 1)
        metrics.inc("overlap.minimizers", 1234)
        metrics.inc("overlap.candidate_pairs", 56)
        metrics.inc("overlap.freq_capped_buckets", 7)
        metrics.inc("overlap.chains_kept", 40)
        metrics.inc("overlap.chains_dropped", 16)
        metrics.inc("overlap.lanes_occupied", 900)
        metrics.inc("overlap.lanes_total", 1024)
        metrics.inc("overlap.chunks", 3)
        metrics.inc("overlap.join_bailouts", 1)
        metrics.inc("overlap.cache_hits", 2)
        metrics.inc("overlap.cache_misses", 1)
        metrics.add_time("overlap.seed.dispatch", 0.5)
        metrics.add_time("overlap.join.dispatch", 0.125)
        metrics.add_time("overlap.join.fetch", 0.375)
        metrics.add_time("overlap.chain.fetch", 0.25)
    finally:
        metrics.set_scope(None)
    scoped = report.build_report("job", scope="job.ov1.")
    assert report.validate_report(scoped) == []
    assert scoped["overlap"]["mode"] == "auto"
    assert scoped["overlap"]["minimizers"] == 1234
    assert scoped["overlap"]["candidate_pairs"] == 56
    assert scoped["overlap"]["freq_capped_buckets"] == 7
    assert scoped["overlap"]["chains_kept"] == 40
    assert scoped["overlap"]["chains_dropped"] == 16
    assert scoped["overlap"]["lanes_occupied"] == 900
    assert scoped["overlap"]["lanes_total"] == 1024
    assert scoped["overlap"]["chunks"] == 3
    assert scoped["overlap"]["join_bailouts"] == 1
    assert scoped["overlap"]["cache_hits"] == 2
    assert scoped["overlap"]["cache_misses"] == 1
    assert scoped["overlap"]["seed_dispatch_s"] == 0.5
    assert scoped["overlap"]["join_dispatch_s"] == 0.125
    assert scoped["overlap"]["join_fetch_s"] == 0.375
    assert scoped["overlap"]["chain_fetch_s"] == 0.25
    metrics.clear("job.ov1.")


def test_report_shard_row_filters_manifest_keys():
    entry = {"id": 3, "status": "done", "part": "part_0003.fasta",
             "contigs": [1, 2], "engine": "primary", "mbp": 1.25,
             "wall_s": 9.0, "retrace": {"align": 0}, "timings": {},
             "peak_rss_mb": 100}
    row = report.shard_row(entry)
    assert "part" not in row and "contigs" not in row
    assert row["id"] == 3 and row["engine"] == "primary"
    rep = report.build_report("exec", shards=[entry])
    assert report.validate_report(rep) == []


def test_report_check_cli(tmp_path):
    rep = report.build_report("cli")
    path = tmp_path / "rep.json"
    report.write_report(str(path), rep)
    ok = subprocess.run(
        [sys.executable, "-m", "racon_tpu.obs", "--check", str(path)],
        cwd=REPO, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stderr
    path.write_text("{}")
    bad = subprocess.run(
        [sys.executable, "-m", "racon_tpu.obs", "--check", str(path)],
        cwd=REPO, capture_output=True, text=True)
    assert bad.returncode == 1


# ----------------------------------------------------- CLI e2e (subprocess)

def _cli(tmp_path, inputs, *extra, env_extra=None):
    import os

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-t", "4", *extra,
         *map(str, inputs)],
        capture_output=True, timeout=600, cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc


@pytest.fixture(scope="module")
def synthetic_inputs(tmp_path_factory):
    sys.path.insert(0, str(REPO / "tests"))
    from test_columnar_init import write_synthetic_assembly

    td = tmp_path_factory.mktemp("obs_cli")
    return write_synthetic_assembly(td, seed=29, n_contigs=2, contig=2500)


def test_cli_env_trace_byte_identity_and_schema(synthetic_inputs,
                                                tmp_path):
    """The acceptance triple on a full CLI run, driven by the ENV flags:
    polished stdout byte-identical with RACON_TPU_TRACE on vs off, the
    trace is Chrome trace-event JSON covering the required pipeline
    spans, and run_report.json validates against its schema.  The
    device-aligner path is on (--tpualigner-batches) so the trace shows
    the align dispatch-vs-fetch split."""
    plain = _cli(tmp_path, synthetic_inputs, "--tpualigner-batches", "1")
    tr = tmp_path / "trace.json"
    rp = tmp_path / "report.json"
    traced = _cli(tmp_path, synthetic_inputs, "--tpualigner-batches", "1",
                  env_extra={"RACON_TPU_TRACE": str(tr),
                             "RACON_TPU_RUN_REPORT": str(rp)})
    assert traced.stdout == plain.stdout, \
        "tracing changed the polished output bytes"

    doc = json.loads(tr.read_bytes())
    assert "traceEvents" in doc
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    missing = REQUIRED_SPANS - names
    assert not missing, f"trace missing required spans: {missing}"
    assert {"align.dispatch", "align.fetch"} <= names
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0

    rep = json.loads(rp.read_bytes())
    assert report.validate_report(rep) == [], report.validate_report(rep)
    assert rep["kind"] == "cli"
    assert rep["phases"].get("align_s") is not None
    assert rep["dispatch_fetch"]["align_dispatch_s"] > 0
    assert rep["queue"]["stall_s"] >= 0


def test_cli_trace_flag_defaults_report_next_to_trace(synthetic_inputs,
                                                      tmp_path):
    """--trace FILE alone also emits run_report.json next to FILE."""
    tr = tmp_path / "t2" / "trace.json"
    tr.parent.mkdir()
    _cli(tmp_path, synthetic_inputs, "--trace", str(tr))
    assert tr.exists()
    rep = json.loads((tr.parent / "run_report.json").read_bytes())
    assert report.validate_report(rep) == []


def test_cli_exec_trace_and_report(synthetic_inputs, tmp_path):
    """Sharded (exec) CLI run: byte-identical output, per-shard trace
    tracks, a valid kind=exec report with one row per shard at BOTH the
    --run-report path and next to the manifest in the work dir."""
    plain = _cli(tmp_path, synthetic_inputs)
    tr = tmp_path / "exec_trace.json"
    rp = tmp_path / "exec_report.json"
    work = tmp_path / "work"
    sharded = _cli(tmp_path, synthetic_inputs, "--shards", "2",
                   "--shard-dir", str(work), "--trace", str(tr),
                   "--run-report", str(rp))
    assert sharded.stdout == plain.stdout
    err = sharded.stderr.decode()
    assert "pack[" in err and "queue[" in err and "retrace[" in err

    doc = json.loads(tr.read_bytes())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"exec.index", "exec.plan", "exec.extract", "exec.shard",
            "exec.merge"} <= names
    tracks = {e["args"]["name"] for e in doc["traceEvents"]
              if e["name"] == "thread_name"}
    assert any(t.endswith("shard 0") for t in tracks)
    assert any(t.endswith("shard 1") for t in tracks)

    for path in (rp, work / "run_report.json"):
        rep = json.loads(path.read_bytes())
        assert report.validate_report(rep) == [], (
            path, report.validate_report(rep))
        assert rep["kind"] == "exec"
        assert [r["id"] for r in rep["shards"]] == [0, 1]
        assert all(r["status"] == "done" for r in rep["shards"])
        assert all("retrace" in r for r in rep["shards"])


def test_exec_work_dir_report_has_real_timers(synthetic_inputs,
                                              tmp_path):
    """The shard runner persists its work-dir report on EVERY run, so it
    arms the span timers itself — a default run (no --trace /
    --run-report) must record real span seconds, not schema-valid
    zeros, and run-level retrace totals must survive the per-shard
    clear."""
    work = tmp_path / "work_plain"
    _cli(tmp_path, synthetic_inputs, "--shards", "2",
         "--shard-dir", str(work))
    rep = json.loads((work / "run_report.json").read_bytes())
    assert report.validate_report(rep) == []
    timers = rep["metrics"]["timers"]
    assert timers.get("exec.extract", 0) > 0
    assert timers.get("exec.shard", 0) > 0
    # run-level totals cover every shard (gauges are per-shard cleared)
    assert set(rep["retrace"]) >= {"align", "consensus"}


def test_run_boundary_clears_per_run_metrics():
    """clear_run()/obs.begin() drop every per-run name so back-to-back
    runs in one process do not report each other's numbers."""
    from racon_tpu import obs

    metrics.inc("consensus.lanes_total", 123)
    metrics.add_time("align.dispatch", 9.0)
    metrics.add_time("queue.consumer_wait_s", 9.0)
    metrics.inc("retrace_total.align", 7)
    metrics.inc("swallowed.ctx|ValueError", 5)
    metrics.set_gauge("trace.dropped_events", 11)
    obs.begin()
    assert metrics.counter("consensus.lanes_total") == 0
    assert metrics.timer_s("align.dispatch") == 0.0
    assert metrics.queue_summary()["stall_s"] == 0.0
    assert metrics.group("retrace_total.") == {}
    assert metrics.group("swallowed.") == {}
    assert metrics.gauge("trace.dropped_events") == 0


def test_exec_run_is_isolated_from_prior_registry_state(
        synthetic_inputs, tmp_path):
    """A ShardRunner.run() in a process that already polished (bench,
    tests, service mode) must report ITS pack/dispatch numbers, not the
    process-lifetime accumulation."""
    from racon_tpu.exec import ShardRunner

    metrics.inc("consensus.lanes_total", 10**9)
    metrics.add_time("align.dispatch", 1e6)
    rp, pp, lp = synthetic_inputs
    runner = ShardRunner(str(rp), str(pp), str(lp), num_threads=2,
                         n_shards=2, work_dir=str(tmp_path / "iso"))
    with open(tmp_path / "iso.fasta", "wb") as out:
        runner.run(out)
    assert metrics.counter("consensus.lanes_total") < 10**9
    assert runner.report["dispatch_fetch"]["align_dispatch_s"] < 1e5


def test_track_survives_deactivate_mid_track(clean_trace):
    """deactivate() while a thread is inside obs.track() must not make
    the track exit pop from the freshly re-registered (empty) buffer."""
    from racon_tpu import obs

    trace.activate(tracing=True)
    with obs.track("t_mid.shard"):
        trace.deactivate()
        trace.activate(tracing=True)
        with obs.span("t_mid.inner"):
            pass  # re-registers a fresh buffer with an empty track stack
    # the new buffer's (empty) track stack was left alone
    assert trace._buf().tracks == []


def test_cli_create_polisher_error_still_writes_report(tmp_path):
    """A bad input (the most common user error) exits 1 but still writes
    the requested trace/run-report — a report of the failed run is the
    data needed to debug it."""
    import os

    tr = tmp_path / "err_trace.json"
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "--trace", str(tr),
         str(tmp_path / "missing.fastq"), str(tmp_path / "missing.paf"),
         str(tmp_path / "missing.fasta")],
        capture_output=True, timeout=300, cwd=str(REPO), env=env)
    assert proc.returncode == 1
    rep = json.loads((tmp_path / "run_report.json").read_bytes())
    assert report.validate_report(rep) == []
    assert rep["kind"] == "cli"
    assert tr.exists()


def test_cli_golden_byte_exact_with_trace(data_dir, tmp_path):
    """λ-phage golden with tracing on: the recorded golden was produced
    WITHOUT tracing, so a byte-exact match proves --trace cannot perturb
    output on real data (skips where the reference set is absent)."""
    golden = REPO / "tests" / "data" / "golden_lambda_fastq_paf.fasta"
    tr = tmp_path / "lambda_trace.json"
    proc = _cli(tmp_path,
                [data_dir / "sample_reads.fastq.gz",
                 data_dir / "sample_overlaps.paf.gz",
                 data_dir / "sample_layout.fasta.gz"],
                "-t", "8", "--trace", str(tr))
    assert proc.stdout == golden.read_bytes()
    rep = json.loads((tmp_path / "run_report.json").read_bytes())
    assert report.validate_report(rep) == []
    names = {e["name"]
             for e in json.loads(tr.read_bytes())["traceEvents"]
             if e.get("ph") == "X"}
    assert REQUIRED_SPANS <= names
