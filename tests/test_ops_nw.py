"""Batched device aligner tests (run on the CPU XLA backend via conftest;
the same code path runs on TPU — see .claude/skills/verify/SKILL.md)."""

import random

import pytest

from racon_tpu.core.backends import NativeAligner, PythonAligner
from racon_tpu.models.nw import edit_distance
from racon_tpu.ops.nw import TpuAligner, BUCKETS
from tests.test_nw import cigar_cost, cigar_consumes


def mutate(rng, s, err):
    out = bytearray()
    for ch in s:
        r = rng.random()
        if r < err * 0.4:
            out.append(rng.choice(b"ACGT"))
        elif r < err * 0.7:
            pass
        elif r < err:
            out.extend([ch, rng.choice(b"ACGT")])
        else:
            out.append(ch)
    return bytes(out)


@pytest.fixture(scope="module")
def aligner():
    try:
        fb = NativeAligner(1)
    except RuntimeError:
        fb = PythonAligner()
    return TpuAligner(fallback=fb)


def test_device_alignments_optimal(aligner):
    rng = random.Random(11)
    pairs = []
    for L, err in [(60, 0.2), (200, 0.15), (900, 0.15), (2000, 0.12),
                   (300, 0.3), (500, 0.02), (100, 0.0)]:
        a = bytes(rng.choice(b"ACGT") for _ in range(L))
        pairs.append((mutate(rng, a, err), a))
    cigars = aligner.align_batch(pairs)
    for (q, t), cig in zip(pairs, cigars):
        assert cigar_consumes(cig) == (len(q), len(t))
        assert cigar_cost(cig, q, t) == edit_distance(q, t)


def test_length_mismatch_and_empty(aligner):
    rng = random.Random(12)
    a = bytes(rng.choice(b"ACGT") for _ in range(400))
    pairs = [(a, a[:200]), (a[:150], a), (b"", a[:30]), (a[:30], b"")]
    cigars = aligner.align_batch(pairs)
    for (q, t), cig in zip(pairs, cigars):
        assert cigar_consumes(cig) == (len(q), len(t))
    assert cigars[2] == "30D"
    assert cigars[3] == "30I"


def test_band_escalation_handles_high_divergence(aligner):
    rng = random.Random(13)
    a = bytes(rng.choice(b"ACGT") for _ in range(1500))
    b = mutate(rng, a, 0.45)  # extreme divergence forces band escalation
    (cig,) = aligner.align_batch([(b, a)])
    assert cigar_consumes(cig) == (len(b), len(a))
    assert cigar_cost(cig, b, a) == edit_distance(b, a)


def test_oversize_pair_falls_back(aligner):
    max_len = max(m for m, _ in BUCKETS)
    rng = random.Random(14)
    a = bytes(rng.choice(b"ACGT") for _ in range(max_len + 10))
    before = dict(aligner.stats)
    (cig,) = aligner.align_batch([(a, a)])
    assert cig == f"{len(a)}M"
    assert aligner.stats["fallback_length"] == before["fallback_length"] + 1


def test_breaking_points_match_cigar_walker():
    """Device breaking points (per-boundary tables computed from the
    device-resident op stream) must equal walking the device CIGAR with
    the shared oracle walker, for every pair, strand offset and window
    phase — including pairs with matchless windows (deletion crossings)."""
    import numpy as np

    from racon_tpu.core.overlap import breaking_points_from_cigar
    from racon_tpu.ops.nw import TpuAligner

    rng = np.random.default_rng(29)
    bases = np.frombuffer(b"ACGT", np.uint8)
    pairs, metas = [], []
    for k in range(24):
        ln = int(rng.integers(120, 240))
        t = bases[rng.integers(0, 4, ln)]
        q = t.copy()
        flips = rng.random(ln) < 0.12
        q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        q = np.delete(q, rng.integers(0, len(q), 5))
        if k % 4 == 0:  # a long deletion -> a window with no matches
            cut = int(rng.integers(20, ln - 60))
            q = np.concatenate([q[:cut], q[cut + 45:]])
        pairs.append((q.tobytes(), t.tobytes()))
        metas.append((int(rng.integers(0, 1000)),    # global t_begin
                      int(rng.integers(0, 500))))    # global q_off
    w = 64

    from racon_tpu.core.backends import PythonAligner
    from racon_tpu.core.overlap import bp_array_to_pairs
    al = TpuAligner(buckets=((256, 128),), fallback=PythonAligner())
    bps = al.breaking_points_batch(pairs, metas, w)
    assert al.stats["fallback_length"] > 0  # deletion pairs exercise the
    cigars = al.align_batch(pairs)        # host-walker fallback path too
    for k, ((q, t), (t_begin, q_off)) in enumerate(zip(pairs, metas)):
        oracle = breaking_points_from_cigar(
            cigars[k], q_off, t_begin, t_begin + len(t), w)
        assert bps[k].dtype == np.int32 and bps[k].shape[1] == 4
        assert bp_array_to_pairs(bps[k]) == oracle, f"pair {k}"
