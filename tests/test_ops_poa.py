"""Batched device consensus (TPU pileup engine) tests on the CPU XLA
backend. Quality parity with the CPU POA engine is asserted loosely — like
the reference, the accelerated engine records its own goldens
(test/racon_test.cpp:312 vs :106)."""

import random

import pytest

from racon_tpu.core.backends import CpuPoaConsensus
from racon_tpu.core.window import Window, WindowType
from racon_tpu.models.nw import edit_distance
from racon_tpu.ops.poa import TpuPoaConsensus


def mutate(rng, s, err):
    out = bytearray()
    for ch in s:
        r = rng.random()
        if r < err * 0.25:
            out.append(rng.choice(b"ACGT"))
        elif r < err * 0.6:
            pass
        elif r < err:
            out.extend([ch, rng.choice(b"ACGT")])
        else:
            out.append(ch)
    return bytes(out)


def make_window(rng, truth, err=0.15, depth=25, backbone_err=0.13):
    backbone = mutate(rng, truth, backbone_err)
    L = len(backbone)
    w = Window(0, 0, WindowType.TGS, backbone, b"!" * L)
    for _ in range(depth):
        if rng.random() < 0.3:
            b = rng.randrange(0, L // 2)
            e = rng.randrange(b + L // 4, L)
        else:
            b, e = 0, L - 1
        tfrac = truth[int(b / L * len(truth)): int((e + 1) / L * len(truth))]
        frac = mutate(rng, tfrac, err)
        qual = bytes(33 + min(50, max(1, int(rng.gauss(12, 4))))
                     for _ in frac)
        w.add_layer(frac, qual, b, e)
    return w, backbone


def test_device_consensus_improves_backbone():
    rng = random.Random(5)
    truth = bytes(rng.choice(b"ACGT") for _ in range(400))
    w, backbone = make_window(rng, truth)
    engine = TpuPoaConsensus(3, -5, -4, fallback=None)
    flags = engine.run([w], trim=True)
    assert flags == [True]
    d_bb = edit_distance(backbone, truth)
    d_cons = edit_distance(w.consensus, truth)
    assert d_cons < 0.35 * d_bb
    assert engine.stats["device_windows"] == 1


def test_determinism():
    rng = random.Random(6)
    truth = bytes(rng.choice(b"ACGT") for _ in range(300))
    state = rng.getstate()
    w1, _ = make_window(rng, truth)
    rng.setstate(state)
    w2, _ = make_window(rng, truth)
    engine = TpuPoaConsensus(3, -5, -4, fallback=None)
    engine.run([w1], trim=True)
    engine.run([w2], trim=True)
    assert w1.consensus == w2.consensus


def test_passthrough_below_three_sequences():
    w = Window(0, 0, WindowType.TGS, b"ACGTACGT", b"!" * 8)
    w.add_layer(b"ACGTACGT", None, 0, 7)
    engine = TpuPoaConsensus(3, -5, -4, fallback=None)
    flags = engine.run([w], trim=True)
    assert flags == [False]
    assert w.consensus == b"ACGTACGT"
    assert engine.stats["passthrough"] == 1


def test_cpu_fallback_for_low_effective_depth():
    # max_depth=1 leaves a single usable layer -> CPU fallback
    rng = random.Random(7)
    truth = bytes(rng.choice(b"ACGT") for _ in range(200))
    w, _ = make_window(rng, truth, depth=3)
    engine = TpuPoaConsensus(3, -5, -4,
                             fallback=CpuPoaConsensus(3, -5, -4), max_depth=1)
    flags = engine.run([w], trim=True)
    assert flags == [True]
    assert engine.stats["fallback_windows"] == 1


def test_mixed_batch_with_ngs_window():
    rng = random.Random(8)
    truth = bytes(rng.choice(b"ACGT") for _ in range(300))
    w1, _ = make_window(rng, truth)
    w2 = Window(1, 0, WindowType.NGS, truth, b"!" * len(truth))
    for _ in range(5):
        w2.add_layer(mutate(rng, truth, 0.02), None, 0, len(truth) - 1)
    engine = TpuPoaConsensus(3, -5, -4, fallback=None)
    flags = engine.run([w1, w2], trim=True)
    assert flags == [True, True]
    # NGS windows are never trimmed
    assert edit_distance(w2.consensus, truth) <= 3
