"""SWAR-packed kernel parity harness (runs on the CPU XLA backend via
conftest; the same code paths run on TPU).

The packed paths (int16x2 score lanes, 2-bit bases, packed qpw layer
lanes, the widened insertion accumulator) must be **bit-exact** against
the int32 paths — scores, direction matrices, tracebacks, breaking
points and consensus bytes all equal. These tests are the tier-1 gate
for that contract (wired as a dedicated shard in ci/cpu/test.sh)."""

import numpy as np
import pytest

import jax.numpy as jnp

from racon_tpu.ops import swar
from racon_tpu.ops.nw import (_build_rows_packed, _build_rows_packed2,
                              _nw_wavefront_kernel, _walk_ops_kernel,
                              TpuAligner)

BASES = np.frombuffer(b"ACGT", np.uint8)


# ------------------------------------------------------------ primitives

def _fields(x):
    x = np.asarray(x).astype(np.int64)
    return x & 0xFFFF, (x >> 16) & 0xFFFF


def test_swar16_primitives_match_per_field_reference():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 1 << 15, 8192).astype(np.int32)
    b = rng.integers(0, 1 << 15, 8192).astype(np.int32)
    ap = jnp.asarray(a[0::2] | (a[1::2] << 16))
    bp = jnp.asarray(b[0::2] | (b[1::2] << 16))

    lo, hi = _fields(swar.swar16_ge(ap, bp))
    assert np.array_equal(lo, (a[0::2] >= b[0::2]) * 0xFFFF)
    assert np.array_equal(hi, (a[1::2] >= b[1::2]) * 0xFFFF)

    lo, hi = _fields(swar.swar16_min(ap, bp))
    assert np.array_equal(lo, np.minimum(a[0::2], b[0::2]))
    assert np.array_equal(hi, np.minimum(a[1::2], b[1::2]))

    lo, hi = _fields(swar.swar16_eq(ap, bp))
    assert np.array_equal(lo, (a[0::2] == b[0::2]) * 0xFFFF)
    assert np.array_equal(hi, (a[1::2] == b[1::2]) * 0xFFFF)

    # XOR + mask equality on 4-bit codes
    c = rng.integers(0, 16, 8192).astype(np.int32)
    d = rng.integers(0, 16, 8192).astype(np.int32)
    cp = jnp.asarray(c[0::2] | (c[1::2] << 16))
    dp = jnp.asarray(d[0::2] | (d[1::2] << 16))
    lo, hi = _fields(swar.swar16_ne_small(cp ^ dp, 4))
    assert np.array_equal(lo, (c[0::2] != d[0::2]).astype(np.int64))
    assert np.array_equal(hi, (c[1::2] != d[1::2]).astype(np.int64))


def test_swar_probe_and_overflow_guard():
    assert swar.swar_ok()
    assert swar.swar_fits(16384)       # every current bucket
    assert not swar.swar_fits(32768)   # a hypothetical 32k bucket


# --------------------------------------------------------- kernel parity

def _pack_batch(pairs, max_len, band):
    c = band // 2
    width = c + max_len + band
    B = len(pairs)
    qrp = np.zeros((B, width), np.uint8)
    tp = np.zeros((B, width), np.uint8)
    n = np.zeros(B, np.int32)
    m = np.zeros(B, np.int32)
    for k, (q, t) in enumerate(pairs):
        qrp[k, c + max_len - len(q): c + max_len] = q[::-1]
        tp[k, c: c + len(t)] = t
        n[k], m[k] = len(q), len(t)
    return (jnp.asarray(qrp), jnp.asarray(tp), jnp.asarray(n),
            jnp.asarray(m)), n, m


def _assert_kernel_parity(pairs, max_len, band, steps=0):
    args, n, m = _pack_batch(pairs, max_len, band)
    dp, sp = _nw_wavefront_kernel(*args, max_len=max_len, band=band,
                                  steps=steps, swar=True)
    dx, sx = _nw_wavefront_kernel(*args, max_len=max_len, band=band,
                                  steps=steps)
    assert np.array_equal(np.asarray(dp), np.asarray(dx))
    assert np.array_equal(np.asarray(sp), np.asarray(sx))
    op_p, fip, fjp = _walk_ops_kernel(dp, args[2], args[3], band=band)
    op_x, fix, fjx = _walk_ops_kernel(dx, args[2], args[3], band=band)
    assert np.array_equal(np.asarray(op_p), np.asarray(op_x))
    assert np.array_equal(np.asarray(fip), np.asarray(fix))
    assert np.array_equal(np.asarray(fjp), np.asarray(fjx))


def _mutated_pair(rng, ln, err, ndel=4, nins=4):
    t = BASES[rng.integers(0, 4, ln)]
    q = t.copy()
    flips = rng.random(ln) < err
    q[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
    q = np.delete(q, rng.integers(0, len(q), ndel))
    q = np.insert(q, rng.integers(0, len(q), nins),
                  BASES[rng.integers(0, 4, nins)])
    return q, t


def test_randomized_1k_pair_parity_sweep():
    """The acceptance-criteria sweep: 1k random pairs, packed vs int32 —
    scores, direction matrices and walked tracebacks all bit-equal."""
    rng = np.random.default_rng(41)
    pairs = [_mutated_pair(rng, int(rng.integers(16, 240)),
                           float(rng.uniform(0.0, 0.35)))
             for _ in range(1000)]
    _assert_kernel_parity(pairs, max_len=256, band=128)


def test_band_edge_saturation_parity():
    """Pairs engineered to escape the band (structural rearrangement)
    keep score BIG in both paths and produce identical dirs — the
    saturation classes {BIG, BIG+1} line up across the encodings."""
    rng = np.random.default_rng(42)
    pairs = []
    for _ in range(16):
        ln = int(rng.integers(150, 250))
        t = BASES[rng.integers(0, 4, ln)]
        q = np.concatenate([t[ln // 2:], t[:ln // 2]])  # off-diagonal
        pairs.append((q, t))
    args, n, m = _pack_batch(pairs, 256, 128)
    dp, sp = _nw_wavefront_kernel(*args, max_len=256, band=128, swar=True)
    dx, sx = _nw_wavefront_kernel(*args, max_len=256, band=128)
    assert np.array_equal(np.asarray(dp), np.asarray(dx))
    assert np.array_equal(np.asarray(sp), np.asarray(sx))
    assert np.asarray(sp).max() >= 128 // 2  # at least one real escape


def test_odd_lane_counts_and_bucket_boundaries():
    """Odd (unpaired) batch rows and n/m pinned at the bucket caps: the
    packed path must agree where lengths sit exactly on max_len, on the
    steps bound, and at zero."""
    rng = np.random.default_rng(43)
    max_len = 256
    full = BASES[rng.integers(0, 4, max_len)]
    fullq = full.copy()
    flips = rng.random(max_len) < 0.1
    fullq[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
    pairs = [
        (fullq, full),                    # n = m = max_len (hits steps)
        (full[:0], full[:7]),             # n = 0
        (full[:7], full[:0]),             # m = 0
        (full[:1], full[:1]),             # minimal
        (fullq[:max_len - 1], full),      # one off the cap
        (full, full),                     # identity at the cap
        (fullq[:129], full[:128]),        # straddling band/2
    ]  # 7 rows: odd count, not a power of two
    _assert_kernel_parity(pairs, max_len=max_len, band=128)


def test_aligner_end_to_end_swar_parity():
    """TpuAligner with and without SWAR: identical CIGARs and breaking
    points, including an N-bearing batch (alphabet > 4 symbols falls
    back to the nibble pack) and band-escalation pairs."""
    from racon_tpu.core.backends import PythonAligner

    rng = np.random.default_rng(44)
    pairs, metas = [], []
    for k in range(48):
        q, t = _mutated_pair(rng, int(rng.integers(60, 240)),
                             0.3 if k % 7 == 0 else 0.1)
        if k % 5 == 0:  # sprinkle Ns -> 5-symbol alphabet chunks
            q = q.copy()
            q[rng.integers(0, len(q), 3)] = ord("N")
        pairs.append((q.tobytes(), t.tobytes()))
        metas.append((int(rng.integers(0, 500)), int(rng.integers(0, 200))))
    a_sw = TpuAligner(fallback=PythonAligner())
    a_32 = TpuAligner(fallback=PythonAligner(), use_swar=False)
    assert a_sw.align_batch(pairs) == a_32.align_batch(pairs)
    assert ([a.tolist() for a in a_sw.breaking_points_batch(pairs, metas,
                                                            64)]
            == [a.tolist() for a in a_32.breaking_points_batch(pairs,
                                                               metas, 64)])
    assert a_sw.stats["swar_chunks"] > 0
    assert a_32.stats["swar_chunks"] == 0


def test_build_rows_packed2_matches_nibble_rows():
    """The 2-bit row builder must place exactly the bytes the nibble
    builder places (same codes modulo the encoding bijection) at every
    in-range position; out-of-range lanes are pad in both."""
    from racon_tpu.ops.swar import pack_bases_2bit

    rng = np.random.default_rng(45)
    max_len, band = 256, 128
    B = 8
    codes = rng.integers(0, 4, (B, max_len)).astype(np.uint8)
    n = rng.integers(1, max_len + 1, B).astype(np.int32)
    m = rng.integers(1, max_len + 1, B).astype(np.int32)
    flat = codes.reshape(-1)
    q2 = pack_bases_2bit(flat)
    # nibble encoding of the same data shifted +1 (nibble code 0 is pad)
    q4 = (flat + 1).astype(np.uint8)
    q4 = q4[0::2] | (q4[1::2] << 4)
    nd, md = jnp.asarray(n), jnp.asarray(m)
    qr2, tp2 = _build_rows_packed2(jnp.asarray(q2), jnp.asarray(q2),
                                   nd, md, max_len=max_len, band=band)
    qr4, tp4 = _build_rows_packed(jnp.asarray(q4), jnp.asarray(q4),
                                  nd, md, max_len=max_len, band=band)
    qr4 = np.asarray(qr4).astype(np.int16)
    tp4 = np.asarray(tp4).astype(np.int16)
    # in-range lanes: code2 == code4 - 1; pad lanes are 0 in both
    assert np.array_equal(np.asarray(qr2),
                          np.where(qr4 > 0, qr4 - 1, 0).astype(np.uint8))
    assert np.array_equal(np.asarray(tp2),
                          np.where(tp4 > 0, tp4 - 1, 0).astype(np.uint8))


def test_pallas_swar_kernel_interpret_parity():
    """The explicit int32-word SWAR Mosaic kernel, executed in Pallas
    interpret mode (the only way to run it off-TPU): direction matrix
    and scores bit-equal to the XLA reference. On real hardware the
    same comparison is `pallas_swar_ok()`."""
    from jax.experimental import pallas as pl
    import racon_tpu.ops.pallas_nw as pnw

    rng = np.random.default_rng(50)
    pairs = [_mutated_pair(rng, int(rng.integers(60, 200)), 0.2)
             for _ in range(8)]
    args, n, m = _pack_batch(pairs, 256, 128)
    orig = pl.pallas_call

    def interpreted(*a, **k):
        k["interpret"] = True
        return orig(*a, **k)

    pl.pallas_call = interpreted
    try:
        try:
            dp, sp = pnw.pallas_nw_fwd(*args, max_len=256, band=128,
                                       out_quant=512, use_swar=True)
        except Exception as e:  # interpret-mode support varies by jax
            pytest.skip(f"pallas interpret mode unavailable: {e!r}")
    finally:
        pl.pallas_call = orig
    dx, sx = _nw_wavefront_kernel(*args, max_len=256, band=128)
    mx = int((n + m).max())
    assert np.array_equal(np.asarray(dp)[:, :mx], np.asarray(dx)[:, :mx])
    assert np.array_equal(np.asarray(sp), np.asarray(sx))


# ------------------------------------------------------------- consensus

def _consensus_windows(rng, n_w=8, wl=400, depth=10, with_quality=True):
    from racon_tpu.core.window import Window, WindowType

    windows = []
    for wi in range(n_w):
        truth = BASES[rng.integers(0, 4, wl)]
        bb = truth.copy()
        flips = rng.random(wl) < 0.1
        bb[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
        win = Window(0, wi, WindowType.TGS, bb.tobytes(), b"!" * wl)
        for _ in range(depth):
            layer, _ = _mutated_pair(rng, wl, 0.08, ndel=5, nins=5)
            qual = (bytes(33 + int(x) for x in
                          rng.integers(5, 50, len(layer)))
                    if with_quality else None)
            win.add_layer(layer.tobytes(), qual, 0, wl - 1)
        windows.append(win)
    return windows


def _clone_windows(windows):
    from racon_tpu.core.window import Window

    out = []
    for w in windows:
        c = Window(w.id, w.rank, w.type, w.sequences[0], w.qualities[0])
        for i in range(1, len(w.sequences)):
            b, e = w.positions[i]
            c.add_layer(w.sequences[i], w.qualities[i], b, e)
        out.append(c)
    return out


def test_consensus_swar_parity_bit_exact():
    from racon_tpu.ops.poa import TpuPoaConsensus

    rng = np.random.default_rng(46)
    w1 = _consensus_windows(rng)
    w2 = _clone_windows(w1)
    e_sw = TpuPoaConsensus(3, -5, -4)
    e_32 = TpuPoaConsensus(3, -5, -4, use_swar=False)
    r1 = e_sw.run(w1, trim=True)
    r2 = e_32.run(w2, trim=True)
    assert r1 == r2
    for a, b in zip(w1, w2):
        assert a.consensus == b.consensus
    assert e_sw.stats["device_windows"] == len(w1)


def test_insertion_accumulator_deep_window_regression():
    """Regression for the silent 23-bit-weight / 9-bit-count saturation:
    more than 511 insertion votes at ONE address must accumulate exactly
    (the old single-u32 packing carried the count into the weight bits —
    at 640 votes it wrapped u32 entirely). Covers both the folded and
    the unfolded scatter paths."""
    from racon_tpu.ops.poa import CH, _accumulate_votes

    L, K, nW, band = 64, 4, 2, 64
    addr = (L + 3 * K + 1) * CH + 2   # insertion slot 1 of junction 3
    for B in (640, 600):              # 640 folds (B % 32 == 0), 600 not
        S = 16
        idx = np.full((B, S), L * (1 + K) * CH, np.int32)
        idx[:, 0] = addr
        w = np.zeros((B, S), np.int32)
        w[:, 0] = 9
        ok = np.ones(B, bool)
        win_of = np.zeros(B, np.int32)
        span_m = np.ones(B, np.int32)
        n = np.full(B, 2, np.int32)
        score = np.ones(B, np.int32)
        args = [jnp.asarray(a) for a in
                (idx, w, ok, win_of, span_m, np.zeros(B, np.int32), n,
                 score)]
        weighted, unweighted, ovf, _ = _accumulate_votes(
            *args, n_windows=nW, L=L, K=K, band=band)
        # alpha == 64 at default scores: every vote lands as 9 * 64
        assert float(np.asarray(weighted)[0, addr]) == B * 9 * 64
        assert int(np.asarray(unweighted)[0, addr]) == B
        assert int(ovf) == 0


def test_max_depth_cap_lifted_past_511():
    """The 511 voting-depth clamp existed only to protect the 9-bit
    count field; the widened accumulator moved the ceiling to the f32
    matmul-exactness bound (2047), and the round-10 int8/int32 matmul
    vote path removes that bound at the default scores — the cap moves
    to a conservative 65535 (explicit use_matmul_votes so the test is
    independent of the RACON_TPU_MATMUL_VOTES env)."""
    from racon_tpu.ops.poa import TpuPoaConsensus

    assert TpuPoaConsensus(3, -5, -4, max_depth=4096,
                           use_matmul_votes=False).max_depth == 2047
    assert TpuPoaConsensus(3, -5, -4, max_depth=4096,
                           use_matmul_votes=True).max_depth == 4096
    assert TpuPoaConsensus(3, -5, -4, max_depth=10 ** 6,
                           use_matmul_votes=True).max_depth == 65535
    assert TpuPoaConsensus(3, -5, -4, max_depth=200,
                           use_matmul_votes=True).max_depth == 200
    # custom -m/-x/-g: vote sums lose 64-alignment, the f32 handoff to
    # the consensus kernel re-binds the cap at 2047 even on matmul votes
    assert TpuPoaConsensus(4, -5, -4, max_depth=4096,
                           use_matmul_votes=True).max_depth == 2047


def test_matmul_votes_deep_address_regression():
    """Round 10: >= 4096 votes on ONE address through the int8-matmul
    vote path accumulate exactly, bit-compared against an integer numpy
    reference — the per-address weighted sum here (4608 x 5760 ≈ 26.5M)
    is past the 2^24 f32-exactness bound that set the old 2047 depth
    cap, so only an exact integer reduction can pass. Extends the
    round-6 600+640-vote test (which stayed under the f32 bound)."""
    from racon_tpu.ops.poa import CH, _accumulate_votes

    L, K, nW, band = 64, 4, 2, 64
    B, S = 4608, 16
    col_addr = 5 * CH + 1             # column 5 (bg=0, span 6), base C
    ins_addr = (L + 3 * K + 1) * CH + 2  # junction 3, slot 1, base G
    idx = np.full((B, S), L * (1 + K) * CH, np.int32)
    idx[:, 0] = col_addr
    idx[:, 1] = ins_addr
    w = np.zeros((B, S), np.int32)
    w[:, 0] = 90                      # x alpha 64 -> 5760 per vote
    w[:, 1] = 90
    ok = np.ones(B, bool)
    win_of = np.zeros(B, np.int32)
    span_m = np.full(B, 6, np.int32)  # one col step -> lands column 5
    n = np.full(B, 2, np.int32)
    score = np.ones(B, np.int32)
    args = [jnp.asarray(a) for a in
            (idx, w, ok, win_of, span_m, np.zeros(B, np.int32), n,
             score)]
    weighted, unweighted, ovf, _ = _accumulate_votes(
        *args, n_windows=nW, L=L, K=K, band=band, matmul_votes=True)
    expect = np.int64(B) * 90 * 64
    assert expect > (1 << 24)         # past the old f32 exactness bound
    for addr in (col_addr, ins_addr):
        assert int(np.asarray(weighted)[0, addr]) == expect
        assert int(np.asarray(unweighted)[0, addr]) == B
    assert int(ovf) == 0
    # the unweighted counts (exact ints on both paths) must agree with
    # the scatter/f32 reference emitter bit-for-bit
    _, unw_ref, _, _ = _accumulate_votes(
        *args, n_windows=nW, L=L, K=K, band=band, matmul_votes=False)
    assert np.array_equal(np.asarray(unweighted), np.asarray(unw_ref))


# --------------------------------------------------------------- warm-up

def test_warmup_async_compiles_and_engine_still_exact():
    from racon_tpu.ops.poa import TpuPoaConsensus

    rng = np.random.default_rng(47)
    eng = TpuPoaConsensus(3, -5, -4)
    th = eng.warmup_async(64, est_pairs=64, est_windows=8)
    assert th is not None
    th.join(timeout=300)
    assert not th.is_alive()
    # the engine still produces the exact non-warmed results
    w1 = _consensus_windows(rng, n_w=4, wl=120, depth=6)
    w2 = _clone_windows(w1)
    ref = TpuPoaConsensus(3, -5, -4)
    assert eng.run(w1, trim=True) == ref.run(w2, trim=True)
    for a, b in zip(w1, w2):
        assert a.consensus == b.consensus


def test_warmup_skipped_for_empty_estimates():
    from racon_tpu.ops.poa import TpuPoaConsensus

    assert TpuPoaConsensus(3, -5, -4).warmup_async(500, 0, 0) is None


# ------------------------------------------------------ streaming parser

def test_native_parser_streams_multi_chunk_gzip(tmp_path):
    """Records spanning the chunked-inflate boundaries (>1 MiB buffer)
    parse identically to the Python oracle — the bounded-buffer rewrite
    must not change a byte."""
    from racon_tpu.io import parsers
    from racon_tpu import native

    if not native.available():
        pytest.skip("native core unavailable")
    import gzip

    rng = np.random.default_rng(48)
    chunks = []
    for i in range(300):
        seq = BASES[rng.integers(0, 4, 12000)].tobytes()
        qual = bytes(33 + int(x) for x in rng.integers(0, 60, len(seq)))
        chunks.append(b"@read_%d some description\n%s\n+\n%s\n"
                      % (i, seq, qual))
    raw = b"".join(chunks)
    assert len(raw) > 3 << 20  # several LineReader chunks
    path = tmp_path / "big.fastq.gz"
    path.write_bytes(gzip.compress(raw))
    nat = list(parsers.parse_fastq(str(path)))
    ora = list(parsers._parse_fastq_py(str(path)))
    assert len(nat) == len(ora) == 300
    for a, b in zip(nat, ora):
        assert (a.name, a.data, a.quality) == (b.name, b.data, b.quality)


def test_native_parser_long_single_line_fasta(tmp_path):
    """A FASTA record on one line longer than the read chunk exercises
    the rolling buffer's growth path."""
    from racon_tpu.io import parsers
    from racon_tpu import native

    if not native.available():
        pytest.skip("native core unavailable")
    rng = np.random.default_rng(49)
    seq = BASES[rng.integers(0, 4, (1 << 20) + 12345)].tobytes()
    path = tmp_path / "one_line.fasta"
    path.write_bytes(b">contig_long trailing meta\n" + seq + b"\n")
    recs = list(parsers.parse_fasta(str(path)))
    assert len(recs) == 1
    assert recs[0].name == b"contig_long"
    assert recs[0].data == seq
