"""Overlap semantics tests: constructors, transmute, breaking points.

The run-based breaking-point walker is validated against a direct per-base
re-implementation of the reference's loop (``src/overlap.cpp:226-292``)."""

import random

import pytest

from racon_tpu.core.overlap import Overlap
from racon_tpu.core.sequence import Sequence
from racon_tpu.utils.cigar import parse_cigar


def perbase_breaking_points(cigar, strand, q_begin, q_end, q_length,
                            t_begin, t_end, window_length):
    """Literal per-base transcription of the reference walker (oracle)."""
    window_ends = []
    i = 0
    while i < t_end:
        if i > t_begin:
            window_ends.append(i - 1)
        i += window_length
    window_ends.append(t_end - 1)

    w = 0
    found = False
    first = (0, 0)
    last = (0, 0)
    out = []
    q_ptr = (q_length - q_end if strand else q_begin) - 1
    t_ptr = t_begin - 1
    for n, op in parse_cigar(cigar):
        if op in ("M", "=", "X"):
            for _ in range(n):
                q_ptr += 1
                t_ptr += 1
                if not found:
                    found = True
                    first = (t_ptr, q_ptr)
                last = (t_ptr + 1, q_ptr + 1)
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found:
                        out.append(first)
                        out.append(last)
                    found = False
                    w += 1
        elif op == "I":
            q_ptr += n
        elif op in ("D", "N"):
            for _ in range(n):
                t_ptr += 1
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found:
                        out.append(first)
                        out.append(last)
                    found = False
                    w += 1
    return out


def random_cigar(rng, approx_len):
    ops = []
    total_t = 0
    while total_t < approx_len:
        op = rng.choices(["M", "I", "D"], weights=[8, 1, 1])[0]
        n = rng.randint(1, 30)
        ops.append(f"{n}{op}")
        if op in ("M", "D"):
            total_t += n
    return "".join(ops), total_t


@pytest.mark.parametrize("seed", range(8))
def test_breaking_points_match_perbase_walker(seed):
    rng = random.Random(seed)
    window_length = rng.choice([25, 100, 500])
    t_begin = rng.randint(0, 700)
    cigar, t_span = random_cigar(rng, rng.randint(40, 2000))
    t_end = t_begin + t_span
    # q span derived from cigar
    q_span = sum(n for n, op in parse_cigar(cigar) if op in ("M", "I"))
    strand = rng.random() < 0.5
    q_begin = rng.randint(0, 50)
    q_end = q_begin + q_span
    q_length = q_end + rng.randint(0, 50)

    o = Overlap()
    o.q_begin, o.q_end, o.q_length = q_begin, q_end, q_length
    o.t_begin, o.t_end = t_begin, t_end
    o.strand = strand
    o.cigar = cigar
    o.is_transmuted = True
    o.find_breaking_points_from_cigar(window_length)

    expected = perbase_breaking_points(
        cigar, strand, q_begin, q_end, q_length, t_begin, t_end, window_length)
    assert o.breaking_point_pairs() == expected
    # columnar invariants: (k, 4) int32 rows, one per window region
    assert o.breaking_points.dtype.name == "int32"
    assert o.breaking_points.shape == (len(expected) // 2, 4)


@pytest.mark.parametrize("seed", range(4))
def test_native_bp_decode_matches_python_walker(seed):
    """The native thread-pool CIGAR decoder (native/bp.cpp) must emit
    rows identical to the Python run-based walker for whole batches,
    including empty CIGARs and unknown ops."""
    import numpy as np

    from racon_tpu import native
    from racon_tpu.core.overlap import (breaking_points_from_cigar,
                                        bp_pairs_to_array,
                                        decode_breaking_points_batch)

    if not native.available():
        pytest.skip("native library unavailable")
    rng = random.Random(100 + seed)
    window_length = rng.choice([25, 100, 500])
    cigars, qos, tbs, tes = [], [], [], []
    for _ in range(64):
        cigar, t_span = random_cigar(rng, rng.randint(40, 1500))
        tb = rng.randint(0, 700)
        cigars.append(cigar)
        qos.append(rng.randint(0, 300))
        tbs.append(tb)
        tes.append(tb + t_span)
    cigars.append("")  # degenerate: no runs -> no rows
    qos.append(1)
    tbs.append(5)
    tes.append(5)
    arrs = decode_breaking_points_batch(cigars, qos, tbs, tes,
                                        window_length, num_threads=4)
    for cig, qo, tb, te, arr in zip(cigars, qos, tbs, tes, arrs):
        oracle = bp_pairs_to_array(breaking_points_from_cigar(
            cig, qo, tb, te, window_length))
        assert np.array_equal(arr, oracle)


def test_paf_ctor_error():
    o = Overlap.from_paf(b"q", 100, 10, 90, "+", b"t", 200, 20, 120)
    assert o.length == 100
    assert o.error == pytest.approx(1 - 80 / 100)
    assert not o.strand


def test_mhap_ctor_ids_are_one_based():
    o = Overlap.from_mhap(1, 2, 0, 10, 90, 100, 1, 20, 120, 200)
    assert o.q_id == 0 and o.t_id == 1
    assert o.strand  # 0 ^ 1


def test_sam_ctor_clips_and_strand():
    # 5S10M2I3D5M3S on forward strand
    o = Overlap.from_sam(b"q", 0, b"t", 101, b"5S10M2I3D5M3S")
    assert o.t_begin == 100
    assert o.q_begin == 5
    assert o.q_end == 5 + 10 + 2 + 5
    assert o.q_length == 5 + 17 + 3
    assert o.t_end == 100 + 10 + 3 + 5
    # reverse strand flips q coords
    o2 = Overlap.from_sam(b"q", 16, b"t", 101, b"5S10M2I3D5M3S")
    assert o2.strand
    assert o2.q_begin == o2.q_length - o.q_end
    assert o2.q_end == o2.q_length - o.q_begin


def test_sam_unmapped_is_invalid():
    o = Overlap.from_sam(b"q", 4, b"t", 0, b"*")
    assert not o.is_valid


def test_transmute_by_name():
    seqs = [Sequence(b"t1", b"A" * 200), Sequence(b"r1", b"C" * 100)]
    name_to_id = {b"t1t": 0, b"t1q": 0, b"r1q": 1}
    o = Overlap.from_paf(b"r1", 100, 10, 90, "+", b"t1", 200, 20, 120)
    o.transmute(seqs, name_to_id, {})
    assert o.is_transmuted and o.q_id == 1 and o.t_id == 0

    o2 = Overlap.from_paf(b"unknown", 100, 10, 90, "+", b"t1", 200, 20, 120)
    o2.transmute(seqs, name_to_id, {})
    assert not o2.is_valid


def test_transmute_length_mismatch_raises():
    seqs = [Sequence(b"t1", b"A" * 200), Sequence(b"r1", b"C" * 100)]
    name_to_id = {b"t1t": 0, b"r1q": 1}
    o = Overlap.from_paf(b"r1", 999, 10, 90, "+", b"t1", 200, 20, 120)
    with pytest.raises(ValueError):
        o.transmute(seqs, name_to_id, {})


def test_query_span_strand():
    s = Sequence(b"r", b"AACCGGTT")
    seqs = [Sequence(b"t", b"A" * 8), s]
    o = Overlap.from_paf(b"r", 8, 2, 6, "-", b"t", 8, 0, 4)
    o.q_id, o.t_id = 1, 0
    o.is_transmuted = True
    # reverse complement of AACCGGTT = AACCGGTT
    assert o.query_span_bytes(seqs) == s.reverse_complement[2:6]
