"""First-party overlapper suite (``--overlaps auto``): randomized
kernel-vs-numpy-oracle parity for both stages (minimizer seeding and
chain DP), strand canonicalization, the slice-boundary dedup, the
resident fetch path, frequency-cap accounting, warm-up shape caching,
and the end-to-end determinism contract — auto-mode polish output
byte-identical across thread counts and ``--shards 2``, gz/FASTQ/FASTA
input variants producing identical auto PAFs, F mode, and the
planner/rampler no-overlaps-file cases.
"""

import gzip
import io
import pathlib

import numpy as np
import pytest

from test_columnar_init import write_synthetic_assembly

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.exec import ShardRunner
from racon_tpu.exec.index import build_index_readsonly, write_auto_paf
from racon_tpu.exec.planner import estimate_job_cost
from racon_tpu.io import parsers
from racon_tpu.obs import metrics
from racon_tpu.ops import chain, overlap_seed

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])
_ACGT = np.frombuffer(b"ACGT", np.uint8)
_COMP = bytes.maketrans(b"ACGT", b"TGCA")


def rand_seq(rng, n):
    return rng.choice(_ACGT, size=n).astype(np.uint8).tobytes()


def revcomp(s):
    return s.translate(_COMP)[::-1]


def table_rows(table):
    h, i, p, s = table
    return list(zip(i.tolist(), p.tolist(), h.tolist(),
                    np.asarray(s, bool).tolist()))


# ------------------------------------------------- stage 1: minimizers

def test_minimizer_matches_numpy_oracle():
    """The jit'd minimizer kernel agrees with the pure-numpy oracle
    exactly — randomized lengths, several (k, w) geometries, ambiguous
    bases included."""
    rng = np.random.default_rng(11)
    for k, w in ((15, 5), (11, 3), (8, 7), (4, 1)):
        for trial in range(4):
            n = int(rng.integers(k + w - 1, 3000))
            seq = bytearray(rand_seq(rng, n))
            if trial % 2:  # sprinkle ambiguity
                for j in rng.integers(0, n, size=max(1, n // 50)):
                    seq[int(j)] = ord(b"N")
            seq = bytes(seq)
            got = table_rows(overlap_seed.build_seed_table(
                [seq], k=k, w=w))
            want = [(0, p, h, bool(s))
                    for h, p, s in overlap_seed.minimizers_np(seq, k, w)]
            assert got == want, (k, w, trial, n)


def test_minimizer_strand_canonical():
    """Reverse-complementing a sequence yields the same canonical hash
    multiset with mirrored positions (p -> L - k - p) and flipped
    strand bits — the property seed matching across strands rests on."""
    rng = np.random.default_rng(12)
    k, w = 15, 5
    seq = rand_seq(rng, 1200)
    fwd = overlap_seed.minimizers_np(seq, k, w)
    rev = overlap_seed.minimizers_np(revcomp(seq), k, w)
    L = len(seq)
    # windowed selection differs at the edges, but every interior
    # minimizer must appear mirrored; compare the intersection both ways
    fset = {(h, p, s) for h, p, s in fwd}
    rset = {(h, p, s) for h, p, s in rev}
    mirrored = {(h, L - k - p, 1 - s) for h, p, s in rev}
    assert len(fset & mirrored) >= int(0.9 * min(len(fset), len(rset)))
    assert {h for h, _, _ in fset} == {h for h, _, _ in mirrored}


def test_minimizer_slice_boundary_dedup(monkeypatch):
    """Long sequences are seeded in bounded overlapping slices; a
    minimizer selected by windows on both sides of a slice boundary
    must emit ONCE. Shrinking SEED_SLICE forces many boundaries through
    a short sequence so the dedup is exercised cheaply."""
    rng = np.random.default_rng(13)
    seq = rand_seq(rng, 700)
    want = table_rows(overlap_seed.build_seed_table([seq]))
    monkeypatch.setattr(overlap_seed, "SEED_SLICE", 64)
    got = table_rows(overlap_seed.build_seed_table([seq]))
    assert got == want


def test_seed_table_resident_matches_host():
    """The device-compaction fetch path returns the identical table to
    the host nonzero path (order included)."""
    rng = np.random.default_rng(14)
    seqs = [rand_seq(rng, int(n)) for n in rng.integers(80, 1500, 6)]
    host = table_rows(overlap_seed.build_seed_table(seqs))
    res = table_rows(overlap_seed.build_seed_table(seqs, resident=True))
    assert res == host


def test_seed_table_skips_short_sequences():
    rng = np.random.default_rng(15)
    k, w = 15, 5
    table = overlap_seed.build_seed_table(
        [b"ACGT", rand_seq(rng, 400), b""], k=k, w=w)
    assert set(table[1].tolist()) == {1}


# --------------------------------------------------- stage 2: chain DP

def test_chain_kernel_matches_numpy_oracle():
    """The banded chain DP kernel reproduces the integer numpy oracle
    bit-exactly over randomized seed sets (score, seed count, and the
    chained span)."""
    rng = np.random.default_rng(21)
    k = 15
    for S in (16, 32):
        B = chain._pair_batch(S, 3)
        ts = np.zeros((B, S), np.int32)
        qs = np.zeros((B, S), np.int32)
        ns = np.zeros(B, np.int32)
        for lane in range(3):
            n = int(rng.integers(S // 2, S + 1))
            t = np.sort(rng.integers(0, 4000, n)).astype(np.int32)
            q = (t + rng.integers(-300, 300, n)).clip(0).astype(np.int32)
            ts[lane, :n], qs[lane, :n], ns[lane] = t, q, n
        out = np.asarray(chain._chain_kernel(ts, qs, ns, S=S, k=k))
        for lane in range(3):
            n = int(ns[lane])
            want = chain.chain_np(ts[lane, :n], qs[lane, :n], k)
            assert out[lane].tolist() == list(want), (S, lane)


def test_find_overlaps_exact_spans():
    """Reads cut verbatim from a target map back to their exact source
    spans with the right strand (forward and reverse-complement)."""
    rng = np.random.default_rng(22)
    target = rand_seq(rng, 8000)
    fwd = target[1000:4000]
    rev = revcomp(target[4500:7500])
    rows = chain.find_overlaps([fwd, rev], [target],
                               np.full(2, -1, np.int64),
                               k=15, w=5, max_occ=64, min_seeds=4)
    for q, strand, t_lo, t_hi in ((0, 0, 1000, 4000),
                                  (1, 1, 4500, 7500)):
        mine = np.flatnonzero(rows["q_ord"] == q)
        assert mine.size == 1
        i = int(mine[0])
        assert int(rows["strand"][i]) == strand
        assert abs(int(rows["t_begin"][i]) - t_lo) < 40
        assert abs(int(rows["t_end"][i]) - t_hi) < 40
        span = int(rows["q_end"][i]) - int(rows["q_begin"][i])
        assert span > 2800


def test_find_overlaps_suppresses_self_hits():
    """C-mode self suppression: a read that IS target j emits no row
    against j, but still maps to other targets."""
    rng = np.random.default_rng(23)
    t0 = rand_seq(rng, 3000)
    t1 = t0[:2000] + rand_seq(rng, 1000)  # shares a 2 kb prefix
    rows = chain.find_overlaps([t0], [t0, t1],
                               np.array([0], np.int64), k=15, w=5)
    assert 0 not in rows["t_idx"].tolist()
    assert 1 in rows["t_idx"].tolist()


def test_freq_cap_accounting():
    """Buckets hotter than max_occ drop WHOLE and are counted — never
    silently; raising the cap readmits them."""
    rng = np.random.default_rng(24)
    motif = rand_seq(rng, 400)
    reads = [motif] * 12  # every minimizer bucket has 12+12 entries
    rt = overlap_seed.build_seed_table(reads)
    tt = overlap_seed.build_seed_table(reads)
    self_t = np.full(12, -1, np.int64)
    qlens = np.full(12, 400, np.int64)
    hits, capped = chain.match_seeds(rt, tt, self_t, qlens,
                                     k=15, max_occ=4)
    assert capped > 0 and hits["q"].size == 0
    hits2, capped2 = chain.match_seeds(rt, tt, self_t, qlens,
                                       k=15, max_occ=64)
    assert capped2 == 0 and hits2["q"].size > 0


def test_min_seeds_drop_accounting():
    """Pairs under the min_seeds floor are dropped and counted, both
    pre-DP (candidate too small) and post-DP (chain too small)."""
    rng = np.random.default_rng(25)
    target = rand_seq(rng, 4000)
    reads = [target[500:2500], rand_seq(rng, 2000)]
    rows_loose = chain.find_overlaps(reads, [target],
                                     np.full(2, -1, np.int64),
                                     k=15, w=5, min_seeds=4)
    rows_tight = chain.find_overlaps(reads, [target],
                                     np.full(2, -1, np.int64),
                                     k=15, w=5, min_seeds=10 ** 6)
    assert rows_loose["q_ord"].size > 0
    assert rows_tight["q_ord"].size == 0


# ------------------------------------------- stage 1.5: device seed join

def rand_table(rng, n_seqs, n_entries, hash_space):
    """A synthetic minimizer table with a deliberately tiny hash space
    (dense cross-table collisions) — deduped on (seq, pos) exactly like
    ``build_seed_table``, the property that makes the join's 5-tuples
    unique and the device sort's tie-break freedom harmless."""
    sid = rng.integers(0, n_seqs, n_entries).astype(np.int32)
    pos = rng.integers(0, 4000, n_entries).astype(np.int32)
    order = np.lexsort((pos, sid))
    sid, pos = sid[order], pos[order]
    keep = np.ones(sid.size, bool)
    keep[1:] = (sid[1:] != sid[:-1]) | (pos[1:] != pos[:-1])
    sid, pos = sid[keep], pos[keep]
    h = rng.integers(0, hash_space, sid.size).astype(np.uint32)
    strand = rng.integers(0, 2, sid.size).astype(bool)
    return h, sid, pos, strand


def test_device_join_matches_oracle():
    """The device seed join (sort kernel + ragged expand kernel)
    reproduces the numpy ``match_seeds`` oracle exactly — randomized
    dense tables (collision-heavy hash space), both strands, self-hit
    suppression, and hot-bucket capping included — with zero bail-outs
    to the oracle."""
    rng = np.random.default_rng(31)
    before = metrics.counter("overlap.join_bailouts")
    for trial in range(8):
        n_reads = int(rng.integers(2, 10))
        n_targets = int(rng.integers(1, 6))
        hash_space = int(rng.integers(20, 300))
        max_occ = int(rng.integers(2, 40))
        rt = rand_table(rng, n_reads, int(rng.integers(50, 600)),
                        hash_space)
        tt = rand_table(rng, n_targets, int(rng.integers(50, 600)),
                        hash_space)
        self_t = np.where(rng.random(n_reads) < 0.3,
                          rng.integers(0, n_targets, n_reads),
                          -1).astype(np.int64)
        qlens = rng.integers(4100, 6000, n_reads).astype(np.int64)
        want, capped_w = chain.match_seeds(rt, tt, self_t, qlens,
                                           k=15, max_occ=max_occ)
        got, capped_g = chain.join_seeds(rt, tt, self_t, qlens, k=15,
                                         max_occ=max_occ,
                                         device_join=True)
        assert capped_g == capped_w, trial
        for key in ("q", "t", "rel", "tp", "qc"):
            assert np.array_equal(np.asarray(got[key], np.int64),
                                  want[key]), (trial, key)
    assert metrics.counter("overlap.join_bailouts") == before


def test_device_join_resident_layout():
    """Under ``resident=True`` the join keeps the matched seed
    coordinates on device (``tp_dev``/``qc_dev``); their valid prefix
    must equal the oracle's host ``tp``/``qc`` columns."""
    rng = np.random.default_rng(32)
    rt = rand_table(rng, 6, 400, 150)
    tt = rand_table(rng, 3, 400, 150)
    self_t = np.full(6, -1, np.int64)
    qlens = np.full(6, 5000, np.int64)
    want, _ = chain.match_seeds(rt, tt, self_t, qlens, k=15, max_occ=32)
    got, _ = chain.join_seeds(rt, tt, self_t, qlens, k=15, max_occ=32,
                              device_join=True, resident=True)
    assert "tp_dev" in got and "qc_dev" in got and "tp" not in got
    n = got["q"].size
    assert n == want["q"].size > 0
    assert np.array_equal(np.asarray(got["tp_dev"])[:n].astype(np.int64),
                          want["tp"])
    assert np.array_equal(np.asarray(got["qc_dev"])[:n].astype(np.int64),
                          want["qc"])


def test_device_join_empty_side_bails_to_oracle():
    """An empty table on either side takes the counted bail-out rung —
    the oracle's trivial path, never a kernel launch."""
    rng = np.random.default_rng(33)
    rt = rand_table(rng, 4, 200, 100)
    empty = (np.zeros(0, np.uint32), np.zeros(0, np.int32),
             np.zeros(0, np.int32), np.zeros(0, bool))
    before = metrics.counter("overlap.join_bailouts")
    hits, capped = chain.join_seeds(rt, empty, np.full(4, -1, np.int64),
                                    np.full(4, 5000, np.int64),
                                    k=15, max_occ=64, device_join=True)
    assert hits["q"].size == 0 and capped == 0
    assert metrics.counter("overlap.join_bailouts") == before + 1


# ------------------------------------------- stage 2.5: ragged streaming

def test_chain_stream_feed_batching_invariance():
    """Per-pair chain rows are invariant to how the stream is fed: one
    giant batch, pair-at-a-time pumping, and ragged 3-pair batches all
    yield identical rows for every pair id — the property the
    streamed/barriered byte-identity contract rests on."""
    rng = np.random.default_rng(34)
    target = rand_seq(rng, 6000)
    reads = [target[i * 400:i * 400 + 1500] for i in range(8)]
    reads += [revcomp(target[2000:3500]), rand_seq(rng, 900)]
    rt = overlap_seed.build_seed_table(reads)
    tt = overlap_seed.build_seed_table([target])
    self_t = np.full(len(reads), -1, np.int64)
    qlens = np.fromiter((len(r) for r in reads), np.int64, len(reads))
    hits, _ = chain.match_seeds(rt, tt, self_t, qlens, k=15, max_occ=64)
    starts, _, counts = chain._pair_runs(hits)
    jobs = [(p, int(starts[p]), int(counts[p]))
            for p in range(starts.size)]
    assert len(jobs) >= 9
    outs = []
    for split in (len(jobs), 1, 3):
        st = chain._ChainStream(k=15, tp=hits["tp"], qc=hits["qc"])
        for i, (pid, s0, c) in enumerate(jobs):
            st.add(pid, s0, c)
            if (i + 1) % split == 0:
                st.pump()
        outs.append(st.finish())
    for other in outs[1:]:
        assert set(other) == set(outs[0])
        for pid in outs[0]:
            assert other[pid].tolist() == outs[0][pid].tolist()


def test_ragged_stream_matches_barrier_rows():
    """find_overlaps emits identical rows (and PAF bytes) across the
    2x2 of {ragged stream, phase barrier} x {device join, host join} —
    the kernel-level half of the acceptance byte-identity matrix; the
    vectorized PAF writer must match its row-at-a-time oracle on the
    same rows."""
    rng = np.random.default_rng(35)
    target = rand_seq(rng, 9000)
    reads = [target[500:3200], revcomp(target[2800:6000]),
             target[5500:8700], rand_seq(rng, 2000),
             revcomp(target[100:1900])]
    self_t = np.full(len(reads), -1, np.int64)
    legs = {}
    for ragged in (True, False):
        for dj in (True, False):
            legs[(ragged, dj)] = chain.find_overlaps(
                reads, [target], self_t, k=15, w=5,
                ragged=ragged, device_join=dj)
    base = legs[(True, True)]
    assert base["q_ord"].size > 0
    for key_leg, rows in legs.items():
        for col in chain._ROW_KEYS:
            assert np.array_equal(rows[col], base[col]), (key_leg, col)
    names = [b"r%d" % i for i in range(len(reads))]
    lens = np.fromiter((len(r) for r in reads), np.int64, len(reads))
    vec = chain.paf_bytes(base, names, lens, [b"t0"],
                          np.array([len(target)], np.int64), k=15)
    oracle = chain.paf_bytes_rowwise(base, names, lens, [b"t0"],
                                     np.array([len(target)], np.int64),
                                     k=15)
    assert vec and vec == oracle
    assert chain.paf_bytes({key: v[:0] for key, v in base.items()},
                           names, lens, [b"t0"],
                           np.array([len(target)], np.int64), k=15) == []


def test_warmed_repeat_run_zero_new_compiles():
    """The serve-job contract: a repeat of an identical overlap run
    dispatches the chain stream into already-compiled executables —
    the jit cache must not grow by a single entry on the second run."""
    rng = np.random.default_rng(36)
    target = rand_seq(rng, 5000)
    reads = [target[200:1800], target[2500:4200],
             revcomp(target[1000:2600])]
    self_t = np.full(3, -1, np.int64)
    first = chain.find_overlaps(reads, [target], self_t, k=15, w=5,
                                ragged=True)
    before = chain._chain_kernel._cache_size()
    again = chain.find_overlaps(reads, [target], self_t, k=15, w=5,
                                ragged=True)
    assert chain._chain_kernel._cache_size() == before
    for col in chain._ROW_KEYS:
        assert np.array_equal(first[col], again[col])


# ------------------------------------------------------------- warm-up

def test_warmup_shape_cache():
    """warmup_async compiles each (shape, k, w) geometry once per
    process: the first call returns a live thread, an identical second
    call is a cache hit and returns None (the cache-size claim — the
    set grows by exactly the new shapes)."""
    before = len(overlap_seed._warmed_shapes)
    th = overlap_seed.warmup_async(900, 7, k=9, w=4)
    assert th is not None
    th.join(60.0)
    assert not th.is_alive()
    assert len(overlap_seed._warmed_shapes) == before + 1
    assert overlap_seed.warmup_async(900, 7, k=9, w=4) is None
    assert len(overlap_seed._warmed_shapes) == before + 1

    before_c = len(chain._warmed_shapes)
    ladder = chain._warmup_shapes(24, 5)
    assert 1 <= len(ladder) <= 4
    th_c = chain.warmup_async(24, 5, k=9)
    assert th_c is not None
    th_c.join(60.0)
    assert not th_c.is_alive()
    assert len(chain._warmed_shapes) == before_c + len(ladder)
    assert chain.warmup_async(24, 5, k=9) is None
    assert len(chain._warmed_shapes) == before_c + len(ladder)


def test_warmup_zero_estimates_skip():
    assert overlap_seed.warmup_async(0, 0) is None
    assert chain.warmup_async(0, 0) is None


# ------------------------------------------- end-to-end: --overlaps auto

def fasta_bytes(seqs):
    return b"".join(b">" + s.name + b"\n" + s.data + b"\n" for s in seqs)


def auto_single_shot(rp, lp, num_threads=4, type_=PolisherType.C):
    p = create_polisher(str(rp), parsers.AUTO_OVERLAPS, str(lp), type_,
                        num_threads=num_threads)
    return fasta_bytes(p.run(True))


@pytest.fixture(scope="module")
def assembly(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ovl")
    return write_synthetic_assembly(tmp, seed=41, n_contigs=2,
                                    contig=3000)


def test_auto_mode_polishes(assembly):
    """--overlaps auto end-to-end on the synthetic assembly: both
    contigs polish (the PAF-free path finds the read pile-ups), and the
    output carries the standard polished headers."""
    rp, _, lp = assembly
    out = auto_single_shot(rp, lp)
    assert out.count(b">") == 2
    assert b"ctg0" in out and b"ctg1" in out


def test_auto_mode_thread_determinism(assembly):
    """Auto-mode output is byte-identical across worker thread counts
    (the overlapper sorts canonically; threading must not leak in)."""
    rp, _, lp = assembly
    assert auto_single_shot(rp, lp, num_threads=1) == \
        auto_single_shot(rp, lp, num_threads=4)


def test_auto_mode_shards_byte_identical(assembly, tmp_path):
    """A --shards 2 auto run (PAF materialized into the work dir, index
    replayed over it) is byte-identical to the single-shot in-memory
    path — the acceptance determinism contract."""
    rp, _, lp = assembly
    want = auto_single_shot(rp, lp)
    runner = ShardRunner(str(rp), parsers.AUTO_OVERLAPS, str(lp),
                         work_dir=str(tmp_path / "work"), n_shards=2,
                         num_threads=4)
    buf = io.BytesIO()
    summary = runner.run(buf)
    assert buf.getvalue() == want
    assert summary["n_shards"] == 2
    assert (tmp_path / "work" / "auto_overlaps.paf").stat().st_size > 0


def test_auto_mode_flag_matrix_byte_identical(assembly, tmp_path,
                                              monkeypatch):
    """The acceptance determinism matrix at the polisher level: the
    polished FASTA is byte-identical across {device join, host join} x
    {streaming handoff, barrier} — including a barriered --shards 2 run
    against the default streamed single-shot."""
    rp, _, lp = assembly
    want = auto_single_shot(rp, lp)
    for dj, rag in (("0", "1"), ("1", "0"), ("0", "0")):
        monkeypatch.setenv("RACON_TPU_OVERLAP_DEVICE_JOIN", dj)
        monkeypatch.setenv("RACON_TPU_OVERLAP_RAGGED", rag)
        assert auto_single_shot(rp, lp) == want, (dj, rag)
    runner = ShardRunner(str(rp), parsers.AUTO_OVERLAPS, str(lp),
                         work_dir=str(tmp_path / "work"), n_shards=2,
                         num_threads=4)
    buf = io.BytesIO()
    runner.run(buf)
    assert buf.getvalue() == want


def test_auto_mode_f_mode(assembly):
    """Fragment correction (-f) with auto overlaps: reads map against
    the read pool itself with self-hits suppressed, and correction
    emits corrected reads."""
    rp, _, _ = assembly
    out = auto_single_shot(rp, rp, type_=PolisherType.F)
    assert out.count(b">") > 10


def test_auto_paf_input_variants(assembly, tmp_path):
    """write_auto_paf emits identical PAF bytes whether the reads
    arrive as FASTQ, gzipped FASTQ, or FASTA — parser-layer variance
    must not reach the overlapper."""
    rp, _, lp = assembly
    raw = pathlib.Path(rp).read_bytes()
    gz = tmp_path / "reads.fastq.gz"
    with gzip.open(gz, "wb") as f:
        f.write(raw)
    fa = tmp_path / "reads.fasta"
    lines = raw.split(b"\n")
    with open(fa, "wb") as f:
        for i in range(0, len(lines) - 3, 4):
            f.write(b">" + lines[i][1:] + b"\n" + lines[i + 1] + b"\n")
    outs = []
    for i, reads in enumerate((rp, gz, fa)):
        paf = tmp_path / f"auto{i}.paf"
        write_auto_paf(str(reads), str(lp), str(paf))
        outs.append(paf.read_bytes())
    assert outs[0] and outs[0] == outs[1] == outs[2]


def test_auto_mode_rejects_bad_extension_still(assembly):
    """'auto' is a sentinel, not a loosened parser: a real path with an
    unknown extension still raises."""
    rp, _, lp = assembly
    with pytest.raises(ValueError, match="auto"):
        create_polisher(str(rp), "overlaps.xyz", str(lp),
                        PolisherType.C, num_threads=1)


# ----------------------------------------- planner / rampler auto cases

def test_estimate_job_cost_auto(assembly):
    """Auto jobs have no overlaps file: the estimate charges the reads
    term once more instead, and never trips on a missing path."""
    rp, pp, lp = assembly
    auto = estimate_job_cost(str(rp), parsers.AUTO_OVERLAPS, str(lp))
    paf = estimate_job_cost(str(rp), str(pp), str(lp))
    assert auto > 0 and paf > 0


def test_rampler_plan_auto(assembly):
    """rampler plan with --overlaps auto: a reads-only index (reads
    apportioned to contigs by size) feeds the planner without a PAF."""
    from racon_tpu import rampler
    rp, _, lp = assembly
    out = rampler.plan(str(rp), parsers.AUTO_OVERLAPS, str(lp),
                       n_shards=2)
    assert out["n_contigs"] == 2 and out["n_overlaps"] == 0
    assert len(out["shards"]) == 2
    assert all(s["contigs"] for s in out["shards"])


def test_readsonly_index_apportions_reads(assembly):
    rp, _, lp = assembly
    idx = build_index_readsonly(str(rp), str(lp))
    assert idx.uniform_read_bases > 0
    per_contig = idx.contig_read_bytes()
    assert per_contig.size == len(idx.targets)
    assert all(int(b) > 0 for b in per_contig)
    assert int(per_contig.sum()) <= idx.uniform_read_bases
