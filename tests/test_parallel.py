"""Multi-device dispatch tests on the virtual 8-CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``). Sharded results must be
identical to single-device results — windows/pairs are embarrassingly
parallel, so sharding must not change any output byte (reference analog:
multi-GPU binning changes nothing about per-batch results,
``src/cuda/cudapolisher.cpp:72-83``)."""

import sys

import numpy as np
import pytest

import jax

from racon_tpu.parallel import get_mesh, mesh_size, partition_balanced
from racon_tpu.ops.nw import TpuAligner
from racon_tpu.ops.poa import TpuPoaConsensus
from racon_tpu.core.window import Window, WindowType


def _random_pairs(count, lo=60, hi=200, err=0.12, seed=5):
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    pairs = []
    for _ in range(count):
        ln = int(rng.integers(lo, hi))
        t = bases[rng.integers(0, 4, ln)]
        q = t.copy()
        flips = rng.random(ln) < err
        q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        pairs.append((q.tobytes(), t.tobytes()))
    return pairs


def _random_windows(count, depth=5, blen=64, seed=9):
    rng = np.random.default_rng(seed)
    bases = b"ACGT"
    windows = []
    for k in range(count):
        backbone = bytes(bases[i] for i in rng.integers(0, 4, blen))
        win = Window(0, k, WindowType.TGS, backbone, b"5" * blen)
        for _ in range(depth):
            layer = bytearray(backbone)
            for p in rng.integers(1, blen - 1, 4):
                layer[p] = bases[int(rng.integers(0, 4))]
            win.add_layer(bytes(layer), b"9" * len(layer), 0, blen - 1)
        windows.append(win)
    return windows


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    assert mesh_size(get_mesh()) == 8
    assert mesh_size(get_mesh(4)) == 4
    assert mesh_size(None) == 1


def test_partition_balanced():
    costs = [9, 1, 1, 1, 8, 2, 2, 4]
    bins = partition_balanced(costs, 3)
    assert sorted(i for b in bins for i in b) == list(range(8))
    loads = [sum(costs[i] for i in b) for b in bins]
    assert max(loads) <= 10  # LPT on this input: 9|8+1|4+2+2+1 -> 9/9/10


def test_sharded_aligner_matches_single_device():
    pairs = _random_pairs(37)
    single = TpuAligner(buckets=((256, 128),))
    sharded = TpuAligner(buckets=((256, 128),), mesh=get_mesh())
    c1 = single.align_batch(pairs)
    c2 = sharded.align_batch(pairs)
    assert c1 == c2
    assert sharded.stats["device"] == len(pairs)


def test_sharded_aligner_smaller_mesh():
    pairs = _random_pairs(10, seed=6)
    sharded = TpuAligner(buckets=((256, 128),), mesh=get_mesh(4))
    single = TpuAligner(buckets=((256, 128),))
    assert sharded.align_batch(pairs) == single.align_batch(pairs)


def test_sharded_consensus_matches_single_device():
    wins_a = _random_windows(13)
    wins_b = _random_windows(13)
    single = TpuPoaConsensus(3, -5, -4, band=64, rounds=2)
    sharded = TpuPoaConsensus(3, -5, -4, band=64, rounds=2, mesh=get_mesh())
    f1 = single.run(wins_a, trim=True)
    f2 = sharded.run(wins_b, trim=True)
    assert f1 == f2
    assert [w.consensus for w in wins_a] == [w.consensus for w in wins_b]
    assert sharded.stats["device_windows"] == len(wins_b)


def test_sharded_consensus_fewer_windows_than_devices():
    wins_a = _random_windows(3, seed=21)
    wins_b = _random_windows(3, seed=21)
    single = TpuPoaConsensus(3, -5, -4, band=64, rounds=1)
    sharded = TpuPoaConsensus(3, -5, -4, band=64, rounds=1, mesh=get_mesh())
    single.run(wins_a, trim=False)
    sharded.run(wins_b, trim=False)
    assert [w.consensus for w in wins_a] == [w.consensus for w in wins_b]


def test_dryrun_multichip():
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    try:
        from __graft_entry__ import dryrun_multichip, entry
    finally:
        sys.path.pop(0)
    fn, args = entry()
    packed, score = jax.jit(fn)(*args)
    assert int(jax.device_get(score).min()) >= 0
    dryrun_multichip(8)


def test_pipeline_mesh_auto_engages_and_matches_single(data_dir):
    """CLI-reachable multi-device semantics (reference: `-c N` engages
    every visible GPU, ``src/cuda/cudapolisher.cpp:46,72-83``): the
    ``tpu`` consensus backend auto-builds a mesh over all 8 visible
    devices, and the polished FASTA is byte-identical to a single-device
    run of the same engine."""
    from racon_tpu.core.polisher import create_polisher

    def polish(force_single):
        p = create_polisher(
            str(data_dir / "sample_reads.fastq.gz"),
            str(data_dir / "sample_overlaps.sam.gz"),
            str(data_dir / "sample_layout.fasta.gz"),
            num_threads=8, consensus_backend="tpu")
        if force_single:
            p.consensus.mesh = None
        else:
            assert p.consensus.mesh is not None
            assert p.consensus.mesh.shape["d"] == 8
        p.initialize()
        (polished,) = p.polish(True)
        return polished.name, polished.data, dict(p.consensus.stats)

    name_s, data_s, stats_s = polish(force_single=True)
    name_m, data_m, stats_m = polish(force_single=False)
    assert stats_m["device_windows"] > 90, stats_m
    assert (name_s, data_s) == (name_m, data_m)
