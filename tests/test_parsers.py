"""Parser tests on the reference's λ-phage dataset (read in place from
/root/reference/test/data — public test fixtures, not code)."""

from racon_tpu.io import (
    parse_fasta, parse_fastq, parse_paf, parse_mhap, parse_sam,
    sequence_parser_for, overlap_parser_for,
)


def test_fasta_layout(data_dir):
    recs = list(parse_fasta(str(data_dir / "sample_layout.fasta.gz")))
    assert len(recs) == 1
    assert recs[0].name == b"utg000001l"
    assert len(recs[0].data) == 47564
    assert recs[0].quality is None


def test_fastq_reads_multiline(data_dir):
    recs = list(parse_fastq(str(data_dir / "sample_reads.fastq.gz")))
    assert len(recs) > 100
    for r in recs:
        assert len(r.data) == len(r.quality)
    total = sum(len(r.data) for r in recs)
    assert total > 1_000_000  # ~1.6 Mbp of ONT reads


def test_paf(data_dir):
    recs = list(parse_paf(str(data_dir / "sample_overlaps.paf.gz")))
    assert len(recs) == 181
    qn, ql, qb, qe, strand, tn, tl, tb, te = recs[0].fields
    assert tn == b"utg000001l" and tl == 47564
    assert strand in "+-"
    assert 0 <= qb < qe <= ql


def test_mhap(data_dir):
    recs = list(parse_mhap(str(data_dir / "sample_ava_overlaps.mhap.gz")))
    assert len(recs) > 1000
    a_id, b_id, _, _, a_rc, ab, ae, al, b_rc, bb, be, bl = recs[0].fields
    assert a_id >= 1 and b_id >= 1
    assert a_rc in (0, 1) and b_rc in (0, 1)


def test_sam(data_dir):
    recs = list(parse_sam(str(data_dir / "sample_overlaps.sam.gz")))
    assert len(recs) > 100
    qn, flag, tn, pos, cigar = recs[0].fields
    assert tn == b"utg000001l"
    assert pos >= 1
    assert any(c in b"MIDSH=X" for c in cigar)


def test_dispatch():
    assert sequence_parser_for("x.fasta.gz") is parse_fasta
    assert sequence_parser_for("x.fq") is parse_fastq
    assert sequence_parser_for("x.bam") is None
    assert overlap_parser_for("x.paf.gz") is parse_paf
    assert overlap_parser_for("x.mhap") is parse_mhap
    assert overlap_parser_for("x.sam.gz") is parse_sam
    assert overlap_parser_for("x.vcf") is None


def test_native_parser_matches_python_oracle(data_dir):
    """The native zlib parser must produce record-for-record identical
    output to the Python parsers on the real λ files (gzipped FASTA and
    FASTQ, multi-record, names with suffixes)."""
    import racon_tpu.io.parsers as P
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")

    for fname, is_fastq in (("sample_reads.fasta.gz", False),
                            ("sample_reads.fastq.gz", True),
                            ("sample_layout.fasta.gz", False)):
        path = str(data_dir / fname)
        got = native.parse_seqfile(path, is_fastq)
        # bypass the native fast path to reach the Python oracle
        import unittest.mock as mock
        with mock.patch.object(P, "_native_records", lambda *a: None):
            want = list((P.parse_fastq if is_fastq else P.parse_fasta)(path))
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[0] == w.name and g[1] == w.data and g[2] == w.quality


def test_native_parser_rejects_malformed(tmp_path):
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    bad = tmp_path / "bad.fastq"
    bad.write_bytes(b"not a header\nACGT\n+\n!!!!\n")
    import pytest
    with pytest.raises(ValueError, match="malformed FASTQ header"):
        native.parse_seqfile(str(bad), True)
    trunc = tmp_path / "trunc.fastq"
    trunc.write_bytes(b"@r1\nACGTACGT\n+\n!!!\n")
    with pytest.raises(ValueError, match="truncated FASTQ"):
        native.parse_seqfile(str(trunc), True)


def test_native_parser_skips_leading_header_whitespace(tmp_path):
    """'>  name extra' must yield b'name' like the Python oracle's
    split(None, 1)."""
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    f = tmp_path / "pad.fasta"
    f.write_bytes(b">  ctg1 extra\nACGT\n")
    (rec,) = native.parse_seqfile(str(f), False)
    assert rec[0] == b"ctg1" and rec[1] == b"ACGT"


def test_native_ovl_parser_matches_python_oracle(data_dir):
    """The native overlap parser (PAF/MHAP/SAM) must produce field
    tuples identical to the Python oracle parsers on the real λ files,
    including the float jaccard (both are correctly-rounded doubles of
    the same token) and the SAM header skip."""
    import racon_tpu.io.parsers as P
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")

    import unittest.mock as mock
    for fname, fmt, parser in (
            ("sample_overlaps.paf.gz", 0, P.parse_paf),
            ("sample_ava_overlaps.paf.gz", 0, P.parse_paf),
            ("sample_ava_overlaps.mhap.gz", 1, P.parse_mhap),
            ("sample_overlaps.sam.gz", 2, P.parse_sam)):
        path = str(data_dir / fname)
        got = native.parse_ovlfile(path, fmt)
        with mock.patch.object(P, "_native_ovl", lambda *a: None):
            want = list(parser(path))
        assert len(got) == len(want)
        assert [r.fields for r in got] == [r.fields for r in want]
        assert all(g.fmt == w.fmt for g, w in zip(got, want))


def test_native_ovl_parser_rejects_malformed(tmp_path):
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    bad = tmp_path / "bad.paf"
    bad.write_bytes(b"q1\t100\t0\t100\n")  # too few fields
    import pytest
    with pytest.raises(ValueError, match="malformed line 1"):
        native.parse_ovlfile(str(bad), 0)


def test_ctypes_ovl_fallback_matches_oracle(data_dir):
    """The ctypes record-reconstruction path (used when the CPython
    extension cannot build) must match the oracle too."""
    import unittest.mock as mock
    import racon_tpu.io.parsers as P
    from racon_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")

    with mock.patch.object(native, "_load_ext", lambda: None):
        for fname, fmt, parser in (
                ("sample_overlaps.paf.gz", 0, P.parse_paf),
                ("sample_ava_overlaps.mhap.gz", 1, P.parse_mhap),
                ("sample_overlaps.sam.gz", 2, P.parse_sam)):
            path = str(data_dir / fname)
            got = native.parse_ovlfile(path, fmt)
            with mock.patch.object(P, "_native_ovl", lambda *a: None):
                want = list(parser(path))
            assert [r.fields for r in got] == [r.fields for r in want]
            assert all(g.fmt == w.fmt for g, w in zip(got, want))


# ----------------------------------------------- structured parse errors

def test_parse_error_carries_file_and_line(tmp_path):
    """Malformed records surface as ParseError (a ValueError) with the
    file and the 1-based line number in the message — the round-12
    parser-hardening satellite. The Python oracles are exercised
    directly so the line numbers are deterministic regardless of the
    native build."""
    import pytest

    import racon_tpu.io.parsers as P

    fq = tmp_path / "bad.fastq"
    fq.write_bytes(b"@r1\nACGT\n+\n!!!!\nnot a header\nACGT\n+\n!!!!\n")
    with pytest.raises(P.ParseError, match=r"bad\.fastq:5.*malformed "
                                           r"FASTQ header") as ei:
        list(P._parse_fastq_py(str(fq)))
    assert ei.value.line == 5 and ei.value.path == str(fq)

    trunc = tmp_path / "trunc.fastq"
    trunc.write_bytes(b"@r1\nACGTACGT\n+\n!!!\n")
    with pytest.raises(P.ParseError, match=r"trunc\.fastq:1.*truncated"):
        list(P._parse_fastq_py(str(trunc)))

    nosep = tmp_path / "nosep.fastq"
    nosep.write_bytes(b"@r1\nACGT\nACGT\n")
    with pytest.raises(P.ParseError, match=r"no '\+' separator"):
        list(P._parse_fastq_py(str(nosep)))

    fa = tmp_path / "headerless.fasta"
    fa.write_bytes(b"ACGTACGT\n>ctg\nACGT\n")
    with pytest.raises(P.ParseError, match=r"headerless\.fasta:1.*"
                                           r"before the first"):
        list(P._parse_fasta_py(str(fa)))

    noname = tmp_path / "noname.fasta"
    noname.write_bytes(b">\nACGT\n")
    with pytest.raises(P.ParseError, match=r"noname\.fasta:1.*empty "
                                           r"sequence name"):
        list(P._parse_fasta_py(str(noname)))


def test_overlap_parse_errors_carry_file_and_line(tmp_path):
    import pytest

    import racon_tpu.io.parsers as P

    paf = tmp_path / "bad.paf"
    paf.write_bytes(b"q1\t100\t0\t100\t+\tt1\t100\t0\t100\t50\t100\t255\n"
                    b"q2\t100\t0\n")
    with pytest.raises(P.ParseError, match=r"bad\.paf:2.*malformed PAF"):
        list(P._parse_paf_py(str(paf)))
    notint = tmp_path / "notint.paf"
    notint.write_bytes(b"q1\tNaN\t0\t100\t+\tt1\t100\t0\t100\t5\t10\t2\n")
    with pytest.raises(P.ParseError, match=r"notint\.paf:1"):
        list(P._parse_paf_py(str(notint)))

    mhap = tmp_path / "bad.mhap"
    mhap.write_bytes(b"1 2 0.1 5 0 0 100 100 0 0 100 100\n1 2 0.1\n")
    with pytest.raises(P.ParseError, match=r"bad\.mhap:2.*malformed "
                                           r"MHAP"):
        list(P._parse_mhap_py(str(mhap)))

    sam = tmp_path / "bad.sam"
    sam.write_bytes(b"@HD\tVN:1.6\nq1\tzero\tt1\t1\t60\t4M\n")
    with pytest.raises(P.ParseError, match=r"bad\.sam:2.*malformed SAM"):
        list(P._parse_sam_py(str(sam)))


def test_parse_error_through_public_api_and_native(tmp_path):
    """Through the public parse_* surface (native parser when built,
    Python fallback otherwise) a malformed file still raises a
    ValueError subclass naming the file."""
    import pytest

    import racon_tpu.io.parsers as P

    fq = tmp_path / "pub.fastq"
    fq.write_bytes(b"not a header\nACGT\n+\n!!!!\n")
    with pytest.raises(ValueError, match="malformed FASTQ header"):
        list(P.parse_fastq(str(fq)))

    paf = tmp_path / "pub.paf"
    paf.write_bytes(b"q1\t100\t0\n")
    with pytest.raises(ValueError, match=r"pub\.paf|malformed line"):
        list(P.parse_paf(str(paf)))


def test_span_scanners_report_byte_offsets(tmp_path):
    import pytest

    import racon_tpu.io.parsers as P

    fq = tmp_path / "scan.fastq"
    fq.write_bytes(b"@r1\nACGT\n+\n!!!!\nbroken\n")
    with pytest.raises(P.ParseError, match=r"byte 16") as ei:
        list(P._scan_fastq_spans(str(fq)))
    assert ei.value.offset == 16

    fa = tmp_path / "scan.fasta"
    fa.write_bytes(b"ACGT\n>ctg\nACGT\n")
    with pytest.raises(P.ParseError, match=r"byte 0"):
        list(P._scan_fasta_spans(str(fa)))
