"""End-to-end pipeline golden tests on the λ-phage dataset.

Mirrors the reference's integration-test strategy
(``test/racon_test.cpp:88-290``): polish the miniasm layout with real reads
and assert the exact edit distance of the reverse-complemented polished
contig vs the NC_001416 reference genome. The reference's CPU goldens (spoa)
are 1312 (FASTQ+PAF), 1566 (FASTA+PAF), 1317 (FASTQ+SAM); our engine is a
faithful but independent reimplementation, so we record our own exact
goldens and additionally assert closeness to the reference's.

The raw backbone scores 8765 — any value near 1300-1600 means the pipeline
is polishing correctly.
"""

import os

import pytest

from racon_tpu import native
from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.core.sequence import Sequence
from racon_tpu.io import parse_fasta

RUN_SLOW = os.environ.get("RACON_TPU_SLOW", "") == "1"


def polish(data_dir, reads, overlaps, **kw):
    p = create_polisher(str(data_dir / reads), str(data_dir / overlaps),
                        str(data_dir / "sample_layout.fasta.gz"),
                        kw.pop("type_", PolisherType.C),
                        num_threads=8, **kw)
    p.initialize()
    return p.polish(True)


def rc_distance_to_reference(data_dir, polished: Sequence) -> int:
    ref = list(parse_fasta(str(data_dir / "sample_reference.fasta.gz")))[0]
    return native.edit_distance(polished.reverse_complement, ref.data)


@pytest.fixture(scope="module")
def fastq_paf_result(data_dir):
    return polish(data_dir, "sample_reads.fastq.gz", "sample_overlaps.paf.gz")


def test_consensus_fastq_paf_golden(data_dir, fastq_paf_result):
    (polished,) = fastq_paf_result
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1324  # our golden; reference spoa golden: 1312
    assert abs(d - 1312) <= 60


def test_output_tags(fastq_paf_result):
    (polished,) = fastq_paf_result
    name = polished.name.decode()
    assert name.startswith("utg000001l ")
    assert f"LN:i:{len(polished.data)}" in name
    assert "RC:i:181" in name
    assert "XC:f:1.000000" in name


def test_consensus_fastq_sam_golden(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fastq.gz",
                         "sample_overlaps.sam.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1346  # our golden; reference spoa golden: 1317
    assert abs(d - 1317) <= 60


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_consensus_fasta_paf_golden(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fasta.gz",
                         "sample_overlaps.paf.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert abs(d - 1566) <= 80  # reference golden: 1566


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_quality(data_dir):
    """Device (TpuPoaConsensus) pipeline quality: like the reference's CUDA
    goldens, the accelerated engine records its own target — 1351 vs CPU
    1324 (reference: cudapoa 1385 vs spoa 1312,
    ``test/racon_test.cpp:312``). Vote weights are integral, so float
    scatter sums are exact and order-independent — the XLA kernels on
    this CPU mesh land on the same bytes as the Pallas kernels on real
    TPU, and the chip golden holds exactly here too."""
    p = create_polisher(str(data_dir / "sample_reads.fastq.gz"),
                        str(data_dir / "sample_overlaps.paf.gz"),
                        str(data_dir / "sample_layout.fasta.gz"),
                        num_threads=8, consensus_backend="tpu")
    p.initialize()
    engine = p.consensus
    (polished,) = p.polish(True)
    # the quality must come from the device path, not CPU fallback
    assert engine.stats["device_windows"] > 90, engine.stats
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1351  # device golden (real TPU == CPU-mesh XLA)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_consensus_window_1000(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fastq.gz",
                         "sample_overlaps.paf.gz", window_length=1000)
    d = rc_distance_to_reference(data_dir, polished)
    assert abs(d - 1289) <= 80  # reference golden: 1289
