"""End-to-end pipeline golden tests on the λ-phage dataset.

Mirrors the reference's integration-test strategy
(``test/racon_test.cpp:88-290``): polish the miniasm layout with real reads
and assert the exact edit distance of the reverse-complemented polished
contig vs the NC_001416 reference genome. The reference's CPU goldens (spoa)
are 1312 (FASTQ+PAF), 1566 (FASTA+PAF), 1317 (FASTQ+SAM); our engine is a
faithful but independent reimplementation, so we record our own exact
goldens and additionally assert closeness to the reference's.

The raw backbone scores 8765 — any value near 1300-1600 means the pipeline
is polishing correctly.
"""

import pytest

from racon_tpu import flags as racon_flags
from racon_tpu import native
from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.core.sequence import Sequence
from racon_tpu.io import parse_fasta

RUN_SLOW = racon_flags.get_bool("RACON_TPU_SLOW")


def polish(data_dir, reads, overlaps, **kw):
    p = create_polisher(str(data_dir / reads), str(data_dir / overlaps),
                        str(data_dir / "sample_layout.fasta.gz"),
                        kw.pop("type_", PolisherType.C),
                        num_threads=8, **kw)
    p.initialize()
    return p.polish(True)


def rc_distance_to_reference(data_dir, polished: Sequence) -> int:
    ref = list(parse_fasta(str(data_dir / "sample_reference.fasta.gz")))[0]
    return native.edit_distance(polished.reverse_complement, ref.data)


@pytest.fixture(scope="module")
def fastq_paf_result(data_dir):
    return polish(data_dir, "sample_reads.fastq.gz", "sample_overlaps.paf.gz")


def test_consensus_fastq_paf_golden(data_dir, fastq_paf_result):
    (polished,) = fastq_paf_result
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1324  # our golden; reference spoa golden: 1312
    assert abs(d - 1312) <= 60


def test_output_tags(fastq_paf_result):
    (polished,) = fastq_paf_result
    name = polished.name.decode()
    assert name.startswith("utg000001l ")
    assert f"LN:i:{len(polished.data)}" in name
    assert "RC:i:181" in name
    assert "XC:f:1.000000" in name


def test_consensus_fastq_sam_golden(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fastq.gz",
                         "sample_overlaps.sam.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1346  # our golden; reference spoa golden: 1317
    assert abs(d - 1317) <= 60


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_consensus_fasta_paf_golden(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fasta.gz",
                         "sample_overlaps.paf.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert abs(d - 1566) <= 80  # reference golden: 1566


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_quality(data_dir):
    """Device (TpuPoaConsensus) pipeline quality: like the reference's CUDA
    goldens, the accelerated engine records its own target — 1346 vs CPU
    1324 (reference: cudapoa 1385 vs spoa 1312,
    ``test/racon_test.cpp:312``). Vote weights are integral and the
    accumulation (column-vote matmul + packed insertion scatter) sums
    exactly, so the XLA kernels on this CPU mesh land on the same bytes
    as the Pallas kernels on real TPU and the chip golden holds exactly
    here too."""
    p = create_polisher(str(data_dir / "sample_reads.fastq.gz"),
                        str(data_dir / "sample_overlaps.paf.gz"),
                        str(data_dir / "sample_layout.fasta.gz"),
                        num_threads=8, consensus_backend="tpu")
    p.initialize()
    engine = p.consensus
    (polished,) = p.polish(True)
    # the quality must come from the device path, not CPU fallback
    assert engine.stats["device_windows"] > 90, engine.stats
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1346  # device golden (real TPU == CPU-mesh XLA)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_consensus_window_1000(data_dir):
    (polished,) = polish(data_dir, "sample_reads.fastq.gz",
                         "sample_overlaps.paf.gz", window_length=1000)
    d = rc_distance_to_reference(data_dir, polished)
    assert abs(d - 1289) <= 80  # reference golden: 1289


def test_multi_target_stitch(tmp_path):
    """Two-contig pipeline: windows must stitch back per target (the
    reference CI golden polishes 3 contigs; the λ set has one). Two
    synthetic 3 kbp contigs at ~5x forward-strand coverage: the output
    must contain exactly one polished record per target, in target
    order, each strictly closer to its truth than the mutated backbone
    was."""
    import numpy as np

    rng = np.random.default_rng(23)
    bases = np.frombuffer(b"ACGT", np.uint8)

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, 3000)] for _ in range(2)]
    backbones = [mutate(t, 0.06) for t in truths]

    layout = tmp_path / "layout.fasta"
    with open(layout, "wb") as f:
        for ti, bb in enumerate(backbones):
            f.write(b">ctg%d\n" % ti + bb.tobytes() + b"\n")

    reads_path = tmp_path / "reads.fastq"
    paf_path = tmp_path / "ovl.paf"
    with open(reads_path, "wb") as rf, open(paf_path, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            for start in range(0, 2400, 150):  # ~5x mean of 900bp reads
                end = min(start + 900, 3000)
                read = mutate(truth[start:end], 0.08)
                name = b"read%d" % ri
                rf.write(b"@" + name + b"\n" + read.tobytes() +
                         b"\n+\n" + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    b"+", b"ctg%d" % ti, b"3000", b"%d" % start,
                    b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1

    p = create_polisher(str(reads_path), str(paf_path), str(layout),
                        num_threads=4)
    p.initialize()
    polished = p.polish(True)
    assert len(polished) == 2
    for ti, seq in enumerate(polished):
        assert seq.name.split()[0] == b"ctg%d" % ti
        d_backbone = native.edit_distance(backbones[ti].tobytes(),
                                          truths[ti].tobytes())
        d_polished = native.edit_distance(seq.data, truths[ti].tobytes())
        assert d_polished < d_backbone / 2, (ti, d_polished, d_backbone)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_banded(data_dir):
    """-b banded approximation through the device engine: half the
    alignment band for speed at a quality cost, like banded cudapoa
    (reference banded golden degrades to 4168 from 1385 full-band,
    ``test/racon_test.cpp:400``). Recorded: 3180 (bit-reproducible
    across XLA-on-CPU-mesh and Pallas-on-TPU, like the full-band
    golden)."""
    p = create_polisher(str(data_dir / "sample_reads.fastq.gz"),
                        str(data_dir / "sample_overlaps.paf.gz"),
                        str(data_dir / "sample_layout.fasta.gz"),
                        num_threads=8, consensus_backend="tpu",
                        banded=True)
    p.initialize()
    (polished,) = p.polish(True)
    assert p.consensus.stats["device_windows"] > 90
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 3180  # banded device golden


# ---- device-engine goldens for every scenario the reference records CUDA
# goldens for (test/racon_test.cpp:292-422). Values recorded on real TPU
# v5e by tools/record_goldens.py and bit-reproducible on the CPU-mesh XLA
# kernels; the reference's own CUDA-vs-CPU divergence is the yardstick
# (e.g. cudapoa 1385 vs spoa 1312; banded/w1000 degrade to 4168).

def device_polish(data_dir, reads, overlaps, **kw):
    p = create_polisher(str(data_dir / reads), str(data_dir / overlaps),
                        str(data_dir / "sample_layout.fasta.gz"),
                        num_threads=8, consensus_backend="tpu", **kw)
    p.initialize()
    out = p.polish(True)
    assert p.consensus.stats["fallback_windows"] == 0
    return out


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_fasta_paf(data_dir):
    (polished,) = device_polish(data_dir, "sample_reads.fasta.gz",
                                "sample_overlaps.paf.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1702  # device golden (cudapoa: 1607; CPU engines: ~1566)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_fastq_sam(data_dir):
    (polished,) = device_polish(data_dir, "sample_reads.fastq.gz",
                                "sample_overlaps.sam.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1388  # device golden (cudapoa: 1541; our CPU: 1346)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_fasta_sam(data_dir):
    (polished,) = device_polish(data_dir, "sample_reads.fasta.gz",
                                "sample_overlaps.sam.gz")
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 2024  # device golden (cudapoa: 1661; reference CPU: 1770)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_w1000(data_dir):
    (polished,) = device_polish(data_dir, "sample_reads.fastq.gz",
                                "sample_overlaps.paf.gz",
                                window_length=1000)
    d = rc_distance_to_reference(data_dir, polished)
    # the alignment band scales with window length (r5): w=1000 layers
    # align inside a 1024 band with zero drops, closing the r4 cliff
    # (was 2591) to near-CPU quality — reference CUDA degrades to 4168
    # at banded/w1000 vs its CPU 1289
    assert d == 1350  # device golden


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_unit_scores(data_dir):
    (polished,) = device_polish(data_dir, "sample_reads.fastq.gz",
                                "sample_overlaps.paf.gz",
                                match=1, mismatch=-1, gap=-1)
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1598  # device golden (cudapoa: 1361; reference CPU: 1321)


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_device_consensus_e2e_scores(data_dir):
    """The reference's GPU-CI invocation `-m 8 -x -6 -g -8 -c 1`
    (ci/gpu/cuda_test.sh:29) through the device engine: -m/-x/-g reach
    the score-weighted voting and the emission thresholds — recorded
    golden, no ignored-flag warnings."""
    import warnings

    with warnings.catch_warnings(record=True) as wlist:
        warnings.simplefilter("always")
        (polished,) = device_polish(data_dir, "sample_reads.fastq.gz",
                                    "sample_overlaps.paf.gz",
                                    match=8, mismatch=-6, gap=-8)
    assert not [w for w in wlist if "only affect" in str(w.message)]
    d = rc_distance_to_reference(data_dir, polished)
    assert d == 1518  # device golden
