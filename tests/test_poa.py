"""POA graph/consensus tests against hand-checkable cases."""

import pytest

from racon_tpu.models.poa import PoaAlignmentEngine, PoaGraph


@pytest.fixture
def engine():
    return PoaAlignmentEngine(match=3, mismatch=-5, gap=-4)


def build_graph(engine, seqs, quals=None):
    graph = engine.create_graph()
    quals = quals or [None] * len(seqs)
    graph.add_alignment([], seqs[0], quals[0])
    for s, q in zip(seqs[1:], quals[1:]):
        aln = engine.align(s, graph)
        graph.add_alignment(aln, s, q)
    return graph


def test_single_sequence_roundtrip(engine):
    g = build_graph(engine, [b"ACGTACGT"])
    assert g.generate_consensus() == b"ACGTACGT"


def test_identical_sequences(engine):
    g = build_graph(engine, [b"ACGTACGT"] * 5)
    assert g.generate_consensus() == b"ACGTACGT"
    # one linear chain: 8 nodes only
    assert len(g.letters) == 8


def test_majority_substitution(engine):
    seqs = [b"ACGTACGT", b"ACGAACGT", b"ACGAACGT", b"ACGAACGT"]
    g = build_graph(engine, seqs)
    assert g.generate_consensus() == b"ACGAACGT"


def test_majority_insertion_deletion(engine):
    seqs = [b"ACGTT", b"ACGTT", b"ACGT", b"ACGTT"]
    g = build_graph(engine, seqs)
    assert g.generate_consensus() == b"ACGTT"
    seqs = [b"ACGTT", b"ACGT", b"ACGT", b"ACGT"]
    g = build_graph(engine, seqs)
    assert g.generate_consensus() == b"ACGT"


def test_quality_weights_break_ties(engine):
    # Two variants, equal counts; higher-quality bases should win.
    hi = bytes([33 + 40] * 4)
    lo = bytes([33 + 2] * 4)
    g = build_graph(engine, [b"ACGT", b"AGGT", b"ACGT", b"AGGT"],
                    quals=[lo, hi, lo, hi])
    assert g.generate_consensus() == b"AGGT"


def test_alignment_pairs_wellformed(engine):
    g = build_graph(engine, [b"ACGTACGTAA"])
    aln = engine.align(b"ACGTTACGT", g)
    # every pair references a valid node/position
    seq_positions = [p for _, p in aln if p != -1]
    assert seq_positions == sorted(seq_positions)
    assert seq_positions[0] == 0 and seq_positions[-1] == 8
    node_ids = [n for n, _ in aln if n != -1]
    assert all(0 <= n < len(g.letters) for n in node_ids)


def test_coverage_counts(engine):
    g = build_graph(engine, [b"ACGT"] * 4)
    consensus, cov = g.generate_consensus_with_coverage()
    assert consensus == b"ACGT"
    assert cov == [4, 4, 4, 4]


def test_subgraph_partial_layer(engine):
    backbone = b"AAAACCCCGGGGTTTT"
    g = engine.create_graph()
    g.add_alignment([], backbone, None)
    # layer covering backbone positions 4..11 ("CCCCGGGG")
    sub, mapping = g.subgraph(4, 11)
    assert bytes(sub.letters) == b"CCCCGGGG"
    aln = engine.align(b"CCCCGGGG", sub)
    aln = sub.update_alignment(aln, mapping)
    g.add_alignment(aln, b"CCCCGGGG", None)
    # no new nodes should have been created (perfect match onto backbone)
    assert len(g.letters) == len(backbone)
    assert g.generate_consensus() == backbone


def test_mismatch_creates_aligned_node(engine):
    g = build_graph(engine, [b"ACGT", b"ATGT"])
    # position 1: C and T aligned -> 5 nodes, C/T in one aligned ring
    assert len(g.letters) == 5
    rings = [r for r in g.aligned if r]
    assert len(rings) == 2
