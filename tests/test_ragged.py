"""Round-10 ragged window packing: byte-identical consensus vs the
padded path, across the {padded, ragged} x {scatter, matmul} grid.

The ragged packer buckets windows by their OWN power-of-two lane width
and greedy-fills groups against a fixed lane arena (the cudabatch
batch-fill design) instead of padding every window to the global bucket
maxima; the int8-matmul vote path replaces the f32 one-hot matmul +
packed insertion scatter. Both are on by default, so this suite is the
tier-1 gate for their joint contract: per-window consensus must be
**byte-identical** on every combination (windows are independent and the
vote accumulation is exact integer arithmetic at any grouping), across
randomized mixed window lengths, strand mixes, F-mode short reads,
dummy-quality reads and empty/singleton windows — wired as a fail-fast
shard in ci/cpu/test.sh (and re-run under RACON_TPU_SANITIZE=1 there).

Economy: every engine here uses ``band=128`` and window lengths 60-300
(the 60/150 bp windows land in the L=256 ragged bucket, the 300 bp ones
in L=512 — two buckets, small Lq), so the whole grid shares a handful of
compile geometries; parity is a per-window bytes property, independent
of the band, so nothing is lost vs the production 512 band.
"""

import numpy as np
import pytest

from racon_tpu.core.window import Window, WindowType

BASES = np.frombuffer(b"ACGT", np.uint8)
TEST_BAND = 128


def _engine(ragged, matmul, max_depth=200, rounds=4, num_batches=1):
    from racon_tpu.core.backends import CpuPoaConsensus
    from racon_tpu.ops.poa import TpuPoaConsensus

    return TpuPoaConsensus(
        3, -5, -4, fallback=CpuPoaConsensus(3, -5, -4),
        max_depth=max_depth, band=TEST_BAND, rounds=rounds,
        num_batches=num_batches, use_ragged=ragged,
        use_matmul_votes=matmul)


def _mixed_windows(rng, n_w=18, with_quality=True, type_=WindowType.TGS):
    """Randomized mixed workload: window lengths spanning two ragged
    buckets (60..300 bp), depths 0..12 (empty, singleton and passthrough
    windows included), mixed real/dummy qualities."""
    lengths = [60, 150, 300]
    windows = []
    for wi in range(n_w):
        wl = lengths[int(rng.integers(0, len(lengths)))]
        truth = BASES[rng.integers(0, 4, wl)]
        bb = truth.copy()
        flips = rng.random(wl) < 0.1
        bb[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
        win = Window(0, wi, type_, bb.tobytes(), b"!" * wl)
        depth = int(rng.integers(0, 13)) if wi % 7 else wi % 3  # 0/1/2 mix
        for _ in range(depth):
            layer = truth.copy()
            flips = rng.random(wl) < 0.08
            layer[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
            layer = np.delete(layer, rng.integers(0, len(layer), 4))
            layer = np.insert(layer, rng.integers(0, len(layer), 4),
                              BASES[rng.integers(0, 4, 4)])
            qual = (bytes(33 + int(x) for x in
                          rng.integers(5, 50, len(layer)))
                    if with_quality and wi % 3 else None)
            win.add_layer(layer.tobytes(), qual, 0, wl - 1)
        windows.append(win)
    return windows


def _run_grid(windows, **eng_kw):
    """Run all four path combinations on the same windows; return
    {(ragged, matmul): (flags, [consensus bytes])}."""
    out = {}
    for ragged in (True, False):
        for matmul in (True, False):
            eng = _engine(ragged, matmul, **eng_kw)
            flags = eng.run(windows, trim=True)
            out[(ragged, matmul)] = (flags,
                                     [w.consensus for w in windows])
    return out


@pytest.mark.parametrize("seed", range(2))
def test_ragged_grid_parity_randomized(seed):
    rng = np.random.default_rng(100 + seed)
    windows = _mixed_windows(rng, with_quality=bool(seed % 2))
    grid = _run_grid(windows)
    ref_flags, ref_cons = grid[(False, False)]  # the r05 configuration
    assert any(ref_flags)
    for key, (flags, cons) in grid.items():
        assert flags == ref_flags, key
        assert cons == ref_cons, key


def test_ragged_parity_f_mode_short_reads():
    """F-mode (fragment correction) windows: short backbones/layers, the
    NGS window type — the shapes that land in the smallest ragged
    bucket and pack the most windows per group."""
    rng = np.random.default_rng(321)
    windows = _mixed_windows(rng, n_w=24, type_=WindowType.NGS)
    eng_r = _engine(True, True)
    flags_r = eng_r.run(windows, trim=True)
    cons_r = [w.consensus for w in windows]
    flags_p = _engine(False, False).run(windows, trim=True)
    assert flags_r == flags_p
    assert cons_r == [w.consensus for w in windows]


def test_ragged_stream_feed_batches_match_single_feed():
    """Polisher.run() feeds the stream session in producer-sized ranges;
    the grouping must not change any window's bytes vs one monolithic
    feed (and vs the padded path)."""
    rng = np.random.default_rng(7)
    windows = _mixed_windows(rng, n_w=21)

    eng = _engine(True, True)
    sess = eng.stream(trim=True)
    assert sess is not None
    for a in range(0, len(windows), 7):
        sess.feed(windows[a:a + 7])
    flags_stream = sess.finish()
    cons_stream = [w.consensus for w in windows]

    flags_pad = _engine(False, True).run(windows, trim=True)
    assert flags_stream == flags_pad
    assert cons_stream == [w.consensus for w in windows]


def test_ragged_strand_mix_via_polisher_store():
    """Columnar-store windows (the production path: layers are (offset,
    len) views into the read pool, strands mixed) through ragged vs
    padded — exercises the vectorized store gather packing, not just
    the hand-built add_layer path."""
    from tests.test_columnar_init import (build_with, make_polisher,
                                          random_state)

    sequences, nt, overlaps = random_state(5, 100)
    assert any(o.strand for o in overlaps)          # strand mix present
    assert any(not o.strand for o in overlaps)
    p = build_with(make_polisher(100), sequences, nt, overlaps,
                   legacy=False)
    windows = p.windows
    assert any(w.layer_view[0] is not None for w in windows)
    flags_r = _engine(True, True).run(windows, trim=True)
    cons_r = [w.consensus for w in windows]
    flags_p = _engine(False, False).run(windows, trim=True)
    assert flags_r == flags_p
    assert cons_r == [w.consensus for w in windows]


def test_ragged_reject_parity_oversized_layers():
    """The reject SET is part of the byte-identity contract: a window
    whose layers exceed the padded path's pair buffer (Lq from the
    batch-global backbone maximum) goes to the CPU fallback there — the
    ragged packer must NOT quietly polish it on device in a bigger
    bucket, or the two paths diverge on exactly the stress shapes the
    scale bench asserts on."""
    rng = np.random.default_rng(55)
    windows = _mixed_windows(rng, n_w=8)
    # one window with layers far past Lq_pad = L_pad + band (~640 for
    # this 300 bp batch at band=128): device reject on the padded path
    wl = 150
    truth = BASES[rng.integers(0, 4, wl)]
    win = Window(0, len(windows), WindowType.TGS, truth.tobytes(),
                 b"!" * wl)
    for _ in range(4):
        layer = np.insert(truth.copy(), rng.integers(0, wl, 800),
                          BASES[rng.integers(0, 4, 800)])
        win.add_layer(layer.tobytes(), None, 0, wl - 1)
    windows.append(win)

    er, ep = _engine(True, True), _engine(False, False)
    flags_r = er.run(windows, trim=True)
    cons_r = [w.consensus for w in windows]
    assert er.stats["fallback_windows"] >= 1     # the oversized window
    flags_p = ep.run(windows, trim=True)
    assert ep.stats["fallback_windows"] >= 1
    assert flags_r == flags_p
    assert cons_r == [w.consensus for w in windows]


def test_ragged_occupancy_telemetry():
    """The round-10 occupancy counters must account real lanes: both
    paths report occupied <= total, a sane efficiency/pad split and a
    windows-per-group mean >= 1."""
    rng = np.random.default_rng(13)
    # short windows only: the padded path still pads each pair row to
    # the global bucket width
    windows = []
    for wi in range(16):
        wl = 80
        truth = BASES[rng.integers(0, 4, wl)]
        win = Window(0, wi, WindowType.TGS, truth.tobytes(), b"!" * wl)
        for _ in range(6):
            layer = truth.copy()
            flips = rng.random(wl) < 0.05
            layer[flips] = BASES[rng.integers(0, 4, int(flips.sum()))]
            win.add_layer(layer.tobytes(), None, 0, wl - 1)
        windows.append(win)

    er = _engine(True, True)
    ep = _engine(False, True)
    er.run(windows, trim=True)
    ep.run(windows, trim=True)
    pr, pp = er.pack_metrics(), ep.pack_metrics()
    assert pr["groups"] >= 1 and pp["groups"] >= 1
    assert 0 < pr["pack_efficiency"] <= 1
    assert pr["windows_per_group"] >= 1
    assert abs(pr["pack_efficiency"] + pr["pad_fraction"] - 1) < 1e-6
    # both paths bucket these 80 bp windows at L=256, so efficiencies
    # tie; the ragged win is MORE PAIRS PER GROUP on mixed-size batches
    # (covered by the parity tests) — here just require no regression
    assert pr["pack_efficiency"] >= pp["pack_efficiency"] - 1e-6
    st = er.stats
    assert st["lanes_occupied"] <= st["lanes_total"]
    assert st["lanes_occupied"] > 0


def test_dropped_layers_warns_once_per_run(capsys):
    """scale_stats.dropped_layers was 4943 at r05 with no warning; the
    engine now emits ONE summary line per run through
    utils.logger.warn."""
    rng = np.random.default_rng(3)
    windows = _mixed_windows(rng, n_w=6)
    eng = _engine(True, True, max_depth=3)  # force depth-cap drops
    eng.run(windows, trim=True)
    err = capsys.readouterr().err
    assert eng.stats["dropped_layers"] > 0
    lines = [ln for ln in err.splitlines()
             if "layer alignments dropped" in ln]
    assert len(lines) == 1
    assert "dropped_layers" in lines[0]
