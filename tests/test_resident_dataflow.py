"""Device-resident align→consensus dataflow parity (round 19).

With ``RACON_TPU_RESIDENT=1`` the accepted breaking-point tables stay on
device, window assignment and per-window layer rows derive via jit'd
array ops (``ops/nw._derive_layer_rows``), and the consensus engine
gathers its ``weight<<3|code`` lanes from the device-resident pool
(``ops/poa._gather_qpw_rows``).  The contract is BYTE-PARITY with the
host path — the host ``Polisher._filter_layer_rows`` oracle — not
approximation.  This suite drives the real create_polisher surface with
the device backends across the shapes that stress the filters (mixed
strands, dummy-quality FASTA reads, F-mode multi-overlap inputs, the
chunked pipelined emit), asserts the resident path actually ENGAGED
(``dataflow.resident`` gauge; a silently-disengaged path would pass
parity trivially), and pins the bail-out ladder: every precondition
failure must fall back to the host path with identical output.
"""

import os

import numpy as np
import pytest

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.obs import metrics

from test_columnar_init import polished_bytes, write_synthetic_assembly


def _fastq_to_fasta(fastq_path, fasta_path):
    """Strip qualities: the dummy-quality (FASTA-reads) leg."""
    with open(fastq_path, "rb") as f:
        lines = f.read().split(b"\n")
    with open(fasta_path, "wb") as f:
        for i in range(0, len(lines) - 3, 4):
            f.write(b">" + lines[i][1:] + b"\n" + lines[i + 1] + b"\n")
    return fasta_path


def _device_engines():
    """Single-device engines (mesh=None): the conftest 8-virtual-device
    mesh would gate off the ragged align stream — the only
    resident-capable dispatch path — and the device-lane consensus
    ingest, exactly as a production mesh run would."""
    from racon_tpu.core.backends import NativeAligner, NativePoaConsensus
    from racon_tpu.ops.nw import TpuAligner
    from racon_tpu.ops.poa import TpuPoaConsensus

    return (TpuAligner(fallback=NativeAligner(2), mesh=None),
            TpuPoaConsensus(3, -5, -4,
                            fallback=NativePoaConsensus(3, -5, -4, 2),
                            mesh=None))


def _run_leg(reads, paf, layout, *, resident, type_=PolisherType.C,
             num_threads=1, quality_threshold=10.0):
    """One polishing run through single-device engines; returns
    (polished bytes, timings, dataflow summary)."""
    metrics.clear_run()
    if resident:
        os.environ["RACON_TPU_RESIDENT"] = "1"
    try:
        aligner, consensus = _device_engines()
        p = create_polisher(
            str(reads), str(paf), str(layout), type_=type_,
            quality_threshold=quality_threshold,
            num_threads=num_threads,
            aligner_backend="tpu", consensus_backend="tpu",
            aligner=aligner, consensus=consensus)
        out = polished_bytes(p.run(True))
    finally:
        os.environ.pop("RACON_TPU_RESIDENT", None)
    return out, dict(p.timings), metrics.dataflow_summary()


def _assert_engaged(timings, dataflow):
    """The resident leg must have actually run on device — a leg that
    silently fell back to host would make every parity assert vacuous."""
    assert dataflow["resident"] == 1, dataflow
    assert dataflow["bytes_fetched"] > 0, dataflow
    assert dataflow["bytes_avoided"] > 0, dataflow
    assert "window_derive_s" in timings, timings


@pytest.mark.parametrize("seed,n_contigs,threads", [
    (23, 2, 1),    # mixed strands, sequential (monolithic assembly)
    (31, 2, 4),    # mixed strands, pipelined chunked emit
    (47, 1, 1),    # single contig
])
def test_resident_matches_host_e2e(tmp_path, seed, n_contigs, threads):
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=seed,
                                          n_contigs=n_contigs)
    want, host_tm, host_df = _run_leg(rp, pp, lp, resident=False,
                                      num_threads=threads)
    assert host_df["resident"] == 0, host_df
    got, tm, df = _run_leg(rp, pp, lp, resident=True,
                           num_threads=threads)
    _assert_engaged(tm, df)
    assert got == want


def test_resident_dummy_quality_fasta_reads(tmp_path):
    """FASTA reads (quality None): the PHRED gate must not fire on
    device either — has_q lanes are False, min-span still filters."""
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=29)
    fa = _fastq_to_fasta(rp, tmp_path / "reads.fasta")
    want, _, _ = _run_leg(fa, pp, lp, resident=False)
    got, tm, df = _run_leg(fa, pp, lp, resident=True)
    _assert_engaged(tm, df)
    assert got == want


def test_resident_f_mode_multi_overlap(tmp_path):
    """F-mode keeps every overlap per query (no best-per-group rule):
    the multi-overlap-per-read shape through the device derive."""
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=37)
    want, _, _ = _run_leg(rp, pp, lp, resident=False,
                          type_=PolisherType.F)
    got, tm, df = _run_leg(rp, pp, lp, resident=True,
                           type_=PolisherType.F)
    _assert_engaged(tm, df)
    assert got == want


def test_resident_high_quality_threshold_filters_on_device(tmp_path):
    """A threshold that actually rejects rows (the b'9'=24 qualities
    fail a 30.0 mean-PHRED gate) must reject the SAME rows on device —
    the integer-inequality form of the filter is exercised, not just
    the everything-passes case."""
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=41, n_contigs=1)
    want, _, _ = _run_leg(rp, pp, lp, resident=False,
                          quality_threshold=30.0)
    got, tm, df = _run_leg(rp, pp, lp, resident=True,
                           quality_threshold=30.0)
    _assert_engaged(tm, df)
    assert got == want


def test_resident_bails_on_fractional_quality_threshold(tmp_path):
    """The device mean-PHRED gate is exact only for integer thresholds:
    a fractional one must BAIL to the host path (resident gauge 0,
    bailout counted) and still produce byte-identical output."""
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=43, n_contigs=1)
    want, _, _ = _run_leg(rp, pp, lp, resident=False,
                          quality_threshold=10.5)
    got, tm, df = _run_leg(rp, pp, lp, resident=True,
                           quality_threshold=10.5)
    assert df["resident"] == 0, df
    assert df["resident_bailouts"] >= 1, df
    assert "window_derive_s" not in tm, tm
    assert got == want


def test_resident_off_publishes_zero_dataflow(tmp_path):
    """With the flag off, the dataflow ledger stays all-zero (the run
    report's v8 section is meaningful, not noise)."""
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=53, n_contigs=1)
    _, tm, df = _run_leg(rp, pp, lp, resident=False)
    assert df["resident"] == 0 and df["bytes_fetched"] == 0, df
    assert df["lanes_device_groups"] == 0, df
    assert "window_derive_s" not in tm, tm


def test_resident_unit_derive_matches_host_oracle():
    """Unit-level grid over the jit'd row-derive kernel vs an
    independent numpy re-statement of the host oracle's arithmetic:
    min-span boundary spans (0..3 around s_min=2), integer mean-PHRED
    boundaries, empty-layer rows, dead lanes and past-n_reg slots —
    the exactness proofs, pinned."""
    import jax.numpy as jnp

    from racon_tpu.ops.nw import (_ROW_SENTINEL, _derive_layer_rows,
                                  _pow2_pool)

    rng = np.random.default_rng(7)
    wl = 100
    B, NW, Lq = 16, 8, 256
    s_min = int(np.ceil(0.02 * wl))  # = 2
    q_need = 10

    pool_len = _pow2_pool(Lq * B)
    qpw = np.zeros(pool_len, np.uint16)
    # weights (high 13 bits of weight<<3|code) clustered around q_need
    # so the cross-multiplied PHRED gate lands on both sides, including
    # exact-equality sums
    qpw[:Lq * B] = (rng.integers(q_need - 2, q_need + 3,
                                 Lq * B).astype(np.uint16) << 3) \
        | rng.integers(0, 8, Lq * B).astype(np.uint16)
    weights = (qpw >> 3).astype(np.int64)

    tb = rng.integers(0, 64, B).astype(np.int32)
    qo_read = rng.integers(0, 32, B).astype(np.int32)
    qo_pool = (np.arange(B, dtype=np.int32) * Lq)
    n_reg = rng.integers(2, NW, B).astype(np.int32)
    live = rng.random(B) < 0.9
    has_q = rng.random(B) < 0.7
    qlen = np.full(B, Lq, np.int32)
    win_base = rng.integers(0, 1000, B).astype(np.int32)
    ov_idx = np.arange(B, dtype=np.int32)

    # packed tpos<<14|qpos slot tables (positions relative to the
    # overlap: tb/qo_read are added by the kernel), monotone per lane
    BIG = 1 << 30
    bp_first = np.full((B, NW), BIG, np.int32)
    bp_last = np.full((B, NW), BIG, np.int32)
    ref = np.zeros((B, NW, 4), np.int64)  # t_first, qf, t_endx, qe
    for b in range(B):
        t = int(rng.integers(0, wl // 2))
        q = 0
        for k in range(NW):
            span = int(rng.integers(0, 4))      # brackets s_min = 2
            t_span = int(rng.integers(1, wl))
            t_last = t + t_span - 1
            q_last = min(q + max(span - 1, 0), Lq - 2)
            bp_first[b, k] = (t << 14) | q
            bp_last[b, k] = (t_last << 14) | q_last
            ref[b, k] = (tb[b] + t, q, tb[b] + t_last + 1, q_last + 1)
            t = t_last + 1
            q = q_last + int(rng.integers(0, 2))

    rows = np.asarray(_derive_layer_rows(
        jnp.asarray(bp_first), jnp.asarray(bp_last), jnp.asarray(qpw),
        jnp.asarray(live), jnp.asarray(tb), jnp.asarray(qo_read),
        jnp.asarray(qo_pool), jnp.asarray(n_reg),
        jnp.asarray(win_base), jnp.asarray(ov_idx),
        jnp.asarray(has_q), jnp.asarray(qlen),
        np.int32(s_min), np.int32(q_need), w=wl, NW=NW, Lq=Lq))
    assert rows.shape == (B * NW, 6)

    csum = np.zeros(pool_len + 1, np.int64)
    np.cumsum(weights, out=csum[1:])
    checked_kept = checked_dropped = 0
    for b in range(B):
        for k in range(NW):
            row = rows[b * NW + k]
            if not live[b] or k > n_reg[b]:
                assert row[0] == _ROW_SENTINEL, (b, k, row)
                continue
            t_first, qf, t_endx, qe = ref[b, k]
            span = qe - qf
            keep = span >= s_min
            if keep and has_q[b]:
                lo = qo_pool[b] + qf
                keep = (csum[lo + span] - csum[lo]) >= q_need * span
            rank = t_first // wl
            lb = t_first - rank * wl
            le = t_endx - rank * wl - 1
            keep = keep and lb != le
            if not keep:
                assert row[0] == _ROW_SENTINEL, (b, k, row)
                checked_dropped += 1
            else:
                assert row[0] == win_base[b] + rank, (b, k, row)
                assert row[1] == ov_idx[b]
                assert row[2] == qo_read[b] + qf
                assert row[3] == qo_read[b] + qe
                assert row[4] == lb and row[5] == le
                checked_kept += 1
    # the grid must exercise both outcomes or the parity claim is hollow
    assert checked_kept > 0 and checked_dropped > 0
