"""Runtime-sanitizer coverage (``RACON_TPU_SANITIZE=1``).

The two acceptance halves from the graftlint issue:

- a **seeded int16 overflow** (packed-path score corruption injected at
  the kernel seam — the static guards make a real overflow unreachable,
  which is exactly what they are for) that ONLY the int32 shadow
  execution catches: the unsanitized run ships the corrupt result
  silently;
- a **deliberately stalled consensus consumer** that triggers the
  pipelined-polish queue watchdog's all-thread stack dump within the
  timeout.

Plus unit coverage for the canaries and the jit-retrace phase budget.
"""

import io
import time

import numpy as np
import pytest

from racon_tpu import sanitize


@pytest.fixture
def sanitize_on(monkeypatch):
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    monkeypatch.setenv("RACON_TPU_SANITIZE_SAMPLE", "1")


def _pairs(n=6, ln=120, seed=3):
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    pairs = []
    for _ in range(n):
        t = bases[rng.integers(0, 4, ln)]
        q = t.copy()
        flips = rng.random(ln) < 0.15
        q[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        pairs.append((q.tobytes(), t.tobytes()))
    return pairs


def _seed_packed_corruption(monkeypatch):
    """Inject the failure mode the SWAR guards exist to prevent: the
    packed path's scores come back off-by-one (what a wrapped int16
    lane produces), while the int32 path stays correct. Bypasses the
    bit-exactness probe — a real overflow would bypass it too, since
    the probe runs once at a safe small shape."""
    from racon_tpu.ops import nw, swar

    real = nw._nw_wavefront_kernel

    def corrupt(*args, **kw):
        packed, score = real(*args, **kw)
        if kw.get("swar"):
            score = score + 1
        return packed, score

    monkeypatch.setattr(nw, "_nw_wavefront_kernel", corrupt)
    monkeypatch.setattr(swar, "_SWAR_OK", True)


# ------------------------------------------------------ shadow execution

def _stress_windows(n=6, ln=120, depth=5, seed=3):
    from racon_tpu.core.window import Window, WindowType

    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    windows = []
    for wi in range(n):
        truth = bases[rng.integers(0, 4, ln)]
        bb = truth.copy()
        flips = rng.random(ln) < 0.1
        bb[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        win = Window(0, wi, WindowType.TGS, bb.tobytes(), b"!" * ln)
        for _ in range(depth):
            lay = truth.copy()
            flips = rng.random(ln) < 0.08
            lay[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
            win.add_layer(lay.tobytes(), b"9" * ln, 0, ln - 1)
        windows.append(win)
    return windows


def _consensus_engine():
    from racon_tpu.core.backends import CpuPoaConsensus
    from racon_tpu.ops.poa import TpuPoaConsensus

    return TpuPoaConsensus(3, -5, -4,
                           fallback=CpuPoaConsensus(3, -5, -4, 2),
                           use_swar=True)


def _seed_consensus_packed_corruption(monkeypatch):
    """Consensus-side analog of :func:`_seed_packed_corruption`: the
    packed refine loop's fetched coverage comes back off by one (what a
    wrapped packed lane downstream of the forward DP would produce),
    while the int32 loop stays correct."""
    from racon_tpu.ops import poa, swar

    real = poa._refine_loop_packed

    def corrupt(*args, **kw):
        out = real(*args, **kw)
        if kw.get("use_swar"):
            out = list(out)
            out[5] = out[5] + 1  # covs
            out = tuple(out)
        return out

    monkeypatch.setattr(poa, "_refine_loop_packed", corrupt)
    monkeypatch.setattr(swar, "_SWAR_OK", True)


def test_consensus_shadow_catches_seeded_corruption(sanitize_on,
                                                    monkeypatch):
    """Shadow execution now covers the consensus refine loop too
    (ROADMAP r8 follow-up): a packed-path-only corruption of the
    device-resident state is caught bit-for-bit."""
    _seed_consensus_packed_corruption(monkeypatch)
    with pytest.raises(sanitize.SwarShadowMismatch,
                       match="consensus SWAR group.*covs"):
        _consensus_engine().run(_stress_windows(), trim=True)


def test_consensus_corruption_silent_without_sanitizer(monkeypatch):
    monkeypatch.delenv("RACON_TPU_SANITIZE", raising=False)
    _seed_consensus_packed_corruption(monkeypatch)
    flags = _consensus_engine().run(_stress_windows(), trim=True)
    assert len(flags) == 6  # shipped silently — why the shadow exists


def test_consensus_clean_under_sanitizer(sanitize_on):
    """No seeded fault: the sanitized SWAR consensus passes its shadow
    and emits the same bytes as the int32 engine."""
    from racon_tpu.core.backends import CpuPoaConsensus
    from racon_tpu.ops.poa import TpuPoaConsensus

    wa = _stress_windows(seed=11)
    wb = _stress_windows(seed=11)
    _consensus_engine().run(wa, trim=True)
    TpuPoaConsensus(3, -5, -4, fallback=CpuPoaConsensus(3, -5, -4, 2),
                    use_swar=False).run(wb, trim=True)
    assert [w.consensus for w in wa] == [w.consensus for w in wb]


def test_swar_shadow_catches_seeded_overflow(sanitize_on, monkeypatch):
    from racon_tpu.ops.nw import TpuAligner

    _seed_packed_corruption(monkeypatch)
    aligner = TpuAligner(use_swar=True)
    with pytest.raises(sanitize.SwarShadowMismatch, match="score"):
        aligner.align_batch(_pairs())


def test_seeded_overflow_silent_without_sanitizer(monkeypatch):
    """The control half: with the sanitizer off, the same corruption
    sails through — results are produced with no error, which is why
    the shadow path exists."""
    from racon_tpu.ops.nw import TpuAligner

    monkeypatch.delenv("RACON_TPU_SANITIZE", raising=False)
    _seed_packed_corruption(monkeypatch)
    aligner = TpuAligner(use_swar=True)
    pairs = _pairs()
    out = aligner.align_batch(pairs)
    assert len(out) == len(pairs)  # shipped silently


def test_swar_path_clean_under_sanitizer(sanitize_on):
    """No seeded fault: the sanitized SWAR run passes the shadow check
    and produces the same CIGARs as the int32 path."""
    from racon_tpu.ops.nw import TpuAligner

    pairs = _pairs(seed=11)
    a = TpuAligner(use_swar=True).align_batch(pairs)
    b = TpuAligner(use_swar=False).align_batch(pairs)
    assert a == b


def test_sanitizer_error_pierces_pallas_fallback(sanitize_on,
                                                 monkeypatch):
    """A shadow mismatch must fail the run even on the Pallas-enabled
    path — the try-Pallas-then-XLA fallback chains catch Exception and
    would otherwise silently downgrade the chunk and swallow the
    sanitizer's verdict."""
    from racon_tpu.ops.nw import TpuAligner

    aligner = TpuAligner(use_swar=True)
    monkeypatch.setattr(TpuAligner, "_use_pallas", lambda self, key: True)

    def boom(*a, **kw):
        raise sanitize.SwarShadowMismatch("seeded divergence")

    monkeypatch.setattr(aligner, "_dispatch", boom)
    with pytest.raises(sanitize.SwarShadowMismatch, match="seeded"):
        aligner.align_batch(_pairs(n=2))


def test_shadow_compare_unit():
    x = np.arange(8)
    sanitize.shadow_compare((x,), (x.copy(),), ("x",), "unit")  # equal: ok
    with pytest.raises(sanitize.SwarShadowMismatch, match="2/8"):
        y = x.copy()
        y[3:5] += 1
        sanitize.shadow_compare((x,), (y,), ("x",), "unit")


def test_shadow_sampler(sanitize_on, monkeypatch):
    monkeypatch.setenv("RACON_TPU_SANITIZE_SAMPLE", "4")
    s = sanitize.ShadowSampler()
    hits = [s.should_shadow() for _ in range(8)]
    assert hits == [True, False, False, False, True, False, False, False]
    # a fresh run gets a fresh sampler: its first chunk is always checked
    assert sanitize.ShadowSampler().should_shadow()
    monkeypatch.setenv("RACON_TPU_SANITIZE", "0")
    assert not sanitize.ShadowSampler().should_shadow()


# --------------------------------------------------------------- canaries

def test_aligner_canary_catches_wraparound():
    ok = np.array([0, 5, 1 << 28])
    sanitize.check_aligner_canaries(ok, np.zeros(3), np.zeros(3),
                                    big=1 << 28, context="t")
    with pytest.raises(sanitize.CanaryError, match="wraparound"):
        sanitize.check_aligner_canaries(np.array([5, -3]), np.zeros(2),
                                        np.zeros(2), big=1 << 28,
                                        context="t")
    with pytest.raises(sanitize.CanaryError, match="endpoint"):
        sanitize.check_aligner_canaries(ok, np.array([0, -1, 0]),
                                        np.zeros(3), big=1 << 28,
                                        context="t")


def test_consensus_canary_catches_corruption():
    bc = np.array([[0, 3, 5]], np.uint8)
    sanitize.check_consensus_canaries(bc, np.array([3]), np.ones((1, 3)),
                                      Lb=8, context="t")
    with pytest.raises(sanitize.CanaryError, match="alphabet"):
        sanitize.check_consensus_canaries(np.array([[0, 7]], np.uint8),
                                          np.array([2]), np.ones((1, 2)),
                                          Lb=8, context="t")
    with pytest.raises(sanitize.CanaryError, match="length"):
        sanitize.check_consensus_canaries(bc, np.array([9]),
                                          np.ones((1, 3)), Lb=8,
                                          context="t")


# --------------------------------------------------------- retrace budget

def test_retrace_budget(sanitize_on):
    import jax.numpy as jnp

    from racon_tpu.ops import nw

    def run(batch):
        qrp = jnp.zeros((batch, 64 + 256 + 128), jnp.uint8)
        tp = jnp.zeros((batch, 64 + 256 + 128), jnp.uint8)
        n = jnp.ones((batch,), jnp.int32)
        m = jnp.ones((batch,), jnp.int32)
        nw._nw_wavefront_kernel(qrp, tp, n, m, max_len=256, band=128)

    run(2)  # warm the shape outside any budget
    with sanitize.PhaseRetraceBudget("warm", budget=0):
        run(2)  # cache hit: zero new entries
    with pytest.raises(sanitize.RetraceBudgetExceeded, match="cold"):
        with sanitize.PhaseRetraceBudget("cold", budget=0):
            run(4)  # new batch shape: one silent recompile


def test_retrace_budget_failure_in_run_raises_not_hangs(tmp_path,
                                                        monkeypatch):
    """When the consensus-phase budget fires inside the pipelined
    run(), the error must propagate — the producer is already retired
    by then, so the fault path must not block draining the queue."""
    from racon_tpu.core.polisher import create_polisher
    from test_columnar_init import write_synthetic_assembly

    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    # align phase (enter/exit = first two reads) sees delta 0; the
    # consensus phase exit then reports a huge delta
    reads = iter([0, 0, 0, 10**6])
    monkeypatch.setattr(sanitize, "retrace_count",
                        lambda *a: next(reads, 10**6))
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=37, n_contigs=1,
                                          contig=1500)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2)
    with pytest.raises(sanitize.RetraceBudgetExceeded, match="consensus"):
        p.run(True)


def test_retrace_budget_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("RACON_TPU_SANITIZE", raising=False)
    import jax.numpy as jnp

    from racon_tpu.ops import nw

    with sanitize.PhaseRetraceBudget("off", budget=0):
        qrp = jnp.zeros((8, 64 + 256 + 128), jnp.uint8)
        tp = jnp.zeros((8, 64 + 256 + 128), jnp.uint8)
        nw._nw_wavefront_kernel(qrp, tp, jnp.ones((8,), jnp.int32),
                                jnp.ones((8,), jnp.int32),
                                max_len=256, band=128)


# ---------------------------------------------------------- queue watchdog

def test_queue_watchdog_dumps_stacks_on_stall():
    buf = io.StringIO()
    wd = sanitize.QueueWatchdog(0.2, "test-queue", stream=buf).start()
    try:
        wd.beat()
        assert wd.stalled.wait(5.0), "watchdog never fired"
    finally:
        wd.stop()
    out = buf.getvalue()
    assert "test-queue made no progress" in out
    assert "MainThread" in out  # every thread's stack is in the dump
    assert wd.fired == 1  # one dump per stall, not one per poll


def test_queue_watchdog_quiet_while_beating():
    buf = io.StringIO()
    wd = sanitize.QueueWatchdog(0.3, "beating", stream=buf).start()
    try:
        for _ in range(6):
            wd.beat()
            time.sleep(0.05)
    finally:
        wd.stop()
    assert wd.fired == 0 and buf.getvalue() == ""


# ------------------------------------------------------ lock-order witness

def test_lock_witness_fires_on_inverted_order():
    """The acceptance pair, hostile half: two locks taken A->B on one
    code path and B->A on another build a cycle in the order graph —
    a potential deadlock even though this single-threaded run never
    hangs — and the report carries both edges' stacks."""
    w = sanitize.LockOrderWitness()
    a = sanitize.WitnessedLock("exec.A", witness=w)
    b = sanitize.WitnessedLock("exec.B", witness=w)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = w.cycles()
    assert len(cycles) == 1 and set(cycles[0]) == {"exec.A", "exec.B"}
    buf = io.StringIO()
    assert w.report(buf) == 1
    out = buf.getvalue()
    assert "potential deadlock" in out
    # both edges of the cycle print with their first-seen stacks
    assert "edge exec.A -> exec.B" in out
    assert "edge exec.B -> exec.A" in out
    assert out.count("test_lock_witness_fires_on_inverted_order") >= 2


def test_lock_witness_silent_on_ordered_acquisition():
    """The acceptance pair, clean half: nesting that always follows one
    global order (A then B) builds an acyclic graph — no report."""
    w = sanitize.LockOrderWitness()
    a = sanitize.WitnessedLock("exec.A", witness=w)
    b = sanitize.WitnessedLock("exec.B", witness=w)
    for _ in range(3):
        with a:
            with b:
                pass
    assert w.cycles() == []
    buf = io.StringIO()
    assert w.report(buf) == 0 and buf.getvalue() == ""


def test_lock_witness_cross_thread_edges():
    """Edges recorded on different threads still compose into one
    cycle: thread 1 takes A->B, thread 2 takes B->A — the classic
    two-thread deadlock shape, witnessed without ever deadlocking."""
    import threading

    w = sanitize.LockOrderWitness()
    a = sanitize.WitnessedLock("serve.A", witness=w)
    b = sanitize.WitnessedLock("serve.B", witness=w)

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    for fn in (t1, t2):  # sequential: order edges, never the hang
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    assert len(w.cycles()) == 1


def test_named_lock_plain_when_disabled(monkeypatch):
    import threading

    monkeypatch.delenv("RACON_TPU_SANITIZE", raising=False)
    lock = sanitize.named_lock("x")
    assert isinstance(lock, type(threading.Lock()))


def test_named_lock_witnessed_and_condition_compatible(sanitize_on):
    """serve builds threading.Condition(named_lock(...)): the witness
    wrapper must drive the Condition protocol (wait releases/reacquires
    through acquire/release, so the held record stays truthful)."""
    import threading

    lock = sanitize.named_lock("serve.test")
    assert isinstance(lock, sanitize.WitnessedLock)
    cond = threading.Condition(lock)
    ready = threading.Event()

    def waker():
        ready.wait(5.0)
        with cond:
            cond.notify_all()

    t = threading.Thread(target=waker)
    t.start()
    with cond:
        ready.set()
        cond.wait(5.0)
    t.join()
    # balanced acquire/release: nothing held, no edges, no cycles
    assert sanitize.lock_witness().cycles() == []


def test_exec_run_under_witness_is_acyclic(sanitize_on, tmp_path):
    """Armed end-to-end: a real 2-shard exec run constructed under
    RACON_TPU_SANITIZE=1 gets WitnessedLocks for its manifest/notes/
    states coordination points, and the full drain (claims, state
    saves, snapshot writes, heartbeat) leaves the process-wide
    acquisition-order graph acyclic — the invariant the CI chaos soaks
    lock in at scale."""
    from racon_tpu.exec.runner import ShardRunner
    from test_columnar_init import write_synthetic_assembly

    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=5, n_contigs=2,
                                          contig=1200)
    runner = ShardRunner(str(rp), str(pp), str(lp), n_shards=2,
                         num_threads=2,
                         work_dir=str(tmp_path / "wd"))
    assert isinstance(runner._mf_lock, sanitize.WitnessedLock)
    out = io.BytesIO()
    runner.run(out)
    assert out.getvalue().startswith(b">")
    assert sanitize.lock_witness().cycles() == []


def test_stalled_consumer_triggers_watchdog(tmp_path, monkeypatch,
                                            capsys):
    """Integration half: a Polisher.run() whose consensus consumer
    deliberately stalls past the timeout gets the all-thread stack dump
    on stderr (and the run still completes — the watchdog reports, it
    never kills)."""
    from racon_tpu.core.polisher import create_polisher
    from test_columnar_init import write_synthetic_assembly

    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    monkeypatch.setenv("RACON_TPU_SANITIZE_WATCHDOG_S", "0.3")
    rp, pp, lp = write_synthetic_assembly(tmp_path, seed=29, n_contigs=1,
                                          contig=2000)
    p = create_polisher(str(rp), str(pp), str(lp), num_threads=2)
    real_run = p.consensus.run
    state = {"stalled": False}

    def stalling(windows, trim, progress=None):
        if not state["stalled"]:
            state["stalled"] = True
            time.sleep(1.2)  # consumer wedged well past the timeout
        return real_run(windows, trim)

    p.consensus.run = stalling
    out = p.run(True)
    assert len(out) == 1  # the run itself still completes
    err = capsys.readouterr().err
    assert "watchdog" in err and "dumping" in err
    # the dump carries the wedged consumer's frame (the producer thread
    # finished long before the stall, so only live threads appear)
    assert "in stalling" in err
