"""Stress-shaped scale probe correctness (VERDICT r4 #6): on a window
set that exercises the device engine's reject contract — w=500-regime
lengths (80% exactly 500 bp, 20% shorter tails), depths 3..400,
oversized layers, a low-identity slice — the
telemetry must actually fire, and every window the device REJECTS must
come out byte-identical to a CPU-engine-only polish of the same window
(the reject path routes through the same fallback engine; reference
analog: ``src/cuda/cudabatch.cpp:135-156`` rejects re-polished on spoa).
"""

import os
import sys

import pytest

from racon_tpu import flags as racon_flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RUN_SLOW = racon_flags.get_bool("RACON_TPU_SLOW")


@pytest.mark.skipif(not RUN_SLOW, reason="set RACON_TPU_SLOW=1")
def test_stress_scale_rejects_match_cpu_only():
    from bench import build_stress_windows
    from racon_tpu.core.backends import CpuPoaConsensus
    from racon_tpu.ops.poa import TpuPoaConsensus

    windows = build_stress_windows(0.1)
    assert len(windows) >= 100  # all stress kinds present (period 50)
    eng = TpuPoaConsensus(3, -5, -4,
                          fallback=CpuPoaConsensus(3, -5, -4, 8),
                          num_batches=2)
    flags = eng.run(windows, trim=True)
    # the reject contract fires on this workload
    assert eng.stats["fallback_windows"] > 0, eng.stats
    assert eng.stats["dropped_layers"] > 0, eng.stats
    assert eng.stats["passthrough"] > 0, eng.stats
    assert eng.stats["device_windows"] > len(windows) // 2, eng.stats
    assert all(len(w.consensus) > 0 for w in windows)

    # CPU-engine-only polish of the same (deterministically rebuilt) set
    cpu_windows = build_stress_windows(0.1)
    cpu = CpuPoaConsensus(3, -5, -4, 8)
    cpu.run(cpu_windows, trim=True)

    # kind-49 windows carry layers far beyond the device pair buffer —
    # deterministic rejects, so their output must equal the CPU-only run
    n_checked = 0
    for i, (w, cw) in enumerate(zip(windows, cpu_windows)):
        if i % 50 == 49:
            assert w.consensus == cw.consensus, i
            n_checked += 1
    assert n_checked >= 2
    # kind-47 windows (<3 sequences) pass through as their backbone
    for i, w in enumerate(windows):
        if i % 50 == 47:
            assert w.consensus == w.sequences[0], i
    assert sum(flags) > len(windows) // 2
