"""The resident polishing service (round 14, ROADMAP item 3).

Acceptance contract at test scale: jobs submitted over the unix-socket
newline-JSON protocol come back **byte-identical** to the equivalent
one-shot CLI run; once the engine pool is warm, a job's compile cost is
~zero (``compile_s``/``retrace`` from job #2 on — the
``service_compile_fraction < 0.1`` criterion, measured for real by
``bench_service()``); admission rejects with a reason instead of
OOMing; a job walking the fault ladder never takes the server down; and
every job returns a schema-valid per-job run report built from its own
metric scope (two interleaved jobs report disjoint numbers — the
``clear_run`` one-run-per-process fix).
"""

import io
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from racon_tpu.obs import metrics
from racon_tpu.obs.report import validate_report
from racon_tpu.serve import protocol
from racon_tpu.serve.client import ServiceClient, submit_and_stream
from racon_tpu.serve.service import PolishServer, parse_warm_shapes

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -------------------------------------------------------------- workloads

def _assembly(td, sizes, seed=31, prefix="a"):
    """Synthetic per-contig assembly triple (the test_topology
    generator, re-homed so serve tests stand alone)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    comp = bytes.maketrans(b"ACGT", b"TGCA")

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, n)] for n in sizes]
    layout = os.path.join(td, f"{prefix}_layout.fasta")
    with open(layout, "wb") as f:
        for ti, t in enumerate(truths):
            f.write(b">ctg%d\n" % ti + mutate(t, 0.06).tobytes() + b"\n")
    reads = os.path.join(td, f"{prefix}_reads.fastq")
    paf = os.path.join(td, f"{prefix}_ovl.paf")
    with open(reads, "wb") as rf, open(paf, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            contig = len(truth)
            for start in range(0, max(1, contig - 600), 150):
                end = min(start + 900, contig)
                read = mutate(truth[start:end], 0.08)
                name = b"%s_read%d" % (prefix.encode(), ri)
                strand = b"-" if ri % 3 == 0 else b"+"
                rb = (read.tobytes().translate(comp)[::-1]
                      if strand == b"-" else read.tobytes())
                rf.write(b"@" + name + b"\n" + rb + b"\n+\n"
                         + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    strand, b"ctg%d" % ti, b"%d" % contig,
                    b"%d" % start, b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1
    return reads, paf, layout


def _spec(reads, paf, layout, **opts):
    spec = {"sequences": reads, "overlaps": paf,
            "target_sequences": layout, "window_length": 150,
            "threads": 2}
    spec.update(opts)
    return spec


def _oneshot_cli(reads, paf, layout, *extra):
    """The equivalent one-shot CLI run's stdout (the byte-identity
    reference)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-w", "150", "-t", "2",
         *extra, reads, paf, layout],
        capture_output=True, timeout=600, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc.stdout


@pytest.fixture()
def short_tmp():
    """AF_UNIX socket paths are length-bounded (~107 bytes); pytest's
    tmp_path can blow through that, so sockets live in a short /tmp
    dir."""
    with tempfile.TemporaryDirectory(dir="/tmp", prefix="rsv") as td:
        yield td


class _Server:
    """In-process server harness: serve_forever on a thread, always
    shut down (and joined) on exit."""

    def __init__(self, td, **kw):
        self.server = PolishServer(os.path.join(td, "racon.sock"), **kw)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    def __enter__(self):
        self.thread.start()
        assert self.server.started.wait(60), "server did not start"
        return self.server

    def __exit__(self, exc_type, exc, tb):
        self.server.shutdown()
        self.thread.join(timeout=30)
        return False

    def client(self, timeout_s=300.0):
        return ServiceClient(self.server.socket_path,
                             timeout_s=timeout_s)


# --------------------------------------------------------------- protocol

def test_protocol_roundtrip(short_tmp, monkeypatch):
    """submit/status/result round-trip over a real socket, plus the
    protocol's error paths (unknown op/job, malformed line) — none of
    which may end the server."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2500])
    with _Server(short_tmp, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            pong = c.ping()
            assert pong["ok"] and pong["workers"] == 1
            assert pong["profile"]["match"] == 3

            # error paths first: the server must shrug them off
            bad = c._roundtrip({"op": "frobnicate"})
            assert not bad["ok"] and "unknown op" in bad["error"]
            bad = c.status("j999")
            assert not bad["ok"] and "unknown job" in bad["error"]

            sub = c.submit(_spec(reads, paf, layout))
            assert sub["ok"] and sub["job"] == "j1"
            assert sub["cost_bytes"] > 0
            header, payload = c.result(sub["job"], timeout_s=300)
            assert header["ok"] and header["state"] == "done"
            assert header["bytes"] == len(payload)
            assert payload.startswith(b">ctg0")
            st = c.status(sub["job"])
            assert st["state"] == "done" and st["engine"] == "primary"

            # retention: the payload is handed out once
            again, payload2 = c.result(sub["job"], timeout_s=10)
            assert payload2 is None
            assert "already collected" in again["error"]

        # a malformed line errors that connection, not the server
        with ServiceClient(server.socket_path) as c:
            c.sock.sendall(b"this is not json\n")
            resp = protocol.read_msg(c.rfile)
            assert not resp["ok"] and "bad request" in resp["error"]
        with ServiceClient(server.socket_path) as c:
            assert c.ping()["ok"]  # still serving


def test_concurrent_jobs_byte_identical_to_oneshot_cli(short_tmp,
                                                       monkeypatch):
    """THE byte-identity acceptance: three different jobs running
    CONCURRENTLY on a two-worker pool each stream back exactly the
    bytes the equivalent one-shot CLI run prints."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    triples = [_assembly(short_tmp, [2200 + 400 * i], seed=11 + i,
                         prefix=f"w{i}") for i in range(3)]
    want = [_oneshot_cli(*t) for t in triples]
    got = [None] * 3
    errors = []
    with _Server(short_tmp, num_threads=2, workers=2) as server:
        def one(i):
            try:
                with ServiceClient(server.socket_path) as c:
                    sub = c.submit(_spec(*triples[i]))
                    assert sub["ok"], sub
                    header, payload = c.result(sub["job"],
                                               timeout_s=300)
                    assert header["ok"], header
                    got[i] = payload
            # graftlint: disable=swallowed-exception (re-raised via the errors list on the main thread)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        stats = server._counts
        assert stats["done"] == 3 and stats["failed"] == 0
    for i in range(3):
        assert got[i] == want[i], f"job {i} diverged from one-shot CLI"


def test_submit_cli_streams_byte_identical(short_tmp, monkeypatch):
    """``racon --submit SOCK ...`` — the full CLI client — streams the
    job's FASTA to stdout byte-identical to the one-shot run, and
    writes the per-job report when asked."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2600], seed=5)
    want = _oneshot_cli(reads, paf, layout)
    report_path = os.path.join(short_tmp, "job_report.json")
    with _Server(short_tmp, num_threads=2) as server:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "racon_tpu", "-w", "150", "-t", "2",
             "--submit", server.socket_path,
             "--run-report", report_path, reads, paf, layout],
            capture_output=True, timeout=600, cwd=REPO_ROOT, env=env)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        assert proc.stdout == want
        assert b"done in" in proc.stderr
    import json
    with open(report_path) as f:
        rep = json.load(f)
    assert rep["kind"] == "job" and validate_report(rep) == []


# -------------------------------------------------------------- admission

def test_admission_rejects_with_reason(short_tmp, monkeypatch):
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2000], seed=3)
    with _Server(short_tmp, budget_bytes=16 << 10, max_queue=1,
                 autostart=False) as server:
        with ServiceClient(server.socket_path) as c:
            # over-budget: rejected with the budget in the reason —
            # never silently queued into an OOM
            r = c.submit(_spec(reads, paf, layout))
            assert not r["ok"] and r.get("rejected")
            assert "exceeds the service budget" in r["error"]
    with _Server(short_tmp, max_queue=1, autostart=False) as server:
        with ServiceClient(server.socket_path) as c:
            # engine-profile mismatch: the resident kernels are
            # compiled for the server's scores
            r = c.submit(_spec(reads, paf, layout, match=5))
            assert not r["ok"]
            assert "engine profile mismatch" in r["error"]
            # missing input
            r = c.submit(_spec("/nonexistent.fasta", paf, layout))
            assert not r["ok"] and "input not found" in r["error"]
            # malformed spec
            r = c.submit({"sequences": reads})
            assert not r["ok"] and "missing input path" in r["error"]
            # queue bound (workers are parked, so the first job stays
            # queued deterministically)
            assert c.submit(_spec(reads, paf, layout))["ok"]
            r = c.submit(_spec(reads, paf, layout))
            assert not r["ok"] and "queue full" in r["error"]


def test_cancel_and_queue_order(short_tmp, monkeypatch):
    """A queued job cancels cleanly (and never runs); a running or
    terminal one refuses with the reason."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2000], seed=9)
    with _Server(short_tmp, autostart=False, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            j1 = c.submit(_spec(reads, paf, layout))["job"]
            j2 = c.submit(_spec(reads, paf, layout))["job"]
            assert c.status(j2)["queue_position"] == 1
            r = c.cancel(j1)
            assert r["ok"] and r["state"] == "cancelled"
            server.start_workers()
            header, payload = c.result(j2, timeout_s=300)
            assert header["ok"] and payload
            h1, p1 = c.result(j1, timeout_s=10)
            assert not h1["ok"] and p1 is None
            assert h1["state"] == "cancelled"
            r = c.cancel(j2)  # terminal: not cancellable
            assert not r["ok"] and "not queued" in r["error"]


def test_result_survives_dead_client(short_tmp, monkeypatch):
    """A client that asked for the result and died waiting must not
    burn the one-fetch retention: the payload is dropped only after a
    SUCCESSFUL send, so a reconnecting client still gets it.  A
    malformed request field answers with the reason instead of
    killing the connection."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2200], seed=41)
    with _Server(short_tmp, autostart=False, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            job_id = c.submit(_spec(reads, paf, layout))["job"]
            # malformed field: reject-with-reason, connection survives
            bad = c._roundtrip({"op": "result", "job": job_id,
                                "timeout_s": "soon"})
            assert not bad["ok"] and "bad request field" in bad["error"]
            assert c.ping()["ok"]
        # client A requests the result, then dies while the job is
        # still queued (the workers are parked — deterministic)
        dead = ServiceClient(server.socket_path)
        protocol.send_msg(dead.sock, {"op": "result", "job": job_id,
                                      "timeout_s": 300})
        time.sleep(0.2)
        dead.close()
        server.start_workers()
        with ServiceClient(server.socket_path) as c:
            header, payload = c.result(job_id, timeout_s=300)
        assert header["ok"], header
        assert payload and payload.startswith(b">ctg0")
    # the job's scoped metrics were retired with the job
    assert metrics.group(metrics.job_scope(job_id)) == {}


def test_footprint_bounds_concurrency(short_tmp, monkeypatch):
    """Two jobs that each fit the budget alone — but not together —
    run strictly serially on a two-worker pool: the in-flight
    footprint gate, not worker count, bounds concurrency (the
    reject-over-silent-OOM contract's runtime half)."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2400], seed=17)
    from racon_tpu.exec.planner import estimate_job_cost
    cost = estimate_job_cost(reads, paf, layout)
    with _Server(short_tmp, num_threads=2, workers=2,
                 budget_bytes=int(1.5 * cost)) as server:
        with ServiceClient(server.socket_path) as c:
            j1 = c.submit(_spec(reads, paf, layout))["job"]
            j2 = c.submit(_spec(reads, paf, layout))["job"]
            h1, p1 = c.result(j1, timeout_s=300)
            h2, p2 = c.result(j2, timeout_s=300)
    assert h1["ok"] and h2["ok"] and p1 == p2
    job1 = server._jobs[j1]
    job2 = server._jobs[j2]
    # FIFO: j1 started first, and j2 could not start until j1's
    # footprint was released
    assert job2.started_at >= job1.started_at + job1.wall_s - 0.05


# ------------------------------------------------------------ fault ladder

def test_fault_ladder_and_server_survival(short_tmp, monkeypatch):
    """Injected faults walk the per-job degradation ladder — transient
    backoff, CPU retry, fail-with-reason — and the server keeps serving
    after every outcome (the resident pool must outlive any job)."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setenv("RACON_TPU_EXEC_BACKOFF_S", "0")
    reads, paf, layout = _assembly(short_tmp, [2400], seed=13)
    want = _oneshot_cli(reads, paf, layout)
    with _Server(short_tmp, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            # deterministic-compute fault on the first attempt: ladder
            # falls through to the CPU engines and the job SUCCEEDS
            monkeypatch.setenv("RACON_TPU_FAULTS", "serve.polish:err@1")
            sub = c.submit(_spec(reads, paf, layout))
            header, payload = c.result(sub["job"], timeout_s=300)
            assert header["ok"], header
            assert payload == want
            assert header["engine"] == "cpu-retry"
            acts = [a["action"] for a in header["attempts"]]
            assert acts == ["cpu-retry"]

            # transient-io fault: same-engine retry with backoff
            monkeypatch.setenv("RACON_TPU_FAULTS", "serve.polish:io@1")
            sub = c.submit(_spec(reads, paf, layout))
            header, payload = c.result(sub["job"], timeout_s=300)
            assert header["ok"] and payload == want
            assert header["engine"] == "primary"
            assert [a["action"] for a in header["attempts"]] \
                == ["retry-backoff"]

            # a job that fails EVERY rung is failed with the full
            # ladder record — and the server survives it
            monkeypatch.setenv("RACON_TPU_FAULTS", "serve.polish:err*")
            sub = c.submit(_spec(reads, paf, layout))
            header, payload = c.result(sub["job"], timeout_s=300)
            assert not header["ok"] and header["state"] == "failed"
            assert payload is None
            assert "InjectedFault" in header["error"]
            acts = [a["action"] for a in header["attempts"]]
            assert acts == ["cpu-retry", "fail"]
            rep = header["report"]
            assert validate_report(rep) == []
            assert rep["faults"].get("deterministic-compute", 0) >= 2

            # ladder over: the next clean job polishes fine
            monkeypatch.delenv("RACON_TPU_FAULTS")
            sub = c.submit(_spec(reads, paf, layout))
            header, payload = c.result(sub["job"], timeout_s=300)
            assert header["ok"] and payload == want


# ------------------------------------------- per-job obs + warm-path claim

def test_warm_path_report_compile_amortized(short_tmp, monkeypatch):
    """The tentpole's measured claim at test scale, on the DEVICE
    engine: job #1 pays the jit compiles, job #2 with the same
    geometry recompiles NOTHING (per-job retrace == 0) and its
    measured XLA compile seconds are under 10% of its wall — the
    ``service_compile_fraction < 0.1`` criterion — while both jobs'
    reports validate and carry disjoint scoped metrics."""
    import racon_tpu.core.backends as backends_mod
    import racon_tpu.ops.poa as poa_mod
    monkeypatch.setattr(poa_mod, "BAND", 64)  # small-geometry compiles
    monkeypatch.setattr(backends_mod, "_auto_mesh", lambda mesh: None)
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    # admission warm-up estimates a geometry from file sizes; a
    # background compile racing job #2's consensus phase would blur
    # the retrace == 0 assert, so park it for this test
    monkeypatch.setattr(PolishServer, "_warm_job_geometry",
                        lambda self, spec: None)
    reads, paf, layout = _assembly(short_tmp, [2600], seed=23)
    with _Server(short_tmp, num_threads=2,
                 consensus_backend="tpu") as server:
        with ServiceClient(server.socket_path) as c:
            reports = []
            for k in range(2):
                sub = c.submit(_spec(reads, paf, layout))
                header, payload = c.result(sub["job"], timeout_s=600)
                assert header["ok"], header
                assert payload.startswith(b">ctg0")
                reports.append(header)
    rep1, rep2 = (h["report"] for h in reports)
    assert validate_report(rep1) == [] and validate_report(rep2) == []
    assert rep1["kind"] == "job" and rep2["kind"] == "job"
    # job 1 compiled the consensus loop; job 2 hit the warm caches
    assert sum(rep1["retrace"].values()) > 0
    assert sum(rep2["retrace"].values()) == 0, rep2["retrace"]
    assert reports[1]["compile_s"] <= max(0.1 * reports[1]["wall_s"],
                                          0.05), reports[1]
    # per-job scoping: each report embeds only its own scope's numbers
    assert rep1["metrics"]["timers"].get("consensus", 0) > 0
    assert rep2["metrics"]["timers"].get("consensus", 0) > 0
    assert rep2["dispatch_fetch"]["consensus_dispatch_s"] >= 0


def test_startup_warm_profile_reaches_engines(short_tmp, monkeypatch):
    """RACON_TPU_SERVE_WARM_SHAPES drives warmup_async on every pool
    worker at startup — job #1's shapes compile before job #1
    exists."""
    calls = []

    def fake_warm(self, wl, pairs, windows, est_layer_len=0,
                  est_contigs=0):
        calls.append((wl, pairs, windows, est_contigs))
        return None

    import racon_tpu.ops.poa as poa_mod
    monkeypatch.setattr(poa_mod.TpuPoaConsensus, "warmup_async",
                        fake_warm)
    monkeypatch.setattr(
        "racon_tpu.core.backends._auto_mesh", lambda mesh: None)
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES",
                       "500:4096:512:4,250:2048:256:2")
    with _Server(short_tmp, consensus_backend="tpu",
                 autostart=False):
        pass
    assert (500, 4096, 512, 4) in calls
    assert (250, 2048, 256, 2) in calls


def test_parse_warm_shapes():
    assert parse_warm_shapes("500:131072:8192:8") == \
        [(500, 131072, 8192, 8)]
    assert parse_warm_shapes("500:10:5, 250:4:2:7") == \
        [(500, 10, 5, 1), (250, 4, 2, 7)]
    assert parse_warm_shapes("") == []
    with pytest.raises(ValueError):
        parse_warm_shapes("500:10")
    with pytest.raises(ValueError):
        parse_warm_shapes("500:0:5")


def test_interleaved_job_scopes_stay_disjoint():
    """The satellite regression for obs: ``metrics.clear_run()`` fired
    by one concurrent job (a run boundary in its thread) must NOT wipe
    another job's in-flight scoped gauges, and two interleaved jobs'
    scoped numbers stay disjoint and correct."""
    metrics.clear_job("A")
    metrics.clear_job("B")
    barrier = threading.Barrier(2, timeout=30)
    results = {}

    def job(name, gauge_val):
        metrics.set_scope(metrics.job_scope(name))
        try:
            metrics.set_gauge("queue.depth", gauge_val)
            metrics.inc("consensus.groups", gauge_val)
            metrics.add_time("align.dispatch", gauge_val / 10.0)
            barrier.wait()
            if name == "B":
                # the one-run-per-process assumption under test: a run
                # boundary inside job B (obs.begin / a bench leg)...
                metrics.clear_run()
            barrier.wait()
            results[name] = {
                "gauge": metrics.gauge(
                    metrics.job_scope(name) + "queue.depth"),
                "group": metrics.group(metrics.job_scope(name)),
            }
        finally:
            metrics.set_scope(None)

    ta = threading.Thread(target=job, args=("A", 3))
    tb = threading.Thread(target=job, args=("B", 7))
    ta.start(), tb.start()
    ta.join(30), tb.join(30)
    # ...must not have wiped job A's in-flight gauges
    assert results["A"]["gauge"] == 3
    assert results["A"]["group"]["queue.depth"] == 3
    assert results["A"]["group"]["consensus.groups"] == 3
    assert results["B"]["group"]["consensus.groups"] == 7
    assert set(results["A"]["group"]) == set(results["B"]["group"])
    # and the two jobs' namespaces never bled into each other
    assert results["A"]["group"]["align.dispatch"] == \
        pytest.approx(0.3)
    assert results["B"]["group"]["align.dispatch"] == \
        pytest.approx(0.7)
    metrics.clear_job("A")
    metrics.clear_job("B")


def test_producer_thread_inherits_job_scope(short_tmp, monkeypatch):
    """``Polisher.run`` spawns a layer-producer thread; its queue
    telemetry must land in the spawning job's scope, not the global
    namespace (thread-locals do not inherit — the polisher forwards
    the scope explicitly)."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2400], seed=29)
    metrics.clear("queue.")
    with _Server(short_tmp, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            sub = c.submit(_spec(reads, paf, layout, threads=2))
            header, _ = c.result(sub["job"], timeout_s=300)
            assert header["ok"]
            rep = header["report"]
    # producer wait seconds were recorded — inside the job's scope
    assert "queue.producer_wait_s" in rep["metrics"]["timers"]
    # ...and not leaked into the global namespace by the producer
    assert metrics.timer_s("queue.producer_wait_s") == 0.0


# ------------------------------------------- sanitized serve warm path

@pytest.mark.slow  # device-engine compiles; the CI resident-service shard runs it
def test_serve_sanitized_warm_path_assert_fires_only_when_unwarmed(
        short_tmp, monkeypatch):
    """THE round-18 serve acceptance at test scale, on the device
    engine under RACON_TPU_SANITIZE=1: job #1 compiles and seals the
    warm path; job #2 (same spec) is warm — zero post-warm compiles,
    succeeds; job #3 (a window length the warm set never saw,
    admission warm-up parked) compiles a genuinely unwarmed geometry —
    the sanitized assert FAILS it with the offending signature named
    next to the nearest warmed one.  Defined LAST in this file on
    purpose: it traces the same engine geometries the warm-path/
    retrace asserts above rely on being cold."""
    import racon_tpu.core.backends as backends_mod
    import racon_tpu.ops.poa as poa_mod
    from racon_tpu.obs import compilewatch, report

    monkeypatch.setattr(poa_mod, "BAND", 64)  # small-geometry compiles
    monkeypatch.setattr(backends_mod, "_auto_mesh", lambda mesh: None)
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    # headroom: job #1's cold compiles are the point, not a retrace bug
    monkeypatch.setenv("RACON_TPU_SANITIZE_RETRACE_BUDGET", "512")
    # park the SWAR shadow sampler: this test is about the warm-path
    # assert, and shadow re-dispatches would compile int32 twins of
    # every geometry (cost, and extra warmed shapes)
    monkeypatch.setenv("RACON_TPU_SANITIZE_SAMPLE", "1000000")
    # park the admission warm-up so job #3's new geometry is GENUINELY
    # unwarmed (normally it would start compiling at admission)
    monkeypatch.setattr(PolishServer, "_warm_job_geometry",
                        lambda self, spec: None)

    reads, paf, layout = _assembly(short_tmp, [2600], seed=23)
    try:
        with _Server(short_tmp, num_threads=2,
                     consensus_backend="tpu") as server:
            with ServiceClient(server.socket_path) as c:
                # job #1: cold compiles, completes, seals the warm path
                sub = c.submit(_spec(reads, paf, layout))
                h1, p1 = c.result(sub["job"], timeout_s=600)
                assert h1["ok"], h1
                assert compilewatch.sealed() is not None

                # job #2: identical spec — warm path, zero post-warm
                sub = c.submit(_spec(reads, paf, layout))
                h2, p2 = c.result(sub["job"], timeout_s=600)
                assert h2["ok"], h2
                assert h2["compiles_after_warm"] == 0
                assert p2 == p1
                # the versioned job report carries the attribution
                # section, clean for the repeat-shape job
                rep2 = h2["report"]
                assert report.validate_report(rep2) == []
                assert rep2["schema_version"] == report.SCHEMA_VERSION
                assert rep2["compiles"]["post_warm"] == 0
                assert rep2["compiles"]["sealed"] == 1

                # job #3: a never-warmed window length -> new consensus
                # geometry -> the sanitized warm-path assert fires
                sub = c.submit(_spec(reads, paf, layout,
                                     window_length=600))
                h3, p3 = c.result(sub["job"], timeout_s=600)
                assert not h3["ok"], h3
                assert h3["state"] == "failed"
                assert h3["compiles_after_warm"] >= 1
                assert "warm-path assert" in h3["error"]
                assert "nearest warmed" in h3["error"]

                # the server survived the assert: a repeat of the WARM
                # spec still succeeds
                sub = c.submit(_spec(reads, paf, layout))
                h4, p4 = c.result(sub["job"], timeout_s=600)
                assert h4["ok"] and p4 == p1
    finally:
        # the seal and warmed set are process-global: a later in-process
        # server resets them itself, but tests that read the watch
        # directly must not inherit this one's
        from racon_tpu.obs import compilewatch as _cw
        _cw.reset()
