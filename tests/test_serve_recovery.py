"""Crash-safe resident serving (round 16): the durable job journal,
restart recovery, slot supervision, the drain protocol and the
retrying client.

The crash contract under test: the death of ANY participant — server
process (SIGKILL mid-batch), chip-worker slot (thread death), or
client connection — loses no work and duplicates none.  The headline
is the kill-server chaos soak: K jobs submitted to a 2-slot server,
the server SIGKILLed mid-batch by ``RACON_TPU_FAULTS=server.kill``, a
restart from the same ``--serve-dir`` — and every job's result is
byte-identical to its one-shot CLI run, jobs completed at crash time
are NOT re-polished (the journal shows zero duplicate ``running``
records for them), and the schema-v5 report's ``recovery`` counts
match.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from racon_tpu import faults
from racon_tpu.obs import metrics
from racon_tpu.obs.report import validate_report
from racon_tpu.serve.client import ServiceClient
from racon_tpu.serve.journal import JobJournal
from racon_tpu.serve.service import PolishServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -------------------------------------------------------------- workloads

def _assembly(td, sizes, seed=31, prefix="a"):
    """Synthetic per-contig assembly triple (the test_serve generator,
    re-homed so the recovery tests stand alone)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    comp = bytes.maketrans(b"ACGT", b"TGCA")

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, n)] for n in sizes]
    layout = os.path.join(td, f"{prefix}_layout.fasta")
    with open(layout, "wb") as f:
        for ti, t in enumerate(truths):
            f.write(b">ctg%d\n" % ti + mutate(t, 0.06).tobytes() + b"\n")
    reads = os.path.join(td, f"{prefix}_reads.fastq")
    paf = os.path.join(td, f"{prefix}_ovl.paf")
    with open(reads, "wb") as rf, open(paf, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            contig = len(truth)
            for start in range(0, max(1, contig - 600), 150):
                end = min(start + 900, contig)
                read = mutate(truth[start:end], 0.08)
                name = b"%s_read%d" % (prefix.encode(), ri)
                strand = b"-" if ri % 3 == 0 else b"+"
                rb = (read.tobytes().translate(comp)[::-1]
                      if strand == b"-" else read.tobytes())
                rf.write(b"@" + name + b"\n" + rb + b"\n+\n"
                         + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    strand, b"ctg%d" % ti, b"%d" % contig,
                    b"%d" % start, b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1
    return reads, paf, layout


def _spec(reads, paf, layout, **opts):
    spec = {"sequences": reads, "overlaps": paf,
            "target_sequences": layout, "window_length": 150,
            "threads": 2}
    spec.update(opts)
    return spec


def _oneshot_cli(reads, paf, layout, *extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu", "-w", "150", "-t", "2",
         *extra, reads, paf, layout],
        capture_output=True, timeout=600, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc.stdout


@pytest.fixture()
def short_tmp():
    """AF_UNIX socket paths are length-bounded (~107 bytes); sockets
    live in a short /tmp dir."""
    with tempfile.TemporaryDirectory(dir="/tmp", prefix="rrec") as td:
        yield td


class _Server:
    """In-process server harness (the test_serve one, plus serve_dir)."""

    def __init__(self, td, **kw):
        self.server = PolishServer(os.path.join(td, "racon.sock"), **kw)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    def __enter__(self):
        self.thread.start()
        assert self.server.started.wait(60), "server did not start"
        return self.server

    def __exit__(self, exc_type, exc, tb):
        self.server.shutdown()
        self.thread.join(timeout=30)
        return False


def _journal_records(serve_dir):
    path = os.path.join(serve_dir, "journal.jsonl")
    out = []
    with open(path, "rb") as f:
        for line in f.read().splitlines():
            if line.strip():
                out.append(json.loads(line))
    return out


def _running_counts(records):
    counts = {}
    for r in records:
        if r.get("rec") == "running":
            counts[r["job"]] = counts.get(r["job"], 0) + 1
    return counts


# ------------------------------------------------------- kill-server soak

def test_chaos_kill_restart_soak(short_tmp):
    """THE crash contract: SIGKILL the server mid-batch (injected
    ``server.kill`` on the 3rd job start), restart it on the same
    --serve-dir, and assert byte-identity for every job, zero
    re-polishing of jobs already journaled done, idempotency-key
    dedupe across the restart, and the v5 report's recovery counts."""
    n_jobs = 4
    triples = [_assembly(short_tmp, [1500 + 150 * i], seed=11 + i,
                         prefix=f"k{i}") for i in range(n_jobs)]
    want = [_oneshot_cli(*t) for t in triples]
    sock = os.path.join(short_tmp, "racon.sock")
    serve_dir = os.path.join(short_tmp, "serve_dir")
    log_a = open(os.path.join(short_tmp, "server_a.log"), "wb")
    base_cmd = [sys.executable, "-m", "racon_tpu", "--serve", sock,
                "--serve-dir", serve_dir, "-w", "150", "-t", "2",
                "--workers", "2"]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_SERVE_WARM_SHAPES="",
               RACON_TPU_FAULTS="server.kill:kill@3")
    server_a = subprocess.Popen(base_cmd, cwd=REPO_ROOT, env=env,
                                stderr=log_a)
    job_ids = []
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(sock):
            assert time.monotonic() < deadline, "server A did not start"
            assert server_a.poll() is None, "server A died at startup"
            time.sleep(0.1)
        for i, t in enumerate(triples):
            with ServiceClient(sock, timeout_s=60) as c:
                resp = c.submit(_spec(*t), key=f"soak{i}")
                assert resp["ok"], resp
                job_ids.append(resp["job"])
        # the injected fault SIGKILLs the server on the 3rd job start:
        # by then >=1 job is done (2 slots drain jobs 1 and 2 first)
        server_a.wait(timeout=600)
        assert server_a.returncode == -9, \
            f"server A exited {server_a.returncode}, wanted SIGKILL"
    finally:
        if server_a.poll() is None:
            server_a.kill()
            server_a.wait()
        log_a.close()
    # pre-restart journal truth: which jobs completed before the kill
    pre = _journal_records(serve_dir)
    done_jobs = {r["job"] for r in pre if r.get("rec") == "done"}
    running_pre = _running_counts(pre)
    assert len(done_jobs) >= 1, "kill landed before any job finished"
    assert done_jobs < set(job_ids), "kill landed after every job"
    for j in done_jobs:
        assert running_pre[j] == 1

    # SIGKILL left the socket file behind; drop it so the wait below
    # detects the RESTARTED server's bind, not the stale path
    try:
        os.unlink(sock)
    except FileNotFoundError:
        pass
    env_b = dict(os.environ, JAX_PLATFORMS="cpu",
                 RACON_TPU_SERVE_WARM_SHAPES="")
    env_b.pop("RACON_TPU_FAULTS", None)
    log_b = open(os.path.join(short_tmp, "server_b.log"), "wb")
    server_b = subprocess.Popen(base_cmd, cwd=REPO_ROOT, env=env_b,
                                stderr=log_b)
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(sock):
            assert time.monotonic() < deadline, "server B did not start"
            assert server_b.poll() is None, "server B died at startup"
            time.sleep(0.1)
        # startup compaction preserved the live history: still exactly
        # ONE running record per completed-at-crash job (zero
        # duplicate polishing — they serve from the spool)
        post = _running_counts(_journal_records(serve_dir))
        for j in done_jobs:
            assert post.get(j, 0) == 1, \
                f"job {j} was re-polished after recovery: {post}"
        # a resubmission under an already-journaled key returns the
        # EXISTING job, not a duplicate
        with ServiceClient(sock, timeout_s=60) as c:
            dup = c.submit(_spec(*triples[0]), key="soak0")
            assert dup["ok"] and dup["existing"]
            assert dup["job"] == job_ids[0]
        # every job's result — recovered-from-spool or re-run — is
        # byte-identical to its one-shot CLI run
        report = None
        for i, jid in enumerate(job_ids):
            with ServiceClient(sock, timeout_s=900) as c:
                header, payload = c.result(jid, timeout_s=850)
                assert header["ok"], (jid, header)
                assert payload == want[i], \
                    f"job {jid} diverged from its one-shot run"
                if header.get("report"):
                    report = header["report"]
        # recovered done jobs keep no per-crash report; a re-run job
        # carries a fresh v5 report whose recovery section holds the
        # server's restart truth
        assert report is not None
        assert validate_report(report) == [], validate_report(report)
        rec = report["recovery"]
        assert rec["recovered_jobs"] == n_jobs
        assert rec["served_from_spool"] == len(done_jobs)
        assert rec["requeued_jobs"] == n_jobs - len(done_jobs)
        assert rec["journal_replayed"] > 0
        assert rec["journal_compactions"] >= 1
        with ServiceClient(sock, timeout_s=60) as c:
            c.shutdown()
        server_b.wait(timeout=120)
    finally:
        if server_b.poll() is None:
            server_b.kill()
            server_b.wait()
        log_b.close()


# --------------------------------------------- in-process restart recovery

def test_restart_serves_done_from_spool(short_tmp, monkeypatch):
    """A job completed (and never fetched) before a stop is served
    from the CRC-verified spool by the restarted server — no
    re-polish (journal_runs stays 1) — and its bytes match the
    one-shot run."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [2000], seed=7)
    want = _oneshot_cli(reads, paf, layout)
    serve_dir = os.path.join(short_tmp, "sd")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            jid = c.submit(_spec(reads, paf, layout), key="spool1")["job"]
            st = c.status(jid)
            deadline = time.monotonic() + 300
            while st["state"] not in ("done", "failed"):
                assert time.monotonic() < deadline
                time.sleep(0.2)
                st = c.status(jid)
            assert st["state"] == "done"
        # job done, result spooled, NOT fetched
        assert server._jobs[jid].result is None  # RAM holds no payload
        assert os.path.exists(os.path.join(serve_dir, "spool",
                                           f"result_{jid}.fasta"))
    base_spool = metrics.counter("serve.spool_served")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            header, payload = c.result(jid, timeout_s=60)
            assert header["ok"], header
            assert payload == want
            # the recovered job was never re-run
            assert server._jobs[jid].journal_runs == 1
            # ...and the key still dedupes to it
            dup = c.submit(_spec(reads, paf, layout), key="spool1")
            assert dup["ok"] and dup["existing"] and dup["job"] == jid
    assert metrics.counter("serve.spool_served") == base_spool + 1


def test_restart_requeues_queued_jobs(short_tmp, monkeypatch):
    """Jobs still queued at shutdown survive: the journal re-admits
    them on restart (in submission order) and they complete
    byte-identically under their ORIGINAL ids."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [1800], seed=19)
    want = _oneshot_cli(reads, paf, layout)
    serve_dir = os.path.join(short_tmp, "sd")
    with _Server(short_tmp, autostart=False,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            j1 = c.submit(_spec(reads, paf, layout))["job"]
            j2 = c.submit(_spec(reads, paf, layout))["job"]
    # hard stop answered the waiting clients FAILED but deliberately
    # did not journal the failures — the disk still says "submitted"
    base_requeued = metrics.counter("serve.requeued_jobs")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            for jid in (j1, j2):
                header, payload = c.result(jid, timeout_s=300)
                assert header["ok"], (jid, header)
                assert payload == want
    assert metrics.counter("serve.requeued_jobs") == base_requeued + 2


def test_corrupt_spool_requeues_job(short_tmp, monkeypatch):
    """A truncated/corrupt spool file fails CRC verification at
    recovery time and the job re-polishes instead of serving garbage
    (the round-12 part-verification rule, re-homed)."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [1900], seed=23)
    want = _oneshot_cli(reads, paf, layout)
    serve_dir = os.path.join(short_tmp, "sd")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            jid = c.submit(_spec(reads, paf, layout))["job"]
            st = c.status(jid)
            deadline = time.monotonic() + 300
            while st["state"] not in ("done", "failed"):
                assert time.monotonic() < deadline
                time.sleep(0.2)
                st = c.status(jid)
            assert st["state"] == "done"
    spool = os.path.join(serve_dir, "spool", f"result_{jid}.fasta")
    with open(spool, "r+b") as f:  # flip a byte: CRC must catch it
        f.seek(3)
        b = f.read(1)
        f.seek(3)
        f.write(bytes([b[0] ^ 0xFF]))
    base_corrupt = metrics.counter("serve.spool_corrupt")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            header, payload = c.result(jid, timeout_s=300)
            assert header["ok"], header
            assert payload == want  # re-polished, not served corrupt
            assert server._jobs[jid].journal_runs >= 2  # it re-ran
    assert metrics.counter("serve.spool_corrupt") == base_corrupt + 1


def test_tmp_litter_swept_on_startup(short_tmp):
    serve_dir = os.path.join(short_tmp, "sd")
    spool = os.path.join(serve_dir, "spool")
    os.makedirs(spool)
    litter = [os.path.join(serve_dir, "journal.jsonl.tmp"),
              os.path.join(spool, "result_j1.fasta.tmp")]
    for p in litter:
        with open(p, "wb") as f:
            f.write(b"torn")
    JobJournal(serve_dir)
    for p in litter:
        assert not os.path.exists(p), p


# ----------------------------------------------------- journal compaction

def test_journal_compaction_bounds_size(short_tmp, monkeypatch):
    """A long-lived server's serve-dir stays bounded: with a tiny
    compaction threshold, N fetched-and-retired jobs leave a journal
    whose size is bounded by the LIVE set (empty here), not the
    history, and their spool files are swept."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setattr(JobJournal, "compact_every", 4)
    reads, paf, layout = _assembly(short_tmp, [1600], seed=29)
    serve_dir = os.path.join(short_tmp, "sd")
    base_compactions = metrics.counter("serve.journal_compactions")
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path, timeout_s=600) as c:
            for k in range(5):
                jid = c.submit(_spec(reads, paf, layout))["job"]
                header, payload = c.result(jid, timeout_s=300)
                assert header["ok"] and payload
                # the `collected` journal append happens on the
                # connection thread after sendall: wait for it so the
                # final compaction sees every job fully retired
                deadline = time.monotonic() + 30
                while not server._jobs[jid].collected:
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
    assert metrics.counter("serve.journal_compactions") \
        > base_compactions
    # every job was collected -> the final compaction leaves NO
    # records and NO spool files: the size bound the satellite asks for
    records = _journal_records(serve_dir)
    assert records == [], records
    assert os.path.getsize(
        os.path.join(serve_dir, "journal.jsonl")) == 0
    assert os.listdir(os.path.join(serve_dir, "spool")) == []


def test_append_retry_rolls_back_partial_write(short_tmp, monkeypatch):
    """A transient append failure that landed PARTIAL bytes must roll
    the file back before retrying — otherwise the retry welds a torn
    prefix onto the record and replay halts there for every later
    job."""
    import errno

    j = JobJournal(os.path.join(short_tmp, "sd"))
    j.append({"rec": "submitted", "job": "j1", "cost": 1,
              "key": None, "unix": 0.0, "spec": {}})
    import racon_tpu.exec.manifest as mf_mod
    real = mf_mod.append_durable
    state = {"fired": False}

    def flaky(f, blob):
        if not state["fired"]:
            state["fired"] = True
            f.write(blob[: len(blob) // 2])
            f.flush()
            raise faults.TransientIOError(errno.EIO, "partial append")
        real(f, blob)

    monkeypatch.setattr("racon_tpu.serve.journal.mf.append_durable",
                        flaky)
    j.append({"rec": "running", "job": "j1", "worker": "w", "run": 1})
    monkeypatch.setattr("racon_tpu.serve.journal.mf.append_durable",
                        real)
    recs = j.replay()
    assert [r["rec"] for r in recs] == ["submitted", "running"], recs
    j.close()


def test_journal_fault_injection_admission_contract(short_tmp,
                                                    monkeypatch):
    """The ``serve.journal`` chaos site, injected end-to-end (the
    round-22 fault-site-registry rule flagged this as the one site no
    test injected).  A transient injected blip (``io@1``) is absorbed
    by the append retry ladder — the admission still succeeds; a
    persistent deterministic failure (``err*``) rejects the admission
    with the durable-admission reason, the server keeps serving, and
    the same idempotency key is reusable once the journal recovers
    (a FAILED prior is retryable by design)."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    serve_dir = os.path.join(short_tmp, "sd")
    reads, paf, layout = _assembly(short_tmp, [2200], seed=23)
    with _Server(short_tmp, num_threads=2,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            # transient blip: retried under the journal's own ladder
            monkeypatch.setenv("RACON_TPU_FAULTS", "serve.journal:io@1")
            sub = c.submit(_spec(reads, paf, layout), key="k-blip")
            assert sub["ok"], sub
            header, payload = c.result(sub["job"], timeout_s=300)
            assert header["ok"] and payload.startswith(b">ctg0")

            # persistent failure: the job is NOT admitted (write-ahead
            # admission — no durable `submitted` record, no run)
            monkeypatch.setenv("RACON_TPU_FAULTS", "serve.journal:err*")
            rej = c.submit(_spec(reads, paf, layout), key="k-dur")
            assert not rej["ok"]
            assert "journal write failed" in rej["error"]

            # the server survived, and the key is reusable now that
            # the journal accepts writes again
            monkeypatch.delenv("RACON_TPU_FAULTS")
            assert c.ping()["ok"]
            sub2 = c.submit(_spec(reads, paf, layout), key="k-dur")
            assert sub2["ok"], sub2
            header2, payload2 = c.result(sub2["job"], timeout_s=300)
            assert header2["ok"] and payload2 == payload
    # whatever compaction left behind references only the two ADMITTED
    # jobs — the rejected attempt never reached the journal
    recs = JobJournal(serve_dir).replay()
    assert {r["job"] for r in recs} <= {sub["job"], sub2["job"]}


def test_journal_replay_tolerates_torn_tail(short_tmp):
    j = JobJournal(os.path.join(short_tmp, "sd"))
    j.append({"rec": "submitted", "job": "j1", "cost": 1,
              "key": None, "unix": 0.0, "spec": {}})
    j.append({"rec": "running", "job": "j1", "worker": "w", "run": 1})
    j.close()
    with open(j.path, "ab") as f:  # a crash mid-append tears the tail
        f.write(b'{"rec": "done", "job": "j1", "by')
    j2 = JobJournal(os.path.join(short_tmp, "sd"))
    recs = j2.replay()
    assert [r["rec"] for r in recs] == ["submitted", "running"]


# -------------------------------------------------------- idempotent keys

def test_idempotent_double_submit(short_tmp, monkeypatch):
    """Two submissions under one key admit ONE job; a different key
    admits another."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [1700], seed=37)
    serve_dir = os.path.join(short_tmp, "sd")
    with _Server(short_tmp, autostart=False,
                 serve_dir=serve_dir) as server:
        with ServiceClient(server.socket_path) as c:
            r1 = c.submit(_spec(reads, paf, layout), key="K")
            assert r1["ok"] and not r1["existing"]
            r2 = c.submit(_spec(reads, paf, layout), key="K")
            assert r2["ok"] and r2["existing"]
            assert r2["job"] == r1["job"]
            r3 = c.submit(_spec(reads, paf, layout), key="K2")
            assert r3["ok"] and not r3["existing"]
            assert r3["job"] != r1["job"]
            with server._lock:
                assert len(server._queue) == 2
            assert server._counts["submitted"] == 2


# --------------------------------------------------------- slot supervision

@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_slot_death_restarts_and_job_completes(short_tmp, monkeypatch):
    """A worker-slot thread that dies outside the per-job ladder is
    detected by the supervisor: the orphaned job re-queues with a
    crash-ladder record, the slot restarts with fresh engines, and the
    job completes on the restarted slot."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setenv("RACON_TPU_FAULTS", "serve.slot:err@1")
    faults.reset()
    reads, paf, layout = _assembly(short_tmp, [1900], seed=41)
    want = _oneshot_cli(reads, paf, layout)
    base_restarts = metrics.counter("slot.restarts")
    with _Server(short_tmp, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            jid = c.submit(_spec(reads, paf, layout))["job"]
            header, payload = c.result(jid, timeout_s=300)
            assert header["ok"], header
            assert payload == want
            classes = [a["class"] for a in header.get("attempts", [])]
            assert "crash" in classes
    assert metrics.counter("slot.restarts") == base_restarts + 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_repeated_slot_deaths_quarantine(short_tmp, monkeypatch):
    """Repeated slot deaths walk the job off the crash ladder (fail
    after 3) and quarantine the slot — advertised capacity shrinks and
    admission rejects instead of queueing into a dead pool."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    monkeypatch.setenv("RACON_TPU_FAULTS", "serve.slot:err*")
    faults.reset()
    reads, paf, layout = _assembly(short_tmp, [1700], seed=43)
    base_quarantined = metrics.counter("slot.quarantined")
    with _Server(short_tmp, num_threads=2) as server:
        with ServiceClient(server.socket_path) as c:
            jid = c.submit(_spec(reads, paf, layout))["job"]
            header, payload = c.result(jid, timeout_s=120)
            assert not header["ok"] and header["state"] == "failed"
            assert payload is None
            acts = [a["action"] for a in header["attempts"]]
            assert acts.count("requeue") == 2 and acts[-1] == "fail"
            # the slot died 3 times -> quarantined, capacity 0
            deadline = time.monotonic() + 30
            while server.healthy_workers() > 0:
                assert time.monotonic() < deadline
                time.sleep(0.2)
            r = c.submit(_spec(reads, paf, layout))
            assert not r["ok"] and "quarantined" in r["error"]
    assert metrics.counter("slot.quarantined") == base_quarantined + 1


# ------------------------------------------------------------------ drain

def test_drain_protocol(short_tmp, monkeypatch):
    """shutdown {"mode": "drain"} stops admission immediately, lets the
    queue finish, flushes the journal, and exits."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    reads, paf, layout = _assembly(short_tmp, [1700], seed=47)
    serve_dir = os.path.join(short_tmp, "sd")
    harness = _Server(short_tmp, autostart=False, num_threads=2,
                      serve_dir=serve_dir)
    with harness as server:
        with ServiceClient(server.socket_path) as c:
            jid = c.submit(_spec(reads, paf, layout))["job"]
        drainer = ServiceClient(server.socket_path)
        resp = drainer.shutdown(mode="drain")
        assert resp["ok"] and resp["state"] == "draining"
        drainer.close()
        # admission is stopped the moment the drain begins
        with ServiceClient(server.socket_path) as c:
            r = c.submit(_spec(reads, paf, layout))
            assert not r["ok"] and "drain" in r["error"]
            # the queued job still runs to completion
            server.start_workers()
            header, payload = c.result(jid, timeout_s=300, keep=True)
            assert header["ok"] and payload
        deadline = time.monotonic() + 60
        while not server._stop.is_set():
            assert time.monotonic() < deadline, "drain never completed"
            time.sleep(0.2)
    # the drained server flushed/compacted: the job (uncollected,
    # keep=True) survives as the journal's one live record set
    recs = _journal_records(serve_dir)
    assert {r["rec"] for r in recs} == {"submitted", "running", "done"}
    assert all(r["job"] == jid for r in recs)


# --------------------------------------------------------- retrying client

def test_client_connect_retries_until_server_up(short_tmp, monkeypatch):
    """ServiceClient's bounded connect retry rides the shared backoff:
    a server that binds 1s late is reached; a zero-retry client fails
    fast."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    sock = os.path.join(short_tmp, "racon.sock")
    with pytest.raises(ConnectionError):
        ServiceClient(sock, retries=0)
    harness = _Server(short_tmp, autostart=False)

    def late_start():
        time.sleep(1.0)
        harness.thread.start()

    threading.Thread(target=late_start, daemon=True).start()
    try:
        c = ServiceClient(sock, timeout_s=60, retries=20,
                          backoff_s=0.2)
        assert c.ping()["ok"]
        c.close()
    finally:
        assert harness.server.started.wait(60)
        harness.server.shutdown()
        harness.thread.join(timeout=30)


def test_client_socket_fault_injection_retries(short_tmp, monkeypatch):
    """The serve.socket fault site exercises the retry loop
    deterministically: two injected connect faults, third attempt
    lands."""
    monkeypatch.setenv("RACON_TPU_SERVE_WARM_SHAPES", "")
    with _Server(short_tmp, autostart=False) as server:
        monkeypatch.setenv("RACON_TPU_FAULTS", "serve.socket:io@1")
        faults.reset()
        c = ServiceClient(server.socket_path, retries=3, backoff_s=0.0)
        assert c.ping()["ok"]
        c.close()
        monkeypatch.delenv("RACON_TPU_FAULTS")
        faults.reset()


def test_backoff_is_deterministic_and_exponential():
    a = faults.backoff_s(0.5, 0, "tok")
    b = faults.backoff_s(0.5, 0, "tok")
    assert a == b  # replayable
    assert 0.375 <= a <= 0.625  # ±25% jitter around base
    assert faults.backoff_s(0.5, 3, "tok") == a * 8
    assert faults.backoff_s(0.0, 5, "x") == 0.0
