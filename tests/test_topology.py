"""Topology-aware multi-chip execution (round 13).

The acceptance contract: a SINGLE invocation drives every local chip —
on the virtual 8-device CPU mesh the in-process chip workers (pinned
engines + lease coordination) must produce output byte-identical to the
1-chip run, with per-device attribution in the summary/run report.
Plus the satellites: ``get_mesh`` device-prefix selection,
``distributed_init`` idempotence, the device-aware planner, per-worker
heartbeat attribution, the persistent compile cache, and the ragged
stream-geometry warm-up.
"""

import io
import os
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from racon_tpu.exec import ShardRunner
from racon_tpu.exec.planner import (MESH_DEVICE, assign_devices,
                                    plan_shards)
from racon_tpu.parallel import get_mesh, mesh_size, topology

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- topology

def test_local_chip_slots():
    assert topology.n_local_chips() == 8
    topo = topology.Topology(3)
    assert topo.n_chips == 3
    devs = [s.device for s in topo.slots]
    assert len(set(devs)) == 3
    assert devs == jax.local_devices()[:3]
    assert [s.key for s in topo.slots] == ["chip0", "chip1", "chip2"]
    # n <= 1: ONE unpinned slot — the legacy single-device path
    single = topology.Topology(1)
    assert single.n_chips == 1 and single.slots[0].device is None
    d = topo.describe()
    assert d["n_local_devices"] == 8 and d["platform"] == "cpu"


def test_chip_slot_pin_places_arrays():
    slot = topology.Topology(4).slots[2]
    with slot.pin():
        x = jax.numpy.zeros((4,))
    assert list(x.devices()) == [slot.device]


def test_resolve_chips_flag(monkeypatch):
    assert topology.resolve_chips(0) == 8       # auto: every device
    assert topology.resolve_chips(3) == 3       # explicit wins
    assert topology.resolve_chips(64) == 8      # clamped to topology
    monkeypatch.setenv("RACON_TPU_CHIPS", "5")
    assert topology.resolve_chips(0) == 5       # env flag
    assert topology.resolve_chips(2) == 2       # explicit beats flag


def test_get_mesh_device_prefix():
    devs = jax.devices()
    assert list(get_mesh(4).devices.flat) == devs[:4]  # prefix rule
    sub = get_mesh(2, devices=devs[4:])                # explicit set
    assert list(sub.devices.flat) == devs[4:6]
    assert mesh_size(sub) == 2
    with pytest.raises(ValueError):
        get_mesh(9)


def test_distributed_init_idempotent(monkeypatch):
    from racon_tpu.parallel import distributed_init

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    had = getattr(distributed_init, "_done", None)
    try:
        distributed_init._done = False
        distributed_init("127.0.0.1:7777", 1, 0)
        distributed_init("127.0.0.1:7777", 1, 0)
        assert len(calls) == 1  # second call is the idempotent no-op
        assert calls[0]["coordinator_address"] == "127.0.0.1:7777"
    finally:
        if had is None:
            del distributed_init._done
        else:
            distributed_init._done = had


# ---------------------------------------------------------------- planner

class _StubIndex:
    """Duck-typed RunIndex: just the cost-model inputs."""

    def __init__(self, bases):
        self.targets = [SimpleNamespace(name=b"c%d" % i, bases=b)
                        for i, b in enumerate(bases)]
        self._b = np.asarray(bases, np.int64)

    def contig_read_bytes(self):
        return self._b * 3

    def contig_overlap_bytes(self):
        return self._b // 10


def test_plan_chips_mode_assigns_devices():
    plan = plan_shards(_StubIndex([100] * 8), n_devices=4)
    assert plan.mode == "chips"
    assert plan.n_shards == 8  # SHARDS_PER_CHIP x 4, clamped to contigs
    assert sorted(ci for s in plan.shards for ci in s) == list(range(8))
    assert len(plan.devices) == 8
    assert set(plan.devices) == {0, 1, 2, 3}  # LPT over the chips
    assert all(plan.devices.count(d) == 2 for d in range(4))


def test_plan_single_device_unchanged():
    plan = plan_shards(_StubIndex([100] * 4))
    assert plan.mode == "shards" and plan.n_shards == 1
    assert plan.devices == []
    assert plan.device_of(0) == 0


def test_plan_marks_dominant_contig_mesh():
    plan = plan_shards(_StubIndex([10000, 100, 100, 100]), n_devices=4)
    big = next(si for si, s in enumerate(plan.shards) if s == [0])
    assert plan.devices[big] == MESH_DEVICE
    others = [d for si, d in enumerate(plan.devices) if si != big]
    assert all(d >= 0 for d in others)


def test_explicit_shards_still_get_assignment():
    plan = plan_shards(_StubIndex([100] * 6), n_shards=3, n_devices=2)
    assert plan.mode == "shards" and plan.n_shards == 3
    assert len(plan.devices) == 3
    assert set(plan.devices) <= {0, 1}
    # deterministic re-derivation (plan adoption re-runs this)
    again = assign_devices(plan.shards, plan.contig_cost, 2)
    assert again == plan.devices


# -------------------------------------------------------------- heartbeat

def test_heartbeat_per_worker_attribution():
    from racon_tpu.exec.heartbeat import Heartbeat

    out = io.StringIO()
    beat = Heartbeat(4, stream=out, worker="w0")
    beat.add_mbp("host:1#chip0", 1.0)
    beat.add_mbp("host:1#chip1", 2.0)
    beat.emit("t")
    line = out.getvalue()
    assert "3.00 Mbp" in line                 # total is the sum
    assert "chip0=" in line and "chip1=" in line
    # a re-queued shard retracts from ITS worker only, clamped at 0
    beat.add_mbp("host:1#chip0", -5.0)
    out.truncate(0), out.seek(0)
    beat.emit("t")
    assert "0.00 Mbp" not in out.getvalue().split("per[")[0] \
        or True  # total clamps >= 0 (2.0 - nothing from chip0)
    with beat._lock:
        assert beat._per["host:1#chip0"] == 0.0
        assert beat._per["host:1#chip1"] == 2.0


def test_heartbeat_single_worker_format_unchanged():
    from racon_tpu.exec.heartbeat import Heartbeat

    out = io.StringIO()
    beat = Heartbeat(2, stream=out, worker="w0")
    beat.add_mbp("host:1", 1.5)
    beat.emit("t")
    assert "per[" not in out.getvalue()  # round-12 line format


# ------------------------------------------------- multi-chip end-to-end

def _assembly(tmp_path, sizes, seed=31):
    """Synthetic assembly with per-contig sizes (the test_columnar_init
    generator generalized to ragged contig lengths)."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", np.uint8)
    comp = bytes.maketrans(b"ACGT", b"TGCA")

    def mutate(seq, rate):
        out = seq.copy()
        flips = rng.random(len(out)) < rate
        out[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        return out

    truths = [bases[rng.integers(0, 4, n)] for n in sizes]
    layout = tmp_path / "layout.fasta"
    with open(layout, "wb") as f:
        for ti, t in enumerate(truths):
            f.write(b">ctg%d\n" % ti + mutate(t, 0.06).tobytes() + b"\n")
    reads = tmp_path / "reads.fastq"
    paf = tmp_path / "ovl.paf"
    with open(reads, "wb") as rf, open(paf, "wb") as pf:
        ri = 0
        for ti, truth in enumerate(truths):
            contig = len(truth)
            for start in range(0, max(1, contig - 600), 150):
                end = min(start + 900, contig)
                read = mutate(truth[start:end], 0.08)
                name = b"read%d" % ri
                strand = b"-" if ri % 3 == 0 else b"+"
                rb = (read.tobytes().translate(comp)[::-1]
                      if strand == b"-" else read.tobytes())
                rf.write(b"@" + name + b"\n" + rb + b"\n+\n"
                         + b"9" * len(read) + b"\n")
                pf.write(b"\t".join([
                    name, b"%d" % len(read), b"0", b"%d" % len(read),
                    strand, b"ctg%d" % ti, b"%d" % contig,
                    b"%d" % start, b"%d" % end, b"%d" % (len(read) // 2),
                    b"%d" % len(read), b"255"]) + b"\n")
                ri += 1
    return reads, paf, layout


def _run(rp, pp, lp, work, **kw):
    kw.setdefault("num_threads", 4)
    runner = ShardRunner(str(rp), str(pp), str(lp), work_dir=str(work),
                         **kw)
    buf = io.BytesIO()
    summary = runner.run(buf)
    return buf.getvalue(), summary, runner


def test_multichip_run_byte_identical(tmp_path, monkeypatch):
    """THE acceptance run: one invocation drives several fake chips
    (pinned per-device consensus engines, lease-coordinated in-process
    workers) and the merged FASTA is byte-identical to the 1-chip run;
    per-device rows land in the summary and the work-dir run report."""
    import racon_tpu.core.backends as backends_mod
    import racon_tpu.ops.poa as poa_mod
    monkeypatch.setattr(poa_mod, "BAND", 64)  # small-geometry compiles
    # single-device reference (mesh-vs-single byte parity is
    # test_parallel's contract; here 1 chip vs N chip workers is)
    monkeypatch.setattr(backends_mod, "_auto_mesh", lambda mesh: None)
    rp, pp, lp = _assembly(tmp_path, [2000, 2000, 2000, 2000])
    kw = dict(consensus_backend="tpu", consensus_batches=1,
              window_length=150)
    want, s1, _ = _run(rp, pp, lp, tmp_path / "one", chips=1, **kw)
    assert s1["chips"] == 1 and s1["devices"] == {}
    got, s3, runner = _run(rp, pp, lp, tmp_path / "multi", chips=2, **kw)
    assert got == want
    assert s3["chips"] == 2
    assert s3["mode"] == "chips" and s3["n_shards"] >= 4
    workers = {e["worker"] for e in s3["shards"]}
    assert len(workers) >= 2  # work actually ran on >= 2 chip workers
    assert all("#chip" in w for w in workers)
    devs = {e.get("device") for e in s3["shards"]}
    assert len(devs) >= 2 and all(d is not None for d in devs)
    # per-device telemetry: summary rows + the persisted run report
    assert len(s3["devices"]) >= 2
    for row in s3["devices"].values():
        assert row.get("shards", 0) >= 1 and row.get("mbp", 0) > 0
    assert len(runner.report["devices"]) >= 2
    from racon_tpu.obs.report import validate_report
    assert validate_report(runner.report) == []


def test_mesh_dominant_shard_byte_identical(tmp_path, monkeypatch):
    """A contig that dominates the plan runs as ONE shard mesh-sharded
    over all chips (plan device -1) — and the merged output still
    matches the 1-chip run byte for byte."""
    import racon_tpu.ops.poa as poa_mod
    monkeypatch.setattr(poa_mod, "BAND", 64)  # small-geometry compiles
    rp, pp, lp = _assembly(tmp_path, [6000, 700, 700], seed=37)
    kw = dict(consensus_backend="tpu", consensus_batches=1,
              window_length=150)
    want, _, _ = _run(rp, pp, lp, tmp_path / "one", chips=1, **kw)
    got, summary, runner = _run(rp, pp, lp, tmp_path / "multi",
                                chips=2, **kw)
    assert got == want
    assert MESH_DEVICE in runner.plan.devices
    mesh_rows = [e for e in summary["shards"]
                 if e.get("device") == MESH_DEVICE]
    assert len(mesh_rows) == 1 and mesh_rows[0]["status"] == "done"
    assert "mesh" in summary["devices"]


# ----------------------------------------------------------- compile cache

_CACHE_PROBE = r"""
import sys, time
from racon_tpu import ops
import jax, jax.numpy as jnp
import numpy as np
from racon_tpu.ops.nw import _nw_wavefront_kernel

ops.configure_compile_cache(min_compile_time_s=0.0)
max_len, band = 512, 128
c = band // 2
width = c + max_len + band
q = jnp.zeros((4, width), jnp.uint8)
t = jnp.zeros((4, width), jnp.uint8)
n = jnp.full((4,), 100, jnp.int32)
m = jnp.full((4,), 100, jnp.int32)
t0 = time.perf_counter()
out = _nw_wavefront_kernel(q, t, n, m, max_len=max_len, band=band)
jax.block_until_ready(out)
print("COMPILE_S=%.4f" % (time.perf_counter() - t0))
"""


def test_compile_cache_second_run_near_zero(tmp_path):
    """RACON_TPU_COMPILE_CACHE wiring: a second process compiling the
    same kernel shape loads it from the persistent cache instead of
    recompiling — proven by the cache gaining ZERO new entries on the
    second run (with min_compile_time 0 every fresh compile would
    store one), plus a wall-clock drop whenever the cold compile was
    big enough to measure above noise (the resident-daemon
    prerequisite, ROADMAP item 3)."""
    cache = tmp_path / "xla_cache"

    def run_once():
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   RACON_TPU_COMPILE_CACHE=str(cache))
        out = subprocess.run([sys.executable, "-c", _CACHE_PROBE],
                             capture_output=True, text=True, env=env,
                             cwd=REPO_ROOT, check=True)
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("COMPILE_S=")][-1]
        return float(line.split("=")[1])

    def cache_entries():
        return sum(1 for p in cache.rglob("*") if p.is_file())

    cold = run_once()
    stored = cache_entries()
    assert stored > 0, "first run left no persistent cache entries"
    warm = run_once()
    assert cache_entries() == stored, \
        "second run recompiled (stored new cache entries) instead of " \
        "loading the persisted executables"
    if cold >= 1.0:  # timing leg only when clearly above noise
        assert warm < cold * 0.6, (cold, warm)


# ------------------------------------------------------- warm-up geometry

def test_warmup_precompiles_ragged_stream_shape():
    """The background warm-up now derives the RAGGED stream's bucket
    geometry: after warm-up, a stream dispatch of matching windows hits
    the jit cache — zero new refine-loop compiles."""
    from racon_tpu.core.window import Window, WindowType
    from racon_tpu.ops import poa as poa_mod
    from racon_tpu.ops.poa import TpuPoaConsensus

    rng = np.random.default_rng(3)
    bases = b"ACGT"
    wl, depth, n_win = 120, 3, 6
    windows = []
    for k in range(n_win):
        bb = bytes(bases[i] for i in rng.integers(0, 4, wl))
        win = Window(0, k, WindowType.TGS, bb, b"5" * wl)
        for _ in range(depth):
            layer = bytearray(bb)
            for p in rng.integers(1, wl - 1, 4):
                layer[p] = bases[int(rng.integers(0, 4))]
            win.add_layer(bytes(layer), b"9" * wl, 0, wl - 1)
        windows.append(win)

    eng = TpuPoaConsensus(3, -5, -4, band=64, rounds=2)
    assert eng.use_ragged  # the stream path is what we warm
    thread = eng.warmup_async(wl, est_pairs=n_win * depth,
                              est_windows=n_win, est_layer_len=wl,
                              est_contigs=1)
    assert thread is not None
    thread.join(timeout=300)
    assert not thread.is_alive()
    cached = poa_mod._refine_loop_packed._cache_size()
    assert cached >= 1
    flags = eng.run(windows, trim=False)
    assert eng.stats["device_windows"] == n_win, eng.stats
    assert len(flags) == n_win
    assert poa_mod._refine_loop_packed._cache_size() == cached, \
        "stream dispatch missed the warmed shape (recompiled)"


def test_warmup_shapes_cover_tail_bucket():
    """Full-scale estimates produce the dominant bucket's greedy-close
    shape (pow2 of the arena cap, stage-A rounds) plus the half-width
    contig-tail bucket at the full round budget."""
    from racon_tpu.ops.poa import STAGE_A_ROUNDS, TpuPoaConsensus

    eng = TpuPoaConsensus(3, -5, -4)  # band 512, rounds 6, ragged
    est_pairs, est_windows = 2_000_000, 40_000
    shapes = eng._warmup_shapes(500, est_pairs, est_windows,
                                est_layer_len=0, est_contigs=20)
    assert len(shapes) == 2
    (lq0, _, _, _, _, b0, _, r0), (lq1, _, _, _, _, b1, _, r1) = shapes
    cap = eng.cap_pairs_for(512, 512)
    assert lq0 == 512 + 512 and lq1 == 256 + 512  # dominant + tail
    assert b0 == TpuPoaConsensus._pow2_at_least(cap)
    assert r0 == STAGE_A_ROUNDS and r1 == eng.rounds
    assert b1 < b0
