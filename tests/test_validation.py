"""Factory validation tests — the reference's five death tests
(``test/racon_test.cpp:55-86``) as ``pytest.raises`` against
``create_polisher``: invalid polisher type, window length 0, and a bad file
extension for each of the three inputs."""

import pytest

from racon_tpu.core.polisher import PolisherType, create_polisher


@pytest.fixture
def paths(data_dir):
    return (str(data_dir / "sample_reads.fastq.gz"),
            str(data_dir / "sample_overlaps.paf.gz"),
            str(data_dir / "sample_layout.fasta.gz"))


def test_invalid_polisher_type(paths):
    with pytest.raises(ValueError, match="invalid polisher type"):
        create_polisher(*paths, type_=3)  # type: ignore[arg-type]


def test_invalid_window_length(paths):
    with pytest.raises(ValueError, match="invalid window length"):
        create_polisher(*paths, window_length=0)


def test_bad_sequences_extension(paths):
    _, overlaps, target = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher("reads.txt", overlaps, target)


def test_bad_overlaps_extension(paths):
    seqs, _, target = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher(seqs, "overlaps.txt", target)


def test_bad_target_extension(paths):
    seqs, overlaps, _ = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher(seqs, overlaps, "layout.txt")


def test_malformed_overlap_file_names_file_and_line(tmp_path):
    """End-to-end parser hardening: a torn overlap line deep in an
    otherwise-valid file fails polisher initialization with a
    structured error naming the file (and, on the Python oracle path,
    the line) instead of a bare IndexError."""
    lp = tmp_path / "t.fasta"
    lp.write_bytes(b">A\n" + b"ACGT" * 100 + b"\n")
    rp = tmp_path / "r.fasta"
    rp.write_bytes(b">r1\n" + b"ACGT" * 90 + b"\n")
    bad = tmp_path / "torn.paf"
    bad.write_bytes(b"r1\t360\t0\t360\t+\tA\t400\t0\t360\t50\t100\t255\n"
                    b"r1\t360\n")
    p = create_polisher(str(rp), str(bad), str(lp))
    with pytest.raises(ValueError, match=r"torn\.paf|malformed line"):
        p.initialize()
