"""Factory validation tests — the reference's five death tests
(``test/racon_test.cpp:55-86``) as ``pytest.raises`` against
``create_polisher``: invalid polisher type, window length 0, and a bad file
extension for each of the three inputs."""

import pytest

from racon_tpu.core.polisher import PolisherType, create_polisher


@pytest.fixture
def paths(data_dir):
    return (str(data_dir / "sample_reads.fastq.gz"),
            str(data_dir / "sample_overlaps.paf.gz"),
            str(data_dir / "sample_layout.fasta.gz"))


def test_invalid_polisher_type(paths):
    with pytest.raises(ValueError, match="invalid polisher type"):
        create_polisher(*paths, type_=3)  # type: ignore[arg-type]


def test_invalid_window_length(paths):
    with pytest.raises(ValueError, match="invalid window length"):
        create_polisher(*paths, window_length=0)


def test_bad_sequences_extension(paths):
    _, overlaps, target = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher("reads.txt", overlaps, target)


def test_bad_overlaps_extension(paths):
    seqs, _, target = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher(seqs, "overlaps.txt", target)


def test_bad_target_extension(paths):
    seqs, overlaps, _ = paths
    with pytest.raises(ValueError, match="unsupported format extension"):
        create_polisher(seqs, overlaps, "layout.txt")
