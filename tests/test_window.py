"""Window.add_layer boundary semantics (single collapsed guard).

The reference validates layer bounds in ``window.cpp:42-63``; our
``add_layer`` used to test ``begin >= end`` twice (a ``begin == end``
early-return made the later ``>=`` check half-dead). The collapsed guard
must keep the exact legacy semantics: empty/zero-span layers skip
silently (even with out-of-range positions), inverted or overflowing
bounds raise, and the inclusive ``end == backbone_len`` boundary is
accepted."""

import pytest

from racon_tpu.core.window import Window, WindowType


def make_window(backbone=b"ACGTACGTAC"):
    return Window(0, 0, WindowType.TGS, backbone, b"!" * len(backbone))


def test_add_layer_appends_valid_layer():
    w = make_window()
    w.add_layer(b"ACGT", b"9999", 2, 6)
    assert w.sequences[-1] == b"ACGT"
    assert w.qualities[-1] == b"9999"
    assert w.positions[-1] == (2, 6)


def test_add_layer_end_at_backbone_len_accepted():
    w = make_window()
    w.add_layer(b"ACG", None, 7, 10)  # end == len(backbone): inclusive cap
    assert w.positions[-1] == (7, 10)


def test_add_layer_zero_span_skips_silently():
    w = make_window()
    w.add_layer(b"ACGT", None, 5, 5)
    assert len(w.sequences) == 1  # backbone only


def test_add_layer_zero_span_skips_even_out_of_range():
    # legacy contract: the begin == end early-return fires before any
    # bounds validation, so an out-of-range zero-span layer skips quietly
    w = make_window()
    w.add_layer(b"ACGT", None, 99, 99)
    assert len(w.sequences) == 1


def test_add_layer_empty_sequence_skips_silently():
    w = make_window()
    w.add_layer(b"", None, 12, 3)  # invalid bounds, but empty skips first
    assert len(w.sequences) == 1


def test_add_layer_inverted_bounds_raise():
    w = make_window()
    with pytest.raises(ValueError, match="begin and end"):
        w.add_layer(b"ACGT", None, 6, 2)


def test_add_layer_end_past_backbone_raises():
    w = make_window()
    with pytest.raises(ValueError, match="begin and end"):
        w.add_layer(b"ACGT", None, 2, 11)


def test_add_layer_quality_length_mismatch_raises():
    w = make_window()
    with pytest.raises(ValueError, match="quality"):
        w.add_layer(b"ACGT", b"99", 2, 6)
