"""L6 wrapper + rampler tests.

Reference contract: ``scripts/racon_wrapper.py`` (split/subsample via
rampler subprocesses, then sequential racon runs per chunk whose stdout
concatenation is the final FASTA)."""

import gzip
import os
import pathlib
import subprocess
import sys

import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

from racon_tpu import rampler
from racon_tpu.io import parsers


@pytest.fixture()
def reads_subset(data_dir, tmp_path):
    """First 24 λ-phage reads + their ava overlaps, uncompressed."""
    reads = []
    for rec in parsers.parse_fastq(str(data_dir / "sample_reads.fastq.gz")):
        reads.append(rec)
        if len(reads) >= 24:
            break
    names = {r.name.split()[0] for r in reads}
    reads_path = tmp_path / "subset.fastq"
    with open(reads_path, "wb") as f:
        for r in reads:
            f.write(b"@" + r.name + b"\n" + r.data + b"\n+\n" + r.quality
                    + b"\n")
    ovl_path = tmp_path / "subset.paf"
    with gzip.open(data_dir / "sample_ava_overlaps.paf.gz", "rb") as fin, \
            open(ovl_path, "wb") as out:
        for line in fin:
            cols = line.split(b"\t")
            if cols[0] in names and cols[5] in names:
                out.write(line)
    return reads_path, ovl_path, reads


# ------------------------------------------------------------------ rampler

def test_rampler_split_round_trip(reads_subset, tmp_path):
    reads_path, _, reads = reads_subset
    out_dir = tmp_path / "split"
    out_dir.mkdir()
    total = sum(len(r.data) for r in reads)
    parts = rampler.split(str(reads_path), total // 3, str(out_dir))
    assert len(parts) >= 3
    joined = []
    for part in parts:
        joined.extend(parsers.parse_fastq(part))
    assert [r.name for r in joined] == [r.name for r in reads]
    assert [r.data for r in joined] == [r.data for r in reads]
    assert [r.quality for r in joined] == [r.quality for r in reads]
    # every chunk except possibly a single-record overflow stays under size
    for part in parts:
        recs = list(parsers.parse_fastq(part))
        if len(recs) > 1:
            assert sum(len(r.data) for r in recs) <= total // 3


def test_rampler_split_cli_names(reads_subset, tmp_path):
    reads_path, _, _ = reads_subset
    out_dir = tmp_path / "splitcli"
    assert rampler.main(["-o", str(out_dir), "split", str(reads_path),
                         "50000"]) == 0
    assert (out_dir / "subset_0.fastq").exists()  # <base>_<i>.<ext> contract


def test_rampler_subsample(reads_subset, tmp_path):
    reads_path, _, reads = reads_subset
    out_dir = tmp_path / "sub"
    out_dir.mkdir()
    ref_len = 20000
    cov = 3
    out = rampler.subsample(str(reads_path), ref_len, cov, str(out_dir))
    assert out.endswith("subset_3x.fastq")  # <base>_<cov>x.<ext> contract
    recs = list(parsers.parse_fastq(out))
    total = sum(len(r.data) for r in recs)
    assert total >= ref_len * cov  # reached requested coverage
    assert total < sum(len(r.data) for r in reads)  # strict subset
    # deterministic by default
    out2 = rampler.subsample(str(reads_path), ref_len, cov, str(tmp_path))
    assert [r.name for r in parsers.parse_fastq(out2)] == \
           [r.name for r in recs]


# ------------------------------------------------------------------ wrapper

def run_cli(module, args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-m", module] + args,
                          capture_output=True, cwd=cwd, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return proc.stdout


def test_wrapper_split_reproduces_unsplit(reads_subset, tmp_path):
    """Fragment-correct 24 reads against themselves, whole vs --split into
    ~3 target chunks: concatenated chunk outputs must equal the unsplit
    run's output (chunked runs drop overlaps to absent targets, so each
    read's correction only depends on its own overlaps)."""
    reads_path, ovl_path, reads = reads_subset
    common = ["-f", "-t", "4", "-m", "1", "-x", "-1", "-g", "-1", "-u",
              str(reads_path), str(ovl_path), str(reads_path)]

    whole = run_cli("racon_tpu.cli",
                    ["-f", "-t", "4", "-m", "1", "-x", "-1", "-g", "-1",
                     "-u", str(reads_path), str(ovl_path), str(reads_path)],
                    cwd=tmp_path)
    total = sum(len(r.data) for r in reads)
    split = run_cli("racon_tpu.wrapper",
                    ["--split", str(total // 3)] + common, cwd=tmp_path)
    assert whole == split
    assert whole.count(b">") == 24


def test_wrapper_subsample_runs(reads_subset, tmp_path):
    reads_path, ovl_path, _ = reads_subset
    out = run_cli("racon_tpu.wrapper",
                  ["--subsample", "20000", "5", "-f", "-u", "-t", "4",
                   str(reads_path), str(ovl_path), str(reads_path)],
                  cwd=tmp_path)
    assert out.count(b">") == 24
